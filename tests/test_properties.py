"""Randomized property sweeps: algebraic invariants that must hold for ANY
shape/configuration, exercised across seeded random configs (the reference
tests only five fixed scenarios, kmeans_spark.py:355-621)."""

import numpy as np
import pytest

from kmeans_tpu import KMeans
from kmeans_tpu.ops.assign import assign_reduce


def _random_config(rng):
    n = int(rng.integers(5, 2000))
    d = int(rng.integers(1, 40))
    k = int(rng.integers(1, min(n, 12) + 1))
    return n, d, k


@pytest.mark.parametrize("seed", range(8))
def test_fit_predict_invariants_random_shapes(seed, mesh8):
    """For any (n, d, k): k centroids come back finite, every label is in
    range, every predicted label points at the point's true nearest
    centroid (lowest index on ties), and counts sum to n."""
    rng = np.random.default_rng(seed)
    n, d, k = _random_config(rng)
    X = rng.normal(size=(n, d)).astype(np.float32)
    km = KMeans(k=k, seed=seed, max_iter=10, verbose=False,
                mesh=mesh8).fit(X)
    assert km.centroids.shape == (k, d)
    assert np.all(np.isfinite(km.centroids))
    labels = km.predict(X)
    assert labels.shape == (n,) and labels.min() >= 0 and labels.max() < k
    assert int(km.cluster_sizes_.sum()) == n
    # Brute-force nearest-centroid oracle in float64.
    from conftest import sq_dists_f64
    d2 = sq_dists_f64(X, km.centroids)
    oracle = np.argmin(d2, axis=1)
    # fp32-vs-f64 boundary flips allowed only where the CHOSEN centroid is
    # within a tiny margin of the true nearest (a grossly wrong label must
    # fail regardless of how close the top-2 oracle distances are).
    diff = np.flatnonzero(labels != oracle)
    if diff.size:
        excess = d2[diff, labels[diff]] - d2[diff, oracle[diff]]
        assert excess.max() < 1e-3, (excess.max(), diff.size)


@pytest.mark.parametrize("seed", range(4))
def test_chunk_size_invariance(seed):
    """assign_reduce statistics must not depend on the scan chunking
    (beyond fp addition order)."""
    import jax.numpy as jnp
    rng = np.random.default_rng(100 + seed)
    n, d, k = 960, int(rng.integers(2, 20)), int(rng.integers(2, 9))
    X = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    W = jnp.ones((n,), jnp.float32)
    C = X[:k]
    ref = None
    for chunk in (32, 96, 480, 960):
        st = assign_reduce(X, W, C, chunk_size=chunk)
        got = (np.asarray(st.sums), np.asarray(st.counts), float(st.sse))
        if ref is None:
            ref = got
            continue
        np.testing.assert_array_equal(got[1], ref[1])       # counts exact
        np.testing.assert_allclose(got[0], ref[0], rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(got[2], ref[2], rtol=1e-5)


@pytest.mark.parametrize("seed", range(4))
def test_row_permutation_invariance(seed, mesh8):
    """Shuffling input rows must not change the fitted centroid SET (fp
    accumulation order shifts values only within tolerance)."""
    rng = np.random.default_rng(200 + seed)
    X = rng.normal(size=(800, 5)).astype(np.float32)
    k = 4
    init = X[rng.choice(800, size=k, replace=False)].copy()
    km1 = KMeans(k=k, seed=0, init=init, max_iter=15, verbose=False,
                 mesh=mesh8).fit(X)
    perm = rng.permutation(800)
    km2 = KMeans(k=k, seed=0, init=init, max_iter=15, verbose=False,
                 mesh=mesh8).fit(X[perm])
    c1 = np.array(sorted(km1.centroids.tolist()))
    c2 = np.array(sorted(km2.centroids.tolist()))
    np.testing.assert_allclose(c1, c2, atol=1e-4)
