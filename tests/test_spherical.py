"""SphericalKMeans: cosine-similarity clustering (beyond-reference model
family; the reference is Euclidean-only, kmeans_spark.py:153).

For unit vectors, chordal distance^2 = 2 - 2*cos, so the assertions check
direction-based invariants: scale invariance of labels, unit-norm
centroids, and recovery of known directional clusters.
"""

import numpy as np
import pytest

from kmeans_tpu import SphericalKMeans


def _directional_data(seed=0, n_per=150):
    """Three tight cones around orthogonal directions, random magnitudes."""
    rng = np.random.default_rng(seed)
    dirs = np.eye(3)
    X, y = [], []
    for j, d in enumerate(dirs):
        v = d[None, :] + rng.normal(scale=0.05, size=(n_per, 3))
        r = rng.uniform(0.1, 100.0, size=(n_per, 1))   # magnitude is noise
        X.append(v * r)
        y.append(np.full(n_per, j))
    return np.concatenate(X), np.concatenate(y)


def test_recovers_directional_clusters(mesh8):
    X, y = _directional_data()
    km = SphericalKMeans(k=3, seed=1, compute_sse=True, mesh=mesh8,
                         verbose=False, dtype=np.float64).fit(X)
    # Unit-norm centroids, one per axis direction.
    np.testing.assert_allclose(np.linalg.norm(km.centroids, axis=1), 1.0,
                               atol=1e-9)
    axes = np.argmax(km.centroids, axis=1)
    assert set(axes) == {0, 1, 2}
    assert np.max(km.centroids) > 0.99
    # Labels agree with the true cones up to permutation.
    labels = km.predict(X)
    for j in range(3):
        vals = labels[y == j]
        assert len(np.unique(vals)) == 1


def test_scale_invariance(mesh8):
    X, _ = _directional_data(seed=3)
    rng = np.random.default_rng(4)
    scales = rng.uniform(0.01, 1000.0, size=(X.shape[0], 1))
    km = SphericalKMeans(k=3, seed=2, mesh=mesh8, verbose=False,
                         dtype=np.float64)
    km.fit(X)
    np.testing.assert_array_equal(km.predict(X), km.predict(X * scales))


def test_sse_is_chordal_and_monotone(mesh8):
    X, _ = _directional_data(seed=5)
    km = SphericalKMeans(k=3, seed=0, compute_sse=True, mesh=mesh8,
                         verbose=False, dtype=np.float64).fit(X)
    hist = np.asarray(km.sse_history)
    assert np.all(np.diff(hist) <= 1e-6)
    # SSE equals sum of 2 - 2*cos(x, nearest centroid).
    Xn = X / np.linalg.norm(X, axis=1, keepdims=True)
    cos = Xn @ km.centroids.T
    expect = float(np.sum(2.0 - 2.0 * cos.max(axis=1)))
    assert np.isclose(hist[-1], expect, rtol=1e-5)


def test_transform_chordal_vs_cosine(mesh8):
    X, _ = _directional_data(seed=6)
    km = SphericalKMeans(k=3, seed=0, mesh=mesh8, verbose=False,
                         dtype=np.float64).fit(X)
    D = km.transform(X[:20])
    Xn = X[:20] / np.linalg.norm(X[:20], axis=1, keepdims=True)
    cos = Xn @ km.centroids.T
    np.testing.assert_allclose(1.0 - D ** 2 / 2.0, cos, atol=1e-6)


def test_zero_rows_tolerated(mesh8):
    X, _ = _directional_data(seed=7)
    X[10] = 0.0                   # no direction
    km = SphericalKMeans(k=3, seed=0, mesh=mesh8, verbose=False,
                         dtype=np.float64).fit(X)
    assert np.all(np.isfinite(km.centroids))
    labels = km.predict(X)
    assert labels.shape == (X.shape[0],)


@pytest.mark.parametrize("mesh_name", ["mesh1", "mesh8", "mesh4x2"])
def test_spherical_device_loop_matches_host(mesh_name, request):
    """ISSUE 2 satellite: the sphere projection is folded into the
    one-dispatch device loop's update step — host_loop=False must
    reproduce the host loop's trajectory exactly (the same parity pin
    tests/test_device_loop.py holds for the base KMeans)."""
    mesh = request.getfixturevalue(mesh_name)
    rng = np.random.default_rng(0)
    X = rng.normal(size=(3000, 8)) + 2.0 * rng.integers(0, 5, size=(3000, 1))
    kw = dict(k=5, max_iter=25, seed=42, compute_sse=True, mesh=mesh,
              dtype=np.float64, empty_cluster="keep", verbose=False)
    host = SphericalKMeans(host_loop=True, **kw).fit(X)
    dev = SphericalKMeans(host_loop=False, **kw).fit(X)
    assert dev.iterations_run == host.iterations_run
    np.testing.assert_allclose(dev.centroids, host.centroids, atol=1e-9)
    np.testing.assert_allclose(dev.sse_history, host.sse_history, rtol=1e-9)
    np.testing.assert_allclose(np.linalg.norm(dev.centroids, axis=1), 1.0,
                               atol=1e-12)


def test_spherical_device_multi_restart_matches_host(mesh8):
    """Batched n_init sweep composes with the sphere projection on
    device: winner and trajectory match the host's sequential restarts."""
    X, _ = _directional_data(seed=12)
    kw = dict(k=3, max_iter=20, seed=7, n_init=3, init="forgy",
              compute_sse=True, mesh=mesh8, dtype=np.float64,
              empty_cluster="keep", verbose=False)
    host = SphericalKMeans(host_loop=True, **kw).fit(X)
    dev = SphericalKMeans(host_loop=False, **kw).fit(X)
    assert dev.best_restart_ == host.best_restart_
    np.testing.assert_allclose(dev.restart_inertias_,
                               host.restart_inertias_, rtol=1e-9)
    np.testing.assert_allclose(dev.centroids, host.centroids, atol=1e-9)


def test_spherical_device_loop_empty_resample(mesh8):
    """'resample' refill inside the spherical device loop: refilled rows
    are (normalized) data rows, re-projected by the device hook — result
    matches the host loop on a hostless dataset (the engine both loops
    share)."""
    X, _ = _directional_data(seed=13)
    init = np.concatenate([_normalize(X[:2]), [[0.0, 0.0, -1.0]]])

    def run(host_loop):
        km = SphericalKMeans(k=3, max_iter=10, seed=3, init=init,
                             empty_cluster="resample", mesh=mesh8,
                             dtype=np.float64, host_loop=host_loop,
                             verbose=False, compute_sse=True)
        ds = km.cache(X)
        ds._host = None
        ds._host_weights = None
        return km.fit(ds)

    host, dev = run(True), run(False)
    assert dev.iterations_run == host.iterations_run
    np.testing.assert_allclose(dev.centroids, host.centroids, atol=1e-9)


def _normalize(x):
    return x / np.linalg.norm(x, axis=1, keepdims=True)


def test_foreign_sharded_dataset_rejected(mesh8):
    from kmeans_tpu import KMeans
    X, _ = _directional_data(seed=10)
    foreign = KMeans(k=3, mesh=mesh8, dtype=np.float64).cache(X)
    km = SphericalKMeans(k=3, mesh=mesh8, verbose=False, dtype=np.float64)
    with pytest.raises(ValueError, match="row-normalized"):
        km.fit(foreign)
    own = km.cache(X)                  # normalizing cache is accepted
    km.fit(own)
    assert np.all(np.isfinite(km.centroids))


def test_zero_mean_keeps_previous_direction(mesh8):
    km = SphericalKMeans(k=2, mesh=mesh8, verbose=False, dtype=np.float64)
    new = np.array([[0.0, 0.0], [3.0, 4.0]])
    prev = np.array([[0.0, 1.0], [1.0, 0.0]])
    out = km._postprocess_centroids(new, prev=prev)
    np.testing.assert_allclose(out[0], [0.0, 1.0])   # kept old direction
    np.testing.assert_allclose(out[1], [0.6, 0.8])   # normalized mean


def test_checkpoint_roundtrip(tmp_path, mesh8):
    X, _ = _directional_data(seed=8)
    km = SphericalKMeans(k=3, seed=9, mesh=mesh8, verbose=False,
                         dtype=np.float64).fit(X)
    km.save(tmp_path / "sph.npz")
    loaded = SphericalKMeans.load(tmp_path / "sph.npz")
    assert isinstance(loaded, SphericalKMeans)
    np.testing.assert_allclose(loaded.centroids, km.centroids)
    np.testing.assert_array_equal(loaded.predict(X[:10]), km.predict(X[:10]))
