"""End-to-end KMeans with distance_mode='pallas' (interpret mode on CPU):
must reproduce the XLA path's trajectory on DP meshes AND under model-axis
(centroid) sharding (r1 VERDICT #3)."""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from conftest import pallas_x64_skip

pytestmark = pallas_x64_skip

from kmeans_tpu import KMeans
from kmeans_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def data():
    X, _ = make_blobs(n_samples=1500, centers=4, n_features=6,
                      random_state=2)
    return X.astype(np.float32)


def test_pallas_mode_matches_matmul(data, mesh8):
    a = KMeans(k=4, max_iter=15, seed=42, compute_sse=True, mesh=mesh8,
               distance_mode="matmul", verbose=False).fit(data)
    b = KMeans(k=4, max_iter=15, seed=42, compute_sse=True, mesh=mesh8,
               distance_mode="pallas", verbose=False).fit(data)
    assert a.iterations_run == b.iterations_run
    np.testing.assert_allclose(a.centroids, b.centroids, atol=1e-4)
    np.testing.assert_allclose(a.sse_history, b.sse_history, rtol=1e-5)
    np.testing.assert_array_equal(a.predict(data), b.predict(data))


def test_pallas_mode_device_loop(data, mesh8):
    km = KMeans(k=4, max_iter=15, seed=42, empty_cluster="keep", mesh=mesh8,
                distance_mode="pallas", host_loop=False, verbose=False)
    km.fit(data)
    assert np.all(np.isfinite(km.centroids))


def test_pallas_under_model_sharding_matches_matmul(data, mesh4x2):
    """r1 VERDICT #3: pallas x TP now composes — assignment-only kernel +
    global argmin reconstruction + ownership-masked accumulation."""
    a = KMeans(k=4, max_iter=15, seed=42, compute_sse=True, mesh=mesh4x2,
               distance_mode="matmul", verbose=False).fit(data)
    b = KMeans(k=4, max_iter=15, seed=42, compute_sse=True, mesh=mesh4x2,
               distance_mode="pallas", verbose=False).fit(data)
    assert a.iterations_run == b.iterations_run
    np.testing.assert_allclose(a.centroids, b.centroids, atol=1e-4)
    np.testing.assert_allclose(a.sse_history, b.sse_history, rtol=1e-5)
    np.testing.assert_array_equal(a.predict(data), b.predict(data))


def test_pallas_tp_device_loop(data, mesh4x2):
    km = KMeans(k=4, max_iter=15, seed=42, empty_cluster="keep",
                mesh=mesh4x2, distance_mode="pallas", host_loop=False,
                verbose=False).fit(data)
    ref = KMeans(k=4, max_iter=15, seed=42, empty_cluster="keep",
                 mesh=mesh4x2, distance_mode="matmul", host_loop=False,
                 verbose=False).fit(data)
    np.testing.assert_allclose(km.centroids, ref.centroids, atol=1e-4)
