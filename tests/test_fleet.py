"""ISSUE 13: fleet observability — identity, merged timelines,
collective-comms accounting, straggler detection.

Three tiers:

1. **Unit semantics** — identity resolution/overrides, per-process sink
   paths, barrier/wall clock alignment math (synthesized streams with
   KNOWN offsets recovered exactly), straggler rules on synthesized
   heartbeats, the HLO collective parser, and the analytic comm model
   cross-checked EXACTLY against the real compiled fit programs.
2. **Simulated fleet** — two REAL worker processes
   (tests/fleet_worker.py; plain processes with env-override identity,
   so this tier needs no jax.distributed and runs on every container)
   produce per-process trace/heartbeat files; the merge, the straggler
   flag on the faults-injected slow host, and both CLIs are driven on
   the artifacts.
3. The REAL multi-process tier (barrier-synced alignment, obs=0 parity
   bit-exact under SPMD) lives in tests/mh_worker.py /
   test_multihost.py behind the jaxlib collective gate.
"""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from kmeans_tpu import KMeans, MiniBatchKMeans, obs
from kmeans_tpu.obs import cost as obs_cost
from kmeans_tpu.obs import fleet
from kmeans_tpu.obs.identity import identity, per_process_path
from kmeans_tpu.obs.trace import TraceReadError

REPO = Path(__file__).parent.parent


# ---------------------------------------------------------------------------
# Identity + per-process sinks
# ---------------------------------------------------------------------------

def test_identity_defaults_single_process():
    ident = identity()
    assert ident["process_index"] == 0
    assert ident["process_count"] == 1
    assert ident["host"]


def test_identity_env_override(monkeypatch):
    monkeypatch.setenv("KMEANS_TPU_PROCESS_INDEX", "3")
    monkeypatch.setenv("KMEANS_TPU_PROCESS_COUNT", "8")
    monkeypatch.setenv("KMEANS_TPU_HOST", "synth-a")
    assert identity() == {"process_index": 3, "process_count": 8,
                          "host": "synth-a"}


def test_identity_malformed_env_falls_through(monkeypatch):
    monkeypatch.setenv("KMEANS_TPU_PROCESS_INDEX", "not-an-int")
    ident = identity()
    assert ident["process_index"] == 0 and ident["process_count"] == 1


def test_per_process_path():
    assert per_process_path("trace.jsonl", 3) == "trace.p3.jsonl"
    assert per_process_path("/a/b/hb.jsonl", 0) == "/a/b/hb.p0.jsonl"
    assert per_process_path("noext", 2) == "noext.p2"
    # A dot inside a DIRECTORY name is not an extension.
    assert per_process_path("/a.b/noext", 1) == "/a.b/noext.p1"


def test_every_record_stamps_identity(tmp_path):
    with obs.tracing(tmp_path / "t.jsonl") as tr:
        with obs.span("dispatch", tag="x"):
            obs.event("dispatch.note", label="y")
    for rec in tr.records():
        assert rec["process_index"] == 0
        assert rec["process_count"] == 1
        assert rec["host"]
    header = json.loads((tmp_path / "t.jsonl").read_text()
                        .splitlines()[0])
    assert header["kind"] == "header" and "process_index" in header


def test_tracing_per_process_sink_policies(tmp_path, monkeypatch):
    # auto + single process: verbatim path (the r15 contract).
    with obs.tracing(tmp_path / "a.jsonl"):
        with obs.span("dispatch"):
            pass
    assert (tmp_path / "a.jsonl").exists()
    # forced True: suffixed even single-process.
    with obs.tracing(tmp_path / "b.jsonl", per_process=True):
        with obs.span("dispatch"):
            pass
    assert (tmp_path / "b.p0.jsonl").exists()
    assert not (tmp_path / "b.jsonl").exists()
    # auto + simulated process_count>1: suffixed (the collision fix).
    monkeypatch.setenv("KMEANS_TPU_PROCESS_INDEX", "1")
    monkeypatch.setenv("KMEANS_TPU_PROCESS_COUNT", "2")
    with obs.tracing(tmp_path / "c.jsonl"):
        with obs.span("dispatch"):
            pass
    assert (tmp_path / "c.p1.jsonl").exists()
    # primary-only alternative: non-zero process writes nothing.
    with obs.tracing(tmp_path / "d.jsonl", per_process=False):
        with obs.span("dispatch"):
            pass
    assert not (tmp_path / "d.jsonl").exists()
    assert not (tmp_path / "d.p1.jsonl").exists()
    # A typo'd policy raises up front — silently writing the verbatim
    # path on every host would reintroduce the torn-file collision.
    with pytest.raises(ValueError, match="per_process"):
        with obs.tracing(tmp_path / "e.jsonl", per_process="true"):
            pass


def test_heartbeat_per_process_sink_policies(tmp_path, monkeypatch):
    monkeypatch.setenv("KMEANS_TPU_PROCESS_INDEX", "1")
    monkeypatch.setenv("KMEANS_TPU_PROCESS_COUNT", "2")
    from kmeans_tpu.obs.heartbeat import Heartbeat
    hb = Heartbeat(tmp_path / "hb.jsonl")
    hb.beat({"iteration": 1})
    hb.close()
    assert hb.resolved_path == str(tmp_path / "hb.p1.jsonl")
    assert (tmp_path / "hb.p1.jsonl").exists()
    # primary-only on a non-zero process: file sink off, callback
    # still fires, and the skip is NOT an error.
    got = []
    hb2 = Heartbeat(tmp_path / "x.jsonl", callback=got.append,
                    per_process=False)
    hb2.beat({"iteration": 1})
    hb2.close()
    assert not (tmp_path / "x.jsonl").exists()
    assert len(got) == 1 and hb2.sink_errors == 0
    with pytest.raises(ValueError, match="per_process"):
        Heartbeat(tmp_path / "y.jsonl", per_process="sometimes")


def test_heartbeat_records_stamp_identity_and_registry_json(monkeypatch):
    monkeypatch.setenv("KMEANS_TPU_PROCESS_INDEX", "2")
    monkeypatch.setenv("KMEANS_TPU_PROCESS_COUNT", "4")
    monkeypatch.setenv("KMEANS_TPU_HOST", "synth-b")
    got = []
    with obs.heartbeat(callback=got.append):
        obs.note_progress(iteration=1)
    assert got[0]["process_index"] == 2
    assert got[0]["process_count"] == 4
    assert got[0]["host"] == "synth-b"
    payload = json.loads(obs.registry().to_json())
    assert payload["__identity__"]["process_index"] == 2


# ---------------------------------------------------------------------------
# Clock alignment (synthesized streams with KNOWN offsets)
# ---------------------------------------------------------------------------

def _stream(idx, *, wall0, barriers, spans=(), host=None, synced=True,
            count=2):
    """A minimal in-memory trace stream: barrier events at the given
    tracer-relative times plus optional (name, t0, dur) spans."""
    host = host or f"h{idx}"
    rid = [0]

    def rec(kind, name, t0, dur=None, attrs=None):
        rid[0] += 1
        r = {"kind": kind, "name": name, "id": rid[0], "parent": None,
             "depth": 0, "tid": 1, "process_index": idx,
             "process_count": count, "host": host, "t0": t0,
             "t1": None if dur is None else t0 + dur,
             "dur": dur if kind == "span" else 0.0}
        if attrs:
            r["attrs"] = attrs
        return r

    records = []
    for i, tb in enumerate(barriers):
        records.append(rec("event", "fleet.barrier", tb,
                           attrs={"tag": f"fit-{i}", "synced": synced}))
    for name, t0, dur in spans:
        records.append(rec("span", name, t0, dur))
    return {"path": f"<mem{idx}>", "header": None, "records": records,
            "process_index": idx, "process_count": count, "host": host,
            "wall0": wall0}


def test_barrier_alignment_recovers_known_offsets():
    # Host 1's monotonic clock started 5.0 s "later": its barrier times
    # are 5.0 smaller.  Two barriers with 1 ms relative drift.
    s0 = _stream(0, wall0=1000.0, barriers=[2.0, 10.0],
                 spans=[("dispatch", 3.0, 0.5)])
    s1 = _stream(1, wall0=1004.9, barriers=[-3.0, 5.001],
                 spans=[("dispatch", -2.0, 0.6)])
    m = fleet.merge_traces([s0, s1])
    assert m["align"] == "barrier" and m["barriers"] == 2
    off = {h["process_index"]: h["offset_s"] for h in m["hosts"]}
    assert off[0] == 0.0
    assert off[1] == pytest.approx(5.0)
    assert m["skew_bound_s"] == pytest.approx(0.001)
    # Host 1's dispatch lands at -2.0 + 5.0 = 3.0 on the merged clock.
    d1 = [r for r in m["records"] if r.get("kind") == "span"
          and r["process_index"] == 1][0]
    assert d1["t0"] == pytest.approx(3.0)
    assert d1["t1"] == pytest.approx(3.6)
    assert d1["fleet_merged"] is True
    # wall anchors disagree with the barrier by 0.1 s — reported.
    assert m["ntp_delta_s"] == pytest.approx(0.1, abs=1e-6)


def test_wall_alignment_when_no_synced_barriers():
    s0 = _stream(0, wall0=1000.0, barriers=[2.0], synced=False,
                 spans=[("dispatch", 0.0, 0.1)])
    s1 = _stream(1, wall0=1003.0, barriers=[2.0], synced=False,
                 spans=[("dispatch", 0.0, 0.1)])
    m = fleet.merge_traces([s0, s1])
    assert m["align"] == "wall"
    assert m["skew_bound_s"] is None
    off = {h["process_index"]: h["offset_s"] for h in m["hosts"]}
    assert off[1] == pytest.approx(3.0)


def test_unalignable_and_malformed_classify():
    s0 = _stream(0, wall0=None, barriers=[], synced=False)
    s1 = _stream(1, wall0=None, barriers=[], synced=False)
    with pytest.raises(TraceReadError, match="clock-unalignable"):
        fleet.merge_traces([s0, s1])
    # Mismatched barrier tag sequences: different runs.
    sa = _stream(0, wall0=1.0, barriers=[1.0])
    sb = _stream(1, wall0=1.0, barriers=[1.0])
    sb["records"][0]["attrs"]["tag"] = "other"
    with pytest.raises(TraceReadError, match="tag sequences"):
        fleet.merge_traces([sa, sb])
    # Duplicate process index: double-counted host.
    with pytest.raises(TraceReadError, match="duplicate process_index"):
        fleet.merge_traces([_stream(0, wall0=1.0, barriers=[1.0]),
                            _stream(0, wall0=1.0, barriers=[1.0])])


def test_single_stream_merge_is_trivial():
    s0 = _stream(0, wall0=1.0, barriers=[], synced=False,
                 spans=[("dispatch", 0.0, 0.1)], count=1)
    m = fleet.merge_traces([s0])
    assert m["align"] == "single" and len(m["hosts"]) == 1
    assert m["hosts"][0]["offset_s"] == 0.0


def test_chrome_export_tracks_per_host():
    s0 = _stream(0, wall0=1.0, barriers=[0.0],
                 spans=[("dispatch", 0.5, 0.1)])
    s1 = _stream(1, wall0=1.0, barriers=[0.0],
                 spans=[("dispatch", 0.5, 0.1)])
    m = fleet.merge_traces([s0, s1])
    evs = obs.chrome_events(m["records"])
    meta = [e for e in evs if e.get("ph") == "M"]
    assert {e["pid"] for e in meta} == {0, 1}
    assert any("h1" in e["args"]["name"] for e in meta)
    body_pids = {e["pid"] for e in evs if e.get("ph") != "M"}
    assert body_pids == {0, 1}


# ---------------------------------------------------------------------------
# Straggler rules (synthesized heartbeats)
# ---------------------------------------------------------------------------

def _beats(idx, *, t0, n, dt, rows=1000, host=None, last_iter=None):
    out = []
    for i in range(n):
        rec = {"ts": t0 + i * dt, "mono": t0 + i * dt,
               "process_index": idx, "process_count": 2,
               "host": host or f"h{idx}", "iteration": i + 1,
               "rows": rows, "phase": "iteration"}
        if i > 0:
            rec["rows_per_sec"] = rows / dt
        out.append(rec)
    if last_iter is not None:
        for rec in out:
            rec["iteration"] = min(rec["iteration"], last_iter)
    return out


def test_straggler_slow_host_flags_and_healthy_silent():
    fast = _beats(0, t0=100.0, n=8, dt=0.01)
    slow = _beats(1, t0=100.0, n=8, dt=0.15)
    rep = fleet.straggler_report(fast + slow)
    assert rep["flagged"] == [1]
    host1 = [h for h in rep["hosts"] if h["process_index"] == 1][0]
    assert "slow" in host1["flags"]
    healthy = fleet.straggler_report(
        _beats(0, t0=100.0, n=8, dt=0.01)
        + _beats(1, t0=100.0, n=8, dt=0.011))
    assert healthy["healthy"], healthy


def test_straggler_behind_and_stalled():
    fast = _beats(0, t0=100.0, n=10, dt=0.5)
    # Host 1 stopped beating at iteration 3, long ago.
    lag = _beats(1, t0=100.0, n=3, dt=0.5)
    rep = fleet.straggler_report(fast + lag)
    host1 = [h for h in rep["hosts"] if h["process_index"] == 1][0]
    assert "behind" in host1["flags"]
    assert "stalled" in host1["flags"]
    assert rep["fleet"]["leader_iteration"] == 10


def test_finished_fleet_stays_silent_posthoc():
    """A fast finisher's last beat is OLD post-hoc — it must not flag
    'stalled' (it is not behind); completed fleets report healthy."""
    fast = _beats(0, t0=100.0, n=8, dt=0.02)
    late = _beats(1, t0=100.0, n=8, dt=0.025)
    rep = fleet.straggler_report(fast + late)
    assert rep["healthy"], rep


def test_straggler_report_empty_raises():
    with pytest.raises(TraceReadError):
        fleet.straggler_report([])


def test_live_paused_fleet_flags_stalled_with_explicit_now():
    """ISSUE 19 regression: a LIVE read (explicit ``now``, the
    ``fleet-status --now`` / autopilot path) of a fleet whose hosts all
    sit at the SAME iteration but stopped beating mid-fit must classify
    them ``stalled`` — under the old behind-only rule this paused fleet
    read healthy and the autopilot could never evict it."""
    recs = _beats(0, t0=100.0, n=8, dt=0.02) \
        + _beats(1, t0=100.0, n=8, dt=0.02)
    # Nobody is behind; every last beat is mid-fit and 60 s old.
    rep = fleet.straggler_report(recs, now=100.0 + 8 * 0.02 + 60.0)
    host_flags = {h["process_index"]: h["flags"] for h in rep["hosts"]}
    assert "stalled" in host_flags[0]
    assert "stalled" in host_flags[1]
    assert not rep["healthy"]
    # Post-hoc (default now) keeps the old behind-only semantics.
    assert fleet.straggler_report(recs)["healthy"]


def test_live_finished_fleet_stays_healthy_with_explicit_now():
    """The terminal completion beat (phase='finished', emitted at the
    end of fit()) exempts a DONE host from the live stall rule: old
    silence after a terminal beat is completion, not a hang."""
    recs = []
    for idx in range(2):
        beats = _beats(idx, t0=100.0, n=8, dt=0.02)
        done = dict(beats[-1])
        done["ts"] = done["mono"] = beats[-1]["ts"] + 0.01
        done["phase"] = "finished"
        done.pop("rows_per_sec", None)
        recs.extend(beats + [done])
    rep = fleet.straggler_report(recs, now=100.0 + 8 * 0.02 + 60.0)
    assert rep["healthy"], rep
    assert all(h["phase"] == "finished" for h in rep["hosts"])


def test_straggler_rows_carry_last_beat_ts():
    """Report rows expose the last beat's ``ts`` — the autopilot's
    per-incarnation stall gate keys on it."""
    rep = fleet.straggler_report(_beats(0, t0=100.0, n=3, dt=0.5))
    assert rep["hosts"][0]["ts"] == pytest.approx(101.0)


def test_fit_emits_terminal_finished_beat():
    """A completed fit()'s LAST beat is the terminal completion beat
    (phase='finished') the live stall rule keys on."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(256, 4)).astype(np.float32)
    got = []
    with obs.heartbeat(callback=got.append, min_period_s=0.0):
        KMeans(k=3, max_iter=3, seed=0, verbose=False).fit(X)
    beats = [r for r in got if not r.get("tick")]
    assert beats and beats[-1]["phase"] == "finished"
    # The terminal beat repeats the final iteration — no rate sample,
    # so fleet rate medians are unchanged by completion.
    assert "rows_per_sec" not in beats[-1] or \
        beats[-1]["iteration"] == beats[-2]["iteration"]


# ---------------------------------------------------------------------------
# Collective-comms accounting
# ---------------------------------------------------------------------------

def test_hlo_collective_parser():
    txt = """
  %all-reduce.8 = f32[16,32]{1,0} all-reduce(f32[16,32]{1,0} %x), replica_groups={{0,1,2,3}}
  %all-gather.5 = f32[4,32]{1,0} all-gather(f32[1,32]{1,0} %y), dimensions={0}
  %ars = (f32[16]{0}, f32[16]{0}) all-reduce-start(f32[16]{0} %p)
  %ard = f32[16]{0} all-reduce-done(f32[16]{0} %ars)
  ROOT %t = (f32[16,32]{1,0}) tuple(f32[16,32]{1,0} %all-reduce.8)
"""
    got = obs_cost.hlo_collective_bytes(txt)
    # 16*32*4 + 4*32*4 + 16*4 (start counted once, done skipped).
    assert got["bytes"] == 2048 + 512 + 64
    assert got["count"] == 3
    assert got["by_op"]["all-reduce"] == 2048 + 64
    assert got["by_op"]["all-gather"] == 512


@pytest.fixture(scope="module")
def mesh4():
    import jax
    from kmeans_tpu.parallel.mesh import make_mesh
    return make_mesh(data=4, model=1, devices=jax.devices()[:4])


def test_comm_crosscheck_kmeans_exact(mesh4):
    """The committed band: the analytic model and the compiled kmeans
    fit program agree on collective bytes (CPU rows match to the byte;
    the ±10% band absorbs backend/version variation)."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(4096, 32)).astype(np.float32)
    with obs_cost.collecting() as col:
        KMeans(k=16, max_iter=3, tolerance=1e-30, seed=0, mesh=mesh4,
               chunk_size=256, host_loop=False, empty_cluster="keep",
               compute_sse=True, verbose=False).fit(X)
    recs = [r for r in col.records() if r.available and r.flops]
    step = max(recs, key=lambda r: r.flops)
    assert step.collective_bytes and step.collectives == 3
    model = fleet.comm_bytes_model("kmeans", k=16, d=32, data_shards=4,
                                   compute_sse=True)
    cc = fleet.comm_crosscheck(model, step)
    assert cc["agree"] is True, cc
    assert cc["ratio"] == pytest.approx(1.0, abs=1e-9)
    # Committed constants are what the artifacts publish.
    assert cc["rtol"] == fleet.COMM_AGREEMENT_RTOL == 0.10
    table = fleet.format_comm_table(model, cc)
    assert "estep.psum_sums" in table and "ratio=1.000" in table


def test_comm_crosscheck_gmm_diag_exact(mesh4):
    from kmeans_tpu import GaussianMixture
    rng = np.random.default_rng(0)
    X = rng.normal(size=(2048, 16)).astype(np.float32)
    with obs_cost.collecting() as col:
        GaussianMixture(n_components=8, covariance_type="diag",
                        max_iter=3, tol=0.0, init_params="random",
                        seed=0, mesh=mesh4, chunk_size=128,
                        host_loop=False, verbose=False).fit(X)
    recs = [r for r in col.records() if r.available and r.flops]
    step = max(recs, key=lambda r: r.flops)
    model = fleet.comm_bytes_model("gmm", k=8, d=16, data_shards=4,
                                   cov_type="diag", acc_bytes=4)
    cc = fleet.comm_crosscheck(model, step)
    assert cc["agree"] is True, cc


def test_comm_model_shapes():
    m = fleet.comm_bytes_model("kmeans", k=10, d=8, data_shards=4,
                               model_shards=2, compute_sse=False,
                               n_members=3)
    assert m["k_pad"] == 10            # already a multiple of 2
    sites = {s["site"]: s for s in m["sites"]}
    assert sites["estep.psum_sums"]["result_bytes"] == 3 * 10 * 8 * 4
    assert "estep.psum_sse" not in sites
    assert "tp.gather_centroid_table" in sites
    # Ring wire estimate: all-reduce pays 2(S-1)/S of its payload.
    s = sites["estep.psum_counts"]
    assert s["wire_bytes_per_device"] == pytest.approx(
        2 * 7 / 8 * s["result_bytes"])
    with pytest.raises(ValueError, match="unknown family"):
        fleet.comm_bytes_model("mystery", k=2, d=2)
    # Seeding + process-local sites are outside the fit program.
    m2 = fleet.comm_bytes_model("kmeans", k=4, d=8, data_shards=4,
                                seeding_rounds=3, seeding_cap=8,
                                processes=2)
    s2 = {s["site"]: s for s in m2["sites"]}
    assert s2["seed.gather_topk"]["count"] == 3
    assert not s2["seed.gather_topk"]["in_program"]
    assert not s2["data.process_allgather_counts"]["in_program"]
    assert m2["hlo_program_bytes"] < m2["per_iteration_bytes"] \
        + m2["per_fit_bytes"]


def test_phase_table_comm_join():
    from kmeans_tpu.utils.profiling import phase_ceiling_table
    ladder = [{"phase": "a", "seconds": 0.1, "cumulative": 0.1,
               "spread": 0.0},
              {"phase": "b", "seconds": 0.2, "cumulative": 0.3,
               "spread": 0.0}]
    model = fleet.comm_bytes_model("kmeans", k=4, d=8, data_shards=4)
    rows = phase_ceiling_table(ladder, comm_model=model)
    assert "comm_bytes_per_iter" not in rows[0]
    assert rows[-1]["comm_bytes_per_iter"] == \
        model["per_iteration_bytes"]
    # TTFI join: the first_dispatch row carries the comm columns and
    # the formatter prints the trailing comm line.
    with obs.tracing() as tr:
        with obs.span("dispatch"):
            pass
    ttfi = obs.time_to_first_iteration(tr.records(), comm_model=model)
    assert ttfi[-1]["phase"] == "first_dispatch"
    assert ttfi[-1]["comm_bytes_per_iter"] > 0
    txt = obs.format_phase_table(ttfi)
    assert "comm (first_dispatch)" in txt


# ---------------------------------------------------------------------------
# rows_per_sec
# ---------------------------------------------------------------------------

def test_rows_per_sec_host_loop():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1024, 8)).astype(np.float32)
    got = []
    with obs.heartbeat(callback=got.append):
        KMeans(k=8, seed=0, max_iter=5, tolerance=1e-30,
               host_loop=True, empty_cluster="keep",
               verbose=False).fit(X)
    iter_beats = [r for r in got if r.get("phase") == "iteration"]
    assert all(r["rows"] == 1024 for r in iter_beats)
    rated = [r for r in iter_beats if "rows_per_sec" in r]
    assert rated and all(r["rows_per_sec"] > 0 for r in rated)


def test_rows_per_sec_minibatch_is_batch():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(2048, 8)).astype(np.float32)
    got = []
    with obs.heartbeat(callback=got.append):
        MiniBatchKMeans(k=4, seed=0, batch_size=256, max_iter=4,
                        host_loop=True, verbose=False).fit(X)
    iter_beats = [r for r in got if "rows" in r]
    assert iter_beats
    # Effective batch: >= the requested batch (sublane rounding), far
    # below the dataset size — minibatch reports sampled rows.
    assert all(256 <= r["rows"] < 2048 for r in iter_beats)


def test_obs0_parity_with_fleet_instrumentation(mesh4):
    """The fleet prelude (barrier + rows bookkeeping) must not move the
    trajectory: instrumented == plain, bit-exact."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(2048, 16)).astype(np.float32)
    kw = dict(k=8, seed=0, max_iter=4, tolerance=1e-30, mesh=mesh4,
              chunk_size=128, empty_cluster="keep", compute_sse=True,
              verbose=False)
    plain = KMeans(**kw).fit(X)
    with obs.tracing() as tr, obs.heartbeat(callback=lambda r: None):
        inst = KMeans(**kw).fit(X)
    assert inst.iterations_run == plain.iterations_run
    np.testing.assert_array_equal(inst.centroids, plain.centroids)
    assert inst.sse_history == plain.sse_history
    evs = [r for r in tr.records() if r.get("kind") == "event"
           and r["name"] == "fleet.barrier"]
    assert len(evs) == 1
    assert evs[0]["attrs"] == {"tag": "fit-start", "synced": False}


# ---------------------------------------------------------------------------
# Simulated two-process fleet (REAL subprocesses, env-override identity)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fleet_run(tmp_path_factory):
    out = tmp_path_factory.mktemp("fleet")
    import os
    env = dict(os.environ)
    env["PYTHONPATH"] = ":".join(
        p for p in [str(REPO), env.get("PYTHONPATH")] if p)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    worker = REPO / "tests" / "fleet_worker.py"
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(i), "2", str(out)]
        + (["--slow", "0.12"] if i == 1 else []),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in range(2)]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o[-3000:]
    return out


def test_simulated_fleet_sinks_and_merge(fleet_run):
    out = fleet_run
    # Per-process sinks: no shared-file tear.
    for i in range(2):
        assert (out / f"trace.p{i}.jsonl").exists()
        assert (out / f"hb.p{i}.jsonl").exists()
    merged = fleet.merge_traces(sorted(out.glob("trace.p*.jsonl")))
    assert [h["process_index"] for h in merged["hosts"]] == [0, 1]
    # Plain processes share no real barrier — the wall fallback (one
    # machine, one clock) applies; offsets are start-skew sized.
    assert merged["align"] == "wall"
    present = {r["process_index"] for r in merged["records"]}
    assert present == {0, 1}
    assert all(r.get("host", "").startswith("simhost")
               for r in merged["records"])


def test_simulated_fleet_straggler_flags(fleet_run):
    hb = fleet.merge_heartbeats(sorted(fleet_run.glob("hb.p*.jsonl")))
    rep = fleet.straggler_report(hb)
    assert 1 in rep["flagged"], rep
    assert 0 not in rep["flagged"], rep
    host1 = [h for h in rep["hosts"] if h["process_index"] == 1][0]
    assert "slow" in host1["flags"]
    # The injected delay must not have moved arithmetic: both workers
    # ran the same seeded fit.
    c0 = np.load(fleet_run / "centroids_0.npy")
    c1 = np.load(fleet_run / "centroids_1.npy")
    np.testing.assert_array_equal(c0, c1)


# ---------------------------------------------------------------------------
# CLIs
# ---------------------------------------------------------------------------

def test_fleet_status_cli(fleet_run, capsys):
    from kmeans_tpu.cli import fleet_status_main
    rc = fleet_status_main([str(fleet_run)])
    cap = capsys.readouterr()
    assert rc == 1                       # stragglers flagged
    assert "STRAGGLERS" in cap.out and "simhost1" in cap.out
    rc = fleet_status_main([str(fleet_run), "--json"])
    cap = capsys.readouterr()
    payload = json.loads(cap.out)
    assert payload["flagged"] == [1]
    assert len(payload["files"]) == 2    # trace files were filtered out


def test_fleet_status_cli_healthy_and_errors(tmp_path, capsys):
    from kmeans_tpu.cli import fleet_status_main
    for i in range(2):
        p = tmp_path / f"hb.p{i}.jsonl"
        p.write_text("".join(
            json.dumps(r) + "\n"
            for r in _beats(i, t0=10.0, n=5, dt=0.01)))
    assert fleet_status_main([str(tmp_path)]) == 0
    assert "HEALTHY" in capsys.readouterr().out
    # Unreadable input: exit 2.
    assert fleet_status_main([str(tmp_path / "nope")]) == 2
    assert "error:" in capsys.readouterr().err
    # A directory with only trace files: exit 2 with guidance.
    tdir = tmp_path / "traces"
    tdir.mkdir()
    (tdir / "t.jsonl").write_text(
        json.dumps({"kind": "header", "wall0": 0.0}) + "\n")
    assert fleet_status_main([str(tdir)]) == 2
    assert "trace" in capsys.readouterr().err


def test_trace_summarize_multi_file_cli(fleet_run, tmp_path, capsys):
    from kmeans_tpu.cli import trace_main
    files = sorted(str(p) for p in fleet_run.glob("trace.p*.jsonl"))
    chrome = tmp_path / "chrome.json"
    rc = trace_main(["summarize", *files, "--chrome", str(chrome)])
    cap = capsys.readouterr()
    assert rc == 0
    assert "fleet timeline: 2 hosts" in cap.out
    assert "align=wall" in cap.out
    evs = json.loads(chrome.read_text())["traceEvents"]
    assert {e["pid"] for e in evs if e.get("ph") == "M"} == {0, 1}
    # Directory form: the dir also holds heartbeat sink files (the
    # natural co-location obs.tracing + obs.heartbeat produce) — they
    # are SKIPPED, not a failure (review finding: the advertised
    # directory mode must work on the layout the sinks themselves
    # write).
    rc = trace_main(["summarize", str(fleet_run), "--json"])
    cap = capsys.readouterr()
    assert rc == 0
    payload = json.loads(cap.out)
    assert payload["fleet"]["align"] == "wall"
    assert len(payload["fleet"]["hosts"]) == 2
    assert all("trace" in Path(f).name for f in payload["files"])
    assert payload["time_to_first_iteration"] is None
    # The explicit glob form behaves identically.
    rc = trace_main(["summarize", str(fleet_run / "trace.p*.jsonl"),
                     "--json"])
    cap = capsys.readouterr()
    assert rc == 0
    assert len(json.loads(cap.out)["fleet"]["hosts"]) == 2
    # A directory holding ONLY heartbeat files still exits 2, with
    # guidance pointing at fleet-status.
    rc = trace_main(["summarize", str(fleet_run / "hb.p0.jsonl"),
                     str(fleet_run / "hb.p1.jsonl")])
    cap = capsys.readouterr()
    assert rc == 2
    assert "fleet-status" in cap.err


def test_trace_summarize_single_file_contract_unchanged(fleet_run,
                                                        capsys):
    from kmeans_tpu.cli import trace_main
    one = sorted(fleet_run.glob("trace.p0.jsonl"))[0]
    rc = trace_main(["summarize", str(one)])
    cap = capsys.readouterr()
    assert rc == 0
    assert "time-to-first-iteration" in cap.out
    assert "fleet timeline" not in cap.out


def test_trace_summarize_malformed_multi_exits_2(tmp_path, capsys):
    from kmeans_tpu.cli import trace_main
    good = tmp_path / "a.jsonl"
    good.write_text(json.dumps({"kind": "span", "name": "dispatch",
                                "id": 1, "t0": 0.0, "dur": 1.0}) + "\n")
    bad = tmp_path / "b.jsonl"
    bad.write_text("{not json\n")
    assert trace_main(["summarize", str(good), str(bad)]) == 2
    assert "error:" in capsys.readouterr().err
    # Unalignable pair (no headers, no barriers): exit 2 too.
    g2 = tmp_path / "c.jsonl"
    g2.write_text(json.dumps({"kind": "span", "name": "dispatch",
                              "id": 1, "t0": 0.0, "dur": 1.0}) + "\n")
    assert trace_main(["summarize", str(good), str(g2)]) == 2
    assert "clock-unalignable" in capsys.readouterr().err


def test_fleet_status_wired_in_main():
    import subprocess as sp
    out = sp.run([sys.executable, "-m", "kmeans_tpu", "fleet-status",
                  "/nonexistent-dir-xyz"], capture_output=True,
                 text=True, cwd=str(REPO))
    assert out.returncode == 2
    assert "error" in out.stderr


# ---------------------------------------------------------------------------
# Heartbeat file reading edge cases
# ---------------------------------------------------------------------------

def test_read_heartbeats_tolerates_torn_tail(tmp_path):
    p = tmp_path / "hb.jsonl"
    p.write_text(json.dumps({"ts": 1.0, "iteration": 1}) + "\n"
                 + '{"ts": 2.0, "iter')      # live writer mid-line
    recs = fleet.read_heartbeats(p)
    assert len(recs) == 1
    bad = tmp_path / "bad.jsonl"
    bad.write_text("{nope\n" + json.dumps({"ts": 1.0}) + "\n")
    with pytest.raises(TraceReadError):
        fleet.read_heartbeats(bad)
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    with pytest.raises(TraceReadError, match="no heartbeat records"):
        fleet.read_heartbeats(empty)


def test_expand_fleet_paths(tmp_path):
    (tmp_path / "a.jsonl").write_text("{}\n")
    (tmp_path / "b.jsonl").write_text("{}\n")
    got = fleet.expand_fleet_paths(tmp_path)
    assert [Path(p).name for p in got] == ["a.jsonl", "b.jsonl"]
    with pytest.raises(TraceReadError, match="no such file"):
        fleet.expand_fleet_paths(tmp_path / "missing.jsonl")
    with pytest.raises(TraceReadError, match="matched no files"):
        fleet.expand_fleet_paths(str(tmp_path / "*.nope"))
    empty = tmp_path / "emptydir"
    empty.mkdir()
    with pytest.raises(TraceReadError, match="no .jsonl"):
        fleet.expand_fleet_paths(empty)
