"""Elastic resume (ISSUE 5): topology-portable checkpoints, OOM-graceful
chunk backoff, and in-loop divergence rollback — every claim proved
through the real code path under deterministic injection
(``utils.faults``), the ISSUE-4 discipline.

Parity classes (measured on this platform, pinned accordingly):

* **Bit-exact cross-mesh** — the K-Means family's device/host loops at
  ``dtype=float64``: f32-width data sums EXACTLY in f64 (24-bit
  mantissas + small exponent spread < 53 bits), so the psum/scan
  regrouping a different mesh width or scan chunk implies is invariant
  and the centroid trajectory is bitwise identical.  The cross-mesh
  resume matrix and the injected-OOM replay pin this with
  ``assert_array_equal``.  (``sse_history`` is a deliberate f32
  reduction — ``distributed._sse_from_stats`` — and is compared
  to rtol instead.)
* **Last-ulp cross-mesh** — the mixture E-pass accumulates
  softmax-weighted moments whose exponent spread defeats exact f64
  summation: cross-mesh GMM trajectories agree to ~1e-14 relative
  (measured 4e-15 at the test shapes) with identical iteration counts;
  pinned with tight ``allclose``.  Same-topology resume through the
  CANONICAL (trimmed) table round trip stays bitwise — pinned.
* **Stream-divergent** — MiniBatch draws its batches per-shard, so a
  different data-mesh width IS a different batch sequence (the r5
  forgy-note class of documented RNG-stream divergence): cross-mesh
  resume is pinned to run/complete with the same iteration budget and
  a healthy final state, not bitwise.
"""

import os

import jax
import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans, NumericalDivergenceError
from kmeans_tpu.models import (BisectingKMeans, GaussianMixture,
                               MiniBatchKMeans, SphericalKMeans)
from kmeans_tpu.models.fault_tolerance import is_oom_error
from kmeans_tpu.parallel.mesh import make_mesh
from kmeans_tpu.parallel.sharding import backoff_chunk
from kmeans_tpu.utils import checkpoint as ckpt
from kmeans_tpu.utils import faults

WIDTHS = (1, 2, 4, 8)


def _mesh(w, m=1):
    if len(jax.devices()) < w * m:
        pytest.skip(f"needs {w * m} devices")
    return make_mesh(data=w, model=m, devices=jax.devices()[: w * m])


def _blobs(n=2000, d=3, centers=4, rs=9):
    # n=2000/rs=9 runs ~17 Lloyd iterations at tolerance=1e-12 (the
    # test_faults fixture): long enough that every kill boundary below
    # lands MID-fit.
    X, _ = make_blobs(n_samples=n, centers=centers, n_features=d,
                      random_state=rs)
    return X.astype(np.float32)


def _blocks_of(X, rows=256):
    def make_blocks():
        def gen():
            for i in range(0, X.shape[0], rows):
                yield X[i: i + rows]
        return gen()
    return make_blocks


def _fit_killed(model, j, fit_call):
    with faults.inject_kill_after_iteration(j) as rec:
        with pytest.raises(faults.SimulatedPreemption):
            fit_call(model)
    assert rec["fired_at"] is not None and rec["fired_at"] >= j
    return rec["fired_at"]


# ------------------------------------------- cross-mesh parity matrix

_KM_KW = dict(k=4, max_iter=14, tolerance=1e-12, seed=1, compute_sse=True,
              empty_cluster="keep", host_loop=False, verbose=False,
              dtype=np.float64)

# Module-level caches so the {1,2,4,8} x {1,2,4,8} matrix costs
# 4 uninterrupted fits + 4 killed checkpoints, not 16 of each.
_FULL_RUNS: dict = {}
_CKPTS: dict = {}


def _full_on(width) -> KMeans:
    if width not in _FULL_RUNS:
        _FULL_RUNS[width] = KMeans(mesh=_mesh(width), **_KM_KW).fit(
            _blobs())
    return _FULL_RUNS[width]


def _ckpt_from(width, tmp_path_factory) -> str:
    if width not in _CKPTS:
        p = str(tmp_path_factory.mktemp(f"xmesh{width}") / "ck.npz")
        _fit_killed(
            KMeans(mesh=_mesh(width), **_KM_KW), 4,
            lambda m: m.fit(_blobs(), checkpoint_every=2,
                            checkpoint_path=p))
        _CKPTS[width] = p
    return _CKPTS[width]


@pytest.mark.parametrize("resume_w", WIDTHS)
@pytest.mark.parametrize("write_w", WIDTHS)
def test_kmeans_cross_mesh_matrix(tmp_path_factory, write_w, resume_w):
    """The full write-on-N x resume-on-M matrix, device loop, float64:
    a checkpoint killed mid-fit on an N-way mesh resumes on an M-way
    mesh BIT-identical (centroids, iteration count) to the
    uninterrupted fit on the M-way mesh — the acceptance pin."""
    full = _full_on(resume_w)
    p = _ckpt_from(write_w, tmp_path_factory)
    info = ckpt.describe_checkpoint(p)
    assert info["written_on_mesh"]["data_shards"] == write_w
    resumed = KMeans(mesh=_mesh(resume_w), **_KM_KW)
    resumed.fit(_blobs(), resume=p)
    assert resumed.iterations_run == full.iterations_run
    np.testing.assert_array_equal(resumed.centroids, full.centroids)
    # SSE history is a deliberate f32 device reduction (not part of the
    # trajectory) — regrouping across meshes moves the last ulp.
    np.testing.assert_allclose(resumed.sse_history, full.sse_history,
                               rtol=1e-6)


def test_kmeans_cross_mesh_host_loop(tmp_path):
    """Host-loop cell: the f64 host finish consumes f64-exact device
    statistics, so write-on-8 -> resume-on-2 is bitwise there too."""
    kw = dict(_KM_KW, host_loop=True)
    X = _blobs()
    full = KMeans(mesh=_mesh(2), **kw).fit(X)
    p = tmp_path / "host.npz"
    _fit_killed(KMeans(mesh=_mesh(8), **kw), 4,
                lambda m: m.fit(X, checkpoint_every=2,
                                checkpoint_path=p))
    resumed = KMeans(mesh=_mesh(2), **kw)
    resumed.fit(X, resume=p)
    assert resumed.iterations_run == full.iterations_run
    np.testing.assert_array_equal(resumed.centroids, full.centroids)


@pytest.mark.parametrize("write_w,resume_w", [(8, 2), (2, 8)])
def test_bisecting_cross_mesh(tmp_path, write_w, resume_w):
    X = _blobs(n=1500, d=4, centers=6, rs=2)
    kw = dict(k=6, max_iter=18, tolerance=1e-10, seed=7, compute_sse=True,
              host_loop=False, verbose=False, dtype=np.float64)
    full = BisectingKMeans(mesh=_mesh(resume_w), **kw).fit(X)
    p = tmp_path / "bk.npz"
    _fit_killed(BisectingKMeans(mesh=_mesh(write_w), **kw), 3,
                lambda m: m.fit(X, checkpoint_every=1,
                                checkpoint_path=p))
    resumed = BisectingKMeans(mesh=_mesh(resume_w), **kw)
    resumed.fit(X, resume=p)
    assert resumed.iterations_run == full.iterations_run
    np.testing.assert_array_equal(resumed.centroids, full.centroids)
    np.testing.assert_array_equal(resumed.labels_, full.labels_)


@pytest.mark.parametrize("write_w,resume_w", [(8, 2), (2, 8)])
def test_spherical_cross_mesh(tmp_path, write_w, resume_w):
    """Spherical projects through full-mantissa divisions whose last
    ulp is platform-fusion-sensitive: iteration counts pin exactly,
    directions to 1e-12 (measured 1 ulp at this shape)."""
    X = _blobs(d=4)
    kw = dict(k=4, max_iter=20, tolerance=1e-12, seed=3, compute_sse=True,
              empty_cluster="keep", host_loop=False, verbose=False,
              dtype=np.float64)
    full = SphericalKMeans(mesh=_mesh(resume_w), **kw).fit(X)
    p = tmp_path / "sp.npz"
    _fit_killed(SphericalKMeans(mesh=_mesh(write_w), **kw), 4,
                lambda m: m.fit(X, checkpoint_every=2,
                                checkpoint_path=p))
    resumed = SphericalKMeans(mesh=_mesh(resume_w), **kw)
    resumed.fit(X, resume=p)
    assert resumed.iterations_run == full.iterations_run
    np.testing.assert_allclose(resumed.centroids, full.centroids,
                               rtol=0, atol=1e-12)
    assert np.allclose(np.linalg.norm(resumed.centroids, axis=1), 1.0,
                       atol=1e-9)


@pytest.mark.parametrize("cov_type", ["diag", "full", "tied",
                                      "spherical"])
@pytest.mark.parametrize("write_w,resume_w", [(8, 2), (2, 8)])
def test_gmm_cross_mesh(tmp_path, cov_type, write_w, resume_w):
    """Mixture cells, all four covariance types: iteration counts and
    convergence pin exactly; parameters to the measured last-ulp
    cross-mesh class (softmax-weighted f64 moments regroup at ~1e-15;
    see module docstring)."""
    X = _blobs(n=1500)
    kw = dict(n_components=4, covariance_type=cov_type, tol=1e-6,
              max_iter=60, init_params="random", seed=0, host_loop=False,
              verbose=False, dtype=np.float64)
    full = GaussianMixture(mesh=_mesh(resume_w), **kw).fit(X)
    assert full.converged_          # the comparison needs a settled run
    p = tmp_path / "g.npz"
    _fit_killed(GaussianMixture(mesh=_mesh(write_w), **kw), 4,
                lambda m: m.fit(X, checkpoint_every=2,
                                checkpoint_path=p))
    resumed = GaussianMixture(mesh=_mesh(resume_w), **kw)
    resumed.fit(X, resume=p)
    assert resumed.n_iter_ == full.n_iter_
    assert resumed.converged_ == full.converged_
    np.testing.assert_allclose(resumed.means_, full.means_,
                               rtol=1e-10, atol=1e-11)
    np.testing.assert_allclose(resumed.covariances_, full.covariances_,
                               rtol=1e-9, atol=1e-11)
    np.testing.assert_allclose(resumed.weights_, full.weights_,
                               rtol=1e-9, atol=1e-12)


def test_gmm_cross_tp_layout(tmp_path):
    """TP-layout portability with k NOT divisible by the model axis
    (k=5: k_pad differs between TP=2 and TP=1): the canonical trimmed
    dev tables re-pad for the resuming layout.  Also pins the
    same-layout round trip through the canonical format BITWISE —
    trimming + re-padding must reproduce the padded carry exactly."""
    X = _blobs()
    kw = dict(n_components=5, tol=1e-6, max_iter=80, init_params="random",
              seed=0, host_loop=False, verbose=False, dtype=np.float64)
    mesh_tp = _mesh(4, 2)
    full_tp = GaussianMixture(mesh=mesh_tp, model_shards=2, **kw).fit(X)
    assert full_tp.converged_
    p = tmp_path / "gtp.npz"
    _fit_killed(
        GaussianMixture(mesh=mesh_tp, model_shards=2, **kw), 4,
        lambda m: m.fit(X, checkpoint_every=2, checkpoint_path=p))
    # Same layout: canonical round trip is bit-exact.
    same = GaussianMixture(mesh=mesh_tp, model_shards=2, **kw)
    same.fit(X, resume=p)
    assert same.n_iter_ == full_tp.n_iter_
    np.testing.assert_array_equal(same.means_, full_tp.means_)
    np.testing.assert_array_equal(same.covariances_,
                                  full_tp.covariances_)
    # Different TP layout (k_pad 6 -> 5): last-ulp class.
    full_dp = GaussianMixture(mesh=_mesh(8), **kw).fit(X)
    other = GaussianMixture(mesh=_mesh(8), **kw)
    other.fit(X, resume=p)
    assert other.n_iter_ == full_dp.n_iter_
    np.testing.assert_allclose(other.means_, full_dp.means_,
                               rtol=1e-10, atol=1e-11)


@pytest.mark.parametrize("write_w,resume_w", [(8, 2), (2, 8)])
def test_minibatch_cross_mesh_runs(tmp_path, write_w, resume_w):
    """MiniBatch samples per shard: a different mesh width IS a
    different (deterministic) batch stream — the r5 forgy-note class of
    documented RNG divergence, so the cross-mesh pin is behavioral:
    the resume loads, keeps the iteration budget, and lands a healthy
    state near the uninterrupted run's quality."""
    X = _blobs(n=2000)
    kw = dict(k=4, max_iter=24, tolerance=1e-12, seed=3, batch_size=256,
              compute_sse=True, host_loop=False, verbose=False,
              dtype=np.float64)
    full = MiniBatchKMeans(mesh=_mesh(resume_w), **kw).fit(X)
    p = tmp_path / "mb.npz"
    _fit_killed(MiniBatchKMeans(mesh=_mesh(write_w), **kw), 10,
                lambda m: m.fit(X, checkpoint_every=5,
                                checkpoint_path=p))
    resumed = MiniBatchKMeans(mesh=_mesh(resume_w), **kw)
    resumed.fit(X, resume=p)
    assert resumed.iterations_run == full.iterations_run
    assert np.all(np.isfinite(resumed.centroids))
    assert resumed.centroids.shape == full.centroids.shape
    # Same data, same k: the two topologies' fits must land in the
    # same quality basin even though the batch streams differ.
    assert abs(resumed.score(X) - full.score(X)) \
        <= 0.1 * abs(full.score(X))


def test_f32_cross_mesh_is_distributional(tmp_path):
    """float32 accumulation regroups inexactly across mesh widths, so
    the f32 cross-mesh pin is equal-in-distribution (documented in
    docs/PERFORMANCE.md "Elastic resume"): the resume runs and the
    final inertia matches the uninterrupted run's to rounding."""
    X = _blobs()
    kw = dict(k=4, max_iter=14, tolerance=1e-12, seed=1,
              empty_cluster="keep", host_loop=False, verbose=False)
    full = KMeans(mesh=_mesh(2), **kw).fit(X)
    p = tmp_path / "f32.npz"
    _fit_killed(KMeans(mesh=_mesh(8), **kw), 4,
                lambda m: m.fit(X, checkpoint_every=2,
                                checkpoint_path=p))
    resumed = KMeans(mesh=_mesh(2), **kw)
    resumed.fit(X, resume=p)
    assert resumed.iterations_run == full.iterations_run
    assert abs(resumed.score(X) - full.score(X)) \
        <= 1e-3 * abs(full.score(X))


# --------------------------------------------------- OOM chunk backoff

def test_backoff_chunk_rules():
    assert backoff_chunk(256) == 128
    assert backoff_chunk(131072) == 65536
    assert backoff_chunk(1024) == 512
    assert backoff_chunk(384) == 192
    assert backoff_chunk(128) is None          # at the floor
    assert backoff_chunk(64) is None
    assert backoff_chunk(250) is None          # no divisor >= 128
    # Off-grid chunks fall back to any divisor >= the floor.
    assert backoff_chunk(300) == 150


def test_is_oom_classification():
    assert is_oom_error(faults.SimulatedOOM(0, 256))
    assert is_oom_error(RuntimeError("RESOURCE_EXHAUSTED: Out of "
                                     "memory allocating 1024 bytes"))
    assert not is_oom_error(faults.SimulatedPreemption("kill"))
    assert not is_oom_error(ValueError("RESOURCE_EXHAUSTED"))
    assert not is_oom_error(RuntimeError("something else"))


def test_oom_backoff_replays_segment_bitwise(tmp_path):
    """Injected RESOURCE_EXHAUSTED on segment 1: the chunk halves
    (256 -> 128), the segment replays from the checkpoint boundary, and
    the f64 trajectory is reproduced BITWISE vs the no-OOM run."""
    X = _blobs()
    kw = dict(k=4, max_iter=14, tolerance=1e-12, seed=1,
              compute_sse=True, empty_cluster="keep", host_loop=False,
              verbose=False, chunk_size=256, dtype=np.float64)
    clean = KMeans(mesh=_mesh(8), **kw).fit(
        X, checkpoint_every=3, checkpoint_path=tmp_path / "c.npz")
    m = KMeans(mesh=_mesh(8), **kw)
    with faults.inject_oom_on_segment(1) as rec:
        with pytest.warns(UserWarning, match="retrying at chunk 128"):
            m.fit(X, checkpoint_every=3,
                  checkpoint_path=tmp_path / "o.npz")
    assert rec["fired"] == 1 and rec["chunks"] == [256]
    assert m.oom_backoffs_ == 1
    assert m.effective_chunk_ == 128
    assert m.iterations_run == clean.iterations_run
    np.testing.assert_array_equal(m.centroids, clean.centroids)
    # SSE is the deliberate f32 reduction; a chunk change regroups it.
    np.testing.assert_allclose(m.sse_history, clean.sse_history,
                               rtol=1e-6)


def test_oom_backoff_gmm_device_loop(tmp_path):
    X = _blobs(n=1500)
    kw = dict(n_components=4, tol=1e-6, max_iter=60,
              init_params="random", seed=0, host_loop=False,
              verbose=False, chunk_size=256, dtype=np.float64)
    clean = GaussianMixture(mesh=_mesh(8), **kw).fit(
        X, checkpoint_every=3, checkpoint_path=tmp_path / "c.npz")
    m = GaussianMixture(mesh=_mesh(8), **kw)
    with faults.inject_oom_on_segment(1) as rec:
        with pytest.warns(UserWarning, match="retrying at chunk 128"):
            m.fit(X, checkpoint_every=3,
                  checkpoint_path=tmp_path / "o.npz")
    assert rec["fired"] == 1
    assert m.oom_backoffs_ == 1 and m.effective_chunk_ == 128
    assert m.n_iter_ == clean.n_iter_
    np.testing.assert_allclose(m.means_, clean.means_,
                               rtol=1e-10, atol=1e-11)


def test_oom_backoff_exhausted_reraises(tmp_path):
    """At the 128-row floor no further backoff exists: the original
    RESOURCE_EXHAUSTED propagates with the remedy chained in, and the
    counters record the attempts that were made."""
    X = _blobs()
    m = KMeans(k=4, max_iter=10, tolerance=1e-12, seed=1,
               empty_cluster="keep", host_loop=False, verbose=False,
               chunk_size=128, mesh=_mesh(8))
    with faults.inject_oom_on_segment(0):
        with pytest.raises(RuntimeError, match="chunk backoff "
                                               "exhausted"):
            m.fit(X, checkpoint_every=3,
                  checkpoint_path=tmp_path / "x.npz")
    assert m.oom_backoffs_ == 0


def test_oom_counters_reset_between_fits(tmp_path):
    X = _blobs()
    kw = dict(k=4, max_iter=8, tolerance=1e-12, seed=1,
              empty_cluster="keep", host_loop=False, verbose=False,
              chunk_size=256, mesh=_mesh(8))
    m = KMeans(**kw)
    with faults.inject_oom_on_segment(0):
        with pytest.warns(UserWarning, match="retrying at chunk"):
            m.fit(X, checkpoint_every=4,
                  checkpoint_path=tmp_path / "a.npz")
    assert m.oom_backoffs_ == 1
    m.fit(X)
    assert m.oom_backoffs_ == 0 and m.effective_chunk_ == 256


def test_preemption_is_never_absorbed_by_backoff(tmp_path):
    """A SimulatedPreemption fired at a boundary must pass straight
    through the OOM machinery (is_oom_error excludes it)."""
    X = _blobs()
    m = KMeans(k=4, max_iter=10, tolerance=1e-12, seed=1,
               empty_cluster="keep", host_loop=False, verbose=False,
               chunk_size=256, mesh=_mesh(8))
    _fit_killed(m, 2, lambda mm: mm.fit(
        X, checkpoint_every=2, checkpoint_path=tmp_path / "p.npz"))
    assert m.oom_backoffs_ == 0


# ------------------------------------------------- divergence rollback

def test_stream_divergence_rolls_back_to_last_good(tmp_path):
    """A mid-fit poisoned block (huge FINITE values: passes the IO
    finite check, overflows the f32 device accumulator) diverges the
    trajectory; the fit rolls back to the last-good checkpoint and the
    error names the iteration and quantity."""
    X = _blobs(n=2000)
    poisoned = faults.poison_blocks(
        _blocks_of(X), block=3, value=2e38, row=0, rows=4, col=None,
        from_epoch=5)
    p = tmp_path / "div.npz"
    m = KMeans(k=4, max_iter=20, tolerance=1e-12, seed=1,
               compute_sse=True, mesh=_mesh(8), verbose=False)
    with pytest.raises(NumericalDivergenceError) as ei:
        m.fit_stream(poisoned, d=3, prefetch=0, checkpoint_every=2,
                     checkpoint_path=p)
    e = ei.value
    assert e.quantity == "centroids"
    assert e.rolled_back_to is not None
    assert f"iteration {e.iteration}" in str(e)
    assert "rolled back" in str(e)
    state = ckpt.load_state(p)
    assert int(state["iterations_run"]) == e.rolled_back_to
    np.testing.assert_array_equal(m.cluster_centers_,
                                  state["centroids"])
    assert np.all(np.isfinite(m.cluster_centers_))


def test_device_loop_divergence_stops_early_and_rolls_back(tmp_path):
    """The in-loop all-finite flag exits the dispatch AT the diverging
    iteration (not max_iter later); resume-onto-poisoned-data is the
    in-memory trigger: the checkpointed prefix state survives."""
    X = _blobs()
    p = tmp_path / "g.npz"
    kw = dict(k=4, max_iter=6, tolerance=1e-12, seed=1, mesh=_mesh(8),
              host_loop=False, verbose=False)
    KMeans(**kw).fit(X, checkpoint_every=2, checkpoint_path=p)
    good = ckpt.load_state(p)
    pX = X.copy()
    pX[100] = np.nan                   # corrupted re-materialized data
    m = KMeans(**dict(kw, max_iter=40))
    with pytest.raises(NumericalDivergenceError) as ei:
        m.fit(pX, resume=p, checkpoint_every=2, checkpoint_path=p)
    e = ei.value
    assert e.quantity == "centroids"
    # Early exit: the NaN lands in iteration 7 (first of the resumed
    # segment), nowhere near the 40-iteration budget.
    assert e.iteration == int(good["iterations_run"]) + 1
    assert e.rolled_back_to == int(good["iterations_run"])
    np.testing.assert_array_equal(m.cluster_centers_,
                                  good["centroids"])


def test_divergence_never_restores_a_stale_foreign_checkpoint(tmp_path):
    """Review r10: a fit that reuses a checkpoint path from an EARLIER,
    unrelated fit and diverges before writing its own first checkpoint
    must NOT silently restore the stale file's state — rollback is only
    legal for a checkpoint this fit wrote or resumed from."""
    X = _blobs()
    p = tmp_path / "stale.npz"
    kw = dict(k=4, max_iter=6, tolerance=1e-12, seed=1, mesh=_mesh(8),
              host_loop=False, verbose=False)
    KMeans(**kw).fit(X, checkpoint_every=2, checkpoint_path=p)  # fit A
    stale = ckpt.load_state(p)
    pX = _blobs(rs=3)                      # fit B: different data
    pX[5] = np.nan
    b = KMeans(**kw)
    with pytest.raises(NumericalDivergenceError) as ei:
        b.fit(pX, checkpoint_every=2, checkpoint_path=p)
    assert ei.value.rolled_back_to is None
    assert b.cluster_centers_ is None or not np.array_equal(
        b.cluster_centers_, stale["centroids"])


def test_partial_fit_divergence_keeps_incremental_progress(tmp_path):
    """Review r10: partial_fit is not a checkpointed session — a
    diverging batch must raise IN PLACE, never roll the model back to
    the stale checkpoint a previous fit() left at the path (which
    would silently destroy all incremental progress since)."""
    X = _blobs()
    p = tmp_path / "mbfit.npz"
    m = MiniBatchKMeans(k=4, max_iter=6, tolerance=1e-12, seed=3,
                        batch_size=256, mesh=_mesh(8), verbose=False)
    m.fit(X, checkpoint_every=2, checkpoint_path=p)
    fit_iters = m.iterations_run
    for i in range(5):
        m.partial_fit(X[i * 200: (i + 1) * 200])
    assert m.iterations_run == fit_iters + 5
    healthy = np.array(m.centroids)
    bad = X[:200].copy()
    bad[3] = np.inf
    with pytest.raises(NumericalDivergenceError) as ei:
        m.partial_fit(bad)
    assert ei.value.rolled_back_to is None
    assert ei.value.checkpoint_path is None
    np.testing.assert_array_equal(m.centroids, healthy)
    assert m.iterations_run == fit_iters + 5


@pytest.mark.parametrize("host_loop", [True, False])
def test_divergence_without_checkpoint_is_plain_error(host_loop):
    """Un-checkpointed fits keep the historical ValueError contract
    (NumericalDivergenceError subclasses it, message phrase intact) —
    with the iteration/quantity now attached and nothing rolled back."""
    X = _blobs()
    pX = X.copy()
    pX[7] = np.inf
    m = KMeans(k=4, max_iter=8, tolerance=1e-12, seed=1, mesh=_mesh(8),
               host_loop=host_loop, verbose=False)
    with pytest.raises(ValueError,
                       match="NaN or Inf detected in centroids") as ei:
        m.fit(pX)
    assert isinstance(ei.value, NumericalDivergenceError)
    assert ei.value.rolled_back_to is None


def test_gmm_stream_divergence_rolls_back(tmp_path):
    X = _blobs(n=1200, centers=3, rs=5)
    poisoned = faults.poison_blocks(
        _blocks_of(X, rows=300), block=2, value=2e38, row=0, rows=4,
        col=None, from_epoch=6)
    p = tmp_path / "gdiv.npz"
    gm = GaussianMixture(n_components=3, tol=1e-9, max_iter=30,
                         init_params="random", seed=0, mesh=_mesh(8),
                         verbose=False)
    with pytest.raises(NumericalDivergenceError) as ei:
        gm.fit_stream(poisoned, d=3, prefetch=0, checkpoint_every=2,
                      checkpoint_path=p)
    e = ei.value
    assert e.quantity == "log-likelihood"
    assert "non-finite log-likelihood" in str(e)
    assert e.rolled_back_to is not None
    state = ckpt.load_state(p)
    np.testing.assert_array_equal(gm.means_, state["means_"])
    assert np.all(np.isfinite(gm.means_))


# -------------------------------------------- Cholesky jitter ladder

def test_cholesky_jitter_ladder_rescues_borderline():
    gm = GaussianMixture(n_components=2, covariance_type="full",
                         reg_covar=1e-4, seed=0, verbose=False)
    d = 3
    good = np.eye(d)
    # Indefinite by a hair: smallest eigenvalue -1e-5, inside the
    # reg_covar * 10^j <= 0.1 ladder's reach.
    bad = np.eye(d)
    bad[0, 0] = -1e-5
    covs = np.stack([good, bad])
    with pytest.warns(UserWarning, match="jitter ladder"):
        p_chol, ldh = gm._prec_chol_guarded(covs)
    assert gm.cov_jitter_retries_ >= 1
    assert np.all(np.isfinite(p_chol)) and np.all(np.isfinite(ldh))


def test_cholesky_jitter_ladder_exhausts_actionably():
    gm = GaussianMixture(n_components=2, covariance_type="full",
                         reg_covar=1e-9, seed=0, verbose=False)
    bad = -np.eye(3)                   # hopeless: -1 eigenvalues
    covs = np.stack([np.eye(3), bad])
    with pytest.raises(ValueError) as ei:
        gm._prec_chol_guarded(covs)
    msg = str(ei.value)
    assert "ill-defined empirical covariance" in msg
    assert "component(s) [1]" in msg
    assert gm.cov_jitter_retries_ == 0


def test_cholesky_ladder_is_fit_only_inference_stays_strict():
    """Review r10: the jitter ladder serves the FIT path only — predict
    on a model whose covariances cannot factor must raise the strict
    ill-defined error, not silently score jittered densities, and the
    fit-time audit counter must not move."""
    X = _blobs(d=3)
    gm = GaussianMixture(n_components=2, covariance_type="full",
                         reg_covar=1e-4, max_iter=3,
                         init_params="random", seed=0, mesh=_mesh(8),
                         verbose=False).fit(X)
    gm.covariances_ = np.stack([np.eye(3), -np.eye(3)])
    before = gm.cov_jitter_retries_
    with pytest.raises(ValueError,
                       match="ill-defined empirical covariance"):
        gm.predict(X[:16])
    assert gm.cov_jitter_retries_ == before


def test_cholesky_ladder_tied_names_shared_cov():
    gm = GaussianMixture(n_components=2, covariance_type="tied",
                         reg_covar=0.0, seed=0, verbose=False)
    with pytest.raises(ValueError, match="shared tied covariance"):
        gm._prec_chol_guarded(-np.eye(3))


# --------------------------------------- metadata + ckpt-info command

def test_checkpoint_carries_topology_metadata(tmp_path):
    X = _blobs()
    p = tmp_path / "meta.npz"
    KMeans(k=4, max_iter=4, seed=1, mesh=_mesh(4, 2), model_shards=2,
           verbose=False).fit(X, checkpoint_every=2, checkpoint_path=p)
    info = ckpt.describe_checkpoint(p)
    assert info["source"] == "primary"
    assert info["model_class"] == "KMeans"
    assert info["k"] == 4
    assert info["iteration"] >= 2
    assert info["written_on_mesh"] == {"data_shards": 4,
                                       "model_shards": 2}
    assert info["format_version"] == ckpt.FORMAT_VERSION
    assert info["jax_version"] == jax.__version__
    assert info["prev_exists"] and info["prev_loads"]


def test_metadata_present_in_every_family(tmp_path):
    X = _blobs(d=4, centers=4)
    models = [
        KMeans(k=4, max_iter=2, verbose=False, mesh=_mesh(8)),
        MiniBatchKMeans(k=4, max_iter=2, batch_size=128, verbose=False,
                        mesh=_mesh(8)),
        SphericalKMeans(k=4, max_iter=2, verbose=False, mesh=_mesh(8)),
        BisectingKMeans(k=3, max_iter=2, verbose=False, mesh=_mesh(8)),
        GaussianMixture(n_components=3, max_iter=2,
                        init_params="random", verbose=False,
                        mesh=_mesh(8)),
    ]
    for m in models:
        m.fit(X)
        state = m._state_dict()
        assert state["meta_mesh_data_shards"] == 8, type(m).__name__
        assert state["meta_format_version"] == ckpt.FORMAT_VERSION
        assert state["meta_jax_version"] == jax.__version__


def test_ckpt_info_cli(tmp_path, capsys):
    from kmeans_tpu.cli import ckpt_info_main
    X = _blobs()
    p = tmp_path / "cli.npz"
    KMeans(k=4, max_iter=4, seed=1, mesh=_mesh(8), verbose=False).fit(
        X, checkpoint_every=2, checkpoint_path=p)
    assert ckpt_info_main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "KMeans" in out and "data_shards=8" in out
    assert ".prev rotation  : exists=True, loads=True" in out
    # Torn primary: the summary comes from .prev, exit code still 0.
    p.write_bytes(b"torn mid-write")
    assert ckpt_info_main([str(p), "--json"]) == 0
    import json
    info = json.loads(capsys.readouterr().out)
    assert info["source"] == "prev" and info["primary_error"]
    # Both unreadable: exit code 2.
    ckpt.prev_path(p).write_bytes(b"also torn")
    assert ckpt_info_main([str(p)]) == 2


def test_legacy_padded_gmm_checkpoint_still_resumes(tmp_path):
    """An r9-era checkpoint stored the dev tables PADDED; the canonical
    loader trims them on the way in, so old checkpoints keep resuming
    bit-exactly on the topology they were written on."""
    X = _blobs()
    kw = dict(n_components=5, tol=1e-6, max_iter=80,
              init_params="random", seed=0, host_loop=False,
              verbose=False, dtype=np.float64, model_shards=2)
    mesh = _mesh(4, 2)
    full = GaussianMixture(mesh=mesh, **kw).fit(X)
    assert full.converged_
    p = tmp_path / "legacy.npz"
    _fit_killed(GaussianMixture(mesh=mesh, **kw), 4,
                lambda m: m.fit(X, checkpoint_every=2,
                                checkpoint_path=p))
    # Re-write the checkpoint with PADDED tables (the r9 layout).
    state = ckpt.load_state(p)
    k_pad, d = 6, 3
    mc = np.zeros((k_pad, d), state["dev_means_c"].dtype)
    mc[:5] = state["dev_means_c"]
    cv = np.ones((k_pad, d), state["dev_cov"].dtype)
    cv[:5] = state["dev_cov"]
    lw = np.full((k_pad,), -np.inf, state["dev_log_w"].dtype)
    lw[:5] = state["dev_log_w"]
    state["dev_means_c"], state["dev_cov"], state["dev_log_w"] = \
        mc, cv, lw
    ckpt.save_state(p, state)
    resumed = GaussianMixture(mesh=mesh, **kw)
    resumed.fit(X, resume=p)
    assert resumed.n_iter_ == full.n_iter_
    np.testing.assert_array_equal(resumed.means_, full.means_)
