"""Profiling/observability: per-iteration wall times, jax.profiler trace
capture, and dataset resharding (the ``repartition`` analogue)."""

import os

import pytest

import numpy as np
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans
from kmeans_tpu.parallel.mesh import make_mesh


def _data():
    X, _ = make_blobs(n_samples=1200, centers=3, n_features=4,
                      random_state=0)
    return X.astype(np.float64)


def test_iter_times_recorded(mesh8):
    X = _data()
    km = KMeans(k=3, mesh=mesh8, dtype=np.float64, verbose=False).fit(X)
    assert len(km.iter_times_) == km.iterations_run
    assert all(t > 0 for t in km.iter_times_)


def test_iter_times_device_loop(mesh8):
    X = _data()
    km = KMeans(k=3, empty_cluster="keep", host_loop=False, mesh=mesh8,
                dtype=np.float64, verbose=False).fit(X)
    assert len(km.iter_times_) == km.iterations_run


def test_profile_trace_written(tmp_path, mesh8):
    X = _data()
    km = KMeans(k=3, mesh=mesh8, dtype=np.float64, verbose=False)
    km.fit(X, profile_dir=str(tmp_path / "trace"))
    produced = []
    for root, _, files in os.walk(tmp_path / "trace"):
        produced.extend(files)
    assert produced                     # profiler wrote trace artifacts


def test_reshard(mesh8):
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("reshard-to-2 needs 2 devices")
    X = _data()
    km = KMeans(k=3, mesh=mesh8, dtype=np.float64, verbose=False)
    ds = km.cache(X)
    mesh2 = make_mesh(data=2, model=1, devices=jax.devices()[:2])
    ds2 = ds.reshard(mesh2)
    assert ds2.n == ds.n and ds2.mesh is mesh2
    km2 = KMeans(k=3, mesh=mesh2, dtype=np.float64, verbose=False).fit(ds2)
    km.fit(ds)
    np.testing.assert_allclose(km.centroids, km2.centroids, atol=1e-9)
