"""Test D capability: empty-cluster handling (kmeans_spark.py:503-540).

3 tight blobs (cluster_std=0.5), deliberately k=6 to force empties; passes if
fit completes with all-finite centroids.  Also covers the policies the
reference could not test: the deterministic resample divergence and the
farthest-point policy (dead code in the reference, kmeans_spark.py:84-129,
live here).
"""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans


@pytest.fixture()
def tight_blobs():
    X, _ = make_blobs(n_samples=800, centers=3, n_features=2,
                      cluster_std=0.5, random_state=42)
    return X


@pytest.mark.parametrize("policy", ["resample", "farthest", "keep"])
def test_overclustered_fit_stays_finite(tight_blobs, mesh8, policy):
    km = KMeans(k=6, max_iter=30, tolerance=1e-4, seed=42, compute_sse=True,
                empty_cluster=policy, mesh=mesh8, verbose=False)
    km.fit(tight_blobs)
    assert km.centroids.shape == (6, 2)
    assert np.all(np.isfinite(km.centroids))     # kmeans_spark.py:529-535


def test_resample_is_deterministic(tight_blobs, mesh8):
    # Deliberate divergence from the reference's time.time() seed
    # (kmeans_spark.py:195-196): two identical runs now agree exactly.
    runs = [KMeans(k=6, max_iter=30, seed=42, mesh=mesh8,
                   verbose=False).fit(tight_blobs).centroids
            for _ in range(2)]
    np.testing.assert_array_equal(runs[0], runs[1])


def test_farthest_policy_uses_a_data_point(mesh8):
    # Force an empty cluster with an explicit-array init: two centroids on
    # the data, one far away that captures nothing.
    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 2)).astype(np.float64)
    init = np.array([[0.0, 0.0], [0.5, 0.5], [1e3, 1e3]])
    km = KMeans(k=3, max_iter=1, init=init, empty_cluster="farthest",
                mesh=mesh8, dtype=np.float64, verbose=False).fit(X)
    # The empty slot was refilled with an actual data point.
    replaced = km.centroids[2]
    assert np.any(np.all(np.isclose(X, replaced[None, :], atol=1e-9), axis=1))


def test_resample_hostless_dataset_uses_device_sampler(tight_blobs, mesh8):
    """A dataset with no host copy routes 'resample' through the on-device
    Gumbel-argmax sampler (r1 VERDICT #6) — refills must be real data rows
    and two runs must agree bit-for-bit."""
    X = tight_blobs.astype(np.float32)

    def run():
        km = KMeans(k=6, max_iter=30, seed=42, empty_cluster="resample",
                    mesh=mesh8, verbose=False)
        ds = km.cache(X)
        ds._host = None                    # simulate device-only data
        ds._host_weights = None
        return km.fit(ds)

    a, b = run(), run()
    assert np.all(np.isfinite(a.centroids))
    np.testing.assert_array_equal(a.centroids, b.centroids)


def test_sample_positive_rows_device_path_draws_data_rows(mesh8):
    from kmeans_tpu.parallel.sharding import to_device
    rng = np.random.default_rng(5)
    X = rng.normal(size=(300, 4)).astype(np.float32)
    ds = to_device(X, mesh8, 32, np.float32)
    ds._host = None
    ds._host_weights = None
    rows = ds.sample_positive_rows(3, [42, 1])
    assert rows.shape == (3, 4)
    for row in rows:                        # each drawn row is a real row
        assert np.any(np.all(np.isclose(X, row[None, :], atol=1e-6),
                             axis=1))
    rows2 = ds.sample_positive_rows(3, [42, 1])
    np.testing.assert_array_equal(rows, rows2)      # seeded -> identical
    # distinct rows (without replacement)
    assert len(np.unique(rows.round(6), axis=0)) == 3
