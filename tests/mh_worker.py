"""Worker for the two-process multi-host integration test (not a test
module itself — spawned by tests/test_multihost.py).

Each process loads ONLY its own slice of the blob dataset, builds the
global data-sharded array with ``from_process_local``, fits with a shared
explicit init, and writes its view of the result for the parent to
compare.  Also smoke-tests the on-device kmeans++ init (the documented
multi-host seeding path).
"""

import os
import sys
from pathlib import Path

import numpy as np

proc_id = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]
out_dir = Path(sys.argv[4])

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Initialize the distributed runtime BEFORE anything touches jax backends
# (package imports may call jax.devices(), which pins single-process mode).
from kmeans_tpu.parallel.multihost import initialize, is_primary  # noqa: E402

initialize(coordinator_address=f"127.0.0.1:{port}",
           num_processes=nproc, process_id=proc_id)
assert jax.process_count() == nproc

from kmeans_tpu import KMeans  # noqa: E402
from kmeans_tpu.parallel.mesh import make_mesh  # noqa: E402
from kmeans_tpu.parallel.sharding import from_process_local  # noqa: E402

# Deterministic global dataset; UNEVEN split across processes (exercises
# the padded per-process layout).
rng = np.random.default_rng(0)
centers = np.array([[0, 0, 0, 0], [10, 10, 0, 0],
                    [-10, 0, 10, 0], [0, -10, 0, 10]], np.float32)
X = (centers[rng.integers(0, 4, 3000)]
     + rng.normal(size=(3000, 4)).astype(np.float32))
# UNEVEN per-process splits (exercises the padded per-process layout);
# 2-process keeps the original 1900/1100 split, 4-process goes further.
bounds = {2: [0, 1900, 3000], 4: [0, 1000, 1700, 2600, 3000]}[nproc]
X_local = X[bounds[proc_id]: bounds[proc_id + 1]]
init = X[rng.choice(3000, size=4, replace=False)]

mesh = make_mesh()
ds = from_process_local(X_local, mesh, k_hint=4)
assert ds.n == 3000, ds.n

km = KMeans(k=4, seed=0, init=init, empty_cluster="keep",
            compute_sse=True, verbose=is_primary()).fit(ds)
# Process-local labels (r3 VERDICT #4): labels_ holds THIS process's own
# rows' labels; concatenated across processes = the global label array
# (asserted by the parent test).
labels_local = km.labels_
assert labels_local.shape == (len(X_local),), labels_local.shape
np.save(out_dir / f"labels_{proc_id}.npy", labels_local)
# predict on the process-local dataset agrees with the eager labels.
np.testing.assert_array_equal(km.predict(ds), labels_local)

# 'resample' on a process-local dataset: the on-device Gumbel sampler
# replaces the r1 rejection (r1 VERDICT #6).  Force empties with two
# far-away init rows; both processes must agree bit-for-bit (the draw is
# replicated) and every refilled centroid must be finite.
init6 = np.concatenate([init, np.full((2, 4), 1e3, np.float32)])
km_rs = KMeans(k=6, seed=0, init=init6, empty_cluster="resample",
               max_iter=5, verbose=False).fit(ds)
assert np.all(np.isfinite(km_rs.centroids))
np.save(out_dir / f"centroids_rs_{proc_id}.npy", km_rs.centroids)

# kmeans++ on-device seeding must also work with no host copy.
km2 = KMeans(k=4, seed=0, init="kmeans++", empty_cluster="keep",
             verbose=False).fit(ds)
assert np.all(np.isfinite(km2.centroids))

# MiniBatch on the process-local dataset: labels_ is materialized EAGERLY
# inside fit (all processes join the dispatch), so a later single-process
# pickle/labels_ read cannot desync the SPMD program (review r4).
from kmeans_tpu.models import MiniBatchKMeans  # noqa: E402

mb = MiniBatchKMeans(k=4, init=init, batch_size=256, max_iter=8, seed=0,
                     verbose=False).fit(ds)
assert mb._labels_cache is not None and mb._fit_ds is None
assert mb._labels_cache.shape == (len(X_local),)
import pickle  # noqa: E402
pickle.dumps(mb)          # single-process-safe: no implicit dispatch left
# Device sampling's stratified draw is seeded and replicated, so the
# Sculley trajectory must agree bit-for-bit across processes (r4
# VERDICT #7) — asserted by the parent.
np.save(out_dir / f"centroids_mb_{proc_id}.npy", mb.centroids)

# --- multi-host checkpoint: every process calls save(); only process 0
# writes, and the barrier makes the file visible before any return
# (r1 VERDICT #5).
km.save(out_dir / "mh_ckpt")
loaded = KMeans.load(out_dir / "mh_ckpt")
np.testing.assert_array_equal(loaded.centroids, km.centroids)

# --- fit_stream across the process boundary (r4 VERDICT #7): every
# process streams the SAME deterministic global blocks (weighted), each
# block is device_put to the global data-axis sharding, and the host-side
# f64 statistics summation is identical per process — so the streamed
# trajectory must agree bit-for-bit across processes.
wts = (1.0 + (np.arange(3000) % 3)).astype(np.float32)


def _stream_blocks():
    for i in range(0, 3000, 1000):
        yield X[i:i + 1000], wts[i:i + 1000]


km_st = KMeans(k=4, seed=0, init=init, empty_cluster="keep",
               compute_sse=True, max_iter=8, verbose=False)
km_st.fit_stream(_stream_blocks)
assert np.all(np.isfinite(km_st.centroids))
np.save(out_dir / f"centroids_stream_{proc_id}.npy", km_st.centroids)
np.save(out_dir / f"sse_stream_{proc_id}.npy",
        np.asarray(km_st.sse_history))

# --- full-covariance GMM on the process-local dataset (r4 VERDICT #7):
# the (k, D, D) scatter psum and the on-device batched Cholesky cross
# the process boundary; replicated results agree bit-for-bit.
from kmeans_tpu import GaussianMixture  # noqa: E402

gm_full = GaussianMixture(n_components=4, covariance_type="full",
                          means_init=init.astype(np.float64),
                          max_iter=5, tol=0.0, seed=0)
gm_full.fit(ds)
assert np.all(np.isfinite(gm_full.covariances_))
np.save(out_dir / f"gmm_full_means_{proc_id}.npy", gm_full.means_)
np.save(out_dir / f"gmm_full_covs_{proc_id}.npy", gm_full.covariances_)

# --- Sections needing exactly 2 processes x 2 devices (the 2x2 TP grid).
if nproc == 2:
    # TP mesh with the MODEL axis spanning processes: the per-chunk
    # all_gather of per-block minima (the TP collective) crosses the
    # process boundary for real.  Each data-axis row block is replicated
    # across the model axis, so both processes hold every row — built
    # with make_array_from_callback from the full (deterministic) X.
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

    from kmeans_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS  # noqa: E402
    from kmeans_tpu.parallel.sharding import (ShardedDataset,  # noqa: E402
                                              pad_points)

    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    assert len(devs) == 4
    # data x model grid: model axis pairs one device of EACH process.
    grid = np.array([[devs[0], devs[2]], [devs[1], devs[3]]])
    mesh_tp = Mesh(grid, (DATA_AXIS, MODEL_AXIS))
    chunk = 64
    x_pad, w_pad = pad_points(X.astype(np.float32), 2 * chunk)
    pts = jax.make_array_from_callback(
        x_pad.shape, NamedSharding(mesh_tp, P(DATA_AXIS, None)),
        lambda idx: x_pad[idx])
    w = jax.make_array_from_callback(
        w_pad.shape, NamedSharding(mesh_tp, P(DATA_AXIS)),
        lambda idx: w_pad[idx])
    ds_tp = ShardedDataset(pts, w, len(X), chunk, mesh_tp)
    km_tp = KMeans(k=4, seed=0, init=init, empty_cluster="keep",
                   compute_sse=True, verbose=False).fit(ds_tp)
    np.save(out_dir / f"centroids_tp_{proc_id}.npy", km_tp.centroids)
    np.save(out_dir / f"sse_tp_{proc_id}.npy",
            np.asarray(km_tp.sse_history))

    # Pallas mode (interpret off-TPU) under the SAME cross-process TP
    # mesh: covers pallas_assign + the prepped ownership-masked
    # accumulation with the model-axis all_gather crossing the process
    # boundary for real.
    km_ptp = KMeans(k=4, seed=0, init=init, empty_cluster="keep",
                    compute_sse=True, verbose=False,
                    distance_mode="pallas").fit(ds_tp)
    np.testing.assert_allclose(km_ptp.centroids, km_tp.centroids,
                               rtol=1e-5, atol=1e-5)
    # And data-parallel pallas on the process-local dataset.
    km_pdp = KMeans(k=4, seed=0, init=init, empty_cluster="keep",
                    compute_sse=True, verbose=False,
                    distance_mode="pallas").fit(ds)
    np.testing.assert_allclose(km_pdp.centroids, km.centroids,
                               rtol=1e-5, atol=1e-5)

# --- GMM on the process-local dataset (r3): the E-step's psum-embedded
# statistics AND the centering shift's GSPMD weighted mean cross the
# process boundary; the replicated results must agree bit-for-bit
# across processes.  Explicit means_init (forgy would need a host copy).
from kmeans_tpu import GaussianMixture  # noqa: E402

gm = GaussianMixture(n_components=4, means_init=init.astype(np.float64),
                     max_iter=5, tol=0.0, seed=0)
gm.fit(ds)
assert np.all(np.isfinite(gm.means_)) and np.isfinite(gm.lower_bound_)
np.save(out_dir / f"gmm_means_{proc_id}.npy", gm.means_)
np.save(out_dir / f"gmm_ll_{proc_id}.npy",
        np.asarray([gm.lower_bound_]))

# --- ISSUE 13: fleet observability under REAL multi-process SPMD.
# (a) obs=0 parity under multiprocess: the fully-instrumented fit must
# be BIT-identical to the plain one on every host; (b) per-process
# sinks: tracing/heartbeat paths auto-suffix (no torn shared file);
# (c) TWO instrumented fits emit two synced fit-start barriers, so the
# parent's merge measures a real cross-barrier skew bound.
import contextlib  # noqa: E402

from kmeans_tpu import obs  # noqa: E402
from kmeans_tpu.utils import faults  # noqa: E402

obs_kw = dict(k=4, seed=0, init=init, empty_cluster="keep",
              compute_sse=True, max_iter=6, tolerance=1e-30,
              verbose=False)
km_plain = KMeans(**obs_kw).fit(ds)
with obs.tracing(out_dir / "fleet_trace.jsonl") as fleet_tr, \
        obs.heartbeat(out_dir / "fleet_hb.jsonl") as fleet_hb:
    km_obs = KMeans(**obs_kw).fit(ds)
    km_obs2 = KMeans(**obs_kw).fit(ds)      # second fit-start barrier
assert km_obs.iterations_run == km_plain.iterations_run
np.testing.assert_array_equal(km_obs.centroids, km_plain.centroids)
assert km_obs.sse_history == km_plain.sse_history
np.testing.assert_array_equal(km_obs2.centroids, km_obs.centroids)
ident = fleet_tr.identity()
assert ident["process_index"] == proc_id, ident
assert ident["process_count"] == nproc, ident
assert (out_dir / f"fleet_trace.p{proc_id}.jsonl").exists()
assert fleet_hb.resolved_path == str(
    out_dir / f"fleet_hb.p{proc_id}.jsonl"), fleet_hb.resolved_path
barrier_evs = [r for r in fleet_tr.records()
               if r.get("kind") == "event"
               and r["name"] == "fleet.barrier"]
assert len(barrier_evs) == 2, barrier_evs
assert all(e["attrs"]["synced"] for e in barrier_evs), barrier_evs

# (d) straggler fleet: per-host INDEPENDENT local fits (the elastic-
# loop regime — each host trains on its own slice, coordinating only
# through checkpoints/heartbeats), process 1 slowed by the
# deterministic faults hook; the parent's straggler report must flag
# exactly it.  Local 1-device mesh: no cross-process collectives.
local_mesh = make_mesh(data=1, model=1,
                       devices=[jax.local_devices()[0]])
delay = (faults.inject_checkpoint_delay(0.1) if proc_id == 1
         else contextlib.nullcontext())
with obs.heartbeat(out_dir / "straggler_hb.jsonl"), delay:
    KMeans(k=4, seed=0, init=init, empty_cluster="keep",
           compute_sse=True, max_iter=6, tolerance=1e-30,
           host_loop=True, mesh=local_mesh, verbose=False).fit(
        X_local, checkpoint_every=1,
        checkpoint_path=out_dir / f"straggler_ckpt_{proc_id}.npz")
assert (out_dir / f"straggler_hb.p{proc_id}.jsonl").exists()

np.save(out_dir / f"centroids_{proc_id}.npy", km.centroids)
np.save(out_dir / f"sse_{proc_id}.npy", np.asarray(km.sse_history))
tp_note = f" tp_iters={km_tp.iterations_run}" if nproc == 2 else ""
print(f"proc {proc_id}: OK iters={km.iterations_run}"
      f"{tp_note}", flush=True)
