"""ISSUE 18: the device-side ingest pipeline.

Four pinned contracts:

1. **Mode parity** — ``ingest='slab'`` (double-buffered slab staging)
   and ``ingest='mono'`` (the blocking per-shard oracle) assemble
   BIT-identical arrays on every mesh shape, weighted or not, through
   every loader (``to_device``, ``from_npy``, ``from_raw``) and every
   model family, including ``fit(resume=)`` re-ingest.
2. **On-device synthesis** — ``data.synthetic.device_shards`` equals
   its ``host_equivalent`` oracle bit-for-bit on any mesh (the per-row
   ``fold_in`` partition invariance).
3. **No resurrected host copies** — the weighted slab path stages
   VIEWS of the caller's arrays for fully-real ranges (the ISSUE 18
   satellite: the old path built a full-size ones buffer even when
   aligned).
4. **Telemetry** — per-slab ``stage`` spans feed ``ingest_breakdown``
   and the ``ingest.bytes``/``ingest.slabs`` counters move.

A real 2-process multi-host run (gated like tests/test_multihost.py)
pins the streamed per-host path: every process touches only its own
shard bytes yet all agree bitwise.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

from conftest import jaxlib_cpu_multiprocess_skip
from kmeans_tpu import (BisectingKMeans, GaussianMixture, KMeans,
                        MiniBatchKMeans, SphericalKMeans, make_mesh)
from kmeans_tpu.data import synthetic as synth
from kmeans_tpu.data.io import from_npy, from_raw
from kmeans_tpu.obs import memory as obs_memory
from kmeans_tpu.obs import metrics_registry as obs_metrics
from kmeans_tpu.obs import trace as obs_trace
from kmeans_tpu.obs.report import format_ingest_table, ingest_breakdown
from kmeans_tpu.parallel.sharding import (INGEST_MODES, _w_slice, _x_slice,
                                          check_ingest, resolve_ingest,
                                          to_device)
from kmeans_tpu.utils import faults


def _mesh(dp, mp=1):
    if len(jax.devices()) < dp * mp:
        pytest.skip(f"needs {dp * mp} devices")
    return make_mesh(data=dp, model=mp, devices=jax.devices()[: dp * mp])


def _data(n=1037, d=5, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, d)).astype(dtype)


def _weights(n=1037, seed=1):
    rng = np.random.default_rng(seed)
    return rng.uniform(0.5, 2.0, size=n).astype(np.float32)


def _assert_ds_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.points),
                                  np.asarray(b.points))
    np.testing.assert_array_equal(np.asarray(a.weights),
                                  np.asarray(b.weights))


# ---------------------------------------------------------------------------
# Grammar
# ---------------------------------------------------------------------------

def test_check_ingest_grammar():
    for mode in INGEST_MODES:
        assert check_ingest(mode) == mode
    with pytest.raises(ValueError, match="ingest must be one of"):
        check_ingest("bogus")
    with pytest.raises(ValueError, match="ingest must be one of"):
        check_ingest(None)


def test_resolve_ingest_explicit_modes_pass_through():
    assert resolve_ingest("mono") == "mono"
    assert resolve_ingest("slab") == "slab"


def test_resolve_ingest_auto_is_the_committed_platform_rule():
    """The BENCH_INGEST r22 decision: CPU measured BELOW the 1.2x adopt
    bar (median mono/slab 1.04x on the single-core proxy — a pinned
    measured rejection), so 'auto' keeps the mono oracle there;
    accelerators stage slabs (DMA transfer/compute overlap)."""
    expected = "mono" if jax.default_backend() == "cpu" else "slab"
    assert resolve_ingest("auto") == expected


@pytest.mark.parametrize("ctor", [
    lambda: KMeans(k=2, ingest="bogus"),
    lambda: MiniBatchKMeans(k=2, ingest="bogus"),
    lambda: GaussianMixture(n_components=2, ingest="bogus"),
    lambda: SphericalKMeans(k=2, ingest="bogus"),
    lambda: BisectingKMeans(k=2, ingest="bogus"),
])
def test_constructors_reject_bad_ingest(ctor):
    with pytest.raises(ValueError, match="ingest must be one of"):
        ctor()


# ---------------------------------------------------------------------------
# Mode parity: the mono/slab bit-exactness pin
# ---------------------------------------------------------------------------

MESHES = [(1, 1), (2, 1), (4, 1), (8, 1), (2, 2), (4, 2)]


@pytest.mark.parametrize("weighted", [False, True],
                         ids=["unweighted", "weighted"])
@pytest.mark.parametrize("dp,mp", MESHES)
def test_slab_mono_bit_parity_across_meshes(dp, mp, weighted):
    """The acceptance pin: both placement paths assemble byte-identical
    global arrays on every mesh shape (incl. TP replication), with the
    padded tail (1037 % (shards*chunk) != 0) and explicit weights."""
    mesh = _mesh(dp, mp)
    X = _data()
    sw = _weights() if weighted else None
    ds_mono = to_device(X, mesh, 32, np.float32, sample_weight=sw,
                        ingest="mono")
    ds_slab = to_device(X, mesh, 32, np.float32, sample_weight=sw,
                        ingest="slab")
    _assert_ds_equal(ds_mono, ds_slab)
    assert ds_mono.points.shape == ds_slab.points.shape
    # Shardings agree too — parity is layout, not just values.
    assert (ds_mono.points.sharding.spec
            == ds_slab.points.sharding.spec)


def test_slab_mono_parity_meshless():
    """mesh=None single-device path: every mode collapses to the same
    committed upload."""
    X = _data(257, 3)
    for mode in INGEST_MODES:
        ds = to_device(X, None, 32, np.float32, ingest=mode)
        np.testing.assert_array_equal(np.asarray(ds.points)[:257], X)


def test_multi_slab_parity_and_slab_counter(monkeypatch):
    """Shrinking the slab target to 1 byte forces one slab PER SHARD —
    the deepest staging schedule stays bit-exact and the ingest.slabs
    counter counts exactly the slabs."""
    mesh = _mesh(8)
    monkeypatch.setattr(obs_memory, "INGEST_SLAB_TARGET_BYTES", 1)
    X = _data()
    before = obs_metrics.REGISTRY.counter("ingest.slabs").value
    ds_slab = to_device(X, mesh, 32, np.float32, ingest="slab")
    assert (obs_metrics.REGISTRY.counter("ingest.slabs").value
            - before) == 8
    ds_mono = to_device(X, mesh, 32, np.float32, ingest="mono")
    _assert_ds_equal(ds_mono, ds_slab)


def test_min_rows_bucket_padding_parity():
    """Shape-bucket padding (ISSUE 15b min_rows) rides through both
    paths identically — bucketed warm fits may re-ingest either way."""
    mesh = _mesh(4)
    X = _data(500, 4)
    ds_m = to_device(X, mesh, 32, np.float32, min_rows=1024,
                     ingest="mono")
    ds_s = to_device(X, mesh, 32, np.float32, min_rows=1024,
                     ingest="slab")
    assert ds_m.points.shape[0] >= 1024
    _assert_ds_equal(ds_m, ds_s)


# ---------------------------------------------------------------------------
# Loaders: from_npy / from_raw, streamed vs oracle, prefetch=0 sync oracle
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("prefetch", [0, 2])
def test_from_npy_ingest_modes_bit_equal(tmp_path, prefetch):
    """Streamed (slab) and blocking (mono) file ingest agree bitwise,
    with and without the readahead thread (prefetch=0 is the fully
    synchronous oracle)."""
    mesh = _mesh(4)
    X = _data(701, 6, seed=3)
    path = tmp_path / "x.npy"
    np.save(path, X)
    ds = {mode: from_npy(path, mesh, chunk_size=32, ingest=mode,
                         prefetch=prefetch)
          for mode in ("mono", "slab")}
    _assert_ds_equal(ds["mono"], ds["slab"])
    np.testing.assert_array_equal(np.asarray(ds["slab"].points)[:701], X)


def test_from_npy_weighted_streamed_parity(tmp_path):
    mesh = _mesh(4)
    X = _data(400, 3, seed=5)
    sw = _weights(400, seed=6)
    path = tmp_path / "xw.npy"
    np.save(path, X)
    ds_m = from_npy(path, mesh, chunk_size=32, sample_weight=sw,
                    ingest="mono")
    ds_s = from_npy(path, mesh, chunk_size=32, sample_weight=sw,
                    ingest="slab")
    _assert_ds_equal(ds_m, ds_s)
    np.testing.assert_array_equal(np.asarray(ds_s.weights)[:400], sw)


def test_from_raw_ingest_modes_bit_equal(tmp_path):
    mesh = _mesh(4)
    X = _data(333, 4, seed=7)
    path = tmp_path / "x.bin"
    X.tofile(path)
    ds_m = from_raw(path, (333, 4), mesh, chunk_size=32, ingest="mono")
    ds_s = from_raw(path, (333, 4), mesh, chunk_size=32, ingest="slab")
    _assert_ds_equal(ds_m, ds_s)


# ---------------------------------------------------------------------------
# Family fits: bit-identical datasets -> bit-identical fits
# ---------------------------------------------------------------------------

def _family_fits(mode, mesh, X):
    """One small deterministic fit per family against a mode-ingested
    dataset; returns the fitted arrays that must match bitwise."""
    common = dict(seed=0, mesh=mesh, chunk_size=32, verbose=False)
    out = {}
    km = KMeans(k=4, max_iter=5, tolerance=1e-12, host_loop=False,
                empty_cluster="keep", ingest=mode, **common).fit(X)
    out["kmeans"] = (km.centroids, km.iterations_run)
    sk = SphericalKMeans(k=4, max_iter=5, tolerance=1e-12,
                         host_loop=False, empty_cluster="keep",
                         ingest=mode, **common).fit(X)
    out["spherical"] = (sk.centroids, sk.iterations_run)
    bk = BisectingKMeans(k=3, max_iter=5, ingest=mode, **common).fit(X)
    out["bisecting"] = (bk.centroids,)
    mb = MiniBatchKMeans(k=4, max_iter=5, batch_size=128,
                         sampling="device", ingest=mode, **common).fit(X)
    out["minibatch"] = (mb.centroids,)
    gm = GaussianMixture(n_components=3, max_iter=4, tol=0.0,
                         host_loop=False, init_params="random",
                         ingest=mode, **common).fit(X)
    out["gmm"] = (gm.means_, gm.weights_, gm.covariances_)
    return out


def test_five_family_fit_parity_mono_vs_slab():
    """The datasets are bit-identical across modes, so every family's
    whole fitted state must be too — ingest mode can never leak into
    results."""
    mesh = _mesh(4)
    X = _data(600, 4, seed=11)
    fits = {mode: _family_fits(mode, mesh, X)
            for mode in ("mono", "slab")}
    for family in fits["mono"]:
        for a, b in zip(fits["mono"][family], fits["slab"][family]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=family)


# ---------------------------------------------------------------------------
# Resume re-ingest parity
# ---------------------------------------------------------------------------

_RESUME_KW = dict(k=4, max_iter=14, tolerance=1e-12, seed=1,
                  compute_sse=True, empty_cluster="keep",
                  host_loop=False, verbose=False, dtype=np.float64)


@pytest.mark.parametrize("dp", [2, 4])
def test_resume_reingests_bit_identical_across_modes(tmp_path, dp):
    """A checkpoint killed mid-fit resumes BIT-identical whether the
    resuming process re-ingests mono or slab — and both match the
    uninterrupted fit (the test_elastic pin, ingest axis)."""
    mesh = _mesh(dp)
    from sklearn.datasets import make_blobs
    X, _ = make_blobs(n_samples=2000, centers=4, n_features=3,
                      random_state=9)
    X = X.astype(np.float32)
    full = KMeans(mesh=mesh, ingest="mono", **_RESUME_KW).fit(X)
    p = str(tmp_path / "ck.npz")
    with faults.inject_kill_after_iteration(4):
        with pytest.raises(faults.SimulatedPreemption):
            KMeans(mesh=mesh, ingest="mono", **_RESUME_KW).fit(
                X, checkpoint_every=2, checkpoint_path=p)
    resumed = {}
    for mode in ("mono", "slab"):
        m = KMeans(mesh=mesh, ingest=mode, **_RESUME_KW)
        m.fit(X, resume=p)
        resumed[mode] = m
    for m in resumed.values():
        assert m.iterations_run == full.iterations_run
        np.testing.assert_array_equal(m.centroids, full.centroids)
    np.testing.assert_array_equal(resumed["mono"].centroids,
                                  resumed["slab"].centroids)


# ---------------------------------------------------------------------------
# On-device synthetic shards (ISSUE 18c)
# ---------------------------------------------------------------------------

_BLOB_CENTERS = np.array([[0., 0., 0.], [5., 5., 0.], [-5., 0., 5.]],
                         np.float32)


def _synth_kw(kind):
    return {"centers": _BLOB_CENTERS} if kind == "blobs" else {}


@pytest.mark.parametrize("kind", synth.SYNTH_KINDS)
def test_device_shards_match_host_equivalent(kind):
    """The partition-invariance pin: rows born on their shard device
    equal the host oracle bit-for-bit (same (seed, row) fold_in
    stream)."""
    mesh = _mesh(8)
    n, d = 511, 3
    ds = synth.device_shards(n, d, mesh=mesh, kind=kind, seed=4,
                             chunk_size=16, **_synth_kw(kind))
    host = synth.host_equivalent(n, d, kind=kind, seed=4,
                                 **_synth_kw(kind))
    np.testing.assert_array_equal(np.asarray(ds.points)[:n], host)
    w = np.asarray(ds.weights)
    np.testing.assert_array_equal(w[:n], np.ones(n, np.float32))
    np.testing.assert_array_equal(w[n:], np.zeros(len(w) - n,
                                                  np.float32))


def test_device_shards_partition_invariant():
    """Any mesh produces the same rows — the property that makes the
    weak-scaling config reproducible at every worker count."""
    n, d = 256, 4
    a = synth.device_shards(n, d, mesh=_mesh(2), seed=7, chunk_size=16)
    b = synth.device_shards(n, d, mesh=_mesh(8), seed=7, chunk_size=16)
    c = synth.device_shards(n, d, mesh=None, seed=7, chunk_size=16)
    np.testing.assert_array_equal(np.asarray(a.points)[:n],
                                  np.asarray(b.points)[:n])
    np.testing.assert_array_equal(np.asarray(a.points)[:n],
                                  np.asarray(c.points)[:n])


def test_device_shards_tp_mesh_and_fit():
    """TP replication on the model axis + a fit on the device-born
    dataset (no host copy exists to fall back on)."""
    mesh = _mesh(2, 2)
    ds = synth.device_shards(300, 4, mesh=mesh, kind="uniform", seed=2,
                             chunk_size=16)
    host = synth.host_equivalent(300, 4, kind="uniform", seed=2)
    np.testing.assert_array_equal(np.asarray(ds.points)[:300], host)
    km = KMeans(k=3, max_iter=3, seed=0, mesh=mesh, chunk_size=16,
                host_loop=False, empty_cluster="keep",
                verbose=False).fit(ds)
    assert km.iterations_run >= 1
    assert np.all(np.isfinite(km.centroids))


def test_synthetic_error_cases():
    with pytest.raises(ValueError, match="kind must be one of"):
        synth.device_shards(10, 2, kind="cauchy")
    with pytest.raises(ValueError, match="kind must be one of"):
        synth.host_equivalent(10, 2, kind="cauchy")
    with pytest.raises(ValueError, match="requires an explicit"):
        synth.device_shards(10, 2, kind="blobs")
    with pytest.raises(ValueError, match="centers must be"):
        synth.host_equivalent(10, 2, kind="blobs",
                              centers=np.zeros((3, 5), np.float32))


# ---------------------------------------------------------------------------
# No resurrected host copies (the weighted-path satellite)
# ---------------------------------------------------------------------------

def test_slice_helpers_return_views_for_real_ranges():
    X = _data(100, 3)
    sw = _weights(100)
    assert np.shares_memory(_x_slice(X, 10, 50, 100), X)
    assert np.shares_memory(_w_slice(sw, 10, 50, 100, np.float32), sw)
    # Tail crossing n: a fresh padded buffer, zeros past n.
    tail = _x_slice(X, 90, 120, 100)
    assert not np.shares_memory(tail, X)
    np.testing.assert_array_equal(tail[10:], 0.0)
    wt = _w_slice(sw, 90, 120, 100, np.float32)
    np.testing.assert_array_equal(wt[:10], sw[90:])
    np.testing.assert_array_equal(wt[10:], 0.0)


def test_aligned_weighted_slab_ingest_allocates_no_row_scale_buffers(
        monkeypatch):
    """The satellite regression: an ALIGNED weighted slab ingest (n a
    multiple of shards*chunk — no padding tail) must stage pure views;
    the old path np.ones'd a full-size weight buffer every time."""
    mesh = _mesh(4)
    n = 4 * 32 * 8                      # aligned: no pad rows at all
    X = _data(n, 3)
    sw = _weights(n)
    big = []
    real_ones, real_zeros = np.ones, np.zeros

    def spy(real):
        def wrapped(shape, *a, **kw):
            size = int(np.prod(shape))
            if size >= n:
                big.append(shape)
            return real(shape, *a, **kw)
        return wrapped

    monkeypatch.setattr(np, "ones", spy(real_ones))
    monkeypatch.setattr(np, "zeros", spy(real_zeros))
    ds = to_device(X, mesh, 32, np.float32, sample_weight=sw,
                   ingest="slab")
    assert big == [], f"row-scale host allocations resurrected: {big}"
    np.testing.assert_array_equal(np.asarray(ds.weights)[:n], sw)


# ---------------------------------------------------------------------------
# Telemetry: counters, per-slab spans, the breakdown table
# ---------------------------------------------------------------------------

def test_ingest_bytes_counter_counts_the_payload():
    mesh = _mesh(2)
    X = _data(200, 4)
    before = obs_metrics.REGISTRY.counter("ingest.bytes").value
    to_device(X, mesh, 32, np.float32, ingest="mono")
    assert (obs_metrics.REGISTRY.counter("ingest.bytes").value
            - before) == X.nbytes


def test_per_slab_spans_feed_the_breakdown(monkeypatch):
    """Each staged slab emits a 'stage' span with slab/rows/bytes attrs;
    ingest_breakdown turns them into the per-slab TTFI attribution and
    format_ingest_table renders them with a TOTAL row."""
    mesh = _mesh(8)
    monkeypatch.setattr(obs_memory, "INGEST_SLAB_TARGET_BYTES", 1)
    X = _data(512, 4)
    with obs_trace.tracing() as tr:
        to_device(X, mesh, 32, np.float32, ingest="slab")
    rows = ingest_breakdown(tr.records())
    assert [r["slab"] for r in rows] == list(range(8))
    assert all(r["slabs"] == 8 for r in rows)
    assert sum(r["rows"] for r in rows) == 512
    assert sum(r["bytes"] for r in rows) == 512 * 4 * 4
    assert all(r["ms"] >= 0 for r in rows)
    table = format_ingest_table(rows)
    assert "TOTAL" in table and "slab" in table


def test_mono_ingest_has_no_slab_rows():
    mesh = _mesh(4)
    with obs_trace.tracing() as tr:
        to_device(_data(200, 3), mesh, 32, np.float32, ingest="mono")
    assert ingest_breakdown(tr.records()) == []


# ---------------------------------------------------------------------------
# Real multi-process streamed ingest (gated like tests/test_multihost.py)
# ---------------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_ingest_workers(nproc, tmp_path, timeout=420, mode="parity",
                        expect_rc=0):
    repo = Path(__file__).parent.parent
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = ":".join(
        p for p in [str(repo), env.get("PYTHONPATH")] if p)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [subprocess.Popen(
        [sys.executable, str(repo / "tests" / "ingest_worker.py"),
         str(i), str(nproc), str(port), str(tmp_path), mode],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in range(nproc)]
    outs = [p.communicate(timeout=timeout)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == expect_rc, out[-3000:]


@jaxlib_cpu_multiprocess_skip
@pytest.mark.parametrize("nproc", [2, 4])
def test_multiprocess_streamed_ingest_agrees(tmp_path, nproc):
    """REAL jax.distributed processes: each streams only its own local
    shards from the shared .npy (ingest='slab') yet matches the mono
    oracle locally, device-synthesizes the same rows as the host
    oracle, and every process fits to bitwise-identical centroids."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1536, 4)).astype(np.float32)
    np.save(tmp_path / "global.npy", X)
    _run_ingest_workers(nproc, tmp_path)
    c = [np.load(tmp_path / f"ingest_centroids_{i}.npy")
         for i in range(nproc)]
    for i in range(1, nproc):
        np.testing.assert_array_equal(c[0], c[i])


@jaxlib_cpu_multiprocess_skip
def test_streamed_ingest_resume_after_shrink(tmp_path):
    """ISSUE 19 shrink scenario at the ingest layer: a 2-process
    streamed-ingest fit is preempted mid-fit (deterministic kill after
    iteration 3, exit 75, rotating checkpoint left behind), then a
    1-process world resumes from that checkpoint.  The shrunk world
    must RE-DERIVE its streamed block ranges (its slab shards now tile
    ALL rows — asserted in the worker), and the resumed fit must match
    the uninterrupted same-world oracle bit-exactly (f64)."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1536, 4)).astype(np.float64)
    np.save(tmp_path / "global.npy", X)
    _run_ingest_workers(2, tmp_path, mode="kill-fit", expect_rc=75)
    assert (tmp_path / "ingest_ck.npz").exists()
    _run_ingest_workers(1, tmp_path, mode="resume-fit")
    got = np.load(tmp_path / "resume_centroids_0.npy")
    assert got.dtype == np.float64 and got.shape == (4, 4)
