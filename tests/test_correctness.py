"""Test A capability: correctness parity against the sklearn gold standard.

Reproduces the reference's T1 (``test_a_correctness``, kmeans_spark.py:
355-399) as a REAL assertion (the reference swallows its own AssertionError,
:391-397, so its exit code never reflects failure — SURVEY.md §4): 1000
points / 3 centers / 2-D make_blobs(random_state=42); sorted centroids within
``atol=1e-4`` of sklearn.

One strengthening over the reference: T1 only matched sklearn because two
DIFFERENT random inits (Spark ``takeSample`` vs sklearn's weighted
``RandomState.choice``) happened to converge to the same optimum on this easy
fixture.  Here the trajectory-parity tests pin the SAME explicit init on both
implementations (both APIs accept an init array), so centroid equality is a
property of the algorithm, not of fixture luck; a separate test covers
default-init quality parity via SSE.
"""

import numpy as np
import pytest
from sklearn.cluster import KMeans as SklearnKMeans

from kmeans_tpu import KMeans


def _sorted(c):
    return np.array(sorted(np.asarray(c).tolist()))


def _shared_init(X, k, seed=42):
    rng = np.random.RandomState(seed)
    return X[rng.choice(X.shape[0], size=k, replace=False)]


@pytest.mark.parametrize("mode", ["matmul", "direct"])
def test_parity_with_sklearn(blobs_small, mesh8, mode):
    # Run BOTH to the exact Lloyd fixed point (tiny tol) — sklearn scales its
    # tol by data variance, so matching loose tolerances compares stopping
    # criteria, not the algorithm.  At the fixed point the comparison is
    # sharp and the reference's atol=1e-4 (kmeans_spark.py:392) is easy.
    X, _ = blobs_small
    init = _shared_init(X, 3)
    ours = KMeans(k=3, max_iter=300, tolerance=1e-12, seed=42,
                  compute_sse=True, init=init, mesh=mesh8, dtype=np.float64,
                  distance_mode=mode, verbose=False).fit(X)
    ref = SklearnKMeans(n_clusters=3, init=init, n_init=1, max_iter=300,
                        random_state=42, tol=1e-14).fit(X)
    np.testing.assert_allclose(
        _sorted(ours.centroids), _sorted(ref.cluster_centers_), atol=1e-4)


def test_default_init_quality_parity(blobs_small, mesh8):
    # Default seeded Forgy init vs sklearn's default run: same fixture, SSE
    # within 1% — the robust version of the reference's luck-dependent check.
    X, _ = blobs_small
    ours = KMeans(k=3, max_iter=100, tolerance=1e-4, seed=0,
                  compute_sse=True, mesh=mesh8, dtype=np.float64,
                  verbose=False).fit(X)
    ref = SklearnKMeans(n_clusters=3, n_init=10, random_state=0).fit(X)
    assert ours.inertia_ <= ref.inertia_ * 1.01


def test_parity_float32_single_device(blobs_small, mesh1):
    # The TPU-realistic dtype still matches the oracle on this fixture.
    X, _ = blobs_small
    init = _shared_init(X, 3)
    ours = KMeans(k=3, max_iter=300, tolerance=1e-7, seed=42,
                  compute_sse=True, init=init, mesh=mesh1,
                  dtype=np.float32, verbose=False).fit(X)
    ref = SklearnKMeans(n_clusters=3, init=init, n_init=1, max_iter=300,
                        random_state=42, tol=1e-14).fit(X)
    np.testing.assert_allclose(
        _sorted(ours.centroids), _sorted(ref.cluster_centers_), atol=1e-3)


def test_final_sse_matches_sklearn_inertia(blobs_small, mesh8):
    X, _ = blobs_small
    init = _shared_init(X, 3)
    ours = KMeans(k=3, seed=42, compute_sse=True, init=init, mesh=mesh8,
                  dtype=np.float64, verbose=False).fit(X)
    ref = SklearnKMeans(n_clusters=3, init=init, n_init=1,
                        random_state=42, tol=1e-4).fit(X)
    # Our recorded SSE is measured against each iteration's STARTING
    # centroids (reference semantics, kmeans_spark.py:279); at convergence
    # the assignment is stable so it equals sklearn's inertia_.
    assert ours.inertia_ == pytest.approx(ref.inertia_, rel=1e-4)


def test_predict_self_consistent(blobs_small, mesh8):
    X, _ = blobs_small
    ours = KMeans(k=3, seed=42, mesh=mesh8, dtype=np.float64,
                  verbose=False).fit(X)
    labels = ours.predict(X)
    # Internal consistency: every point is closest to its assigned centroid.
    d = ours.transform(X)
    np.testing.assert_array_equal(labels, np.argmin(d, axis=1))
