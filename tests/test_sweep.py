"""Batched multi-k sweeps (ISSUE 7): fit-many, pick-best in O(1)
dispatches.

Parity discipline: ``sweep(batched=0)`` — sequential per-member
device-loop fits on the same cached dataset — is the oracle.  Every
batched member's trajectory (centroids, iteration counts, histories)
must match its standalone fit at the matched seed BIT-EXACTLY for the
K-Means f64 device-loop class (the r10 parity table: each padded
distance column and one-hot scatter row is an independent dot product,
and min/argmin over extra sentinel columns is exact); the final-inertia
SCORES sit in the cross-program f64 reduction class (a vmapped
reduction tree need not match the unbatched one — ≤ few ulps), and the
GMM members in the documented GMM reduction class.
"""

import numpy as np
import pytest

import jax

from kmeans_tpu import GaussianMixture, KMeans, SphericalKMeans
from kmeans_tpu import metrics as metrics_mod
from kmeans_tpu.parallel.mesh import make_mesh
from kmeans_tpu.sweep import (SweepResult, elbow_index, parse_k_range,
                              select_k)
from kmeans_tpu.utils import profiling


def blobs(n_per=150, d=4, n_centers=4, seed=0, scale=10.0):
    # f32-WIDTH values in a float64 array — the r10 f64 parity-class
    # convention: f32-width data accumulated in f64 sums exactly, so
    # any reduction regrouping (vmapped vs unbatched, resharded psum)
    # is invariant and the bit-exact pins below are well-defined.
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=scale, size=(n_centers, d))
    X = np.concatenate([c + rng.normal(size=(n_per, d))
                        for c in centers])
    return X.astype(np.float32).astype(np.float64)


# ------------------------------------------------------------- k-range


def test_parse_k_range_grammar():
    assert parse_k_range("2:9") == tuple(range(2, 9))
    assert parse_k_range("2:9:2") == (2, 4, 6, 8)
    assert parse_k_range("8,2,4,2") == (2, 4, 8)
    assert parse_k_range(range(3, 6)) == (3, 4, 5)
    assert parse_k_range([5, 3]) == (3, 5)


@pytest.mark.parametrize("bad", ["9:2", "abc", "2:3:4:5", "", "0:4", 7])
def test_parse_k_range_invalid(bad):
    with pytest.raises(ValueError):
        parse_k_range(bad)


def test_elbow_rule():
    # A clean knee at k=4 on an inertia-like curve.
    ks = (2, 3, 4, 5, 6, 7)
    inertias = [100.0, 60.0, 10.0, 9.0, 8.5, 8.2]
    assert ks[elbow_index(ks, inertias)] == 4
    assert select_k(ks, inertias, "inertia") == 4
    # Degenerate: < 3 points falls back to min inertia.
    assert select_k((2, 3), [100.0, 10.0], "inertia") == 3
    # Direction rules.
    assert select_k(ks, [1, 5, 3, 2, 1, 0], "silhouette") == 3
    assert select_k(ks, [4, 1, 3, 9, 9, 9], "davies_bouldin") == 3
    assert select_k(ks, [4, 1, 3, 9, 9, 9], "bic") == 3


# ------------------------------------------------------- kmeans parity


def kw64(**over):
    kw = dict(max_iter=25, tolerance=1e-10, seed=7, n_init=2,
              empty_cluster="keep", verbose=False, dtype=np.float64)
    kw.update(over)
    return kw


def test_kmeans_sweep_batched_matches_oracle_f64():
    X = blobs()
    ks = range(2, 8)
    res = KMeans(k=3, **kw64()).sweep(X, k_range=ks, criterion="inertia")
    res0 = KMeans(k=3, **kw64()).sweep(X, k_range=ks, criterion="inertia",
                                       batched=0)
    # Trajectory parity: bit-exact member iteration counts and the
    # selected model's centroids (the f64 device-loop class).
    np.testing.assert_array_equal(res.n_iters, res0.n_iters)
    assert res.selected_k == res0.selected_k
    assert res.selected_restart == res0.selected_restart
    np.testing.assert_array_equal(res.best_model.centroids,
                                  res0.best_model.centroids)
    np.testing.assert_array_equal(res.best_model.cluster_sizes_,
                                  res0.best_model.cluster_sizes_)
    # Scores: cross-program f64 reduction class (<= few ulps).
    np.testing.assert_allclose(res.member_scores, res0.member_scores,
                               rtol=1e-12)


def test_kmeans_sweep_member_matches_standalone_fit():
    """Each batched member == a standalone device-loop fit at (k, seed)
    on the same cached dataset — the inert k_max padding never perturbs
    real-member arithmetic (iteration counts and final inertias pinned
    per member; the selected member's centroids bit-exactly)."""
    X = blobs(seed=3)
    engine = KMeans(k=6, **kw64(n_init=1))
    ds = engine.cache(X)
    res = engine.sweep(ds, k_range=[3, 5, 6], criterion="inertia")
    seed = engine._restart_seeds()[0]
    for i, k in enumerate([3, 5, 6]):
        st = KMeans(**kw64(k=k, n_init=1, seed=seed, host_loop=False))
        st.fit(ds)
        assert res.n_iters[i, 0] == st.iterations_run
        inertia = -st.score(ds)
        np.testing.assert_allclose(res.member_scores[i, 0], inertia,
                                   rtol=1e-12)
        if k == res.selected_k:
            np.testing.assert_array_equal(res.best_model.centroids,
                                          st.centroids)


@pytest.mark.parametrize("data,model", [(1, 1), (2, 1), (4, 1), (8, 1),
                                        (2, 2), (4, 2)])
def test_kmeans_sweep_mesh_matrix(data, model):
    """Batched == oracle across the {1,2,4,8}-way mesh matrix including
    TP centroid sharding (the k_max padding interacts with the model-
    axis padding)."""
    X = blobs(n_per=64, d=8, seed=1)
    mesh = make_mesh(data=data, model=model,
                     devices=jax.devices()[: data * model])
    kw = kw64(mesh=mesh, chunk_size=32, max_iter=10)
    res = KMeans(k=3, **kw).sweep(X, k_range=range(2, 6),
                                  criterion="inertia")
    kw2 = kw64(mesh=mesh, chunk_size=32, max_iter=10)
    res0 = KMeans(k=3, **kw2).sweep(X, k_range=range(2, 6),
                                    criterion="inertia", batched=0)
    np.testing.assert_array_equal(res.n_iters, res0.n_iters)
    np.testing.assert_array_equal(res.best_model.centroids,
                                  res0.best_model.centroids)
    np.testing.assert_allclose(res.member_scores, res0.member_scores,
                               rtol=1e-12)
    assert res.selected_k == res0.selected_k


def test_sweep_dispatch_count_is_O1_in_k_range():
    """The tentpole's economics: ONE fit dispatch regardless of
    |k_range| (pinned via utils/profiling.log_dispatches)."""
    X = blobs(n_per=60)
    counts = {}
    for ks in (range(2, 4), range(2, 10)):
        km = KMeans(k=3, **kw64(max_iter=8))
        with profiling.log_dispatches() as log:
            res = km.sweep(X, k_range=ks, criterion="inertia")
        counts[len(tuple(ks))] = (log.count("sweep/fit"), len(log))
        assert res.n_dispatches == 1
    # Same dispatch structure for a 2-wide and an 8-wide range.
    assert counts[2] == counts[8] == (1, 1)


def test_sweep_metric_criteria_batched_vs_sequential():
    X = blobs(n_per=80, seed=5)
    for crit in ("calinski_harabasz", "davies_bouldin", "silhouette"):
        res = KMeans(k=3, **kw64(max_iter=12)).sweep(
            X, k_range=[2, 3, 4, 5], criterion=crit)
        res0 = KMeans(k=3, **kw64(max_iter=12)).sweep(
            X, k_range=[2, 3, 4, 5], criterion=crit, batched=0)
        np.testing.assert_allclose(res.scores, res0.scores, rtol=1e-5,
                                   err_msg=crit)
        assert res.selected_k == res0.selected_k
        # Criterion scoring is batched: fit + one packed-labels pass +
        # the O(1) metric passes, NOT O(|k_range|) round trips.
        assert res.n_dispatches <= 2 + \
            metrics_mod.SWEEP_SCORE_DISPATCHES[crit]


def test_batched_criterion_scores_match_single_fns():
    X = blobs(n_per=70, seed=9).astype(np.float32)
    rng = np.random.default_rng(0)
    L = np.stack([rng.integers(0, 3, X.shape[0]),
                  rng.integers(0, 5, X.shape[0]),
                  (X[:, 0] > 0).astype(np.int32)])
    for crit, single in [
            ("calinski_harabasz", metrics_mod.calinski_harabasz_score),
            ("davies_bouldin", metrics_mod.davies_bouldin_score),
            ("silhouette", metrics_mod.silhouette_score)]:
        batched = metrics_mod.batched_criterion_scores(X, L, crit)
        singles = [single(X, L[m]) for m in range(L.shape[0])]
        np.testing.assert_allclose(batched, singles, rtol=1e-5,
                                   atol=1e-7, err_msg=crit)


def test_batched_silhouette_sample_size_matches_single():
    # The subsample path mirrors silhouette_score(sample_size=, seed=):
    # the SAME seeded rows for every member, so batched == singles.
    X = blobs(n_per=80, seed=4).astype(np.float32)
    rng = np.random.default_rng(1)
    L = np.stack([rng.integers(0, 3, X.shape[0]),
                  rng.integers(0, 4, X.shape[0])])
    batched = metrics_mod.batched_criterion_scores(
        X, L, "silhouette", sample_size=100, seed=7)
    singles = [metrics_mod.silhouette_score(X, L[m], sample_size=100,
                                            seed=7)
               for m in range(L.shape[0])]
    np.testing.assert_allclose(batched, singles, rtol=1e-5, atol=1e-7)


def test_batched_criterion_degenerate_member_scores_nan():
    # One collapsed member (a single occupied cluster) must score NaN
    # — NOT abort the batch (a sweep winner can collapse under
    # empty_cluster='keep' at k far above the data's structure).
    X = blobs(n_per=60, seed=2).astype(np.float32)
    rng = np.random.default_rng(3)
    L = np.stack([rng.integers(0, 3, X.shape[0]),
                  np.zeros(X.shape[0], np.int64),      # degenerate
                  rng.integers(0, 4, X.shape[0])])
    for crit, single in [
            ("calinski_harabasz", metrics_mod.calinski_harabasz_score),
            ("davies_bouldin", metrics_mod.davies_bouldin_score),
            ("silhouette", metrics_mod.silhouette_score)]:
        scores = metrics_mod.batched_criterion_scores(X, L, crit)
        assert np.isnan(scores[1]), crit
        np.testing.assert_allclose(
            scores[[0, 2]], [single(X, L[m]) for m in (0, 2)],
            rtol=1e-5, atol=1e-7, err_msg=crit)


def test_sweep_result_summary_jsonable():
    import json
    X = blobs(n_per=40)
    res = KMeans(k=3, **kw64(max_iter=6)).sweep(X, k_range=[2, 3],
                                                criterion="inertia")
    assert isinstance(res, SweepResult)
    s = json.loads(json.dumps(res.summary()))
    assert s["selected_k"] == res.selected_k
    assert s["dispatches"] == res.n_dispatches


def test_sweep_empty_policy_resample_parity():
    """Gumbel empty-refill draws are keyed per member seed — the batched
    sweep refills exactly like the sequential members (k=8 on 3 tight
    blobs forces empties)."""
    rng = np.random.default_rng(2)
    centers = np.array([[0.0, 0.0], [30.0, 30.0], [60.0, 0.0]])
    X = np.concatenate([c + 0.2 * rng.normal(size=(50, 2))
                        for c in centers]).astype(np.float64)
    kw = kw64(max_iter=12, empty_cluster="resample", n_init=2)
    res = KMeans(k=3, **kw).sweep(X, k_range=[4, 8], criterion="inertia")
    kw2 = kw64(max_iter=12, empty_cluster="resample", n_init=2)
    res0 = KMeans(k=3, **kw2).sweep(X, k_range=[4, 8],
                                    criterion="inertia", batched=0)
    np.testing.assert_array_equal(res.n_iters, res0.n_iters)
    np.testing.assert_allclose(res.member_scores, res0.member_scores,
                               rtol=1e-12)


# ------------------------------------------------------------ spherical


def test_spherical_sweep_runs_on_normalized_geometry():
    rng = np.random.default_rng(4)
    dirs = rng.normal(size=(3, 8))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    X = np.concatenate([
        (d + 0.15 * rng.normal(size=(80, 8))) * rng.uniform(
            0.5, 5.0, size=(80, 1)) for d in dirs])
    sk = SphericalKMeans(k=2, max_iter=15, seed=0, n_init=2,
                         empty_cluster="keep", verbose=False)
    res = sk.sweep(X, k_range=range(2, 6), criterion="silhouette")
    assert res.selected_k in range(2, 6)
    # The winner is a spherical model: unit-norm centroids.
    np.testing.assert_allclose(
        np.linalg.norm(res.best_model.centroids, axis=1), 1.0,
        atol=1e-4)
    labels = res.best_model.predict(X[:32])
    assert labels.shape == (32,)


# ------------------------------------------------------------------ GMM


def test_gmm_sweep_bic_selects_true_k_and_matches_oracle():
    rng = np.random.default_rng(0)
    centers = np.array([[0, 0, 0], [9, 9, 0], [18, 0, 9]], float)
    X = (centers[rng.integers(0, 3, 900)]
         + rng.normal(size=(900, 3))).astype(np.float32)
    kw = dict(covariance_type="diag", max_iter=30, tol=1e-5, seed=3,
              n_init=2, init_params="random", verbose=False)
    res = GaussianMixture(n_components=2, **kw).sweep(
        X, k_range=range(1, 7), criterion="bic")
    assert res.selected_k == 3
    assert res.n_dispatches == 1
    res0 = GaussianMixture(n_components=2, **kw).sweep(
        X, k_range=range(1, 7), criterion="bic", batched=0)
    assert res0.selected_k == 3
    # Documented GMM reduction class: same members, close scores.
    np.testing.assert_allclose(res.member_scores, res0.member_scores,
                               rtol=1e-4)
    np.testing.assert_allclose(res.scores, res0.scores, rtol=1e-3)
    np.testing.assert_allclose(
        np.sort(res.best_model.means_, axis=0),
        np.sort(res0.best_model.means_, axis=0), atol=1e-2)
    # The fitted winner scores like a normally-fitted model.
    assert np.isfinite(res.best_model.bic(X))


def test_gmm_sweep_aic_and_spherical_cov():
    rng = np.random.default_rng(1)
    X = np.concatenate([rng.normal(loc=c, size=(200, 2))
                        for c in ((0, 0), (8, 8))]).astype(np.float32)
    gm = GaussianMixture(n_components=2, covariance_type="spherical",
                         max_iter=20, seed=0, init_params="random",
                         verbose=False)
    res = gm.sweep(X, k_range=[1, 2, 3, 4], criterion="aic")
    assert res.selected_k == 2
    assert res.best_model.covariances_.shape == (2,)


def test_gmm_sweep_full_cov_falls_back_sequential():
    rng = np.random.default_rng(2)
    X = np.concatenate([rng.normal(loc=c, size=(150, 2))
                        for c in ((0, 0), (7, 7))]).astype(np.float32)
    gm = GaussianMixture(n_components=2, covariance_type="full",
                         max_iter=15, seed=0, init_params="random",
                         verbose=False)
    with pytest.warns(UserWarning, match="diag/spherical"):
        res = gm.sweep(X, k_range=[1, 2, 3], criterion="bic")
    assert res.batched is False
    assert res.selected_k == 2
    assert res.best_model.covariances_.shape == (2, 2, 2)


# ---------------------------------------------------------------- errors


def test_sweep_rejects_array_init_and_unsweepable_families():
    X = blobs(n_per=30)
    with pytest.raises(ValueError, match="init"):
        KMeans(k=3, init=X[:3], verbose=False).sweep(
            X, k_range=[2, 3])
    from kmeans_tpu import BisectingKMeans, MiniBatchKMeans
    for cls in (MiniBatchKMeans, BisectingKMeans):
        with pytest.raises(NotImplementedError):
            cls(k=3, verbose=False).sweep(X, k_range=[2, 3])
    with pytest.raises(ValueError, match="criterion"):
        KMeans(k=3, verbose=False).sweep(X, k_range=[2, 3],
                                         criterion="bic")
    with pytest.raises(ValueError, match="must be <"):
        KMeans(k=3, verbose=False).sweep(X[:5], k_range=[2, 6])
    with pytest.raises(ValueError, match="means"):
        GaussianMixture(n_components=2, means_init=X[:2, :],
                        verbose=False).sweep(X, k_range=[2, 3])
