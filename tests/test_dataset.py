"""ShardedDataset (device-resident data, the ``rdd.cache()`` analogue,
kmeans_spark.py:256): upload once, reuse across fit/predict/score."""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans, ShardedDataset


@pytest.fixture(scope="module")
def data():
    X, _ = make_blobs(n_samples=2500, centers=4, n_features=6,
                      random_state=3)
    return X.astype(np.float64)


def test_cached_dataset_matches_array_fit(data, mesh8):
    km_a = KMeans(k=4, seed=1, compute_sse=True, mesh=mesh8,
                  dtype=np.float64, verbose=False).fit(data)
    km_b = KMeans(k=4, seed=1, compute_sse=True, mesh=mesh8,
                  dtype=np.float64, verbose=False)
    ds = km_b.cache(data)
    assert isinstance(ds, ShardedDataset) and ds.n == len(data)
    km_b.fit(ds)
    np.testing.assert_array_equal(km_a.centroids, km_b.centroids)
    assert km_a.sse_history == km_b.sse_history
    # Reuse for predict and score without re-upload.
    np.testing.assert_array_equal(km_a.predict(data), km_b.predict(ds))
    assert km_a.score(data) == pytest.approx(km_b.score(ds))


def test_dataset_device_loop(data, mesh8):
    km = KMeans(k=4, seed=1, empty_cluster="keep", mesh=mesh8,
                dtype=np.float64, host_loop=False, verbose=False)
    ds = km.cache(data)
    km.fit(ds)
    assert np.all(np.isfinite(km.centroids))


def test_dataset_dtype_mismatch_raises(data, mesh8):
    km32 = KMeans(k=4, mesh=mesh8, dtype=np.float32, verbose=False)
    ds64 = KMeans(k=4, mesh=mesh8, dtype=np.float64,
                  verbose=False).cache(data)
    with pytest.raises(ValueError, match="dtype"):
        km32.fit(ds64)


def test_dataset_mesh_mismatch_raises(data, mesh8, mesh4x2):
    ds = KMeans(k=4, mesh=mesh8, dtype=np.float64, verbose=False).cache(data)
    km = KMeans(k=4, mesh=mesh4x2, dtype=np.float64, verbose=False)
    with pytest.raises(ValueError, match="different mesh"):
        km.fit(ds)


def test_dataset_take(data, mesh8):
    ds = KMeans(k=4, mesh=mesh8, dtype=np.float64, verbose=False).cache(data)
    idx = np.array([0, 5, 2499])
    np.testing.assert_array_equal(ds.take(idx), data[idx])
    # Device-only gather path (host reference dropped).
    ds._host = None
    np.testing.assert_allclose(ds.take(idx), data[idx])
