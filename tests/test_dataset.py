"""ShardedDataset (device-resident data, the ``rdd.cache()`` analogue,
kmeans_spark.py:256): upload once, reuse across fit/predict/score."""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans, ShardedDataset


@pytest.fixture(scope="module")
def data():
    X, _ = make_blobs(n_samples=2500, centers=4, n_features=6,
                      random_state=3)
    return X.astype(np.float64)


def test_cached_dataset_matches_array_fit(data, mesh8):
    km_a = KMeans(k=4, seed=1, compute_sse=True, mesh=mesh8,
                  dtype=np.float64, verbose=False).fit(data)
    km_b = KMeans(k=4, seed=1, compute_sse=True, mesh=mesh8,
                  dtype=np.float64, verbose=False)
    ds = km_b.cache(data)
    assert isinstance(ds, ShardedDataset) and ds.n == len(data)
    km_b.fit(ds)
    np.testing.assert_array_equal(km_a.centroids, km_b.centroids)
    assert km_a.sse_history == km_b.sse_history
    # Reuse for predict and score without re-upload.
    np.testing.assert_array_equal(km_a.predict(data), km_b.predict(ds))
    assert km_a.score(data) == pytest.approx(km_b.score(ds))


def test_dataset_device_loop(data, mesh8):
    km = KMeans(k=4, seed=1, empty_cluster="keep", mesh=mesh8,
                dtype=np.float64, host_loop=False, verbose=False)
    ds = km.cache(data)
    km.fit(ds)
    assert np.all(np.isfinite(km.centroids))


def test_dataset_dtype_mismatch_raises(data, mesh8):
    km32 = KMeans(k=4, mesh=mesh8, dtype=np.float32, verbose=False)
    ds64 = KMeans(k=4, mesh=mesh8, dtype=np.float64,
                  verbose=False).cache(data)
    with pytest.raises(ValueError, match="dtype"):
        km32.fit(ds64)


def test_dataset_mesh_mismatch_raises(data, mesh8, mesh4x2):
    ds = KMeans(k=4, mesh=mesh8, dtype=np.float64, verbose=False).cache(data)
    km = KMeans(k=4, mesh=mesh4x2, dtype=np.float64, verbose=False)
    with pytest.raises(ValueError, match="different mesh"):
        km.fit(ds)


def test_dataset_take(data, mesh8):
    ds = KMeans(k=4, mesh=mesh8, dtype=np.float64, verbose=False).cache(data)
    idx = np.array([0, 5, 2499])
    np.testing.assert_array_equal(ds.take(idx), data[idx])
    # Device-only gather path (host reference dropped).
    ds._host = None
    np.testing.assert_allclose(ds.take(idx), data[idx])


# --------------------------------------------------------- chunk sizing (r5)

def test_choose_chunk_size_regions():
    """The r5 single-chunk shortcut (experiments/exp_small_shapes.py:
    1.72x at 1M x 16 k=64) and the unchanged scan regions."""
    from kmeans_tpu.parallel.sharding import choose_chunk_size
    # Single-chunk region: n*k <= 2^26 -> whole shard, rounded up to 8.
    assert choose_chunk_size(1_000_000, 64, 16) == 1_000_000
    assert choose_chunk_size(999_999, 64, 16) == 1_000_000
    # Scan regions unchanged: headline and high-k shapes.
    assert choose_chunk_size(10_000_000, 1024, 128) == 32768
    assert choose_chunk_size(400_000, 3000, 100) == (1 << 25) // 3000 // 8 * 8
    # Explicit budget (the EM paths) opts OUT of the shortcut.
    assert choose_chunk_size(1_000_000, 64, 16,
                             budget_elems=1 << 23) == 131072
    # Tiny inputs keep the 128-row floor.
    assert choose_chunk_size(5, 5, 2) == 128


def test_clamp_chunk_for_k_divisor_property():
    """clamp_chunk_for_k returns a multiple-of-8 divisor within budget —
    the guard against load-time k_hint undershooting the fitted k
    (r5 review finding)."""
    from kmeans_tpu.parallel.sharding import clamp_chunk_for_k
    # No-op when the tile fits.
    assert clamp_chunk_for_k(1_000_000, 64) == 1_000_000
    # Mis-hinted: 4M-row chunk fitted with k=1024 must shrink to the
    # LARGEST divisor with chunk*k <= 2^26 — not merely any divisor
    # (the r5 review caught a units bug returning 6400 here).
    assert clamp_chunk_for_k(4_000_000, 1024) == 50_000
    assert clamp_chunk_for_k(1 << 20, 1024) == 1 << 16
    # Non-multiple-of-8 explicit chunks pass through untouched (only
    # true divisors of the committed chunk re-chunk safely).
    assert clamp_chunk_for_k(1_000_004, 1024) == 1_000_004
    # Awkward row counts yield either the largest in-budget divisor
    # >= the 128-row floor, or — when the divisor structure skips the
    # whole [128, budget] window — the smallest divisor >= 128 with a
    # warning (the sparse-divisor fallback, ADVICE r5 medium; the old
    # contract's sub-128-row results were the pathology it replaces).
    import warnings
    for chunk in (999_992, 777_768, 123_456_008):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            c = clamp_chunk_for_k(chunk, 4096, budget_elems=1 << 20)
        assert chunk % c == 0 and c % 8 == 0 and c >= 128
        if c * 4096 <= 1 << 20:
            # In budget: no bigger multiple-of-8 divisor >= 128 fits.
            bigger = [v for v in range(c + 8, chunk + 1, 8)
                      if chunk % v == 0 and v * 4096 <= 1 << 20]
            assert not bigger
        else:
            # Fallback: the smallest multiple-of-8 divisor >= 128.
            smaller = [v for v in range(128, c, 8) if chunk % v == 0]
            assert not smaller


def test_mis_hinted_dataset_fit_matches(data, mesh8, tmp_path):
    """A dataset loaded with a too-small k_hint still fits correctly
    (the clamp changes only tiling, never results)."""
    from kmeans_tpu.data.io import from_npy
    p = tmp_path / "x.npy"
    np.save(p, data.astype(np.float64))
    ds = from_npy(p, mesh8, k_hint=1, dtype=np.float64)
    km_a = KMeans(k=4, seed=1, mesh=mesh8, dtype=np.float64,
                  verbose=False).fit(ds)
    km_b = KMeans(k=4, seed=1, mesh=mesh8, dtype=np.float64,
                  verbose=False).fit(data)
    np.testing.assert_allclose(km_a.centroids, km_b.centroids)


def test_explicit_chunk_passes_through(data, mesh8):
    """A user-supplied chunk_size is the documented override: fits must
    honor it verbatim, never clamp it (r5 review)."""
    km = KMeans(k=4, seed=1, mesh=mesh8, dtype=np.float64,
                chunk_size=320, verbose=False)
    ds = km.cache(data)
    assert ds.explicit_chunk and ds.chunk == 320
    assert ds.effective_chunk(10 ** 9) == 320      # huge k: still honored
    # Auto-chunked datasets are clamped for huge k (with a floor).
    ds_auto = KMeans(k=4, mesh=mesh8, dtype=np.float64,
                     verbose=False).cache(data)
    assert not ds_auto.explicit_chunk
    # with_weights shares placement AND the explicit flag.
    assert ds.with_weights(np.ones(len(data))).explicit_chunk


def test_clamp_chunk_sparse_divisor_fallback_warns():
    """Divisor-pathology regression (ADVICE r5 medium): a committed
    chunk whose divisors skip the [128, budget] window must fall back
    to the SMALLEST multiple-of-8 divisor >= 128 (budget overshoot,
    loudly) instead of silently scanning degenerate 24-row tiles —
    4,000,008 rows at k=1024 is the reported case (divisors of 500001
    are {1, 3, 166667, 500001})."""
    import warnings
    from kmeans_tpu.parallel.sharding import clamp_chunk_for_k
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        c = clamp_chunk_for_k(4_000_008, 1024)
    assert c == 1_333_336                    # 166667 * 8: smallest >= 128
    assert 4_000_008 % c == 0 and c % 8 == 0
    assert any("clamp_chunk_for_k" in str(w.message) for w in rec)
    # A prime-structured chunk with NO in-window divisor at all keeps
    # the whole chunk (the only divisor >= 128), still warning.
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        c = clamp_chunk_for_k(8 * 166667, 1024, budget_elems=1 << 20)
    assert c == 8 * 166667
    assert any("clamp_chunk_for_k" in str(w.message) for w in rec)


def test_choose_chunk_shortcut_honors_max_chunk():
    """The single-chunk shortcut must not violate an explicit non-default
    ``max_chunk`` cap (ADVICE r5 low) — while the DEFAULT cap is still
    deliberately exceeded in the single-chunk region."""
    from kmeans_tpu.parallel.sharding import choose_chunk_size
    capped = choose_chunk_size(5000, 4, 8, max_chunk=1024)
    assert capped <= 1024 and capped % 8 == 0 and capped >= 128
    # Default cap: the shortcut intentionally returns the whole shard.
    assert choose_chunk_size(5000, 4, 8) == 5000
    assert choose_chunk_size(4_000_000, 16, 8) == 4_000_000
    # An EXPLICIT cap equal to the implicit default is still a stated
    # contract (None is the unspecified sentinel).
    assert choose_chunk_size(1_000_000, 16, 8, max_chunk=1 << 17) \
        == 1 << 17
    # Sub-floor caps are floored like the scan branch's 128 floor.
    assert choose_chunk_size(5000, 4, 8, max_chunk=64) == 128


def test_gmm_eff_chunk_bounded_by_em_plateau():
    """GMM's clamp of a mis-hinted foreign dataset is bounded by the
    measured EM row plateau, not the element budget alone (ADVICE r5
    low): a 50,000-row committed chunk at small k survives the 2^23
    budget but must still land at a divisor near EM_MAX_CHUNK."""
    from kmeans_tpu.models.gmm import EM_MAX_CHUNK, GaussianMixture
    from kmeans_tpu.parallel.sharding import to_device
    rng = np.random.default_rng(0)
    X = rng.normal(size=(50_000, 4)).astype(np.float64)
    ds = to_device(X, None, 50_000, np.float64)    # auto-style commit
    assert not ds.explicit_chunk
    gm = GaussianMixture(n_components=64, dtype=np.float64, verbose=False)
    eff = gm._eff_chunk(ds)
    assert eff == 25_000                           # largest divisor <= 32768
    assert ds.chunk % eff == 0 and eff <= EM_MAX_CHUNK
    # Explicit chunks keep the documented pass-through override.
    ds_exp = to_device(X, None, 50_000, np.float64, explicit=True)
    assert gm._eff_chunk(ds_exp) == 50_000


def test_clamp_noop_at_the_row_floor():
    """clamp_chunk_for_k must not shrink chunks at/below the 128-row
    floor choose_chunk_size deliberately enforces (r5 review: a full-
    covariance GMM with k*D > budget/128 floors at 128 and must stay
    there, not scan 8-row tiles)."""
    from kmeans_tpu.parallel.sharding import clamp_chunk_for_k
    assert clamp_chunk_for_k(128, 256 * 512, 1 << 23) == 128
    assert clamp_chunk_for_k(128, 1024 * 1024, 1 << 23) == 128
