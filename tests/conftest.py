"""Test harness configuration.

The reference's "multi-node without a cluster" story is Spark local mode with
parallelism simulated by partition count (SURVEY.md §4).  Ours is the JAX
equivalent: an 8-virtual-device CPU platform
(``--xla_force_host_platform_device_count=8``) so every sharding/collective
path runs in CI without TPU hardware; the same code runs unchanged on a real
TPU mesh.  x64 is enabled so parity tests can run in float64 like the
NumPy-based reference; the framework itself defaults to float32.
"""

import os

# Force the CPU platform for tests (the session environment may pin
# JAX_PLATFORMS to a real accelerator); override with
# KMEANS_TPU_TEST_PLATFORM=tpu to run the suite on hardware.
os.environ["JAX_PLATFORMS"] = os.environ.get(
    "KMEANS_TPU_TEST_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The config update (not just the env var) matters: a sitecustomize may have
# imported jax before this conftest ran, baking the session's JAX_PLATFORMS
# into the config default.
jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
jax.config.update("jax_enable_x64", True)
if os.environ["JAX_PLATFORMS"] != "cpu":
    # On TPU, "f32" dots run at bf16 MXU precision by default (the fast
    # path the benchmarks use).  Parity/monotonicity tests need true-f32
    # distances — the standard JAX knob, documented in README
    # troubleshooting, makes every f32 dot exact at ~3x matmul cost.
    jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from kmeans_tpu.parallel.mesh import make_mesh  # noqa: E402

# The Pallas kernels COMPILE under jax_enable_x64 since r3 (the i64
# index-map blocker is fixed, pallas_kernels._specs) — but these modules
# compare against oracles that PROMOTE to float64 under the x64 flag on
# hardware, while the kernel is an f32 engine by design, so the
# comparisons are only meaningful with x64 off.  On hardware,
# tests/test_pallas_tpu.py covers the Mosaic compile path (including one
# live-x64 compile+run test).
pallas_x64_skip = pytest.mark.skipif(
    jax.default_backend() != "cpu" and jax.config.jax_enable_x64,
    reason="f32 kernel vs f64-promoted oracle is not a parity comparison")


def _version_tuple(v: str):
    """Leading numeric components of a version string ('0.4.37' ->
    (0, 4, 37); dev/rc suffixes are truncated — '0.5rc1' parses as
    (0, 5), never (0, 51) — the right rounding for a `< (0, 5)`
    boundary check)."""
    import re
    parts = []
    for piece in v.split(".")[:3]:
        m = re.match(r"\d+", piece)
        if not m:
            break
        parts.append(int(m.group()))
    return tuple(parts)


import jaxlib  # noqa: E402

# jaxlib < 0.5 CPU backend raises
# "INVALID_ARGUMENT: Multiprocess computations aren't implemented on the
# CPU backend" from any cross-process collective (observed from
# multihost_utils.process_allgather on this container's jaxlib 0.4.36;
# the CPU collectives landed in the 0.5 runtime).  Gates ONLY the real
# spawned-process tests — the in-process virtual-device mesh coverage
# runs everywhere.
jaxlib_cpu_multiprocess_skip = pytest.mark.skipif(
    jax.default_backend() == "cpu"
    and _version_tuple(jaxlib.__version__) < (0, 5),
    reason="jaxlib {} CPU backend: multiprocess computations "
           "unimplemented (\"Multiprocess computations aren't "
           "implemented on the CPU backend\"; CPU cross-process "
           "collectives landed in jaxlib 0.5) — real multi-process "
           "parity runs on hardware or jaxlib >= 0.5".format(
               jaxlib.__version__))

# jax < 0.5 draws DIFFERENT threefry streams for some keyed sampling
# paths than the >= 0.5 releases these exact-parity pins were recorded
# on (BASELINE.md "Tier-1 environment gates"): the device/host refill
# parity under TP meshes and the minibatch near-convergence basin both
# depend on the exact sampled rows, not on correctness of either
# engine.  Affected tests skip on old jax with this shared condition.
old_jax_rng_streams = _version_tuple(jax.__version__) < (0, 5)
old_jax_rng_skip = pytest.mark.skipif(
    old_jax_rng_streams,
    reason="jax {} (< 0.5) keyed-sampling RNG streams differ from the "
           ">= 0.5 streams this exact-trajectory pin was recorded on — "
           "the comparison is stream-identity, not engine correctness"
           .format(jax.__version__))


@pytest.fixture(scope="session")
def mesh1():
    """Single-device mesh — the un-parallel baseline."""
    return make_mesh(data=1, model=1, devices=jax.devices()[:1])


@pytest.fixture(scope="session")
def mesh8():
    """8-way data-parallel mesh (the reference's 4-partition sim, doubled).

    On real hardware with fewer chips (KMEANS_TPU_TEST_PLATFORM=axon on a
    single tunneled chip), downscales to all available devices — sharding
    code is device-count-agnostic; CI covers the multi-shard paths."""
    return make_mesh(data=min(8, len(jax.devices())), model=1)


@pytest.fixture(scope="session")
def mesh4x2():
    """Data x model mesh: 4-way DP, 2-way centroid (TP) sharding."""
    n = len(jax.devices())
    if n >= 8:
        return make_mesh(data=4, model=2)
    if n >= 2:
        return make_mesh(data=n // 2, model=2,
                         devices=jax.devices()[: 2 * (n // 2)])
    pytest.skip("centroid (model-axis) sharding needs >= 2 devices")


@pytest.fixture()
def blobs_small():
    """The reference's T1 fixture: 1000 pts, 3 centers, 2-D, rs=42
    (kmeans_spark.py:366)."""
    from sklearn.datasets import make_blobs
    X, y = make_blobs(n_samples=1000, centers=3, n_features=2,
                      random_state=42)
    return X, y


def sq_dists_f64(X, C):
    """Shared float64 brute-force pairwise squared-distance oracle
    (expanded matmul form, clamped at 0) used by the op/property tests."""
    import numpy as _np
    x64 = _np.asarray(X, dtype=_np.float64)
    c64 = _np.asarray(C, dtype=_np.float64)
    d2 = ((x64 * x64).sum(1)[:, None] + (c64 * c64).sum(1)[None, :]
          - 2.0 * x64 @ c64.T)
    return _np.maximum(d2, 0.0)
