"""ISSUE 15: portable AOT executable cache, shape-bucketed fits, and
compile/ingest overlap.

Pinned contracts:

* ``bucket=0`` and AOT-off are BIT-exact parity oracles (the
  ``prefetch=0`` discipline): the knobs move where padding/compiles
  happen, never arithmetic.
* A second same-bucket fit adds ZERO new compile-cache entries
  (``recompilation_sentinel``) — serving's warm-kernel residency
  discipline applied to training shapes.
* Cross-process AOT round trip: compile+serialize in subprocess A,
  deserialize-and-fit in subprocess B, bit-exact vs an in-process fit,
  for the f64 device-loop class across {1, 2, 4, 8}-way meshes
  including TP.
* A corrupted or version-skewed artifact falls back to trace+compile
  with a counted warning — never a wrong program.
"""

import json
import os
import subprocess
import sys
import textwrap
import warnings
import zipfile
from pathlib import Path

import numpy as np
import pytest

import jax

from kmeans_tpu import (BisectingKMeans, GaussianMixture, KMeans,
                        MiniBatchKMeans, SphericalKMeans)
from kmeans_tpu.obs import trace as obs_trace
from kmeans_tpu.parallel.sharding import (BUCKET_FLOOR, bucket_rows,
                                          to_device)
from kmeans_tpu.utils import aot
from kmeans_tpu.utils.profiling import recompilation_sentinel
import kmeans_tpu.models.kmeans as km_mod
import kmeans_tpu.models.gmm as gmm_mod


@pytest.fixture(autouse=True)
def _aot_isolation():
    """Every test starts and ends with no active store and cold step
    caches touched by AOT wrappers cleared — wrappers must never leak
    into unrelated tests' cache entries."""
    aot.deactivate()
    yield
    if aot.active_store() is not None:
        km_mod._STEP_CACHE.clear()
        gmm_mod._STEP_CACHE.clear()
    aot.deactivate()


def _blobs(n=600, d=6, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    cents = rng.normal(size=(4, d)) * 6
    return (cents[rng.integers(0, 4, n)]
            + rng.normal(size=(n, d))).astype(dtype)


# ---------------------------------------------------------------------------
# Bucket ladder
# ---------------------------------------------------------------------------

def test_bucket_rows_ladder():
    assert bucket_rows(1) == BUCKET_FLOOR
    assert bucket_rows(BUCKET_FLOOR) == BUCKET_FLOOR
    assert bucket_rows(BUCKET_FLOOR + 1) == int(BUCKET_FLOOR * 1.25)
    # Boundaries are fixed points; values just past a boundary land on
    # the next rung; waste is bounded by the 1.25x rung ratio.
    for n in (257, 900, 1020, 1024, 1025, 123457, 10**6):
        b = bucket_rows(n)
        assert b >= n
        assert b / n <= 1.25 + 1e-9
        assert bucket_rows(b) == b
    # Monotone.
    vals = [bucket_rows(n) for n in range(1, 5000, 7)]
    assert vals == sorted(vals)


def test_bucket_param_validation():
    with pytest.raises(ValueError, match="bucket"):
        KMeans(k=2, bucket="sometimes")
    with pytest.raises(ValueError, match="bucket"):
        KMeans(k=2, bucket=-1)
    with pytest.raises(ValueError, match="bucket"):
        GaussianMixture(n_components=2, bucket="sometimes")
    with pytest.raises(ValueError, match="overlap"):
        KMeans(k=2, overlap=2)


def test_bucket_pads_with_inert_rows():
    X = _blobs(n=600)
    km = KMeans(k=4, bucket="auto", verbose=False)
    ds = km.cache(X)
    assert ds.n == 600                       # real rows untouched
    assert ds.points.shape[0] >= bucket_rows(600)
    w = np.asarray(ds.weights)
    assert w[:600].sum() == 600 and w[600:].sum() == 0.0


# ---------------------------------------------------------------------------
# bucket=0 parity oracle — all five families
# ---------------------------------------------------------------------------

FAMILIES = [
    ("kmeans", lambda **kw: KMeans(k=4, max_iter=8, seed=5,
                                   verbose=False, **kw)),
    ("minibatch", lambda **kw: MiniBatchKMeans(k=4, max_iter=6, seed=5,
                                               batch_size=128,
                                               verbose=False, **kw)),
    ("bisecting", lambda **kw: BisectingKMeans(k=4, max_iter=6, seed=5,
                                               verbose=False, **kw)),
    ("spherical", lambda **kw: SphericalKMeans(k=4, max_iter=8, seed=5,
                                               verbose=False, **kw)),
    ("gmm", lambda **kw: GaussianMixture(n_components=3, max_iter=6,
                                         seed=5, verbose=False, **kw)),
]


def _table(model):
    return np.asarray(model.centroids if hasattr(model, "centroids")
                      and model.centroids is not None else model.means_)


@pytest.mark.parametrize("name,build", FAMILIES,
                         ids=[f[0] for f in FAMILIES])
def test_bucket0_is_bit_exact_oracle(name, build):
    X = _blobs(n=700, d=5)
    base = build().fit(X)
    oracle = build(bucket=0).fit(X)
    assert np.array_equal(_table(base), _table(oracle))


@pytest.mark.parametrize("name,build", FAMILIES,
                         ids=[f[0] for f in FAMILIES])
def test_bucket_auto_same_semantics(name, build):
    """'auto' changes only the fp summation fold (extra all-zero
    chunks), never semantics: same trajectory to numerical tolerance,
    attributes at real shapes."""
    X = _blobs(n=700, d=5)
    base = build().fit(X)
    auto = build(bucket="auto").fit(X)
    assert _table(auto).shape == _table(base).shape
    assert np.allclose(_table(base), _table(auto), atol=1e-4)
    if hasattr(auto, "labels_") and auto.labels_ is not None:
        assert np.asarray(auto.labels_).shape[0] == 700


def test_same_bucket_repeat_fit_zero_new_entries():
    """The warm-fleet pin: two different row counts in one bucket run
    the SAME compiled programs — zero cache growth, zero compile
    spans."""
    build = lambda: KMeans(k=4, max_iter=5, seed=5, verbose=False,
                           bucket="auto", host_loop=False,
                           empty_cluster="keep")
    assert bucket_rows(900) == bucket_rows(1000)
    build().fit(_blobs(n=900))
    with obs_trace.tracing() as tr, recompilation_sentinel():
        build().fit(_blobs(n=1000, seed=9))
    spans = [r for r in tr.records()
             if r.get("kind") == "span" and r["name"] == "compile"]
    assert spans == []


def test_explicit_int_bucket_rounds_up():
    km = KMeans(k=4, bucket=500, verbose=False)
    assert km._bucket_target(601) == 1000
    assert km._bucket_target(1000) == 1000


def test_bucket_roundtrips_through_params_and_checkpoint(tmp_path):
    km = KMeans(k=4, max_iter=4, seed=0, bucket="auto", overlap=0,
                verbose=False).fit(_blobs())
    assert km.get_params()["bucket"] == "auto"
    km.save(tmp_path / "m.npz")
    loaded = KMeans.load(tmp_path / "m.npz")
    assert loaded.bucket == "auto" and loaded.overlap == 0
    g = GaussianMixture(n_components=2, max_iter=3, seed=0,
                        bucket=512, verbose=False).fit(_blobs())
    g.save(tmp_path / "g.npz")
    assert GaussianMixture.load(tmp_path / "g.npz").bucket == 512


# ---------------------------------------------------------------------------
# Compile/ingest overlap
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("host_loop", [True, False])
def test_overlap_bit_exact_parity(host_loop):
    X = _blobs(n=800, d=6)
    kw = dict(k=4, max_iter=8, seed=2, verbose=False,
              host_loop=host_loop, empty_cluster="keep")
    serial = KMeans(overlap=0, **kw).fit(X)
    lapped = KMeans(overlap=1, **kw).fit(X)
    assert np.array_equal(serial.centroids, lapped.centroids)
    assert np.array_equal(serial.labels_, lapped.labels_)
    assert serial.iterations_run == lapped.iterations_run


def test_overlap_stages_on_producer_thread():
    """The overlapped prelude's 'stage' spans come from the prefetch
    producer's tid — the compile/ingest concurrency is visible on the
    timeline."""
    import threading
    X = _blobs(n=800)
    with obs_trace.tracing() as tr:
        KMeans(k=4, max_iter=3, seed=2, verbose=False, overlap=1,
               host_loop=False, empty_cluster="keep").fit(X)
    main_tid = threading.get_ident()
    stage = [r for r in tr.records() if r.get("kind") == "span"
             and r["name"] == "stage"]
    assert stage and any(s["tid"] != main_tid for s in stage)


def test_overlap_skips_sharded_dataset_input():
    km = KMeans(k=4, max_iter=4, seed=2, verbose=False, overlap=1)
    ds = km.cache(_blobs())
    ref = KMeans(k=4, max_iter=4, seed=2, verbose=False,
                 overlap=0).fit(_blobs())
    assert np.array_equal(km.fit(ds).centroids, ref.centroids)


# ---------------------------------------------------------------------------
# AOT executable store — in-process
# ---------------------------------------------------------------------------

def test_aot_supported_on_cpu():
    ok, reason = aot.aot_supported()
    assert ok, reason


def test_artifact_key_spans_versions_and_backend():
    fields = aot.artifact_key("kmeans._STEP_CACHE", ("k", 4), ((4,),))
    import jaxlib
    assert fields["jax"] == jax.__version__
    assert fields["jaxlib"] == jaxlib.__version__
    assert fields["platform"] == jax.default_backend()
    assert fields["format"] == aot.FORMAT
    for f in ("cache", "key", "sig", "device_kind", "device_count",
              "process_count"):
        assert f in fields
    json.dumps(fields)        # must be JSON-stable (digest input)


def test_aot_roundtrip_in_process(tmp_path):
    """Cold fit builds+serializes; after an in-memory cache wipe (a
    simulated fresh process) the same fit LOADS — compile spans flip
    from via='aot-build' to via='aot-load' — and the trajectory is
    bit-exact, also vs AOT-off."""
    X = _blobs(n=900, d=8, dtype=np.float64)
    kw = dict(k=4, max_iter=8, seed=7, verbose=False, host_loop=False,
              empty_cluster="keep", dtype=np.float64)
    store = aot.configure(tmp_path / "store")
    km_mod._STEP_CACHE.clear()
    with obs_trace.tracing() as tr1:
        cold = KMeans(**kw).fit(X)
    vias1 = [r["attrs"]["via"] for r in tr1.records()
             if r.get("kind") == "span" and r["name"] == "compile"
             and r.get("attrs", {}).get("via")]
    assert "aot-build" in vias1 and store.stats()["saved"] > 0

    km_mod._STEP_CACHE.clear()
    with obs_trace.tracing() as tr2:
        warm = KMeans(**kw).fit(X)
    vias2 = [r["attrs"]["via"] for r in tr2.records()
             if r.get("kind") == "span" and r["name"] == "compile"
             and r.get("attrs", {}).get("via")]
    assert vias2 and set(vias2) == {"aot-load"}
    assert store.stats()["loaded"] >= len(vias2)
    assert np.array_equal(cold.centroids, warm.centroids)

    aot.deactivate()
    km_mod._STEP_CACHE.clear()
    off = KMeans(**kw).fit(X)
    assert np.array_equal(cold.centroids, off.centroids)


def test_aot_corrupted_artifact_counted_fallback(tmp_path):
    X = _blobs(n=700, d=6)
    kw = dict(k=4, max_iter=6, seed=3, verbose=False, host_loop=False,
              empty_cluster="keep")
    store = aot.configure(tmp_path / "store")
    km_mod._STEP_CACHE.clear()
    ref = KMeans(**kw).fit(X)
    for f in Path(store.root).glob("*.aotx"):
        f.write_bytes(b"not a zip")
    km_mod._STEP_CACHE.clear()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        again = KMeans(**kw).fit(X)
    assert np.array_equal(ref.centroids, again.centroids)
    assert store.stats()["fallbacks"] >= 1
    assert any("unusable" in str(x.message) for x in w)


def test_aot_version_skewed_artifact_counted_fallback(tmp_path):
    """An artifact whose stored meta names another jax build must load
    as a MISMATCH (counted fallback), never as this build's program —
    tampered in place so the content-hash lookup still finds it."""
    X = _blobs(n=700, d=6)
    kw = dict(k=4, max_iter=6, seed=3, verbose=False, host_loop=False,
              empty_cluster="keep")
    store = aot.configure(tmp_path / "store")
    km_mod._STEP_CACHE.clear()
    ref = KMeans(**kw).fit(X)
    for f in Path(store.root).glob("*.aotx"):
        with zipfile.ZipFile(f) as z:
            meta = json.loads(z.read("meta.json"))
            trees, exe = z.read("trees.pkl"), z.read("exe.bin")
        meta["jax"] = "999.0.0"
        with zipfile.ZipFile(f, "w") as z:
            z.writestr("meta.json", json.dumps(meta, sort_keys=True))
            z.writestr("trees.pkl", trees)
            z.writestr("exe.bin", exe)
    km_mod._STEP_CACHE.clear()
    before = store.stats()["fallbacks"]
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        again = KMeans(**kw).fit(X)
    assert np.array_equal(ref.centroids, again.centroids)
    assert store.stats()["fallbacks"] > before
    assert any("mismatch" in str(x.message) for x in w)


def test_aot_off_by_default_zero_hook_cost():
    """Without a store or env knob, cache entries are plain jitted
    functions — no wrapper, no store, nothing on disk (the tier-1
    default)."""
    assert aot.active_store() is None
    km_mod._STEP_CACHE.clear()
    KMeans(k=3, max_iter=3, seed=0, verbose=False).fit(_blobs())
    for key in km_mod._STEP_CACHE.keys():
        entry = km_mod._STEP_CACHE[key]
        for member in (entry if isinstance(entry, tuple) else (entry,)):
            assert not isinstance(member, aot._AOTProgram)


def test_describe_dir_and_ship_with_checkpoint(tmp_path):
    """checkpoint_every + an active store mirrors artifacts into
    <ckpt>.aot; resume from that checkpoint registers the dir as a
    read path; describe_dir summarizes it."""
    X = _blobs(n=700, d=6)
    store = aot.configure(tmp_path / "store")
    km_mod._STEP_CACHE.clear()
    ckpt = tmp_path / "model.npz"
    KMeans(k=4, max_iter=6, seed=3, verbose=False, host_loop=False,
           empty_cluster="keep").fit(X, checkpoint_every=3,
                                     checkpoint_path=ckpt)
    shipped = aot.aot_dir_for(ckpt)
    assert shipped.is_dir() and list(shipped.glob("*.aotx"))
    desc = aot.describe_dir(shipped)
    assert desc["exists"] and desc["artifacts"] >= 1
    assert desc["bytes"] > 0 and desc["unreadable"] == 0
    assert any(p["cache"] == "kmeans._STEP_CACHE"
               for p in desc["programs"])
    # Fresh store elsewhere + resume: the shipped dir joins the read
    # path and the resumed fit LOADS instead of building.
    store2 = aot.configure(tmp_path / "other")
    km_mod._STEP_CACHE.clear()
    km2 = KMeans(k=4, max_iter=10, seed=3, verbose=False,
                 host_loop=False, empty_cluster="keep")
    km2.fit(X, resume=ckpt)
    assert str(shipped) in [str(d) for d in store2.read_dirs]
    assert store2.stats()["loaded"] >= 1


def test_env_knob_activates_store(tmp_path, monkeypatch):
    monkeypatch.setenv("KMEANS_TPU_AOT_CACHE", str(tmp_path / "env"))
    # Reset the lazy env check (configure()/deactivate() marks it
    # checked; tests must re-arm it).
    aot._ENV_CHECKED = False
    aot._STORE = None
    try:
        store = aot.active_store()
        assert store is not None
        assert str(store.root) == str(tmp_path / "env")
    finally:
        aot.deactivate()


def test_enable_compilation_cache_env_knob(tmp_path, monkeypatch):
    monkeypatch.setenv("KMEANS_TPU_COMPILE_CACHE", str(tmp_path / "cc"))
    aot._COMPILE_CACHE_SET = False
    assert aot.enable_compilation_cache() == str(tmp_path / "cc")
    monkeypatch.setenv("KMEANS_TPU_COMPILE_CACHE", "")
    aot._COMPILE_CACHE_SET = False
    assert aot.enable_compilation_cache() is None


# ---------------------------------------------------------------------------
# Cross-process round trip — the portable-artifact pin
# ---------------------------------------------------------------------------

_CHILD = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import numpy as np
    import jax
    jax.config.update("jax_enable_x64", True)
    from kmeans_tpu import KMeans
    from kmeans_tpu.parallel.mesh import make_mesh
    from kmeans_tpu.utils import aot

    cfg = json.loads(os.environ["KMEANS_TPU_AOT_TEST_CFG"])
    store = aot.configure(cfg["store"])
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1024, 8)).astype(np.float64)
    out = {}
    for data, model in cfg["meshes"]:
        mesh = make_mesh(data=data, model=model,
                         devices=jax.devices()[: data * model])
        km = KMeans(k=4, max_iter=6, seed=11, verbose=False,
                    host_loop=False, empty_cluster="keep",
                    dtype=np.float64, mesh=mesh)
        km.fit(X)
        out[f"{data}x{model}"] = np.asarray(
            km.centroids, np.float64).tobytes().hex()
    stats = store.stats()
    print("AOT_TEST " + json.dumps(
        {"tables": out, "built": stats["built"],
         "loaded": stats["loaded"], "saved": stats["saved"],
         "fallbacks": stats["fallbacks"]}))
""")

#: {1, 2, 4, 8}-way meshes including a TP (model-axis) layout.
_MESHES = [[1, 1], [2, 1], [4, 1], [4, 2]]


def _spawn_child(store_dir):
    env = dict(os.environ)
    env["KMEANS_TPU_AOT_TEST_CFG"] = json.dumps(
        {"store": str(store_dir), "meshes": _MESHES})
    env.pop("KMEANS_TPU_AOT_CACHE", None)
    proc = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                          capture_output=True, text=True, timeout=600)
    for line in proc.stdout.splitlines():
        if line.startswith("AOT_TEST "):
            return json.loads(line[len("AOT_TEST "):])
    raise AssertionError(
        f"child produced no payload (exit {proc.returncode}):\n"
        f"{proc.stderr[-3000:]}")


def test_cross_process_aot_roundtrip_bit_exact(tmp_path):
    """Process A compiles + serializes for every mesh layout; process B
    deserializes-and-fits from the shared store (zero builds) and
    reproduces A's f64 device-loop trajectories bit-exactly; the parent
    process's in-process fit is the oracle both must match."""
    store_dir = tmp_path / "shared_store"
    a = _spawn_child(store_dir)
    assert a["built"] > 0 and a["saved"] == a["built"]
    assert a["fallbacks"] == 0

    b = _spawn_child(store_dir)
    assert b["built"] == 0, "process B recompiled despite the store"
    assert b["loaded"] >= len(_MESHES)
    assert b["tables"] == a["tables"], \
        "cross-process AOT fit diverged from the compiling process"

    # In-process oracle (AOT off) at the 4x2 TP layout.
    from kmeans_tpu.parallel.mesh import make_mesh
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1024, 8)).astype(np.float64)
    km = KMeans(k=4, max_iter=6, seed=11, verbose=False,
                host_loop=False, empty_cluster="keep",
                dtype=np.float64, mesh=make_mesh(data=4, model=2))
    km.fit(X)
    oracle = np.asarray(km.centroids, np.float64).tobytes().hex()
    assert a["tables"]["4x2"] == oracle
