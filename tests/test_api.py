"""API-surface tests, including the gaps the reference left untested
(SURVEY.md §4: predict, validation error paths, n<k, NaN rejection).
"""

import numpy as np
import pytest

from conftest import old_jax_rng_skip

from kmeans_tpu import KMeans
from kmeans_tpu.models import MiniBatchKMeans, kmeanspp_init


@pytest.fixture()
def small_X():
    rng = np.random.default_rng(3)
    return rng.normal(size=(120, 4))


# --- constructor validation (kmeans_spark.py:49-56) -------------------------

@pytest.mark.parametrize("kwargs", [dict(k=0), dict(k=-2),
                                    dict(max_iter=0), dict(tolerance=0.0),
                                    dict(tolerance=-1e-4)])
def test_invalid_params_raise(kwargs):
    with pytest.raises(ValueError, match="must be positive"):
        KMeans(**kwargs)


def test_invalid_empty_policy_raises():
    with pytest.raises(ValueError, match="empty_cluster"):
        KMeans(empty_cluster="nope")


# --- init edge cases (kmeans_spark.py:58-82) --------------------------------

def test_fewer_points_than_k_raises(mesh8):
    X = np.zeros((2, 3))
    with pytest.raises(ValueError, match="Not enough data points"):
        KMeans(k=5, mesh=mesh8, verbose=False).fit(X)


def test_nan_data_raises(mesh8, small_X):
    X = small_X.copy()
    X[7, 1] = np.nan
    # The reference rejects NaN when it lands in the init sample
    # (kmeans_spark.py:79-80) or, failing that, via the per-iteration finite
    # guard (:289-290).  We accept either message.
    with pytest.raises(ValueError, match="NaN or Inf"):
        KMeans(k=100, mesh=mesh8, verbose=False).fit(X)


def test_explicit_init_shape_checked(small_X, mesh8):
    with pytest.raises(ValueError, match="explicit init"):
        KMeans(k=3, init=np.zeros((2, 4)), mesh=mesh8,
               verbose=False).fit(small_X)


def test_unknown_init_raises(small_X, mesh8):
    with pytest.raises(ValueError, match="unknown init"):
        KMeans(k=3, init="zzz", mesh=mesh8, verbose=False).fit(small_X)


def test_kmeanspp_init_runs(small_X, mesh8):
    km = KMeans(k=4, init="k-means++", mesh=mesh8, verbose=False)
    km.fit(small_X)
    assert km.centroids.shape == (4, 4)
    c = kmeanspp_init(small_X, 4, seed=0)
    assert len(np.unique(c, axis=0)) == 4


# --- predict / transform / score (kmeans_spark.py:321-352) ------------------

def test_predict_before_fit_raises():
    with pytest.raises(ValueError,
                       match="Model must be fitted before prediction"):
        KMeans(k=3).predict(np.zeros((4, 2)))


def test_predict_labels_in_range(small_X, mesh8):
    km = KMeans(k=5, mesh=mesh8, verbose=False).fit(small_X)
    labels = km.predict(small_X)
    assert labels.shape == (len(small_X),)
    assert labels.min() >= 0 and labels.max() < 5


def test_fit_predict_and_sklearn_aliases(small_X, mesh8):
    km = KMeans(k=4, compute_sse=True, mesh=mesh8, verbose=False)
    labels = km.fit_predict(small_X)
    assert labels.shape == (len(small_X),)
    np.testing.assert_array_equal(km.cluster_centers_, km.centroids)
    assert km.n_iter_ == km.iterations_run >= 1
    assert km.inertia_ == km.sse_history[-1]


def test_transform_shape_and_score(small_X, mesh8):
    km = KMeans(k=4, mesh=mesh8, verbose=False).fit(small_X)
    d = km.transform(small_X)
    assert d.shape == (len(small_X), 4)
    # score = negative SSE under current centroids
    assert km.score(small_X) == pytest.approx(
        -np.sum(np.min(d, axis=1) ** 2), rel=1e-5)


def test_transform_streams_in_blocks(small_X, mesh8):
    """r2 VERDICT weak #5: transform must stream (block, k) tiles through
    the mesh, not materialize (n, k) on one device.  Tiny blocks force
    many round trips; the result must be identical to one-shot."""
    km = KMeans(k=4, mesh=mesh8, verbose=False).fit(small_X)
    one = km.transform(small_X)
    blocked = km.transform(small_X, block_rows=96)
    np.testing.assert_allclose(blocked, one, atol=1e-6)
    # transform_stream yields the same tiles block-by-block.
    tiles = list(km.transform_stream(
        lambda: iter([small_X[:150], small_X[150:]]), block_rows=64))
    np.testing.assert_allclose(np.concatenate(tiles), one, atol=1e-6)


def test_transform_model_sharded(small_X, mesh4x2):
    """The (n, k) tile shards over BOTH axes: centroid-sharded transform
    agrees with the replicated-table result (incl. k=5 padding on the
    2-shard model axis)."""
    km_ref = KMeans(k=5, seed=3, verbose=False).fit(small_X)
    km_tp = KMeans(k=5, seed=3, mesh=mesh4x2, verbose=False)
    km_tp.fit(small_X)
    km_tp.centroids = km_ref.centroids         # same table, TP layout
    np.testing.assert_allclose(km_tp.transform(small_X),
                               km_ref.transform(small_X), atol=1e-5)


def test_non_2d_input_raises(mesh8):
    with pytest.raises(ValueError, match="2-D"):
        KMeans(k=2, mesh=mesh8, verbose=False).fit(np.zeros(8))


# --- minibatch --------------------------------------------------------------

# atol=0.3 near-convergence was tuned for the batch sequence the
# >= 0.5 jax stream samples; jax < 0.5 samples different batches and
# lands ~0.6 off on one coordinate of this 3-blob basin (engine
# correctness is covered stream-independently by
# test_minibatch_device.py's host/device parity).  BASELINE.md
# "Tier-1 environment gates".
@old_jax_rng_skip
def test_minibatch_converges_near_fullbatch(mesh8):
    from sklearn.datasets import make_blobs
    X, _ = make_blobs(n_samples=4000, centers=3, n_features=2,
                      cluster_std=0.4, random_state=7)
    full = KMeans(k=3, seed=0, mesh=mesh8, verbose=False).fit(X)
    mb = MiniBatchKMeans(k=3, seed=0, max_iter=60, batch_size=512,
                         mesh=mesh8, verbose=False).fit(X)
    a = np.array(sorted(full.centroids.tolist()))
    b = np.array(sorted(mb.centroids.tolist()))
    np.testing.assert_allclose(a, b, atol=0.3)


def test_minibatch_invalid_batch_size():
    with pytest.raises(ValueError, match="batch_size"):
        MiniBatchKMeans(batch_size=0)


def test_set_params_revalidates_and_preserves_fit():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(100, 3)).astype(np.float32)
    km = KMeans(k=3, verbose=False).fit(X)
    before = km.centroids.copy()
    with pytest.raises(ValueError, match="empty_cluster"):
        km.set_params(empty_cluster="typo")
    assert km.empty_cluster == "resample"          # unchanged on failure
    np.testing.assert_array_equal(km.centroids, before)
    with pytest.raises(ValueError, match="n_init"):
        km.set_params(n_init=0)
    km.set_params(dtype="float64")
    assert km.dtype == np.dtype(np.float64)        # normalized like __init__
    np.testing.assert_array_equal(km.centroids, before)   # fit preserved


def test_labels_matches_predict_and_releases_dataset():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(200, 4)).astype(np.float32)
    km = KMeans(k=4, seed=1, verbose=False).fit(X)
    np.testing.assert_array_equal(km.labels_, km.predict(X))
    assert km._fit_ds is None            # device reference released
    np.testing.assert_array_equal(km.labels_, km.predict(X))  # cached


def test_labels_before_fit_raises():
    with pytest.raises(AttributeError, match="after fit"):
        KMeans(k=2, verbose=False).labels_


def test_labels_minibatch():
    rng = np.random.default_rng(4)
    X = rng.normal(size=(500, 8)).astype(np.float32)
    mb = MiniBatchKMeans(k=3, seed=0, batch_size=128, max_iter=5,
                         verbose=False).fit(X)
    np.testing.assert_array_equal(mb.labels_, mb.predict(X))


def test_labels_refreshed_by_refit():
    rng = np.random.default_rng(5)
    X1 = rng.normal(size=(150, 3)).astype(np.float32)
    X2 = rng.normal(size=(90, 3)).astype(np.float32) + 10.0
    km = KMeans(k=3, seed=2, verbose=False).fit(X1)
    _ = km.labels_
    km.fit(X2)
    assert km.labels_.shape == (90,)
    np.testing.assert_array_equal(km.labels_, km.predict(X2))


def test_fitted_model_pickles_and_deepcopies():
    import copy
    import pickle
    rng = np.random.default_rng(6)
    X = rng.normal(size=(120, 4)).astype(np.float32)
    km = KMeans(k=3, seed=0, verbose=False).fit(X)
    km2 = pickle.loads(pickle.dumps(km))
    np.testing.assert_array_equal(km2.labels_, km.labels_)
    np.testing.assert_array_equal(km2.predict(X), km.predict(X))
    km3 = copy.deepcopy(km)
    np.testing.assert_array_equal(km3.centroids, km.centroids)


def test_deepcopy_preserves_mesh_and_fit():
    import copy
    import jax
    from kmeans_tpu import make_mesh
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    rng = np.random.default_rng(7)
    X = rng.normal(size=(160, 4)).astype(np.float32)
    mesh = make_mesh(data=4, model=2)
    km = KMeans(k=3, seed=0, verbose=False, mesh=mesh).fit(X)
    km2 = copy.deepcopy(km)
    assert km2.mesh is mesh                       # user mesh survives
    np.testing.assert_array_equal(km2.predict(X), km.predict(X))


def test_fit_predict_reuses_eager_labels():
    rng = np.random.default_rng(8)
    X = rng.normal(size=(140, 3)).astype(np.float32)
    km = KMeans(k=3, seed=1, verbose=False)
    labels = km.fit_predict(X)
    assert labels is km._labels_cache             # no second pass
    np.testing.assert_array_equal(labels, km.predict(X))


def test_partial_fit_streaming():
    rng = np.random.default_rng(9)
    centers = np.array([[0.0, 0.0], [8.0, 8.0], [-8.0, 8.0]], np.float32)
    mb = MiniBatchKMeans(k=3, seed=0, verbose=False)
    for i in range(20):
        batch = (centers[rng.integers(0, 3, 256)]
                 + rng.normal(size=(256, 2)).astype(np.float32))
        mb.partial_fit(batch)
    assert mb.iterations_run == 20
    assert np.all(np.isfinite(mb.centroids))
    # Each true center has a fitted centroid nearby.
    d = np.linalg.norm(mb.centroids[None] - centers[:, None], axis=2)
    assert d.min(axis=1).max() < 1.0
    assert mb.labels_.shape == (256,)        # labels of the LAST batch
    np.testing.assert_array_equal(mb.labels_, mb.predict(batch))


def test_partial_fit_first_call_initializes():
    rng = np.random.default_rng(10)
    X = rng.normal(size=(300, 5)).astype(np.float32)
    mb = MiniBatchKMeans(k=4, seed=1, verbose=False).partial_fit(X)
    assert mb.centroids.shape == (4, 5)
    assert mb.iterations_run == 1
    with pytest.raises(ValueError, match="2-D"):
        mb.partial_fit(X[0])


def test_partial_fit_feature_mismatch_raises():
    mb = MiniBatchKMeans(k=2, seed=0, verbose=False)
    mb.partial_fit(np.zeros((50, 4), np.float32) +
                   np.arange(50, dtype=np.float32)[:, None])
    with pytest.raises(ValueError, match="4"):
        mb.partial_fit(np.zeros((50, 6), np.float32))


def test_pickle_after_partial_fit_keeps_labels():
    import pickle
    rng = np.random.default_rng(11)
    batch = rng.normal(size=(200, 3)).astype(np.float32)
    mb = MiniBatchKMeans(k=3, seed=0, verbose=False).partial_fit(batch)
    mb2 = pickle.loads(pickle.dumps(mb))
    np.testing.assert_array_equal(mb2.labels_, mb.predict(batch))


def test_float64_without_x64_warns_and_works():
    """Regression: requesting dtype=float64 without jax_enable_x64 used to
    leave model.dtype=float64 while the device silently stored float32 —
    the eager labels_ pass then crashed on the dtype re-check.  Now the
    dtype canonicalizes up front (with a warning) and fit/labels_ work."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).parent.parent
    env = dict(os.environ)
    env["PYTHONPATH"] = ":".join(
        p for p in [str(repo), env.get("PYTHONPATH")] if p)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_ENABLE_X64", None)
    code = (
        # Config-update BEFORE first backend use: the env var alone does
        # not stop the axon PJRT plugin from initializing, and with the
        # tunnel down that init blocks forever (r5, memory
        # axon-tunnel-quirks) — the same pattern tests/conftest.py uses.
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import warnings, numpy as np\n"
        "from kmeans_tpu import KMeans\n"
        "X = np.random.default_rng(0).normal(size=(200, 3))\n"
        "with warnings.catch_warnings(record=True) as w:\n"
        "    warnings.simplefilter('always')\n"
        "    km = KMeans(k=3, seed=0, verbose=False, dtype=np.float64)\n"
        "assert any('x64' in str(x.message) for x in w), w\n"
        "assert km.dtype == np.float32, km.dtype\n"
        "km.fit(X)\n"
        "assert km.labels_.shape == (200,)\n"
        "print('OK')\n")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK" in proc.stdout


def test_sklearn_clone_and_pipeline_interop(small_X):
    """get_params/set_params/transform satisfy the sklearn estimator and
    transformer protocols: clone() produces an unfitted twin, and KMeans
    works as a Pipeline feature-extraction stage."""
    from sklearn.base import clone
    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import Pipeline

    km = KMeans(k=4, seed=0, verbose=False)
    twin = clone(km)
    assert twin is not km and twin.get_params() == km.get_params()
    assert twin.centroids is None

    y = (small_X[:, 0] > 0).astype(int)
    pipe = Pipeline([("km", KMeans(k=4, seed=0, verbose=False)),
                     ("clf", LogisticRegression(max_iter=200))])
    pipe.fit(small_X.astype(np.float32), y)
    assert pipe.predict(small_X.astype(np.float32)).shape == (len(small_X),)
    names = pipe.named_steps["km"].get_feature_names_out()
    assert list(names) == [f"kmeans{i}" for i in range(4)]


def test_compute_labels_false_skips_labels_pass(small_X, mesh8):
    """ADVICE r1: public opt-out of the eager labels_ pass (sklearn's
    MiniBatchKMeans compute_labels analogue) for centroid-only workloads."""
    km = KMeans(k=3, seed=0, verbose=False, mesh=mesh8,
                compute_labels=False).fit(small_X)
    assert km._fit_ds is None                 # dataset released, no pass run
    with pytest.raises(AttributeError, match="compute_labels=False"):
        _ = km.labels_
    assert km.predict(small_X).shape == (len(small_X),)
    assert km.get_params()["compute_labels"] is False
    # Round-trips through set_params back to eager labels.
    km.set_params(compute_labels=True).fit(small_X)
    assert km.labels_.shape == (len(small_X),)


def test_compute_labels_false_partial_fit(small_X):
    """compute_labels=False holds for partial_fit too (sklearn's
    MiniBatchKMeans leaves labels_ unset after partial_fit)."""
    mb = MiniBatchKMeans(k=3, seed=0, verbose=False, batch_size=32,
                         compute_labels=False)
    mb.partial_fit(small_X[:64])
    assert mb._fit_ds is None
    with pytest.raises(AttributeError, match="compute_labels=False"):
        _ = mb.labels_
    mb2 = MiniBatchKMeans(k=3, seed=0, verbose=False, batch_size=32,
                          compute_labels=False, max_iter=3).fit(small_X)
    with pytest.raises(AttributeError, match="compute_labels=False"):
        _ = mb2.labels_
