"""Determinism checker (utils.debug) — the SPMD 'race detector' analogue.

The reference's empty-cluster path is deliberately time-seeded and thus
non-reproducible (kmeans_spark.py:195-196, SURVEY.md §4); this framework
replaces it with derived seeds, and these tests prove the determinism
contract holds (and that the checker can DETECT a nondeterministic model).
"""

import numpy as np
import pytest

from kmeans_tpu import KMeans, MiniBatchKMeans
from kmeans_tpu.data.synthetic import make_blobs
from kmeans_tpu.utils.debug import check_determinism


@pytest.fixture()
def X():
    return make_blobs(3000, centers=5, n_features=6, random_state=3,
                      dtype=np.float32)[0]


def test_kmeans_deterministic(X, mesh8):
    report = check_determinism(
        lambda: KMeans(k=5, seed=7, compute_sse=True, verbose=False,
                       mesh=mesh8), X)
    assert report["deterministic"], report


def test_empty_cluster_resample_deterministic(mesh8):
    # Forced empties (3 tight blobs, k=6 — the reference's T4 fixture,
    # kmeans_spark.py:513-524) with the 'resample' policy: deterministic
    # here, UNLIKE the reference's time-seeded resample.
    X = make_blobs(800, centers=3, n_features=2, cluster_std=0.5,
                   random_state=42, dtype=np.float32)[0]
    report = check_determinism(
        lambda: KMeans(k=6, seed=42, empty_cluster="resample",
                       verbose=False, mesh=mesh8), X)
    assert report["deterministic"], report


def test_minibatch_deterministic(X):
    report = check_determinism(
        lambda: MiniBatchKMeans(k=5, seed=3, batch_size=256, max_iter=8,
                                verbose=False), X)
    assert report["deterministic"], report


def test_detects_nondeterminism(X):
    import itertools
    counter = itertools.count()

    def factory():
        # Different seed each run — the checker must catch the divergence.
        return KMeans(k=5, seed=next(counter), verbose=False)

    report = check_determinism(factory, X)
    assert not report["deterministic"]
    assert "diverged" in report["details"]


def test_rejects_bad_args(X):
    with pytest.raises(ValueError, match="runs"):
        check_determinism(lambda: KMeans(k=2, verbose=False), X, runs=1)
    with pytest.raises(ValueError, match="verbose"):
        check_determinism(lambda: KMeans(k=2), X)


def test_sample_weight_unsupported_model_clear_error(X):
    """Every shipped model family accepts sample_weight now
    (MiniBatchKMeans gained it r4), so the pointed guard is exercised
    with a minimal stub whose fit doesn't take the kwarg."""
    class NoWeights:
        verbose = False

        def fit(self, X):
            return self

    with pytest.raises(ValueError, match="sample_weight"):
        check_determinism(lambda: NoWeights(), X,
                          sample_weight=np.ones(X.shape[0], np.float32))


def test_minibatch_sample_weight_deterministic(X, mesh8):
    """r4: weighted MiniBatch fits are reproducible through the checker."""
    w = np.ones(X.shape[0], np.float32)
    w[:100] = 3.0
    report = check_determinism(
        lambda: MiniBatchKMeans(k=3, seed=0, batch_size=128, max_iter=6,
                                verbose=False, mesh=mesh8), X,
        sample_weight=w)
    assert report["deterministic"], report


def test_sample_weight_supported(X, mesh8):
    w = np.ones(X.shape[0], np.float32)
    w[: 100] = 2.0
    report = check_determinism(
        lambda: KMeans(k=5, seed=2, verbose=False, mesh=mesh8), X,
        sample_weight=w)
    assert report["deterministic"], report


def test_determinism_checker_covers_gmm():
    """r4: the reproducibility checker (SURVEY.md §5 race-detection
    analogue) serves the mixture family too."""
    from kmeans_tpu import GaussianMixture
    from kmeans_tpu.data.synthetic import make_blobs
    X, _ = make_blobs(600, centers=3, n_features=4, random_state=0,
                      dtype=np.float32)
    rep = check_determinism(
        lambda: GaussianMixture(n_components=3, seed=0, max_iter=10,
                                covariance_type="full"), X)
    assert rep["deterministic"], rep
