"""Massive-k tier tests (ISSUE 16): k-sharded centroid tables,
two-level assignment, the batched PQ codebook trainer, and the
planner/CLI surfaces that route between them.

The parity discipline mirrors the repo's other route tests: every new
execution path is pinned against the dense oracle it replaces —
bit-exact where the construction guarantees it (k-shard; two-level at
``nprobe >= C``), by explicit error contract where it does not
(two-level candidate sets, PQ/ADC quantization).
"""

import json

import numpy as np
import pytest

from kmeans_tpu import KMeans, ProductQuantizer
from kmeans_tpu.models.pq import default_subspaces


@pytest.fixture(scope="module")
def clusters():
    """Well-separated blobs: 600 x 16, three lattice offsets."""
    rng = np.random.default_rng(5)
    return (rng.normal(size=(600, 16))
            + 8.0 * rng.integers(0, 3, size=(600, 1)))


def _fit_kw(**over):
    kw = dict(k=12, max_iter=15, seed=0, dtype=np.float64,
              tolerance=1e-6, compute_sse=True, verbose=False)
    kw.update(over)
    return kw


def _dense_argmin(Q, table):
    Q = np.asarray(Q, np.float64)
    T = np.asarray(table, np.float64)
    d2 = (np.sum(Q * Q, 1)[:, None] - 2.0 * Q @ T.T
          + np.sum(T * T, 1)[None, :])
    return np.argmin(d2, axis=1).astype(np.int32)


# ---------------------------------------------------------------------------
# k-sharded centroid tables (TP mesh)
# ---------------------------------------------------------------------------

class TestKShard:
    def test_bit_parity_vs_dense_tp_oracle(self, mesh4x2, clusters):
        """k_shard=model_shards is the dense TP fit's bit-exact twin:
        same trajectory, same iteration count, same final table."""
        dense = KMeans(mesh=mesh4x2, k_shard=0, assign="dense",
                       host_loop=True, **_fit_kw()).fit(clusters)
        shard = KMeans(mesh=mesh4x2, k_shard=2, **_fit_kw()).fit(clusters)
        assert np.array_equal(np.asarray(dense.centroids),
                              np.asarray(shard.centroids))
        assert dense.n_iter_ == shard.n_iter_
        assert np.array_equal(np.asarray(dense.predict(clusters)),
                              np.asarray(shard.predict(clusters)))
        assert shard.k_shard_resolved_ == 2
        assert shard.assign_resolved_ == "dense"

    def test_explicit_kshard_requires_tp_mesh(self, mesh8, clusters):
        with pytest.raises(ValueError, match="model-sharded mesh"):
            KMeans(mesh=mesh8, k_shard=2, **_fit_kw()).fit(clusters)

    def test_explicit_kshard_must_match_mesh(self, mesh4x2, clusters):
        with pytest.raises(ValueError, match="does not match"):
            KMeans(mesh=mesh4x2, k_shard=4, **_fit_kw()).fit(clusters)

    def test_kshard_rejects_device_loop(self, mesh4x2, clusters):
        with pytest.raises(ValueError, match="host_loop=False"):
            KMeans(mesh=mesh4x2, k_shard=2, host_loop=False,
                   **_fit_kw()).fit(clusters)

    def test_knob_grammar(self):
        with pytest.raises(ValueError, match="k_shard"):
            KMeans(k=4, k_shard="bogus")
        with pytest.raises(ValueError, match="k_shard"):
            KMeans(k=4, k_shard=-1)
        with pytest.raises(ValueError, match="assign"):
            KMeans(k=4, assign="bogus")
        with pytest.raises(ValueError, match="coarse_cells"):
            KMeans(k=4, coarse_cells=0)
        with pytest.raises(ValueError, match="nprobe"):
            KMeans(k=4, nprobe=0)
        with pytest.raises(ValueError, match="init_cap"):
            KMeans(k=4, init_cap=0)


# ---------------------------------------------------------------------------
# Two-level (coarse-quantizer) assignment
# ---------------------------------------------------------------------------

class TestTwoLevel:
    def test_exact_probe_is_dense_bit_parity(self, mesh8, clusters):
        """nprobe >= C probes every cell — the candidate set is the
        whole table, sorted member lists reproduce dense argmin's
        tie-break, so the fit trajectory is bit-exact."""
        dense = KMeans(mesh=mesh8, k_shard=0, assign="dense",
                       host_loop=True, **_fit_kw()).fit(clusters)
        two = KMeans(mesh=mesh8, assign="two_level", coarse_cells=4,
                     nprobe=4, **_fit_kw()).fit(clusters)
        assert np.array_equal(np.asarray(dense.centroids),
                              np.asarray(two.centroids))
        assert dense.n_iter_ == two.n_iter_
        assert two.assign_resolved_ == "two_level"
        assert two.k_shard_resolved_ == 0

    def test_predict_matches_dense_argmin(self, mesh8, clusters):
        rng = np.random.default_rng(9)
        rows = (rng.normal(size=(80, 16))
                + 8.0 * rng.integers(0, 3, size=(80, 1)))
        two = KMeans(mesh=mesh8, assign="two_level", coarse_cells=4,
                     nprobe=4, **_fit_kw()).fit(clusters)
        assert np.array_equal(np.asarray(two.predict(rows)),
                              _dense_argmin(rows, two.centroids))

    def test_default_probe_quality_contract(self, mesh8, clusters):
        """Default nprobe (an eighth of the cells) is NOT exact — the
        contract is exact SSE over the candidate assignment, with the
        routed fit landing within a few percent of the dense one on
        separated data (docs/ANALYSIS.md)."""
        two = KMeans(mesh=mesh8, assign="two_level",
                     **_fit_kw(k=24, max_iter=20)).fit(clusters)
        dense = KMeans(mesh=mesh8, host_loop=True,
                       **_fit_kw(k=24, max_iter=20)).fit(clusters)
        ratio = two.inertia_ / dense.inertia_
        assert 0.5 < ratio < 1.1
        C, npb = two._two_level_params()
        assert npb < C  # the default really exercises the routed path

    def test_two_level_requires_dp_mesh(self, mesh4x2, clusters):
        with pytest.raises(ValueError, match="two_level"):
            KMeans(mesh=mesh4x2, assign="two_level",
                   **_fit_kw()).fit(clusters)

    def test_auto_resolves_dense_on_unreported_backend(self, mesh8,
                                                       clusters):
        """CPU reports no allocator stats, so 'auto' must resolve to
        the dense oracle — massive-k routing is opt-in there."""
        import jax
        if jax.default_backend() != "cpu":
            pytest.skip("auto-resolution fallback is the CPU contract")
        km = KMeans(mesh=mesh8, **_fit_kw()).fit(clusters)
        assert km.k_shard_resolved_ == 0
        assert km.assign_resolved_ == "dense"


# ---------------------------------------------------------------------------
# Batched PQ codebook trainer
# ---------------------------------------------------------------------------

class TestProductQuantizer:
    @pytest.fixture(scope="class")
    def fitted(self, mesh8):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(1024, 16)).astype(np.float64)
        pq = ProductQuantizer(m=4, k=8, max_iter=20, tolerance=1e-6,
                              seed=7, dtype=np.float64, mesh=mesh8)
        pq.fit(X)
        return pq, X

    def test_subspace_equivalence_vs_independent_fits(self, fitted,
                                                      mesh8):
        """The one-dispatch batched trainer is M independent per-
        subspace k-means fits, bit-for-bit (the r12 member-axis
        contract applied to subspaces)."""
        pq, X = fitted
        seeds = pq._member_seeds(4)
        for j in range(4):
            sub = X[:, j * 4:(j + 1) * 4]
            km = KMeans(k=8, max_iter=20, tolerance=1e-6, seed=seeds[j],
                        init="k-means++", empty_cluster="keep",
                        dtype=np.float64, mesh=mesh8, host_loop=False,
                        verbose=False).fit(sub)
            assert np.max(np.abs(np.asarray(km.centroids, np.float64)
                                 - pq.codebooks_[j])) == 0.0
            d2 = ((sub[:, None, :]
                   - pq.codebooks_[j][None, :, :]) ** 2).sum(-1)
            sse = float(np.sum(np.min(d2, axis=1)))
            assert pq.subspace_inertias_[j] == pytest.approx(
                sse, rel=1e-9)

    def test_encode_is_exact_argmin(self, fitted):
        pq, X = fitted
        codes = pq.encode(X)
        assert codes.shape == (1024, 4) and codes.dtype == np.uint8
        for j in (0, 2):
            sub = X[:, j * 4:(j + 1) * 4]
            d2 = ((sub[:, None, :]
                   - pq.codebooks_[j][None, :, :]) ** 2).sum(-1)
            assert np.array_equal(codes[:, j], np.argmin(d2, axis=1))
        dec = pq.decode(codes)
        assert dec.shape == X.shape
        assert pq.compression_ratio() > 1.0

    def test_adc_assign_matches_exact_decoded_argmin(self, mesh8):
        """The guarded ADC contract: f32 LUT sums with near-tie rows
        recomputed exactly — labels equal the exact f64 argmin over
        the DECODED table (the bf16-guard discipline applied to PQ)."""
        rng = np.random.default_rng(3)
        table = rng.normal(size=(64, 16))
        pq, codes = ProductQuantizer.for_table(table, m=4, k=16,
                                               seed=3, mesh=mesh8)
        queries = rng.normal(size=(200, 16))
        labels, corrected = pq.adc_assign(queries, codes)
        oracle = _dense_argmin(queries, pq.decode(codes))
        assert np.array_equal(labels, oracle)
        assert 0 <= corrected <= len(queries)

    def test_plan_recorded(self, fitted):
        pq, _ = fitted
        assert pq.plan_ is not None
        assert "predicted_peak_bytes" in pq.plan_

    def test_auto_subspaces_and_validation(self, mesh8):
        assert default_subspaces(16) == 8
        assert default_subspaces(7) == 7
        assert default_subspaces(13) == 1
        rng = np.random.default_rng(0)
        X = rng.normal(size=(64, 16))
        with pytest.raises(ValueError, match="divide"):
            ProductQuantizer(m=5, mesh=mesh8).fit(X)
        with pytest.raises(ValueError, match="Not enough data points"):
            ProductQuantizer(m=4, k=8, mesh=mesh8).fit(X[:4])

    def test_fitted_state(self, fitted):
        pq, _ = fitted
        fs = pq.fitted_state()
        assert fs["family"] == "pq"
        assert fs["stackable"] is False
        assert fs["m"] == 4


# ---------------------------------------------------------------------------
# Checkpoint roundtrip of the large-k knobs
# ---------------------------------------------------------------------------

def test_checkpoint_roundtrip_preserves_knobs(tmp_path, mesh8, clusters):
    km = KMeans(mesh=mesh8, assign="two_level", coarse_cells=4,
                nprobe=4, init_cap=4096, init="k-means||",
                **_fit_kw()).fit(clusters)
    path = tmp_path / "largek.npz"
    km.save(path)
    back = KMeans.load(path)
    assert back.assign == "two_level"
    assert back.coarse_cells == 4
    assert back.nprobe == 4
    assert back.init_cap == 4096
    assert back.k_shard == "auto"
    assert np.array_equal(np.asarray(back.centroids),
                          np.asarray(km.centroids))
    assert np.array_equal(np.asarray(back.predict(clusters)),
                          np.asarray(km.predict(clusters)))


def test_checkpoint_carries_coarse_table(tmp_path, mesh8, clusters):
    """The coarse quantizer is FITTED state: with nprobe < coarse_cells
    (non-collapse regime, where candidate sets actually depend on the
    coarse table) a loaded model must predict IDENTICALLY to the model
    that was saved — retraining coarse from the final table at load
    time would re-route rows.  Regression pin for the r20 verify
    finding (the collapse-regime roundtrip above cannot catch it)."""
    km = KMeans(mesh=mesh8, assign="two_level", coarse_cells=6,
                nprobe=1, **_fit_kw()).fit(clusters)
    path = tmp_path / "largek_probe1.npz"
    km.save(path)
    back = KMeans.load(path)
    saved_coarse = km._two_level_route_[0]
    assert back._two_level_route_ is not None
    assert np.array_equal(back._two_level_route_[0], saved_coarse)
    assert np.array_equal(np.asarray(back.predict(clusters)),
                          np.asarray(km.predict(clusters)))


# ---------------------------------------------------------------------------
# Serving routes (engine dispatch)
# ---------------------------------------------------------------------------

class TestServingRoutes:
    @pytest.fixture(scope="class")
    def engine(self, mesh8):
        from kmeans_tpu.serving.engine import ServingEngine
        return ServingEngine(mesh=mesh8, buckets=(64, 256),
                             quality=False)

    @pytest.fixture(scope="class")
    def rows(self):
        rng = np.random.default_rng(9)
        return (rng.normal(size=(50, 16))
                + 8.0 * rng.integers(0, 3, size=(50, 1)))

    def test_pq_serving_matches_decoded_oracle(self, engine, mesh8,
                                               clusters, rows):
        km = KMeans(mesh=mesh8, **_fit_kw()).fit(clusters)
        engine.add_model("pq-m", km, quantize="pq")
        try:
            labels = engine.call("pq-m", rows)
            rm = engine._rm("pq-m")
            oracle = _dense_argmin(rows, rm.pq.decode(rm.pq_codes))
            assert np.array_equal(labels, oracle)
            v = engine.verify_quantized("pq-m", rows)
            assert "dist_max_rel" in v and v["label_mismatches"] >= 0
            st = engine.stats()["models"]["pq-m"]
            assert st["quantize"] == "pq"
            assert "pq_corrected_rows" in st
        finally:
            engine.remove("pq-m")

    def test_two_level_serving_matches_model_predict(self, engine,
                                                     mesh8, clusters,
                                                     rows):
        km = KMeans(mesh=mesh8, assign="two_level", coarse_cells=4,
                    nprobe=4, **_fit_kw()).fit(clusters)
        engine.add_model("tl-m", km)
        try:
            labels = engine.call("tl-m", rows)
            assert np.array_equal(labels, np.asarray(km.predict(rows)))
            assert np.array_equal(labels,
                                  _dense_argmin(rows, km.centroids))
        finally:
            engine.remove("tl-m")

    def test_rejections(self, engine, mesh8, mesh4x2, clusters):
        from kmeans_tpu.serving.engine import ServingEngine
        km_tl = KMeans(mesh=mesh8, assign="two_level", coarse_cells=4,
                       nprobe=4, **_fit_kw()).fit(clusters)
        with pytest.raises(ValueError):
            engine.add_model("bad", km_tl, quantize="bf16")
        assert "bad" not in engine.models()
        km = KMeans(mesh=mesh8, **_fit_kw()).fit(clusters)
        with pytest.raises(ValueError, match="'pq'"):
            engine.add_model("bad", km, quantize="int8")
        eng_tp = ServingEngine(mesh=mesh4x2, buckets=(64,),
                               quality=False)
        km_tp = KMeans(mesh=mesh4x2, **_fit_kw(max_iter=5)).fit(clusters)
        with pytest.raises(ValueError):
            eng_tp.add_model("m", km_tp, quantize="pq")
        with pytest.raises(ValueError):
            eng_tp.add_model("m", km_tl)
        assert eng_tp.models() == []


# ---------------------------------------------------------------------------
# Comm accounting + HBM planner
# ---------------------------------------------------------------------------

class TestCommAndPlanner:
    def test_kshard_comm_sites(self):
        from kmeans_tpu.obs.fleet import comm_bytes_model
        dense = comm_bytes_model("kmeans", k=64, d=8, data_shards=4,
                                 model_shards=2, n_chunks=4,
                                 chunk_rows=128)
        ksh = comm_bytes_model("kmeans", k=64, d=8, data_shards=4,
                               model_shards=2, n_chunks=4,
                               chunk_rows=128, k_shard=2)
        dn = {s["site"] for s in dense["sites"]}
        kn = {s["site"] for s in ksh["sites"]}
        assert "tp.gather_centroid_table" in dn
        assert "tp.gather_centroid_table" not in kn
        assert "estep.pmin_assign_pair" in kn
        assert "estep.pmin_assign_pair" not in dn
        sums_d = next(s for s in dense["sites"]
                      if s["site"] == "estep.psum_sums")
        sums_k = next(s for s in ksh["sites"]
                      if s["site"] == "estep.psum_sums")
        # k-local accumulator rows: half the bytes over the DATA group
        # only, instead of full k_pad over the whole mesh.
        assert sums_k["result_bytes"] == sums_d["result_bytes"] / 2
        assert sums_k["group"] == 4 and sums_d["group"] == 8
        pair = next(s for s in ksh["sites"]
                    if s["site"] == "estep.pmin_assign_pair")
        assert pair["result_bytes"] == 128 * 8  # (f32 dist + i32 idx)/row
        assert pair["count"] == 4 and pair["group"] == 2
        assert ksh["k_shard"] == 2 and dense["k_shard"] == 0

    def test_dp_comm_model_unchanged(self):
        from kmeans_tpu.obs.fleet import comm_bytes_model
        dp = comm_bytes_model("kmeans", k=64, d=8, data_shards=8)
        assert {s["site"] for s in dp["sites"]} == {
            "estep.psum_sums", "estep.psum_counts", "estep.psum_sse"}
        assert dp["k_shard"] == 0

    def test_plan_fit_kshard_shrinks_stats(self):
        from kmeans_tpu.obs.memory import plan_fit
        dense = plan_fit("kmeans", 1_000_000, 64, 16384, data_shards=4,
                         model_shards=2, chunk=4096, k_shard=0)
        ksh = plan_fit("kmeans", 1_000_000, 64, 16384, data_shards=4,
                       model_shards=2, chunk=4096, k_shard=2)
        assert ksh["components"]["stats_bytes"] \
            < dense["components"]["stats_bytes"]
        assert ksh["predicted_peak_bytes"] < dense["predicted_peak_bytes"]
        assert ksh["k_shard"] == 2 and dense["k_shard"] == 0

    def test_bucket_candidates_ladder(self):
        from kmeans_tpu.parallel.sharding import bucket_candidates
        assert bucket_candidates(1) == 32
        assert bucket_candidates(32) == 32
        widths = [bucket_candidates(n) for n in range(1, 4097)]
        assert all(w >= n for n, w in enumerate(widths, start=1))
        assert all(b >= a for a, b in zip(widths, widths[1:]))
        assert len(set(widths)) < 32  # a bounded ladder, not one per n


# ---------------------------------------------------------------------------
# CLI surfaces
# ---------------------------------------------------------------------------

class TestCli:
    def test_plan_json(self, capsys):
        from kmeans_tpu.cli import plan_main
        rc = plan_main(["--n", "1000000", "--d", "64", "--k", "16384",
                        "--data-shards", "4", "--model-shards", "2",
                        "--json"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert len(doc["plans"]) == 2
        res = doc["resolution"]
        assert res["k_shard"] in (0, 2)
        assert res["assign"] in ("dense", "two_level")

    def test_plan_human_table(self, capsys):
        from kmeans_tpu.cli import plan_main
        rc = plan_main(["--n", "1000000", "--d", "64", "--k", "16384",
                        "--data-shards", "4", "--model-shards", "2"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "hbm footprint plan" in out
        assert "resolution" in out
        assert "k-shard saves" in out

    def test_plan_rejects_two_level_on_tp(self, capsys):
        from kmeans_tpu.cli import plan_main
        rc = plan_main(["--n", "1000", "--d", "8", "--k", "64",
                        "--model-shards", "2", "--assign", "two_level"])
        assert rc == 2

    def test_plan_rejects_bad_kshard(self, capsys):
        from kmeans_tpu.cli import plan_main
        rc = plan_main(["--n", "1000", "--d", "8", "--k", "64",
                        "--model-shards", "2", "--k-shard", "3"])
        assert rc == 2

    def test_ckpt_info_plan_block(self, tmp_path, mesh8, clusters,
                                  capsys):
        from kmeans_tpu.cli import ckpt_info_main
        km = KMeans(mesh=mesh8, assign="two_level", coarse_cells=4,
                    nprobe=4, **_fit_kw()).fit(clusters)
        path = tmp_path / "ck.npz"
        km.save(path)
        rc = ckpt_info_main([str(path), "--json", "--plan-n", "50000"])
        assert rc == 0
        info = json.loads(capsys.readouterr().out)
        plan = info["plan"]
        assert plan is not None
        assert plan["n_assumed"] == 50000
        assert plan["k"] == 12 and plan["d"] == 16
        # The checkpoint's own explicit knobs win over the auto rule.
        assert plan["assign"] == "two_level"
        assert plan["resolved_by"] == "checkpoint knobs"
        assert len(plan["plans"]) >= 1

    def test_bench_diff_discriminates_k(self):
        """BENCH_LARGEK rows at different k must never be compared as
        a regression pair — 'k' is a discriminator key."""
        from kmeans_tpu.cli import _BENCH_DISCRIMINATORS
        assert "k" in _BENCH_DISCRIMINATORS


# ---------------------------------------------------------------------------
# Bench harness (tiny shape; the published curve runs via
# BENCH_LARGEK=1 python bench.py)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_bench_large_k_rows(monkeypatch):
    from kmeans_tpu.benchmarks import bench_large_k
    out = bench_large_k(2000, 8, (16,), iters=2, reps=1)
    assert out["ks"] == [16]
    row = out["rows"][0]
    assert row["dense_ms_per_iter"] > 0
    assert row["routed_ms_per_iter"] > 0
    assert row["sse_rel_gap"] is not None
    assert row["predicted_peak_bytes_dense"] > 0
    assert "auto_resolution" in row
