"""GMM numerics on real hardware.

On TPU, "f32" dots execute with bf16-rounded products by default — fine
for the responsibility softmax, fatal for the M-step's variance
difference S2/R - mu^2 once a cluster's mean sits more than ~16 sigma
from the centering shift (r3: covariances collapsed to reg_covar and
the log-likelihood went POSITIVE via the density-spike singularity,
found only by driving the chip — the CPU suite computes exact f32 dots
and cannot see it).  The moment matmuls now run at Precision.HIGH —
r3 pinned HIGHEST; the r5 ladder (experiments/exp_gmm_estep_retry.py)
measured HIGH indistinguishable on the failure shape and 1.53x faster
(gmm_step._estep_tile) — and this pins the survival bound on hardware
either way.
"""

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="bf16-rate matmul products only exist on real TPU hardware")


def test_moment_matmuls_survive_offset_clusters_on_tpu():
    from kmeans_tpu import GaussianMixture

    rng = np.random.default_rng(0)
    k, d, n = 32, 64, 50_000
    # Cluster means ~N(0, 25) per dim after global centering: |mu|/sigma
    # up to ~25 — beyond the bf16-product survival bound (~16), inside
    # the f32 one (~4096).
    centers = rng.normal(size=(k, d)) * 5 + 1e3
    y = rng.integers(0, k, size=n)
    X = (centers[y] + rng.normal(size=(n, d))).astype(np.float32)

    gm = GaussianMixture(n_components=k, means_init=centers, max_iter=3,
                         tol=0.0, seed=1).fit(X)
    # True per-dim variances are 1.0; bf16-product moments collapsed
    # them to reg_covar (1e-6) and pushed the mean loglik positive.
    assert gm.covariances_.min() > 0.5, gm.covariances_.min()
    assert gm.covariances_.max() < 2.0
    assert gm.lower_bound_ < 0

    # Device loop agrees with the host loop on the same hardware path.
    gm_dev = GaussianMixture(n_components=k, means_init=centers,
                             max_iter=3, tol=0.0, seed=1,
                             host_loop=False).fit(X)
    np.testing.assert_allclose(gm_dev.covariances_, gm.covariances_,
                               rtol=1e-4)
    np.testing.assert_allclose(gm_dev.lower_bound_, gm.lower_bound_,
                               rtol=1e-5)
