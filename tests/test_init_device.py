"""One-dispatch on-device kmeans|| pipeline (ISSUE 2 tentpole).

Covers the four coverage gaps the issue names: sharded-vs-single-device
invariance over virtual meshes, device-vs-host candidate-set parity at
small n, fixed-capacity buffer overflow behavior, and an O(1)-dispatch
regression pin via the profiling hooks — plus the legacy-oracle
trajectory pin and the final-inertia parity acceptance criterion.
"""

import jax
import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans
from kmeans_tpu.models.init import kmeans_parallel_init
from kmeans_tpu.parallel.mesh import make_mesh
from kmeans_tpu.utils import profiling


def _blobby(n=2048, d=6, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, d))
            + 6.0 * rng.integers(0, 4, size=(n, 1))).astype(np.float64)


def test_sharded_matches_single_device():
    """The device pipeline's draws are functions of the GLOBAL row index
    and its distributed top-k combine is exact, so a 1/2/4/8-way
    data-sharded init over the same padded layout is bit-identical."""
    X = _blobby()
    ndev = len(jax.devices())
    res = {}
    for s in (1, 2, 4, 8):
        if s > ndev:
            pytest.skip(f"needs {s} devices")
        mesh = make_mesh(data=s, model=1, devices=jax.devices()[:s])
        # Explicit chunk so every mesh pads to the same 2048-row layout
        # (the RNG streams are defined on the padded global row space).
        km = KMeans(k=16, mesh=mesh, chunk_size=256, dtype=np.float64,
                    verbose=False)
        res[s] = kmeans_parallel_init(km.cache(X), 16, seed=7)
    for s in res:
        np.testing.assert_array_equal(res[s], res[1])


def test_data_model_mesh_matches_data_only(mesh8, mesh4x2):
    """A (data, model) mesh runs the init identically on every model
    replica — same result as the data-only mesh of equal padded layout."""
    X = _blobby()
    out = {}
    for name, mesh in (("dp", make_mesh(data=4, model=1,
                                        devices=jax.devices()[:4])),
                       ("tp", mesh4x2)):
        km = KMeans(k=8, mesh=mesh, chunk_size=256, dtype=np.float64,
                    verbose=False)
        out[name] = kmeans_parallel_init(km.cache(X), 8, seed=3)
    np.testing.assert_array_equal(out["dp"], out["tp"])


def test_device_vs_host_candidate_set_parity_small_n():
    """At small n with a saturating oversampling factor the Bernoulli
    round degenerates to p=1 for every uncovered point, so BOTH engines
    must select the SAME candidate set — all n rows — even though their
    RNG streams differ (the documented divergence covers which rows win
    ties, not set membership here).  Masses must both sum to n."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(60, 3)).astype(np.float64)
    _, cand_d, mass_d = kmeans_parallel_init(
        X, 8, seed=0, oversampling=1e6, return_candidates=True)
    _, cand_h, mass_h = kmeans_parallel_init(
        X, 8, seed=0, oversampling=1e6, device=False,
        return_candidates=True)
    sort = lambda a: a[np.lexsort(a.T)]          # noqa: E731
    np.testing.assert_allclose(sort(np.unique(cand_d, axis=0)),
                               sort(np.unique(cand_h, axis=0)), atol=0)
    assert len(np.unique(cand_d, axis=0)) == len(X)
    np.testing.assert_allclose(mass_d.sum(), len(X), rtol=1e-12)
    np.testing.assert_allclose(mass_h.sum(), len(X), rtol=1e-12)


def test_fixed_capacity_buffer_overflow():
    """A cap smaller than the per-round sample count: the buffer keeps
    exactly cap winners per round (top scores), stays fixed-shape, and
    the reduce still returns k finite centers."""
    rng = np.random.default_rng(1)
    X = rng.normal(size=(500, 3)).astype(np.float64)
    centers, cands, mass = kmeans_parallel_init(
        X, 4, seed=0, cap=8, oversampling=1e6, return_candidates=True)
    assert centers.shape == (4, 3)
    assert np.all(np.isfinite(centers))
    # rounds is raised to ceil(1.5k/cap)=1 -> max(5, 1) = 5 rounds of 8.
    assert cands.shape[0] <= 1 + 5 * 8
    assert cands.shape[0] > 8          # multiple rounds actually landed
    assert np.all(mass >= 0)


def test_device_init_dispatch_count_is_O1_in_rounds():
    """THE structural claim of ISSUE 2: the device pipeline is ONE
    dispatch regardless of the round count, while the legacy engine pays
    one round trip per round (plus cell-mass and host-reduce syncs)."""
    X = _blobby(n=1024, d=4)

    def count(device, rounds):
        with profiling.log_dispatches() as log:
            kmeans_parallel_init(X, 8, seed=1, rounds=rounds,
                                 device=device)
        return list(log)

    d3, d6 = count(True, 3), count(True, 6)
    assert d3 == d6 == ["kmeans||/device-pipeline"]
    h3, h6 = count(False, 3), count(False, 6)
    assert h3.count("kmeans||/round") == 3
    assert h6.count("kmeans||/round") == 6
    assert "kmeans||/cell-mass" in h6 and "kmeans||/host-reduce" in h6


@pytest.mark.skipif(jax.default_backend() != "cpu",
                    reason="golden values pinned on the CPU f64 path")
def test_legacy_trajectory_pinned():
    """The device=False oracle's seeded trajectory is pinned: any change
    to _kmeans_parallel_host that moves these values is a breaking change
    (the acceptance criterion keeps the legacy path bit-stable)."""
    rng = np.random.default_rng(11)
    X = rng.normal(size=(256, 3)).astype(np.float64)
    a = kmeans_parallel_init(X, 4, seed=5, device=False)
    b = kmeans_parallel_init(X, 4, seed=5, device=False)
    np.testing.assert_array_equal(a, b)          # deterministic
    # Every legacy seed is a data row of X (kmeans|| seeds are data
    # points), and the seeded selection is stable.
    for row in a:
        assert np.any(np.all(np.isclose(X, row[None, :], atol=1e-12),
                             axis=1))


def test_final_inertia_parity_device_vs_legacy(mesh8):
    """Acceptance criterion: a fit seeded by the device pipeline lands
    within tolerance of the legacy-seeded fit's final inertia on the
    correctness suite's blob shape (different RNG streams, same
    algorithm and quality)."""
    X, _ = make_blobs(n_samples=4000, centers=6, n_features=5,
                      cluster_std=0.4, random_state=2)
    X = X.astype(np.float64)

    def final_inertia(device):
        init = kmeans_parallel_init(X, 6, seed=3, device=device)
        km = KMeans(k=6, init=init, max_iter=50, mesh=mesh8,
                    dtype=np.float64, compute_sse=True,
                    verbose=False).fit(X)
        return km.sse_history[-1]

    dev, leg = final_inertia(True), final_inertia(False)
    assert dev <= leg * 1.05 + 1e-9


def test_degenerate_data_backfills_duplicates():
    """Review regression: data with fewer distinct points than the
    recluster can separate forces the device pipeline's duplicate
    backfill — which writes into the returned center table (np.asarray
    of a jax array is read-only; the wrapper must take a writable
    copy).  k <= n_distinct here, so distinctness is also restorable."""
    rng = np.random.default_rng(2)
    base = rng.normal(size=(4, 3))
    X = np.repeat(base, 15, axis=0)          # 60 rows, 4 distinct points
    centers = kmeans_parallel_init(X, 4, seed=0)
    assert centers.shape == (4, 3)
    assert np.all(np.isfinite(centers))
    assert len(np.unique(centers, axis=0)) == 4


def test_device_init_deterministic_per_seed():
    X = _blobby(n=1024, d=4, seed=9)
    a = kmeans_parallel_init(X, 8, seed=13)
    b = kmeans_parallel_init(X, 8, seed=13)
    c = kmeans_parallel_init(X, 8, seed=14)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_device_init_hostless_dataset(mesh8):
    """The pipeline never needs host row access: a device-only dataset
    (no host copy) initializes fine — the capability that matters for
    multi-host process-local data."""
    X = _blobby(n=2048, d=5, seed=4)
    km = KMeans(k=8, init="kmeans||", seed=7, mesh=mesh8,
                dtype=np.float64, compute_sse=True, verbose=False)
    ds = km.cache(X)
    ds._host = None
    ds._host_weights = None
    km.fit(ds)
    assert np.all(np.isfinite(km.centroids))
    assert len(np.unique(km.centroids.round(9), axis=0)) == 8
