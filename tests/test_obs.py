"""ISSUE 11: the unified telemetry layer.

Coverage tiers:

1. **Tracer/registry/heartbeat units** — span nesting, self-time
   attribution (nested spans never double-count), JSONL round-trip,
   Chrome export schema, malformed-input classification, typed metric
   semantics, heartbeat emission + thread join-on-close.
2. **obs-off parity** — the disabled path is the bit-exact oracle: a
   fit run under tracing + metrics + heartbeat equals the plain fit
   bit-for-bit for all five model families across 1/2/4/8-way meshes.
3. **Span structure under the hard paths** — segmented fits, injected
   OOM replay (attempt spans inside ONE segment span — never a second
   segment), checkpoint restore, the note_dispatch migration shim, and
   the recompilation sentinel's timeline twin.
4. **Time-to-first-iteration report** — span-derived ladder through the
   shared ``phase_ceiling_table`` formatter.
5. **CLI** — ``python -m kmeans_tpu trace summarize`` (table/json/
   chrome; exit 2 on unreadable/malformed).
"""

import json
import threading
import time

import jax
import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans, obs
from kmeans_tpu.models import (BisectingKMeans, GaussianMixture,
                               MiniBatchKMeans, SphericalKMeans)
from kmeans_tpu.obs.heartbeat import (Heartbeat,
                                      get_heartbeat)
from kmeans_tpu.obs import metrics_registry as mr_mod
from kmeans_tpu.obs import trace as trace_mod
from kmeans_tpu.obs.report import ttfi_ladder, time_to_first_iteration
from kmeans_tpu.parallel.mesh import make_mesh
from kmeans_tpu.utils import faults, profiling

WIDTHS = (1, 2, 4, 8)


def _mesh(w, m=1):
    if len(jax.devices()) < w * m:
        pytest.skip(f"needs {w * m} devices")
    return make_mesh(data=w, model=m, devices=jax.devices()[: w * m])


def _blobs(n=800, d=4, centers=4, rs=7):
    X, _ = make_blobs(n_samples=n, centers=centers, n_features=d,
                      random_state=rs)
    return X.astype(np.float32)


def spans_named(records, name):
    return [r for r in records if r.get("kind") == "span"
            and r["name"] == name]


# ---------------------------------------------------------------------------
# Tracer unit semantics
# ---------------------------------------------------------------------------

def test_span_nesting_parent_depth():
    with obs.tracing() as tr:
        with obs.span("segment", index=0):
            with obs.span("dispatch", tag="x"):
                pass
            with obs.span("dispatch", tag="y"):
                pass
    recs = tr.records()
    seg = spans_named(recs, "segment")[0]
    disps = spans_named(recs, "dispatch")
    assert len(disps) == 2
    for d in disps:
        assert d["parent"] == seg["id"]
        assert d["depth"] == 1
        assert seg["t0"] <= d["t0"] and d["t1"] <= seg["t1"]
    assert seg["parent"] is None and seg["depth"] == 0


def test_disabled_path_is_noop_and_allocation_free():
    assert obs.get_tracer() is None
    ctx1 = obs.span("dispatch", tag="x")
    ctx2 = obs.span("stage")
    assert ctx1 is ctx2           # the one shared null context manager
    with ctx1:
        pass
    obs.event("dispatch.note", label="x")      # must not raise


def test_span_records_error_type_and_propagates():
    with obs.tracing() as tr:
        with pytest.raises(ValueError):
            with obs.span("dispatch"):
                raise ValueError("boom")
    rec = spans_named(tr.records(), "dispatch")[0]
    assert rec["error"] == "ValueError"
    assert rec["dur"] is not None


def test_self_time_excludes_children_no_double_count():
    with obs.tracing() as tr:
        with obs.span("stage"):            # outer (prefetch-style)
            with obs.span("stage"):        # inner (shard_points-style)
                time.sleep(0.02)
    recs = tr.records()
    summ = obs.summarize(recs)
    outer_total = max(r["dur"] for r in spans_named(recs, "stage"))
    # Total SELF time ~= the one real sleep, NOT 2x (the nested same-
    # name span must not double-count).
    assert summ["stage"]["count"] == 2
    assert summ["stage"]["total"] == pytest.approx(outer_total, rel=0.25)


def test_jsonl_roundtrip_and_header():
    with obs.tracing() as tr:
        with obs.span("seed", strategy="forgy"):
            pass
        obs.event("dispatch.note", label="x")
    return_path = None

    def check(tmp):
        tr.write_jsonl(tmp)
        back = trace_mod.read_jsonl(tmp)
        kinds = [r["kind"] for r in back]
        assert kinds[0] == "header"
        assert "span" in kinds and "event" in kinds
        sp = spans_named(back, "seed")[0]
        assert sp["attrs"]["strategy"] == "forgy"
        return back
    import tempfile, os
    with tempfile.TemporaryDirectory() as td:
        check(os.path.join(td, "t.jsonl"))
    return return_path


def test_read_jsonl_malformed_raises(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_text("not json\n")
    with pytest.raises(trace_mod.TraceReadError):
        trace_mod.read_jsonl(p)
    p2 = tmp_path / "empty_records.jsonl"
    p2.write_text(json.dumps({"kind": "header"}) + "\n")
    with pytest.raises(trace_mod.TraceReadError):
        trace_mod.read_jsonl(p2)
    p3 = tmp_path / "missing_fields.jsonl"
    p3.write_text(json.dumps({"kind": "span"}) + "\n")
    with pytest.raises(trace_mod.TraceReadError):
        trace_mod.read_jsonl(p3)
    with pytest.raises(trace_mod.TraceReadError):
        trace_mod.read_jsonl(tmp_path / "nonexistent.jsonl")


def test_read_jsonl_span_missing_id_is_malformed(tmp_path):
    """'id' is load-bearing (self_times keys on it): a record without
    it must classify as TraceReadError at read time, never a KeyError
    deep in summarize (the CLI's exit-2 contract)."""
    p = tmp_path / "noid.jsonl"
    p.write_text(json.dumps({"kind": "header"}) + "\n" + json.dumps(
        {"kind": "span", "name": "dispatch", "t0": 0.1,
         "dur": 0.5}) + "\n")
    with pytest.raises(trace_mod.TraceReadError):
        trace_mod.read_jsonl(p)
    from kmeans_tpu.cli import trace_main
    assert trace_main(["summarize", str(p)]) == 2


def test_measurement_cache_opts_out_of_compile_spans():
    """A cache constructed with compile_spans=False (the _AUTO_CACHE
    measurement cache) emits no 'compile' span on a miss — its factory
    is a measurement, not a program build."""
    from kmeans_tpu.utils.cache import LRUCache
    quiet = LRUCache(4, name="test._QUIET", compile_spans=False)
    loud = LRUCache(4, name="test._LOUD")
    with obs.tracing() as tr:
        quiet.get_or_create("k", lambda: 1)
        loud.get_or_create("k", lambda: 2)
    compiles = spans_named(tr.records(), "compile")
    assert [c["attrs"]["cache"] for c in compiles] == ["test._LOUD"]


def test_chrome_export_schema(tmp_path):
    with obs.tracing() as tr:
        with obs.span("dispatch", tag="x"):
            time.sleep(0.002)
        obs.event("dispatch.note", label="y")
    out = tmp_path / "chrome.json"
    tr.write_chrome(out)
    doc = json.loads(out.read_text())
    evs = doc["traceEvents"]
    assert evs, "no trace events exported"
    phases = {e["ph"] for e in evs}
    assert "X" in phases and "i" in phases
    for e in evs:
        assert {"name", "ph", "ts", "pid", "tid"} <= set(e)
        assert e["ts"] >= 0
        if e["ph"] == "X":
            assert e["dur"] >= 0
    # ts sorted ascending (the chrome loader expects it)
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)


def test_nested_tracing_scopes_shadow():
    with obs.tracing() as outer:
        with obs.span("seed"):
            pass
        with obs.tracing() as inner:
            with obs.span("dispatch"):
                pass
        with obs.span("io.block"):
            pass
    assert [r["name"] for r in outer.records()] == ["seed", "io.block"]
    assert [r["name"] for r in inner.records()] == ["dispatch"]


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

def test_registry_typed_metrics_and_snapshot():
    reg = mr_mod.MetricsRegistry()
    reg.counter("a.hits").inc()
    reg.counter("a.hits").inc(2)
    reg.gauge("a.level").set(7)
    h = reg.histogram("a.lat")
    for v in range(100):
        h.observe(v)
    snap = reg.snapshot()
    assert snap["a.hits"] == {"kind": "counter", "value": 3}
    assert snap["a.level"]["value"] == 7
    lat = snap["a.lat"]["value"]
    assert lat["count"] == 100 and lat["min"] == 0 and lat["max"] == 99
    assert lat["p50"] == pytest.approx(50, abs=3)
    json.loads(reg.to_json())          # JSON-exportable by contract


def test_registry_name_type_conflict_raises():
    reg = mr_mod.MetricsRegistry()
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_histogram_reservoir_thins_deterministically():
    h = mr_mod.Histogram("h", reservoir=64)
    for v in range(10_000):
        h.observe(v)
    assert h.count == 10_000
    assert len(h._reservoir) <= 64
    assert h.percentile(0.5) == pytest.approx(5000, rel=0.1)


def test_note_dispatch_writes_through_registry_and_shim():
    mr_mod.REGISTRY.reset()
    with profiling.log_dispatches() as log:
        with obs.tracing() as tr:
            profiling.note_dispatch("test/label")
            profiling.note_dispatch("test/label")
    # shim list (the existing structural-pin surface)
    assert log.count("test/label") == 2
    # registry counter (the migrated canonical store)
    snap = mr_mod.REGISTRY.snapshot()
    assert snap["dispatch.test/label"]["value"] == 2
    # tracer events
    evs = [r for r in tr.records() if r.get("kind") == "event"]
    assert len(evs) == 2
    assert all(e["attrs"]["label"] == "test/label" for e in evs)


# ---------------------------------------------------------------------------
# Heartbeat
# ---------------------------------------------------------------------------

def test_heartbeat_callback_and_file(tmp_path):
    p = tmp_path / "hb.jsonl"
    got = []
    with obs.heartbeat(str(p), callback=got.append) as hb:
        obs.note_progress(None, phase="iteration", iteration=3)
        obs.note_progress(None, phase="checkpoint", iteration=6)
    assert hb.emitted == 2 and len(got) == 2
    lines = [json.loads(line) for line in p.read_text().splitlines()]
    assert [r["phase"] for r in lines] == ["iteration", "checkpoint"]
    assert all("ts" in r for r in lines)


def test_heartbeat_thread_joins_on_close_no_leak():
    before = set(threading.enumerate())
    hb = Heartbeat(callback=lambda r: None, interval_s=0.02)
    assert hb._thread is not None and hb._thread.is_alive()
    hb.beat({"phase": "iteration"})
    time.sleep(0.06)
    hb.close()
    assert hb._thread is None
    leaked = [t for t in set(threading.enumerate()) - before
              if t.name == "kmeans_tpu-heartbeat"]
    assert not leaked
    hb.close()                         # idempotent


def test_heartbeat_timer_reemits_latest_with_tick():
    got = []
    with obs.heartbeat(callback=got.append, interval_s=0.03):
        obs.note_progress(None, phase="iteration", iteration=1)
        time.sleep(0.1)
    ticks = [r for r in got if r.get("tick")]
    assert ticks, "timer thread emitted no liveness ticks"
    assert all(r["iteration"] == 1 for r in ticks)


def test_heartbeat_throttle_flushes_latest_on_close():
    got = []
    with obs.heartbeat(callback=got.append, min_period_s=60.0):
        for i in range(5):
            obs.note_progress(None, phase="iteration", iteration=i)
    # First beat emitted immediately; throttled tail flushed at close.
    assert [r["iteration"] for r in got] == [0, 4]


def test_heartbeat_callback_errors_isolated():
    def bad(rec):
        raise RuntimeError("observer broke")
    with obs.heartbeat(callback=bad) as hb:
        obs.note_progress(None, phase="iteration")
    assert hb.callback_errors == 1 and hb.emitted == 1


def test_phase_totals_incremental_matches_summarize():
    """The O(names) incremental accumulators agree with the exact
    post-hoc summarize() once spans are closed."""
    with obs.tracing() as tr:
        with obs.span("segment"):
            with obs.span("dispatch"):
                time.sleep(0.005)
        with obs.span("stage"):
            with obs.span("stage"):
                time.sleep(0.002)
    exact = {name: row["total"]
             for name, row in obs.summarize(tr.records()).items()}
    fast = tr.phase_totals()
    assert set(fast) == set(exact)
    for name in exact:
        assert fast[name] == pytest.approx(exact[name], abs=1e-9)


def test_heartbeat_reentrant_callback_does_not_deadlock():
    """A callback that re-enters note_progress recurses through the
    reentrant emit lock instead of deadlocking (review finding)."""
    got = []

    def reentrant(rec):
        got.append(rec)
        if not rec.get("nested"):
            obs.note_progress(None, phase="iteration", nested=True)

    with obs.heartbeat(callback=reentrant):
        obs.note_progress(None, phase="iteration")
    assert len(got) == 2
    assert got[1]["nested"] is True


def test_heartbeat_file_sink_failure_isolated(tmp_path):
    """A dead file sink (unwritable path) is counted and disabled; the
    fit-side beats and the callback keep working (the 'broken observer
    never kills a healthy fit' contract covers BOTH sinks)."""
    got = []
    bad = tmp_path / "no_such_dir" / "hb.jsonl"
    with obs.heartbeat(str(bad), callback=got.append) as hb:
        obs.note_progress(None, phase="iteration", iteration=1)
        obs.note_progress(None, phase="iteration", iteration=2)
    assert hb.sink_errors == 1          # disabled after first failure
    assert len(got) == 2                # callback unaffected


def test_heartbeat_unserializable_field_does_not_raise(tmp_path):
    p = tmp_path / "hb.jsonl"
    with obs.heartbeat(str(p)):
        obs.note_progress(None, phase="iteration",
                          weird=np.float32(1.5), path=p)
    rec = json.loads(p.read_text().splitlines()[0])
    assert rec["phase"] == "iteration"  # default=str serialized it


def test_note_progress_is_noop_without_heartbeat():
    assert get_heartbeat() is None
    obs.note_progress(None, phase="iteration")       # must not raise


def test_heartbeat_validates_interval():
    with pytest.raises(ValueError):
        Heartbeat(interval_s=0)


def test_heartbeat_scope_rejects_kwargs_with_instance():
    """Kwargs alongside a pre-built Heartbeat would be silently
    dropped (no timer thread despite interval_s) — loud error
    instead."""
    hb = Heartbeat()
    try:
        with pytest.raises(ValueError, match="interval_s"):
            with obs.heartbeat(hb, interval_s=5.0):
                pass
    finally:
        hb.close()


# ---------------------------------------------------------------------------
# obs-off parity: all five families, telemetry fully on vs off
# ---------------------------------------------------------------------------

def _fit_pair(build, X, tmp_path, tag):
    """(plain_model, telemetry_model): identical construction, second
    fit runs under tracing + heartbeat (JSONL sinks exercised too)."""
    plain = build().fit(X)
    with obs.tracing(str(tmp_path / f"{tag}.jsonl")), \
            obs.heartbeat(str(tmp_path / f"{tag}.hb.jsonl")):
        traced = build().fit(X)
    return plain, traced


@pytest.mark.parametrize("width", WIDTHS)
def test_obs_off_parity_kmeans(width, tmp_path):
    mesh = _mesh(width)
    X = _blobs()

    def build():
        return KMeans(k=5, max_iter=8, tolerance=1e-12, seed=0,
                      compute_sse=True, mesh=mesh, verbose=False)
    a, b = _fit_pair(build, X, tmp_path, f"km{width}")
    assert a.iterations_run == b.iterations_run
    assert np.array_equal(a.centroids, b.centroids)
    assert a.sse_history == b.sse_history
    assert np.array_equal(a.labels_, b.labels_)


@pytest.mark.parametrize("width", WIDTHS)
def test_obs_off_parity_minibatch(width, tmp_path):
    mesh = _mesh(width)
    X = _blobs()

    def build():
        return MiniBatchKMeans(k=5, max_iter=8, batch_size=128, seed=0,
                               mesh=mesh, verbose=False)
    a, b = _fit_pair(build, X, tmp_path, f"mb{width}")
    assert a.iterations_run == b.iterations_run
    assert np.array_equal(a.centroids, b.centroids)


@pytest.mark.parametrize("width", WIDTHS)
def test_obs_off_parity_bisecting(width, tmp_path):
    mesh = _mesh(width)
    X = _blobs()

    def build():
        return BisectingKMeans(k=4, max_iter=6, seed=0, mesh=mesh,
                               compute_sse=True, verbose=False)
    a, b = _fit_pair(build, X, tmp_path, f"bk{width}")
    assert a.iterations_run == b.iterations_run
    assert np.array_equal(a.centroids, b.centroids)
    assert np.array_equal(a.labels_, b.labels_)


@pytest.mark.parametrize("width", WIDTHS)
def test_obs_off_parity_spherical(width, tmp_path):
    mesh = _mesh(width)
    X = _blobs()

    def build():
        return SphericalKMeans(k=4, max_iter=8, seed=0, mesh=mesh,
                               verbose=False)
    a, b = _fit_pair(build, X, tmp_path, f"sk{width}")
    assert a.iterations_run == b.iterations_run
    assert np.array_equal(a.centroids, b.centroids)


@pytest.mark.parametrize("width", WIDTHS)
def test_obs_off_parity_gmm(width, tmp_path):
    mesh = _mesh(width)
    X = _blobs()

    def build():
        return GaussianMixture(n_components=4, max_iter=6,
                               init_params="random", seed=0, mesh=mesh,
                               verbose=False)
    a, b = _fit_pair(build, X, tmp_path, f"gm{width}")
    assert a.n_iter_ == b.n_iter_
    assert np.array_equal(a.means_, b.means_)
    assert np.array_equal(a.covariances_, b.covariances_)
    assert a.lower_bound_ == b.lower_bound_


def test_obs_off_parity_device_loop_and_stream(tmp_path):
    """The one-dispatch device loop and the streamed fit under full
    telemetry — same bit-exact contract."""
    mesh = _mesh(min(4, len(jax.devices())))
    X = _blobs()

    def build_dev():
        return KMeans(k=5, max_iter=8, tolerance=1e-12, seed=0,
                      compute_sse=True, mesh=mesh, host_loop=False,
                      empty_cluster="keep", verbose=False)
    a, b = _fit_pair(build_dev, X, tmp_path, "kmdev")
    assert np.array_equal(a.centroids, b.centroids)
    assert a.sse_history == b.sse_history

    def blocks():
        for i in range(0, X.shape[0], 256):
            yield X[i: i + 256]
    km_plain = KMeans(k=5, max_iter=4, tolerance=1e-12, seed=0,
                      compute_sse=True, mesh=mesh, verbose=False)
    km_plain.fit_stream(lambda: blocks(), prefetch=2)
    with obs.tracing(str(tmp_path / "stream.jsonl")):
        km_tr = KMeans(k=5, max_iter=4, tolerance=1e-12, seed=0,
                       compute_sse=True, mesh=mesh, verbose=False)
        km_tr.fit_stream(lambda: blocks(), prefetch=2)
    assert np.array_equal(km_plain.centroids, km_tr.centroids)
    assert km_plain.sse_history == km_tr.sse_history


# ---------------------------------------------------------------------------
# Span structure: lifecycle, segments, OOM replay, resume
# ---------------------------------------------------------------------------

def test_traced_fit_emits_lifecycle_spans():
    X = _blobs()
    with obs.tracing() as tr:
        KMeans(k=5, max_iter=5, seed=0, chunk_size=117,  # odd chunk ->
               verbose=False).fit(X)                     # fresh cache key
    recs = tr.records()
    for name in ("place", "stage", "seed", "dispatch"):
        assert spans_named(recs, name), f"no {name!r} span"
    compiles = spans_named(recs, "compile")
    assert compiles, "cache miss emitted no compile span"
    assert any(c["attrs"]["cache"] == "kmeans._STEP_CACHE"
               for c in compiles)
    # builder construction nested inside the compile span
    traces = spans_named(recs, "trace")
    assert traces and all(t["attrs"]["builder"].startswith("make_")
                          for t in traces)


def test_segmented_fit_span_counts(tmp_path):
    mesh = _mesh(min(2, len(jax.devices())))
    X = _blobs()
    p = tmp_path / "seg.npz"
    with obs.tracing() as tr:
        km = KMeans(k=5, max_iter=6, tolerance=1e-12, seed=0, mesh=mesh,
                    host_loop=False, empty_cluster="keep", verbose=False)
        km.fit(X, checkpoint_every=2, checkpoint_path=str(p))
    recs = tr.records()
    segs = spans_named(recs, "segment")
    assert len(segs) == km.checkpoint_segments_
    assert len(spans_named(recs, "checkpoint.save")) \
        == km.checkpoint_segments_
    # one dispatch attempt per healthy segment
    fit_disp = [d for d in spans_named(recs, "dispatch")
                if d.get("attrs", {}).get("tag") == "fit/segment"]
    assert len(fit_disp) == len(segs)


def test_oom_replay_attempts_nest_in_one_segment(tmp_path):
    """The no-double-counting pin: an injected OOM replays the segment
    as a SECOND dispatch-attempt span inside the SAME segment span —
    segment count equals the clean run's."""
    mesh = _mesh(min(2, len(jax.devices())))
    X = _blobs()
    # float64: the chunk backoff (256 -> 128) regroups the scan folds,
    # and only f64 over f32-width data is regrouping-invariant (the r10
    # parity-class table) — the pin here is about SPAN structure, with
    # the trajectory pinned in its bit-exact class.
    kw = dict(k=5, max_iter=6, tolerance=1e-12, seed=0, mesh=mesh,
              chunk_size=256, host_loop=False, empty_cluster="keep",
              verbose=False, dtype=np.float64)
    p = tmp_path / "oom.npz"
    clean = KMeans(**kw).fit(X)
    import warnings
    with obs.tracing() as tr, \
            faults.inject_oom_on_segment(1) as rec, \
            warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        km = KMeans(**kw)
        km.fit(X, checkpoint_every=2, checkpoint_path=str(p))
    assert rec["fired"] == 1 and km.oom_backoffs_ == 1
    assert np.array_equal(km.centroids, clean.centroids)
    recs = tr.records()
    segs = spans_named(recs, "segment")
    assert len(segs) == km.checkpoint_segments_   # replay added NO segment
    fit_disp = [d for d in spans_named(recs, "dispatch")
                if d.get("attrs", {}).get("tag") == "fit/segment"]
    # one extra attempt for the replayed segment, inside its span
    assert len(fit_disp) == len(segs) + 1
    replayed = [d for d in fit_disp if d["attrs"]["attempt"] == 1]
    assert len(replayed) == 1
    seg_of = {s["id"]: s for s in segs}
    assert replayed[0]["parent"] in seg_of
    # registry write-through
    assert mr_mod.REGISTRY.snapshot().get(
        "fit.oom_backoffs", {}).get("value", 0) >= 1


def test_resume_emits_restore_span(tmp_path):
    mesh = _mesh(1)
    X = _blobs()
    p = tmp_path / "res.npz"
    kw = dict(k=5, max_iter=6, tolerance=1e-12, seed=0, mesh=mesh,
              host_loop=False, empty_cluster="keep", verbose=False)
    with faults.inject_kill_after_iteration(2):
        try:
            KMeans(**kw).fit(X, checkpoint_every=2,
                             checkpoint_path=str(p))
        except faults.SimulatedPreemption:
            pass
    with obs.tracing() as tr:
        km = KMeans(**kw)
        km.fit(X, resume=str(p), checkpoint_every=2,
               checkpoint_path=str(p))
    recs = tr.records()
    assert spans_named(recs, "checkpoint.restore")
    assert spans_named(recs, "segment")


def test_sentinel_emits_compile_span_per_new_key():
    X = _blobs(n=400)
    km = KMeans(k=3, max_iter=3, seed=0, chunk_size=97, verbose=False)
    with obs.tracing() as tr:
        with pytest.raises(profiling.RecompilationError):
            with profiling.recompilation_sentinel():
                km.fit(X)          # fresh odd chunk -> new cache keys
    sentinel_spans = [s for s in spans_named(tr.records(), "compile")
                     if s.get("attrs", {}).get("via") == "sentinel"]
    assert sentinel_spans
    assert all("STEP_CACHE" in s["attrs"]["cache"] or
               "CACHE" in s["attrs"]["cache"] for s in sentinel_spans)


def test_heartbeat_records_from_real_fits(tmp_path):
    mesh = _mesh(1)
    X = _blobs()
    got = []
    with obs.heartbeat(callback=got.append):
        KMeans(k=5, max_iter=4, seed=0, mesh=mesh, compute_sse=True,
               verbose=False).fit(X)
    iters = [r for r in got if r["phase"] == "iteration"]
    assert iters and iters[0]["model_class"] == "KMeans"
    assert iters[-1]["iteration"] >= 1
    assert "inertia" in iters[-1] and "shift" in iters[-1]

    got_gm = []
    with obs.heartbeat(callback=got_gm.append):
        GaussianMixture(n_components=3, max_iter=4,
                        init_params="random", seed=0, mesh=mesh,
                        verbose=False).fit(X)
    assert any(r["phase"] == "iteration" and
               r["model_class"] == "GaussianMixture" for r in got_gm)

    got_bk = []
    with obs.heartbeat(callback=got_bk.append):
        BisectingKMeans(k=4, max_iter=5, seed=0, mesh=mesh,
                        verbose=False).fit(X)
    assert any(r["phase"] == "split" for r in got_bk)

    got_mb = []
    with obs.heartbeat(callback=got_mb.append):
        MiniBatchKMeans(k=4, max_iter=5, batch_size=128, seed=0,
                        mesh=mesh, verbose=False).fit(X)
    assert any(r["phase"] == "iteration" and
               r["model_class"] == "MiniBatchKMeans" for r in got_mb)

    p = tmp_path / "ckpt.npz"
    got_ck = []
    with obs.heartbeat(callback=got_ck.append):
        KMeans(k=5, max_iter=6, tolerance=1e-12, seed=0, mesh=mesh,
               host_loop=False, empty_cluster="keep",
               verbose=False).fit(X, checkpoint_every=2,
                                  checkpoint_path=str(p))
    assert any(r["phase"] == "checkpoint" for r in got_ck)


def test_serving_spans(tmp_path):
    from kmeans_tpu.serving import ServingEngine
    mesh = _mesh(1)
    X = _blobs()
    km = KMeans(k=4, max_iter=5, seed=0, mesh=mesh,
                verbose=False).fit(X)
    with ServingEngine(mesh=mesh, start=False) as eng:
        eng.add_model("m", km)
        with obs.tracing() as tr:
            eng.predict("m", X[:8])
            fut = eng.submit("m", X[:4])
            eng.queue.service(now=float("inf"))
            fut.result()
    recs = tr.records()
    reqs = spans_named(recs, "serve.request")
    assert len(reqs) == 2
    assert reqs[0]["attrs"]["model"] == "m"
    flushes = spans_named(recs, "serve.flush")
    assert len(flushes) == 1
    # the flush's dispatch is nested under it
    assert any(r["parent"] == flushes[0]["id"] for r in reqs)


# ---------------------------------------------------------------------------
# Time-to-first-iteration report
# ---------------------------------------------------------------------------

def test_ttfi_ladder_and_table_from_real_fit():
    X = _blobs()
    with obs.tracing() as tr:
        KMeans(k=5, max_iter=4, seed=0, chunk_size=119,
               host_loop=False, empty_cluster="keep",
               verbose=False).fit(X)
    recs = tr.records()
    ladder = ttfi_ladder(recs)
    assert [r["phase"] for r in ladder] == [
        "place", "stage", "trace", "compile", "seed", "first_dispatch"]
    cums = [r["cumulative"] for r in ladder]
    assert cums == sorted(cums)
    assert ladder[-1]["seconds"] > 0
    rows = time_to_first_iteration(recs)
    assert len(rows) == 6
    total_share = sum(r["share"] for r in rows)
    assert total_share == pytest.approx(1.0, abs=1e-6)
    assert all(r["implied_ceiling_speedup"] >= 1.0 for r in rows)
    table = obs.format_phase_table(rows)
    assert "first_dispatch" in table and "TOTAL" in table


def test_ttfi_requires_a_dispatch_span():
    with obs.tracing() as tr:
        with obs.span("seed"):
            pass
    with pytest.raises(ValueError):
        ttfi_ladder(tr.records())


# ---------------------------------------------------------------------------
# CLI: python -m kmeans_tpu trace summarize
# ---------------------------------------------------------------------------

def _write_trace(tmp_path):
    X = _blobs(n=400)
    p = tmp_path / "fit.jsonl"
    with obs.tracing(str(p)):
        KMeans(k=4, max_iter=3, seed=0, host_loop=False,
               empty_cluster="keep", verbose=False).fit(X)
    return p


def test_cli_trace_summarize_table(tmp_path, capsys):
    from kmeans_tpu.cli import trace_main
    p = _write_trace(tmp_path)
    assert trace_main(["summarize", str(p)]) == 0
    out = capsys.readouterr().out
    assert "time-to-first-iteration" in out
    assert "dispatch" in out and "p99" in out


def test_cli_trace_summarize_json_and_chrome(tmp_path, capsys):
    from kmeans_tpu.cli import trace_main
    p = _write_trace(tmp_path)
    chrome = tmp_path / "chrome.json"
    assert trace_main(["summarize", str(p), "--json",
                       "--chrome", str(chrome)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert "phases" in doc and "time_to_first_iteration" in doc
    assert doc["time_to_first_iteration"][-1]["phase"] == "first_dispatch"
    cdoc = json.loads(chrome.read_text())
    assert cdoc["traceEvents"]


def test_cli_trace_exit_2_on_malformed(tmp_path, capsys):
    from kmeans_tpu.cli import trace_main
    bad = tmp_path / "bad.jsonl"
    bad.write_text("{broken\n")
    assert trace_main(["summarize", str(bad)]) == 2
    assert "error:" in capsys.readouterr().err
    assert trace_main(["summarize",
                       str(tmp_path / "missing.jsonl")]) == 2


def test_cli_trace_via_main(tmp_path, capsys, monkeypatch):
    import kmeans_tpu.__main__ as main_mod
    p = _write_trace(tmp_path)
    monkeypatch.setattr("sys.argv",
                        ["kmeans_tpu", "trace", "summarize", str(p)])
    assert main_mod.main() == 0
    assert "time-to-first-iteration" in capsys.readouterr().out


def test_cli_trace_no_dispatch_summary_only(tmp_path, capsys):
    """A trace without dispatch spans still summarizes (no TTFI
    section, no crash)."""
    from kmeans_tpu.cli import trace_main
    p = tmp_path / "nodisp.jsonl"
    with obs.tracing(str(p)):
        with obs.span("seed"):
            pass
    assert trace_main(["summarize", str(p)]) == 0
    out = capsys.readouterr().out
    assert "seed" in out and "time-to-first-iteration" not in out
