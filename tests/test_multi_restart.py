"""Multi-restart (``n_init``) semantics.

A beyond-reference capability (the reference draws Forgy once,
kmeans_spark.py:58-82): n_init independent restarts, winner = lowest TRUE
final inertia.  Two execution paths must agree: sequential restarts in the
host loop, and the batched one-dispatch device sweep
(parallel.distributed.make_multi_fit_fn, vmapped over the restart axis).
"""

import numpy as np
import pytest

from kmeans_tpu import KMeans, MiniBatchKMeans
from sklearn.datasets import make_blobs


def blobs(n_per=100, seed=0):
    rng = np.random.default_rng(seed)
    centers = np.array([[0.0, 0.0], [10.0, 10.0], [20.0, 0.0]])
    X = np.concatenate([c + 0.3 * rng.normal(size=(n_per, 2))
                        for c in centers])
    return X.astype(np.float32)


def final_inertia(km, X):
    return -km.score(X)


def test_n_init_picks_lowest_inertia():
    X = blobs()
    km = KMeans(k=3, max_iter=50, seed=7, n_init=6, verbose=False)
    km.fit(X)
    assert km.restart_inertias_.shape == (6,)
    assert km.best_restart_ == int(np.argmin(km.restart_inertias_))
    got = final_inertia(km, X)
    assert got == pytest.approx(km.restart_inertias_.min(), rel=1e-5)
    # The sweep can never be worse than the single reference draw.
    single = KMeans(k=3, max_iter=50, seed=7, verbose=False).fit(X)
    assert got <= final_inertia(single, X) + 1e-6


def test_n_init_deterministic():
    X = blobs()
    a = KMeans(k=3, max_iter=30, seed=3, n_init=4, verbose=False).fit(X)
    b = KMeans(k=3, max_iter=30, seed=3, n_init=4, verbose=False).fit(X)
    np.testing.assert_array_equal(a.centroids, b.centroids)
    assert a.best_restart_ == b.best_restart_
    np.testing.assert_array_equal(a.restart_inertias_, b.restart_inertias_)


def test_device_multi_matches_host_multi():
    X = blobs()
    kw = dict(k=3, max_iter=50, seed=11, n_init=5, empty_cluster="keep",
              verbose=False)
    host = KMeans(host_loop=True, **kw).fit(X)
    dev = KMeans(host_loop=False, **kw).fit(X)
    assert dev.best_restart_ == host.best_restart_
    np.testing.assert_allclose(
        np.sort(dev.restart_inertias_), np.sort(host.restart_inertias_),
        rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(dev.centroids)[np.lexsort(dev.centroids.T)],
        np.asarray(host.centroids)[np.lexsort(host.centroids.T)],
        atol=1e-3)


def test_device_multi_farthest_policy():
    # 3 tight blobs, k=6 forces empties (reference T4 shape,
    # kmeans_spark.py:513-524); the batched loop must stay finite.
    X = blobs()
    km = KMeans(k=6, max_iter=30, seed=5, n_init=3,
                empty_cluster="farthest", host_loop=False,
                verbose=False).fit(X)
    assert np.all(np.isfinite(km.centroids))
    assert km.restart_inertias_.shape == (3,)


def test_restart0_matches_single_seed():
    # Restart 0 uses `seed` itself, so a single-restart fit with the same
    # seed lands on the same final inertia as restart 0 of the sweep.
    X = blobs()
    multi = KMeans(k=3, max_iter=50, seed=13, n_init=4, verbose=False).fit(X)
    single = KMeans(k=3, max_iter=50, seed=13, verbose=False).fit(X)
    assert multi.restart_inertias_[0] == pytest.approx(
        final_inertia(single, X), rel=1e-5)


def test_explicit_init_collapses_to_one_restart():
    X = blobs()
    init = X[[0, 100, 200]]
    km = KMeans(k=3, max_iter=30, n_init=5, init=init, verbose=False).fit(X)
    assert km.restart_inertias_ is None        # single effective restart
    assert km.best_restart_ == 0


def test_n_init_with_sse_history():
    X = blobs()
    km = KMeans(k=3, max_iter=50, seed=7, n_init=3, compute_sse=True,
                verbose=False).fit(X)
    # History belongs to the winning restart and is monotone.
    assert len(km.sse_history) == km.iterations_run
    diffs = np.diff(km.sse_history)
    assert np.all(diffs <= 1e-6)


def test_invalid_n_init_raises():
    with pytest.raises(ValueError, match="n_init"):
        KMeans(k=3, n_init=0)


def test_minibatch_n_init_selects_best_candidate():
    """r4: MiniBatchKMeans n_init follows sklearn's semantics — score
    candidate inits, keep the lowest-inertia one, run ONE session (not
    full restarts).  A far-out explicit seed pool makes candidate
    quality differ deterministically."""
    X = blobs()
    mb1 = MiniBatchKMeans(k=4, n_init=1, seed=0, batch_size=256,
                          max_iter=30, verbose=False).fit(X)
    mb8 = MiniBatchKMeans(k=4, n_init=8, seed=0, batch_size=256,
                          max_iter=30, verbose=False).fit(X)
    assert mb8.init_inertias_.shape == (8,)
    assert mb8.best_init_ == int(np.argmin(mb8.init_inertias_))
    # The selected candidate's full-data inertia is the pool minimum, so
    # the chosen start is never worse than n_init=1's.
    assert mb8.init_inertias_[mb8.best_init_] <= mb8.init_inertias_[0]
    assert np.all(np.isfinite(mb8.centroids))
    assert mb1.init_inertias_ is None       # single candidate: unscored


def test_minibatch_n_init_host_engine():
    X = blobs()
    mb = MiniBatchKMeans(k=4, n_init=4, seed=1, batch_size=256,
                         max_iter=20, sampling="host",
                         verbose=False).fit(X)
    assert mb.init_inertias_.shape == (4,)
    assert np.all(np.isfinite(mb.centroids))


def test_bisecting_forwards_n_init():
    # n_init applies per bisection (sklearn semantics): the multi-restart
    # tree can never end up with higher total SSE than the single-draw one.
    from kmeans_tpu import BisectingKMeans
    X = blobs()
    kw = dict(k=4, max_iter=30, seed=2, compute_sse=True, verbose=False)
    single = BisectingKMeans(n_init=1, **kw).fit(X)
    multi = BisectingKMeans(n_init=4, **kw).fit(X)
    assert multi.sse_history[-1] <= single.sse_history[-1] + 1e-6
    assert np.all(np.isfinite(multi.centroids))


def test_fit_transform():
    X = blobs()
    km = KMeans(k=3, max_iter=30, verbose=False)
    D = km.fit_transform(X)
    assert D.shape == (X.shape[0], 3)
    np.testing.assert_allclose(D, km.transform(X), atol=1e-6)


def test_checkpoint_roundtrips_n_init(tmp_path):
    X = blobs()
    km = KMeans(k=3, max_iter=20, seed=1, n_init=3, verbose=False).fit(X)
    km.save(tmp_path / "m.npz")
    loaded = KMeans.load(tmp_path / "m.npz")
    assert loaded.n_init == 3
    np.testing.assert_array_equal(loaded.centroids, km.centroids)


def test_device_multi_resample_policy():
    """Batched n_init restarts with the on-device 'resample' refill
    (r1 VERDICT #6): per-(iteration, restart) keys, deterministic."""
    X, _ = make_blobs(n_samples=600, centers=3, n_features=2,
                      cluster_std=0.5, random_state=42)
    kw = dict(k=6, n_init=3, max_iter=20, seed=1, host_loop=False,
              empty_cluster="resample", compute_sse=True, verbose=False)
    a = KMeans(**kw).fit(X)
    b = KMeans(**kw).fit(X)
    assert np.all(np.isfinite(a.centroids))
    np.testing.assert_array_equal(a.centroids, b.centroids)
    assert a.best_restart_ == b.best_restart_


def test_device_multi_under_model_sharding(mesh4x2):
    """r1 VERDICT #3: batched n_init restarts now compose with model-axis
    centroid sharding — the sharded sweep must match the unsharded one."""
    X, _ = make_blobs(n_samples=1200, centers=4, n_features=6,
                      random_state=3)
    X = X.astype(np.float64)
    kw = dict(k=4, n_init=3, max_iter=20, seed=1, host_loop=False,
              compute_sse=True, empty_cluster="keep", verbose=False,
              dtype=np.float64)
    tp = KMeans(mesh=mesh4x2, **kw).fit(X)
    ref = KMeans(**kw).fit(X)          # auto mesh: data-parallel only
    assert tp.best_restart_ == ref.best_restart_
    np.testing.assert_allclose(tp.centroids, ref.centroids, atol=1e-9)
    np.testing.assert_allclose(tp.restart_inertias_, ref.restart_inertias_,
                               rtol=1e-9)


def test_device_multi_model_sharding_uneven_k(mesh4x2):
    """k=5 doesn't divide the model axis (2): sentinel padding rows must
    stay inert through the batched sweep."""
    X, _ = make_blobs(n_samples=800, centers=5, n_features=4,
                      random_state=4)
    km = KMeans(k=5, n_init=2, max_iter=15, seed=2, host_loop=False,
                mesh=mesh4x2, verbose=False,
                empty_cluster="farthest").fit(X.astype(np.float32))
    assert km.centroids.shape == (5, 4)
    assert np.all(np.isfinite(km.centroids))


def test_n_init_auto_follows_sklearn():
    """r4: n_init='auto' — 1 for D^2-seeded inits, 10 for plain random
    draws (sklearn's rule)."""
    assert KMeans(k=3, n_init="auto", init="forgy").n_init == 10
    assert KMeans(k=3, n_init="auto", init="k-means++").n_init == 1
    assert KMeans(k=3, n_init="auto", init="kmeans||").n_init == 1
    # MiniBatchKMeans resolves 'auto' to 3 (sklearn: inits are only
    # scored, not trained), via the _auto_n_init hook (advisor r4).
    assert MiniBatchKMeans(k=3, n_init="auto", init="forgy").n_init == 3
    assert MiniBatchKMeans(k=3, n_init="auto", init="k-means++").n_init == 1
    with pytest.raises(ValueError, match="auto"):
        KMeans(k=3, n_init="bogus")
