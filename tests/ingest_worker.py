"""Worker for the multi-process streamed-ingest tests (not a test module
itself — spawned by tests/test_ingest.py).

Modes (argv[5], default ``parity``):

* ``parity`` — each process streams ONLY its own local shards of the
  shared ``.npy`` (``ingest='slab'``: the per-host O(slab) path), checks
  them bitwise against the blocking mono oracle and the source rows,
  device-synthesizes its shards of a second dataset against the host
  oracle, then fits with a shared explicit init and writes its centroids
  for the parent's cross-process bitwise comparison.
* ``kill-fit`` — streamed-ingest fit with ``checkpoint_every=1`` and a
  deterministic ``inject_kill_after_iteration`` preemption: every
  process dies mid-fit (exit 75) leaving the rotating checkpoint — the
  ISSUE 19 shrink scenario's first act.
* ``resume-fit`` — run at a SMALLER world (2 -> 1): the process must
  re-derive its streamed block ranges for the new world (its slab
  shards now cover ALL rows), ``fit(resume=)`` from the checkpoint the
  larger fleet left, and land bit-exact on the uninterrupted
  same-world oracle.
"""

import os
import sys
from pathlib import Path

import numpy as np

proc_id = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]
tmp_dir = Path(sys.argv[4])
mode = sys.argv[5] if len(sys.argv) > 5 else "parity"

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
if mode != "parity":
    # The shrink/resume matrix runs in f64: resume parity across a
    # WORLD-SIZE change must be bitwise, like the autopilot chaos tier.
    jax.config.update("jax_enable_x64", True)

from kmeans_tpu.parallel.multihost import initialize, is_primary  # noqa: E402

initialize(coordinator_address=f"127.0.0.1:{port}",
           num_processes=nproc, process_id=proc_id)
assert jax.process_count() == nproc

from kmeans_tpu import KMeans  # noqa: E402
from kmeans_tpu.data import synthetic as synth  # noqa: E402
from kmeans_tpu.data.io import from_npy  # noqa: E402
from kmeans_tpu.parallel.mesh import make_mesh  # noqa: E402
from kmeans_tpu.utils import faults  # noqa: E402

mesh = make_mesh()
path = tmp_dir / "global.npy"
X = np.load(path)                         # oracle only — ingest reads mm


def shared_fit_model(**kw):
    """The fit every mode shares: explicit init from the source rows so
    all processes/worlds start identically."""
    rng = np.random.default_rng(1)
    init = X[rng.choice(X.shape[0], size=4, replace=False)]
    return KMeans(k=4, max_iter=6, tolerance=1e-12, seed=0, init=init,
                  empty_cluster="keep", host_loop=False,
                  verbose=is_primary(), **kw)


ckpt = tmp_dir / "ingest_ck.npz"

if mode == "kill-fit":
    ds = from_npy(path, mesh, chunk_size=32, ingest="slab")
    with faults.inject_kill_after_iteration(3):
        try:
            shared_fit_model().fit(ds, checkpoint_every=1,
                                   checkpoint_path=ckpt)
        except faults.SimulatedPreemption:
            print(f"worker {proc_id}/{nproc} preempted OK", flush=True)
            sys.exit(75)
    print(f"worker {proc_id}/{nproc} was never preempted", flush=True)
    sys.exit(1)

if mode == "resume-fit":
    # The shrunk world re-derives its streamed block ranges from
    # scratch: this process's slab shards must now tile ALL rows.
    ds = from_npy(path, mesh, chunk_size=32, ingest="slab")
    spans = sorted((s.index[0].start or 0,
                    min(s.index[0].stop, X.shape[0]))
                   for s in ds.points.addressable_shards)
    covered = 0
    for lo, hi in spans:
        assert lo <= covered, f"gap/overlap at {lo} (covered {covered})"
        covered = max(covered, hi)
    assert covered == X.shape[0], (covered, X.shape[0])
    for s in ds.points.addressable_shards:
        lo = s.index[0].start or 0
        hi = min(s.index[0].stop, X.shape[0])
        if hi > lo:
            np.testing.assert_array_equal(
                np.asarray(s.data)[: hi - lo], X[lo:hi])

    resumed = shared_fit_model().fit(ds, resume=ckpt)
    oracle = shared_fit_model().fit(
        from_npy(path, mesh, chunk_size=32, ingest="slab"))
    assert resumed.iterations_run == oracle.iterations_run
    np.testing.assert_array_equal(np.asarray(resumed.centroids),
                                  np.asarray(oracle.centroids))
    np.save(tmp_dir / f"resume_centroids_{proc_id}.npy",
            np.asarray(resumed.centroids))
    print(f"worker {proc_id}/{nproc} resume OK", flush=True)
    sys.exit(0)

# Streamed per-host ingest vs the blocking mono oracle: every LOCAL
# shard must be bitwise identical (each process checks only bytes it
# owns — the touch-only-local-bytes contract).
ds_slab = from_npy(path, mesh, chunk_size=32, ingest="slab")
ds_mono = from_npy(path, mesh, chunk_size=32, ingest="mono")
assert ds_slab.n == X.shape[0]
slab_shards = sorted(ds_slab.points.addressable_shards,
                     key=lambda s: s.index[0].start or 0)
mono_shards = sorted(ds_mono.points.addressable_shards,
                     key=lambda s: s.index[0].start or 0)
assert len(slab_shards) == len(mono_shards) > 0
for a, b in zip(slab_shards, mono_shards):
    assert a.index == b.index
    np.testing.assert_array_equal(np.asarray(a.data), np.asarray(b.data))
    lo = a.index[0].start or 0
    hi = min(a.index[0].stop, X.shape[0])
    if hi > lo:
        np.testing.assert_array_equal(
            np.asarray(a.data)[: hi - lo], X[lo:hi])

# On-device synthesis: local shards equal the host oracle's rows (the
# partition-invariant fold_in stream crosses process boundaries too).
n_syn, d_syn = 640, 4
ds_syn = synth.device_shards(n_syn, d_syn, mesh=mesh, kind="uniform",
                             seed=5, chunk_size=16)
host_syn = synth.host_equivalent(n_syn, d_syn, kind="uniform", seed=5)
for s in ds_syn.points.addressable_shards:
    lo = s.index[0].start or 0
    hi = min(s.index[0].stop, n_syn)
    if hi > lo:
        np.testing.assert_array_equal(
            np.asarray(s.data)[: hi - lo], host_syn[lo:hi])

# Fit on the streamed dataset with a shared explicit init: every
# process must land on identical centroids.
km = shared_fit_model().fit(ds_slab)
np.save(tmp_dir / f"ingest_centroids_{proc_id}.npy",
        np.asarray(km.centroids))
print(f"worker {proc_id}/{nproc} OK", flush=True)
