"""On-device training loop (``host_loop=False``): the whole fit runs in one
dispatch under ``lax.while_loop``.  Must agree with the host loop — same
trajectory, same iteration count, same SSE history — across mesh layouts and
device-expressible empty-cluster policies.
"""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans


@pytest.fixture(scope="module")
def data():
    X, _ = make_blobs(n_samples=3000, centers=5, n_features=8,
                      random_state=11)
    return X


def _fit(mesh, data, host_loop, **kw):
    kw.setdefault("empty_cluster", "keep")
    km = KMeans(k=5, max_iter=25, seed=42, compute_sse=True, mesh=mesh,
                dtype=np.float64, host_loop=host_loop, verbose=False, **kw)
    return km.fit(data)


@pytest.mark.parametrize("mesh_name", ["mesh1", "mesh8", "mesh4x2"])
def test_device_loop_matches_host_loop(data, mesh_name, request):
    mesh = request.getfixturevalue(mesh_name)
    host = _fit(mesh, data, True)
    dev = _fit(mesh, data, False)
    assert dev.iterations_run == host.iterations_run
    np.testing.assert_allclose(dev.centroids, host.centroids, atol=1e-9)
    np.testing.assert_allclose(dev.sse_history, host.sse_history, rtol=1e-9)


def test_device_loop_farthest_policy(mesh8):
    # Over-clustered fixture (the reference's T4 scenario) with the
    # farthest-point refill running fully on device.
    X, _ = make_blobs(n_samples=800, centers=3, n_features=2,
                      cluster_std=0.5, random_state=42)
    km = KMeans(k=6, max_iter=30, seed=42, compute_sse=True,
                empty_cluster="farthest", mesh=mesh8, host_loop=False,
                verbose=False).fit(X)
    assert np.all(np.isfinite(km.centroids))
    assert km.centroids.shape == (6, 2)


def test_device_loop_resample_policy(mesh8):
    """r1 VERDICT #6: 'resample' now runs fully on device (seeded Gumbel-
    argmax refill) — finite result, bit-deterministic across runs."""
    X, _ = make_blobs(n_samples=800, centers=3, n_features=2,
                      cluster_std=0.5, random_state=42)
    kw = dict(k=6, max_iter=30, seed=42, compute_sse=True,
              empty_cluster="resample", mesh=mesh8, host_loop=False,
              verbose=False)
    a = KMeans(**kw).fit(X)
    b = KMeans(**kw).fit(X)
    assert np.all(np.isfinite(a.centroids))
    np.testing.assert_array_equal(a.centroids, b.centroids)


def test_device_loop_resample_uses_a_data_point(mesh8):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 2)).astype(np.float64)
    init = np.array([[0.0, 0.0], [0.5, 0.5], [1e3, 1e3]])
    km = KMeans(k=3, max_iter=1, init=init, empty_cluster="resample",
                mesh=mesh8, dtype=np.float64, host_loop=False,
                verbose=False).fit(X)
    replaced = km.centroids[2]
    assert np.any(np.all(np.isclose(X, replaced[None, :], atol=1e-9),
                         axis=1))


def _hostless(km, X):
    """Cache X and drop the host copy, so the host loop's 'resample'
    routes through the device Gumbel engine — the one the device loop
    bit-matches."""
    ds = km.cache(X)
    ds._host = None
    ds._host_weights = None
    return ds


@pytest.mark.parametrize("mesh_name", ["mesh1", "mesh8", "mesh4x2"])
@pytest.mark.parametrize("policy", ["resample", "farthest"])
def test_multi_empty_refill_matches_host_loop(mesh_name, policy, request):
    """r2 VERDICT #2: >=3 SIMULTANEOUS empties must all refill in ONE
    device-loop iteration, drawing the same rows in the same order as the
    host loop (kmeans_spark.py:196-200 samples all replacements at once).
    Three far-away init rows capture nothing on iteration 1, forcing
    three empties at once; trajectories must then agree exactly."""
    from conftest import old_jax_rng_streams
    if old_jax_rng_streams and policy == "resample" \
            and mesh_name == "mesh4x2":
        # Only this cell depends on the host and device refill engines
        # drawing IDENTICAL keyed rows under a TP (model-sharded) mesh;
        # jax < 0.5 derives a different threefry stream there than the
        # >= 0.5 releases the exact-parity pin was recorded on (the
        # refill itself is verified by the finite/near-data asserts in
        # every other cell).  BASELINE.md "Tier-1 environment gates".
        pytest.skip("jax < 0.5 keyed-sampling stream differs under TP "
                    "meshes — exact device/host refill-row parity is "
                    "pinned on jax >= 0.5 streams")
    mesh = request.getfixturevalue(mesh_name)
    rng = np.random.default_rng(3)
    X = rng.normal(size=(240, 4))
    init = np.concatenate(
        [X[:3], 1e3 * np.arange(1, 4, dtype=float)[:, None]
         + np.arange(4, dtype=float)[None, :]])

    def run(host_loop):
        km = KMeans(k=6, max_iter=12, seed=7, compute_sse=True, init=init,
                    empty_cluster=policy, mesh=mesh, dtype=np.float64,
                    host_loop=host_loop, verbose=False)
        return km.fit(_hostless(km, X))

    host, dev = run(True), run(False)
    # The refill really happened: all six centroids are finite and near
    # the data, not the 1e3-scale init rows.
    assert np.all(np.isfinite(dev.centroids))
    assert np.abs(dev.centroids).max() < 100
    assert dev.iterations_run == host.iterations_run
    np.testing.assert_allclose(dev.centroids, host.centroids, atol=1e-9)
    np.testing.assert_allclose(dev.sse_history, host.sse_history,
                               rtol=1e-9)


def test_empty_refill_exhaustion_keeps_old_centroids(mesh8):
    """More empties than positive-weight rows: draws stop when the
    without-replacement mask is exhausted and the surplus slots keep
    their old centroids (the host under-return rule, kmeans_spark.py:
    201-204) — identically on the host and device loops."""
    rng = np.random.default_rng(9)
    X = rng.normal(size=(40, 2))
    w = np.zeros(40)
    w[:4] = 1.0                      # only 4 rows may become centroids
    init = np.concatenate(
        [X[:2], 1e3 * np.arange(1, 7, dtype=float)[:, None]
         + np.zeros((6, 2))])        # 6 far slots -> 6 empties, 4 draws

    def run(host_loop):
        # max_iter=1: the replacement pool refreshes every iteration (host
        # semantics), so retention is only observable on a single step.
        km = KMeans(k=8, max_iter=1, seed=13, init=init,
                    empty_cluster="resample", mesh=mesh8,
                    dtype=np.float64, host_loop=host_loop, verbose=False)
        ds = km.cache(X, sample_weight=w)
        ds._host = None
        ds._host_weights = None
        return km.fit(ds)

    host, dev = run(True), run(False)
    np.testing.assert_allclose(dev.centroids, host.centroids, atol=1e-9)
    far = np.abs(dev.centroids).max(axis=1) > 100
    assert far.sum() == 2, dev.centroids   # 4 refilled, 2 kept old
    # Every refilled slot holds a POSITIVE-weight row, never a w=0 row.
    for row in dev.centroids[~far][2:]:
        assert np.any(np.all(np.isclose(X[:4], row[None, :], atol=1e-9),
                             axis=1))


def test_multi_restart_empty_refill_matches_host(mesh8):
    """Batched n_init restarts refill empties exactly like the host's
    sequential restarts: per-restart draw keys, all slots per iteration."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(256, 3))

    def far_init(src, k, seed):
        rs = np.random.RandomState(seed)
        base = np.array(X[rs.choice(len(X), size=k, replace=False)])
        base[-3:] = (1e3 * (1 + seed % 7)
                     + np.arange(3 * 3, dtype=float).reshape(3, 3))
        return base

    def run(host_loop):
        km = KMeans(k=6, max_iter=10, seed=11, n_init=3, compute_sse=True,
                    init=far_init, empty_cluster="resample", mesh=mesh8,
                    dtype=np.float64, host_loop=host_loop, verbose=False)
        return km.fit(_hostless(km, X))

    host, dev = run(True), run(False)
    assert host.best_restart_ == dev.best_restart_
    np.testing.assert_allclose(dev.restart_inertias_,
                               host.restart_inertias_, rtol=1e-9)
    np.testing.assert_allclose(dev.centroids, host.centroids, atol=1e-9)


def test_device_loop_resume_draws_same_refill_sequence(mesh8):
    """A fit interrupted and resumed must draw the SAME empty-refill
    rows an uninterrupted fit would: the per-iteration seed schedule is
    keyed by ABSOLUTE iteration ([seed, iter+1]), and the resumed
    program receives the offset schedule as a traced argument."""
    rng = np.random.default_rng(21)
    X = rng.normal(size=(240, 3))
    init = np.concatenate(
        [X[:2], 1e3 * np.arange(1, 4, dtype=float)[:, None]
         + np.zeros((3, 3))])
    kw = dict(k=5, seed=17, init=init, empty_cluster="resample",
              compute_sse=True, tolerance=1e-12, mesh=mesh8,
              dtype=np.float64, host_loop=False, verbose=False)

    def hostless(km):
        ds = km.cache(X)
        ds._host = None
        ds._host_weights = None
        return ds

    full = KMeans(max_iter=9, **kw)
    full.fit(hostless(full))

    part = KMeans(max_iter=4, **kw)
    part.fit(hostless(part))
    part.max_iter = 9
    part.fit(hostless(part), resume=True)

    assert part.iterations_run == full.iterations_run
    np.testing.assert_allclose(part.centroids, full.centroids, atol=1e-9)


def test_device_loop_early_convergence(mesh8):
    X, _ = make_blobs(n_samples=2000, centers=3, n_features=2,
                      random_state=0, cluster_std=0.3)
    km = KMeans(k=3, max_iter=100, tolerance=1e-4, seed=1, mesh=mesh8,
                empty_cluster="keep", host_loop=False, verbose=False).fit(X)
    assert 1 <= km.iterations_run < 100
