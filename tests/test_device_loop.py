"""On-device training loop (``host_loop=False``): the whole fit runs in one
dispatch under ``lax.while_loop``.  Must agree with the host loop — same
trajectory, same iteration count, same SSE history — across mesh layouts and
device-expressible empty-cluster policies.
"""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans


@pytest.fixture(scope="module")
def data():
    X, _ = make_blobs(n_samples=3000, centers=5, n_features=8,
                      random_state=11)
    return X


def _fit(mesh, data, host_loop, **kw):
    kw.setdefault("empty_cluster", "keep")
    km = KMeans(k=5, max_iter=25, seed=42, compute_sse=True, mesh=mesh,
                dtype=np.float64, host_loop=host_loop, verbose=False, **kw)
    return km.fit(data)


@pytest.mark.parametrize("mesh_name", ["mesh1", "mesh8", "mesh4x2"])
def test_device_loop_matches_host_loop(data, mesh_name, request):
    mesh = request.getfixturevalue(mesh_name)
    host = _fit(mesh, data, True)
    dev = _fit(mesh, data, False)
    assert dev.iterations_run == host.iterations_run
    np.testing.assert_allclose(dev.centroids, host.centroids, atol=1e-9)
    np.testing.assert_allclose(dev.sse_history, host.sse_history, rtol=1e-9)


def test_device_loop_farthest_policy(mesh8):
    # Over-clustered fixture (the reference's T4 scenario) with the
    # farthest-point refill running fully on device.
    X, _ = make_blobs(n_samples=800, centers=3, n_features=2,
                      cluster_std=0.5, random_state=42)
    km = KMeans(k=6, max_iter=30, seed=42, compute_sse=True,
                empty_cluster="farthest", mesh=mesh8, host_loop=False,
                verbose=False).fit(X)
    assert np.all(np.isfinite(km.centroids))
    assert km.centroids.shape == (6, 2)


def test_device_loop_resample_policy(mesh8):
    """r1 VERDICT #6: 'resample' now runs fully on device (seeded Gumbel-
    argmax refill) — finite result, bit-deterministic across runs."""
    X, _ = make_blobs(n_samples=800, centers=3, n_features=2,
                      cluster_std=0.5, random_state=42)
    kw = dict(k=6, max_iter=30, seed=42, compute_sse=True,
              empty_cluster="resample", mesh=mesh8, host_loop=False,
              verbose=False)
    a = KMeans(**kw).fit(X)
    b = KMeans(**kw).fit(X)
    assert np.all(np.isfinite(a.centroids))
    np.testing.assert_array_equal(a.centroids, b.centroids)


def test_device_loop_resample_uses_a_data_point(mesh8):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 2)).astype(np.float64)
    init = np.array([[0.0, 0.0], [0.5, 0.5], [1e3, 1e3]])
    km = KMeans(k=3, max_iter=1, init=init, empty_cluster="resample",
                mesh=mesh8, dtype=np.float64, host_loop=False,
                verbose=False).fit(X)
    replaced = km.centroids[2]
    assert np.any(np.all(np.isclose(X, replaced[None, :], atol=1e-9),
                         axis=1))


def test_device_loop_early_convergence(mesh8):
    X, _ = make_blobs(n_samples=2000, centers=3, n_features=2,
                      random_state=0, cluster_std=0.3)
    km = KMeans(k=3, max_iter=100, tolerance=1e-4, seed=1, mesh=mesh8,
                empty_cluster="keep", host_loop=False, verbose=False).fit(X)
    assert 1 <= km.iterations_run < 100
