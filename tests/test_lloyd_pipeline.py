"""Pipelined Lloyd E-step + guarded bf16 distance rung (ISSUE 8): the
software-pipelined two-stage chunk schedule (``pipeline=1``) against the
serial oracle (``pipeline=0``), and ``distance_mode='matmul_bf16_guarded'``
against its f32 'matmul' twin — the ``prefetch=0`` / ``checkpoint_every=0``
discipline: both knobs move WHERE work happens (or at what rate the
distance tile computes), never the arithmetic of any label, sum, or count,
so trajectories must match the oracle bit-for-bit."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kmeans_tpu.models import KMeans, MiniBatchKMeans, SphericalKMeans
from kmeans_tpu.ops import assign
from kmeans_tpu.parallel import distributed as dist
from kmeans_tpu.parallel.mesh import make_mesh


def _blobs(n=2048, d=8, centers=5, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    comp = rng.integers(0, centers, n)
    return (comp[:, None] * 4.0
            + rng.normal(size=(n, d))).astype(dtype)


def _fit_pair(mesh, *, cls=KMeans, host_loop=False, k=5, X=None,
              sample_weight=None, chunk=256, max_iter=8, dtype=None,
              pipeline_on=1, **extra):
    """Fit the same model under both schedules; returns (pipelined,
    serial)."""
    out = []
    for pipeline in (pipeline_on, 0):
        m = cls(k=k, max_iter=max_iter, tolerance=1e-7, seed=0,
                compute_sse=True, mesh=mesh, chunk_size=chunk,
                host_loop=host_loop, pipeline=pipeline, verbose=False,
                dtype=dtype, **extra)
        m.fit(_blobs() if X is None else X, sample_weight=sample_weight)
        out.append(m)
    return out


def _assert_trajectory_equal(a, b):
    assert a.iterations_run == b.iterations_run
    np.testing.assert_array_equal(a.centroids, b.centroids)
    assert a.sse_history == b.sse_history
    np.testing.assert_array_equal(a.labels_, b.labels_)


def _assert_guard_trajectory_equal(g, f):
    """The guarded rung's bit-exact contract: labels, centroids, and
    iteration counts.  SSE reads the winner's full-precision distance
    (``ops.assign._winner_sq_dists``) — the value equals the f32-class
    min(d2) up to the dot's reduction order, so the history lands in
    the repo's documented rtol-compared class, not the bitwise one."""
    assert g.iterations_run == f.iterations_run
    np.testing.assert_array_equal(g.centroids, f.centroids)
    np.testing.assert_array_equal(g.labels_, f.labels_)
    np.testing.assert_allclose(g.sse_history, f.sse_history, rtol=1e-5)


# --------------------------------------------------- pipelined schedule

@pytest.mark.parametrize("host_loop", [True, False])
def test_pipeline_parity_host_and_device_loops(host_loop, mesh1):
    m1, m0 = _fit_pair(mesh1, host_loop=host_loop)
    assert m1.estep_path_ == "pipelined" and m0.estep_path_ == "serial"
    _assert_trajectory_equal(m1, m0)


@pytest.mark.parametrize("data_shards", [1, 2, 4, 8])
def test_pipeline_parity_data_meshes(data_shards):
    """1/2/4/8-way data-parallel virtual meshes in the f64 device-loop
    class: per-shard chunking differs with the width, so the schedules
    must agree at each (the acceptance-criteria mesh matrix)."""
    if len(jax.devices()) < data_shards:
        pytest.skip(f"needs {data_shards} devices")
    mesh = make_mesh(data=data_shards, model=1,
                     devices=jax.devices()[:data_shards])
    X = _blobs(n=2048, dtype=np.float64)
    m1, m0 = _fit_pair(mesh, X=X, chunk=128, dtype=np.float64)
    _assert_trajectory_equal(m1, m0)


def test_pipeline_parity_model_sharded_with_padding(mesh4x2):
    """Centroid (TP) sharding with k=5 on a 2-way model axis ->
    k_pad=6: the sentinel padding row rides the carried distance tile
    through the skewed schedule and must stay inert in both."""
    m1, m0 = _fit_pair(mesh4x2, k=5, X=_blobs(n=2048),
                       empty_cluster="keep")
    _assert_trajectory_equal(m1, m0)


def test_pipeline_parity_spherical(mesh8):
    X = _blobs(n=2048)
    m1, m0 = _fit_pair(mesh8, cls=SphericalKMeans, X=X, chunk=128)
    assert m1.estep_path_ == "pipelined"
    _assert_trajectory_equal(m1, m0)


def test_pipeline_parity_weighted_zero_tail(mesh1):
    """Zero-weight rows (the padding contract) contribute nothing under
    either schedule — including as the FINAL chunk, which the pipelined
    epilogue drains outside the scan."""
    X = _blobs(n=1536)
    w = np.ones(X.shape[0], np.float64)
    w[-300:] = 0.0                      # zero tail crosses chunk edges
    m1, m0 = _fit_pair(mesh1, X=X, sample_weight=w)
    _assert_trajectory_equal(m1, m0)


def test_pipeline_parity_batched_restarts(mesh1):
    """The batched n_init device multi-fit threads pipeline through the
    vmapped member loop; restart selection must agree."""
    X = _blobs(n=1024)
    fits = []
    for pipeline in (1, 0):
        m = KMeans(k=4, max_iter=6, tolerance=1e-7, seed=0, n_init=3,
                   init="forgy", compute_sse=True, mesh=mesh1,
                   chunk_size=256, host_loop=False, pipeline=pipeline,
                   verbose=False).fit(X)
        fits.append(m)
    m1, m0 = fits
    assert m1.best_restart_ == m0.best_restart_
    np.testing.assert_array_equal(m1.restart_inertias_,
                                  m0.restart_inertias_)
    _assert_trajectory_equal(m1, m0)


def test_pipeline_parity_fit_stream(mesh1):
    X = _blobs(n=1200)

    def blocks():
        for i in range(0, X.shape[0], 400):
            yield X[i:i + 400]

    fits = []
    for pipeline in (1, 0):
        m = KMeans(k=4, max_iter=5, tolerance=1e-7, seed=0,
                   compute_sse=True, mesh=mesh1, chunk_size=200,
                   pipeline=pipeline, verbose=False)
        m.fit_stream(blocks, d=X.shape[1], prefetch=0)
        fits.append(m)
    m1, m0 = fits
    assert m1.estep_path_ == "pipelined"
    assert m1.iterations_run == m0.iterations_run
    np.testing.assert_array_equal(m1.centroids, m0.centroids)
    assert m1.sse_history == m0.sse_history


def test_pipeline_parity_checkpoint_segmented(tmp_path, mesh1):
    """pipeline x checkpoint_every interplay: the segmented device loop
    re-dispatches mid-fit; each segment must run the same schedule and
    the segmented pipelined fit must equal the one-dispatch serial fit
    bit-for-bit (checkpoint_every=0 is already pinned bit-identical)."""
    X = _blobs(n=1024)
    fits = []
    for pipeline in (1, 0):
        m = KMeans(k=4, max_iter=6, tolerance=1e-7, seed=0,
                   compute_sse=True, mesh=mesh1, chunk_size=256,
                   host_loop=False, pipeline=pipeline, verbose=False)
        m.fit(X, checkpoint_every=2,
              checkpoint_path=tmp_path / f"ck{pipeline}.npz")
        fits.append(m)
    m1, m0 = fits
    assert m1.checkpoint_segments_ == m0.checkpoint_segments_ >= 2
    _assert_trajectory_equal(m1, m0)


def test_single_chunk_pipeline(mesh1):
    """One chunk = prologue + empty scan + epilogue; must equal serial."""
    m1, m0 = _fit_pair(mesh1, X=_blobs(n=512), chunk=512, max_iter=5)
    _assert_trajectory_equal(m1, m0)


def test_step_level_bit_parity(mesh1):
    """Dispatch-level: the two schedules' StepStats are bit-identical
    (not merely trajectory-close), weighted, with every optional
    statistic on."""
    rng = np.random.default_rng(1)
    n, d, k, chunk = 2048, 8, 4, 256
    x = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    w = jnp.asarray(rng.uniform(0, 2, size=(n,)), jnp.float32)
    cents = jnp.asarray(rng.normal(size=(k, d)), jnp.float32)
    s0 = dist.make_step_fn(mesh1, chunk_size=chunk, pipeline=0)(x, w, cents)
    s1 = dist.make_step_fn(mesh1, chunk_size=chunk, pipeline=1)(x, w, cents)
    for name in s0._fields:
        np.testing.assert_array_equal(np.asarray(getattr(s0, name)),
                                      np.asarray(getattr(s1, name)),
                                      err_msg=name)


def test_pipeline_knob_validation_params_and_auto():
    with pytest.raises(ValueError, match="pipeline"):
        KMeans(k=2, pipeline=2)
    with pytest.raises(ValueError, match="pipeline"):
        KMeans(k=2, pipeline="yes")
    m = KMeans(k=2, verbose=False)
    assert m.pipeline == "auto"
    assert m.get_params()["pipeline"] == "auto"
    m.set_params(pipeline=0)
    assert m.pipeline == 0
    # 'auto' resolves by platform: serial on CPU (nothing to overlap —
    # the r8 measured-rejection precedent), pipelined on accelerators.
    m.set_params(pipeline="auto")
    expected = 0 if jax.default_backend() == "cpu" else 1
    assert m._resolve_pipeline() == expected


def test_pipeline_save_load_roundtrip(tmp_path, mesh1):
    X = _blobs(n=512)
    m = KMeans(k=3, max_iter=4, seed=0, mesh=mesh1, chunk_size=256,
               pipeline=1, verbose=False).fit(X)
    p = tmp_path / "km.npz"
    m.save(p)
    loaded = KMeans.load(p)
    assert loaded.pipeline == 1
    np.testing.assert_array_equal(loaded.centroids, m.centroids)
    m_auto = KMeans(k=3, max_iter=2, seed=0, mesh=mesh1, chunk_size=256,
                    verbose=False).fit(X)
    m_auto.save(p)
    assert KMeans.load(p).pipeline == "auto"


def test_minibatch_pipeline_degenerates_to_serial(mesh1):
    """The mini-batch statistics pass is ONE scan chunk, so the knob is
    accepted but the recorded path is what actually runs: serial."""
    X = _blobs(n=2048)
    fits = []
    for pipeline in (1, 0):
        m = MiniBatchKMeans(k=4, max_iter=10, seed=0, batch_size=512,
                            mesh=mesh1, pipeline=pipeline,
                            verbose=False).fit(X)
        fits.append(m)
    m1, m0 = fits
    assert m1.estep_path_ == m0.estep_path_ == "serial"
    assert m1.iterations_run == m0.iterations_run
    np.testing.assert_array_equal(m1.centroids, m0.centroids)


# ------------------------------------------------- guarded bf16 rung

def _close_pair_init(X, k):
    """Init whose rows 0/1 are a deliberately CLOSE centroid pair: the
    exact midpoint of that pair is a guaranteed near-tie at iteration 1
    (a midpoint of an arbitrary pair is not — a third centroid can sit
    closer), so salting the data with copies of it makes the guard
    demonstrably fire — the r11 serving-test pattern, now aimed at the
    training path."""
    init = np.asarray(X[:k], X.dtype).copy()
    init[1] = init[0] + np.asarray(1e-3, X.dtype)
    mids = np.repeat(((init[0] + init[1]) / 2.0)[None], 8, axis=0)
    return init, np.concatenate([X, mids.astype(X.dtype)])


def _guard_pair(mesh, X, init, *, k=5, max_iter=6, n_init=1,
                host_loop=False):
    out = []
    for mode in ("matmul_bf16_guarded", "matmul"):
        m = KMeans(k=k, max_iter=max_iter, tolerance=1e-7, seed=0,
                   init=init, n_init=n_init, compute_sse=True,
                   empty_cluster="keep", mesh=mesh, chunk_size=256,
                   host_loop=host_loop, distance_mode=mode,
                   verbose=False).fit(X)
        out.append(m)
    return out


def test_guard_fires_and_trajectory_stays_bit_equal(mesh1):
    """The Voronoi-midpoint regression: the guard FIRES (corrected rows
    counted in the audit attr) while centroids, labels, SSE decisions
    and iteration counts stay bit-equal to the f32 class — the
    by-construction contract, exercised on data where plain bf16 argmin
    WOULD flip labels."""
    X = _blobs(n=2048)
    init, Xg = _close_pair_init(X, 5)
    mg, mf = _guard_pair(mesh1, Xg, init)
    assert mg.bf16_guard_corrected_rows_ > 0       # the guard fired
    assert mf.bf16_guard_corrected_rows_ is None   # f32 class: no audit
    _assert_guard_trajectory_equal(mg, mf)


def test_guard_parity_multiway_mesh(mesh8):
    """Guarded rung on the multi-shard data-parallel mesh: per-shard
    guard counts psum into one replicated audit; parity holds across
    shard boundaries (chunk edges differ from the 1-way mesh)."""
    X = _blobs(n=2048)
    init, Xg = _close_pair_init(X, 5)
    mg, mf = _guard_pair(mesh8, Xg, init)
    assert mg.bf16_guard_corrected_rows_ > 0
    _assert_guard_trajectory_equal(mg, mf)


def test_guard_parity_batched_restarts(mesh1):
    """lax.map member loop (NOT vmap — a vmapped cond would pay the f32
    correction tile for every chunk of every member): audit sums over
    members, selection bit-agrees with the f32 class."""
    X = _blobs(n=1024, centers=4)
    fits = []
    for mode in ("matmul_bf16_guarded", "matmul"):
        m = KMeans(k=4, max_iter=5, tolerance=1e-7, seed=0, n_init=3,
                   init="forgy", compute_sse=True, empty_cluster="keep",
                   mesh=mesh1, chunk_size=256, host_loop=False,
                   distance_mode=mode, verbose=False).fit(X)
        fits.append(m)
    mg, mf = fits
    assert mg.bf16_guard_corrected_rows_ is not None
    assert mg.best_restart_ == mf.best_restart_
    _assert_guard_trajectory_equal(mg, mf)


def test_guard_predict_matches_f32_on_near_ties(mesh1):
    """predict under the guarded rung runs the chunk-level guard too:
    Voronoi-midpoint probes label bit-equal to the f32 class."""
    X = _blobs(n=1024)
    m = KMeans(k=5, max_iter=10, seed=0, mesh=mesh1, chunk_size=256,
               verbose=False).fit(X)
    C = np.asarray(m.centroids, np.float64)
    rng = np.random.default_rng(0)
    probe = np.asarray(
        [(C[i] + C[j]) / 2.0 * (1.0 + 1e-4 * rng.standard_normal())
         for i in range(len(C)) for j in range(i + 1, len(C))],
        np.float32)
    mq = KMeans(k=5, max_iter=1, seed=0, mesh=mesh1, chunk_size=256,
                distance_mode="matmul_bf16_guarded", verbose=False)
    mq.centroids = np.asarray(m.centroids)
    np.testing.assert_array_equal(mq.predict(probe), m.predict(probe))


def test_guard_transform_and_score_map_to_f32_class(mesh1):
    """Distance VALUES are the output of transform/score — the guarded
    rung's value surface IS the f32 class (the kmeans.py serve-mode
    table rule), so both must equal the 'matmul' results bitwise."""
    X = _blobs(n=1024)
    mf = KMeans(k=4, max_iter=8, seed=0, mesh=mesh1, chunk_size=256,
                distance_mode="matmul", verbose=False).fit(X)
    mg = KMeans(k=4, max_iter=8, seed=0, mesh=mesh1, chunk_size=256,
                distance_mode="matmul_bf16_guarded", verbose=False)
    mg.centroids = np.asarray(mf.centroids)
    np.testing.assert_array_equal(mg.transform(X[:256]),
                                  mf.transform(X[:256]))
    assert mg.score(X[:256]) == pytest.approx(mf.score(X[:256]))


def test_guard_rejected_under_tp_sharding(mesh4x2):
    """Satellite 5: the rung has no TP form (the guard's f32 re-resolve
    needs the full centroid table) — pointed error, mirroring the
    serving quantize rejection."""
    m = KMeans(k=4, max_iter=2, seed=0, mesh=mesh4x2, chunk_size=256,
               distance_mode="matmul_bf16_guarded", host_loop=False,
               verbose=False)
    with pytest.raises(ValueError, match="data-parallel"):
        m.fit(_blobs(n=1024))


def test_guard_rejected_with_farthest_policy():
    with pytest.raises(ValueError, match="farthest"):
        KMeans(k=4, distance_mode="matmul_bf16_guarded",
               empty_cluster="farthest")


def test_guard_rejected_on_minibatch(mesh1):
    with pytest.raises(ValueError, match="Sculley"):
        MiniBatchKMeans(k=4, max_iter=4, seed=0, batch_size=512,
                        mesh=mesh1, verbose=False,
                        distance_mode="matmul_bf16_guarded"
                        ).fit(_blobs(n=1024))


def test_guard_mode_save_load_and_params(tmp_path, mesh1):
    X = _blobs(n=512)
    m = KMeans(k=3, max_iter=4, seed=0, mesh=mesh1, chunk_size=256,
               host_loop=False, distance_mode="matmul_bf16_guarded",
               verbose=False).fit(X)
    assert m.get_params()["distance_mode"] == "matmul_bf16_guarded"
    p = tmp_path / "g.npz"
    m.save(p)
    loaded = KMeans.load(p)
    assert loaded.distance_mode == "matmul_bf16_guarded"
    np.testing.assert_array_equal(loaded.centroids, m.centroids)


def test_guarded_assign_chunk_unit():
    """Unit level: the shared guarded-assignment primitive flags exactly
    the rows inside the margin bound and re-labels them to the f32
    argmin; well-separated rows never pay the correction."""
    rng = np.random.default_rng(3)
    cents = rng.normal(size=(6, 8)).astype(np.float32)
    cents[1] = cents[0] + 1e-3       # close pair: guaranteed near-tie
    xs = cents[rng.integers(0, 6, 128)] + \
        0.01 * rng.normal(size=(128, 8)).astype(np.float32)
    mids = ((cents[0] + cents[1]) / 2.0)[None, :].repeat(4, 0)
    x = jnp.asarray(np.concatenate([xs, mids]).astype(np.float32))
    c = jnp.asarray(cents)
    d2_bf16 = assign.pairwise_sq_dists(x, c, mode="matmul_bf16")
    labels, n_corr = assign.guarded_assign_chunk(x, d2_bf16, c)
    d2_f32 = assign.pairwise_sq_dists(x, c, mode="matmul")
    np.testing.assert_array_equal(
        np.asarray(labels), np.asarray(jnp.argmin(d2_f32, axis=1)))
    assert int(n_corr) >= 4          # every midpoint row was flagged
    # `valid` excludes rows from flag AND audit (the pad-row contract:
    # predict/fit padding must never cost a correction pass).
    valid = jnp.arange(x.shape[0]) < 128        # mask off the midpoints
    _, n_masked = assign.guarded_assign_chunk(x, d2_bf16, c, valid=valid)
    assert int(n_masked) < int(n_corr)
    # `real_mask` keeps sentinel rows out of the distance scale: with a
    # fake 1e12 pad row appended, an unmasked scale would flag ALL rows.
    c_pad = jnp.concatenate([c, jnp.full((1, 8), 1e12, c.dtype)])
    d2_pad = assign.pairwise_sq_dists(x, c_pad, mode="matmul_bf16")
    _, n_all = assign.guarded_assign_chunk(x, d2_pad, c_pad)
    assert int(n_all) == x.shape[0]             # the failure mode
    _, n_real = assign.guarded_assign_chunk(
        x, d2_pad, c_pad, real_mask=jnp.arange(7) < 6)
    assert int(n_real) == int(n_corr)           # masked == unpadded
    # One error model, two call sites: the serving bound IS this bound.
    from kmeans_tpu.serving.engine import BF16_TIE_RTOL
    assert BF16_TIE_RTOL is assign.BF16_GUARD_RTOL


def test_serving_guard_fix_dispatch_tagged(mesh1):
    """Satellite 5: the serving engine's f32 correction ride-along is
    tagged 'bf16-guard-fix' in the dispatch log, so dispatch-count pins
    can tell guard traffic from serving traffic."""
    from kmeans_tpu.serving.engine import ServingEngine
    from kmeans_tpu.utils import profiling
    X = _blobs(n=1024)
    km = KMeans(k=5, max_iter=15, seed=0, verbose=False).fit(X)
    km.mesh = None
    C = np.asarray(km.centroids, np.float64)
    probe = np.asarray([(C[i] + C[j]) / 2.0
                        for i in range(len(C))
                        for j in range(i + 1, len(C))], np.float32)
    eng = ServingEngine(mesh=mesh1)
    try:
        eng.add_model("q", km, quantize="bf16")
        with profiling.log_dispatches() as log:
            eng.predict("q", probe)
        assert any(lbl == "bf16-guard-fix" for lbl in log)
    finally:
        eng.close()


def test_guard_sweep_sentinel_padding_not_flagged(mesh1):
    """Review regression: a batched k-sweep pads member centroid tables
    to k_max with 1e12 sentinel rows.  The guard's distance scale must
    exclude them — an unmasked ``max_k |c_k|^2`` would be ~1e24,
    flagging EVERY row of EVERY member (audit = n*iters*R, correction
    pass on every chunk).  Selection and trajectories must bit-agree
    with the f32 sweep, and the audit must stay a boundary-row count."""
    X = _blobs(n=1024, centers=4)
    kw = dict(max_iter=8, tolerance=1e-7, seed=7, n_init=1,
              empty_cluster="keep", verbose=False, mesh=mesh1,
              chunk_size=256)
    mg = KMeans(k=3, distance_mode="matmul_bf16_guarded", **kw)
    rg = mg.sweep(X, k_range=[2, 3, 4], criterion="inertia")
    mf = KMeans(k=3, distance_mode="matmul", **kw)
    rf = mf.sweep(X, k_range=[2, 3, 4], criterion="inertia")
    assert rg.selected_k == rf.selected_k
    np.testing.assert_array_equal(rg.n_iters, rf.n_iters)
    np.testing.assert_array_equal(rg.best_model.centroids,
                                  rf.best_model.centroids)
    # The audit is a near-tie count, not all-rows-always: strictly less
    # than ONE full member-pass over the data (the unmasked-sentinel
    # failure floor is n * iters * members ~ 25k here).
    assert 0 <= mg.bf16_guard_corrected_rows_ < X.shape[0]
    # The selected model carries the sweep's observability (the
    # documented reading surface is the model that owns the centroids).
    assert rg.best_model.bf16_guard_corrected_rows_ == \
        mg.bf16_guard_corrected_rows_
    assert rg.best_model.estep_path_ == mg.estep_path_ is not None


def test_guard_zero_weight_padding_not_flagged(mesh1):
    """Review regression: zero-weight data-padding rows sit at the
    origin, where d2_k ~= |c_k|^2 — with two centroid norms close they
    are spurious near-ties.  They contribute to no statistic, so they
    must not enter the audit or trigger the correction pass.  Mirrored
    blobs (equal-norm centroid pairs) + a non-multiple-of-chunk n force
    exactly that configuration; well-separated real rows -> audit 0."""
    rng = np.random.default_rng(5)
    base = rng.normal(size=(500, 8)).astype(np.float32) * 0.05
    X = np.concatenate([base + 4.0, base - 4.0]).astype(np.float32)
    rng.shuffle(X)
    X = X[:900]                       # pads to 1024 -> 124 zero rows
    # Explicit one-row-per-blob init: every REAL row is decisively owned
    # from iteration 1 (a same-blob k-means++ draw would legitimately
    # flag the whole first pass and mask the pad-row regression).
    init = np.stack([X[X.mean(1) > 0][0], X[X.mean(1) < 0][0]])
    m = KMeans(k=2, max_iter=6, tolerance=1e-7, seed=0, init=init,
               empty_cluster="keep", mesh=mesh1, chunk_size=256,
               host_loop=False, distance_mode="matmul_bf16_guarded",
               verbose=False).fit(X)
    # Pre-fix floor: the mirrored centroids have EQUAL norms, so every
    # zero pad row is an exact |c_k|^2 tie -> 124 flags per iteration.
    assert m.bf16_guard_corrected_rows_ == 0


def test_estep_path_fused_pallas(mesh1):
    """Review regression: the Pallas modes ignore the pipeline knob (the
    fused kernel owns its own overlap schedule) — estep_path_ must
    record what actually ran, not 'pipelined'."""
    X = _blobs(n=1024)
    m = KMeans(k=4, max_iter=3, seed=0, mesh=mesh1, chunk_size=256,
               distance_mode="pallas", pipeline=1, host_loop=False,
               verbose=False).fit(X)
    assert m.estep_path_ == "fused-pallas"
    assert m._resolve_pipeline("pallas") == 0   # no duplicate cache key


# --------------------------------------- phase table + BENCH_PHASES smoke

def test_phase_ceiling_table_math():
    """The ceiling table turns ladder rows into shares, implied
    if-this-phase-were-free speedups, and the committed >= 15%
    actionability rule."""
    from kmeans_tpu.utils.profiling import phase_ceiling_table
    ladder = [
        {"phase": "distance", "seconds": 0.003, "cumulative": 0.003,
         "spread": 0.01},
        {"phase": "assign", "seconds": 0.0033, "cumulative": 0.0063,
         "spread": 0.02},
        {"phase": "reduce", "seconds": 0.0047, "cumulative": 0.011,
         "spread": 0.02},
    ]
    table = phase_ceiling_table(ladder, flops_per_iter=1e9,
                                peak_tflops=100.0)
    assert [r["phase"] for r in table] == ["distance", "assign", "reduce"]
    full = 0.011
    for r, src in zip(table, ladder):
        assert r["ms"] == pytest.approx(src["seconds"] * 1e3)
        assert r["share"] == pytest.approx(src["seconds"] / full)
        assert r["implied_ceiling_speedup"] == pytest.approx(
            full / (full - src["seconds"]))
        assert r["actionable"] == (src["seconds"] / full >= 0.15)
        assert r["implied_ceiling_mfu"] == pytest.approx(
            1e9 / (full - src["seconds"]) / 1e14)
    # A sub-threshold phase is pinned, not actionable.
    small = phase_ceiling_table(
        [{"phase": "a", "seconds": 0.001, "cumulative": 0.001,
          "spread": 0.0},
         {"phase": "b", "seconds": 0.099, "cumulative": 0.1,
          "spread": 0.0}])
    assert not small[0]["actionable"] and small[1]["actionable"]


def test_bench_phases_cpu_smoke(capsys):
    """Satellite 6: the BENCH_PHASES harness (phase ladder + ceiling
    table + chunk-geometry re-sweep) runs end-to-end at a tiny CPU
    shape inside the tier-1 budget, so the code path can't rot between
    hardware sessions.  The CPU numbers are a harness exercise — the
    decision rules are hardware measurements."""
    from kmeans_tpu.benchmarks import bench_phases
    result = bench_phases(4096, 8, 8, gap=2, reps=1, chunks=(128, 256))
    assert result["ceiling_table"] and result["chunk_sweep"]
    assert {r["phase"] for r in result["ceiling_table"]} == \
        set(dist.ESTEP_PHASES)
    assert any(r["committed"] for r in result["chunk_sweep"])
    rules = result["decision_rules"]
    assert rules["phase_actionable_share"] == 0.15
    assert rules["pipelined_vs_serial_adopt"] == 1.05
    assert rules["chunk_resweep_adopt_shift"] == 0.03
    # The emitted artifact is one strict-JSON line (inf spreads -> null).
    import json
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("{")]
    assert json.loads(lines[-1])["metric"].startswith(
        "lloyd_phase_ceiling")
