"""MiniBatchKMeans low-count center reassignment (r3 VERDICT #1).

The Sculley update gates on ``counts > 0``, so a center that never
receives points would stay frozen forever; ``reassignment_ratio``
(sklearn-style) re-seeds such centers from the current batch.  This is
the mini-batch analogue of the reference's one fault-tolerance path —
empty-cluster resample, kmeans_spark.py:190-204.
"""

import numpy as np
import pytest

from kmeans_tpu.models import MiniBatchKMeans
from kmeans_tpu.data.synthetic import make_blobs


BLOB_CENTERS = np.array([[-12.0, -12.0], [-12.0, 12.0],
                         [12.0, -12.0], [12.0, 12.0]])


@pytest.fixture()
def blobs4():
    X, _ = make_blobs(4000, centers=BLOB_CENTERS, cluster_std=0.8,
                      random_state=0, dtype=np.float32)
    return X


def _dead_init():
    """k=4 init: three centers near three blobs, one far outside the data
    — the far center never receives a point and (without reassignment)
    can never move."""
    init = BLOB_CENTERS.copy() + 0.5
    init[3] = [1e3, 1e3]
    return init.astype(np.float32)


def _blob_coverage(centroids):
    """Max distance from any true blob center to its nearest centroid."""
    d = np.linalg.norm(BLOB_CENTERS[:, None, :] - centroids[None], axis=2)
    return d.min(axis=1).max()


def test_dead_center_recovers(blobs4, mesh8):
    # 1200 iterations, not 300: when the reassignment draw lands in an
    # already-covered blob, the migrated center crawls to the orphan
    # blob at the sklearn-faithful damped rate (the count reset to the
    # kept centers' MINIMUM is sklearn's own "dirty hack" that shrinks
    # the learning rate, sklearn _kmeans.py::_mini_batch_step).  The
    # r5 chunk-layout change reshuffled the batch stream and exposed
    # exactly that path for seed=0: coverage 4.50 -> 2.49 -> 1.32 over
    # 300/600/1200 iterations — recovery, at the designed rate.
    mb = MiniBatchKMeans(k=4, init=_dead_init(), batch_size=512,
                         max_iter=1200, seed=0, verbose=False, mesh=mesh8)
    mb.fit(blobs4)
    assert _blob_coverage(mb.centroids) < 2.5   # every blob has a centroid
    assert np.all(mb.cluster_sizes_ > 0)


def test_ratio_zero_keeps_dead_center(blobs4, mesh8):
    """reassignment_ratio=0 restores the r3 behavior: the far-out center
    is frozen at its init position for the whole fit."""
    mb = MiniBatchKMeans(k=4, init=_dead_init(), batch_size=512,
                         max_iter=300, seed=0, verbose=False, mesh=mesh8,
                         reassignment_ratio=0.0)
    mb.fit(blobs4)
    np.testing.assert_array_equal(mb.centroids[3], _dead_init()[3])
    assert _blob_coverage(mb.centroids) > 10.0  # one blob left unserved


def test_matches_sklearn_recovery_quality(blobs4, mesh8):
    """sklearn's MiniBatchKMeans with the same init and default
    reassignment_ratio also recovers; final inertia should be in the
    same class (not bitwise — different batch/reassignment streams)."""
    skc = pytest.importorskip("sklearn.cluster")
    mb = MiniBatchKMeans(k=4, init=_dead_init(), batch_size=512,
                         max_iter=300, seed=0, verbose=False, mesh=mesh8)
    mb.fit(blobs4)
    sk = skc.MiniBatchKMeans(
        n_clusters=4, init=_dead_init(), n_init=1, batch_size=512,
        max_iter=300, random_state=0, reassignment_ratio=0.01).fit(blobs4)
    ours = -mb.score(blobs4)
    theirs = float(np.sum((blobs4 - sk.cluster_centers_[
        sk.predict(blobs4)]) ** 2))
    assert ours < theirs * 1.5


def test_host_engine_recovers(blobs4):
    mb = MiniBatchKMeans(k=4, init=_dead_init(), batch_size=512,
                         max_iter=300, seed=0, verbose=False,
                         sampling="host")
    mb.fit(blobs4)
    assert _blob_coverage(mb.centroids) < 2.5


def test_device_loop_matches_per_iteration_with_reassignment(blobs4, mesh8):
    """The one-dispatch loop's apply_reassignment must follow the exact
    candidate draws and reset rule of the per-iteration engine (float64
    makes the interpolation bit-comparable)."""
    kw = dict(k=4, init=_dead_init().astype(np.float64), batch_size=512,
              max_iter=20, tolerance=1e-12, seed=5, verbose=False,
              mesh=mesh8, dtype=np.float64, compute_sse=True)
    a = MiniBatchKMeans(host_loop=True, **kw).fit(blobs4.astype(np.float64))
    b = MiniBatchKMeans(host_loop=False, **kw).fit(blobs4.astype(np.float64))
    np.testing.assert_allclose(b.centroids, a.centroids, atol=1e-10)
    np.testing.assert_allclose(b._seen, a._seen)
    np.testing.assert_allclose(b.sse_history, a.sse_history, rtol=1e-9)


def test_resume_continuity_with_reassignment(blobs4, tmp_path, mesh8):
    """Cadence and candidate keys derive from the ABSOLUTE iteration, so
    an interrupted+resumed fit reproduces the uninterrupted trajectory
    even across reassignment events."""
    kw = dict(k=4, init=_dead_init().astype(np.float64), batch_size=512,
              tolerance=1e-12, seed=5, verbose=False, mesh=mesh8,
              dtype=np.float64, host_loop=False)
    X = blobs4.astype(np.float64)
    full = MiniBatchKMeans(max_iter=16, **kw).fit(X)
    part = MiniBatchKMeans(max_iter=6, **kw).fit(X)
    part.save(tmp_path / "mb.npz")
    resumed = MiniBatchKMeans.load(tmp_path / "mb.npz")
    resumed.max_iter = 16
    resumed.mesh = mesh8
    resumed.fit(X, resume=True)
    np.testing.assert_allclose(resumed.centroids, full.centroids,
                               atol=1e-10)


def test_partial_fit_reassigns(blobs4):
    """partial_fit (caller-provided batches) shares the recovery path."""
    rng = np.random.default_rng(0)
    mb = MiniBatchKMeans(k=4, init=_dead_init(), verbose=False,
                         compute_labels=False)
    for _ in range(300):
        mb.partial_fit(blobs4[rng.choice(len(blobs4), 512, replace=False)])
    assert _blob_coverage(mb.centroids) < 2.5


def test_ratio_roundtrips_checkpoint(blobs4, tmp_path):
    mb = MiniBatchKMeans(k=3, max_iter=3, reassignment_ratio=0.2,
                         verbose=False).fit(blobs4)
    mb.save(tmp_path / "mb.npz")
    assert MiniBatchKMeans.load(tmp_path / "mb.npz").reassignment_ratio \
        == 0.2


def test_negative_ratio_raises():
    with pytest.raises(ValueError, match="reassignment_ratio"):
        MiniBatchKMeans(reassignment_ratio=-0.1)
