"""Test E capability: strong-scaling run + speedup graph artifact
(kmeans_spark.py:543-621): 50k x 10, k=5, max_iter=10, swept over shard
counts, speedup = t[1]/t[n], matplotlib Agg plot of ideal-vs-actual saved to
``speedup_graph.png``.

On the CI's virtual CPU devices the timing is not meaningful (8 "devices"
share the same cores), so the assertions cover completion, result
equivalence across shard counts, and artifact generation; real speedup
numbers come from `bench.py` on TPU hardware.
"""

import time
from pathlib import Path

import jax
import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans
from kmeans_tpu.parallel.mesh import make_mesh
from kmeans_tpu.utils.plotting import save_speedup_graph

SHARD_COUNTS = [1, 2, 4, 8]


@pytest.mark.slow
def test_speedup_sweep_and_graph(tmp_path):
    X, _ = make_blobs(n_samples=50_000, centers=5, n_features=10,
                      random_state=42)
    X = X.astype(np.float32)
    times, results = {}, {}
    for n in SHARD_COUNTS:
        if n > len(jax.devices()):
            continue                     # single-chip hardware mode
        mesh = make_mesh(data=n, model=1, devices=jax.devices()[:n])
        km = KMeans(k=5, max_iter=10, tolerance=1e-4, seed=42,
                    compute_sse=False, mesh=mesh, verbose=False)
        km.fit(X)               # warmup (compile) — the reference times cold
        km2 = KMeans(k=5, max_iter=10, tolerance=1e-4, seed=42,
                     compute_sse=False, mesh=mesh, verbose=False)
        start = time.perf_counter()
        km2.fit(X)
        times[n] = time.perf_counter() - start
        results[n] = np.array(sorted(km2.centroids.tolist()))

    ran = sorted(times)                 # may be just [1] on one real chip
    for n in ran[1:]:  # same answer at every parallelism degree
        np.testing.assert_allclose(results[1], results[n], atol=1e-3)

    speedups = {n: times[1] / times[n] for n in ran}
    out = tmp_path / "speedup_graph.png"
    save_speedup_graph(ran, speedups, out)
    assert out.exists() and out.stat().st_size > 0
