"""Driver-contract guards for ``__graft_entry__.py``.

The driver compile-checks ``entry()`` single-chip and executes
``dryrun_multichip(n)`` on a virtual mesh.  ``dryrun_multichip`` is too
heavy for the unit suite (it fits the whole model zoo — the driver runs
it for real each round); ``entry()`` is cheap and breaks silently if the
fused step's signature or shapes drift, so it is pinned here.
"""

import sys
from pathlib import Path

import jax
import numpy as np

sys.path.insert(0, str(Path(__file__).parent.parent))


def test_entry_compiles_and_runs():
    import __graft_entry__ as g

    fn, args = g.entry()
    compiled = jax.jit(fn).lower(*args).compile()
    stats = compiled(*args)
    # The fused pass returns StepStats with consistent totals.
    k = int(np.asarray(stats.counts).shape[0])
    assert k >= 16
    total = float(np.asarray(stats.counts).sum())
    assert total == float(np.asarray(args[1]).sum())   # all weight assigned
    assert np.isfinite(float(np.asarray(stats.sse)))


def test_dryrun_multichip_is_importable_and_documented():
    import __graft_entry__ as g

    assert callable(g.dryrun_multichip)
    # The driver passes a bare int; the signature must stay (n_devices).
    import inspect
    (param,) = inspect.signature(g.dryrun_multichip).parameters.values()
    assert param.name == "n_devices"
