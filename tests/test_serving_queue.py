"""Micro-batching queue semantics (ISSUE 6 satellite): injectable
clock, flush-on-timer vs flush-on-full, per-request ordering, error
isolation, and clean shutdown — all deterministic (``start=False``
tests never spin a thread; the worker tests reuse the
``data.prefetch`` no-leaked-threads discipline)."""

import threading
import time

import numpy as np
import pytest

from kmeans_tpu.serving.batching import (MicroBatchQueue,
                                         ServingClosedError,
                                         bucket_for, check_buckets)
from kmeans_tpu.utils import faults


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


class DispatchSpy:
    """Records every dispatched batch; result = rows' first column + a
    per-model offset (so slices are checkable per request AND per
    model)."""

    def __init__(self, fail_on=None):
        self.calls = []
        self.fail_on = fail_on or (lambda model_id, op, rows: False)

    def __call__(self, model_id, op, rows):
        self.calls.append((model_id, op, np.array(rows)))
        if self.fail_on(model_id, op, rows):
            raise RuntimeError(f"poisoned batch for {model_id}")
        base = {"a": 0, "b": 1000}.get(model_id, 0)
        return rows[:, 0] + base


def _rows(*vals):
    return np.asarray([[float(v), 0.0] for v in vals], np.float32)


def test_bucket_ladder():
    assert check_buckets((64, 8, 512, 8)) == (8, 64, 512)
    with pytest.raises(ValueError, match="buckets"):
        check_buckets(())
    with pytest.raises(ValueError, match="buckets"):
        check_buckets((0, 8))
    bs = (8, 64, 512, 4096)
    assert bucket_for(1, bs) == 8
    assert bucket_for(8, bs) == 8
    assert bucket_for(9, bs) == 64
    assert bucket_for(4096, bs) == 4096
    assert bucket_for(5000, bs) == 8192      # oversize: top multiple


def test_flush_on_timer_injectable_clock():
    clock = FakeClock()
    spy = DispatchSpy()
    q = MicroBatchQueue(spy, buckets=(8,), max_wait_ms=5.0, clock=clock,
                        start=False)
    f1 = q.submit("a", _rows(1, 2))
    clock.advance(0.002)
    f2 = q.submit("a", _rows(3))
    # Not due yet: the OLDEST request has waited < 5 ms.
    assert q.service(now=clock.t) == 0
    assert not f1.done() and q.pending() == 2
    # One tick past the oldest request's deadline: ONE coalesced
    # dispatch, both requests resolved from their own slices.
    assert q.service(now=clock.advance(0.0031)) == 1
    assert len(spy.calls) == 1
    model_id, op, rows = spy.calls[0]
    assert (model_id, op) == ("a", "predict")
    np.testing.assert_array_equal(rows[:, 0], [1.0, 2.0, 3.0])
    np.testing.assert_array_equal(f1.result(0), [1.0, 2.0])
    np.testing.assert_array_equal(f2.result(0), [3.0])
    q.close()


def test_flush_on_full_runs_inline_without_worker():
    clock = FakeClock()
    spy = DispatchSpy()
    q = MicroBatchQueue(spy, buckets=(4,), max_wait_ms=1e9, clock=clock,
                        start=False)
    futs = [q.submit("a", _rows(i)) for i in range(4)]
    # The 4th submit fills the bucket: dispatched inline, no service()
    # call, no thread, timer never consulted.
    assert len(spy.calls) == 1
    assert all(f.done() for f in futs)
    assert [f.result(0)[0] for f in futs] == [0.0, 1.0, 2.0, 3.0]
    q.close()


def test_requests_never_mix_models_and_keep_order():
    clock = FakeClock()
    spy = DispatchSpy()
    q = MicroBatchQueue(spy, buckets=(8,), max_wait_ms=1.0, clock=clock,
                        start=False)
    fa1 = q.submit("a", _rows(1))
    fb1 = q.submit("b", _rows(10, 11))
    fa2 = q.submit("a", _rows(2))
    fb2 = q.submit("b", _rows(12))
    q.service(now=clock.advance(0.01))
    # One dispatch per model; rows in submission order within each.
    assert len(spy.calls) == 2
    by_model = {c[0]: c[2] for c in spy.calls}
    np.testing.assert_array_equal(by_model["a"][:, 0], [1.0, 2.0])
    np.testing.assert_array_equal(by_model["b"][:, 0],
                                  [10.0, 11.0, 12.0])
    assert fa1.result(0)[0] == 1.0 and fa2.result(0)[0] == 2.0
    assert fb1.result(0).tolist() == [1010.0, 1011.0]
    assert fb2.result(0)[0] == 1012.0
    q.close()


def test_oversize_request_rides_alone():
    spy = DispatchSpy()
    q = MicroBatchQueue(spy, buckets=(4,), max_wait_ms=1e9,
                        clock=FakeClock(), start=False)
    small = q.submit("a", _rows(1))
    big = q.submit("a", _rows(*range(10, 16)))   # 6 rows > bucket cap 4
    q.service(now=1.0)
    # FIFO: the small request dispatches first (the oversize one would
    # blow the cap when appended), then the oversize rides alone.
    assert [c[2].shape[0] for c in spy.calls] == [1, 6]
    assert small.result(0)[0] == 1.0
    assert big.result(0).shape == (6,)
    q.close()


def test_submit_time_validation_fails_alone():
    def validate(model_id, op, rows):
        rows = np.asarray(rows, np.float32)
        if not np.all(np.isfinite(rows)):
            raise ValueError("non-finite request")
        return rows

    spy = DispatchSpy()
    q = MicroBatchQueue(spy, buckets=(8,), max_wait_ms=1.0,
                        clock=FakeClock(), start=False,
                        validate=validate)
    good = q.submit("a", _rows(1))
    bad = q.submit("a", np.asarray([[np.nan, 0.0]], np.float32))
    # The poisoned request never entered the queue.
    assert bad.done()
    with pytest.raises(ValueError, match="non-finite"):
        bad.result(0)
    q.service(now=1.0)
    np.testing.assert_array_equal(good.result(0), [1.0])
    assert len(spy.calls) == 1 and spy.calls[0][2].shape[0] == 1
    q.close()


def test_dispatch_error_isolation_poisoned_fails_alone():
    # The batch dispatch fails whenever the POISON marker row (first
    # column == -1) is present; individual re-dispatches then succeed
    # for everyone else — one poisoned request fails alone.
    spy = DispatchSpy(
        fail_on=lambda m, o, rows: bool(np.any(rows[:, 0] == -1.0)))
    q = MicroBatchQueue(spy, buckets=(8,), max_wait_ms=1.0,
                        clock=FakeClock(), start=False)
    f1 = q.submit("a", _rows(1, 2))
    poisoned = q.submit("a", _rows(-1))
    f2 = q.submit("a", _rows(3))
    q.service(now=1.0)
    np.testing.assert_array_equal(f1.result(0), [1.0, 2.0])
    np.testing.assert_array_equal(f2.result(0), [3.0])
    with pytest.raises(RuntimeError, match="poisoned"):
        poisoned.result(0)
    # 1 failed batch dispatch + 3 isolation re-dispatches.
    assert len(spy.calls) == 4
    assert q.dispatches == 4


def test_transient_fault_costs_one_isolation_round():
    """A transient dispatch fault (utils.faults.fail_first_attempts)
    fails the coalesced batch once; the isolation round re-dispatches
    each member and ALL succeed."""
    spy = DispatchSpy()
    flaky = faults.fail_first_attempts(spy, 1)
    q = MicroBatchQueue(flaky, buckets=(8,), max_wait_ms=1.0,
                        clock=FakeClock(), start=False)
    futs = [q.submit("a", _rows(i)) for i in range(3)]
    q.service(now=1.0)
    assert [f.result(0)[0] for f in futs] == [0.0, 1.0, 2.0]
    # 1 failed batch + 3 per-request retries reached the spy's counter;
    # the failed attempt recorded no call (it raised before the spy).
    assert len(spy.calls) == 3


def test_worker_thread_timer_flush_and_clean_shutdown():
    """Real worker: requests below the full threshold flush by timer
    without any service() call; close() joins the thread (prefetch
    shutdown discipline — no leaked threads)."""
    before = {t.name for t in threading.enumerate()}
    spy = DispatchSpy()
    q = MicroBatchQueue(spy, buckets=(64,), max_wait_ms=5.0, start=True)
    futs = [q.submit("a", _rows(i)) for i in range(3)]
    got = [f.result(timeout=10.0) for f in futs]
    assert [g[0] for g in got] == [0.0, 1.0, 2.0]
    # Usually one coalesced dispatch; a loaded CI host may stall the
    # submitter past the timer and split the wave — never more
    # dispatches than requests, and every row served exactly once.
    assert 1 <= len(spy.calls) <= 3
    assert sum(c[2].shape[0] for c in spy.calls) == 3
    q.close()
    q.close()                                # idempotent
    leaked = {t.name for t in threading.enumerate()} - before
    assert not any("serving" in n for n in leaked)


def test_close_drains_pending_and_rejects_new():
    spy = DispatchSpy()
    q = MicroBatchQueue(spy, buckets=(64,), max_wait_ms=1e9,
                        clock=FakeClock(), start=False)
    f1 = q.submit("a", _rows(7))
    q.close()                    # drain: the pending request is served
    np.testing.assert_array_equal(f1.result(0), [7.0])
    late = q.submit("a", _rows(8))
    assert isinstance(late.exception(0), ServingClosedError)
    assert q.pending() == 0


def test_future_timeout_and_exception_accessor():
    q = MicroBatchQueue(DispatchSpy(), buckets=(8,), max_wait_ms=1e9,
                        clock=FakeClock(), start=False)
    f = q.submit("a", _rows(1))
    with pytest.raises(TimeoutError):
        f.result(timeout=0.01)
    with pytest.raises(TimeoutError):
        f.exception(timeout=0.01)
    q.close()
    assert f.exception(0) is None
    np.testing.assert_array_equal(f.result(0), [1.0])


def test_knob_validation():
    with pytest.raises(ValueError, match="max_wait_ms"):
        MicroBatchQueue(DispatchSpy(), max_wait_ms=-1.0, start=False)


def test_concurrent_submitters_all_resolve():
    """Many threads submitting against a live worker: every future
    resolves with its own slice, nothing lost, no thread leaked."""
    spy = DispatchSpy()
    q = MicroBatchQueue(spy, buckets=(8, 64), max_wait_ms=1.0,
                        start=True)
    results = {}
    errs = []

    def client(tid):
        try:
            futs = [(v, q.submit("a", _rows(v)))
                    for v in range(tid * 100, tid * 100 + 20)]
            results[tid] = [(v, f.result(timeout=10.0)[0])
                            for v, f in futs]
        except Exception as e:           # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    q.close()
    assert not errs
    for tid, pairs in results.items():
        assert all(v == got for v, got in pairs)
    assert q.requests == 80 and q.rows == 80
