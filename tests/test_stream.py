"""Streaming full-batch Lloyd (KMeans.fit_stream): exact K-Means over
data that never resides in memory at once — the bigger-than-HBM path."""

import numpy as np
import pytest

from kmeans_tpu import KMeans
from kmeans_tpu.data.io import iter_npy_blocks
from kmeans_tpu.data.synthetic import make_blobs


@pytest.fixture()
def data():
    X, _ = make_blobs(6000, centers=5, n_features=8, random_state=11,
                      dtype=np.float32)
    return X


def _blocks_of(X, size):
    def make_blocks():
        for i in range(0, len(X), size):
            yield X[i: i + size]
    return make_blocks


def test_stream_matches_in_memory_fit(data, mesh8):
    rng = np.random.RandomState(0)
    init = data[rng.choice(len(data), 5, replace=False)].copy()
    km_mem = KMeans(k=5, seed=0, init=init, compute_sse=True,
                    empty_cluster="keep", verbose=False, mesh=mesh8,
                    chunk_size=128).fit(data)
    km_st = KMeans(k=5, seed=0, init=init, compute_sse=True,
                   empty_cluster="keep", verbose=False, mesh=mesh8,
                   chunk_size=128)
    km_st.fit_stream(_blocks_of(data, 1000))
    # fp summation order differs (per-block f64 accumulation vs one
    # on-device pass), so the stop decision can shift by an iteration
    # right at the tolerance threshold; the fixed-point must agree.
    assert abs(km_st.iterations_run - km_mem.iterations_run) <= 1
    np.testing.assert_allclose(km_st.centroids, km_mem.centroids, atol=1e-4)
    n = min(len(km_st.sse_history), len(km_mem.sse_history))
    np.testing.assert_allclose(km_st.sse_history[:n],
                               km_mem.sse_history[:n], rtol=1e-5)


def test_stream_uneven_blocks_and_npy(tmp_path, data, mesh8):
    path = tmp_path / "pts.npy"
    np.save(path, data)
    rng = np.random.RandomState(1)
    init = data[rng.choice(len(data), 4, replace=False)].copy()
    km = KMeans(k=4, seed=0, init=init, empty_cluster="farthest",
                verbose=False, mesh=mesh8, chunk_size=128)
    km.fit_stream(iter_npy_blocks(path, 1700))      # 6000 -> 1700*3 + 900
    assert np.all(np.isfinite(km.centroids))
    ref = KMeans(k=4, seed=0, init=init, empty_cluster="farthest",
                 verbose=False, mesh=mesh8, chunk_size=128).fit(data)
    np.testing.assert_allclose(km.centroids, ref.centroids, atol=1e-4)


def test_stream_guards(data):
    # ('resample' is no longer rejected — it samples from a per-epoch
    # reservoir; n_init > 1 is supported since r4 — see
    # test_stream_n_init_*.  resume composes only with a single restart.)
    km_r = KMeans(k=3, n_init=2, empty_cluster="keep", verbose=False,
                  max_iter=1)
    km_r.fit_stream(_blocks_of(data, 1000))
    with pytest.raises(ValueError, match="resume requires n_init"):
        km_r.fit_stream(_blocks_of(data, 1000), resume=True)
    km = KMeans(k=3, empty_cluster="keep", verbose=False, max_iter=2)
    km.fit_stream(_blocks_of(data, 1000))
    with pytest.raises(AttributeError, match="fit_stream"):
        km.labels_
    labels = km.predict(data[:100])                 # per-block predict works
    assert labels.shape == (100,)


def test_stream_too_few_points():
    X = np.zeros((3, 2), np.float32)
    km = KMeans(k=5, empty_cluster="keep", verbose=False,
                init=np.zeros((5, 2), np.float32))
    with pytest.raises(ValueError, match="Not enough data points"):
        km.fit_stream(_blocks_of(X, 2))


def test_stream_farthest_multiple_empties_keeps_old(mesh8):
    """>= 2 empty clusters under 'farthest': one slot refills from the
    farthest point, the rest keep their old centroids (no crash)."""
    X = np.concatenate([np.zeros((50, 2)), np.ones((50, 2)) * 100.0]
                       ).astype(np.float32)
    far_init = np.array([[0, 0], [100, 100], [500, 500], [600, 600],
                         [700, 700]], np.float32)
    km = KMeans(k=5, init=far_init, empty_cluster="farthest", max_iter=3,
                verbose=False, mesh=mesh8, chunk_size=8)
    km.fit_stream(_blocks_of(X, 40))
    assert np.all(np.isfinite(km.centroids))


def test_stream_one_shot_iterable_raises(data):
    blocks = iter([data[:2000], data[2000:]])      # NOT a fresh iterable

    def make_blocks():
        return blocks                               # exhausted after epoch 0

    km = KMeans(k=3, empty_cluster="keep", verbose=False, max_iter=5,
                init=data[:3].copy())
    with pytest.raises(ValueError, match="FRESH iterable"):
        km.fit_stream(make_blocks)


def test_fit_after_fit_stream_clears_stale_labels_error(data, mesh8):
    """ADVICE r1: a successful fit() after fit_stream() must clear the
    'not materialized by fit_stream' error state."""
    km = KMeans(k=5, seed=0, empty_cluster="keep", verbose=False, mesh=mesh8)
    km.fit_stream(_blocks_of(data, 2000))
    with pytest.raises(AttributeError, match="fit_stream"):
        _ = km.labels_
    km.fit(data)
    assert km.labels_.shape == (len(data),)


def test_minibatch_and_bisecting_fit_stream_blocked():
    """ADVICE r1: the inherited exact-Lloyd fit_stream would silently bypass
    mini-batch / bisecting semantics — both must refuse."""
    from kmeans_tpu.models import BisectingKMeans, MiniBatchKMeans
    with pytest.raises(NotImplementedError, match="partial_fit"):
        MiniBatchKMeans(k=3, verbose=False).fit_stream(lambda: [])
    with pytest.raises(NotImplementedError, match="KMeans.fit_stream"):
        BisectingKMeans(k=3, verbose=False).fit_stream(lambda: [])


def test_stream_resample_policy_from_reservoir(mesh8):
    """r1 VERDICT #6: 'resample' under fit_stream draws replacements from
    the per-epoch seeded reservoir — finite, deterministic, and the
    refilled slot holds a real (streamed) data row."""
    rng = np.random.RandomState(7)
    X = rng.normal(size=(400, 2)).astype(np.float32)
    far_init = np.array([[0, 0], [0.3, 0.3], [1e3, 1e3]], np.float32)

    def run(max_iter):
        km = KMeans(k=3, init=far_init, empty_cluster="resample",
                    max_iter=max_iter, verbose=False, mesh=mesh8,
                    chunk_size=8)
        km.fit_stream(_blocks_of(X, 64))
        return km

    a = run(1)
    replaced = a.centroids[2]
    assert np.any(np.all(np.isclose(X, replaced[None, :], atol=1e-6),
                         axis=1))
    b, c = run(8), run(8)
    assert np.all(np.isfinite(b.centroids))
    np.testing.assert_array_equal(b.centroids, c.centroids)


def test_reservoir_draw_is_uniform_chi2():
    """r2 VERDICT #8: the epoch reservoir's draw must be UNIFORM over a
    multi-block epoch.  Composite draw (Algorithm-R reservoir -> seeded
    subsample) repeated over many independent seeds; a chi-squared test
    against the uniform row-inclusion frequency must not reject."""
    stats = pytest.importorskip("scipy.stats")  # optional oracle, like sklearn

    from kmeans_tpu.models.kmeans import _EpochReservoir

    n, cap, m, trials = 120, 12, 4, 3000
    rows = np.arange(n, dtype=np.float64)[:, None]    # identifiable rows
    counts = np.zeros(n)
    for t in range(trials):
        res = _EpochReservoir(cap, 1, np.random.default_rng([t, 1]))
        # Uneven multi-block epoch, incl. a block smaller than cap.
        for blk in (rows[:7], rows[7:60], rows[60:101], rows[101:]):
            res.offer(blk)
        drawn = res.sample(m, np.random.default_rng([t, 2]))
        counts[drawn[:, 0].astype(int)] += 1
    expected = trials * m / n
    chi2 = float(((counts - expected) ** 2 / expected).sum())
    p = float(stats.chi2.sf(chi2, df=n - 1))
    assert p > 1e-4, (chi2, p, counts.min(), counts.max())


def test_reservoir_matches_sequential_algorithm_r():
    """The vectorized offer() must reproduce textbook sequential
    Algorithm R exactly (same rng consumption order): last-write-wins
    fancy assignment is the claimed equivalence — pin it."""
    from kmeans_tpu.models.kmeans import _EpochReservoir

    n, cap = 257, 16
    rows = np.arange(n, dtype=np.float64)[:, None]
    res = _EpochReservoir(cap, 1, np.random.default_rng(99))
    for blk in np.array_split(rows, 7):
        res.offer(blk)

    rng = np.random.default_rng(99)                 # sequential reference
    ref = np.zeros((cap, 1))
    for t in range(n):
        if t < cap:
            ref[t] = rows[t]
        else:
            j = rng.integers(0, t + 1)
            if j < cap:
                ref[j] = rows[t]
    # The vectorized version draws j for a whole tail at once — same
    # distribution only if the per-row j draws consume the SAME stream.
    np.testing.assert_array_equal(res.rows, ref)


def test_stream_resample_single_block_equals_memory_until_refill(mesh8):
    """r2 VERDICT #8: fit_stream over ONE block covering the whole
    dataset vs the in-memory fit, with 'resample' empties forced.  The
    two paths share every statistic; only the replacement SAMPLER
    differs (epoch reservoir vs global row draw — both uniform, but
    different streams; documented in fit_stream's docstring).  So:
    identical up to the first refill, equal-in-distribution after, and
    both must land on data rows and a comparable final fit."""
    rng = np.random.RandomState(11)
    X = np.concatenate([rng.normal(size=(150, 2)),
                        rng.normal(size=(150, 2)) + 8.0]).astype(np.float32)
    far_init = np.array([[0, 0], [8, 8], [1e3, 1e3]], np.float32)
    kw = dict(k=3, init=far_init, empty_cluster="resample", seed=5,
              compute_sse=True, tolerance=1e-7, max_iter=40,
              verbose=False, mesh=mesh8)

    km_mem = KMeans(**kw).fit(X)
    km_st = KMeans(**kw)
    km_st.fit_stream(lambda: [X])

    # Iteration 1 (pre-refill statistics): bitwise-identical SSE.
    assert km_st.sse_history[0] == km_mem.sse_history[0]
    # The refilled slot holds a real data row on BOTH paths.
    for km in (km_mem, km_st):
        assert np.all(np.isfinite(km.centroids))
        assert np.abs(km.centroids).max() < 100
    # Equal in distribution, not bitwise: both converge onto the two
    # blob centers + one data row; final inertia within a loose factor.
    a, b = km_st.sse_history[-1], km_mem.sse_history[-1]
    assert min(a, b) > 0 and max(a, b) / min(a, b) < 3.0, (a, b)


def test_predict_stream_matches_predict():
    """predict_stream over blocks == predict on the concatenated array,
    including ragged final blocks and per-size compilation reuse."""
    import numpy as np

    from kmeans_tpu import KMeans
    from kmeans_tpu.data.synthetic import make_blobs

    X, _ = make_blobs(5_000, 4, 8, random_state=11, dtype=np.float32)
    km = KMeans(k=4, seed=2, verbose=False).fit(X)

    def blocks():
        yield X[:2_000]
        yield X[2_000:4_100]        # different size -> second compile
        yield X[4_100:]             # ragged tail

    streamed = np.concatenate(list(km.predict_stream(blocks)))
    np.testing.assert_array_equal(streamed, km.predict(X))


def test_predict_stream_guards():
    import numpy as np
    import pytest

    from kmeans_tpu import KMeans

    km = KMeans(k=3)
    # Fail-fast: the guard raises AT THE CALL, not on first iteration.
    with pytest.raises(ValueError, match="fitted before prediction"):
        km.predict_stream(lambda: iter([np.zeros((4, 2))]))
    X = np.random.default_rng(0).normal(size=(200, 6)).astype(np.float32)
    km.fit(X)
    bad = lambda: iter([np.zeros((8, 5), np.float32)])
    with pytest.raises(ValueError, match=r"block shape .* != \(\*, 6\)"):
        list(km.predict_stream(bad))
    # An exhausted/empty stream raises, never silently yields nothing.
    with pytest.raises(ValueError, match="FRESH iterable"):
        list(km.predict_stream(lambda: iter([])))
    with pytest.raises(ValueError, match="FRESH iterable"):
        km.score_stream(lambda: iter([]))


# ---- streamed init over the FULL stream (r3 VERDICT #3) ----------------

def _sorted_blob_blocks(n_per=800, k=4, d=4, std=0.6, seed=0):
    """Cluster-SORTED stream: block i contains ONLY blob i — the
    adversarial shape for first-block seeding (all k seeds would land in
    one blob)."""
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-20, 20, size=(k, d))
    blocks = [centers[i] + std * rng.normal(size=(n_per, d))
              for i in range(k)]
    blocks = [b.astype(np.float32) for b in blocks]
    return (lambda: iter([b.copy() for b in blocks])), np.concatenate(blocks)


def test_stream_kmeanspp_init_sse_matches_memory(mesh8):
    """On a cluster-sorted stream the streamed kmeans|| init must seed
    across ALL blobs (first-block seeding would start every centroid
    inside blob 0); final SSE within ~1% of an in-memory k-means++ fit.
    (Forgy gets a coverage test instead — uniform draws have no SSE-
    parity guarantee between two different streams, in-memory included.)"""
    make_blocks, X = _sorted_blob_blocks()
    km_st = KMeans(k=4, seed=0, init="k-means++", verbose=False,
                   mesh=mesh8, compute_sse=True, max_iter=50)
    km_st.fit_stream(make_blocks)
    km_mem = KMeans(k=4, seed=0, init="k-means++", verbose=False,
                    mesh=mesh8, compute_sse=True, max_iter=50).fit(X)
    sse_st, sse_mem = -km_st.score(X), -km_mem.score(X)
    assert sse_st <= sse_mem * 1.01, (sse_st, sse_mem)


def test_stream_forgy_init_covers_all_blocks(mesh8):
    """Streamed forgy draws over the WHOLE cluster-sorted stream: with
    k=4 over 4 single-blob blocks, the seeds must not all come from
    block 0 (the old first-block seeding guaranteed they did), and the
    fixed-seed fit must serve every blob."""
    make_blocks, X = _sorted_blob_blocks()
    from kmeans_tpu.models.init import streamed_forgy_init
    outs, n = streamed_forgy_init(make_blocks, 4, [0], 4, np.float32)
    blob_of = np.repeat(np.arange(4), 800)
    seeded_blobs = {int(blob_of[np.argmin(
        np.linalg.norm(X - c, axis=1))]) for c in outs[0]}
    assert len(seeded_blobs) > 1 and n == 3200
    km = KMeans(k=4, seed=0, init="forgy", verbose=False, mesh=mesh8,
                max_iter=50)
    km.fit_stream(make_blocks)
    blob_centers = np.stack([X[blob_of == i].mean(axis=0)
                             for i in range(4)])
    cover = np.linalg.norm(
        blob_centers[:, None] - km.centroids[None], axis=2).min(axis=1)
    assert cover.max() < 2.0


def test_stream_callable_init_sees_full_stream(mesh8):
    """r5 (r4 VERDICT #8): a CALLABLE init receives a uniform reservoir
    sample of the WHOLE stream, not the first block — on a
    cluster-sorted stream the sample must contain rows from every blob,
    zero-weight rows must never appear, and the contract is
    deterministic per (seed, restart)."""
    make_blocks, X = _sorted_blob_blocks()
    blob_of = np.repeat(np.arange(4), 800)
    seen = []

    def grab_init(sample, k, seed):
        seen.append((np.array(sample), seed))
        return sample[:k]

    km = KMeans(k=4, init=grab_init, n_init=2, seed=7, verbose=False,
                mesh=mesh8, max_iter=2)
    km.fit_stream(make_blocks)
    assert len(seen) == 2 and seen[0][1] != seen[1][1]
    for sample, _ in seen:
        assert sample.shape == (2048, 4)       # the default cap for k=4
        blobs_in_sample = {int(blob_of[np.argmin(
            np.linalg.norm(X - r, axis=1))]) for r in sample[:64]}
        assert len(blobs_in_sample) > 1        # permuted, not fill-order

    # Weighted streams: zero-weight rows are excluded from the sample.
    def weighted_blocks():
        for i, b in enumerate(np.split(X, 4)):
            yield b, np.full(len(b), 0.0 if i == 3 else 1.0, np.float32)

    seen.clear()
    km2 = KMeans(k=4, init=grab_init, seed=7, verbose=False, mesh=mesh8,
                 max_iter=2)
    km2.fit_stream(weighted_blocks)
    (sample, _), = seen
    assert sample.shape == (2048, 4)           # 2400 positive rows, capped
    assert {int(blob_of[np.argmin(np.linalg.norm(X - r, axis=1))])
            for r in sample} == {0, 1, 2}

    # Determinism: same seed -> bit-identical sample and fit.
    seen.clear()
    km3 = KMeans(k=4, init=grab_init, seed=7, verbose=False, mesh=mesh8,
                 max_iter=2)
    km3.fit_stream(weighted_blocks)
    np.testing.assert_array_equal(seen[0][0], sample)
    np.testing.assert_array_equal(km3.centroids, km2.centroids)


def test_stream_init_deterministic(mesh8):
    make_blocks, _ = _sorted_blob_blocks()
    a = KMeans(k=4, seed=3, init="forgy", verbose=False, mesh=mesh8,
               max_iter=3)
    b = KMeans(k=4, seed=3, init="forgy", verbose=False, mesh=mesh8,
               max_iter=3)
    a.fit_stream(make_blocks)
    b.fit_stream(make_blocks)
    np.testing.assert_array_equal(a.centroids, b.centroids)


def test_stream_forgy_is_uniform_over_stream():
    """The reservoir draw behind streamed forgy must be uniform over the
    WHOLE stream, not biased to early blocks: over many seeds, the mean
    fraction of seeds drawn from the second half of a 2-block stream
    must be ~1/2."""
    from kmeans_tpu.models.init import streamed_forgy_init
    lo = np.zeros((500, 2))
    hi = np.ones((500, 2))
    frac = []
    for s in range(200):
        outs, n = streamed_forgy_init(
            lambda: iter([lo.copy(), hi.copy()]), 4, [s], 2, np.float32)
        frac.append(float(np.mean(outs[0][:, 0] > 0.5)))
    assert abs(np.mean(frac) - 0.5) < 0.06
    assert n == 1000


# ---- streamed n_init (r3 VERDICT #3) -----------------------------------

def _seed_only_init(pool):
    """Callable init that depends ONLY on its seed (same pool for the
    in-memory and streamed fits), so both paths start from identical
    restart centroids and their winners are comparable."""
    def init(X_ignored, k, seed):
        rng = np.random.default_rng(seed)
        return pool[rng.choice(len(pool), size=k, replace=False)]
    return init


def test_stream_n_init_picks_same_winner_as_memory(mesh8):
    make_blocks, X = _sorted_blob_blocks()
    pool = X[np.random.default_rng(7).choice(len(X), 64, replace=False)]
    kw = dict(k=4, seed=0, n_init=3, init=_seed_only_init(pool),
              verbose=False, mesh=mesh8, max_iter=40)
    km_st = KMeans(**kw)
    km_st.fit_stream(make_blocks)
    km_mem = KMeans(**kw).fit(X)
    assert km_st.best_restart_ == km_mem.best_restart_
    np.testing.assert_allclose(km_st.centroids, km_mem.centroids,
                               atol=1e-3)
    np.testing.assert_allclose(km_st.restart_inertias_,
                               km_mem.restart_inertias_, rtol=1e-4)


def test_stream_resume_continues(mesh8):
    # Overlapping blobs (std=6): no exact Lloyd fixed point within the
    # iteration budget, so full/resumed runs compare iteration-for-
    # iteration (an early fixed point would make resume re-run one no-op
    # iteration, the same semantics as in-memory fit resume).
    make_blocks, X = _sorted_blob_blocks(std=6.0)
    init = X[np.random.default_rng(1).choice(len(X), 4, replace=False)]
    kw = dict(k=4, seed=0, init=init, empty_cluster="keep",
              verbose=False, mesh=mesh8, tolerance=1e-12, compute_sse=True)
    full = KMeans(max_iter=12, **kw)
    full.fit_stream(make_blocks)
    part = KMeans(max_iter=5, **kw)
    part.fit_stream(make_blocks)
    part.max_iter = 12
    part.fit_stream(make_blocks, resume=True)
    np.testing.assert_allclose(part.centroids, full.centroids, atol=1e-6)
    assert part.iterations_run == full.iterations_run
    np.testing.assert_allclose(part.sse_history, full.sse_history,
                               rtol=1e-9)


def test_stream_resume_exhausted_budget_is_noop(mesh8):
    """review r4: resume with no iteration budget left must keep the
    fitted state (the in-memory resume is a no-op in the same case), not
    reset iterations_run/cluster_sizes_."""
    # Overlapping blobs: no fixed point inside the budget, so the first
    # fit truly exhausts max_iter (a converged fit would legitimately
    # re-run one no-op iteration on resume, like in-memory fit).
    make_blocks, X = _sorted_blob_blocks(std=6.0)
    init = X[np.random.default_rng(1).choice(len(X), 4, replace=False)]
    km = KMeans(k=4, seed=0, init=init, empty_cluster="keep",
                verbose=False, mesh=mesh8, max_iter=4, tolerance=1e-12)
    km.fit_stream(make_blocks)
    assert km.iterations_run == 4                  # budget actually used
    cents, iters = km.centroids.copy(), km.iterations_run
    sizes = km.cluster_sizes_.copy()
    km.fit_stream(make_blocks, resume=True)       # budget exhausted
    np.testing.assert_array_equal(km.centroids, cents)
    assert km.iterations_run == iters
    np.testing.assert_array_equal(km.cluster_sizes_, sizes)


def test_spherical_fit_stream_normalizes_blocks(mesh8):
    """r4: SphericalKMeans' streaming paths must L2-normalize raw
    blocks exactly like fit/predict do — a streamed fit on raw-magnitude
    vectors must match the in-memory fit of the same data."""
    from kmeans_tpu.models import SphericalKMeans
    rng = np.random.default_rng(0)
    dirs = rng.normal(size=(4, 6))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    # Raw magnitudes vary wildly; direction carries the cluster signal.
    X = np.concatenate([
        d * rng.uniform(0.1, 100.0, size=(300, 1))
        + 0.05 * rng.normal(size=(300, 6)) for d in dirs
    ]).astype(np.float32)
    init = X[rng.choice(len(X), 4, replace=False)]
    kw = dict(k=4, seed=0, init=init, empty_cluster="keep",
              verbose=False, mesh=mesh8, compute_sse=True)
    mem = SphericalKMeans(**kw).fit(X)
    st = SphericalKMeans(**kw)
    st.fit_stream(_blocks_of(X, 400))
    np.testing.assert_allclose(np.linalg.norm(st.centroids, axis=1),
                               1.0, rtol=1e-5)
    np.testing.assert_allclose(st.centroids, mem.centroids, atol=1e-4)
    lab = np.concatenate(list(st.predict_stream(_blocks_of(X, 400))))
    np.testing.assert_array_equal(lab, mem.predict(X))


def test_spherical_score_stream_normalizes_blocks(mesh8):
    """Advisor r4: score_stream was inherited WITHOUT the normalizing
    wrapper, so raw-magnitude streams scored raw points against unit-norm
    centroids.  score_stream on raw blocks must equal score(X)."""
    from kmeans_tpu.models import SphericalKMeans
    rng = np.random.default_rng(3)
    X = (rng.normal(size=(600, 5))
         * rng.uniform(0.1, 50.0, size=(600, 1))).astype(np.float32)
    km = SphericalKMeans(k=4, seed=0, verbose=False, mesh=mesh8,
                         empty_cluster="keep").fit(X)
    s_mem = km.score(X)
    s_stream = km.score_stream(_blocks_of(X, 200))
    np.testing.assert_allclose(s_stream, s_mem, rtol=1e-5)


def test_weighted_stream_matches_weighted_memory_fit(data, mesh8):
    """r4: (block, weights) stream items fold weights into every
    statistic exactly like fit's sample_weight."""
    rng = np.random.RandomState(3)
    w = rng.randint(1, 4, size=len(data)).astype(np.float64)
    init = data[rng.choice(len(data), 5, replace=False)].copy()
    mem = KMeans(k=5, seed=0, init=init, empty_cluster="keep",
                 compute_sse=True, verbose=False, mesh=mesh8,
                 chunk_size=128).fit(data, sample_weight=w)

    def make_blocks():
        for i in range(0, len(data), 1000):
            yield data[i: i + 1000], w[i: i + 1000]

    st = KMeans(k=5, seed=0, init=init, empty_cluster="keep",
                compute_sse=True, verbose=False, mesh=mesh8,
                chunk_size=128)
    st.fit_stream(make_blocks)
    np.testing.assert_allclose(st.centroids, mem.centroids, atol=1e-4)
    n = min(len(st.sse_history), len(mem.sse_history))
    np.testing.assert_allclose(st.sse_history[:n], mem.sse_history[:n],
                               rtol=1e-5)


def test_weighted_stream_init_skips_zero_weight_rows(mesh8):
    """Zero-weight rows must never seed a centroid (the in-memory
    positive-rows rule) — a poisoned far-out zero-weight region cannot
    leak into streamed forgy or kmeans|| seeds."""
    rng = np.random.RandomState(5)
    good = rng.normal(size=(500, 2)).astype(np.float32)
    poison = (rng.normal(size=(500, 2)) + 1e3).astype(np.float32)
    X = np.concatenate([good, poison])
    w = np.concatenate([np.ones(500), np.zeros(500)])

    def make_blocks():
        yield X[:600], w[:600]
        yield X[600:], w[600:]

    for init in ("forgy", "k-means++"):
        km = KMeans(k=3, seed=0, init=init, empty_cluster="keep",
                    verbose=False, mesh=mesh8, max_iter=5)
        km.fit_stream(make_blocks)
        assert np.all(np.abs(km.centroids) < 100), init


def test_weighted_stream_guards(data):
    km = KMeans(k=3, verbose=False, max_iter=1, empty_cluster="keep")
    with pytest.raises(ValueError, match="must have shape"):
        km.fit_stream(lambda: iter([(data[:100], np.ones(5))]))
    with pytest.raises(ValueError, match="finite and >= 0"):
        km.fit_stream(lambda: iter([(data[:100], -np.ones(100))]))
    # GMM weighted streams are supported too (r4):
    from kmeans_tpu import GaussianMixture
    with pytest.raises(ValueError, match="must have shape"):
        GaussianMixture(n_components=2).fit_stream(
            lambda: iter([(data[:100], np.ones(5))]))


def test_weighted_stream_reusable_for_predict_and_transform(data, mesh8):
    """A weighted make_blocks is reusable for predict_stream /
    transform_stream: the weights are simply ignored there."""
    rng = np.random.RandomState(3)
    w = rng.randint(1, 4, size=len(data)).astype(np.float64)

    def make_blocks():
        for i in range(0, len(data), 2000):
            yield data[i: i + 2000], w[i: i + 2000]

    km = KMeans(k=4, seed=0, verbose=False, mesh=mesh8, max_iter=5,
                empty_cluster="keep")
    km.fit_stream(make_blocks)
    lab = np.concatenate(list(km.predict_stream(make_blocks)))
    np.testing.assert_array_equal(lab, km.predict(data))
    tiles = np.concatenate(list(km.transform_stream(make_blocks)))
    np.testing.assert_allclose(tiles, km.transform(data), atol=1e-5)


def test_gmm_weighted_stream_matches_weighted_memory(data, mesh8):
    """r4: GMM weighted streams fold weights into the E statistics
    exactly like fit's sample_weight."""
    from kmeans_tpu import GaussianMixture
    rng = np.random.RandomState(4)
    w = rng.randint(1, 4, size=len(data)).astype(np.float64)
    init = data[rng.choice(len(data), 3, replace=False)].astype(np.float64)
    kw = dict(n_components=3, means_init=init, max_iter=15, tol=1e-6,
              seed=0, mesh=mesh8)
    mem = GaussianMixture(**kw).fit(data, sample_weight=w)

    def make_blocks():
        for i in range(0, len(data), 2000):
            yield data[i: i + 2000], w[i: i + 2000]

    st = GaussianMixture(**kw).fit_stream(make_blocks)
    np.testing.assert_allclose(st.lower_bound_, mem.lower_bound_,
                               rtol=1e-5)
    np.testing.assert_allclose(st.means_, mem.means_, atol=1e-3)
    np.testing.assert_allclose(st.covariances_, mem.covariances_,
                               rtol=1e-3, atol=1e-3)


def test_all_zero_weight_stream_raises_pointed_error(data):
    """review r4: all-zero weights must raise the weight error, not the
    misleading FRESH-iterable one (rows WERE yielded)."""
    from kmeans_tpu import GaussianMixture
    with pytest.raises(ValueError, match="total sample weight"):
        GaussianMixture(n_components=2).fit_stream(
            lambda: iter([(data[:100], np.zeros(100))]))


def test_score_stream_matches_score(data, mesh8):
    km = KMeans(k=4, seed=0, verbose=False, mesh=mesh8, max_iter=5,
                empty_cluster="keep").fit(data)
    got = km.score_stream(_blocks_of(data, 1700))
    np.testing.assert_allclose(got, km.score(data), rtol=1e-6)
    # Weighted: 2x weights double the SSE of an unweighted stream.
    w = np.full(len(data), 2.0)
    got_w = km.score_stream(
        lambda: ((data[i:i+1700], w[i:i+1700])
                 for i in range(0, len(data), 1700)))
    np.testing.assert_allclose(got_w, 2.0 * km.score(data), rtol=1e-6)
