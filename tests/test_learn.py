"""Serve-and-learn actuator acceptance (ISSUE 20).

The headline invariants, pinned end to end through the REAL code paths
(the ``utils.faults`` injectors — no mocks):

* QUIESCED EQUIVALENCE: after an in-place online update, the serving
  model is bit-exact equal to the same ``partial_fit`` batch sequence
  replayed offline from the pre-update snapshot, across {1,2,4,8}-way
  meshes — the float64 Sculley carry makes the trajectory reproducible.
* NEVER A FAILED REQUEST: an injected update failure leaves the model
  bit-identical on last-good; an injected quality regression rolls the
  model back to the snapshot (f32 table, f64 carry, and lifetime
  counts all bit-exact) — and the engine serves throughout both.
* NEVER A TORN TABLE: concurrent readers hammering the identity-keyed
  ``_cents_dev`` cache during repeated atomic swaps always see exactly
  one published table version, never a mix.
* ZERO NEW COMPILES: fixed-size update batches reuse the warm step
  programs — the second update runs inside the recompilation sentinel.

Plus the decision surface (``update_status``, triple recording,
``serve-status`` aggregation, budgets/disarm), the ``remove()``-vs-
in-flight-update hammer, fleet aggregation, and the CLI.
"""

import io
import json
import os
import threading
import time

import numpy as np
import pytest
from sklearn.datasets import make_blobs

import jax

from kmeans_tpu.models.minibatch import MiniBatchKMeans
from kmeans_tpu.obs import metrics_registry as obs_metrics
from kmeans_tpu.obs.drift import format_quality_status, quality_report
from kmeans_tpu.parallel.mesh import make_mesh
from kmeans_tpu.serving import ServingEngine, ServingFleet, publish_tables
from kmeans_tpu.serving.learn import (COMMITTED_LEARN_RULES,
                                      UpdateRolledBack)
from kmeans_tpu.utils import faults
from kmeans_tpu.utils.profiling import recompilation_sentinel


@pytest.fixture(autouse=True)
def _fresh_metrics():
    obs_metrics.REGISTRY.reset()
    yield
    obs_metrics.REGISTRY.reset()


@pytest.fixture(scope="module")
def data():
    X, _ = make_blobs(n_samples=6000, centers=4, n_features=8,
                      cluster_std=0.5, center_box=(-40, 40),
                      random_state=7)
    return X.astype(np.float32)


def _fitted(data, seed=0):
    return MiniBatchKMeans(k=4, seed=seed, batch_size=256, max_iter=8,
                           verbose=False).fit(data[:3000])


@pytest.fixture(scope="module")
def mb(data):
    model = _fitted(data)
    model.mesh = None                   # engine re-points to its mesh
    return model


#: Fast-test learner config: small exact batches, no cooldown.
_LEARN = {"batch_rows": 128, "min_rows": 128, "max_batches": 2,
          "cooldown_windows": 0}


def _engine(model, tmp_path, *, mesh=None, learn=None, **kw):
    eng = ServingEngine(mesh=mesh, quality=True,
                        quality_dir=str(tmp_path), start=False,
                        learn=dict(_LEARN, **(learn or {})), **kw)
    eng.add_model("m", model)
    return eng


def _feed(eng, data, n_blocks=4, rows=128, model_id="m"):
    for i in range(n_blocks):
        eng.call(model_id, data[3000 + i * rows: 3000 + (i + 1) * rows],
                 op="predict")


# ------------------------------------------------------------- surface


def test_learn_requires_quality_monitoring(tmp_path):
    with pytest.raises(ValueError, match="drift monitor"):
        ServingEngine(quality=False, learn=True, start=False)


def test_learn_rejects_unknown_config_keys(tmp_path):
    with pytest.raises(ValueError, match="unknown learn config"):
        ServingEngine(quality=True, learn={"batch_size": 9},
                      start=False)


def test_learner_attach_and_update_status(data, mb, tmp_path):
    """Eligible MiniBatch residents get a learner whose status carries
    the committed rules; ineligible families report None."""
    from kmeans_tpu import KMeans
    km = KMeans(k=4, seed=0, verbose=False, max_iter=5).fit(data[:2000])
    km.mesh = None
    eng = _engine(mb, tmp_path)
    try:
        eng.add_model("plain", km)      # no partial_fit -> no learner
        st = eng.update_status()
        assert st["plain"] is None
        assert st["m"]["armed"] and st["m"]["updates_applied"] == 0
        # Overrides land in the effective rules; untouched knobs keep
        # the committed module constants.
        assert st["m"]["rules"]["batch_rows"] == 128
        assert st["m"]["rules"]["regression_ratio"] == \
            COMMITTED_LEARN_RULES["regression_ratio"]
        assert eng.registry.spec("m")["updatable"] is True
        assert eng.registry.spec("plain")["updatable"] is False
        assert "learn" in eng.stats()
    finally:
        eng.close()


def test_update_skipped_on_empty_reservoir(data, mb, tmp_path):
    eng = _engine(mb, tmp_path)
    try:
        ln = eng._residents["m"].learner
        dec = ln.update_now(force=True)
        assert dec["action"] == "update-skipped"
        assert dec["reason"] == "reservoir-underfilled"
    finally:
        eng.close()


# ------------------------------------------------- quiesced equivalence


@pytest.mark.parametrize("width", [1, 2, 4, 8])
def test_quiesced_update_equals_offline_replay(data, width, tmp_path):
    """THE headline invariant: a quiesced serve-and-learn model is
    bit-exact equal to the same ``partial_fit`` sequence replayed
    offline from the pre-update snapshot — f32 table, f64 Sculley
    carry, lifetime counts, and iteration counter — on every mesh
    width (the device reduction order is part of the trajectory, so
    online and offline run the SAME mesh)."""
    if len(jax.devices()) < width:
        pytest.skip(f"needs {width} devices")
    mesh = make_mesh(data=width, model=1, devices=jax.devices()[:width])
    model = _fitted(data)
    eng = _engine(model, tmp_path / f"w{width}", mesh=mesh)
    try:
        blocks = [data[3000 + i * 128: 3000 + (i + 1) * 128]
                  for i in range(4)]
        for b in blocks:
            eng.call("m", b, op="predict")
        ln = eng._residents["m"].learner
        dec = ln.update_now(force=True)
        assert dec["action"] == "update"
        batches = ln.applied_batches[-1]
        # The drained batches ARE the retained traffic in arrival
        # (FIFO) order — the offline replay needs no side channel.
        np.testing.assert_array_equal(
            np.concatenate(batches),
            np.concatenate(blocks)[: 2 * 128].astype(model.dtype))
        off = MiniBatchKMeans.load(ln.snapshot_path)
        off.mesh = mesh
        for b in batches:
            off.partial_fit(b)
        assert model.centroids.dtype == off.centroids.dtype
        np.testing.assert_array_equal(model.centroids, off.centroids)
        np.testing.assert_array_equal(model._centroids_f64,
                                      off._centroids_f64)
        np.testing.assert_array_equal(model._seen, off._seen)
        assert model.iterations_run == off.iterations_run
        # And the served labels agree with the replayed model's own.
        q = data[4000:4100]
        np.testing.assert_array_equal(eng.call("m", q, op="predict"),
                                      off.predict(q))
    finally:
        eng.close()


def test_second_update_is_zero_new_compiles(data, tmp_path):
    """Fixed exact-size update batches hit one compiled step shape:
    after the first update warms it, a further update (and the serving
    traffic around it) adds ZERO cache entries."""
    model = _fitted(data)
    eng = _engine(model, tmp_path)
    try:
        ln = eng._residents["m"].learner
        _feed(eng, data)
        assert ln.update_now(force=True)["action"] == "update"
        _feed(eng, data)
        with recompilation_sentinel():
            assert ln.update_now(force=True)["action"] == "update"
            eng.call("m", data[3000:3128], op="predict")
    finally:
        eng.close()


# ------------------------------------------------------ torn-swap hammer


def test_concurrent_readers_never_see_torn_table(data, mb):
    """N reader threads hammer ``_cents_dev`` while the main thread
    publishes a sequence of KNOWN tables through the atomic swap
    helper: every table a reader observes must be bit-equal to exactly
    one published version — never a mix of two."""
    mesh = make_mesh()
    model = _fitted(data)
    model.mesh = mesh
    k, d = model.centroids.shape
    rng = np.random.default_rng(0)
    versions = [np.asarray(model.centroids, np.float64)]
    versions += [versions[0] + rng.normal(scale=0.1, size=(k, d))
                 for _ in range(12)]
    expected = [v.astype(model.dtype) for v in versions]
    seen = np.asarray(model._seen, np.float64)
    stop = threading.Event()
    errors: list = []

    def reader():
        try:
            while not stop.is_set():
                dev = model._cents_dev(mesh, 1)
                host = np.asarray(dev)[:k]
                if not any(np.array_equal(host, v) for v in expected):
                    errors.append("torn table observed")
                    return
        except Exception as e:  # noqa: BLE001 — the assertion IS
            errors.append(repr(e))  # "no reader ever fails"

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for i, v in enumerate(versions[1:], start=1):
            publish_tables(model, mesh, 1, centroids_f64=v, seen=seen,
                           iterations_run=i, sse_history=[])
            time.sleep(0.002)
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert errors == []


def test_serving_requests_survive_update_storm(data, tmp_path):
    """Engine-level chaos: readers keep dispatching while updates and
    swaps run concurrently — zero failed requests, and every label
    batch matches the argmin oracle of SOME published table version
    (well-separated blobs: the oracle is tie-free)."""
    model = _fitted(data)
    eng = _engine(model, tmp_path)
    versions = [np.asarray(model.centroids, np.float64)]
    q = data[4000:4128]
    stop = threading.Event()
    errors: list = []

    def oracle(table):
        dist = (np.sum(q.astype(np.float64) ** 2, axis=1)[:, None]
                - 2.0 * q.astype(np.float64) @ table.T
                + np.sum(table ** 2, axis=1)[None, :])
        return np.argmin(dist, axis=1)

    def reader():
        try:
            while not stop.is_set():
                lab = eng.call("m", q, op="predict")
                if not any(np.array_equal(lab, oracle(v))
                           for v in versions):
                    errors.append("labels match no published table")
                    return
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    threads = [threading.Thread(target=reader) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        ln = eng._residents["m"].learner
        for _ in range(4):
            _feed(eng, data)
            dec = ln.update_now(force=True)
            assert dec["action"] == "update"
            versions.append(np.asarray(model._centroids_f64, np.float64))
            ln._pending = None          # next forced update, no eval
    finally:
        stop.set()
        for t in threads:
            t.join()
        eng.close()
    assert errors == []


# ------------------------------------------------------- chaos injection


def test_injected_update_failure_never_fails_serving(data, tmp_path):
    """A failed update dies with the working clone: the serving model
    stays IDENTICAL (same array object — nothing was published), the
    request path never notices, and the failure is recorded all three
    ways (decision log + counter + JSONL line)."""
    model = _fitted(data)
    eng = _engine(model, tmp_path)
    try:
        ln = eng._residents["m"].learner
        _feed(eng, data)
        before = model.centroids
        with faults.inject_update_failure("m") as rec:
            dec = ln.update_now(force=True)
        assert rec["fired"] == 1
        assert dec["action"] == "update-failed"
        assert "SimulatedUpdateFailure" in dec["detail"]["error"]
        assert model.centroids is before          # nothing published
        assert ln.status()["updates_applied"] == 0
        assert ln.status()["updates_failed"] == 1
        # Zero failed serving requests, on last-good.
        lab = eng.call("m", data[4000:4032], op="predict")
        assert lab.shape == (32,)
        assert obs_metrics.REGISTRY.counter(
            "serve.learn.update_failures").value == 1
    finally:
        eng.close()
    rep = quality_report([tmp_path / "quality.m.jsonl"])
    assert rep["models"]["m"]["update_failures"] == 1
    assert rep["models"]["m"]["updates"] == 0


def test_injected_regression_rolls_back_to_last_good(data, tmp_path):
    """The full rollback story: update applies (tables move), the
    injected regression verdict breaches the committed ratio, and the
    learner restores the pre-update snapshot BIT-EXACT (f32 table, f64
    carry, lifetime counts) through the same atomic swap — typed
    ``UpdateRolledBack`` record, full decision log, serving alive
    throughout."""
    model = _fitted(data)
    eng = _engine(model, tmp_path)
    try:
        ln = eng._residents["m"].learner
        _feed(eng, data)
        pre_f32 = np.array(model.centroids, copy=True)
        pre_f64 = np.array(model._centroids_f64, copy=True)
        pre_seen = np.array(model._seen, copy=True)
        pre_sizes = np.array(model.cluster_sizes_, copy=True)
        assert ln.update_now(force=True)["action"] == "update"
        assert not np.array_equal(model.centroids, pre_f32)
        with faults.inject_quality_regression("m", ratio=10.0) as rec:
            ln.evaluate_now(force=True)
        assert rec["fired"] == 1
        np.testing.assert_array_equal(model.centroids, pre_f32)
        np.testing.assert_array_equal(model._centroids_f64, pre_f64)
        np.testing.assert_array_equal(model._seen, pre_seen)
        np.testing.assert_array_equal(model.cluster_sizes_, pre_sizes)
        [rb] = ln.rollbacks
        assert isinstance(rb, UpdateRolledBack)
        assert rb.ratio == 10.0 and rb.restored_from == "primary"
        actions = [d["action"] for d in ln.status()["decisions"]]
        assert actions == ["update", "rollback"]
        assert obs_metrics.REGISTRY.counter(
            "serve.learn.rollbacks").value == 1
        # Zero failed requests, back on last-good.
        lab = eng.call("m", data[4000:4032], op="predict")
        np.testing.assert_array_equal(
            lab, eng._residents["m"].model.predict(data[4000:4032]))
    finally:
        eng.close()
    rep = quality_report([tmp_path / "quality.m.jsonl"])
    row = rep["models"]["m"]
    assert row["updates"] == 1 and row["rollbacks"] == 1
    assert "1upd,1rb" in format_quality_status(rep)


def test_rollback_budget_disarms_the_learner(data, tmp_path):
    """Two rolled-back updates mean live traffic is not learnable by
    this loop: the learner disarms itself (committed ROLLBACK_BUDGET)
    with an explicit 'disabled' decision, and further updates are
    refused while serving continues."""
    model = _fitted(data)
    eng = _engine(model, tmp_path, learn={"rollback_budget": 2})
    try:
        ln = eng._residents["m"].learner
        for _ in range(2):
            _feed(eng, data)
            assert ln.update_now(force=True)["action"] == "update"
            with faults.inject_quality_regression("m", ratio=10.0):
                ln.evaluate_now(force=True)
        st = ln.status()
        assert st["armed"] is False
        assert st["rollback_budget_left"] == 0
        assert [d["action"] for d in st["decisions"]][-1] == "disabled"
        assert ln.update_now(force=True) is None
        assert eng.call("m", data[4000:4016], op="predict").shape == (16,)
    finally:
        eng.close()


def test_update_budget_exhaustion_is_an_explicit_skip(data, tmp_path):
    model = _fitted(data)
    eng = _engine(model, tmp_path, learn={"update_budget": 1})
    try:
        ln = eng._residents["m"].learner
        _feed(eng, data)
        assert ln.update_now(force=True)["action"] == "update"
        ln._pending = None
        _feed(eng, data)
        dec = ln.update_now(force=True)
        assert dec["action"] == "update-skipped"
        assert dec["reason"] == "update-budget-exhausted"
    finally:
        eng.close()


# --------------------------------------------- drift-triggered automation


def test_drift_fires_the_update_automatically(data, tmp_path):
    """The closed loop, end to end on the real trigger: single-cluster
    traffic drifts the monitor (PSI debounced), the post-dispatch poke
    spawns the background update, and the decision log shows
    reason='drift' — no manual update_now anywhere."""
    model = _fitted(data)
    eng = _engine(model, tmp_path, quality_window=128)
    try:
        ln = eng._residents["m"].learner
        one = data[np.argsort(model.predict(data[:3000]))[:1500]]
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            for i in range(8):
                eng.call("m", one[i * 128:(i + 1) * 128], op="predict")
            if ln.status()["updates_applied"] >= 1:
                break
        st = ln.status()
        assert st["updates_applied"] >= 1
        ups = [d for d in st["decisions"] if d["action"] == "update"]
        assert ups and ups[0]["reason"] == "drift"
    finally:
        eng.close()


# ------------------------------------------------- remove()-vs-update


def test_remove_mid_update_joins_cleanly(data, tmp_path):
    """Removing a model with an update in flight must JOIN the update
    (or let it abort unpublished) before the sinks close — no
    write-after-remove, no crash, valid sink JSON (hammered)."""
    for rep in range(6):
        model = _fitted(data, seed=rep)
        eng = _engine(model, tmp_path / f"rep{rep}")
        ln = eng._residents["m"].learner
        _feed(eng, data)
        t = threading.Thread(
            target=lambda: ln.update_now(force=True, reason="hammer"))
        t.start()
        eng.remove("m")
        t.join(timeout=30.0)
        assert not t.is_alive()
        assert ln._closed
        worker = ln._thread
        assert worker is None or not worker.is_alive()
        eng.close()
        sink = tmp_path / f"rep{rep}" / "quality.m.jsonl"
        if sink.exists():
            for line in sink.read_text().splitlines():
                json.loads(line)                  # every record intact


def test_engine_close_joins_learners(data, tmp_path):
    model = _fitted(data)
    eng = _engine(model, tmp_path)
    ln = eng._residents["m"].learner
    _feed(eng, data)
    t = threading.Thread(
        target=lambda: ln.update_now(force=True, reason="close-race"))
    t.start()
    eng.close()
    t.join(timeout=30.0)
    assert not t.is_alive() and ln._closed


# ---------------------------------------------------------------- fleet


def test_fleet_learn_shared_model_and_aggregation(data, tmp_path):
    """Fleet replicas share the fitted model object: one replica's
    applied update is served by EVERY replica the instant it publishes,
    per-replica learners serialize on the per-model lock, and
    ``update_status`` / ``serve-status`` aggregate the per-replica
    state."""
    model = _fitted(data)
    model.mesh = None
    fdir = tmp_path / "fleet"
    fleet = ServingFleet(2, quality=True, fleet_dir=str(fdir),
                         start=False, learn=_LEARN, max_wait_ms=1.0)
    try:
        fleet.add_model("m", model)
        fleet.warmup(prewarm=False)
        for i in range(8):
            fleet.call("m", data[3000 + i * 128: 3000 + (i + 1) * 128])
        st = fleet.update_status()
        assert set(st["m"]) == {"r0", "r1"}
        reps = [r for r in fleet._replicas
                if r.engine._residents["m"].learner.status()
                ["reservoir_rows"] >= 256]
        assert reps, "router starved both learners"
        ln = reps[0].engine._residents["m"].learner
        pre = np.array(model.centroids, copy=True)
        assert ln.update_now(force=True)["action"] == "update"
        assert not np.array_equal(model.centroids, pre)
        # Every replica serves the swapped table (shared model object).
        q = data[4000:4064]
        want = model.predict(q)
        for rep in fleet._replicas:
            np.testing.assert_array_equal(
                rep.engine.call("m", q, op="predict"), want)
        agg = fleet.update_status()["m"]
        assert sum(s["updates_applied"] for s in agg.values()) == 1
    finally:
        fleet.close()
    rep = quality_report(sorted(fdir.glob("quality.m.*.jsonl")))
    assert rep["models"]["m"]["updates"] == 1


# ------------------------------------------------------------------ CLI


def test_serve_cli_learn_surface(data, mb, tmp_path, monkeypatch,
                                 capsys):
    from kmeans_tpu.cli import serve_main
    mb.save(tmp_path / "mb.npz")
    lines = [
        json.dumps({"x": data[:3].tolist(), "id": "r1"}),
        json.dumps({"learn": True}),
    ]
    monkeypatch.setattr("sys.stdin",
                        io.StringIO("\n".join(lines) + "\n"))
    rc = serve_main(["--model", str(tmp_path / "mb.npz"), "--learn",
                     "--no-warmup", "--quality-dir",
                     str(tmp_path / "q")])
    assert rc == 0
    out = [json.loads(ln) for ln in
           capsys.readouterr().out.strip().splitlines()]
    assert out[0]["id"] == "r1" and len(out[0]["result"]) == 3
    st = out[1]["mb"]
    assert st["armed"] is True and st["updates_applied"] == 0
    assert st["rules"]["batch_rows"] == \
        COMMITTED_LEARN_RULES["batch_rows"]


def test_serve_cli_learn_requires_quality(data, mb, tmp_path, capsys):
    from kmeans_tpu.cli import serve_main
    mb.save(tmp_path / "mb.npz")
    rc = serve_main(["--model", str(tmp_path / "mb.npz"), "--learn",
                     "--no-quality"])
    assert rc == 2
    assert "--learn requires quality" in capsys.readouterr().err


def test_serve_cli_learn_status_needs_learn_flag(data, mb, tmp_path,
                                                 monkeypatch, capsys):
    from kmeans_tpu.cli import serve_main
    mb.save(tmp_path / "mb.npz")
    monkeypatch.setattr("sys.stdin",
                        io.StringIO(json.dumps({"learn": True}) + "\n"))
    rc = serve_main(["--model", str(tmp_path / "mb.npz"),
                     "--no-warmup", "--no-quality"])
    assert rc == 0                          # per-request error, loop on
    out = [json.loads(ln) for ln in
           capsys.readouterr().out.strip().splitlines()]
    assert "error" in out[0] and "--learn" in out[0]["error"]


# ----------------------------------------------------------- bench-diff


def test_bench_diff_guards_the_excursion_row(tmp_path, capsys):
    """The BENCH_LEARN p99-excursion row is a guarded bench-diff
    metric: growth past the recorded spread flags (update work leaking
    into the dispatch path), shrinkage never does."""
    from kmeans_tpu.cli import bench_diff_main

    def doc(name, ratio):
        p = tmp_path / name
        p.write_text(json.dumps({"parsed": {
            "metric": "serve_learn_p99_excursion_N200000_D32_k64",
            "excursion_ratio": ratio, "excursion_spread": 0.10}}))
        return str(p)

    old = doc("old.json", 1.8)
    assert bench_diff_main([old, doc("same.json", 1.9)]) == 0  # in spread
    capsys.readouterr()
    assert bench_diff_main([old, doc("worse.json", 2.6)]) == 1
    assert "REGRESSION" in capsys.readouterr().out
    assert bench_diff_main([old, doc("better.json", 1.2)]) == 0
