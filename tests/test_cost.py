"""ISSUE 12: device-cost observability.

Coverage tiers:

1. **Capture units** — CostRecord normalization, the collector's
   dedupe/write-through, ``analyze_jitted`` on real programs, the
   one-shot proxy semantics.
2. **Five-family capture** — every family's step program yields an
   available CostRecord through the REAL step-cache path on the CPU
   backend (``cost_analysis`` works there), with the analytic FLOPs
   agreeing within the committed 10% band on the kmeans and gmm-diag
   programs.
3. **Degraded backends** — analyses that raise or report partially
   yield ``available=False`` records and never fail a fit, a compile,
   or the recompilation sentinel.
4. **Roofline + planner** — crosscheck/roofline fields,
   ``plan_fit`` arithmetic, the observed-peak join, the advisory
   pre-dispatch check (gauge + ``mem.plan`` event, no behavior change).
5. **Surfaces** — heartbeat ``mem_peak_bytes``/``program_flops``
   fields, serving residency stats, the ``cost-report`` and ``trace
   summarize --cost`` CLIs, and the ``obs`` package-namespace
   regression (the ``heartbeat`` shadowing satellite).
"""

import json
import sys

import numpy as np
import pytest

from kmeans_tpu import KMeans, obs
from kmeans_tpu.models import (BisectingKMeans, GaussianMixture,
                               MiniBatchKMeans, SphericalKMeans)
from kmeans_tpu.obs import cost as cost_mod
from kmeans_tpu.obs import memory as memory_mod
from kmeans_tpu.obs import trace as trace_mod
from kmeans_tpu.obs.cost import (CostRecord, analytic_step_flops,
                                 analyze_jitted, crosscheck,
                                 normalize_compiled, roofline_fields)
from kmeans_tpu.utils.cache import LRUCache
from kmeans_tpu.utils.profiling import recompilation_sentinel


def _X(n=512, d=8, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, d))
            + 3.0 * rng.integers(0, 3, size=(n, 1))).astype(np.float32)


def _fit_kmeans(X, k=4, chunk=136, **kw):
    m = KMeans(k=k, max_iter=2, tolerance=1e-30, seed=0,
               host_loop=False, empty_cluster="keep",
               compute_labels=False, chunk_size=chunk, verbose=False,
               **kw)
    m.fit(X)
    return m


# ---------------------------------------------------------------------------
# Capture units
# ---------------------------------------------------------------------------

def test_no_collector_is_noop_and_identity():
    assert cost_mod.get_collector() is None
    fn = lambda x: x  # noqa: E731
    assert cost_mod.instrument("c", ("k",), fn) is fn
    tup = (fn, 3)
    assert cost_mod.instrument("c", ("k",), tup) is tup


def test_collecting_scope_installs_restores_and_closes():
    with cost_mod.collecting() as col:
        assert cost_mod.get_collector() is col
        with cost_mod.collecting() as inner:     # nested scopes shadow
            assert cost_mod.get_collector() is inner
        assert cost_mod.get_collector() is col
    assert cost_mod.get_collector() is None
    assert col.closed


def test_collector_dedupes_by_cache_key_role():
    col = cost_mod.CostCollector()
    rec = CostRecord(cache="c", key="k", role=0, available=True,
                     flops=1.0, peak_bytes=10)
    assert col.add(rec)
    assert not col.add(CostRecord(cache="c", key="k", role=0))
    assert col.add(CostRecord(cache="c", key="k", role=1))
    assert len(col.records()) == 2


def test_analyze_jitted_real_program():
    import jax
    import jax.numpy as jnp
    f = jax.jit(lambda x: (x @ x.T).sum())
    rec = analyze_jitted(f, jnp.ones((64, 32)), cache="unit", key="k")
    assert rec.available
    assert rec.flops and rec.flops > 2 * 64 * 64 * 32 * 0.9
    assert rec.peak_bytes and rec.peak_bytes > 0
    assert rec.arithmetic_intensity() and rec.arithmetic_intensity() > 0
    d = rec.to_dict()
    assert d["ai"] == rec.arithmetic_intensity()


def test_analyze_jitted_never_raises_without_lower():
    rec = analyze_jitted(lambda x: x, 3, cache="unit", key="nolower")
    assert not rec.available
    assert "lower" in rec.error


def test_proxy_captures_once_and_delegates(monkeypatch):
    import jax
    import jax.numpy as jnp
    cache = LRUCache(8, name="unit._TEST_CACHE")
    x = jnp.ones((16, 8))
    with cost_mod.collecting() as col:
        fn = cache.get_or_create(
            ("a",), lambda: jax.jit(lambda v: (v * 2.0).sum()))
        out1 = float(fn(x))
        out2 = float(fn(x))
    assert out1 == out2 == 256.0
    recs = col.records()
    assert len(recs) == 1                         # one-shot, deduped
    assert recs[0].cache == "unit._TEST_CACHE"
    # Attribute access falls through to the wrapped jit function.
    assert hasattr(fn, "lower")
    # A later call (collector closed) still works and adds nothing.
    assert float(fn(x)) == 256.0
    assert len(col.records()) == 1


def test_tuple_cache_entries_keep_structure():
    import jax
    import jax.numpy as jnp
    cache = LRUCache(8, name="unit._TUPLE_CACHE")
    x = jnp.ones((8,))
    with cost_mod.collecting() as col:
        a, b = cache.get_or_create(
            ("t",), lambda: (jax.jit(lambda v: v + 1),
                             jax.jit(lambda v: v * 2)))
        a(x), b(x)
    roles = sorted(r.role for r in col.records())
    assert roles == [0, 1]


def test_registry_write_through_and_trace_event():
    import jax
    import jax.numpy as jnp
    obs.registry().reset()
    cache = LRUCache(8, name="unit._EVT_CACHE")
    x = jnp.ones((32, 16))
    with trace_mod.tracing() as tr, cost_mod.collecting():
        fn = cache.get_or_create(
            ("e",), lambda: jax.jit(lambda v: (v @ v.T).sum()))
        with trace_mod.span("dispatch", tag="unit"):
            float(fn(x))
    snap = obs.registry().snapshot()
    assert snap["cost.captured"]["value"] == 1
    assert snap["cost.peak_bytes"]["value"] > 0
    events = [r for r in tr.records() if r.get("kind") == "event"
              and r["name"] == "cost.record"]
    assert len(events) == 1
    assert events[0]["attrs"]["available"] is True
    # The event parents into the dispatch span the first call ran under
    # (how `trace summarize --cost` attributes programs to phases).
    spans = {r["id"]: r for r in tr.records()
             if r.get("kind") == "span"}
    assert spans[events[0]["parent"]]["name"] == "dispatch"


# ---------------------------------------------------------------------------
# Five-family capture through the real step-cache path
# ---------------------------------------------------------------------------

def _available(col, cache=None):
    return [r for r in col.records()
            if r.available and (cache is None or r.cache == cache)]


def test_capture_kmeans_family_step_programs():
    X = _X()
    with cost_mod.collecting() as col:
        _fit_kmeans(X, chunk=136)
    recs = _available(col, "kmeans._STEP_CACHE")
    assert recs, [r.error for r in col.records()]
    assert all(r.backend == "cpu" for r in recs)
    assert max(r.flops for r in recs) > 0
    assert max(r.peak_bytes for r in recs) > 0


def test_capture_minibatch_bisecting_spherical_gmm():
    X = _X(768, 8)
    fits = [
        lambda: MiniBatchKMeans(k=4, batch_size=128, max_iter=2,
                                tolerance=1e-30, seed=0, host_loop=False,
                                compute_labels=False, chunk_size=144,
                                verbose=False).fit(X),
        lambda: BisectingKMeans(k=3, max_iter=2, tolerance=1e-30, seed=0,
                                host_loop=False, compute_labels=False,
                                chunk_size=152, verbose=False).fit(X),
        lambda: SphericalKMeans(k=4, max_iter=2, tolerance=1e-30, seed=0,
                                host_loop=False, empty_cluster="keep",
                                compute_labels=False, chunk_size=160,
                                verbose=False).fit(X),
        lambda: GaussianMixture(n_components=3, covariance_type="diag",
                                max_iter=2, tol=0.0, seed=0,
                                init_params="random", host_loop=False,
                                chunk_size=168, verbose=False).fit(X),
    ]
    for fit in fits:
        with cost_mod.collecting() as col:
            fit()
        assert _available(col), [r.error for r in col.records()]


def test_analytic_flops_agreement_kmeans_and_gmm_diag():
    """The acceptance pin: analytic FLOPs within the committed 10% band
    of XLA's report on the kmeans and gmm-diag step programs (single-
    chunk CPU shapes; the hardware headline row is pinned in
    BENCH_COST with the same rule)."""
    rng = np.random.default_rng(1)
    Xk = rng.standard_normal((8192, 128)).astype(np.float32)
    with cost_mod.collecting() as col:
        _fit_kmeans(Xk, k=64, chunk=8192)
    step = max(_available(col, "kmeans._STEP_CACHE"),
               key=lambda r: r.flops)
    chk = crosscheck(analytic_step_flops("kmeans", n=8192, d=128, k=64,
                                         chunk=8192), step)
    assert chk["agree"], chk

    Xg = rng.standard_normal((8192, 64)).astype(np.float32)
    with cost_mod.collecting() as col:
        GaussianMixture(n_components=32, covariance_type="diag",
                        max_iter=2, tol=0.0, seed=0,
                        init_params="random", host_loop=False,
                        chunk_size=8192, verbose=False).fit(Xg)
    step = max(_available(col, "gmm._STEP_CACHE"),
               key=lambda r: r.flops)
    chk = crosscheck(analytic_step_flops("gmm", n=8192, d=64, k=32,
                                         chunk=8192), step)
    assert chk["agree"], chk


def test_capture_parity_fit_unchanged():
    """Cost capture changes no numerics: a collected fit equals the
    plain fit bit-for-bit (the obs=0 oracle extended to capture)."""
    X = _X(600, 6, seed=3)
    with cost_mod.collecting():
        m_on = _fit_kmeans(X, chunk=176)
    m_off = _fit_kmeans(X, chunk=176)
    assert m_on.iterations_run == m_off.iterations_run
    assert np.array_equal(m_on.centroids, m_off.centroids)


# ---------------------------------------------------------------------------
# Degraded backends
# ---------------------------------------------------------------------------

class _StubCompiled:
    def __init__(self, cost=None, mem=None, cost_exc=None, mem_exc=None):
        self._cost, self._mem = cost, mem
        self._cost_exc, self._mem_exc = cost_exc, mem_exc

    def cost_analysis(self):
        if self._cost_exc:
            raise self._cost_exc
        return self._cost

    def memory_analysis(self):
        if self._mem_exc:
            raise self._mem_exc
        return self._mem


class _StubMem:
    argument_size_in_bytes = 100
    output_size_in_bytes = 10
    temp_size_in_bytes = 50
    alias_size_in_bytes = 0
    generated_code_size_in_bytes = 7


def test_normalize_full_report_available():
    rec = normalize_compiled(
        _StubCompiled(cost=[{"flops": 5.0, "bytes accessed": 2.0}],
                      mem=_StubMem()))
    assert rec.available
    assert rec.flops == 5.0 and rec.peak_bytes == 160
    assert rec.error is None


def test_normalize_raising_analyses_unavailable():
    rec = normalize_compiled(
        _StubCompiled(cost_exc=RuntimeError("unsupported"),
                      mem_exc=NotImplementedError("no")))
    assert not rec.available
    assert "cost_analysis" in rec.error and "memory_analysis" in rec.error


def test_normalize_partial_dict_unavailable_keeps_fields():
    rec = normalize_compiled(
        _StubCompiled(cost=[{"bytes accessed": 9.0}], mem=None))
    assert not rec.available
    assert rec.bytes_accessed == 9.0 and rec.flops is None


class _PartialMem:
    argument_size_in_bytes = 100      # output/temp missing entirely


def test_normalize_partial_memory_unavailable():
    rec = normalize_compiled(
        _StubCompiled(cost=[{"flops": 5.0}], mem=_PartialMem()))
    assert not rec.available
    assert rec.flops == 5.0 and rec.peak_bytes is None
    assert "partial" in rec.error


def test_degraded_capture_never_fails_fit_or_sentinel(monkeypatch):
    """An analyzer that raises mid-fit must degrade to an
    available=False record; the fit completes and the recompilation
    sentinel still sees a stable cache."""
    def boom(fn, *a, **k):
        raise RuntimeError("backend cannot report")
    monkeypatch.setattr(cost_mod, "analyze_jitted", boom)
    X = _X(640, 6, seed=5)
    with cost_mod.collecting() as col:
        m = _fit_kmeans(X, chunk=184)
    assert m.iterations_run >= 1
    recs = col.records()
    assert recs and all(not r.available for r in recs)
    assert all("backend cannot report" in r.error for r in recs)
    # Warm repeat under the sentinel: the wrapped entries reuse fine.
    with recompilation_sentinel():
        _fit_kmeans(X, chunk=184)


# ---------------------------------------------------------------------------
# Roofline + planner
# ---------------------------------------------------------------------------

def test_analytic_step_flops_families_and_chunking():
    assert analytic_step_flops("kmeans", n=1000, d=8, k=4) \
        == 4.0 * 1000 * 8 * 4
    # Chunked program: one chunk's flops (the XLA loop-body-once rule).
    assert analytic_step_flops("kmeans", n=1000, d=8, k=4, chunk=100) \
        == 4.0 * 100 * 8 * 4
    # Per-device rows.
    assert analytic_step_flops("kmeans", n=1000, d=8, k=4,
                               n_devices=4) == 4.0 * 250 * 8 * 4
    assert analytic_step_flops("gmm", n=100, d=8, k=4) \
        == 8.0 * 100 * 8 * 4
    with pytest.raises(ValueError):
        analytic_step_flops("nope", n=1, d=1, k=1)


def test_crosscheck_band():
    rec = CostRecord(cache="c", key="k", available=True, flops=105.0)
    assert crosscheck(100.0, rec)["agree"]
    rec.flops = 130.0
    chk = crosscheck(100.0, rec)
    assert not chk["agree"] and chk["ratio"] == pytest.approx(1.3)
    assert not crosscheck(100.0, CostRecord(cache="c", key="k"))["agree"]


def test_roofline_fields():
    rec = CostRecord(cache="c", key="k", available=True, flops=200.0,
                     bytes_accessed=50.0)
    rf = roofline_fields(100.0, 2.0, rec, peak_tflops=1e-12)
    assert rf["ai"] == 4.0
    assert rf["mfu_analytic"] == pytest.approx(50.0)
    rf = roofline_fields(100.0, 2.0, None, peak_tflops=None)
    assert rf["ai"] is None and rf["mfu_analytic"] is None
    assert rf["analytic_flops"] == 100.0


def test_plan_fit_components_and_padding():
    plan = memory_mod.plan_fit("kmeans", 1000, 16, 8, chunk=256)
    comp = plan["components"]
    # 1000 rows pad to 1024 (4 chunks of 256).
    assert comp["points_bytes"] == 1024 * 16 * 4
    assert comp["table_bytes"] == 8 * 16 * 4
    assert comp["tile_bytes"] == 2 * 256 * 8 * 4
    assert plan["predicted_peak_bytes"] == \
        plan["predicted_resident_bytes"] + plan["predicted_temp_bytes"]
    # Pipeline doubles the in-flight tile.
    plan_p = memory_mod.plan_fit("kmeans", 1000, 16, 8, chunk=256,
                                 pipeline=1)
    assert plan_p["components"]["tile_bytes"] == 2 * comp["tile_bytes"]
    with pytest.raises(ValueError):
        memory_mod.plan_fit("nope", 10, 2, 2)
    with pytest.raises(ValueError):
        memory_mod.plan_fit("gmm", 10, 2, 2, cov_type="bogus")


def test_plan_fit_observed_join():
    recs = [CostRecord(cache="kmeans._STEP_CACHE", key="k",
                       available=True, flops=1.0, peak_bytes=12345),
            CostRecord(cache="gmm._STEP_CACHE", key="k",
                       available=True, flops=1.0, peak_bytes=99999)]
    plan = memory_mod.plan_fit("kmeans", 100, 4, 2, records=recs)
    assert plan["observed_peak_bytes"] == 12345     # family-cache join
    plan = memory_mod.plan_fit("gmm", 100, 4, 2, records=recs)
    assert plan["observed_peak_bytes"] == 99999


def test_device_memory_info_cpu_graceful():
    info = memory_mod.device_memory_info()
    assert "available" in info
    if not info["available"]:
        assert info["bytes_free"] is None


def test_advise_dispatch_requires_tracer_and_is_advisory():
    X = _X(600, 6, seed=7)
    m = _fit_kmeans(X, chunk=192)                   # fitted: has tables
    assert memory_mod.advise_dispatch(m, 192) is None   # tracing off
    obs.registry().reset()
    with trace_mod.tracing() as tr:
        adv = memory_mod.advise_dispatch(m, 192, segment=3)
    assert adv is not None
    assert adv["chunk"] == 192 and adv["segment"] == 3
    assert adv["predicted_tile_bytes"] == 192 * m.k * 4
    snap = obs.registry().snapshot()
    assert snap["fit.mem_planned_chunk"]["value"] == 192
    assert any(r.get("name") == "mem.plan" for r in tr.records())


def test_segmented_fit_emits_mem_plan_and_stays_bit_exact(tmp_path):
    X = _X(640, 6, seed=9)
    kw = dict(k=4, max_iter=4, tolerance=1e-30, seed=0,
              host_loop=False, empty_cluster="keep",
              compute_labels=False, chunk_size=200, verbose=False)
    m_plain = KMeans(**kw).fit(X)
    with trace_mod.tracing() as tr:
        m_seg = KMeans(**kw)
        m_seg.fit(X, checkpoint_every=2,
                  checkpoint_path=str(tmp_path / "c.npz"))
    plans = [r for r in tr.records() if r.get("name") == "mem.plan"]
    assert len(plans) == 2                          # one per segment
    assert np.array_equal(m_plain.centroids, m_seg.centroids)


# ---------------------------------------------------------------------------
# Surfaces: heartbeat, serving, CLI, namespace regression
# ---------------------------------------------------------------------------

def test_heartbeat_carries_cost_fields(tmp_path):
    X = _X(640, 6, seed=11)
    beats = []
    with cost_mod.collecting(), obs.heartbeat(callback=beats.append):
        KMeans(k=4, max_iter=3, tolerance=1e-30, seed=0,
               host_loop=True, empty_cluster="keep",
               compute_labels=False, chunk_size=208,
               verbose=False).fit(X)
    assert beats
    last = beats[-1]
    assert last["mem_peak_bytes"] > 0
    assert last["program_flops"] > 0


def test_heartbeat_without_collector_omits_cost_fields():
    X = _X(512, 6, seed=13)
    beats = []
    with obs.heartbeat(callback=beats.append):
        KMeans(k=4, max_iter=2, tolerance=1e-30, seed=0,
               host_loop=True, empty_cluster="keep",
               compute_labels=False, chunk_size=216,
               verbose=False).fit(X)
    assert beats and "mem_peak_bytes" not in beats[-1]


def test_serving_stats_residency_and_program_memory():
    from kmeans_tpu.serving import ServingEngine
    X = _X(512, 8, seed=15)
    km = KMeans(k=4, max_iter=3, seed=0, empty_cluster="keep",
                verbose=False).fit(X)
    gm = GaussianMixture(n_components=3, covariance_type="diag",
                         max_iter=2, seed=0, init_params="random",
                         verbose=False).fit(X)
    engine = ServingEngine(max_wait_ms=1.0, buckets=(8, 64))
    try:
        # Fresh step caches: the bucket-shaped programs must MISS inside
        # the collecting scope for capture to see them (an earlier test
        # may have compiled the same (mesh, chunk, mode) key).
        from kmeans_tpu.models import gmm as gmm_mod
        from kmeans_tpu.models import kmeans as kmeans_mod
        kmeans_mod._STEP_CACHE.clear()
        gmm_mod._STEP_CACHE.clear()
        with cost_mod.collecting():
            engine.add_model("m", km)
            engine.add_model("g", gm)
            engine.warmup()
            st = engine.stats()
        assert st["models"]["m"]["table_bytes"] == km.centroids.nbytes
        assert st["models"]["g"]["table_bytes"] > 0
        assert st["resident_table_bytes"] >= km.centroids.nbytes
        assert st["program_memory"], "warmup under collecting() must " \
            "capture the bucket programs"
        assert all(p["available"] for p in st["program_memory"])
        # BOTH resident families' step caches report (a GMM serves
        # through gmm._STEP_CACHE — review finding).
        caches = {p["cache"] for p in st["program_memory"]}
        assert caches == {"kmeans._STEP_CACHE", "gmm._STEP_CACHE"}
        # Capture off: residency stays, program memory empties.
        assert engine.stats()["program_memory"] == []
    finally:
        engine.close()


def _write_cost_trace(tmp_path, chunk):
    """Trace + capture one device fit.  ``chunk`` must be unique per
    caller: a warm (mesh, chunk, mode) step-cache key would HIT and
    capture only sees programs built while collecting."""
    X = _X(512, 8, seed=17)
    path = tmp_path / "cost_trace.jsonl"
    with trace_mod.tracing(str(path)), cost_mod.collecting():
        _fit_kmeans(X, k=4, chunk=chunk)
    return str(path)


def test_cli_trace_summarize_cost_columns(tmp_path, capsys):
    from kmeans_tpu.cli import trace_main
    path = _write_cost_trace(tmp_path, chunk=224)
    assert trace_main(["summarize", path, "--cost"]) == 0
    out = capsys.readouterr().out
    assert "flops" in out and "bytes" in out
    # The dispatch row carries the captured program's numbers.
    dispatch = [ln for ln in out.splitlines()
                if ln.strip().startswith("dispatch")][0]
    assert "e+" in dispatch or any(c.isdigit() for c in dispatch)
    assert trace_main(["summarize", path, "--cost", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["cost"]["dispatch"]["programs"] >= 1
    assert doc["cost"]["dispatch"]["flops"] > 0


def test_cli_trace_summarize_cost_without_records(tmp_path, capsys):
    """--cost on a trace with no cost.record events: blank columns,
    empty cost block, exit 0 (the satellite's no-records case)."""
    from kmeans_tpu.cli import trace_main
    X = _X(512, 8, seed=19)
    path = tmp_path / "plain_trace.jsonl"
    with trace_mod.tracing(str(path)):          # tracing, NO collector
        _fit_kmeans(X, k=4, chunk=232)
    assert trace_main(["summarize", str(path), "--cost"]) == 0
    out = capsys.readouterr().out
    dispatch = [ln for ln in out.splitlines()
                if ln.strip().startswith("dispatch")][0]
    assert dispatch.rstrip().endswith("-")      # blank cost columns
    assert trace_main(["summarize", str(path), "--cost",
                       "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["cost"] == {}


def test_cli_cost_report_json(capsys):
    from kmeans_tpu.cli import cost_report_main
    rc = cost_report_main(["--families", "kmeans", "--n", "512",
                           "--d", "8", "--k", "4", "--chunk", "248",
                           "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    row = doc["rows"][0]
    assert row["family"] == "kmeans" and row["available"]
    assert row["flops"] > 0 and row["planned_peak_bytes"] > 0
    assert doc["plans"][0]["predicted_peak_bytes"] > 0


def test_cli_cost_report_rejects_unknown_family(capsys):
    from kmeans_tpu.cli import cost_report_main
    assert cost_report_main(["--families", "nope"]) == 2


def test_cli_cost_report_via_main(monkeypatch, capsys):
    from kmeans_tpu.__main__ import main as pkg_main
    monkeypatch.setattr(sys, "argv", [
        "kmeans_tpu", "cost-report", "--families", "kmeans",
        "--n", "512", "--d", "8", "--k", "4", "--chunk", "256",
        "--json"])
    assert pkg_main() == 0
    assert json.loads(capsys.readouterr().out)["rows"]


def test_ttfi_rows_join_cost(tmp_path):
    path = _write_cost_trace(tmp_path, chunk=264)
    records = trace_mod.read_jsonl(path)
    rows = obs.time_to_first_iteration(records)
    fd = rows[-1]
    assert fd["phase"] == "first_dispatch"
    assert fd["flops"] > 0 and fd["ai"] > 0


def test_merge_cost_empty_without_records():
    with trace_mod.tracing() as tr:
        with trace_mod.span("dispatch"):
            pass
    assert obs.merge_cost(tr.records()) == {}


# ---------------------------------------------------------------------------
# obs namespace regression (the heartbeat-shadowing satellite)
# ---------------------------------------------------------------------------

def test_obs_package_reexports_heartbeat_names():
    """`from kmeans_tpu.obs import note_progress` (and Heartbeat /
    get_heartbeat) must work at package level: the `heartbeat` SCOPE
    callable shadows the submodule attribute, so the submodule's names
    are re-exported explicitly."""
    from kmeans_tpu.obs import Heartbeat, get_heartbeat, note_progress
    assert callable(note_progress) and callable(get_heartbeat)
    assert isinstance(Heartbeat, type)
    # The package attribute IS the scope callable (kept deliberately)...
    assert callable(obs.heartbeat)
    from kmeans_tpu.obs.heartbeat import heartbeat as hb_fn
    assert obs.heartbeat is hb_fn
    # ...while the submodule stays importable via sys.modules (note:
    # `import kmeans_tpu.obs.heartbeat as m` resolves the shadowed
    # ATTRIBUTE and yields the function — importlib/from-imports are
    # the supported routes, and this pin documents exactly that).
    import importlib
    hb_mod = importlib.import_module("kmeans_tpu.obs.heartbeat")
    assert hb_mod.note_progress is note_progress
    assert sys.modules["kmeans_tpu.obs.heartbeat"] is hb_mod
    for name in ("note_progress", "Heartbeat", "get_heartbeat",
                 "cost", "memory"):
        assert name in obs.__all__


def test_obs_package_exposes_cost_and_memory():
    assert obs.cost is cost_mod
    assert obs.memory is memory_mod
    assert callable(obs.cost.collecting)
    assert callable(obs.memory.plan_fit)
