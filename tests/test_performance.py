"""Test B capability: the stress benchmark as a smoke test
(kmeans_spark.py:402-454): 100k x 10 standard-normal points (seed 42), k=5,
max_iter=20, SSE off, 4-way parallelism; completes and reports sane timing.
Unlike the reference we (a) count iterations correctly — its per-iteration
time divides by max_iter even on early convergence (:433-438, SURVEY.md
§2.2 T2 bug) — and (b) exclude compile/warmup from timing.
"""

import time

import numpy as np

from kmeans_tpu import KMeans
from kmeans_tpu.data.synthetic import make_gaussian
from kmeans_tpu.parallel.mesh import make_mesh


def test_stress_100k(mesh8):
    X = make_gaussian(100_000, 10, random_state=42, dtype=np.float32)
    km = KMeans(k=5, max_iter=20, tolerance=1e-4, seed=42,
                compute_sse=False, mesh=mesh8, verbose=False)
    start = time.perf_counter()
    km.fit(X)
    total = time.perf_counter() - start
    assert km.iterations_run >= 1
    per_iter = total / km.iterations_run   # correct denominator
    assert np.all(np.isfinite(km.centroids))
    assert per_iter < 30.0                 # generous CI bound; TPU is ~ms
