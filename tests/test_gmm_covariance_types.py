"""GaussianMixture covariance_type 'spherical'/'tied'/'full' (r3 VERDICT
#5 — diag-only was an immediate wall for sklearn users, whose default is
'full').  Parity oracle: sklearn.mixture.GaussianMixture with shared
init and tolerance on correlated-covariance fixtures."""

import numpy as np
import pytest

from kmeans_tpu import GaussianMixture

ALL_TYPES = ("diag", "spherical", "tied", "full")


def _correlated_blobs(n_per=800, seed=0):
    """Three 2-D blobs, two with strong feature correlation — the shape
    diag covariances cannot represent."""
    rng = np.random.default_rng(seed)
    A1 = np.array([[1.0, 0.8], [0.0, 0.6]])
    A2 = np.array([[0.5, -0.4], [0.3, 1.0]])
    X = np.concatenate([
        rng.normal(size=(n_per, 2)) @ A1.T + [5, 5],
        rng.normal(size=(n_per, 2)) @ A2.T + [-5, -3],
        rng.normal(size=(n_per, 2)) * 0.7 + [5, -6]])
    return X.astype(np.float32)


INIT = np.array([[5, 5], [-5, -3], [5, -6]], np.float64)
COV_SHAPES = {"diag": (3, 2), "spherical": (3,), "tied": (2, 2),
              "full": (3, 2, 2)}


@pytest.fixture(scope="module")
def Xc():
    return _correlated_blobs()


@pytest.mark.parametrize("ct", ALL_TYPES)
def test_matches_sklearn_shared_init(ct, Xc):
    skm = pytest.importorskip("sklearn.mixture")
    gm = GaussianMixture(n_components=3, covariance_type=ct,
                         means_init=INIT, max_iter=60, tol=1e-5,
                         seed=0).fit(Xc)
    sk = skm.GaussianMixture(n_components=3, covariance_type=ct,
                             means_init=INIT, max_iter=60, tol=1e-5,
                             random_state=0).fit(Xc.astype(np.float64))
    assert gm.covariances_.shape == COV_SHAPES[ct]
    np.testing.assert_allclose(gm.lower_bound_, sk.lower_bound_,
                               rtol=1e-4)
    np.testing.assert_allclose(gm.means_, sk.means_, atol=5e-2)
    np.testing.assert_allclose(gm.covariances_, sk.covariances_,
                               rtol=0.1, atol=5e-2)
    np.testing.assert_allclose(gm.weights_, sk.weights_, atol=1e-2)


def test_full_beats_diag_on_correlated_data(Xc):
    """The capability justification: on correlated clusters the full
    model must reach a strictly better lower bound than diag."""
    kw = dict(n_components=3, means_init=INIT, max_iter=60, tol=1e-5,
              seed=0)
    full = GaussianMixture(covariance_type="full", **kw).fit(Xc)
    diag = GaussianMixture(covariance_type="diag", **kw).fit(Xc)
    assert full.lower_bound_ > diag.lower_bound_ + 0.05


@pytest.mark.parametrize("ct", ("tied", "full"))
def test_model_sharded_matches_single_device(ct, Xc, mesh4x2, mesh1):
    """Component (model-axis) sharding composes with the non-diag
    densities: the tied/full E-step's cross-shard softmax normalizer and
    scatter psum must reproduce the single-device fit."""
    kw = dict(n_components=3, covariance_type=ct, means_init=INIT,
              max_iter=25, tol=1e-5, seed=0)
    a = GaussianMixture(mesh=mesh4x2, **kw).fit(Xc)
    b = GaussianMixture(mesh=mesh1, **kw).fit(Xc)
    np.testing.assert_allclose(a.lower_bound_, b.lower_bound_, rtol=1e-5)
    np.testing.assert_allclose(a.means_, b.means_, atol=1e-4)
    np.testing.assert_allclose(a.covariances_, b.covariances_, atol=1e-4)
    np.testing.assert_array_equal(a.predict(Xc), b.predict(Xc))


def test_spherical_device_loop_matches_host(Xc, mesh8):
    kw = dict(n_components=3, covariance_type="spherical",
              means_init=INIT, max_iter=25, tol=1e-6, seed=0, mesh=mesh8,
              dtype=np.float64)
    host = GaussianMixture(host_loop=True, **kw).fit(Xc)
    dev = GaussianMixture(host_loop=False, **kw).fit(Xc)
    np.testing.assert_allclose(dev.lower_bound_, host.lower_bound_,
                               rtol=1e-8)
    np.testing.assert_allclose(dev.covariances_, host.covariances_,
                               rtol=1e-6)
    assert dev.covariances_.shape == (3,)


@pytest.mark.parametrize("ct", ("tied", "full"))
def test_full_tied_device_loop_matches_host(ct, Xc, mesh8):
    """r4: the one-dispatch device loop serves full/tied too (on-device
    batched Cholesky per iteration); float64 makes the two engines'
    trajectories comparable."""
    kw = dict(n_components=3, covariance_type=ct, means_init=INIT,
              max_iter=25, tol=1e-6, seed=0, mesh=mesh8,
              dtype=np.float64)
    host = GaussianMixture(host_loop=True, **kw).fit(Xc)
    dev = GaussianMixture(host_loop=False, **kw).fit(Xc)
    np.testing.assert_allclose(dev.lower_bound_, host.lower_bound_,
                               rtol=1e-7)
    np.testing.assert_allclose(dev.means_, host.means_, atol=1e-6)
    np.testing.assert_allclose(dev.covariances_, host.covariances_,
                               rtol=1e-5, atol=1e-8)
    assert dev.covariances_.shape == host.covariances_.shape


@pytest.mark.parametrize("ct", ("tied", "full"))
def test_full_tied_device_loop_under_model_sharding(ct, Xc, mesh4x2):
    """Device loop + component sharding compose for the new types."""
    kw = dict(n_components=3, covariance_type=ct, means_init=INIT,
              max_iter=20, tol=1e-6, seed=0, dtype=np.float64)
    a = GaussianMixture(mesh=mesh4x2, host_loop=False, **kw).fit(Xc)
    b = GaussianMixture(host_loop=True, **kw).fit(Xc)
    np.testing.assert_allclose(a.lower_bound_, b.lower_bound_, rtol=1e-6)
    np.testing.assert_allclose(a.means_, b.means_, atol=1e-5)


@pytest.mark.parametrize("ct", ALL_TYPES)
def test_posterior_and_sampling_surfaces(ct, Xc):
    gm = GaussianMixture(n_components=3, covariance_type=ct,
                         means_init=INIT, max_iter=30, seed=0).fit(Xc)
    proba = gm.predict_proba(Xc[:100])
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)
    assert np.isfinite(gm.score(Xc))
    S, comp = gm.sample(500)
    assert S.shape == (500, 2) and comp.shape == (500,)
    # Sampled data scores reasonably under the model it came from.
    assert gm.score(S) > gm.score(Xc) - 2.0
    prec = gm.precisions_
    if ct in ("diag", "spherical"):
        assert prec.shape == gm.covariances_.shape
    else:
        # P P^T must invert the covariance.
        eye = np.eye(2)
        cov = gm.covariances_
        prod = prec @ cov if ct == "tied" else np.einsum(
            "kde,kef->kdf", prec, cov)
        np.testing.assert_allclose(prod, np.broadcast_to(
            eye, prod.shape), atol=1e-4)


@pytest.mark.parametrize("ct", ALL_TYPES)
def test_bic_penalty_matches_sklearn(ct, Xc):
    skm = pytest.importorskip("sklearn.mixture")
    gm = GaussianMixture(n_components=3, covariance_type=ct,
                         means_init=INIT, max_iter=20, seed=0).fit(Xc)
    sk = skm.GaussianMixture(n_components=3, covariance_type=ct,
                             means_init=INIT, max_iter=20,
                             random_state=0).fit(Xc.astype(np.float64))
    assert gm._n_parameters() == sk._n_parameters()
    np.testing.assert_allclose(gm.bic(Xc), sk.bic(Xc.astype(np.float64)),
                               rtol=1e-3)


@pytest.mark.parametrize("ct", ("spherical", "tied", "full"))
def test_save_load_roundtrip_types(ct, Xc, tmp_path):
    gm = GaussianMixture(n_components=3, covariance_type=ct,
                         means_init=INIT, max_iter=15, seed=0).fit(Xc)
    gm.save(tmp_path / "gm.npz")
    back = GaussianMixture.load(tmp_path / "gm.npz")
    assert back.covariance_type == ct
    np.testing.assert_array_equal(back.covariances_, gm.covariances_)
    np.testing.assert_array_equal(back.predict(Xc[:200]),
                                  gm.predict(Xc[:200]))


def test_precisions_init_roundtrip_full(Xc):
    """Explicit precisions_init for 'full' is inverted into covariances."""
    prec = np.stack([np.eye(2) * 2.0] * 3)
    gm = GaussianMixture(n_components=3, covariance_type="full",
                         means_init=INIT, precisions_init=prec,
                         max_iter=1, tol=1e12, seed=0).fit(Xc)
    assert gm.covariances_.shape == (3, 2, 2)


def test_ill_defined_covariance_raises():
    """Duplicated rows + reg_covar=0 under 'full' collapse a component's
    covariance to singular: the Cholesky fails with sklearn's
    ill-defined-covariance error, not a cryptic LinAlgError."""
    X = np.concatenate([np.full((200, 2), 3.0),
                        np.random.default_rng(0).normal(
                            size=(200, 2))]).astype(np.float32)
    gm = GaussianMixture(n_components=2, covariance_type="full",
                         reg_covar=0.0, max_iter=10, seed=0,
                         means_init=np.array([[3.0, 3.0], [0.0, 0.0]]))
    with pytest.raises(ValueError,
                       match="ill-defined empirical covariance"):
        gm.fit(X)


# ---- streaming EM (r3 VERDICT #6) --------------------------------------

@pytest.mark.parametrize("ct", ALL_TYPES)
def test_gmm_fit_stream_matches_in_memory(ct, Xc, mesh8):
    """One epoch = one exact E-step: the streamed trajectory must match
    an in-memory fit of the concatenated blocks (mirrors
    test_stream.py::test_stream_matches_in_memory_fit)."""
    blocks = [Xc[:900], Xc[900:1500], Xc[1500:]]
    kw = dict(n_components=3, covariance_type=ct, means_init=INIT,
              max_iter=25, tol=1e-6, seed=0, mesh=mesh8)
    st = GaussianMixture(**kw).fit_stream(
        lambda: iter([b.copy() for b in blocks]))
    mem = GaussianMixture(**kw).fit(Xc)
    np.testing.assert_allclose(st.lower_bound_, mem.lower_bound_,
                               rtol=1e-5)
    np.testing.assert_allclose(st.means_, mem.means_, atol=1e-3)
    np.testing.assert_allclose(st.covariances_, mem.covariances_,
                               rtol=1e-3, atol=1e-3)
    assert abs(st.n_iter_ - mem.n_iter_) <= 1


def test_gmm_fit_stream_named_inits_and_n_init(Xc, mesh8):
    """Named init over the FULL stream + interleaved restarts: the
    winner rule matches in-memory (highest final lower bound)."""
    blocks = [Xc[:1200], Xc[1200:]]
    gm = GaussianMixture(n_components=3, init_params="k-means++",
                         n_init=2, max_iter=20, tol=1e-5, seed=0,
                         mesh=mesh8)
    gm.fit_stream(lambda: iter([b.copy() for b in blocks]))
    assert np.isfinite(gm.lower_bound_)
    assert gm.restart_lower_bounds_.shape == (2,)
    assert gm.lower_bound_ == gm.restart_lower_bounds_.max()
    labels = gm.predict(Xc)
    assert len(np.unique(labels)) == 3


def test_gmm_fit_stream_guards(mesh8):
    gm = GaussianMixture(n_components=5)
    with pytest.raises(ValueError, match="Not enough data points"):
        gm.fit_stream(lambda: iter([np.zeros((3, 2), np.float32)]))
    gm2 = GaussianMixture(n_components=2,
                          means_init=np.zeros((2, 2)))
    exhausted = iter([np.random.default_rng(0).normal(
        size=(64, 2)).astype(np.float32)])
    with pytest.raises(ValueError, match="FRESH iterable"):
        gm2.fit_stream(lambda: exhausted)


def test_gmm_fit_stream_restart_resilience(Xc, mesh8, monkeypatch):
    """A failing restart in the streamed interleaved sweep is dropped
    with a warning (same contract as fit(), r3 ADVICE)."""
    blocks = [Xc[:1200], Xc[1200:]]
    gm = GaussianMixture(n_components=3, init_params="random", n_init=3,
                         max_iter=10, tol=1e-5, seed=0, mesh=mesh8)
    orig = GaussianMixture._params_dev
    calls = {"n": 0}

    def flaky(self, mesh, **kw):       # kw: guard_cholesky (ISSUE 5)
        calls["n"] += 1
        if calls["n"] == 2:            # second restart's first epoch
            raise ValueError(
                "ill-defined empirical covariance (synthetic)")
        return orig(self, mesh, **kw)

    monkeypatch.setattr(GaussianMixture, "_params_dev", flaky)
    with pytest.warns(UserWarning, match="restart 2/3 failed"):
        gm.fit_stream(lambda: iter([b.copy() for b in blocks]))
    assert np.isfinite(gm.lower_bound_)
    assert gm.restart_lower_bounds_[1] == -np.inf


@pytest.mark.parametrize("ct", ("diag", "full"))
def test_gmm_predict_stream_matches_predict(ct, Xc, mesh8):
    gm = GaussianMixture(n_components=3, covariance_type=ct,
                         means_init=INIT, max_iter=15, seed=0,
                         mesh=mesh8).fit(Xc)
    blocks = [Xc[:700], Xc[700:1500], Xc[1500:]]
    lab = np.concatenate(list(gm.predict_stream(
        lambda: iter([b.copy() for b in blocks]))))
    np.testing.assert_array_equal(lab, gm.predict(Xc))
    lse = np.concatenate(list(gm.score_samples_stream(
        lambda: iter([b.copy() for b in blocks]))))
    np.testing.assert_allclose(lse, gm.score_samples(Xc), rtol=1e-5)


@pytest.mark.parametrize("ct", ("diag", "spherical"))
def test_batched_device_restarts_match_sequential(ct, Xc, mesh8):
    """r4: host_loop=False + n_init>1 runs ALL restarts vmapped through
    ONE EM dispatch (the mixture analogue of KMeans' batched restart
    sweep); winner, per-restart lower bounds, and parameters match the
    host-sequential path."""
    kw = dict(n_components=3, covariance_type=ct, init_params="random",
              max_iter=20, tol=1e-6, seed=0, n_init=3, mesh=mesh8,
              dtype=np.float64)
    a = GaussianMixture(host_loop=False, **kw).fit(Xc)
    b = GaussianMixture(host_loop=True, **kw).fit(Xc)
    # Per-restart lower bounds agree; winner selection can differ only
    # on sub-1e-7 ties (all restarts reaching the same optimum), so the
    # robust invariants are the bound values and the winning model's
    # quality, not the tie-broken index.
    np.testing.assert_allclose(a.restart_lower_bounds_,
                               b.restart_lower_bounds_, rtol=1e-7)
    np.testing.assert_allclose(a.lower_bound_, b.lower_bound_, rtol=1e-7)
    np.testing.assert_allclose(a.score(Xc), b.score(Xc), rtol=1e-7)
    assert abs(a.n_iter_ - b.n_iter_) <= 1    # borderline tol decision
    assert a.restart_lower_bounds_.shape == (3,)


def test_batched_device_restarts_under_model_sharding(Xc, mesh4x2):
    kw = dict(n_components=3, init_params="random", max_iter=15,
              tol=1e-6, seed=1, n_init=2, dtype=np.float64)
    a = GaussianMixture(host_loop=False, mesh=mesh4x2,
                        model_shards=2, **kw).fit(Xc)
    b = GaussianMixture(host_loop=True, **kw).fit(Xc)
    np.testing.assert_allclose(a.restart_lower_bounds_,
                               b.restart_lower_bounds_, rtol=1e-6)
    np.testing.assert_allclose(a.score(Xc), b.score(Xc), rtol=1e-6)


def test_batched_device_restarts_survive_diverged_restart(mesh8):
    """A diverged restart (collapsed component under reg_covar=0)
    surfaces as -inf and cannot win — the batched sweep keeps the
    sequential path's failed-restart resilience."""
    rng = np.random.default_rng(2)
    X = np.concatenate([np.full((400, 4), 5.0),
                        rng.normal(size=(400, 4))]).astype(np.float32)
    # seed=2: two restarts diverge, two survive, on BOTH the CPU mesh
    # and real v5e hardware (probed r5).  Which restarts collapse under
    # reg_covar=0 on the exact-constant block is a per-restart
    # sign-of-rounding-residual coin flip — seed=0's mix flipped to
    # all-diverged on hardware when the diag moment matmuls moved from
    # HIGHEST to the measured-equivalent HIGH; the resilience contract
    # under test is seed-independent.
    gm = GaussianMixture(n_components=2, reg_covar=0.0, max_iter=15,
                         seed=2, init_params="random", n_init=4,
                         host_loop=False, mesh=mesh8)
    with pytest.warns(UserWarning, match="diverged"):
        gm.fit(X)
    lls = gm.restart_lower_bounds_
    assert np.sum(np.isinf(lls)) >= 1 and np.sum(np.isfinite(lls)) >= 1
    assert gm.lower_bound_ == lls[np.isfinite(lls)].max()
    assert np.all(np.isfinite(gm.means_))
    assert np.isfinite(gm.score(X))


def test_batched_device_restarts_survive_init_failure(Xc, mesh8,
                                                      monkeypatch):
    """An init-time exception in one restart keeps the survivors (same
    contract as the sequential path), with indices mapped back to the
    original restart numbering."""
    calls = {"n": 0}
    orig = GaussianMixture._init_params

    def flaky(self, ds, step_fn, seed):
        calls["n"] += 1
        if calls["n"] == 2:               # second restart's init blows up
            raise ValueError("synthetic init failure")
        return orig(self, ds, step_fn, seed)

    monkeypatch.setattr(GaussianMixture, "_init_params", flaky)
    gm = GaussianMixture(n_components=3, init_params="random", n_init=3,
                         max_iter=15, tol=1e-6, seed=0, mesh=mesh8,
                         host_loop=False)
    with pytest.warns(UserWarning, match="failed at init"):
        gm.fit(Xc)
    assert gm.restart_lower_bounds_.shape == (3,)
    assert gm.restart_lower_bounds_[1] == -np.inf
    assert np.isfinite(gm.lower_bound_)
    assert gm.best_restart_ in (0, 2)
