"""Worker for the SIMULATED-fleet observability tests (not a test
module — spawned by tests/test_fleet.py).

Each invocation is one "host" of a simulated fleet: a plain process
(no jax.distributed — the ungated twin of the real multi-process
harness in tests/mh_worker.py) whose fleet identity comes from the
``KMEANS_TPU_PROCESS_INDEX``/``_COUNT`` environment overrides, running
a fully-instrumented host-loop fit whose telemetry lands in the shared
output directory:

* ``trace.p{idx}.jsonl``  — per-process trace (auto-suffixed sink)
* ``hb.p{idx}.jsonl``     — per-process heartbeat stream

``--slow <seconds>`` arms ``faults.inject_checkpoint_delay`` so THIS
host's iterations stretch (fit runs ``checkpoint_every=1``) — the
deterministic straggler the report must flag.  All hosts share one
machine, hence one wall clock: the merge aligns on the wall anchors
(``align='wall'``), exactly the fallback path the simulated fleet is
meant to exercise (the real barrier path is covered by mh_worker.py).
"""

import argparse
import os
import sys
from pathlib import Path

parser = argparse.ArgumentParser()
parser.add_argument("index", type=int)
parser.add_argument("count", type=int)
parser.add_argument("out_dir")
parser.add_argument("--slow", type=float, default=0.0)
args = parser.parse_args()

os.environ["KMEANS_TPU_PROCESS_INDEX"] = str(args.index)
os.environ["KMEANS_TPU_PROCESS_COUNT"] = str(args.count)
os.environ["KMEANS_TPU_HOST"] = f"simhost{args.index}"
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=1")

import contextlib  # noqa: E402

import numpy as np  # noqa: E402

from kmeans_tpu import KMeans, obs  # noqa: E402
from kmeans_tpu.utils import faults  # noqa: E402

out = Path(args.out_dir)
rng = np.random.default_rng(0)
# Structureless data: Lloyd keeps moving for many iterations, so the
# tolerance never fires and every host runs the full max_iter budget
# (the straggler comparison needs equal iteration counts).
X = rng.normal(size=(2000, 8)).astype(np.float32)
init = X[rng.choice(2000, size=4, replace=False)]

slow = (faults.inject_checkpoint_delay(args.slow) if args.slow
        else contextlib.nullcontext({"fired": 0}))
# A sub-epsilon tolerance runs every iteration; checkpoint_every=1
# gives the delay hook an every-iteration boundary; host_loop=True
# emits one heartbeat per iteration (the fleet-status cadence).
with obs.tracing(out / "trace.jsonl") as tr, \
        obs.heartbeat(out / "hb.jsonl") as hb, slow as rec:
    km = KMeans(k=4, seed=0, init=init, max_iter=8, tolerance=1e-30,
                empty_cluster="keep", compute_sse=True, host_loop=True,
                verbose=False)
    km.fit(X, checkpoint_every=1,
           checkpoint_path=out / f"ckpt_{args.index}.npz")

assert km.iterations_run == 8, km.iterations_run
if args.slow:
    assert rec["fired"] >= 8, rec
assert hb.resolved_path == str(out / f"hb.p{args.index}.jsonl"), \
    hb.resolved_path
ident = tr.identity()
assert ident["process_index"] == args.index, ident
assert ident["process_count"] == args.count, ident
np.save(out / f"centroids_{args.index}.npy", km.centroids)
print(f"fleet worker {args.index}/{args.count}: OK "
      f"iters={km.iterations_run}", flush=True)
sys.exit(0)
