"""Examples stay importable/runnable.

Each example is compiled (syntax + top-level structure) and the fastest one
is executed end-to-end as a subprocess smoke test on the CPU test platform.
"""

import os
import py_compile
import subprocess
import sys
from pathlib import Path

import pytest

EXAMPLES = sorted((Path(__file__).parent.parent / "examples").glob("*.py"))


def test_examples_exist():
    assert len(EXAMPLES) >= 5


@pytest.mark.parametrize("path", EXAMPLES, ids=lambda p: p.name)
def test_example_compiles(path):
    py_compile.compile(str(path), doraise=True)


def test_quickstart_runs():
    quickstart = next(p for p in EXAMPLES if "quickstart" in p.name)
    repo_root = quickstart.parent.parent
    env = dict(os.environ, PYTHONPATH=str(repo_root),
               JAX_PLATFORMS="cpu")   # hermetic: don't grab the TPU
    proc = subprocess.run(
        [sys.executable, str(quickstart)],
        capture_output=True, text=True, timeout=600,
        cwd=repo_root, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "final SSE" in proc.stdout


def test_model_selection_sweep_runs():
    """Example 08 (ISSUE 7): the batched multi-k sweep walkthrough runs
    end-to-end and its one-dispatch claim + oracle agreement asserts
    hold (the example itself asserts batched == sequential selection)."""
    sweep = next(p for p in EXAMPLES if "model_selection_sweep" in p.name)
    repo_root = sweep.parent.parent
    env = dict(os.environ, PYTHONPATH=str(repo_root),
               JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, str(sweep)],
        capture_output=True, text=True, timeout=600,
        cwd=repo_root, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "selected k=" in proc.stdout
    assert "1 device dispatch" in proc.stdout
    assert "sequential oracle agrees" in proc.stdout
