"""``host_loop='auto'`` resolution (r4 VERDICT #6): on high-dispatch-
latency platforms the default config must either switch to the
one-dispatch device loop (when semantically interchangeable) or say,
once, where the wall time goes — and must stay deterministically on the
host path on fast platforms.

Latency is SIMULATED by patching ``_dispatch_rtt`` (the tunneled-TPU
RTT is ~70-100 ms; CPU dispatch is µs, under the 5 ms absolute floor).
"""

import numpy as np
import pytest

import kmeans_tpu.models.kmeans as km_mod
from kmeans_tpu import KMeans
from kmeans_tpu.models import DispatchLatencyHint, SphericalKMeans


@pytest.fixture(autouse=True)
def _fresh_auto_state():
    """Per-test isolation of the once-per-process hint set and the
    (rtt, step) measurement cache — patched RTTs must not leak."""
    km_mod._HINTS_EMITTED.clear()
    km_mod._AUTO_CACHE.clear()
    yield
    km_mod._HINTS_EMITTED.clear()
    km_mod._AUTO_CACHE.clear()


@pytest.fixture
def data():
    rng = np.random.default_rng(0)
    return (rng.normal(size=(600, 6))
            + 8.0 * rng.integers(0, 4, size=(600, 1))).astype(np.float32)


def _spy_device_paths(monkeypatch):
    calls = []
    orig_single = KMeans._fit_on_device
    orig_multi = KMeans._fit_on_device_multi

    def spy_single(self, *a, **kw):
        calls.append("device")
        return orig_single(self, *a, **kw)

    def spy_multi(self, *a, **kw):
        calls.append("device_multi")
        return orig_multi(self, *a, **kw)

    monkeypatch.setattr(KMeans, "_fit_on_device", spy_single)
    monkeypatch.setattr(KMeans, "_fit_on_device_multi", spy_multi)
    return calls


def test_auto_stays_host_on_fast_platform(data, mesh8, monkeypatch):
    """µs-level dispatch (any local platform) stays under the 5 ms
    absolute floor: 'auto' is deterministically the host loop, no hint."""
    calls = _spy_device_paths(monkeypatch)
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("error", DispatchLatencyHint)
        km = KMeans(k=4, seed=0, mesh=mesh8, verbose=False,
                    empty_cluster="keep").fit(data)
    assert km.host_loop == "auto"          # the constructor default
    assert calls == []
    assert km.centroids.shape == (4, 6)


def test_auto_switches_to_device_loop_on_high_latency(data, mesh8,
                                                      monkeypatch):
    """Simulated 1 s RTT (>5 ms and >25% of any CPU step) + verbose=False
    + base hooks -> the fit runs as ONE device dispatch, says so once,
    and matches the host loop's trajectory."""
    monkeypatch.setattr(km_mod, "_dispatch_rtt", lambda mesh: 1.0)
    calls = _spy_device_paths(monkeypatch)
    kw = dict(k=4, seed=0, mesh=mesh8, verbose=False, compute_sse=True,
              dtype=np.float64, empty_cluster="keep")
    with pytest.warns(DispatchLatencyHint, match="one device dispatch"):
        auto = KMeans(host_loop="auto", **kw).fit(data)
    assert calls == ["device"]
    host = KMeans(host_loop=True, **kw).fit(data)
    np.testing.assert_allclose(auto.centroids, host.centroids, atol=1e-9)
    np.testing.assert_allclose(auto.sse_history, host.sse_history,
                               rtol=1e-9)

    # The hint is once-per-process: a second fit is silent.
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("error", DispatchLatencyHint)
        KMeans(host_loop="auto", **kw).fit(data)


def test_auto_batched_restart_sweep_on_high_latency(data, mesh8,
                                                    monkeypatch):
    """n_init > 1 under the switch takes the BATCHED one-dispatch sweep."""
    monkeypatch.setattr(km_mod, "_dispatch_rtt", lambda mesh: 1.0)
    calls = _spy_device_paths(monkeypatch)
    km = KMeans(k=4, n_init=3, seed=0, mesh=mesh8, verbose=False,
                empty_cluster="keep").fit(data)
    assert calls == ["device_multi"]
    assert km.restart_inertias_.shape == (3,)


def test_auto_hints_but_stays_host_when_verbose(data, mesh8, monkeypatch,
                                                capsys):
    """verbose=True keeps the reference's per-iteration logging: no
    switch, but the one-time hint names the dispatch share."""
    monkeypatch.setattr(km_mod, "_dispatch_rtt", lambda mesh: 1.0)
    calls = _spy_device_paths(monkeypatch)
    with pytest.warns(DispatchLatencyHint, match="host dispatch"):
        km = KMeans(k=4, seed=0, mesh=mesh8, verbose=True,
                    empty_cluster="keep").fit(data)
    assert calls == []
    assert km.iterations_run > 0
    assert "Iteration 1" in capsys.readouterr().out


def test_auto_respects_host_side_hooks(data, mesh8, monkeypatch):
    """A subclass with host-side Lloyd hooks must never be routed to the
    device loop, and the one-time hint says why.  (SphericalKMeans pins
    host_loop=True structurally — tested below — so this exercises the
    defensive hook check with a user-defined subclass.)"""
    monkeypatch.setattr(km_mod, "_dispatch_rtt", lambda mesh: 1.0)
    calls = _spy_device_paths(monkeypatch)

    class Nudged(KMeans):
        def _postprocess_centroids(self, centroids, prev=None):
            return centroids + 0.0

    with pytest.warns(DispatchLatencyHint, match="host-side hooks"):
        nk = Nudged(k=4, seed=0, mesh=mesh8, verbose=False,
                    empty_cluster="keep").fit(data)
    assert calls == []
    assert nk.iterations_run > 0


def test_spherical_auto_switches_on_high_latency(data, mesh8, monkeypatch):
    """ISSUE 2 satellite (drops the r5 host_loop=True pin): the sphere
    projection now has a device twin folded into the one-dispatch loop,
    so SphericalKMeans resolves 'auto' exactly like the base class —
    high simulated RTT + verbose=False switches to the device loop, and
    the trajectory matches the host loop."""
    monkeypatch.setattr(km_mod, "_dispatch_rtt", lambda mesh: 1.0)
    calls = _spy_device_paths(monkeypatch)
    kw = dict(k=4, seed=0, mesh=mesh8, verbose=False, compute_sse=True,
              dtype=np.float64, empty_cluster="keep")
    sk = SphericalKMeans(host_loop="auto", **kw)
    assert sk.host_loop == "auto"          # inherited default survives
    with pytest.warns(DispatchLatencyHint, match="one device dispatch"):
        sk.fit(data)
    assert calls == ["device"]
    host = SphericalKMeans(host_loop=True, **kw).fit(data)
    np.testing.assert_allclose(sk.centroids, host.centroids, atol=1e-9)
    np.testing.assert_allclose(sk.sse_history, host.sse_history, rtol=1e-9)
    np.testing.assert_allclose(np.linalg.norm(sk.centroids, axis=1), 1.0,
                               atol=1e-12)


def test_spherical_subclass_override_stays_host(data, mesh8, monkeypatch):
    """A user subclass overriding _postprocess_centroids loses the
    device-equivalent tag: 'auto' must keep it on the host loop."""
    monkeypatch.setattr(km_mod, "_dispatch_rtt", lambda mesh: 1.0)
    calls = _spy_device_paths(monkeypatch)

    class Nudged(SphericalKMeans):
        def _postprocess_centroids(self, centroids, prev=None):
            return super()._postprocess_centroids(centroids, prev)

    with pytest.warns(DispatchLatencyHint, match="host-side hooks"):
        Nudged(k=4, seed=0, mesh=mesh8, verbose=False,
               empty_cluster="keep").fit(data)
    assert calls == []


def test_minibatch_auto_switches_on_high_latency(data, mesh8, monkeypatch):
    """MiniBatch's device-sampling engine resolves 'auto' too (its batch
    step is sub-ms, so RTT past the floor is dispatch-bound by
    construction): verbose=False switches to the bit-matched one-dispatch
    loop; verbose=True hints and stays."""
    from kmeans_tpu.models import MiniBatchKMeans
    monkeypatch.setattr(km_mod, "_dispatch_rtt", lambda mesh: 1.0)
    loop_calls = []
    orig = MiniBatchKMeans._fit_device_loop

    def spy(self, *a, **kw):
        loop_calls.append(1)
        return orig(self, *a, **kw)

    monkeypatch.setattr(MiniBatchKMeans, "_fit_device_loop", spy)
    kw = dict(k=4, seed=0, mesh=mesh8, batch_size=128, max_iter=6,
              empty_cluster="keep")
    with pytest.warns(DispatchLatencyHint, match="mini-batch"):
        auto = MiniBatchKMeans(verbose=False, **kw).fit(data)
    assert loop_calls == [1]
    host = MiniBatchKMeans(verbose=False, host_loop=True, **kw).fit(data)
    np.testing.assert_allclose(auto.centroids, host.centroids, atol=1e-5)

    km_mod._HINTS_EMITTED.clear()
    with pytest.warns(DispatchLatencyHint, match="round trips"):
        MiniBatchKMeans(verbose=True, **kw).fit(data)
    assert loop_calls == [1]          # verbose fit stayed per-iteration


def test_host_loop_normalization():
    """Bool-likes normalize so identity checks can't misroute them
    (review r5: np.False_ passed ==-validation but failed `is False`)."""
    assert KMeans(k=3, host_loop=np.False_).host_loop is False
    assert KMeans(k=3, host_loop=1).host_loop is True
    assert KMeans(k=3, host_loop=0).host_loop is False
    from kmeans_tpu import GaussianMixture
    with pytest.raises(ValueError, match="KMeans-only"):
        GaussianMixture(n_components=2, host_loop="auto")


def test_auto_resample_with_host_copy_stays_host(data, mesh8, monkeypatch):
    """empty_cluster='resample' (the DEFAULT) on a host-resident dataset
    draws replacements with the host rng; the device loop draws with the
    on-device Gumbel engine.  'auto' must not make results
    platform-dependent: it stays host-side and says why.  A hostless
    (device-only) dataset uses the Gumbel engine in BOTH loops, so there
    the switch is allowed."""
    monkeypatch.setattr(km_mod, "_dispatch_rtt", lambda mesh: 1.0)
    calls = _spy_device_paths(monkeypatch)
    kw = dict(k=4, seed=0, mesh=mesh8, verbose=False)
    with pytest.warns(DispatchLatencyHint, match="resample"):
        KMeans(**kw).fit(data)                # default empty_cluster
    assert calls == []

    km_mod._HINTS_EMITTED.clear()
    km = KMeans(**kw)
    ds = km.cache(data)
    ds._host = None                           # device-only dataset
    ds._host_weights = None
    with pytest.warns(DispatchLatencyHint, match="one device dispatch"):
        km.fit(ds)
    assert calls == ["device"]


def test_explicit_host_loop_skips_measurement(data, mesh8, monkeypatch):
    """Explicit True/False are zero-overhead: the RTT probe never runs."""
    def boom(mesh):
        raise AssertionError("explicit host_loop must not measure RTT")
    monkeypatch.setattr(km_mod, "_dispatch_rtt", boom)
    KMeans(k=4, seed=0, mesh=mesh8, verbose=False, host_loop=True,
           empty_cluster="keep").fit(data)
    KMeans(k=4, seed=0, mesh=mesh8, verbose=False, host_loop=False,
           empty_cluster="keep").fit(data)


def test_host_loop_validation():
    with pytest.raises(ValueError, match="host_loop"):
        KMeans(k=3, host_loop="sometimes")
