"""GaussianMixture (diag EM on the K-Means machinery) vs the sklearn
oracle and its own invariants.  Runs on the 8-virtual-device CPU mesh
like the rest of the suite; the E-step is the same sharded psum pass at
any device count."""

import numpy as np
import pytest

from kmeans_tpu import GaussianMixture
from kmeans_tpu.data.synthetic import make_blobs


def _data(n=4_000, centers=3, d=5, seed=0):
    X, y = make_blobs(n, centers, d, random_state=seed, dtype=np.float32)
    return X, y


def _shared_init(X, k, seed=0):
    """Explicit identical init for trajectory-level sklearn parity."""
    rng = np.random.default_rng(seed)
    means = X[rng.choice(len(X), k, replace=False)].astype(np.float64)
    weights = np.full(k, 1.0 / k)
    precisions = np.ones((k, X.shape[1]))
    return means, weights, precisions


def test_em_matches_sklearn_with_shared_init():
    sklearn_gmm = pytest.importorskip("sklearn.mixture").GaussianMixture
    X, _ = _data()
    k = 3
    means, weights, precisions = _shared_init(X, k)
    ours = GaussianMixture(
        n_components=k, max_iter=15, tol=0.0, reg_covar=1e-6,
        means_init=means, weights_init=weights,
        precisions_init=precisions).fit(X)
    ref = sklearn_gmm(
        n_components=k, covariance_type="diag", max_iter=15, tol=0.0,
        reg_covar=1e-6, means_init=means, weights_init=weights,
        precisions_init=precisions, n_init=1).fit(X.astype(np.float64))
    np.testing.assert_allclose(ours.means_, ref.means_, rtol=2e-3,
                               atol=2e-3)
    np.testing.assert_allclose(ours.weights_, ref.weights_, rtol=2e-3,
                               atol=1e-4)
    np.testing.assert_allclose(ours.covariances_, ref.covariances_,
                               rtol=5e-3, atol=5e-4)
    # Mean log-likelihood (sklearn's lower_bound_ is also per-sample).
    np.testing.assert_allclose(ours.lower_bound_, ref.lower_bound_,
                               rtol=1e-4)
    # Posterior agreement.
    np.testing.assert_allclose(ours.predict_proba(X),
                               ref.predict_proba(X.astype(np.float64)),
                               atol=2e-3)
    assert (ours.predict(X) == ref.predict(X.astype(np.float64))).mean() \
        > 0.999


def test_loglik_monotone_nondecreasing():
    X, _ = _data(seed=3)
    gm = GaussianMixture(n_components=4, max_iter=20, tol=0.0, seed=1,
                         verbose=False)
    history = []
    orig = GaussianMixture._m_step

    def spy(self, st):
        history.append(float(st.loglik))
        return orig(self, st)

    GaussianMixture._m_step = spy
    try:
        gm.fit(X)
    finally:
        GaussianMixture._m_step = orig
    ll = np.array(history[1:])       # skip the hard-assignment init pass
    assert np.all(np.diff(ll) >= -1e-3 * np.abs(ll[:-1])), ll


def test_posterior_rows_sum_to_one_and_score():
    X, _ = _data(seed=4)
    gm = GaussianMixture(n_components=3, max_iter=10, seed=2).fit(X)
    proba = gm.predict_proba(X)
    np.testing.assert_allclose(proba.sum(1), 1.0, atol=1e-5)
    assert proba.shape == (len(X), 3)
    assert np.isfinite(gm.score(X))
    np.testing.assert_allclose(gm.score(X), gm.score_samples(X).mean())
    # labels are the argmax of the posterior
    np.testing.assert_array_equal(gm.predict(X), proba.argmax(1))


def test_recovers_blob_structure():
    X, y = _data(n=6_000, centers=4, d=6, seed=7)
    gm = GaussianMixture(n_components=4, max_iter=50, seed=3).fit(X)
    assert gm.converged_
    labels = gm.predict(X)
    # Cluster/label agreement up to permutation: each true blob maps to
    # one dominant component.
    purity = 0.0
    for c in range(4):
        frac = np.bincount(labels[y == c], minlength=4).max() / (y == c).sum()
        purity += frac / 4
    assert purity > 0.95, purity


def test_sample_weight_equivalence_with_duplication():
    X, _ = _data(n=1_000, seed=5)
    Xdup = np.concatenate([X, X[:300]])
    w = np.ones(len(X), np.float32)
    w[:300] = 2.0
    means, weights, precisions = _shared_init(X, 3, seed=1)
    a = GaussianMixture(n_components=3, max_iter=8, tol=0.0,
                        means_init=means, weights_init=weights,
                        precisions_init=precisions).fit(
        X, sample_weight=w)
    b = GaussianMixture(n_components=3, max_iter=8, tol=0.0,
                        means_init=means, weights_init=weights,
                        precisions_init=precisions).fit(Xdup)
    np.testing.assert_allclose(a.means_, b.means_, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(a.weights_, b.weights_, rtol=1e-4)


def test_sample_and_information_criteria():
    X, _ = _data(seed=6)
    gm = GaussianMixture(n_components=3, max_iter=10, seed=4).fit(X)
    S, comp = gm.sample(500)
    assert S.shape == (500, X.shape[1]) and comp.shape == (500,)
    assert set(np.unique(comp)) <= set(range(3))
    # More components should not catastrophically improve BIC on 3 blobs.
    assert np.isfinite(gm.bic(X)) and np.isfinite(gm.aic(X))
    assert gm.bic(X) > gm.aic(X) - 1e9


def test_guards():
    with pytest.raises(ValueError, match="covariance_type"):
        GaussianMixture(covariance_type="full")
    with pytest.raises(ValueError, match="n_components"):
        GaussianMixture(n_components=0)
    with pytest.raises(ValueError, match="init_params"):
        GaussianMixture(init_params="bogus")
    gm = GaussianMixture(n_components=2)
    with pytest.raises(ValueError, match="fitted before prediction"):
        gm.predict(np.zeros((4, 2), np.float32))
    with pytest.raises(ValueError, match="NaN or Inf"):
        GaussianMixture(n_components=2).fit(
            np.array([[1.0, np.nan]], np.float32))


def test_cached_dataset_roundtrip():
    X, _ = _data(seed=8)
    gm = GaussianMixture(n_components=3, max_iter=10, seed=5)
    gm.fit(X)
    ds = gm._dataset(X)
    np.testing.assert_array_equal(gm.predict(ds), gm.predict(X))
