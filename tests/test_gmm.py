"""GaussianMixture (diag EM on the K-Means machinery) vs the sklearn
oracle and its own invariants.  Runs on the 8-virtual-device CPU mesh
like the rest of the suite; the E-step is the same sharded psum pass at
any device count."""

import numpy as np
import pytest

from kmeans_tpu import GaussianMixture
from kmeans_tpu.data.synthetic import make_blobs


def _data(n=4_000, centers=3, d=5, seed=0):
    X, y = make_blobs(n, centers, d, random_state=seed, dtype=np.float32)
    return X, y


def _shared_init(X, k, seed=0):
    """Explicit identical init for trajectory-level sklearn parity."""
    rng = np.random.default_rng(seed)
    means = X[rng.choice(len(X), k, replace=False)].astype(np.float64)
    weights = np.full(k, 1.0 / k)
    precisions = np.ones((k, X.shape[1]))
    return means, weights, precisions


def test_em_matches_sklearn_with_shared_init():
    sklearn_gmm = pytest.importorskip("sklearn.mixture").GaussianMixture
    X, _ = _data()
    k = 3
    means, weights, precisions = _shared_init(X, k)
    ours = GaussianMixture(
        n_components=k, max_iter=15, tol=0.0, reg_covar=1e-6,
        means_init=means, weights_init=weights,
        precisions_init=precisions).fit(X)
    ref = sklearn_gmm(
        n_components=k, covariance_type="diag", max_iter=15, tol=0.0,
        reg_covar=1e-6, means_init=means, weights_init=weights,
        precisions_init=precisions, n_init=1).fit(X.astype(np.float64))
    np.testing.assert_allclose(ours.means_, ref.means_, rtol=2e-3,
                               atol=2e-3)
    np.testing.assert_allclose(ours.weights_, ref.weights_, rtol=2e-3,
                               atol=1e-4)
    np.testing.assert_allclose(ours.covariances_, ref.covariances_,
                               rtol=5e-3, atol=5e-4)
    # Mean log-likelihood (sklearn's lower_bound_ is also per-sample).
    np.testing.assert_allclose(ours.lower_bound_, ref.lower_bound_,
                               rtol=1e-4)
    # Posterior agreement.
    np.testing.assert_allclose(ours.predict_proba(X),
                               ref.predict_proba(X.astype(np.float64)),
                               atol=2e-3)
    assert (ours.predict(X) == ref.predict(X.astype(np.float64))).mean() \
        > 0.999


def test_loglik_monotone_nondecreasing():
    X, _ = _data(seed=3)
    gm = GaussianMixture(n_components=4, max_iter=20, tol=0.0, seed=1,
                         verbose=False)
    history = []
    orig = GaussianMixture._m_step

    def spy(self, st):
        history.append(float(st.loglik))
        return orig(self, st)

    GaussianMixture._m_step = spy
    try:
        gm.fit(X)
    finally:
        GaussianMixture._m_step = orig
    ll = np.array(history[1:])       # skip the hard-assignment init pass
    assert np.all(np.diff(ll) >= -1e-3 * np.abs(ll[:-1])), ll


def test_fit_predict_matches_fit_then_predict():
    X, _ = _data(n=1_000, seed=19)
    kw = dict(n_components=3, max_iter=8, seed=2)
    labels = GaussianMixture(**kw).fit_predict(X)
    ref = GaussianMixture(**kw).fit(X).predict(X)
    np.testing.assert_array_equal(labels, ref)


def test_posterior_rows_sum_to_one_and_score():
    X, _ = _data(seed=4)
    gm = GaussianMixture(n_components=3, max_iter=10, seed=2).fit(X)
    proba = gm.predict_proba(X)
    np.testing.assert_allclose(proba.sum(1), 1.0, atol=1e-5)
    assert proba.shape == (len(X), 3)
    assert np.isfinite(gm.score(X))
    np.testing.assert_allclose(gm.score(X), gm.score_samples(X).mean())
    # labels are the argmax of the posterior
    np.testing.assert_array_equal(gm.predict(X), proba.argmax(1))


def test_recovers_blob_structure():
    X, y = _data(n=6_000, centers=4, d=6, seed=7)
    gm = GaussianMixture(n_components=4, max_iter=50, seed=3).fit(X)
    assert gm.converged_
    labels = gm.predict(X)
    # Cluster/label agreement up to permutation: each true blob maps to
    # one dominant component.
    purity = 0.0
    for c in range(4):
        frac = np.bincount(labels[y == c], minlength=4).max() / (y == c).sum()
        purity += frac / 4
    assert purity > 0.95, purity


def test_sample_weight_equivalence_with_duplication():
    X, _ = _data(n=1_000, seed=5)
    Xdup = np.concatenate([X, X[:300]])
    w = np.ones(len(X), np.float32)
    w[:300] = 2.0
    means, weights, precisions = _shared_init(X, 3, seed=1)
    a = GaussianMixture(n_components=3, max_iter=8, tol=0.0,
                        means_init=means, weights_init=weights,
                        precisions_init=precisions).fit(
        X, sample_weight=w)
    b = GaussianMixture(n_components=3, max_iter=8, tol=0.0,
                        means_init=means, weights_init=weights,
                        precisions_init=precisions).fit(Xdup)
    np.testing.assert_allclose(a.means_, b.means_, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(a.weights_, b.weights_, rtol=1e-4)


def test_sample_and_information_criteria():
    X, _ = _data(seed=6)
    gm = GaussianMixture(n_components=3, max_iter=10, seed=4).fit(X)
    S, comp = gm.sample(500)
    assert S.shape == (500, X.shape[1]) and comp.shape == (500,)
    assert set(np.unique(comp)) <= set(range(3))
    # More components should not catastrophically improve BIC on 3 blobs.
    assert np.isfinite(gm.bic(X)) and np.isfinite(gm.aic(X))
    assert gm.bic(X) > gm.aic(X) - 1e9


def test_guards():
    with pytest.raises(ValueError, match="covariance_type"):
        GaussianMixture(covariance_type="banana")
    with pytest.raises(ValueError, match="n_components"):
        GaussianMixture(n_components=0)
    with pytest.raises(ValueError, match="init_params"):
        GaussianMixture(init_params="bogus")
    gm = GaussianMixture(n_components=2)
    with pytest.raises(ValueError, match="fitted before prediction"):
        gm.predict(np.zeros((4, 2), np.float32))
    with pytest.raises(ValueError, match="NaN or Inf"):
        GaussianMixture(n_components=2).fit(
            np.array([[1.0, np.nan]], np.float32))


def test_cached_dataset_roundtrip():
    X, _ = _data(seed=8)
    gm = GaussianMixture(n_components=3, max_iter=10, seed=5)
    gm.fit(X)
    ds = gm._dataset(X)
    np.testing.assert_array_equal(gm.predict(ds), gm.predict(X))


# ---------------------------------------------------------------- round 3:
# composition with the framework's engines (r2 VERDICT next-round #3) and
# the r2 ADVICE numerics fixes.


def _fit_kw(**kw):
    X, _ = _data(n=3_000, centers=4, d=6, seed=12)
    means, weights, precisions = _shared_init(X, 4, seed=2)
    gm = GaussianMixture(n_components=4, max_iter=12, tol=0.0,
                         means_init=means, weights_init=weights,
                         precisions_init=precisions, **kw).fit(X)
    return X, gm


@pytest.mark.parametrize("mesh_name", ["mesh8", "mesh4x2"])
def test_sharded_fit_matches_single_device(mesh_name, request, mesh1):
    """Data sharding AND component (model-axis) sharding are numerically
    inert: the mesh4x2 fit row-shards the (k, D) parameter tables."""
    mesh = request.getfixturevalue(mesh_name)
    _, ref = _fit_kw(mesh=mesh1)
    _, gm = _fit_kw(mesh=mesh, model_shards=mesh.shape["model"])
    np.testing.assert_allclose(gm.means_, ref.means_, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(gm.covariances_, ref.covariances_,
                               rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(gm.lower_bound_, ref.lower_bound_, rtol=1e-5)


def test_component_sharded_posterior_matches(mesh4x2, mesh1):
    """predict/predict_proba/score agree between replicated and
    component-sharded parameter tables (incl. the k=4 on 2-shard padding
    path via k=5)."""
    X, _ = _data(n=2_000, centers=5, d=4, seed=13)
    kw = dict(n_components=5, max_iter=8, seed=3)
    a = GaussianMixture(**kw, mesh=mesh1).fit(X)
    b = GaussianMixture(**kw, mesh=mesh4x2, model_shards=2)
    # Same parameters, different execution layout.
    b.fit(X)
    b.weights_, b.means_, b.covariances_ = a.weights_, a.means_, \
        a.covariances_
    b.shift_ = a.shift_
    np.testing.assert_allclose(b.predict_proba(X), a.predict_proba(X),
                               atol=1e-5)
    np.testing.assert_allclose(b.score_samples(X), a.score_samples(X),
                               rtol=1e-5, atol=1e-5)
    assert (b.predict(X) == a.predict(X)).mean() > 0.999


@pytest.mark.parametrize("mesh_name", ["mesh1", "mesh8", "mesh4x2"])
def test_device_loop_matches_host_loop(mesh_name, request):
    """host_loop=False (one-dispatch EM under lax.while_loop) follows the
    host loop's trajectory; float64 makes the division paths comparable."""
    mesh = request.getfixturevalue(mesh_name)
    X, _ = _data(n=3_000, centers=4, d=6, seed=12)
    X = X.astype(np.float64)
    means, weights, precisions = _shared_init(X, 4, seed=2)
    kw = dict(n_components=4, max_iter=12, tol=1e-6, dtype=np.float64,
              means_init=means, weights_init=weights,
              precisions_init=precisions, mesh=mesh,
              model_shards=mesh.shape["model"])
    host = GaussianMixture(**kw, host_loop=True).fit(X)
    dev = GaussianMixture(**kw, host_loop=False).fit(X)
    assert dev.n_iter_ == host.n_iter_
    assert dev.converged_ == host.converged_
    np.testing.assert_allclose(dev.means_, host.means_, rtol=1e-9,
                               atol=1e-9)
    np.testing.assert_allclose(dev.covariances_, host.covariances_,
                               rtol=1e-8)
    np.testing.assert_allclose(dev.weights_, host.weights_, rtol=1e-9)
    np.testing.assert_allclose(dev.lower_bound_, host.lower_bound_,
                               rtol=1e-10)


def test_n_init_picks_best_lower_bound():
    X, _ = _data(n=2_000, centers=4, d=5, seed=14)
    gm = GaussianMixture(n_components=4, max_iter=15, seed=9, n_init=3,
                         init_params="random").fit(X)
    assert gm.restart_lower_bounds_.shape == (3,)
    assert gm.best_restart_ == int(np.argmax(gm.restart_lower_bounds_))
    np.testing.assert_allclose(
        gm.lower_bound_, gm.restart_lower_bounds_[gm.best_restart_])
    # Single-restart fit of the winning seed is not WORSE than the sweep.
    assert gm.lower_bound_ >= gm.restart_lower_bounds_.min() - 1e-12


def test_device_loop_n_init(mesh8):
    """n_init restarts compose with the device loop (host-sequential
    restarts, each a one-dispatch fit)."""
    X, _ = _data(n=2_000, centers=3, d=4, seed=15)
    gm = GaussianMixture(n_components=3, max_iter=10, seed=4, n_init=2,
                         init_params="random", host_loop=False,
                         mesh=mesh8).fit(X)
    assert gm.restart_lower_bounds_.shape == (2,)
    assert np.isfinite(gm.lower_bound_)
    assert gm.means_.shape == (3, 4)


def test_offset_data_covariances_not_collapsed():
    """r2 ADVICE (medium): with |mean|/std ~ 1e4, the uncentered f32
    S2/R - mu^2 cancels and covariances collapse to reg_covar.  The
    centered E pass must recover the true ~1.0 variances and match
    sklearn's float64 result."""
    sklearn_gmm = pytest.importorskip("sklearn.mixture").GaussianMixture
    rng = np.random.default_rng(0)
    k, d = 3, 4
    centers = rng.normal(size=(k, d)) * 3 + 1e4    # offset >> spread
    y = rng.integers(0, k, size=4_000)
    X = (centers[y] + rng.normal(size=(4_000, d))).astype(np.float32)
    means = centers.astype(np.float64)
    weights = np.full(k, 1.0 / k)
    precisions = np.ones((k, d))
    ours = GaussianMixture(n_components=k, max_iter=10, tol=0.0,
                           reg_covar=1e-6, means_init=means,
                           weights_init=weights,
                           precisions_init=precisions).fit(X)
    ref = sklearn_gmm(n_components=k, covariance_type="diag", max_iter=10,
                      tol=0.0, reg_covar=1e-6, means_init=means,
                      weights_init=weights, precisions_init=precisions,
                      n_init=1).fit(X.astype(np.float64))
    # Without centering these come out ~reg_covar (1e-6); truth is ~1.
    assert ours.covariances_.min() > 0.5
    np.testing.assert_allclose(ours.covariances_, ref.covariances_,
                               rtol=0.05)
    np.testing.assert_allclose(ours.means_, ref.means_, rtol=1e-6)


def test_log_det_consistent_with_clamped_precision():
    """r2 ADVICE (low): log_det must come from the SAME clamped
    covariance as the precision — densities then integrate to one even
    when covariances_ < reg_covar (reachable via precisions_init)."""
    X, _ = _data(n=1_000, centers=2, d=3, seed=16)
    gm = GaussianMixture(n_components=2, max_iter=3, reg_covar=1e-2,
                         seed=6).fit(X)
    gm.covariances_ = np.full_like(gm.covariances_, 1e-8)  # << reg_covar
    # Explicitly-clamped twin: same density must come out.
    gm2 = GaussianMixture(n_components=2, max_iter=3, reg_covar=1e-2,
                          seed=6).fit(X)
    gm2.covariances_ = np.full_like(gm2.covariances_, 1e-2)
    gm2.weights_, gm2.means_ = gm.weights_, gm.means_
    gm2.shift_ = gm.shift_
    np.testing.assert_allclose(gm.score_samples(X[:100]),
                               gm2.score_samples(X[:100]), rtol=1e-6)


def test_save_load_roundtrip(tmp_path):
    """GMM checkpointing mirrors KMeans.save/load (the reference has no
    serialization, SURVEY.md §5) — incl. the centering shift, so a
    loaded model scores identically."""
    X, _ = _data(n=1_500, centers=3, d=4, seed=17)
    gm = GaussianMixture(n_components=3, max_iter=10, seed=7).fit(X)
    gm.save(tmp_path / "gmm_ckpt")
    loaded = GaussianMixture.load(tmp_path / "gmm_ckpt")
    np.testing.assert_array_equal(loaded.means_, gm.means_)
    np.testing.assert_array_equal(loaded.covariances_, gm.covariances_)
    np.testing.assert_array_equal(loaded.weights_, gm.weights_)
    assert loaded.converged_ == gm.converged_
    assert loaded.n_iter_ == gm.n_iter_
    np.testing.assert_allclose(loaded.lower_bound_, gm.lower_bound_)
    np.testing.assert_array_equal(loaded.predict(X), gm.predict(X))
    np.testing.assert_allclose(loaded.score_samples(X),
                               gm.score_samples(X), rtol=1e-6)
    # Unfitted round-trip keeps config, no fitted state.
    GaussianMixture(n_components=2).save(tmp_path / "unfit")
    assert GaussianMixture.load(tmp_path / "unfit").means_ is None
    # Explicit init arrays are config: a loaded model re-fits exactly
    # like the original would.
    means, weights, precisions = _shared_init(X, 3, seed=2)
    cfg = GaussianMixture(n_components=3, max_iter=5, tol=0.0,
                          means_init=means, weights_init=weights,
                          precisions_init=precisions)
    cfg.save(tmp_path / "cfg")
    cfg2 = GaussianMixture.load(tmp_path / "cfg")
    np.testing.assert_array_equal(cfg2.means_init, means)
    a = cfg.fit(X)
    b = cfg2.fit(X)
    np.testing.assert_array_equal(a.means_, b.means_)


def test_pickle_drops_mesh_deepcopy_keeps_it(mesh8):
    import copy
    import pickle
    X, _ = _data(n=1_200, centers=3, d=4, seed=18)
    gm = GaussianMixture(n_components=3, max_iter=8, seed=8,
                         mesh=mesh8).fit(X)
    clone = pickle.loads(pickle.dumps(gm))
    assert clone.mesh is None                  # device handles dropped
    np.testing.assert_array_equal(clone.means_, gm.means_)
    np.testing.assert_array_equal(clone.predict(X), gm.predict(X))
    # In-process deepcopy keeps the user-configured mesh (KMeans
    # contract).
    dup = copy.deepcopy(gm)
    assert dup.mesh is gm.mesh
    np.testing.assert_array_equal(dup.predict(X), gm.predict(X))


def test_set_params_validates():
    """r2 ADVICE (low): set_params routes through __init__ validation."""
    gm = GaussianMixture(n_components=3)
    gm.set_params(dtype="float64")
    assert gm.dtype == np.dtype(np.float64)       # canonicalized, not str
    with pytest.raises(ValueError, match="n_components"):
        gm.set_params(n_components=0)
    with pytest.raises(ValueError, match="covariance_type"):
        gm.set_params(covariance_type="banana")
    with pytest.raises(ValueError, match="invalid parameter"):
        gm.set_params(bogus=1)
    # Failed set_params leaves the model untouched.
    assert gm.n_components == 3 and gm.covariance_type == "diag"


def test_restart_failure_keeps_best_so_far(monkeypatch):
    """r3 ADVICE: an exception in a later restart must not discard
    earlier successful restarts (the best-so-far result is installed,
    with a warning)."""
    X, _ = make_blobs(600, centers=3, n_features=4, random_state=0,
                      dtype=np.float32)
    gm = GaussianMixture(n_components=3, n_init=3, max_iter=20, seed=0)
    orig = GaussianMixture._fit_one
    calls = {"n": 0}

    def flaky(self, ds, mesh, step_fn, seed, **kwargs):
        calls["n"] += 1
        if calls["n"] == 3:                       # last restart blows up
            raise ValueError("non-finite log-likelihood at EM iteration 1")
        return orig(self, ds, mesh, step_fn, seed, **kwargs)

    monkeypatch.setattr(GaussianMixture, "_fit_one", flaky)
    with pytest.warns(UserWarning, match="restart 3/3 failed"):
        gm.fit(X)
    assert np.isfinite(gm.lower_bound_)
    assert gm.means_ is not None and np.all(np.isfinite(gm.means_))
    assert gm.restart_lower_bounds_.shape == (3,)
    assert gm.restart_lower_bounds_[2] == -np.inf
    # All restarts failing propagates the error.
    def always_fail(self, ds, mesh, step_fn, seed, **kwargs):
        raise ValueError("non-finite log-likelihood at EM iteration 1")

    monkeypatch.setattr(GaussianMixture, "_fit_one", always_fail)
    with pytest.warns(UserWarning):
        with pytest.raises(ValueError, match="non-finite"):
            GaussianMixture(n_components=3, n_init=2, max_iter=5,
                            seed=0).fit(X)


def test_restart_metadata_roundtrips_checkpoint(tmp_path):
    """r3 ADVICE: save/load must not silently drop best_restart_ /
    restart_lower_bounds_."""
    X, _ = make_blobs(600, centers=3, n_features=4, random_state=1,
                      dtype=np.float32)
    gm = GaussianMixture(n_components=3, n_init=3, max_iter=15,
                         seed=3).fit(X)
    gm.save(tmp_path / "gm.npz")
    back = GaussianMixture.load(tmp_path / "gm.npz")
    assert back.best_restart_ == gm.best_restart_
    np.testing.assert_array_equal(back.restart_lower_bounds_,
                                  gm.restart_lower_bounds_)


def test_reg_covar_zero_partial_collapse_survives(mesh8):
    """r3 ADVICE: with reg_covar=0, a NEAR-collapsed component (tiny but
    nonzero variance) must not diverge between engines — the device loop
    floors the covariance at the compute dtype's tiny exactly like the
    host path's _params_dev floor, so both fits complete and the fitted
    model scores finitely."""
    X, _ = make_blobs(800, centers=3, n_features=4, random_state=2,
                      dtype=np.float32)
    X[:200] = X[0] + np.random.default_rng(0).normal(
        scale=1e-3, size=(200, 4)).astype(np.float32)
    for host_loop in (True, False):
        gm = GaussianMixture(n_components=3, reg_covar=0.0, max_iter=15,
                             seed=0, mesh=mesh8, host_loop=host_loop)
        gm.fit(X)
        assert np.isfinite(gm.lower_bound_), host_loop
        assert np.all(np.isfinite(gm.precisions_)), host_loop
        assert np.isfinite(gm.score(X)), host_loop


def test_reg_covar_zero_full_collapse_fails_loudly(mesh8):
    """r4 review: a FULLY collapsed component (identical rows) with
    reg_covar=0 cannot be represented (the density matmul overflows at
    inv_var = 1/tiny; sklearn raises on this too) — both engines must
    fail LOUDLY with the non-finite-loglik error, never silently return
    a model whose score() is NaN."""
    rng = np.random.default_rng(2)
    X = np.concatenate([np.full((400, 4), 5.0),
                        rng.normal(size=(400, 4))]).astype(np.float32)
    for host_loop in (True, False):
        gm = GaussianMixture(n_components=2, reg_covar=0.0, max_iter=15,
                             seed=0, mesh=mesh8, host_loop=host_loop)
        with pytest.raises(ValueError, match="non-finite log-likelihood"):
            gm.fit(X)


def test_fit_resume_continues_em(mesh8, tmp_path):
    """r4: fit(resume=True) continues EM from the current parameters
    (sklearn's warm_start capability) and composes with save/load."""
    X, _ = make_blobs(800, centers=3, n_features=4, random_state=5,
                      dtype=np.float32)
    init = X[:3].astype(np.float64)
    kw = dict(n_components=3, means_init=init, tol=0.0, seed=0,
              mesh=mesh8)
    full = GaussianMixture(max_iter=12, **kw).fit(X)
    part = GaussianMixture(max_iter=5, **kw).fit(X)
    assert part.n_iter_ == 5
    part.max_iter = 7
    part.fit(X, resume=True)
    assert part.n_iter_ == 12
    np.testing.assert_allclose(part.means_, full.means_, rtol=1e-6)
    np.testing.assert_allclose(part.lower_bound_, full.lower_bound_,
                               rtol=1e-7)
    # resume through a checkpoint round-trip
    p = tmp_path / "gm.npz"
    half = GaussianMixture(max_iter=5, **kw).fit(X)
    half.save(p)
    back = GaussianMixture.load(p)
    back.max_iter = 7
    back.mesh = mesh8
    back.fit(X, resume=True)
    np.testing.assert_allclose(back.means_, full.means_, rtol=1e-6)
    with pytest.raises(ValueError, match="n_init == 1"):
        GaussianMixture(n_components=3, n_init=2, means_init=None,
                        seed=0).fit(X).fit(X, resume=True)


def test_fit_resume_device_loop(mesh8):
    X, _ = make_blobs(800, centers=3, n_features=4, random_state=5,
                      dtype=np.float32)
    init = X[:3].astype(np.float64)
    kw = dict(n_components=3, means_init=init, tol=0.0, seed=0,
              mesh=mesh8, host_loop=False, dtype=np.float64)
    full = GaussianMixture(max_iter=12, **kw).fit(X)
    part = GaussianMixture(max_iter=5, **kw).fit(X)
    part.max_iter = 7
    part.fit(X, resume=True)
    assert part.n_iter_ == 12
    np.testing.assert_allclose(part.means_, full.means_, rtol=1e-8)
