"""Cluster-validity metrics vs the sklearn oracle (test-only dependency,
the reference's own policy — README.md:13)."""

import numpy as np
import pytest
from sklearn import metrics as skm

from kmeans_tpu import KMeans
from kmeans_tpu.metrics import (calinski_harabasz_score,
                                davies_bouldin_score, silhouette_samples,
                                silhouette_score)


@pytest.fixture(scope="module")
def labeled_blobs():
    rng = np.random.default_rng(0)
    centers = np.array([[0.0, 0.0, 0.0], [6.0, 6.0, 0.0], [0.0, 8.0, 4.0],
                        [9.0, 0.0, 9.0]])
    X = np.concatenate([c + rng.normal(size=(150, 3)) for c in centers])
    X = X.astype(np.float32)
    labels = KMeans(k=4, seed=1, verbose=False).fit(X).predict(X)
    return X, labels


def test_silhouette_matches_sklearn(labeled_blobs):
    X, labels = labeled_blobs
    ours = silhouette_score(X, labels)
    ref = skm.silhouette_score(X.astype(np.float64), labels)
    assert ours == pytest.approx(ref, abs=2e-3)


def test_silhouette_samples_match_sklearn(labeled_blobs):
    X, labels = labeled_blobs
    ours = silhouette_samples(X, labels)
    ref = skm.silhouette_samples(X.astype(np.float64), labels)
    np.testing.assert_allclose(ours, ref, atol=5e-3)


def test_silhouette_subsample_close(labeled_blobs):
    X, labels = labeled_blobs
    full = silhouette_score(X, labels)
    sub = silhouette_score(X, labels, sample_size=300, seed=3)
    assert sub == pytest.approx(full, abs=0.1)


def test_silhouette_mesh_invariance(labeled_blobs, mesh1, mesh8):
    """The row-sharded O(n^2) pass (r2 VERDICT weak #5) is numerically
    inert: 1-device and 8-device meshes give the same samples."""
    X, labels = labeled_blobs
    a = silhouette_samples(X, labels, mesh=mesh1)
    b = silhouette_samples(X, labels, mesh=mesh8)
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_db_ch_mesh_invariance(labeled_blobs, mesh1, mesh8):
    """Davies-Bouldin / Calinski-Harabasz row-shard over the mesh too
    (r3): 1- and 8-device results agree."""
    X, labels = labeled_blobs
    assert davies_bouldin_score(X, labels, mesh=mesh1) == pytest.approx(
        davies_bouldin_score(X, labels, mesh=mesh8), rel=1e-6)
    assert calinski_harabasz_score(X, labels, mesh=mesh1) == pytest.approx(
        calinski_harabasz_score(X, labels, mesh=mesh8), rel=1e-6)


def test_davies_bouldin_matches_sklearn(labeled_blobs):
    X, labels = labeled_blobs
    ours = davies_bouldin_score(X, labels)
    ref = skm.davies_bouldin_score(X.astype(np.float64), labels)
    assert ours == pytest.approx(ref, rel=1e-3)


def test_calinski_harabasz_matches_sklearn(labeled_blobs):
    X, labels = labeled_blobs
    ours = calinski_harabasz_score(X, labels)
    ref = skm.calinski_harabasz_score(X.astype(np.float64), labels)
    assert ours == pytest.approx(ref, rel=1e-3)


def test_singleton_cluster_scores_zero():
    # One isolated point forms its own cluster -> its silhouette is 0.
    X = np.array([[0.0, 0.0], [0.1, 0.0], [0.0, 0.1], [50.0, 50.0]],
                 dtype=np.float32)
    labels = np.array([0, 0, 0, 1], dtype=np.int32)
    s = silhouette_samples(X, labels)
    assert s[3] == 0.0
    ref = skm.silhouette_samples(X.astype(np.float64), labels)
    np.testing.assert_allclose(s, ref, atol=1e-5)


def test_metrics_reject_single_cluster():
    X = np.zeros((10, 2), dtype=np.float32)
    labels = np.zeros(10, dtype=np.int32)
    for fn in (silhouette_score, davies_bouldin_score,
               calinski_harabasz_score):
        with pytest.raises(ValueError, match="2 <= n_labels"):
            fn(X, labels)


def test_metrics_reject_bad_shapes():
    X = np.zeros((10, 2), dtype=np.float32)
    with pytest.raises(ValueError, match="labels"):
        silhouette_score(X, np.zeros(9, dtype=np.int32))
    with pytest.raises(ValueError, match="2-D"):
        davies_bouldin_score(np.zeros(10), np.zeros(10, dtype=np.int32))


def test_get_set_params_roundtrip():
    from kmeans_tpu import BisectingKMeans, MiniBatchKMeans
    km = KMeans(k=7, n_init=3, distance_mode="direct", verbose=False)
    params = km.get_params()
    assert params["k"] == 7 and params["n_init"] == 3
    clone = KMeans(**params)
    assert clone.get_params() == params
    km.set_params(k=9, tolerance=1e-6)
    assert km.k == 9 and km.tolerance == 1e-6
    with pytest.raises(ValueError, match="unknown parameter"):
        km.set_params(bogus=1)
    assert MiniBatchKMeans(batch_size=128).get_params()["batch_size"] == 128
    assert BisectingKMeans().get_params()["bisecting_strategy"] == \
        "biggest_sse"


def test_better_clustering_scores_better(labeled_blobs):
    X, good = labeled_blobs
    rng = np.random.default_rng(7)
    bad = rng.integers(0, 4, size=len(good)).astype(np.int32)
    assert silhouette_score(X, good) > silhouette_score(X, bad)
    assert davies_bouldin_score(X, good) < davies_bouldin_score(X, bad)
    assert calinski_harabasz_score(X, good) > calinski_harabasz_score(X, bad)


def test_gapped_labels_match_sklearn():
    """Non-contiguous label ids (an emptied cluster, DBSCAN-style -1 noise)
    must be compacted like sklearn's LabelEncoder, not become phantom
    origin clusters."""
    rng = np.random.default_rng(0)
    X = np.concatenate([rng.normal(0, 1, (50, 4)),
                        rng.normal(8, 1, (50, 4))]).astype(np.float32)
    gapped = np.array([0] * 50 + [3] * 50)
    assert davies_bouldin_score(X, gapped) == pytest.approx(
        skm.davies_bouldin_score(X, gapped), rel=1e-4)
    assert calinski_harabasz_score(X, gapped) == pytest.approx(
        skm.calinski_harabasz_score(X, gapped), rel=1e-4)
    noisy = gapped.copy()
    noisy[0] = -1                      # becomes its own singleton cluster
    np.testing.assert_allclose(silhouette_samples(X, noisy),
                               skm.silhouette_samples(X, noisy), atol=5e-3)


def test_external_metrics_match_sklearn():
    """ARI / MI / NMI / homogeneity-completeness-V against sklearn on
    partially-agreeing partitions."""
    skm = pytest.importorskip("sklearn.metrics")
    from kmeans_tpu.metrics import (adjusted_rand_score,
                                    homogeneity_completeness_v_measure,
                                    mutual_info_score,
                                    normalized_mutual_info_score)
    rng = np.random.default_rng(0)
    lt = rng.integers(0, 5, 600)
    lp = lt.copy()
    lp[rng.choice(600, 150, replace=False)] = rng.integers(0, 7, 150)
    np.testing.assert_allclose(adjusted_rand_score(lt, lp),
                               skm.adjusted_rand_score(lt, lp), rtol=1e-9)
    np.testing.assert_allclose(mutual_info_score(lt, lp),
                               skm.mutual_info_score(lt, lp), rtol=1e-9)
    np.testing.assert_allclose(
        normalized_mutual_info_score(lt, lp),
        skm.normalized_mutual_info_score(lt, lp), rtol=1e-9)
    np.testing.assert_allclose(
        homogeneity_completeness_v_measure(lt, lp),
        skm.homogeneity_completeness_v_measure(lt, lp), rtol=1e-9)
    # Identity and degenerate partitions.
    assert adjusted_rand_score(lt, lt) == 1.0
    np.testing.assert_allclose(
        normalized_mutual_info_score(lt, lt), 1.0, rtol=1e-12)
    assert adjusted_rand_score(np.zeros(10), np.zeros(10)) == 1.0
    with pytest.raises(ValueError, match="non-empty"):
        adjusted_rand_score([], [])


def test_external_metrics_reject_nan_labels():
    from kmeans_tpu.metrics import adjusted_rand_score
    bad = np.array([0.0, 1.0, np.nan])
    with pytest.raises(ValueError, match="NaN or Inf"):
        adjusted_rand_score(bad, np.zeros(3))
