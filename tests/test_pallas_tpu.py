"""Mosaic compile-path coverage on real hardware.

The main Pallas test modules run in interpret mode on CPU and skip under
x64 on TPU (their oracles promote to f64 there, see
conftest.pallas_x64_skip).  This module keeps the actual TPU compilation
tested: most tests scope x64 OFF around the kernel call so the oracle
stays f32; ``test_kernel_compiles_under_live_x64`` pins that the kernels
also compile and run with the x64 flag ON (r2 VERDICT #5 — the former
NotImplementedError guard is gone).
"""

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="Mosaic compile path needs real TPU hardware")


def test_kernel_compiles_under_live_x64():
    """r2 VERDICT #5: the x64 guard is removed — the fused kernel must
    compile and run with jax_enable_x64 ON (f32 compute semantics: the
    oracle is scoped to f32 for the comparison)."""
    import jax.numpy as jnp

    from kmeans_tpu.ops.assign import assign_reduce
    from kmeans_tpu.ops.pallas_kernels import fused_assign_reduce

    assert jax.config.jax_enable_x64       # conftest turns it on
    rng = np.random.default_rng(0)
    Xh = rng.normal(size=(2048, 24)).astype(np.float32)
    X = jnp.asarray(Xh, jnp.float32)
    W = jnp.ones((2048,), jnp.float32)
    C = jnp.asarray(Xh[:9], jnp.float32)
    labels, mind2, sums, counts = fused_assign_reduce(X, W, C)
    assert np.asarray(labels).dtype == np.int32
    with jax.enable_x64(False):            # f32 oracle for comparison
        ref = assign_reduce(jnp.asarray(Xh), jnp.ones((2048,), jnp.float32),
                            jnp.asarray(Xh[:9]), chunk_size=512)
    np.testing.assert_array_equal(np.asarray(counts),
                                  np.asarray(ref.counts))
    np.testing.assert_allclose(np.asarray(sums), np.asarray(ref.sums),
                               rtol=1e-4, atol=1e-4)


def test_fused_kernel_compiles_and_matches_oracle_on_tpu():
    import jax.numpy as jnp

    from kmeans_tpu.ops.assign import assign_reduce
    from kmeans_tpu.ops.pallas_kernels import fused_assign_reduce

    with jax.enable_x64(False):
        rng = np.random.default_rng(0)
        X = jnp.asarray(rng.normal(size=(2048, 24)), jnp.float32)
        W = jnp.ones((2048,), jnp.float32)
        C = X[:9]
        labels, mind2, sums, counts = fused_assign_reduce(X, W, C)
        ref = assign_reduce(X, W, C, chunk_size=512)
        np.testing.assert_array_equal(np.asarray(counts),
                                      np.asarray(ref.counts))
        np.testing.assert_allclose(np.asarray(sums), np.asarray(ref.sums),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(float((mind2 * W).sum()),
                                   float(ref.sse), rtol=1e-5)


def test_fori_fallback_compiles_on_tpu():
    """k_tiles > _UNROLL_K_TILES takes the fori_loop path with dynamic
    pl.ds offsets — Mosaic must lower it too, not just the static unroll."""
    import jax.numpy as jnp

    from kmeans_tpu.ops.assign import assign_reduce
    from kmeans_tpu.ops.pallas_kernels import fused_assign_reduce

    with jax.enable_x64(False):
        rng = np.random.default_rng(1)
        X = jnp.asarray(rng.normal(size=(1024, 16)), jnp.float32)
        W = jnp.ones((1024,), jnp.float32)
        C = jnp.asarray(rng.normal(size=(1200, 16)), jnp.float32)
        labels, mind2, sums, counts = fused_assign_reduce(
            X, W, C, tile_k=128)                   # k_tiles = 10 > 8
        ref = assign_reduce(X, W, C, chunk_size=1024)
        np.testing.assert_array_equal(np.asarray(counts),
                                      np.asarray(ref.counts))
        np.testing.assert_allclose(np.asarray(sums), np.asarray(ref.sums),
                                   rtol=1e-4, atol=1e-4)


def test_assign_only_kernel_compiles_and_matches_on_tpu():
    """pallas_assign (the model-sharding variant, r1 VERDICT #3) must
    lower through Mosaic and agree with the fused kernel's assignment."""
    import jax.numpy as jnp

    from kmeans_tpu.ops.pallas_kernels import (fused_assign_reduce,
                                               pallas_assign)

    with jax.enable_x64(False):
        rng = np.random.default_rng(2)
        X = jnp.asarray(rng.normal(size=(2048, 24)), jnp.float32)
        W = jnp.ones((2048,), jnp.float32)
        C = X[:9]
        labels_a, mind2_a = pallas_assign(X, C)
        labels_f, mind2_f, *_ = fused_assign_reduce(X, W, C)
        np.testing.assert_array_equal(np.asarray(labels_a),
                                      np.asarray(labels_f))
        np.testing.assert_allclose(np.asarray(mind2_a),
                                   np.asarray(mind2_f), rtol=1e-6)


def test_pallas_fit_agrees_with_matmul_fit_in_win_region():
    """End-to-end Mosaic-path agreement at a shape where auto picks the
    kernel: both modes must converge to the same centroids from the same
    init (assignments may differ only on bf16-product near-ties, which a
    few Lloyd iterations wash out on blob data)."""
    import numpy as np

    from kmeans_tpu import KMeans
    from kmeans_tpu.data.synthetic import make_blobs

    with jax.enable_x64(False):
        # (n, centers, n_features): d=512, k=512 — inside the win
        # region, with k matching the true center count so both modes
        # converge to the same well-separated optimum (over-clustering
        # would leave near-tie splits that are legitimately
        # mode-dependent under bf16-rate products).
        X, _ = make_blobs(40_000, 512, 512, random_state=3,
                          dtype=np.float32)
        a = KMeans(k=512, seed=5, max_iter=8, verbose=False,
                   distance_mode="pallas", compute_sse=True).fit(X)
        b = KMeans(k=512, seed=5, max_iter=8, verbose=False,
                   distance_mode="matmul", compute_sse=True).fit(X)
        np.testing.assert_allclose(
            np.sort(a.centroids, axis=0), np.sort(b.centroids, axis=0),
            rtol=1e-3, atol=1e-3)
        # Algebraic (pallas) vs per-point (matmul) SSE agree to the
        # bf16-product error class.
        np.testing.assert_allclose(a.sse_history[-1], b.sse_history[-1],
                                   rtol=2e-2)


def test_auto_resolves_to_pallas_on_hardware():
    from kmeans_tpu import KMeans

    with jax.enable_x64(False):
        km = KMeans(k=1024)
        assert km._mode(2_000_000, 128) == "pallas"
        assert km._mode(1_000_000, 16) == "matmul"   # padding-waste region


def test_multi_restart_pallas_composes_on_hardware():
    """n_init>1 vmaps the whole device loop over restarts; the pallas
    kernel must lower under that batching and pick the same winner as
    the XLA path."""
    import numpy as np

    from kmeans_tpu import KMeans
    from kmeans_tpu.data.synthetic import make_blobs

    with jax.enable_x64(False):
        X, _ = make_blobs(50_000, 512, 512, random_state=5,
                          dtype=np.float32)
        a = KMeans(k=512, seed=7, n_init=3, host_loop=False, max_iter=6,
                   verbose=False, distance_mode="pallas",
                   compute_sse=True).fit(X)
        b = KMeans(k=512, seed=7, n_init=3, host_loop=False, max_iter=6,
                   verbose=False, distance_mode="matmul",
                   compute_sse=True).fit(X)
        assert a.best_restart_ == b.best_restart_
        np.testing.assert_allclose(
            np.sort(a.centroids, 0), np.sort(b.centroids, 0),
            rtol=1e-3, atol=1e-3)


def test_pallas_fit_is_deterministic_on_hardware():
    """The determinism checker (the SPMD race-detector analogue) must
    hold bit-exactly for the Mosaic kernel path: fixed grid order, no
    atomics — two identical fits, identical bits."""
    import numpy as np

    from kmeans_tpu import KMeans
    from kmeans_tpu.data.synthetic import make_blobs
    from kmeans_tpu.utils.debug import check_determinism

    with jax.enable_x64(False):
        X, _ = make_blobs(30_000, 512, 512, random_state=6,
                          dtype=np.float32)
        report = check_determinism(
            lambda: KMeans(k=512, seed=4, max_iter=4, verbose=False,
                           distance_mode="pallas", compute_sse=True),
            X, runs=2)
        assert report["deterministic"], report["details"]
