"""Double-buffered streaming pipeline (data.prefetch + the ``prefetch``
knob on every stream consumer): prefetch must move WHERE the per-block
work happens — a bounded background producer — without changing WHAT is
computed (bit-identical trajectories vs the synchronous path), must
re-raise reader errors at the consumer, and must never leak threads."""

import threading
import time

import numpy as np
import pytest

from kmeans_tpu import KMeans
from kmeans_tpu.data.prefetch import check_prefetch, prefetch_iter
from kmeans_tpu.data.synthetic import make_blobs
from kmeans_tpu.models import GaussianMixture


@pytest.fixture()
def data():
    X, _ = make_blobs(6000, centers=5, n_features=8, random_state=11,
                      dtype=np.float32)
    return X


def _blocks_of(X, size, weights=None):
    def make_blocks():
        for i in range(0, len(X), size):
            if weights is None:
                yield X[i: i + size]
            else:
                yield X[i: i + size], weights[i: i + size]
    return make_blocks


def _no_leaked_threads(baseline):
    """Every prefetch producer is named; poll briefly for teardown."""
    for _ in range(50):
        alive = [t for t in threading.enumerate()
                 if t.name.startswith("kmeans_tpu-prefetch")]
        if len(alive) <= baseline:
            return True
        time.sleep(0.02)
    return False


def _prefetch_threads():
    return sum(t.name.startswith("kmeans_tpu-prefetch")
               for t in threading.enumerate())


# ------------------------------------------------------------ primitive


def test_prefetch_iter_order_and_stage():
    for prefetch in (0, 1, 2, 5):
        got = list(prefetch_iter(iter(range(20)), prefetch,
                                 stage=lambda x: x * x))
        assert got == [i * i for i in range(20)]
    assert list(prefetch_iter(iter([]), 2)) == []
    assert _no_leaked_threads(0)


def test_prefetch_validation():
    with pytest.raises(ValueError, match="prefetch"):
        check_prefetch(-1)
    with pytest.raises(ValueError, match="prefetch"):
        list(prefetch_iter([1], -2))


def test_prefetch_iter_source_error_propagates_in_order():
    def source():
        yield 1
        yield 2
        raise RuntimeError("disk died")

    it = prefetch_iter(source(), 2)
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(RuntimeError, match="disk died"):
        next(it)
    assert _no_leaked_threads(0)


def test_prefetch_iter_stage_error_propagates():
    def stage(x):
        if x == 3:
            raise ValueError("bad block")
        return x

    got = []
    with pytest.raises(ValueError, match="bad block"):
        for v in prefetch_iter(iter(range(10)), 2, stage):
            got.append(v)
    assert got == [0, 1, 2]
    assert _no_leaked_threads(0)


def test_prefetch_iter_early_close_joins_thread():
    it = prefetch_iter(iter(range(1000)), 3)
    assert next(it) == 0
    it.close()
    assert _no_leaked_threads(0)
    # close is idempotent and the iterator stays exhausted.
    it.close()
    with pytest.raises(StopIteration):
        next(it)


def test_prefetch_iter_blocked_producer_unblocks_on_close():
    """A producer stuck on a FULL queue (consumer stopped pulling) must
    still join promptly on close."""
    it = prefetch_iter(iter(range(10_000)), 1)
    next(it)
    time.sleep(0.1)          # let the producer fill the queue and block
    it.close()
    assert _no_leaked_threads(0)


def test_prefetch_iter_runs_stage_in_background_thread():
    seen = []

    def stage(x):
        seen.append(threading.current_thread().name)
        return x

    list(prefetch_iter(iter(range(3)), 2, stage))
    assert all(n.startswith("kmeans_tpu-prefetch") for n in seen)
    list(prefetch_iter(iter(range(3)), 0, stage))
    assert seen[-1] == threading.main_thread().name


# ----------------------------------------------- streamed-fit parity


def _fit_pair(data, mesh8, **kw):
    base = dict(k=5, seed=0, compute_sse=True, verbose=False,
                mesh=mesh8, chunk_size=128, dtype=np.float64)
    base.update(kw)
    rng = np.random.RandomState(0)
    base.setdefault("init", data[rng.choice(len(data), base["k"],
                                            replace=False)].copy())
    km0 = KMeans(**base)
    km0.fit_stream(_blocks_of(data, 1000), prefetch=0)
    km2 = KMeans(**base)
    km2.fit_stream(_blocks_of(data, 1000), prefetch=2)
    return km0, km2


def test_kmeans_stream_prefetch_trajectory_bit_identical(data, mesh8):
    """The acceptance-criteria pin: prefetch=2 and prefetch=0 streamed
    fits are trajectory-BIT-identical — centroids, iteration count, and
    the full SSE history."""
    km0, km2 = _fit_pair(data, mesh8, empty_cluster="keep")
    assert km0.iterations_run == km2.iterations_run
    assert np.array_equal(km0.centroids, km2.centroids)
    assert km0.sse_history == km2.sse_history
    assert np.array_equal(km0.cluster_sizes_, km2.cluster_sizes_)
    assert _no_leaked_threads(0)


def test_kmeans_stream_prefetch_identical_under_resample(data, mesh8):
    """The reservoir-fed 'resample' policy draws in consumer block order
    — prefetch must not perturb the draw stream."""
    km0, km2 = _fit_pair(data[:40], mesh8, k=8,
                         empty_cluster="resample", max_iter=12)
    assert km0.iterations_run == km2.iterations_run
    assert np.array_equal(km0.centroids, km2.centroids)


def test_kmeans_stream_prefetch_identical_weighted_multi_restart(
        data, mesh8):
    w = np.random.RandomState(3).uniform(0.1, 2.0,
                                         len(data)).astype(np.float32)
    kw = dict(k=4, n_init=2, seed=7, init="forgy", compute_sse=True,
              empty_cluster="keep", verbose=False, mesh=mesh8,
              chunk_size=128)
    km0 = KMeans(**kw)
    km0.fit_stream(_blocks_of(data, 900, w), prefetch=0)
    km2 = KMeans(**kw)
    km2.fit_stream(_blocks_of(data, 900, w), prefetch=2)
    assert km0.best_restart_ == km2.best_restart_
    assert np.array_equal(km0.centroids, km2.centroids)
    assert np.array_equal(km0.restart_inertias_, km2.restart_inertias_)


def test_gmm_stream_prefetch_trajectory_bit_identical(data, mesh8):
    kw = dict(n_components=3, init_params="random", max_iter=6, seed=0,
              mesh=mesh8, chunk_size=128, verbose=False)
    g0 = GaussianMixture(**kw)
    g0.fit_stream(_blocks_of(data, 1000), prefetch=0)
    g2 = GaussianMixture(**kw)
    g2.fit_stream(_blocks_of(data, 1000), prefetch=2)
    assert g0.n_iter_ == g2.n_iter_
    assert np.array_equal(g0.means_, g2.means_)
    assert np.array_equal(g0.weights_, g2.weights_)
    assert np.array_equal(g0.covariances_, g2.covariances_)
    assert g0.lower_bound_ == g2.lower_bound_
    assert _no_leaked_threads(0)


def test_gmm_tied_stream_prefetch_identical(data, mesh8):
    """Tied covariance adds the prefetched total-scatter pass."""
    kw = dict(n_components=3, covariance_type="tied",
              init_params="random", max_iter=4, seed=0, mesh=mesh8,
              chunk_size=128, verbose=False)
    g0 = GaussianMixture(**kw)
    g0.fit_stream(_blocks_of(data, 1000), prefetch=0)
    g2 = GaussianMixture(**kw)
    g2.fit_stream(_blocks_of(data, 1000), prefetch=2)
    assert np.array_equal(g0.means_, g2.means_)
    assert np.array_equal(g0.covariances_, g2.covariances_)


# ------------------------------------------- inference-stream parity


def test_inference_streams_prefetch_identical(data, mesh8):
    km = KMeans(k=5, seed=0, verbose=False, mesh=mesh8,
                chunk_size=128).fit(data)
    mk = _blocks_of(data, 700)
    l0 = np.concatenate(list(km.predict_stream(mk, prefetch=0)))
    l2 = np.concatenate(list(km.predict_stream(mk, prefetch=2)))
    assert np.array_equal(l0, l2)
    assert km.score_stream(mk, prefetch=0) == km.score_stream(mk,
                                                              prefetch=2)
    t0 = np.concatenate(list(km.transform_stream(mk, prefetch=0)))
    t2 = np.concatenate(list(km.transform_stream(mk, prefetch=2)))
    assert np.array_equal(t0, t2)
    gm = GaussianMixture(n_components=3, seed=0, mesh=mesh8,
                         chunk_size=128, verbose=False).fit(data)
    p0 = np.concatenate(list(gm.predict_stream(mk, prefetch=0)))
    p2 = np.concatenate(list(gm.predict_stream(mk, prefetch=2)))
    assert np.array_equal(p0, p2)
    s0 = np.concatenate(list(gm.score_samples_stream(mk, prefetch=0)))
    s2 = np.concatenate(list(gm.score_samples_stream(mk, prefetch=2)))
    assert np.array_equal(s0, s2)
    assert _no_leaked_threads(0)


# --------------------------------------- failure/shutdown semantics


def test_stream_reader_exception_mid_epoch_propagates_no_threads(
        data, mesh8):
    """Acceptance-criteria pin: a reader exception mid-epoch reaches the
    fit_stream caller AND leaves no live producer threads."""
    def bad_blocks():
        yield data[:1000]
        yield data[1000:2000]
        raise OSError("stream source failed")

    km = KMeans(k=5, seed=0, init=data[:5].copy(), verbose=False,
                mesh=mesh8, chunk_size=128)
    with pytest.raises(OSError, match="stream source failed"):
        km.fit_stream(lambda: bad_blocks(), prefetch=2)
    assert _no_leaked_threads(0)

    gm = GaussianMixture(n_components=3, init_params="random", seed=0,
                         mesh=mesh8, chunk_size=128, verbose=False)
    with pytest.raises(OSError, match="stream source failed"):
        gm.fit_stream(lambda: bad_blocks(), prefetch=2)
    assert _no_leaked_threads(0)


def test_stream_shape_error_still_points_at_block(data, mesh8):
    """Validation errors raised by the producer-side decode keep their
    pointed message at the consumer."""
    def mixed():
        yield data[:1000]
        yield np.zeros((10, 3), np.float32)        # wrong width

    km = KMeans(k=5, seed=0, init=data[:5].copy(), verbose=False,
                mesh=mesh8, chunk_size=128)
    with pytest.raises(ValueError, match="block shape"):
        km.fit_stream(lambda: mixed(), prefetch=2)
    assert _no_leaked_threads(0)


def test_abandoned_predict_stream_generator_joins_thread(data, mesh8):
    km = KMeans(k=5, seed=0, verbose=False, mesh=mesh8,
                chunk_size=128).fit(data)
    gen = km.predict_stream(_blocks_of(data, 500), prefetch=2)
    next(gen)
    gen.close()                                    # partial consumption
    assert _no_leaked_threads(0)
    gen = km.transform_stream(_blocks_of(data, 500), prefetch=2)
    next(gen)
    del gen                                        # GC path
    assert _no_leaked_threads(0)


def test_fit_stream_d_peek_closes_prefetching_source(tmp_path, data):
    """Regression: the d-inference peek takes ONE item from
    make_blocks() and abandons the iterator — with a prefetching source
    (iter_npy_blocks(prefetch=N)) that abandoned producer thread must
    be reaped immediately, not at some future GC cycle."""
    from kmeans_tpu.data.io import iter_npy_blocks
    path = tmp_path / "pts.npy"
    np.save(path, data)
    km = KMeans(k=5, seed=0, init=data[:5].copy(), max_iter=2,
                empty_cluster="keep", verbose=False, chunk_size=128)
    km.fit_stream(iter_npy_blocks(path, 1000, prefetch=2))  # d peeked
    assert _no_leaked_threads(0)
    gm = GaussianMixture(n_components=3, init_params="random", max_iter=2,
                         seed=0, chunk_size=128, verbose=False)
    gm.fit_stream(iter_npy_blocks(path, 1000, prefetch=2))
    assert _no_leaked_threads(0)


def test_nested_prefetch_early_close_reaps_inner_thread(tmp_path, data):
    """Abandoning a prefetched stream whose SOURCE is itself a
    prefetching iterator (iter_npy_blocks(prefetch=N) under a
    prefetch>0 consumer) must close the inner producer too — close
    propagates through the wrapper instead of waiting for cyclic GC."""
    from kmeans_tpu.data.io import iter_npy_blocks
    path = tmp_path / "pts.npy"
    np.save(path, data)
    km = KMeans(k=5, seed=0, verbose=False, chunk_size=128).fit(data)
    gen = km.predict_stream(iter_npy_blocks(path, 500, prefetch=2),
                            prefetch=2)
    next(gen)
    gen.close()
    assert _no_leaked_threads(0)
    # Same through the synchronous wrapper (prefetch=0 consumer over a
    # prefetching source).
    gen = km.predict_stream(iter_npy_blocks(path, 500, prefetch=2),
                            prefetch=0)
    next(gen)
    gen.close()
    assert _no_leaked_threads(0)


def test_iter_npy_blocks_prefetch_knob(tmp_path, data):
    from kmeans_tpu.data.io import iter_npy_blocks
    path = tmp_path / "pts.npy"
    np.save(path, data)
    sync = [b.copy() for b in iter_npy_blocks(path, 1700)()]
    pre = [b.copy() for b in iter_npy_blocks(path, 1700, prefetch=2)()]
    assert len(sync) == len(pre)
    for a, b in zip(sync, pre):
        assert np.array_equal(a, b)
    assert _no_leaked_threads(0)
    with pytest.raises(ValueError, match="prefetch"):
        iter_npy_blocks(path, 1700, prefetch=-1)


def test_from_npy_readahead_matches_sync(tmp_path, data, mesh8):
    from kmeans_tpu.data.io import from_npy
    path = tmp_path / "pts.npy"
    np.save(path, data.astype(np.float64))
    ds_sync = from_npy(path, mesh8, dtype=np.float64, prefetch=0)
    ds_pre = from_npy(path, mesh8, dtype=np.float64, prefetch=2)
    assert np.array_equal(np.asarray(ds_sync.points),
                          np.asarray(ds_pre.points))
    assert np.array_equal(np.asarray(ds_sync.weights),
                          np.asarray(ds_pre.weights))


def test_consumer_abandons_mid_retry_no_leaked_threads(data):
    """ISSUE 4 shutdown hardening: the consumer kills the generator
    while the producer is INSIDE an injected retry-backoff sleep — the
    close must abort the sleep (``abort_source``), join the producer,
    and leak no thread, without waiting out the backoff schedule."""
    from kmeans_tpu.data.io import resilient_blocks
    from kmeans_tpu.utils import faults

    # Block 1 fails every attempt; a 60 s backoff would hang a close()
    # that merely joined the thread.  flaky_blocks' counter proves the
    # producer actually entered the retry loop before the abandon.
    flaky = faults.flaky_blocks(_blocks_of(data, 1500), fail_block=1,
                                fail_times=10 ** 6)
    source = resilient_blocks(flaky, io_retries=5, io_backoff=60.0)
    it = prefetch_iter(source(), prefetch=2)
    first = next(it)                       # producer races ahead to the
    assert np.array_equal(first, data[:1500])   # failing block 1
    for _ in range(100):                   # wait until it is mid-retry
        if flaky.state["failures"]:
            break
        time.sleep(0.02)
    assert flaky.state["failures"] >= 1
    t0 = time.perf_counter()
    it.close()                             # consumer abandons the epoch
    elapsed = time.perf_counter() - t0
    assert elapsed < 10.0, f"close() waited out the backoff ({elapsed:.1f}s)"
    assert _no_leaked_threads(0)
