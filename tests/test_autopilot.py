"""ISSUE 19: the elastic autopilot — chaos matrix over REAL subprocess
workers plus unit coverage of the committed policy surface.

Three tiers:

1. **Policy units** — the committed constants' derived functions
   (deterministic backoff schedule and cap, exit-code classification,
   evict/grow thresholds), ``select_resume`` over real rotated/torn
   checkpoint files, the decision/give-up types, and the new fault
   hooks (``inject_host_kill`` targeting, ``inject_launch_failures``
   counting).
2. **Chaos matrix** — the supervising loop driven end-to-end against
   real ``orchestrator.worker`` subprocesses (simulated-fleet env
   identity, so no jax.distributed needed) with ``utils.faults``
   injection: host kill mid-segment (resume parity BIT-EXACT vs the
   uninterrupted in-process f64 oracle), slow-host straggler
   (evict -> shrink -> degraded, bounded overhead), torn primary
   checkpoint (``.prev`` fallback), torn BOTH rotations (typed give-up
   with the complete decision log), launch flakes (deterministic
   backoff, budget exhaustion).
3. **CLI contract** — ``python -m kmeans_tpu autopilot`` exit codes
   0 converged / 1 degraded / 2 gave-up, ``--json`` payload shape.

Workers are tiny (600x4 f64 blobs, <= 8 iterations) so each supervised
run is dominated by the jax import, not the fit.
"""

import json
from pathlib import Path

import numpy as np
import pytest

from kmeans_tpu import KMeans
from kmeans_tpu.cli import autopilot_main
from kmeans_tpu.obs import REGISTRY
from kmeans_tpu.orchestrator import (Autopilot, AutopilotGaveUpError,
                                     launcher, policy, run_autopilot)
from kmeans_tpu.orchestrator.worker import _load_data
from kmeans_tpu.parallel.multihost import simulated_world_env
from kmeans_tpu.utils import faults
from kmeans_tpu.utils.checkpoint import save_state_rotating

BASE_SPEC = {
    "k": 3, "max_iter": 6, "tolerance": 1e-30, "seed": 7,
    "dtype": "float64", "checkpoint_every": 1,
    "synthetic": {"n": 600, "d": 4, "kind": "blobs", "seed": 3},
    "devices_per_host": 1, "empty_cluster": "keep",
}


def write_spec(dirpath, **overrides):
    spec = dict(BASE_SPEC)
    spec.update(overrides)
    p = Path(dirpath) / "spec.json"
    p.write_text(json.dumps(spec))
    return p, spec


def actions(decisions):
    return [d["action"] for d in decisions]


def oracle_centroids(spec):
    """The uninterrupted single-process f64 fit the chaos matrix must
    match bit-exactly (conftest enables x64 globally)."""
    X = _load_data(spec, np)
    km = KMeans(k=spec["k"], max_iter=spec["max_iter"],
                tolerance=spec["tolerance"], seed=spec["seed"],
                compute_sse=True, empty_cluster=spec["empty_cluster"],
                dtype=np.float64, host_loop=True, compute_labels=False,
                verbose=False).fit(X)
    return np.asarray(km.centroids)


# ---------------------------------------------------------------------------
# Policy units
# ---------------------------------------------------------------------------

def test_backoff_schedule_deterministic_and_capped():
    delays = [policy.backoff_delay_s(a) for a in range(8)]
    assert delays[:3] == [0.05, 0.1, 0.2]
    assert delays == sorted(delays)
    assert max(delays) == policy.LAUNCH_BACKOFF_MAX_S
    # Deterministic: same attempt, same delay — no jitter.
    assert policy.backoff_delay_s(2) == policy.backoff_delay_s(2)


def test_backoff_negative_attempt_raises():
    with pytest.raises(ValueError):
        policy.backoff_delay_s(-1)


def test_classify_exit_contract():
    assert policy.classify_exit(policy.EXIT_DONE) == "done"
    assert policy.classify_exit(policy.EXIT_PREEMPTED) == "preempted"
    assert policy.classify_exit(policy.EXIT_CKPT_CORRUPT) \
        == "checkpoint-corrupt"
    assert policy.classify_exit(1) == "crashed"
    assert policy.classify_exit(-9) == "crashed"


def test_evict_and_grow_thresholds():
    assert not policy.should_evict(policy.STALL_CONSECUTIVE_POLLS - 1)
    assert policy.should_evict(policy.STALL_CONSECUTIVE_POLLS)
    assert not policy.should_grow(2, 2, policy.GROW_HOLDOFF_POLLS)
    assert not policy.should_grow(1, 2, policy.GROW_HOLDOFF_POLLS - 1)
    assert policy.should_grow(1, 2, policy.GROW_HOLDOFF_POLLS)


def test_decision_as_dict_merges_detail():
    d = policy.Decision(seq=3, t_s=1.23456, action="evict",
                        reason="r", world_before=2, world_after=1,
                        detail={"index": 1})
    got = d.as_dict()
    assert got["seq"] == 3 and got["action"] == "evict"
    assert got["world_before"] == 2 and got["world_after"] == 1
    assert got["index"] == 1
    assert got["t_s"] == 1.235


def test_gave_up_error_carries_full_decision_log():
    ds = [policy.Decision(seq=i, t_s=float(i), action=a, reason="r",
                          world_before=1, world_after=1, detail={})
          for i, a in enumerate(["launch", "relaunch", "give-up"])]
    err = policy.AutopilotGaveUpError("budget exhausted", ds)
    assert err.decisions == ds
    rep = err.report()
    for a in ("launch", "relaunch", "give-up"):
        assert a in rep
    assert "budget exhausted" in str(err)


def _state(iteration):
    return {"model_class": "KMeans", "k": 3,
            "iterations_run": iteration,
            "centroids": np.zeros((3, 2))}


def test_select_resume_picks_newest_over_the_fleet(tmp_path):
    for idx, iters in [(0, 3), (1, 5), (2, 4)]:
        save_state_rotating(policy.checkpoint_path(tmp_path, idx),
                            _state(iters))
    path, info = policy.select_resume(tmp_path, [0, 1, 2])
    assert path == policy.checkpoint_path(tmp_path, 1)
    assert info["iteration"] == 5 and info["source"] == "primary"
    assert info["torn"] == []


def test_select_resume_prev_fallback_on_torn_primary(tmp_path):
    ck = policy.checkpoint_path(tmp_path, 0)
    save_state_rotating(ck, _state(2))
    save_state_rotating(ck, _state(3))        # iter 2 rotates to .prev
    ck.write_bytes(b"torn")                   # tear the primary
    path, info = policy.select_resume(tmp_path, [0])
    assert path == ck                          # fallback loader route
    assert info["source"] == "prev" and info["iteration"] == 2


def test_select_resume_all_torn_reports_torn(tmp_path):
    ck = policy.checkpoint_path(tmp_path, 0)
    save_state_rotating(ck, _state(1))
    save_state_rotating(ck, _state(2))
    ck.write_bytes(b"torn")
    (tmp_path / f"{ck.name}.prev").write_bytes(b"torn too")
    path, info = policy.select_resume(tmp_path, [0])
    assert path is None
    assert info["torn"] == [str(ck)]


def test_select_resume_nothing_yet(tmp_path):
    path, info = policy.select_resume(tmp_path, [0, 1])
    assert path is None and info["torn"] == []


# ---------------------------------------------------------------------------
# Fault hooks
# ---------------------------------------------------------------------------

def test_inject_host_kill_targets_one_index(monkeypatch):
    monkeypatch.setenv("KMEANS_TPU_PROCESS_INDEX", "1")
    monkeypatch.setenv("KMEANS_TPU_PROCESS_COUNT", "2")
    with faults.inject_host_kill(0, after_iteration=2) as rec:
        faults.on_checkpoint(5, "ckpt")      # wrong index: no fire
        assert rec["fired_at"] is None
    with faults.inject_host_kill(1, after_iteration=3) as rec:
        faults.on_checkpoint(2, "ckpt")      # too early: no fire
        assert rec["fired_at"] is None
        with pytest.raises(faults.SimulatedPreemption):
            faults.on_checkpoint(3, "ckpt")
        assert rec["fired_at"] == 3
        faults.on_checkpoint(4, "ckpt")      # one-shot: no refire
    faults.on_checkpoint(9, "ckpt")          # removed on exit


def test_inject_launch_failures_counts_then_releases():
    with faults.inject_launch_failures(2) as rec:
        for attempt in range(2):
            with pytest.raises(faults.SimulatedLaunchFailure):
                faults.on_launch(0, attempt)
        faults.on_launch(0, 2)               # budget spent: clean
        assert rec["fired"] == 2
        assert rec["attempts"] == [(0, 0), (0, 1), (0, 2)]
    faults.on_launch(0, 0)                   # removed on exit


def test_simulated_world_env_contract():
    env = simulated_world_env(1, 4)
    assert env == {"KMEANS_TPU_PROCESS_INDEX": "1",
                   "KMEANS_TPU_PROCESS_COUNT": "4",
                   "KMEANS_TPU_HOST": "sim1"}
    assert simulated_world_env(0, 2, host="h")["KMEANS_TPU_HOST"] == "h"
    with pytest.raises(ValueError):
        simulated_world_env(4, 4)
    with pytest.raises(ValueError):
        simulated_world_env(-1, 2)


# ---------------------------------------------------------------------------
# Launcher backoff (no real worker ever spawns: every attempt flakes)
# ---------------------------------------------------------------------------

def test_launch_backoff_exhausts_budget_deterministically(tmp_path):
    spec, _ = write_spec(tmp_path)
    slept = []
    with faults.inject_launch_failures(99) as rec:
        with pytest.raises(launcher.LaunchError):
            launcher.launch_with_backoff(spec, 0, 1, tmp_path,
                                         sleep=slept.append)
    assert len(rec["attempts"]) == policy.LAUNCH_RETRY_BUDGET
    assert slept == [policy.backoff_delay_s(a)
                     for a in range(policy.LAUNCH_RETRY_BUDGET - 1)]


def test_launch_backoff_recovers_after_flakes(tmp_path):
    spec, _ = write_spec(tmp_path)
    slept = []
    with faults.inject_launch_failures(2):
        h = launcher.launch_with_backoff(spec, 0, 1, tmp_path,
                                         sleep=slept.append)
    try:
        assert h.index == 0 and h.launch_attempts == 3
        assert slept == [0.05, 0.1]
    finally:
        h.terminate()


# ---------------------------------------------------------------------------
# Chaos matrix: real subprocess workers
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def kill_resume_run(tmp_path_factory):
    """World=2, host 1 preempted mid-fit (after iteration 2), with the
    supervised resume — plus the uninterrupted oracle."""
    root = tmp_path_factory.mktemp("ap_kill")
    spec_path, spec = write_spec(
        root, faults={"kill": {"process_index": 1, "after_iteration": 2,
                               "tear": "none"}})
    result = run_autopilot(spec_path, root / "run", 2,
                           poll_period_s=0.1)
    return result, root / "run", spec


def test_kill_resume_converges_with_relaunch(kill_resume_run):
    result, out, _ = kill_resume_run
    assert result.outcome == "converged" and result.exit_code == 0
    assert result.final_world == 2
    acts = actions(result.decisions)
    assert acts.count("launch") == 2
    assert "relaunch" in acts and acts[-1] == "done"
    relaunch = [d for d in result.decisions
                if d["action"] == "relaunch"][0]
    assert relaunch["kind"] == "preempted"
    assert relaunch["exit_code"] == policy.EXIT_PREEMPTED
    assert relaunch["resume"]                 # resumed, not restarted
    # The preemption really happened: the latch is on disk.
    assert (out / "fault.kill.p1.latch").exists()


def test_kill_resume_centroids_bitexact_vs_oracle(kill_resume_run):
    result, out, spec = kill_resume_run
    assert result.centroids_agree
    oracle = oracle_centroids(spec)
    for i in range(2):
        got = np.load(out / f"centroids.p{i}.npy")
        assert got.dtype == np.float64
        np.testing.assert_array_equal(got, oracle)


def test_kill_resume_decision_log_is_complete_jsonl(kill_resume_run):
    result, out, _ = kill_resume_run
    logged = [json.loads(l) for l in
              (out / "autopilot.decisions.jsonl").read_text()
              .splitlines()]
    assert logged == result.decisions
    assert [d["seq"] for d in logged] == list(range(len(logged)))
    # Every decision also landed in the metrics registry.
    for a in set(actions(logged)):
        assert REGISTRY.counter(f"autopilot.{a}").value >= 1


def test_kill_resume_emits_decision_trace_events(kill_resume_run):
    _, out, _ = kill_resume_run
    recs = [json.loads(l) for l in
            (out / "autopilot.trace.jsonl").read_text().splitlines()]
    decisions = [r for r in recs if r.get("kind") == "event"
                 and r.get("name") == "autopilot.decision"]
    assert decisions                    # every decision is an r15 event
    spans = {r["name"] for r in recs if r.get("kind") == "span"}
    assert any(n.startswith("autopilot.") for n in spans), spans


@pytest.fixture(scope="module")
def evict_shrink_run(tmp_path_factory):
    """World=2, host 1 goes silent mid-fit (600 s checkpoint stall):
    the loop must evict it and finish degraded on the shrunk fleet."""
    root = tmp_path_factory.mktemp("ap_slow")
    spec_path, spec = write_spec(
        root, faults={"slow": {"process_index": 1, "after_iteration": 2,
                               "seconds": 600.0}})
    result = run_autopilot(spec_path, root / "run", 2, grow=False)
    return result, root / "run", spec


def test_straggler_evicted_fleet_shrinks_and_finishes(evict_shrink_run):
    result, out, _ = evict_shrink_run
    assert result.outcome == "degraded" and result.exit_code == 1
    assert result.final_world == 1
    acts = actions(result.decisions)
    assert "evict" in acts and "shrink" in acts
    evict = [d for d in result.decisions if d["action"] == "evict"][0]
    assert evict["index"] == 1
    assert evict["streak"] >= policy.STALL_CONSECUTIVE_POLLS
    shrink = [d for d in result.decisions if d["action"] == "shrink"][0]
    assert (shrink["world_before"], shrink["world_after"]) == (2, 1)


def test_evict_overhead_is_bounded(evict_shrink_run):
    """Wall-clock bound: the evict fires within the stall window plus
    a handful of polls, and the shrunk relaunch follows immediately —
    the loop never sits on a stalled fleet."""
    result, _, _ = evict_shrink_run
    by_action = {d["action"]: d for d in result.decisions}
    evict_t = by_action["evict"]["t_s"]
    # worker warmup (jax import) + stall window (>= 1 s) + 2 polls
    # + slack; a loop that waited for MAX_RUN_S would blow this.
    assert evict_t < 60.0
    relaunch_after = [d for d in result.decisions
                      if d["action"] == "relaunch"
                      and d["t_s"] >= evict_t]
    assert relaunch_after
    assert relaunch_after[0]["t_s"] - evict_t < 10.0


def test_shrunk_fleet_result_matches_oracle(evict_shrink_run):
    result, out, spec = evict_shrink_run
    assert result.centroids_agree
    np.testing.assert_array_equal(np.load(out / "centroids.p0.npy"),
                                  oracle_centroids(spec))


def test_torn_primary_resumes_from_prev(tmp_path):
    """Preemption that also tore the primary checkpoint: the relaunch
    classifies the tear and resumes from the .prev last-good rotation
    (decision ``resume-fallback-prev``), still bit-exact."""
    spec_path, spec = write_spec(
        tmp_path, faults={"kill": {"process_index": 0,
                                   "after_iteration": 2,
                                   "tear": "primary"}})
    result = run_autopilot(spec_path, tmp_path / "run", 1,
                           poll_period_s=0.1)
    acts = actions(result.decisions)
    assert result.exit_code == 0
    assert "resume-fallback-prev" in acts
    np.testing.assert_array_equal(
        np.load(tmp_path / "run" / "centroids.p0.npy"),
        oracle_centroids(spec))


def test_torn_both_rotations_gives_up_typed(tmp_path):
    """BOTH rotations torn: no silent fresh restart — the worker exits
    checkpoint-corrupt, the loop retries under RELAUNCH_BUDGET, then
    raises the typed give-up carrying the complete decision log."""
    spec_path, _ = write_spec(
        tmp_path, faults={"kill": {"process_index": 0,
                                   "after_iteration": 2,
                                   "tear": "both"}})
    with pytest.raises(AutopilotGaveUpError) as exc:
        run_autopilot(spec_path, tmp_path / "run", 1,
                      poll_period_s=0.1)
    err = exc.value
    acts = [d.action for d in err.decisions]
    assert acts[-1] == "give-up"
    assert "resume-torn" in acts
    assert acts.count("relaunch") == policy.RELAUNCH_BUDGET
    assert "budget" in err.reason
    # The flushed JSONL log survives the raise, complete.
    logged = [json.loads(l) for l in
              (tmp_path / "run" / "autopilot.decisions.jsonl")
              .read_text().splitlines()]
    assert [d["action"] for d in logged] == acts


def test_launch_flake_backoff_decisions(tmp_path):
    """Two injected launch flakes: the supervised launch retries under
    the deterministic schedule and records each backoff as a typed
    decision before converging."""
    spec_path, _ = write_spec(tmp_path)
    with faults.inject_launch_failures(2):
        result = run_autopilot(spec_path, tmp_path / "run", 1,
                               poll_period_s=0.1)
    assert result.exit_code == 0
    backoffs = [d for d in result.decisions
                if d["action"] == "launch-backoff"]
    assert [(b["attempt"], b["delay_s"]) for b in backoffs] \
        == [(0, 0.05), (1, 0.1)]


def test_launch_budget_exhaustion_gives_up(tmp_path):
    spec_path, _ = write_spec(tmp_path)
    with faults.inject_launch_failures(99):
        with pytest.raises(AutopilotGaveUpError) as exc:
            run_autopilot(spec_path, tmp_path / "run", 1)
    acts = [d.action for d in exc.value.decisions]
    assert acts == ["launch-backoff"] * (policy.LAUNCH_RETRY_BUDGET - 1) \
        + ["give-up"]


def test_grow_back_to_target_world(tmp_path):
    """Capacity-return path: a fleet started below its target world
    grows back after GROW_HOLDOFF_POLLS healthy polls and converges at
    the target."""
    spec_path, _ = write_spec(tmp_path, max_iter=8)
    result = run_autopilot(spec_path, tmp_path / "run", 1,
                           target_world=2, poll_period_s=0.05)
    assert result.outcome == "converged" and result.final_world == 2
    acts = actions(result.decisions)
    assert "grow" in acts
    grow = [d for d in result.decisions if d["action"] == "grow"][0]
    assert (grow["world_before"], grow["world_after"]) == (1, 2)
    assert result.centroids_agree


def test_capacity_fn_gates_growth(tmp_path):
    """``capacity_fn`` returning False pins a short fleet short: no
    grow decision, degraded outcome."""
    spec_path, _ = write_spec(tmp_path)
    result = run_autopilot(spec_path, tmp_path / "run", 1,
                           target_world=2, poll_period_s=0.05,
                           capacity_fn=lambda: False)
    assert result.outcome == "degraded" and result.exit_code == 1
    assert "grow" not in actions(result.decisions)


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------

def test_cli_json_converged_run(tmp_path, capsys):
    spec_path, _ = write_spec(tmp_path)
    rc = autopilot_main(["--spec", str(spec_path),
                         "--out", str(tmp_path / "run"),
                         "--world", "1", "--poll-period", "0.1",
                         "--json"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["outcome"] == "converged"
    assert payload["exit_code"] == 0
    assert payload["final_world"] == 1
    assert payload["centroids_agree"] is True
    assert payload["decisions"][-1]["action"] == "done"


def test_cli_gave_up_exits_two_with_report(tmp_path, capsys):
    spec_path, _ = write_spec(tmp_path)
    with faults.inject_launch_failures(99):
        rc = autopilot_main(["--spec", str(spec_path),
                             "--out", str(tmp_path / "run"),
                             "--world", "1", "--json"])
    assert rc == 2
    captured = capsys.readouterr()
    payload = json.loads(captured.out)
    assert payload["outcome"] == "gave-up" and payload["exit_code"] == 2
    assert payload["decisions"][-1]["action"] == "give-up"
    assert "give-up" in captured.err          # human report on stderr


def test_cli_bad_spec_exits_two(tmp_path, capsys):
    rc = autopilot_main(["--spec", str(tmp_path / "missing.json"),
                         "--out", str(tmp_path / "run"),
                         "--world", "1"])
    assert rc == 2
    assert "error:" in capsys.readouterr().err


def test_main_module_routes_autopilot(tmp_path, monkeypatch, capsys):
    import kmeans_tpu.__main__ as main_mod
    monkeypatch.setattr("sys.argv",
                        ["kmeans_tpu", "autopilot", "--spec",
                         str(tmp_path / "missing.json"), "--out",
                         str(tmp_path / "o"), "--world", "1"])
    assert main_mod.main() == 2
