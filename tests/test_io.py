"""Out-of-core ingestion (data.io): shard-local mmap reads -> ShardedDataset.

The reference's data distribution is driver-resident ``sc.parallelize``
(kmeans_spark.py:369/418/568) — bounded by driver RAM.  These tests verify
the mmap-backed path produces bit-identical datasets and fits to the
in-memory path.
"""

import numpy as np
import pytest

from kmeans_tpu import KMeans
from kmeans_tpu.data.io import from_npy, from_raw


@pytest.fixture()
def npy_file(tmp_path):
    rng = np.random.default_rng(11)
    X = rng.normal(size=(1003, 7)).astype(np.float32)   # n % shards != 0
    path = tmp_path / "points.npy"
    np.save(path, X)
    return path, X


def test_from_npy_matches_in_memory(npy_file, mesh8):
    path, X = npy_file
    ds = from_npy(path, mesh8, dtype=np.float32, k_hint=5)
    km_file = KMeans(k=5, seed=42, compute_sse=True, verbose=False,
                     dtype=np.float32).fit(ds)
    km_mem = KMeans(k=5, seed=42, compute_sse=True, verbose=False,
                    dtype=np.float32, mesh=mesh8,
                    chunk_size=ds.chunk).fit(X)
    np.testing.assert_allclose(km_file.centroids, km_mem.centroids,
                               rtol=1e-5)
    assert km_file.iterations_run == km_mem.iterations_run
    np.testing.assert_allclose(km_file.sse_history, km_mem.sse_history,
                               rtol=1e-5)


def test_from_npy_padding_is_inert(npy_file, mesh8):
    path, X = npy_file
    ds = from_npy(path, mesh8, k_hint=5)
    assert ds.n == 1003
    pts = np.asarray(ds.points)
    w = np.asarray(ds.weights)
    np.testing.assert_allclose(pts[:1003], X, rtol=0)
    assert np.all(pts[1003:] == 0)
    assert np.all(w[:1003] == 1.0) and np.all(w[1003:] == 0.0)


def test_from_npy_sample_weight(npy_file, mesh8):
    path, X = npy_file
    sw = np.linspace(0.1, 2.0, 1003)
    ds = from_npy(path, mesh8, k_hint=3, sample_weight=sw)
    np.testing.assert_allclose(np.asarray(ds.weights)[:1003],
                               sw.astype(np.float32), rtol=1e-6)
    # Row sampling reads from the mmap handle.
    rows = ds.take(np.array([0, 500, 1002]))
    np.testing.assert_allclose(rows, X[[0, 500, 1002]], rtol=0)


def test_from_npy_rejects_bad_shapes(tmp_path, mesh8):
    path = tmp_path / "bad.npy"
    np.save(path, np.zeros((4, 3, 2)))
    with pytest.raises(ValueError, match="2-D"):
        from_npy(path, mesh8)
    sw_path = tmp_path / "ok.npy"
    np.save(sw_path, np.zeros((10, 2)))
    with pytest.raises(ValueError, match="sample_weight"):
        from_npy(sw_path, mesh8, sample_weight=np.ones(7))


def test_from_npy_no_mesh_fallback(npy_file):
    path, X = npy_file
    ds = from_npy(path, None, k_hint=5)
    np.testing.assert_allclose(np.asarray(ds.points)[:1003], X, rtol=0)


def test_from_raw_matches_npy(tmp_path, mesh8):
    rng = np.random.default_rng(12)
    X = rng.normal(size=(257, 4)).astype(np.float64)
    raw = tmp_path / "points.bin"
    X.tofile(raw)
    ds = from_raw(raw, (257, 4), mesh8, file_dtype=np.float64,
                  dtype=np.float32, k_hint=4)
    np.testing.assert_allclose(np.asarray(ds.points)[:257],
                               X.astype(np.float32), rtol=0)
    km = KMeans(k=4, seed=0, verbose=False).fit(ds)
    assert km.centroids.shape == (4, 4)
    assert np.all(np.isfinite(km.centroids))


def test_budget_elems_requests_em_sized_chunks(tmp_path, mesh8):
    """r3: loaders forward ``budget_elems`` so datasets destined for a
    GaussianMixture fit get EM-sized chunks (gmm.EM_CHUNK_BUDGET).
    The fixture is large enough (40k rows/shard) that the EM budget
    MUST yield a strictly smaller chunk than the K-Means default."""
    from kmeans_tpu.models.gmm import EM_CHUNK_BUDGET
    rng = np.random.default_rng(3)
    X = rng.normal(size=(320_000, 4)).astype(np.float32)
    path = tmp_path / "big.npy"
    np.save(path, X)
    default = from_npy(path, mesh8, k_hint=256)
    em = from_npy(path, mesh8, k_hint=256, budget_elems=EM_CHUNK_BUDGET)
    assert em.chunk < default.chunk, (em.chunk, default.chunk)
    assert em.chunk <= EM_CHUNK_BUDGET // 256
    # Dataset content is identical either way.
    np.testing.assert_allclose(np.asarray(em.points)[: em.n],
                               np.asarray(default.points)[: default.n],
                               rtol=0)
