"""sample_weight support and the on-device distributed k-means++
(both beyond-reference capabilities)."""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans


def test_sample_weight_equivalent_to_repetition(mesh8):
    # Weighting a point by 3 == including it 3 times.
    rng = np.random.default_rng(0)
    X = rng.normal(size=(300, 3))
    w = rng.integers(1, 4, size=300).astype(np.float64)
    X_rep = np.repeat(X, w.astype(int), axis=0)
    init = X[:4]
    a = KMeans(k=4, init=init, max_iter=40, mesh=mesh8, dtype=np.float64,
               compute_sse=True, verbose=False).fit(X, sample_weight=w)
    b = KMeans(k=4, init=init, max_iter=40, mesh=mesh8, dtype=np.float64,
               compute_sse=True, verbose=False).fit(X_rep)
    np.testing.assert_allclose(a.centroids, b.centroids, atol=1e-9)
    np.testing.assert_allclose(a.sse_history, b.sse_history, rtol=1e-9)


def test_sample_weight_validation(mesh8):
    X = np.zeros((10, 2))
    km = KMeans(k=2, mesh=mesh8, verbose=False)
    with pytest.raises(ValueError, match="shape"):
        km.fit(X, sample_weight=np.ones(5))
    with pytest.raises(ValueError, match="finite"):
        km.fit(X, sample_weight=np.full(10, -1.0))


def test_zero_weight_points_ignored(mesh8):
    rng = np.random.default_rng(1)
    X = np.concatenate([rng.normal(size=(200, 2)),
                        rng.normal(loc=100.0, size=(50, 2))])
    w = np.concatenate([np.ones(200), np.zeros(50)])
    km = KMeans(k=3, seed=0, mesh=mesh8, dtype=np.float64,
                init=X[:3], verbose=False).fit(X, sample_weight=w)
    # No centroid should land in the zero-weight far cluster.
    assert np.all(np.abs(km.centroids) < 50)


def test_weighted_inits_never_seed_zero_weight_rows(mesh8):
    rng = np.random.default_rng(4)
    X = np.concatenate([rng.normal(size=(100, 2)),
                        rng.normal(loc=500.0, size=(40, 2))])
    w = np.concatenate([np.ones(100), np.zeros(40)])
    for init in ("forgy", "k-means++"):
        km = KMeans(k=5, init=init, seed=11, mesh=mesh8, dtype=np.float64,
                    verbose=False).fit(X, sample_weight=w)
        # All centroids near the weighted cluster; the far (zero-weight)
        # cluster — despite its huge D^2 — is never seeded.
        assert np.all(np.abs(km.centroids) < 100), init


def test_sample_weight_on_prebuilt_dataset_raises(mesh8):
    X = np.zeros((10, 2))
    km = KMeans(k=2, mesh=mesh8, verbose=False)
    ds = km.cache(X)
    with pytest.raises(ValueError, match="when caching"):
        km.fit(ds, sample_weight=np.ones(10))


def test_empty_resample_avoids_zero_weight_rows(mesh8):
    rng = np.random.default_rng(6)
    X = np.concatenate([rng.normal(size=(100, 2)),
                        rng.normal(loc=500.0, size=(100, 2))])
    w = np.concatenate([np.ones(100), np.zeros(100)])
    # Force an empty cluster: one init centroid parked far away.
    init = np.array([[0.0, 0.0], [1.0, 1.0], [-1e3, -1e3]])
    km = KMeans(k=3, init=init, max_iter=5, empty_cluster="resample",
                mesh=mesh8, dtype=np.float64, verbose=False)
    km.fit(X, sample_weight=w)
    # The refilled centroid must come from positive-weight rows.
    assert np.all(np.abs(km.centroids) < 100)


def test_device_kmeanspp_on_sharded_data(mesh8):
    X, _ = make_blobs(n_samples=2000, centers=5, n_features=4,
                      cluster_std=0.3, random_state=0)
    X = X.astype(np.float64)
    km = KMeans(k=5, init="k-means++", seed=7, mesh=mesh8,
                dtype=np.float64, compute_sse=True, verbose=False)
    ds = km.cache(X)
    ds._host = None            # force the device-only path
    km.fit(ds)
    assert np.all(np.isfinite(km.centroids))
    # k-means++ on well-separated blobs should find the true optimum:
    # compare against a strong sklearn run.
    from sklearn.cluster import KMeans as SK
    ref = SK(n_clusters=5, n_init=10, random_state=0).fit(X)
    assert km.sse_history[-1] <= ref.inertia_ * 1.05


def test_kmeans_parallel_init_quality(mesh8):
    # kmeans|| on well-separated blobs should land near the true optimum.
    X, _ = make_blobs(n_samples=4000, centers=6, n_features=5,
                      cluster_std=0.3, random_state=1)
    X = X.astype(np.float64)
    km = KMeans(k=6, init="kmeans||", seed=3, mesh=mesh8, dtype=np.float64,
                compute_sse=True, verbose=False).fit(X)
    from sklearn.cluster import KMeans as SK
    ref = SK(n_clusters=6, n_init=10, random_state=0).fit(X)
    assert km.sse_history[-1] <= ref.inertia_ * 1.05


def test_kmeans_parallel_init_weighted_excludes_zero(mesh8):
    rng = np.random.default_rng(9)
    X = np.concatenate([rng.normal(size=(300, 2)),
                        rng.normal(loc=500.0, size=(100, 2))])
    w = np.concatenate([np.ones(300), np.zeros(100)])
    km = KMeans(k=4, init="k-means||", seed=2, mesh=mesh8,
                dtype=np.float64, verbose=False).fit(X, sample_weight=w)
    assert np.all(np.abs(km.centroids) < 100)


def test_kmeans_parallel_init_on_sharded_data(mesh8):
    X, _ = make_blobs(n_samples=3000, centers=5, n_features=4,
                      cluster_std=0.4, random_state=4)
    X = X.astype(np.float64)
    km = KMeans(k=5, init="kmeans||", seed=7, mesh=mesh8, dtype=np.float64,
                compute_sse=True, verbose=False)
    ds = km.cache(X)
    km.fit(ds)
    assert np.all(np.isfinite(km.centroids))
    assert len(np.unique(km.centroids.round(9), axis=0)) == 5


def test_kmeans_parallel_host_array_smaller_than_cap():
    # Regression: a plain (unpadded) host array with n < the top_k cap
    # (always >= 256) must not crash the per-round candidate selection.
    from kmeans_tpu.models.init import kmeans_parallel_init
    rng = np.random.default_rng(5)
    X = rng.normal(size=(100, 3))
    centers = kmeans_parallel_init(X, 4, seed=0)
    assert centers.shape == (4, 3)
    assert np.all(np.isfinite(centers))


def test_kmeans_parallel_first_draw_is_weight_proportional():
    # With all the weight mass on one blob, the seeding must land there.
    rng = np.random.default_rng(6)
    X = np.concatenate([rng.normal(0, 0.1, (200, 2)),
                        rng.normal(50, 0.1, (200, 2))])
    w = np.concatenate([np.zeros(200), np.ones(200)])
    km = KMeans(k=2, init="kmeans||", seed=2, dtype=np.float64,
                verbose=False)
    km.fit(X, sample_weight=w)
    assert np.all(km.centroids[:, 0] > 40)


def test_kmeans_parallel_tiny_data_backfills(mesh8):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(12, 3))
    km = KMeans(k=8, init="kmeans||", seed=1, mesh=mesh8, dtype=np.float64,
                verbose=False).fit(X)
    assert km.centroids.shape == (8, 3)


def test_device_kmeanspp_distinct_centers(mesh8):
    rng = np.random.default_rng(2)
    X = rng.normal(size=(500, 6))
    km = KMeans(k=8, init="k-means++", seed=3, mesh=mesh8,
                dtype=np.float64, verbose=False)
    ds = km.cache(X)
    ds._host = None
    km.fit(ds)
    assert len(np.unique(km.centroids.round(9), axis=0)) == 8


@pytest.mark.parametrize("sampling", ["device", "host"])
def test_minibatch_fit_accepts_sample_weight(sampling, mesh8):
    """r4 sklearn parity: MiniBatchKMeans.fit(X, sample_weight=...) —
    rows sampled uniformly, weights scale every statistic.  Heavily
    up-weighting one blob must pull its centroid estimate like the
    weighted full-batch fit does."""
    from kmeans_tpu.models import MiniBatchKMeans
    rng = np.random.default_rng(0)
    X = np.concatenate([rng.normal(size=(1000, 4)) - 4,
                        rng.normal(size=(1000, 4)) + 4]).astype(np.float32)
    w = np.concatenate([np.full(1000, 10.0), np.ones(1000)])
    init = np.array([[-4.0] * 4, [4.0] * 4], np.float32)
    full = KMeans(k=2, seed=0, init=init, verbose=False,
                  mesh=mesh8).fit(X, sample_weight=w)
    mb = MiniBatchKMeans(k=2, seed=0, init=init, batch_size=512,
                         max_iter=60, verbose=False, mesh=mesh8,
                         sampling=sampling)
    mb.fit(X, sample_weight=w)
    np.testing.assert_allclose(mb.centroids, full.centroids, atol=0.3)
    # Lifetime counts reflect the 10x weight imbalance.
    assert mb._seen[0] > 4 * mb._seen[1]


def test_minibatch_host_engine_weights_respect_zero_rows(mesh8):
    """r4 review: the host engine must keep weights on the HOST (no full
    upload), seed inits only from positive-weight rows, and never
    reassign a dead center onto a zero-weight row."""
    from kmeans_tpu.models import MiniBatchKMeans
    rng = np.random.default_rng(1)
    good = rng.normal(size=(800, 3)).astype(np.float32)
    poison = (rng.normal(size=(800, 3)) + 1e3).astype(np.float32)
    X = np.concatenate([good, poison])
    w = np.concatenate([np.ones(800), np.zeros(800)])
    mb = MiniBatchKMeans(k=3, seed=0, init="forgy", batch_size=256,
                         max_iter=40, verbose=False, mesh=mesh8,
                         sampling="host", n_init=3)
    mb.fit(X, sample_weight=w)
    # No centroid (seeded, reassigned, or updated) may sit in the
    # zero-weight poison region.
    assert np.all(np.abs(mb.centroids) < 100)
    with pytest.raises(ValueError, match="pass sample_weight when"):
        mb.fit(mb.cache(X), sample_weight=w)
