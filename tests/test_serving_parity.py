"""Serving-path parity (ISSUE 6 acceptance): for every resident model
family the engine's labels are BIT-EQUAL to the model's own
``predict`` across 1/2/4/8-way virtual meshes; the bf16 fast path is
pinned label-exact with scale-relative distance comparison; a
multi-model routed batch equals per-model sequential results; and the
engine/``predict`` share one compiled-function + placement cache
(VERDICT C9 follow-through)."""

import json
import os

import jax
import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import (GaussianMixture, KMeans, MiniBatchKMeans,
                        SphericalKMeans)
from kmeans_tpu.models import BisectingKMeans
from kmeans_tpu.models import kmeans as kmeans_mod
from kmeans_tpu.parallel.mesh import make_mesh
from kmeans_tpu.serving import ModelRegistry, ServingEngine, load_fitted

WIDTHS = (1, 2, 4, 8)


def _mesh(w, m=1):
    if len(jax.devices()) < w * m:
        pytest.skip(f"needs {w * m} devices")
    return make_mesh(data=w, model=m, devices=jax.devices()[: w * m])


@pytest.fixture(scope="module")
def data():
    X, _ = make_blobs(n_samples=3000, centers=6, n_features=8,
                      random_state=3)
    return X.astype(np.float32)


def _engine(mesh, **kw):
    kw.setdefault("max_wait_ms", 1.0)
    return ServingEngine(mesh=mesh, **kw)


FAMILIES = {
    "kmeans": lambda: KMeans(k=5, seed=0, verbose=False, max_iter=25),
    "minibatch": lambda: MiniBatchKMeans(k=5, seed=0, verbose=False,
                                         batch_size=256, max_iter=30),
    "bisecting": lambda: BisectingKMeans(k=5, seed=0, verbose=False),
    "spherical": lambda: SphericalKMeans(k=5, seed=0, verbose=False,
                                         max_iter=25),
    "gmm": lambda: GaussianMixture(n_components=4, seed=0),
}


@pytest.mark.parametrize("width", WIDTHS)
@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_serving_labels_bitequal_to_predict(family, width, data):
    mesh = _mesh(width)
    model = FAMILIES[family]()
    model.fit(data)
    model.mesh = None                     # engine re-points to its mesh
    with _engine(mesh) as eng:
        eng.add_model("m", model)
        for m_rows in (1, 7, 64, 300):    # several buckets incl. padding
            probe = data[: m_rows]
            want = model.predict(probe)
            got = eng.predict("m", probe)
            np.testing.assert_array_equal(got, want)
            fut = eng.submit("m", probe)  # queued path, same contract
            np.testing.assert_array_equal(fut.result(timeout=30.0), want)


def test_serving_under_tp_centroid_sharding(data):
    """Model-axis (TP) sharded mesh: the engine serves through the same
    owner-reconstructing predict program; packed routing falls back to
    per-model dispatches (make_multi_predict_fn is DP-only)."""
    mesh = _mesh(4, 2) if len(jax.devices()) >= 8 else _mesh(1, 2)
    km = KMeans(k=6, seed=0, verbose=False, max_iter=25,
                model_shards=2).fit(data)
    km.mesh = None
    km2 = KMeans(k=6, seed=11, verbose=False, max_iter=25,
                 model_shards=2).fit(data)
    km2.mesh = None
    with _engine(mesh) as eng:
        eng.add_model("a", km)
        eng.add_model("b", km2)
        np.testing.assert_array_equal(eng.predict("a", data[:100]),
                                      km.predict(data[:100]))
        outs = eng.predict_multi([("a", data[:50]), ("b", data[50:90])])
        np.testing.assert_array_equal(outs[0], km.predict(data[:50]))
        np.testing.assert_array_equal(outs[1], km2.predict(data[50:90]))
        assert eng.packed_dispatches == 0       # TP fallback path


def test_gmm_proba_and_score_samples_parity(data):
    mesh = _mesh(min(4, len(jax.devices())))
    gm = GaussianMixture(n_components=4, seed=0,
                         covariance_type="diag").fit(data)
    gm.mesh = None
    with _engine(mesh) as eng:
        eng.add_model("gm", gm)
        probe = data[:123]
        np.testing.assert_array_equal(
            eng.submit("gm", probe).result(timeout=30.0),
            gm.predict(probe))
        np.testing.assert_array_equal(
            eng.submit("gm", probe, op="predict_proba").result(
                timeout=30.0),
            gm.predict_proba(probe))
        np.testing.assert_array_equal(
            eng.submit("gm", probe, op="score_samples").result(
                timeout=30.0),
            gm.score_samples(probe))
        assert np.isclose(eng.score("gm", probe), gm.score(probe),
                          rtol=0, atol=0)


@pytest.mark.parametrize("cov", ["full", "tied", "spherical"])
def test_gmm_covariance_types_serve(cov, data):
    mesh = _mesh(min(2, len(jax.devices())))
    gm = GaussianMixture(n_components=3, seed=0,
                         covariance_type=cov).fit(data)
    gm.mesh = None
    with _engine(mesh) as eng:
        eng.add_model("gm", gm)
        probe = data[:57]
        np.testing.assert_array_equal(eng.predict("gm", probe),
                                      gm.predict(probe))


def test_bf16_fast_path_labels_exact_distances_rtol(data):
    """The quantized path's labels must be BIT-EQUAL on separated data
    (argmin is ordering-robust where distances round); distances agree
    to the bf16 input-rounding class (~2^-8) relative to each row's
    distance scale."""
    mesh = _mesh(min(4, len(jax.devices())))
    km = KMeans(k=5, seed=0, verbose=False, max_iter=25).fit(data)
    km.mesh = None
    with _engine(mesh) as eng:
        rm = eng.add_model("q", km, quantize="bf16")
        assert rm.quantize == "bf16"
        probe = data[:400]
        # Serving through the quantized resident: labels == f32 oracle.
        np.testing.assert_array_equal(eng.predict("q", probe),
                                      km.predict(probe))
        report = eng.verify_quantized("q", probe)
        assert report["labels_equal"] and report["label_mismatches"] == 0
        # bf16 cross-term: ~2^-8 relative to the row scale, with
        # cancellation headroom; the f32 path would be ~1e-7.
        assert 0.0 < report["dist_max_rel"] < 0.05
        with pytest.raises(ValueError, match="quantize"):
            eng.add_model("bad", km, quantize="int4")


def test_bf16_near_tie_rows_corrected_exactly(data):
    """The exactness guard: probe rows sitting ON Voronoi boundaries
    (midpoints of centroid pairs, nudged by ~1e-4) have argmin margins
    inside the bf16 error bound — plain bf16 argmin WOULD flip some of
    them (the end-to-end drive measured 14/1000 flips on ordinary
    blobs).  The guarded path re-labels the flagged rows at f32, so
    labels stay bit-equal AND the correction counter proves the guard
    actually fired."""
    mesh = _mesh(min(2, len(jax.devices())))
    km = KMeans(k=5, seed=0, verbose=False, max_iter=25).fit(data)
    km.mesh = None
    C = np.asarray(km.centroids, np.float64)
    mids = []
    rng = np.random.default_rng(0)
    for i in range(len(C)):
        for j in range(i + 1, len(C)):
            mid = (C[i] + C[j]) / 2.0
            mids.append(mid * (1.0 + 1e-4 * rng.standard_normal()))
    probe = np.asarray(mids, np.float32)
    with _engine(mesh) as eng:
        rm = eng.add_model("q", km, quantize="bf16")
        got = eng.predict("q", probe)
        np.testing.assert_array_equal(got, km.predict(probe))
        assert rm.bf16_corrected_rows > 0        # the guard fired
        report = eng.verify_quantized("q", probe)
        assert report["labels_equal"]
        assert report["corrected_rows"] > 0
        assert eng.stats()["models"]["q"]["bf16_corrected_rows"] > 0


def test_packed_routing_of_quantized_models_stays_exact(data):
    """Review regression: packed multi-model routing has no bf16
    near-tie guard, so it must serve at f32 even when every member is
    quantized — a Voronoi-midpoint mixed batch must still equal the
    per-model sequential (guarded) results bit-for-bit."""
    mesh = _mesh(min(2, len(jax.devices())))
    a = KMeans(k=5, seed=0, verbose=False, max_iter=25).fit(data)
    b = KMeans(k=5, seed=9, verbose=False, max_iter=25).fit(data)
    a.mesh = b.mesh = None
    C = np.asarray(a.centroids, np.float64)
    mids = np.asarray([(C[i] + C[j]) / 2.0
                       for i in range(len(C))
                       for j in range(i + 1, len(C))], np.float32)
    with _engine(mesh) as eng:
        eng.add_model("a", a, quantize="bf16")
        eng.add_model("b", b, quantize="bf16")
        outs = eng.predict_multi([("a", mids), ("b", mids)])
        np.testing.assert_array_equal(outs[0], a.predict(mids))
        np.testing.assert_array_equal(outs[1], b.predict(mids))
        assert eng.packed_dispatches == 1
        # Stats coherence: the packed dispatch is ONE physical dispatch
        # in the global count and the fill histogram.
        st = eng.stats()
        assert st["dispatches"] == 1
        assert sum(v["dispatches"]
                   for v in st["batch_fill"].values()) == 1


def test_bf16_rejected_under_tp_sharding(data):
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    mesh = _mesh(1, 2)
    km = KMeans(k=4, seed=0, verbose=False, max_iter=10,
                model_shards=2).fit(data)
    km.mesh = None
    with _engine(mesh) as eng:
        with pytest.raises(ValueError, match="data-parallel"):
            eng.add_model("q", km, quantize="bf16")


def test_bf16_differs_from_f32_distances(data):
    """Guard that the fast path actually quantizes (a no-op 'bf16' mode
    would trivially pass the parity pin)."""
    mesh = _mesh(1)
    km = KMeans(k=5, seed=0, verbose=False, max_iter=25).fit(data)
    km.mesh = None
    with _engine(mesh) as eng:
        eng.add_model("q", km, quantize="bf16")
        report = eng.verify_quantized("q", data[:200])
        assert report["dist_max_rel"] > 1e-5


def test_multi_model_routed_batch_matches_sequential(data):
    """Three same-shape K-Means-family models (incl. a spherical one —
    its rows normalize before packing): one routed mixed batch ==
    per-model sequential predicts, via ONE packed dispatch."""
    mesh = _mesh(min(4, len(jax.devices())))
    a = KMeans(k=5, seed=0, verbose=False, max_iter=25).fit(data)
    b = KMeans(k=5, seed=9, verbose=False, max_iter=25).fit(data)
    s = SphericalKMeans(k=5, seed=3, verbose=False, max_iter=25).fit(data)
    for m in (a, b, s):
        m.mesh = None
    with _engine(mesh) as eng:
        eng.add_model("a", a)
        eng.add_model("b", b)
        eng.add_model("s", s)
        reqs = [("a", data[:40]), ("s", data[40:100]),
                ("b", data[100:110]), ("a", data[110:150])]
        outs = eng.predict_multi(reqs)
        np.testing.assert_array_equal(outs[0], a.predict(data[:40]))
        np.testing.assert_array_equal(outs[1], s.predict(data[40:100]))
        np.testing.assert_array_equal(outs[2], b.predict(data[100:110]))
        np.testing.assert_array_equal(outs[3], a.predict(data[110:150]))
        assert eng.packed_dispatches == 1
        # A GMM (unstackable) mixed in routes per-model, same results.
        gm = GaussianMixture(n_components=3, seed=0).fit(data)
        gm.mesh = None
        eng.add_model("gm", gm)
        outs = eng.predict_multi([("a", data[:20]), ("gm", data[:20])])
        np.testing.assert_array_equal(outs[0], a.predict(data[:20]))
        np.testing.assert_array_equal(outs[1], gm.predict(data[:20]))


def test_kmeans_score_rtol_and_transform_parity(data):
    mesh = _mesh(min(2, len(jax.devices())))
    km = KMeans(k=5, seed=0, verbose=False, max_iter=25).fit(data)
    km.mesh = None
    with _engine(mesh) as eng:
        eng.add_model("m", km)
        probe = data[:97]
        # score: same quantity, different (per-row f64 host) summation
        # order than the fused device SSE -> rtol, not bitwise.
        assert np.isclose(eng.score("m", probe), km.score(probe),
                          rtol=1e-5)
        tile = eng.submit("m", probe, op="transform").result(
            timeout=30.0)
        np.testing.assert_allclose(tile, km.transform(probe),
                                   rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------- caching


def test_predict_placement_cached_on_model(data):
    """ISSUE 6 satellite: repeated same-shape predicts place the
    centroid table ONCE (the _cents_dev instance cache), and a re-fit
    invalidates it (fresh centroids array identity)."""
    km = KMeans(k=4, seed=0, verbose=False, max_iter=10).fit(data)
    calls = []
    orig = KMeans._put_centroids

    def counting(self, cents, mesh, model_shards):
        calls.append(1)
        return orig(self, cents, mesh, model_shards)

    KMeans._put_centroids = counting
    try:
        km.predict(data[:64])
        n_after_first = len(calls)
        km.predict(data[:64])
        km.predict(data[:32])                # different shape, same table
        assert len(calls) == n_after_first   # no re-placement
        km.fit(data)                         # fresh centroids array
        km.predict(data[:64])
        assert len(calls) > n_after_first    # cache invalidated
    finally:
        KMeans._put_centroids = orig


def test_engine_and_predict_share_step_cache(data):
    """The engine's assignment program for a bucket shape IS the one
    ``KMeans.predict`` compiled (one shared _STEP_CACHE — no duplicate
    executables for the same (mesh, chunk, mode))."""
    mesh = _mesh(min(2, len(jax.devices())))
    km = KMeans(k=4, seed=0, verbose=False, max_iter=10).fit(data)
    km.mesh = mesh
    probe = np.zeros((64, data.shape[1]), np.float32)
    km.predict(probe)                        # compile via the model
    before = len(kmeans_mod._STEP_CACHE)
    with _engine(mesh) as eng:
        eng.add_model("m", km)
        eng.predict("m", probe)              # same bucket shape
    assert len(kmeans_mod._STEP_CACHE) == before


def test_explicit_chunk_size_model_serves_at_bucket_chunk(data):
    """A model fitted with an explicit training ``chunk_size`` must NOT
    impose it on serving dispatches (review finding: chunk_size=2M
    would pad an 8-row request to data_shards x 2M rows per call) —
    the engine sizes its scan chunk from the bucket shape."""
    from kmeans_tpu.serving import engine as engine_mod
    mesh = _mesh(min(2, len(jax.devices())))
    big = 65536                              # >> every default bucket
    km = KMeans(k=4, seed=0, verbose=False, max_iter=10,
                chunk_size=big).fit(data)
    ref = KMeans(k=4, seed=0, verbose=False, max_iter=10).fit(data)
    km.mesh = None
    served_chunks = []
    orig = engine_mod.shard_points

    def spying(buf, mesh_, chunk, *a, **kw):
        served_chunks.append(chunk)
        return orig(buf, mesh_, chunk, *a, **kw)

    engine_mod.shard_points = spying
    try:
        with _engine(mesh) as eng:
            rm = eng.add_model("m", km)
            assert eng._serve_chunk(rm, 8) < big
            got = eng.predict("m", data[:3])
            fut = eng.submit("m", data[:3])  # queued path too
            np.testing.assert_array_equal(fut.result(timeout=30.0), got)
    finally:
        engine_mod.shard_points = orig
    np.testing.assert_array_equal(got, ref.predict(data[:3]))
    assert served_chunks and all(c < big for c in served_chunks)


def test_warmup_excluded_from_stats_bf16_audit(data):
    """warmup() probes through the real bf16 guarded path must not
    pollute ``bf16_corrected_rows`` (review finding: the old counter
    rollback missed it).  Centroids are placed so the warm-up probe
    rows (1.0 in column 0) tie exactly — every probe row triggers the
    near-tie correction."""
    mesh = _mesh(1)
    km = KMeans(k=2, seed=0, verbose=False, max_iter=5).fit(data)
    cents = np.zeros((2, data.shape[1]), np.float32)
    cents[0, 1], cents[1, 1] = 1.0, -1.0     # equidistant from e1 probes
    km.centroids = cents
    km.mesh = None
    with _engine(mesh) as eng:
        eng.add_model("m", km, quantize="bf16")
        n = eng.warmup()
        assert n == len(eng.buckets)
        st = eng.stats()
        assert st["dispatches"] == 0 and st["batch_fill"] == {}
        assert st["models"]["m"]["bf16_corrected_rows"] == 0
        # Served traffic on the tie rows DOES audit corrections.
        probe = np.zeros((4, data.shape[1]), np.float32)
        probe[:, 0] = 1.0
        eng.predict("m", probe)
        assert eng.stats()["models"]["m"]["bf16_corrected_rows"] > 0


def test_gmm_params_dev_cached(data):
    gm = GaussianMixture(n_components=3, seed=0).fit(data)
    mesh = gm._resolve_mesh()
    p1 = gm._params_dev(mesh)
    p2 = gm._params_dev(mesh)
    assert all(a is b for a, b in zip(p1, p2))
    gm.fit(data)                             # fresh fitted arrays
    p3 = gm._params_dev(mesh)
    assert p3 is not p1 and not all(a is b for a, b in zip(p1, p3))


# ----------------------------------------------------- registry + ckpts


def test_registry_load_all_families_roundtrip(tmp_path, data):
    mesh = _mesh(min(2, len(jax.devices())))
    models = {name: FAMILIES[name]().fit(data)
              for name in ("kmeans", "spherical", "gmm")}
    with _engine(mesh) as eng:
        for name, model in models.items():
            path = tmp_path / f"{name}.npz"
            model.save(path)
            mid = eng.load(path)
            assert mid == name
            np.testing.assert_array_equal(
                eng.predict(mid, data[:80]), models[name].predict(
                    data[:80]))
        stats = eng.stats()
        assert stats["models_resident"] == 3
        assert stats["models"]["gmm"]["family"] == "gmm"


def test_registry_semantics(tmp_path, data):
    km = KMeans(k=3, seed=0, verbose=False, max_iter=5).fit(data)
    reg = ModelRegistry()
    reg.register("a", km)
    with pytest.raises(ValueError, match="already resident"):
        reg.register("a", km)
    with pytest.raises(KeyError, match="no resident model"):
        reg.get("zzz")
    # Unfitted models are rejected at registration.
    with pytest.raises(ValueError, match="fitted"):
        reg.register("b", KMeans(k=3, verbose=False))
    # Collision-suffixed ids on load.
    km.save(tmp_path / "a.npz")
    mid, _ = reg.load(tmp_path / "a.npz")
    assert mid == "a-2"
    assert reg.ids() == ["a", "a-2"]
    # Pack groups: same (k, d, dtype) K-Means family.
    assert list(reg.pack_groups().values()) == [["a", "a-2"]]
    reg.remove("a-2")
    assert reg.pack_groups() == {}


def test_load_fitted_rejects_unknown_class(tmp_path, data):
    km = KMeans(k=3, seed=0, verbose=False, max_iter=5).fit(data)
    state = km._state_dict()
    state["model_class"] = "FancyModel"
    from kmeans_tpu.utils import checkpoint as ckpt
    path = tmp_path / "weird.npz"
    ckpt.save_state(path, state)
    with pytest.raises(ValueError, match="FancyModel"):
        load_fitted(path)


def test_fitted_state_specs(data):
    km = KMeans(k=4, seed=0, verbose=False, max_iter=5).fit(data)
    spec = km.fitted_state()
    assert spec["family"] == "kmeans" and spec["stackable"]
    assert spec["d"] == data.shape[1]
    sk = SphericalKMeans(k=4, seed=0, verbose=False, max_iter=5).fit(data)
    assert sk.fitted_state()["normalize_inputs"]
    gm = GaussianMixture(n_components=3, seed=0).fit(data)
    gspec = gm.fitted_state()
    assert gspec["family"] == "gmm" and not gspec["stackable"]
    with pytest.raises(ValueError, match="fitted"):
        KMeans(k=3, verbose=False).fitted_state()


# ------------------------------------------------- engine-level behavior


def test_engine_validation_and_stats(data):
    mesh = _mesh(1)
    km = KMeans(k=4, seed=0, verbose=False, max_iter=10).fit(data)
    km.mesh = None
    with _engine(mesh) as eng:
        eng.add_model("m", km)
        # Submit-time poison isolation: bad requests fail alone...
        bad_width = eng.submit("m", np.zeros((2, 3), np.float32))
        nan_rows = eng.submit("m", np.full((1, data.shape[1]), np.nan,
                                           np.float32))
        unknown = eng.submit("zzz", data[:1])
        bad_op = eng.submit("m", data[:1], op="predict_proba")
        good = eng.submit("m", data[:2])
        np.testing.assert_array_equal(good.result(timeout=30.0),
                                      km.predict(data[:2]))
        for fut, match in ((bad_width, "rows must be"),
                           (nan_rows, "non-finite"),
                           (unknown, "no resident model"),
                           (bad_op, "not served")):
            assert match in str(fut.exception(timeout=30.0))
        # 1-D convenience: a single row without the batch axis.
        one = eng.predict("m", data[0])
        assert one.shape == (1,)
        stats = eng.stats()
        assert stats["models_resident"] == 1
        assert stats["dispatches"] >= 2
        fills = stats["batch_fill"]
        assert fills and all(0 < v["fill"] <= 1 for v in fills.values())
        json.dumps(stats)                    # JSON-serializable contract


def test_engine_warmup_excluded_from_stats(data):
    mesh = _mesh(1)
    km = KMeans(k=4, seed=0, verbose=False, max_iter=10).fit(data)
    km.mesh = None
    with _engine(mesh) as eng:
        eng.add_model("m", km)
        n = eng.warmup()
        assert n == len(eng.buckets)
        st = eng.stats()
        assert st["dispatches"] == 0 and st["batch_fill"] == {}


def test_serve_cli_jsonl_loop(tmp_path, data, monkeypatch, capsys):
    """The ``serve`` CLI satellite: JSONL request loop over stdin, per-
    request errors isolated, stats line, final --json stats output."""
    import io

    from kmeans_tpu.cli import serve_main
    km = KMeans(k=4, seed=0, verbose=False, max_iter=10).fit(data)
    km.save(tmp_path / "km.npz")
    want = km.predict(data[:3]).tolist()
    lines = [
        json.dumps({"x": data[:3].tolist(), "id": "r1"}),
        json.dumps({"stats": True}),
        json.dumps({"model": "nope", "x": [[0.0] * data.shape[1]]}),
        "not json at all",
    ]
    monkeypatch.setattr("sys.stdin", io.StringIO("\n".join(lines) + "\n"))
    rc = serve_main(["--model", str(tmp_path / "km.npz"), "--json",
                     "--no-warmup", "--max-wait-ms", "1.0"])
    assert rc == 0
    out_lines = [json.loads(ln) for ln in
                 capsys.readouterr().out.strip().splitlines()]
    assert out_lines[0]["result"] == want and out_lines[0]["id"] == "r1"
    assert out_lines[1]["models_resident"] == 1       # stats request
    assert "error" in out_lines[2] and "error" in out_lines[3]
    final = out_lines[-1]                             # --json stats
    # The serial stdin loop dispatches immediately (no queue — going
    # through submit would pay the flush timer per request for
    # coalescing that can never happen), so requests land on the
    # per-model counters, not the queue's.
    assert final["models"]["km"]["requests"] >= 1
    assert final["models"]["km"]["model_class"] == "KMeans"


def test_serve_cli_missing_checkpoint(tmp_path, capsys):
    from kmeans_tpu.cli import serve_main
    rc = serve_main(["--model", str(tmp_path / "nope.npz")])
    assert rc == 2
    assert "cannot load" in capsys.readouterr().err
