"""Test C capability: SSE monotone convergence (kmeans_spark.py:457-500).

5000 pts / 4 centers / 5-D, k=4, max_iter=30, tol=1e-5, compute_sse=True;
walk ``sse_history`` asserting no increase beyond 1e-6 (the reference's
numerical slack, kmeans_spark.py:487-494).
"""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_sse_monotonically_decreases(mesh8, dtype):
    X, _ = make_blobs(n_samples=5000, centers=4, n_features=5,
                      random_state=42)
    km = KMeans(k=4, max_iter=30, tolerance=1e-5, seed=42, compute_sse=True,
                mesh=mesh8, dtype=dtype, verbose=False).fit(X)
    h = km.sse_history
    assert len(h) >= 2
    for i in range(1, len(h)):
        assert h[i] <= h[i - 1] + 1e-6, \
            f"SSE increased from {h[i-1]} to {h[i]} at iteration {i+1}"


def test_sse_history_empty_when_disabled(mesh8):
    X, _ = make_blobs(n_samples=500, centers=3, n_features=2,
                      random_state=42)
    km = KMeans(k=3, compute_sse=False, mesh=mesh8, verbose=False).fit(X)
    assert km.sse_history == []          # flag semantics, kmeans_spark.py:277
    assert km.iterations_run >= 1        # fixed reference bug (SURVEY §2.1)


def test_converges_before_max_iter(mesh8):
    X, _ = make_blobs(n_samples=2000, centers=3, n_features=2,
                      random_state=0, cluster_std=0.3)
    km = KMeans(k=3, max_iter=100, tolerance=1e-4, seed=1, mesh=mesh8,
                verbose=False).fit(X)
    assert km.iterations_run < 100       # early stop, kmeans_spark.py:310-313
