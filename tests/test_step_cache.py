"""Step-function cache bounding (r3 VERDICT weak #7): streaming many
distinct block shapes must not pin a compiled executable per shape for
the process lifetime."""

import numpy as np
import pytest

from kmeans_tpu import KMeans
from kmeans_tpu.models import kmeans as kmeans_mod
from kmeans_tpu.utils.cache import LRUCache


def test_lru_semantics():
    c = LRUCache(2)
    c["a"] = 1
    c["b"] = 2
    _ = c["a"]          # refresh a
    c["c"] = 3          # evicts b (LRU)
    assert "a" in c and "c" in c and "b" not in c and len(c) == 2
    with pytest.raises(ValueError, match="maxsize"):
        LRUCache(0)


def test_get_or_create_never_raises_on_eviction():
    """The models go through get_or_create, so a concurrent eviction
    between check and read can never surface as KeyError — the factory
    result is returned directly."""
    c = LRUCache(1)
    calls = []
    assert c.get_or_create("a", lambda: calls.append("a") or 1) == 1
    assert c.get_or_create("b", lambda: calls.append("b") or 2) == 2  # evicts a
    assert c.get_or_create("a", lambda: calls.append("a2") or 3) == 3
    assert calls == ["a", "b", "a2"] and len(c) == 1


def test_predict_stream_cache_bounded(monkeypatch):
    cap = 6
    monkeypatch.setattr(kmeans_mod, "_STEP_CACHE", LRUCache(cap))
    rng = np.random.default_rng(0)
    X = rng.normal(size=(512, 4)).astype(np.float32)
    km = KMeans(k=3, seed=0, verbose=False, max_iter=5).fit(X)
    want = km.predict(X)

    # 20 distinct block sizes -> 20 distinct padded shapes; without the
    # bound each would pin its own compiled predict program.
    sizes = [17 + 13 * i for i in range(20)]
    got = np.concatenate(list(km.predict_stream(
        lambda: (X[: s] for s in sizes))))
    assert len(kmeans_mod._STEP_CACHE) <= cap
    # Labels stay correct across evictions/recompiles.
    np.testing.assert_array_equal(got, np.concatenate(
        [want[: s] for s in sizes]))
