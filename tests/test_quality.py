"""ISSUE 14: serving-quality & drift observability.

Five tiers of coverage:

1. **Detector unit fixtures** — hand-computed PSI/JS values, the
   empty-bin smoothing contract, and the sentinel-row label mask.
2. **Monitor semantics** — row-counted windows, the committed
   debounce (fire at exactly DEBOUNCE consecutive breaching windows,
   recover after DEBOUNCE clean ones), the JSONL sink stream.
3. **Reference profiles** — ``quality_profile()`` on all five
   families, persisted through the r10 checkpoint metadata block and
   carried into the serving registry by ``engine.load``.
4. **Engine acceptance** — monitoring-on vs monitoring-off serve
   labels BIT-EQUAL with dispatch counts unchanged across all four
   dispatch paths (direct / queued / packed / bf16-guarded), and the
   injected-drift end-to-end: a traffic generator shifts the blob
   mixture mid-serve — stationary traffic stays silent, shifted
   traffic fires within the committed debounce window.
5. **CLIs** — ``serve-status`` exit codes (0 healthy / 1 drifting /
   2 unreadable) and the ``bench-diff`` regression guard, plus the
   r15 ``obs.heartbeat`` namespace back-compat pin (satellite).
"""

import json
from pathlib import Path

import jax
import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import (GaussianMixture, KMeans, MiniBatchKMeans,
                        SphericalKMeans)
from kmeans_tpu.models import BisectingKMeans
from kmeans_tpu.obs import drift
from kmeans_tpu.obs.trace import TraceReadError
from kmeans_tpu.parallel.mesh import make_mesh
from kmeans_tpu.serving import ServingEngine


def _mesh(w):
    if len(jax.devices()) < w:
        pytest.skip(f"needs {w} devices")
    return make_mesh(data=w, devices=jax.devices()[:w])


@pytest.fixture(scope="module")
def data():
    X, _ = make_blobs(n_samples=4000, centers=4, n_features=8,
                      cluster_std=0.6, random_state=7)
    return X.astype(np.float32)


# ---------------------------------------------------------------------------
# 1. Detector unit fixtures
# ---------------------------------------------------------------------------

def test_psi_hand_computed():
    """ref [50, 50] vs cur [90, 10]:
    PSI = (0.9-0.5)ln(0.9/0.5) + (0.1-0.5)ln(0.1/0.5) = 0.87889...
    (smoothing is 1e-6/bin — invisible at 1e-4 tolerance)."""
    assert drift.psi([50, 50], [90, 10]) == pytest.approx(0.878890,
                                                          abs=1e-4)
    assert drift.psi([50, 50], [50, 50]) == pytest.approx(0.0, abs=1e-9)


def test_js_hand_computed():
    """Same pair: m = [0.7, 0.3],
    JS = 0.5 KL2(r||m) + 0.5 KL2(c||m) = 0.146780... bits; bounded by
    1 and symmetric."""
    assert drift.js_divergence([50, 50], [90, 10]) == pytest.approx(
        0.146780, abs=1e-4)
    assert drift.js_divergence([90, 10], [50, 50]) == pytest.approx(
        drift.js_divergence([50, 50], [90, 10]), abs=1e-12)
    # Disjoint distributions: JS -> 1 bit (its upper bound).
    assert drift.js_divergence([1, 0], [0, 1]) == pytest.approx(
        1.0, abs=1e-3)


def test_empty_bin_smoothing_keeps_detectors_finite():
    """A cluster with zero serving traffic (or zero training mass)
    must contribute a finite term, never an infinity — the smoothing
    contract."""
    for ref, cur in (([100, 0], [0, 100]), ([1, 0, 0], [0, 0, 1])):
        assert np.isfinite(drift.psi(ref, cur))
        assert np.isfinite(drift.js_divergence(ref, cur))
    # PSI on disjoint mass is huge but finite — the alert still fires.
    assert drift.psi([100, 0], [0, 100]) > drift.PSI_ALERT


def test_assignment_counts_masks_sentinel_labels():
    """The k-sweep / TP padding discipline pads centroid tables with
    inert sentinel rows; a sentinel label leaking through must be
    DROPPED (not clipped into a real bin)."""
    counts = drift.assignment_counts(np.array([0, 1, 1, 5, 7]), k=2)
    np.testing.assert_array_equal(counts, [1.0, 2.0])
    # Negative labels (hand-built fixtures) take the masked slow path.
    counts = drift.assignment_counts(np.array([-1, 0, 1]), k=2)
    np.testing.assert_array_equal(counts, [1.0, 1.0])


def test_inline_close_matches_public_detectors():
    """The monitor's optimized in-close arithmetic (cached smoothed
    reference + shared logs) must equal the public psi()/js() to
    float64 — one formula, two spellings."""
    rng = np.random.default_rng(0)
    ref = rng.integers(1, 100, size=16)
    prof = drift.build_profile(family="kmeans", model_class="KMeans",
                               k=16, counts=ref)
    mon = drift.QualityMonitor("m", 16, profile=prof, window_rows=64)
    labels = rng.integers(0, 16, size=64)
    mon.observe(64, labels=labels)
    last = mon.history()[-1]["detectors"]
    cur = drift.assignment_counts(labels, 16)
    # The monitor's reference is the profile's NORMALIZED histogram
    # (that is what the checkpoint persists) — compare against the
    # public detectors on the same inputs.
    ref_hist = prof["assignment_hist"]
    assert last["psi"] == pytest.approx(drift.psi(ref_hist, cur),
                                        rel=1e-12)
    assert last["js"] == pytest.approx(
        drift.js_divergence(ref_hist, cur), rel=1e-12)


def test_committed_thresholds_pinned():
    """The decision table is COMMITTED (the fleet-status discipline):
    these numbers moving is an API change, not a tweak."""
    assert drift.COMMITTED_THRESHOLDS == {
        "psi": 0.25, "js": 0.10, "score_ratio": 2.0,
        "near_tie_frac": 0.05}
    assert drift.DRIFT_WINDOW_ROWS == 512
    assert drift.DRIFT_DEBOUNCE_WINDOWS == 2


def test_build_profile_validates_and_coerces():
    prof = drift.build_profile(
        family="kmeans", model_class="KMeans", k=3,
        counts=np.array([2, 1, 1], np.int64), score_kind="sse",
        score_per_row=np.float64(1.5), n_rows=np.float64(4))
    assert prof["assignment_hist"] == [0.5, 0.25, 0.25]
    # JSON-clean: every value must be a plain Python type.
    json.dumps(prof)
    with pytest.raises(ValueError, match="bins"):
        drift.build_profile(family="kmeans", model_class="KMeans",
                            k=3, counts=[1, 2])
    with pytest.raises(ValueError, match="score_kind"):
        drift.build_profile(family="kmeans", model_class="KMeans",
                            k=2, score_kind="rmse")


# ---------------------------------------------------------------------------
# 2. Monitor semantics: windows, debounce, sink
# ---------------------------------------------------------------------------

def _monitor(tmp_path=None, **kw):
    prof = drift.build_profile(family="kmeans", model_class="KMeans",
                               k=4, counts=[25, 25, 25, 25],
                               score_kind="sse", score_per_row=1.0,
                               n_rows=100)
    sink = str(tmp_path / "quality.m.jsonl") if tmp_path else None
    kw.setdefault("window_rows", 32)
    return drift.QualityMonitor("m", 4, profile=prof, sink_path=sink,
                                **kw)


def test_debounce_fires_at_exactly_n_consecutive_windows():
    mon = _monitor()
    shifted = np.zeros(32, np.int32)          # all mass on cluster 0
    mon.observe(32, labels=shifted)           # window 1: breach
    assert not mon.drifting and mon.events == 0
    mon.observe(32, labels=shifted)           # window 2: debounce met
    assert mon.drifting and mon.events == 1
    mon.observe(32, labels=shifted)           # still drifting, 1 event
    assert mon.events == 1
    balanced = np.arange(32, dtype=np.int32) % 4
    mon.observe(32, labels=balanced)          # clean window 1
    assert mon.drifting                       # debounce on recovery too
    mon.observe(32, labels=balanced)          # clean window 2
    assert not mon.drifting
    assert mon.events == 1


def test_info_free_windows_are_not_evidence():
    """Review regression: a window where no detector could evaluate
    (transform-only traffic — rows, no labels) must neither reset a
    breach streak nor count toward recovery."""
    mon = _monitor()
    shifted = np.zeros(32, np.int32)
    mon.observe(32, labels=shifted)           # breach 1
    mon.observe(32)                           # info-free: no reset
    assert mon.history()[-1]["informative"] is False
    mon.observe(32, labels=shifted)           # breach 2 -> fires
    assert mon.drifting and mon.events == 1
    mon.observe(32)                           # info-free windows must
    mon.observe(32)                           # not "recover" either
    assert mon.drifting


def test_minibatch_profile_score_uses_dataset_rows_not_lifetime_seen():
    """Review regression: MiniBatch's histogram mass is its lifetime
    _seen counts (passes x batch), but inertia_ is the full-dataset
    SSE estimate — the score-per-row denominator must be the dataset
    weight or a healthy multi-pass model reads as drifting forever."""
    rng = np.random.default_rng(0)
    X = (rng.standard_normal((20000, 8)) * 0.5
         + rng.integers(0, 4, 20000)[:, None] * 6).astype(np.float32)
    mb = MiniBatchKMeans(k=4, seed=0, verbose=False, batch_size=1024,
                         max_iter=60, compute_sse=True).fit(X)
    prof = mb.quality_profile()
    assert prof["n_rows"] == pytest.approx(len(X))
    # The reference must agree with the directly recomputed SSE/row —
    # serving the model its own training data must sit near ratio 1.
    true_spr = mb.quality_profile(X)["score_per_row"]
    assert prof["score_per_row"] == pytest.approx(true_spr, rel=0.25)
    assert prof["score_per_row"] / true_spr < drift.SCORE_RATIO_ALERT


def test_sink_never_opens_after_close(tmp_path):
    """Review regression: a monitor whose sink was never lazily opened
    must not create the file from an in-flight dispatch after
    close()."""
    sink = tmp_path / "late.jsonl"
    mon = drift.QualityMonitor("m", 4, sink_path=str(sink),
                               window_rows=8)   # no profile: lazy open
    mon.close()
    mon.observe(8, labels=np.zeros(8, np.int32))   # closes a window
    assert not sink.exists()


def test_one_bad_window_between_clean_ones_never_fires():
    mon = _monitor()
    shifted = np.zeros(32, np.int32)
    balanced = np.arange(32, dtype=np.int32) % 4
    for _ in range(4):
        mon.observe(32, labels=shifted)
        mon.observe(32, labels=balanced)
    assert mon.events == 0 and not mon.drifting


def test_score_ratio_and_near_tie_detectors():
    mon = _monitor()
    balanced = np.arange(32, dtype=np.int32) % 4
    # score 3x the training score_per_row=1.0 -> ratio breach; the
    # near-tie fraction 8/32 = 25% breaches its 5% threshold too.
    for _ in range(drift.DRIFT_DEBOUNCE_WINDOWS):
        mon.observe(32, labels=balanced,
                    score=np.full(32, 3.0), near_ties=8,
                    guarded_rows=32)
    assert mon.drifting
    last = mon.history()[-1]
    assert last["detectors"]["score_ratio"] == pytest.approx(3.0)
    assert last["detectors"]["near_tie_frac"] == pytest.approx(0.25)
    assert {"score_ratio", "near_tie_frac"} <= set(last["breaching"])
    assert last["detectors"]["psi"] < drift.PSI_ALERT  # hist stayed ok


def test_non_positive_score_reference_deactivates_ratio():
    prof = drift.build_profile(family="gmm", model_class="G", k=2,
                               counts=[1, 1], score_kind="neg_log_lik",
                               score_per_row=-0.5)
    mon = drift.QualityMonitor("m", 2, profile=prof, window_rows=8)
    mon.observe(8, labels=np.zeros(8, np.int32), score=np.full(8, 9.0))
    assert mon.history()[-1]["detectors"]["score_ratio"] is None


def test_monitor_rejects_mismatched_reference_k():
    prof = drift.build_profile(family="kmeans", model_class="K", k=3,
                               counts=[1, 1, 1])
    with pytest.raises(ValueError, match="k="):
        drift.QualityMonitor("m", 5, profile=prof)


def test_sink_stream_and_reader(tmp_path):
    mon = _monitor(tmp_path)
    shifted = np.zeros(32, np.int32)
    for _ in range(3):
        mon.observe(32, labels=shifted)
    mon.close()
    records = drift.read_quality_log(tmp_path / "quality.m.jsonl")
    kinds = [r["kind"] for r in records]
    assert kinds[0] == "profile"
    assert kinds.count("window") == 3
    assert kinds.count("drift") == 1          # fired once, debounced
    assert all(r["model"] == "m" for r in records)
    # Torn live tail is tolerated; a garbage body line is not.
    p = tmp_path / "quality.m.jsonl"
    with open(p, "a") as f:
        f.write('{"kind": "window", "model":')       # torn tail
    assert len(drift.read_quality_log(p)) == len(records)
    (tmp_path / "garbage.jsonl").write_text("not json\nstill not\n")
    with pytest.raises(TraceReadError):
        drift.read_quality_log(tmp_path / "garbage.jsonl")


def test_quality_report_aggregates_and_classifies(tmp_path):
    mon = _monitor(tmp_path)
    for _ in range(2):
        mon.observe(32, labels=np.zeros(32, np.int32))
    mon.close()
    # A co-located heartbeat sink must be skipped on a DIRECTORY scan.
    (tmp_path / "hb.jsonl").write_text(
        json.dumps({"ts": 1.0, "iteration": 1}) + "\n")
    report = drift.quality_report(str(tmp_path))
    assert list(report["models"]) == ["m"]
    assert report["models"]["m"]["windows"] == 2
    assert report["models"]["m"]["drifting"] is True
    assert report["drifting"] == ["m"] and not report["healthy"]
    assert drift.format_quality_status(report).startswith(
        "serving quality: 1 model")
    # A directory with no quality stream classifies as unreadable.
    with pytest.raises(TraceReadError):
        drift.quality_report(str(tmp_path / "hb.jsonl") + "x")


# ---------------------------------------------------------------------------
# 3. Reference profiles across the five families
# ---------------------------------------------------------------------------

FAMILIES = {
    "kmeans": lambda: KMeans(k=4, seed=0, verbose=False, max_iter=20,
                             compute_sse=True),
    "minibatch": lambda: MiniBatchKMeans(k=4, seed=0, verbose=False,
                                         batch_size=256, max_iter=25),
    "bisecting": lambda: BisectingKMeans(k=4, seed=0, verbose=False,
                                         compute_sse=True),
    "spherical": lambda: SphericalKMeans(k=4, seed=0, verbose=False,
                                         max_iter=20),
    "gmm": lambda: GaussianMixture(n_components=4, seed=0),
}


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_profile_roundtrips_through_checkpoint(family, data, tmp_path):
    model = FAMILIES[family]().fit(data)
    prof = model.quality_profile()
    assert prof is not None and prof["k"] == 4
    assert prof["assignment_hist"] is not None
    assert sum(prof["assignment_hist"]) == pytest.approx(1.0)
    path = tmp_path / f"{family}.npz"
    model.save(path)
    loaded = type(model).load(path)
    # The loaded model has no training stats, yet carries the SAME
    # reference window via the r10 metadata block.
    assert loaded.quality_profile() == prof


def test_bisecting_profile_carries_per_cluster_sse(data):
    model = FAMILIES["bisecting"]().fit(data)
    prof = model.quality_profile()
    assert prof["per_cluster_sse"] is not None
    assert len(prof["per_cluster_sse"]) == 4


def test_profile_from_explicit_data(data):
    km = FAMILIES["kmeans"]().fit(data)
    prof = km.quality_profile(data)
    assert prof["score_kind"] == "sse"
    assert prof["n_rows"] == float(len(data))
    # Inertia/row from the fused fit pass == the recomputed one.
    attrs = km.quality_profile()
    assert prof["score_per_row"] == pytest.approx(
        attrs["score_per_row"], rel=1e-2)
    assert prof["per_cluster_sse"] is not None
    assert sum(prof["per_cluster_sse"]) == pytest.approx(
        prof["score_per_row"] * prof["n_rows"], rel=1e-6)


def test_unfitted_profile_is_none():
    assert KMeans(k=3, verbose=False).quality_profile() is None
    assert GaussianMixture(n_components=2).quality_profile() is None


# ---------------------------------------------------------------------------
# 4. Engine acceptance: parity, zero extra dispatches, injected drift
# ---------------------------------------------------------------------------

def _paired_engines(mesh, models, tmp_path=None, **on_kw):
    """(quality-on, quality-off) engines holding deepcopies of the
    same fitted models."""
    import copy
    on_kw.setdefault("quality", True)
    if tmp_path is not None:
        on_kw.setdefault("quality_dir", str(tmp_path))
    eng_on = ServingEngine(mesh=mesh, max_wait_ms=1.0, **on_kw)
    eng_off = ServingEngine(mesh=mesh, max_wait_ms=1.0, quality=False)
    for mid, model, kw in models:
        twin = copy.deepcopy(model)
        twin.mesh = None
        eng_on.add_model(mid, model, **kw)
        eng_off.add_model(mid, twin, **kw)
    return eng_on, eng_off


def test_monitoring_parity_all_dispatch_paths(data, tmp_path):
    """THE acceptance pin: monitoring-on vs monitoring-off labels are
    bit-equal and dispatch counts identical across direct, queued,
    packed, and bf16-guarded dispatch paths — the quality feed only
    READS what the dispatch computed."""
    mesh = _mesh(1)
    a = KMeans(k=4, seed=0, verbose=False, max_iter=20).fit(data)
    b = KMeans(k=4, seed=9, verbose=False, max_iter=20).fit(data)
    q = KMeans(k=4, seed=5, verbose=False, max_iter=20).fit(data)
    gm = GaussianMixture(n_components=4, seed=0).fit(data)
    for m in (a, b, q, gm):
        m.mesh = None
    eng_on, eng_off = _paired_engines(
        mesh, [("a", a, {}), ("b", b, {}),
               ("q", q, {"quantize": "bf16"}), ("gm", gm, {})],
        tmp_path)
    with eng_on, eng_off:
        for rows in (1, 7, 300):
            probe = data[:rows]
            for mid in ("a", "gm", "q"):
                np.testing.assert_array_equal(
                    eng_on.predict(mid, probe),          # direct
                    eng_off.predict(mid, probe))
                np.testing.assert_array_equal(
                    eng_on.submit(mid, probe).result(30.0),   # queued
                    eng_off.submit(mid, probe).result(30.0))
            for on, off in zip(                          # packed
                    eng_on.predict_multi([("a", probe), ("b", probe)]),
                    eng_off.predict_multi([("a", probe),
                                           ("b", probe)])):
                np.testing.assert_array_equal(on, off)
            np.testing.assert_array_equal(               # score path
                eng_on.call("a", probe, op="score_rows"),
                eng_off.call("a", probe, op="score_rows"))
        # Zero extra dispatches: identical traffic, identical counts.
        assert eng_on.dispatches == eng_off.dispatches
        assert eng_on.packed_dispatches == eng_off.packed_dispatches
        st = eng_on.stats()
        for mid in ("a", "b", "q", "gm"):
            assert st["models"][mid]["dispatches"] == \
                eng_off.stats()["models"][mid]["dispatches"]
        # The quality block exists and saw the traffic (incl. the
        # guarded path's near-tie accounting on the quantized model).
        assert st["quality"]["a"]["rows"] > 0
        assert st["quality"]["q"]["rows"] > 0
        assert eng_off.stats()["quality"]["a"] is None


def drift_traffic(data, labels_true, weights_a, weights_b,
                  shift_after, batch, seed=0):
    """Faults-style deterministic traffic generator: draws request
    batches from the blob mixture with per-cluster weights
    ``weights_a``, switching to ``weights_b`` after ``shift_after``
    batches — the injected-drift harness."""
    rng = np.random.default_rng(seed)
    by_cluster = [np.flatnonzero(labels_true == c)
                  for c in range(len(weights_a))]
    i = 0
    while True:
        w = np.asarray(weights_a if i < shift_after else weights_b,
                       np.float64)
        w = w / w.sum()
        comps = rng.choice(len(w), size=batch, p=w)
        rows = np.stack([data[rng.choice(by_cluster[c])]
                         for c in comps])
        yield rows
        i += 1


def test_injected_drift_fires_shifted_stays_silent_stationary(
        data, tmp_path):
    """End-to-end: a model fitted on the balanced blob mixture serves
    (a) stationary traffic — same mixture, fresh draws — which must
    stay SILENT, then (b) mixture-shifted traffic (90% of mass on one
    blob) which must fire within the committed debounce window."""
    X, y = make_blobs(n_samples=4000, centers=4, n_features=8,
                      cluster_std=0.6, random_state=7)
    X = X.astype(np.float32)
    km = KMeans(k=4, seed=0, verbose=False, max_iter=25,
                compute_sse=True).fit(X)
    km.mesh = None
    window = 256
    eng = ServingEngine(mesh=_mesh(1), quality=True,
                        quality_dir=str(tmp_path),
                        quality_window=window)
    with eng:
        eng.add_model("m", km)
        batch = 128
        balanced = [1, 1, 1, 1]
        shifted = [0.9, 0.04, 0.03, 0.03]
        # Phase (a): 8 stationary windows.
        gen = drift_traffic(X, y, balanced, balanced, 10 ** 9, batch)
        for _ in range(8 * (window // batch)):
            eng.call("m", next(gen))
        status = eng.quality_status()["m"]
        assert status["windows"] >= 8
        assert status["events"] == 0 and not status["drifting"]
        # Phase (b): shifted traffic must fire after exactly the
        # debounce window count (2 windows = 4 batches here).
        gen = drift_traffic(X, y, shifted, shifted, 0, batch, seed=1)
        for _ in range(drift.DRIFT_DEBOUNCE_WINDOWS
                       * (window // batch)):
            eng.call("m", next(gen))
        status = eng.quality_status()["m"]
        assert status["drifting"] and status["events"] == 1
        assert "psi" in status["breaching"]
        assert status["detectors"]["psi"] > drift.PSI_ALERT
    # The sink recorded it for serve-status.
    report = drift.quality_report(str(tmp_path))
    assert report["drifting"] == ["m"]


def test_engine_load_carries_checkpoint_profile(data, tmp_path):
    km = KMeans(k=4, seed=0, verbose=False, max_iter=20,
                compute_sse=True).fit(data)
    km.save(tmp_path / "km.npz")
    eng = ServingEngine(mesh=_mesh(1), quality=True)
    with eng:
        mid = eng.load(tmp_path / "km.npz")
        status = eng.quality_status()[mid]
        assert status["reference"] is True
        assert status["score_kind"] == "sse"


def test_quality_auto_resolution_and_validation(data):
    """'auto' resolves OFF on CPU (the measured BENCH_QUALITY rule) —
    unless a quality_dir asks for sinks, which implies monitoring."""
    km = KMeans(k=4, seed=0, verbose=False, max_iter=10).fit(data)
    km.mesh = None
    eng = ServingEngine(mesh=_mesh(1))
    if jax.default_backend() == "cpu":
        assert eng._quality is False
    eng.close()
    eng = ServingEngine(mesh=_mesh(1), quality_dir="/tmp/unused-qdir")
    assert eng._quality is True
    eng.close()
    with pytest.raises(ValueError, match="quality"):
        ServingEngine(mesh=_mesh(1), quality="yes")


def test_warmup_and_verify_probes_stay_out_of_monitor(data):
    km = KMeans(k=4, seed=0, verbose=False, max_iter=20).fit(data)
    km.mesh = None
    eng = ServingEngine(mesh=_mesh(1), quality=True)
    with eng:
        eng.add_model("m", km, quantize="bf16")
        eng.warmup()
        eng.verify_quantized("m", data[:100])
        assert eng.quality_status()["m"]["rows"] == 0
        eng.predict("m", data[:50])
        assert eng.quality_status()["m"]["rows"] == 50


def test_latency_histograms_per_model_and_bucket(data):
    from kmeans_tpu.obs.metrics_registry import REGISTRY
    km = KMeans(k=4, seed=0, verbose=False, max_iter=10).fit(data)
    km.mesh = None
    eng = ServingEngine(mesh=_mesh(1), quality=True)
    with eng:
        eng.add_model("lat", km)
        eng.predict("lat", data[:3])          # bucket 8
        eng.predict("lat", data[:100])        # bucket 512
    snap = REGISTRY.snapshot()
    assert snap["serve.latency_ms.lat.b8"]["value"]["count"] >= 1
    assert snap["serve.latency_ms.lat.b512"]["value"]["count"] >= 1


# ---------------------------------------------------------------------------
# 5. CLIs + namespace back-compat satellite
# ---------------------------------------------------------------------------

def test_serve_status_cli_exit_codes(data, tmp_path, capsys):
    from kmeans_tpu.cli import serve_status_main
    km = KMeans(k=4, seed=0, verbose=False, max_iter=20).fit(data)
    km.mesh = None
    qdir = tmp_path / "q"
    eng = ServingEngine(mesh=_mesh(1), quality=True,
                        quality_dir=str(qdir), quality_window=64)
    with eng:
        eng.add_model("m", km)
        for _ in range(3):
            eng.call("m", data[:64])          # stationary -> healthy
    assert serve_status_main([str(qdir)]) == 0
    out = capsys.readouterr().out
    assert "HEALTHY" in out
    assert serve_status_main([str(qdir), "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["healthy"] and report["models"]["m"]["windows"] == 3
    # Drifting stream -> exit 1 (append a drift record the way the
    # monitor writes one).
    sink = qdir / "quality.m.jsonl"
    with open(sink, "a") as f:
        f.write(json.dumps({"kind": "drift", "model": "m", "ts": 9e9,
                            "drifting": True, "window": 4,
                            "detectors": {}, "breaching": ["psi"]})
                + "\n")
    assert serve_status_main([str(qdir)]) == 1
    assert "DRIFTING" in capsys.readouterr().out
    # Unreadable -> exit 2.
    assert serve_status_main([str(tmp_path / "nope")]) == 2
    bad = tmp_path / "bad.jsonl"
    bad.write_text("garbage\n" * 3)
    assert serve_status_main([str(bad)]) == 2


def test_serve_cli_quality_op(data, tmp_path, capsys, monkeypatch):
    import io

    from kmeans_tpu.cli import serve_main
    km = KMeans(k=4, seed=0, verbose=False, max_iter=15).fit(data)
    km.save(tmp_path / "km.npz")
    req = json.dumps({"model": "km", "x": data[:4].tolist()})
    monkeypatch.setattr("sys.stdin",
                        io.StringIO(req + "\n"
                                    + json.dumps({"quality": True})
                                    + "\n"))
    rc = serve_main(["--model", str(tmp_path / "km.npz"), "--quality",
                     "--no-warmup"])
    assert rc == 0
    lines = [json.loads(line) for line in
             capsys.readouterr().out.strip().splitlines()]
    assert lines[0]["result"] == km.predict(data[:4]).tolist()
    assert lines[1]["km"]["reference"] is True
    assert lines[1]["km"]["rows"] == 4


def _bench_doc(path, ms, spread=0.01, metric="kmeans_iter_x"):
    path.write_text(json.dumps(
        {"parsed": {"metric": metric, "ms_per_iter": ms,
                    "value": 1e9 * 38.0 / ms, "spread": spread}}))
    return path


def test_bench_diff_ok_regression_and_unreadable(tmp_path, capsys):
    from kmeans_tpu.cli import bench_diff_main
    old = _bench_doc(tmp_path / "old.json", 38.0)
    # Inside the recorded spread (5% floor): not a regression.
    same = _bench_doc(tmp_path / "same.json", 39.0)
    assert bench_diff_main([str(old), str(same)]) == 0
    capsys.readouterr()
    # 20% slower: regression on ms_per_iter AND on throughput.
    slow = _bench_doc(tmp_path / "slow.json", 45.6)
    assert bench_diff_main([str(old), str(slow)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out
    # An IMPROVEMENT is never flagged.
    fast = _bench_doc(tmp_path / "fast.json", 20.0)
    assert bench_diff_main([str(old), str(fast)]) == 0
    capsys.readouterr()
    # --json is machine-readable and names the regressed rows.
    assert bench_diff_main([str(old), str(slow), "--json"]) == 1
    diff = json.loads(capsys.readouterr().out)
    assert diff["regressed"] == [f"{'kmeans_iter_x'}"]
    # Unreadable / disjoint -> exit 2.
    assert bench_diff_main([str(old), str(tmp_path / "nope.json")]) == 2
    other = _bench_doc(tmp_path / "other.json", 10.0, metric="other")
    assert bench_diff_main([str(old), str(other)]) == 2


def test_bench_diff_honors_any_recorded_spread_field(tmp_path, capsys):
    """Review regression: rows across rounds record noise under
    different names (overhead_spread, speedup_spread, ...); a change
    inside THAT recorded spread must never flag."""
    from kmeans_tpu.cli import bench_diff_main
    old = tmp_path / "o.json"
    new = tmp_path / "n.json"
    old.write_text(json.dumps({"parsed": {
        "metric": "quality_overhead", "overhead_ratio": 1.1413,
        "overhead_spread": 0.196}}))
    new.write_text(json.dumps({"parsed": {
        "metric": "quality_overhead", "overhead_ratio": 1.25,
        "overhead_spread": 0.15}}))
    assert bench_diff_main([str(old), str(new)]) == 0   # inside 19.6%
    capsys.readouterr()
    worse = tmp_path / "w.json"
    worse.write_text(json.dumps({"parsed": {
        "metric": "quality_overhead", "overhead_ratio": 1.40,
        "overhead_spread": 0.02}}))
    assert bench_diff_main([str(old), str(worse)]) == 1  # beyond it


def test_sink_concurrent_window_closes_never_tear(tmp_path):
    """Review regression: concurrent dispatch threads closing windows
    must serialize their sink writes — every line in the stream parses
    (read_quality_log is strict about non-final lines)."""
    import threading
    sink = tmp_path / "quality.c.jsonl"
    mon = drift.QualityMonitor("c", 4, sink_path=str(sink),
                               window_rows=8)
    labels = np.arange(8, dtype=np.int32) % 4

    def hammer():
        for _ in range(200):
            mon.observe(8, labels=labels)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    mon.close()
    assert mon.sink_errors == 0
    records = drift.read_quality_log(sink)
    assert len(records) == 800           # one window per 8-row batch
    assert all(r["kind"] == "window" for r in records)


def test_bench_diff_reads_baseline_format(tmp_path, capsys):
    from kmeans_tpu.cli import bench_diff_main
    base = Path(__file__).resolve().parents[1] / "BASELINE.json"
    assert bench_diff_main([str(base), str(base), "--json"]) == 0
    diff = json.loads(capsys.readouterr().out)
    # Review regression: the 4 per-batch-size serving rows share one
    # config/model key — they must disambiguate, never collapse.
    serving = [k for k in diff["rows"]
               if k.startswith("online serving") and "batch_requests="
               in k]
    assert len(serving) == 4


def test_bench_diff_duplicate_keys_and_jsonl(tmp_path, capsys):
    """Review regressions: same-key rows disambiguate (a regression in
    ANY of them flags), and multi-line JSONL bench artifacts parse."""
    from kmeans_tpu.cli import bench_diff_main

    def rows(q64):
        return "\n".join(json.dumps(
            {"config": "serve", "model": "kmeans", "batch_requests": b,
             "qps": q, "spread": 0.01})
            for b, q in ((8, 1000.0), (64, q64))) + "\n"

    old = tmp_path / "old.jsonl"
    new = tmp_path / "new.jsonl"
    old.write_text(rows(10000.0))
    new.write_text(rows(5000.0))              # B=64 qps halved
    assert bench_diff_main([str(old), str(new), "--json"]) == 1
    diff = json.loads(capsys.readouterr().out)
    assert diff["rows_compared"] == 2
    assert diff["regressed"] == ["serve [kmeans] (batch_requests=64)"]


def test_obs_heartbeat_namespace_backcompat():
    """The r15 namespace wart, pinned closed (ISSUE 14 satellite):
    package-level re-exports are the supported spelling, the scope
    callable still shadows the submodule attribute, and the submodule
    import path keeps working."""
    import importlib

    import kmeans_tpu.obs as obs
    # Package-level re-exports (what consumers use now).
    from kmeans_tpu.obs import Heartbeat, get_heartbeat, note_progress
    hb_mod = importlib.import_module("kmeans_tpu.obs.heartbeat")
    assert obs.heartbeat is hb_mod.heartbeat       # callable, shadows
    assert callable(obs.heartbeat)
    assert Heartbeat is hb_mod.Heartbeat
    assert note_progress is hb_mod.note_progress
    assert get_heartbeat is hb_mod.get_heartbeat
    # The submodule route (pre-r18 consumers) keeps working.
    from kmeans_tpu.obs.heartbeat import note_progress as np2
    assert np2 is note_progress
    # The models now import from package level — no consumer reaches
    # through the shadowed attribute anymore.
    import kmeans_tpu.models.kmeans as km_mod
    assert km_mod.obs_note_progress is note_progress


def test_drift_module_is_lazy_on_obs_package():
    """obs stays stdlib at import; obs.drift resolves lazily and is
    the same module object as the direct import."""
    import kmeans_tpu.obs as obs
    from kmeans_tpu.obs import drift as direct
    assert obs.drift is direct
