"""The ``python -m kmeans_tpu fit`` CLI."""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from kmeans_tpu.cli import main as cli_main


@pytest.fixture()
def data_file(tmp_path):
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=8, size=(4, 6)).astype(np.float32)
    X = (centers[rng.integers(0, 4, 2000)]
         + rng.normal(size=(2000, 6)).astype(np.float32))
    path = tmp_path / "points.npy"
    np.save(path, X)
    return path


def test_fit_cli_kmeans(data_file, tmp_path):
    out = tmp_path / "out"
    rc = cli_main([str(data_file), "--k", "4", "--sse", "--quiet",
                   "--out-dir", str(out)])
    assert rc == 0
    centroids = np.load(out / "centroids.npy")
    labels = np.load(out / "labels.npy")
    summary = json.loads((out / "summary.json").read_text())
    assert centroids.shape == (4, 6)
    assert labels.shape == (2000,) and labels.max() < 4
    assert summary["iterations"] >= 1
    assert summary["sse_history"] == sorted(summary["sse_history"],
                                            reverse=True)


@pytest.mark.parametrize("model", ["minibatch", "bisecting", "spherical"])
def test_fit_cli_model_families(data_file, tmp_path, model):
    out = tmp_path / model
    rc = cli_main([str(data_file), "--k", "3", "--model", model, "--quiet",
                   "--out-dir", str(out), "--max-iter", "10"])
    assert rc == 0
    assert np.load(out / "centroids.npy").shape == (3, 6)


def test_fit_cli_bad_shape(tmp_path):
    path = tmp_path / "bad.npy"
    np.save(path, np.zeros(7, np.float32))
    assert cli_main([str(path), "--k", "2", "--quiet"]) == 2


def test_fit_cli_npz(data_file, tmp_path):
    X = np.load(data_file)
    npz = tmp_path / "data.npz"
    np.savez(npz, features=X)
    out = tmp_path / "npz_out"
    rc = cli_main([str(npz), "--npz-key", "features", "--k", "2", "--quiet",
                   "--out-dir", str(out), "--max-iter", "5"])
    assert rc == 0


def test_fit_cli_missing_file(tmp_path, capsys):
    assert cli_main([str(tmp_path / "nope.npy"), "--k", "2",
                     "--quiet"]) == 2
    assert "error:" in capsys.readouterr().err


def test_fit_cli_bad_npz_key(data_file, tmp_path, capsys):
    npz = tmp_path / "d.npz"
    np.savez(npz, a=np.load(data_file))
    assert cli_main([str(npz), "--npz-key", "missing", "--k", "2",
                     "--quiet"]) == 2
    assert "available" in capsys.readouterr().err


def test_fit_cli_inertia_without_sse(data_file, tmp_path):
    out = tmp_path / "nosse"
    assert cli_main([str(data_file), "--k", "4", "--quiet",
                     "--out-dir", str(out)]) == 0
    summary = json.loads((out / "summary.json").read_text())
    assert summary["inertia"] is not None and summary["inertia"] > 0


def test_report_command_generates_artifacts(tmp_path):
    """Artifact parity (r3 missing #1/#2): the architecture diagram and
    the one-page report regenerate from code."""
    pytest.importorskip("matplotlib")   # optional dep, like the plots
    from kmeans_tpu.utils.diagram import main as report_main
    assert report_main(["--out-dir", str(tmp_path)]) == 0
    png = tmp_path / "architecture_diagram.png"
    pdf = tmp_path / "kmeans_tpu_report.pdf"
    assert png.exists() and png.stat().st_size > 10_000
    assert pdf.exists() and pdf.stat().st_size > 10_000


# ------------------------------------------------------------- sweep CLI


def test_sweep_cli_kmeans_json(data_file, tmp_path, capsys):
    from kmeans_tpu.cli import sweep_main
    out = tmp_path / "sweep_out"
    rc = sweep_main([str(data_file), "--k-range", "2:7", "--n-init", "2",
                     "--max-iter", "20", "--out-dir", str(out), "--json"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["k_range"] == [2, 3, 4, 5, 6]
    assert summary["selected_k"] in summary["k_range"]
    assert summary["batched"] is True
    # O(1) dispatches for the whole inertia sweep: ONE batched fit.
    assert summary["dispatches"] == 1
    assert len(summary["member_scores"]) == 5
    assert all(len(row) == 2 for row in summary["member_scores"])
    # Artifacts: the selected model's table + the machine summary.
    k_sel = summary["selected_k"]
    assert np.load(out / "centroids.npy").shape == (k_sel, 6)
    disk = json.loads((out / "sweep.json").read_text())
    assert disk["selected_k"] == k_sel


def test_sweep_cli_gmm_bic(data_file, tmp_path, capsys):
    from kmeans_tpu.cli import sweep_main
    out = tmp_path / "gmm_sweep"
    rc = sweep_main([str(data_file), "--model", "gmm", "--k-range", "2,4",
                     "--criterion", "bic", "--max-iter", "15",
                     "--out-dir", str(out), "--json"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["criterion"] == "bic"
    assert summary["k_range"] == [2, 4]
    assert summary["dispatches"] == 1
    assert np.load(out / "centroids.npy").shape[0] == summary["selected_k"]


def test_sweep_cli_invalid_range_exits_2(data_file, capsys):
    from kmeans_tpu.cli import sweep_main
    # Empty range, garbage, and k >= n all exit 2 with an error line.
    for bad in ("9:2", "abc", "0:4"):
        assert sweep_main([str(data_file), "--k-range", bad]) == 2
        assert "error:" in capsys.readouterr().err
    assert sweep_main([str(data_file), "--k-range", "2:5000"]) == 2


def test_sweep_cli_criterion_family_mismatch(data_file, capsys):
    from kmeans_tpu.cli import sweep_main
    assert sweep_main([str(data_file), "--k-range", "2:5",
                       "--criterion", "bic"]) == 2
    assert sweep_main([str(data_file), "--model", "gmm", "--k-range",
                       "2:5", "--criterion", "silhouette"]) == 2


def test_sweep_cli_sequential_oracle(data_file, tmp_path, capsys):
    from kmeans_tpu.cli import sweep_main
    out_b = tmp_path / "b"
    out_s = tmp_path / "s"
    rc = sweep_main([str(data_file), "--k-range", "3:6", "--max-iter",
                     "15", "--out-dir", str(out_b), "--json"])
    batched = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    rc = sweep_main([str(data_file), "--k-range", "3:6", "--max-iter",
                     "15", "--sequential", "--out-dir", str(out_s),
                     "--json"])
    seq = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert seq["batched"] is False
    assert seq["selected_k"] == batched["selected_k"]
    np.testing.assert_allclose(
        [batched["scores"][k] for k in map(str, batched["k_range"])],
        [seq["scores"][k] for k in map(str, seq["k_range"])],
        rtol=1e-5)


# ---------------------------------------------------------------------------
# warm / ckpt-info aot block / bench-diff TTFI artifacts (ISSUE 15)
# ---------------------------------------------------------------------------

@pytest.fixture()
def _aot_clean():
    """Warm-command tests simulate a fresh process: the in-memory step
    caches must start cold (earlier suite tests populate entries at
    these small shapes, which would make `warm` a no-op builder) and
    the store must not leak out."""
    from kmeans_tpu.utils import aot
    import kmeans_tpu.models.kmeans as km_mod
    km_mod._STEP_CACHE.clear()
    yield
    km_mod._STEP_CACHE.clear()
    aot.deactivate()


def test_warm_cli_shape_form_json(tmp_path, capsys, _aot_clean):
    from kmeans_tpu.cli import warm_main
    rc = warm_main(["--family", "kmeans", "--shape", "1024x8",
                    "--k", "4", "--aot-dir", str(tmp_path / "aot"),
                    "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["available"] is True
    assert out["built"] >= 1 and out["saved"] == out["built"]
    assert list(Path(tmp_path / "aot").glob("*.aotx"))


def test_warm_cli_from_checkpoint_ships_and_loads(tmp_path, capsys,
                                                  _aot_clean):
    from kmeans_tpu import KMeans
    from kmeans_tpu.cli import ckpt_info_main, warm_main
    from kmeans_tpu.utils import aot
    rng = np.random.default_rng(0)
    X = rng.normal(size=(600, 5)).astype(np.float32)
    KMeans(k=3, max_iter=4, seed=0, verbose=False).fit(X).save(
        tmp_path / "m.npz")
    # Cache wipe = the fresh-process boundary: the fit above populated
    # in-memory entries that a real warm-command process starts
    # without.
    import kmeans_tpu.models.kmeans as km_mod
    km_mod._STEP_CACHE.clear()
    rc = warm_main([str(tmp_path / "m.npz"), "--shape", "1024x5",
                    "--aot-dir", str(tmp_path / "aot"), "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["k"] == 3 and out["built"] >= 1
    shipped = aot.aot_dir_for(tmp_path / "m.npz")
    assert shipped.is_dir() and list(shipped.glob("*.aotx"))
    # ckpt-info reports the shipped aot block.
    rc = ckpt_info_main([str(tmp_path / "m.npz"), "--json"])
    assert rc == 0
    info = json.loads(capsys.readouterr().out)
    assert info["aot"]["exists"] is True
    assert info["aot"]["artifacts"] >= 1
    # A second warm against the same store loads instead of building
    # (in-memory caches cleared = the fresh-process boundary).
    km_mod._STEP_CACHE.clear()
    rc = warm_main([str(tmp_path / "m.npz"), "--shape", "1024x5",
                    "--aot-dir", str(tmp_path / "aot"), "--json"])
    assert rc == 0
    out2 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out2["loaded"] >= 1 and out2["built"] == 0


def test_warm_cli_requires_shape_without_ckpt(capsys, _aot_clean):
    from kmeans_tpu.cli import warm_main
    rc = warm_main(["--family", "kmeans", "--k", "4"])
    assert rc == 2
    assert "--shape" in capsys.readouterr().err


def test_ckpt_info_reports_missing_aot(tmp_path, capsys):
    from kmeans_tpu import KMeans
    from kmeans_tpu.cli import ckpt_info_main
    rng = np.random.default_rng(0)
    KMeans(k=3, max_iter=3, seed=0, verbose=False).fit(
        rng.normal(size=(400, 4)).astype(np.float32)).save(
        tmp_path / "m.npz")
    rc = ckpt_info_main([str(tmp_path / "m.npz")])
    assert rc == 0
    assert "none shipped" in capsys.readouterr().out


def _write_ttfi_trace(path, compile_ms):
    """A minimal trace JSONL with one compile span + one dispatch."""
    recs = [
        {"kind": "header", "wall0": 0.0, "pid": 1,
         "format": "kmeans_tpu.trace.v1"},
        {"kind": "span", "name": "place", "id": 0, "parent": None,
         "depth": 0, "tid": 1, "t0": 0.0, "t1": 0.01, "dur": 0.01},
        {"kind": "span", "name": "compile", "id": 1, "parent": None,
         "depth": 0, "tid": 1, "t0": 0.02,
         "t1": 0.02 + compile_ms / 1e3, "dur": compile_ms / 1e3},
        {"kind": "span", "name": "dispatch", "id": 2, "parent": None,
         "depth": 0, "tid": 1, "t0": 0.5, "t1": 0.6, "dur": 0.1},
    ]
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")


def test_bench_diff_reads_ttfi_trace_artifacts(tmp_path, capsys):
    """The TTFI guard (ISSUE 15 satellite): trace JSONL artifacts
    compare per-phase, and a cold->warm compile regression beyond the
    spread floor exits 1 like any ms/iter row."""
    from kmeans_tpu.cli import bench_diff_main
    old, new = tmp_path / "old.jsonl", tmp_path / "new.jsonl"
    _write_ttfi_trace(old, compile_ms=10.0)
    _write_ttfi_trace(new, compile_ms=9.0)
    assert bench_diff_main([str(old), str(new)]) == 0
    capsys.readouterr()
    _write_ttfi_trace(new, compile_ms=200.0)
    assert bench_diff_main([str(old), str(new), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert any(k.startswith("ttfi compile") for k in doc["rows"])
    assert any("ttfi compile" in r for r in doc["regressed"])
