"""The ``python -m kmeans_tpu fit`` CLI."""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from kmeans_tpu.cli import main as cli_main


@pytest.fixture()
def data_file(tmp_path):
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=8, size=(4, 6)).astype(np.float32)
    X = (centers[rng.integers(0, 4, 2000)]
         + rng.normal(size=(2000, 6)).astype(np.float32))
    path = tmp_path / "points.npy"
    np.save(path, X)
    return path


def test_fit_cli_kmeans(data_file, tmp_path):
    out = tmp_path / "out"
    rc = cli_main([str(data_file), "--k", "4", "--sse", "--quiet",
                   "--out-dir", str(out)])
    assert rc == 0
    centroids = np.load(out / "centroids.npy")
    labels = np.load(out / "labels.npy")
    summary = json.loads((out / "summary.json").read_text())
    assert centroids.shape == (4, 6)
    assert labels.shape == (2000,) and labels.max() < 4
    assert summary["iterations"] >= 1
    assert summary["sse_history"] == sorted(summary["sse_history"],
                                            reverse=True)


@pytest.mark.parametrize("model", ["minibatch", "bisecting", "spherical"])
def test_fit_cli_model_families(data_file, tmp_path, model):
    out = tmp_path / model
    rc = cli_main([str(data_file), "--k", "3", "--model", model, "--quiet",
                   "--out-dir", str(out), "--max-iter", "10"])
    assert rc == 0
    assert np.load(out / "centroids.npy").shape == (3, 6)


def test_fit_cli_bad_shape(tmp_path):
    path = tmp_path / "bad.npy"
    np.save(path, np.zeros(7, np.float32))
    assert cli_main([str(path), "--k", "2", "--quiet"]) == 2


def test_fit_cli_npz(data_file, tmp_path):
    X = np.load(data_file)
    npz = tmp_path / "data.npz"
    np.savez(npz, features=X)
    out = tmp_path / "npz_out"
    rc = cli_main([str(npz), "--npz-key", "features", "--k", "2", "--quiet",
                   "--out-dir", str(out), "--max-iter", "5"])
    assert rc == 0


def test_fit_cli_missing_file(tmp_path, capsys):
    assert cli_main([str(tmp_path / "nope.npy"), "--k", "2",
                     "--quiet"]) == 2
    assert "error:" in capsys.readouterr().err


def test_fit_cli_bad_npz_key(data_file, tmp_path, capsys):
    npz = tmp_path / "d.npz"
    np.savez(npz, a=np.load(data_file))
    assert cli_main([str(npz), "--npz-key", "missing", "--k", "2",
                     "--quiet"]) == 2
    assert "available" in capsys.readouterr().err


def test_fit_cli_inertia_without_sse(data_file, tmp_path):
    out = tmp_path / "nosse"
    assert cli_main([str(data_file), "--k", "4", "--quiet",
                     "--out-dir", str(out)]) == 0
    summary = json.loads((out / "summary.json").read_text())
    assert summary["inertia"] is not None and summary["inertia"] > 0


def test_report_command_generates_artifacts(tmp_path):
    """Artifact parity (r3 missing #1/#2): the architecture diagram and
    the one-page report regenerate from code."""
    pytest.importorskip("matplotlib")   # optional dep, like the plots
    from kmeans_tpu.utils.diagram import main as report_main
    assert report_main(["--out-dir", str(tmp_path)]) == 0
    png = tmp_path / "architecture_diagram.png"
    pdf = tmp_path / "kmeans_tpu_report.pdf"
    assert png.exists() and png.stat().st_size > 10_000
    assert pdf.exists() and pdf.stat().st_size > 10_000
