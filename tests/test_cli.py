"""The ``python -m kmeans_tpu fit`` CLI."""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from kmeans_tpu.cli import main as cli_main


@pytest.fixture()
def data_file(tmp_path):
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=8, size=(4, 6)).astype(np.float32)
    X = (centers[rng.integers(0, 4, 2000)]
         + rng.normal(size=(2000, 6)).astype(np.float32))
    path = tmp_path / "points.npy"
    np.save(path, X)
    return path


def test_fit_cli_kmeans(data_file, tmp_path):
    out = tmp_path / "out"
    rc = cli_main([str(data_file), "--k", "4", "--sse", "--quiet",
                   "--out-dir", str(out)])
    assert rc == 0
    centroids = np.load(out / "centroids.npy")
    labels = np.load(out / "labels.npy")
    summary = json.loads((out / "summary.json").read_text())
    assert centroids.shape == (4, 6)
    assert labels.shape == (2000,) and labels.max() < 4
    assert summary["iterations"] >= 1
    assert summary["sse_history"] == sorted(summary["sse_history"],
                                            reverse=True)


@pytest.mark.parametrize("model", ["minibatch", "bisecting", "spherical"])
def test_fit_cli_model_families(data_file, tmp_path, model):
    out = tmp_path / model
    rc = cli_main([str(data_file), "--k", "3", "--model", model, "--quiet",
                   "--out-dir", str(out), "--max-iter", "10"])
    assert rc == 0
    assert np.load(out / "centroids.npy").shape == (3, 6)


def test_fit_cli_bad_shape(tmp_path):
    path = tmp_path / "bad.npy"
    np.save(path, np.zeros(7, np.float32))
    assert cli_main([str(path), "--k", "2", "--quiet"]) == 2


def test_fit_cli_npz(data_file, tmp_path):
    X = np.load(data_file)
    npz = tmp_path / "data.npz"
    np.savez(npz, features=X)
    out = tmp_path / "npz_out"
    rc = cli_main([str(npz), "--npz-key", "features", "--k", "2", "--quiet",
                   "--out-dir", str(out), "--max-iter", "5"])
    assert rc == 0


def test_fit_cli_missing_file(tmp_path, capsys):
    assert cli_main([str(tmp_path / "nope.npy"), "--k", "2",
                     "--quiet"]) == 2
    assert "error:" in capsys.readouterr().err


def test_fit_cli_bad_npz_key(data_file, tmp_path, capsys):
    npz = tmp_path / "d.npz"
    np.savez(npz, a=np.load(data_file))
    assert cli_main([str(npz), "--npz-key", "missing", "--k", "2",
                     "--quiet"]) == 2
    assert "available" in capsys.readouterr().err


def test_fit_cli_inertia_without_sse(data_file, tmp_path):
    out = tmp_path / "nosse"
    assert cli_main([str(data_file), "--k", "4", "--quiet",
                     "--out-dir", str(out)]) == 0
    summary = json.loads((out / "summary.json").read_text())
    assert summary["inertia"] is not None and summary["inertia"] > 0


def test_report_command_generates_artifacts(tmp_path):
    """Artifact parity (r3 missing #1/#2): the architecture diagram and
    the one-page report regenerate from code."""
    pytest.importorskip("matplotlib")   # optional dep, like the plots
    from kmeans_tpu.utils.diagram import main as report_main
    assert report_main(["--out-dir", str(tmp_path)]) == 0
    png = tmp_path / "architecture_diagram.png"
    pdf = tmp_path / "kmeans_tpu_report.pdf"
    assert png.exists() and png.stat().st_size > 10_000
    assert pdf.exists() and pdf.stat().st_size > 10_000


# ------------------------------------------------------------- sweep CLI


def test_sweep_cli_kmeans_json(data_file, tmp_path, capsys):
    from kmeans_tpu.cli import sweep_main
    out = tmp_path / "sweep_out"
    rc = sweep_main([str(data_file), "--k-range", "2:7", "--n-init", "2",
                     "--max-iter", "20", "--out-dir", str(out), "--json"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["k_range"] == [2, 3, 4, 5, 6]
    assert summary["selected_k"] in summary["k_range"]
    assert summary["batched"] is True
    # O(1) dispatches for the whole inertia sweep: ONE batched fit.
    assert summary["dispatches"] == 1
    assert len(summary["member_scores"]) == 5
    assert all(len(row) == 2 for row in summary["member_scores"])
    # Artifacts: the selected model's table + the machine summary.
    k_sel = summary["selected_k"]
    assert np.load(out / "centroids.npy").shape == (k_sel, 6)
    disk = json.loads((out / "sweep.json").read_text())
    assert disk["selected_k"] == k_sel


def test_sweep_cli_gmm_bic(data_file, tmp_path, capsys):
    from kmeans_tpu.cli import sweep_main
    out = tmp_path / "gmm_sweep"
    rc = sweep_main([str(data_file), "--model", "gmm", "--k-range", "2,4",
                     "--criterion", "bic", "--max-iter", "15",
                     "--out-dir", str(out), "--json"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["criterion"] == "bic"
    assert summary["k_range"] == [2, 4]
    assert summary["dispatches"] == 1
    assert np.load(out / "centroids.npy").shape[0] == summary["selected_k"]


def test_sweep_cli_invalid_range_exits_2(data_file, capsys):
    from kmeans_tpu.cli import sweep_main
    # Empty range, garbage, and k >= n all exit 2 with an error line.
    for bad in ("9:2", "abc", "0:4"):
        assert sweep_main([str(data_file), "--k-range", bad]) == 2
        assert "error:" in capsys.readouterr().err
    assert sweep_main([str(data_file), "--k-range", "2:5000"]) == 2


def test_sweep_cli_criterion_family_mismatch(data_file, capsys):
    from kmeans_tpu.cli import sweep_main
    assert sweep_main([str(data_file), "--k-range", "2:5",
                       "--criterion", "bic"]) == 2
    assert sweep_main([str(data_file), "--model", "gmm", "--k-range",
                       "2:5", "--criterion", "silhouette"]) == 2


def test_sweep_cli_sequential_oracle(data_file, tmp_path, capsys):
    from kmeans_tpu.cli import sweep_main
    out_b = tmp_path / "b"
    out_s = tmp_path / "s"
    rc = sweep_main([str(data_file), "--k-range", "3:6", "--max-iter",
                     "15", "--out-dir", str(out_b), "--json"])
    batched = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    rc = sweep_main([str(data_file), "--k-range", "3:6", "--max-iter",
                     "15", "--sequential", "--out-dir", str(out_s),
                     "--json"])
    seq = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert seq["batched"] is False
    assert seq["selected_k"] == batched["selected_k"]
    np.testing.assert_allclose(
        [batched["scores"][k] for k in map(str, batched["k_range"])],
        [seq["scores"][k] for k in map(str, seq["k_range"])],
        rtol=1e-5)
