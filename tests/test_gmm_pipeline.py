"""Pipelined GMM E-step (ISSUE 3): the software-pipelined chunk
schedule (``pipeline=1``) against the serial four-phase oracle
(``pipeline=0``) — the ``prefetch=0`` discipline of r6: the pipelined
schedule moves WHERE work happens, never its arithmetic or fold order,
so trajectories must match the oracle bit-for-bit (CPU exact dots;
1e-6 is the documented bar on bf16-rate hardware dots)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kmeans_tpu.models import GaussianMixture
from kmeans_tpu.parallel import gmm_step
from kmeans_tpu.parallel.mesh import make_mesh


def _blobs(n=1536, d=6, centers=3, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    comp = rng.integers(0, centers, n)
    return (comp[:, None] * 5.0
            + rng.normal(size=(n, d))).astype(dtype)


def _fit_pair(ct, mesh, *, pipeline_on=1, host_loop=True, k=3,
              model_shards=1, X=None, sample_weight=None, chunk=256,
              max_iter=6):
    """Fit the same model under both schedules; returns the two fits."""
    out = []
    for pipeline in (pipeline_on, 0):
        g = GaussianMixture(n_components=k, covariance_type=ct,
                            max_iter=max_iter, seed=0,
                            init_params="random", host_loop=host_loop,
                            mesh=mesh, model_shards=model_shards,
                            chunk_size=chunk, pipeline=pipeline,
                            verbose=False)
        g.fit(_blobs() if X is None else X, sample_weight=sample_weight)
        out.append(g)
    return out


def _assert_trajectory_equal(a, b):
    assert a.n_iter_ == b.n_iter_
    assert a.lower_bound_ == b.lower_bound_
    np.testing.assert_array_equal(a.means_, b.means_)
    np.testing.assert_array_equal(np.asarray(a.covariances_),
                                  np.asarray(b.covariances_))
    np.testing.assert_array_equal(a.weights_, b.weights_)


@pytest.mark.parametrize("ct", ["diag", "spherical", "tied", "full"])
@pytest.mark.parametrize("host_loop", [True, False])
def test_pipeline_parity_all_covariance_types(ct, host_loop, mesh1):
    g1, g0 = _fit_pair(ct, mesh1, host_loop=host_loop)
    assert g1.estep_path_ == "pipelined" and g0.estep_path_ == "serial"
    _assert_trajectory_equal(g1, g0)


@pytest.mark.parametrize("data_shards", [1, 2, 4, 8])
def test_pipeline_parity_data_meshes(data_shards):
    """1/2/4/8-way data-parallel virtual meshes: pipelined == serial on
    every mesh width (chunking per shard differs with the width, so the
    schedules must agree at each)."""
    n_dev = len(jax.devices())
    if n_dev < data_shards:
        pytest.skip(f"needs {data_shards} devices")
    mesh = make_mesh(data=data_shards, model=1,
                     devices=jax.devices()[:data_shards])
    X = _blobs(n=2048)
    g1, g0 = _fit_pair("diag", mesh, X=X, chunk=128)
    _assert_trajectory_equal(g1, g0)


@pytest.mark.parametrize("ct", ["diag", "tied", "full"])
def test_pipeline_parity_model_sharded(ct, mesh4x2):
    """Component (TP) sharding: the pipelined stage B carries the
    per-chunk pmax/psum normalizer reconstruction — parity must hold
    with the collectives inside the skewed schedule."""
    g1, g0 = _fit_pair(ct, mesh4x2, model_shards=2, k=4, X=_blobs(n=2048))
    _assert_trajectory_equal(g1, g0)


def test_pipeline_parity_component_padding(mesh4x2):
    """k=3 on a 2-way model axis -> k_pad=4 with a -inf log-weight
    padding row riding the carried logp tile; it must stay inert in
    both schedules."""
    g1, g0 = _fit_pair("diag", mesh4x2, model_shards=2, k=3,
                      X=_blobs(n=2048))
    _assert_trajectory_equal(g1, g0)
    assert np.isclose(g1.weights_.sum(), 1.0)


def test_pipeline_parity_zero_weight_padding(mesh1):
    """Zero-weight rows (the padding contract) contribute nothing under
    either schedule — including as the FINAL chunk, which the pipelined
    epilogue drains outside the scan."""
    X = _blobs(n=1536)
    w = np.ones(X.shape[0], np.float64)
    w[-300:] = 0.0                      # zero tail crosses chunk edges
    g1, g0 = _fit_pair("diag", mesh1, X=X, sample_weight=w)
    _assert_trajectory_equal(g1, g0)
    # And the zero rows really were inert: same fit as physically
    # dropping them (fp-order differs across chunk boundaries -> 1e-6).
    g_drop = GaussianMixture(n_components=3, max_iter=6, seed=0,
                             init_params="random", mesh=g0.mesh,
                             chunk_size=256, pipeline=0, verbose=False)
    g_drop.fit(X[:-300])
    np.testing.assert_allclose(g0.means_, g_drop.means_, atol=1e-6)


def test_pipeline_parity_multi_restart_device(mesh1):
    """The batched n_init device sweep threads pipeline through the
    vmapped loop."""
    X = _blobs(n=1024)
    fits = []
    for pipeline in (1, 0):
        g = GaussianMixture(n_components=3, max_iter=5, seed=0, n_init=3,
                            init_params="random", host_loop=False,
                            mesh=mesh1, chunk_size=256,
                            pipeline=pipeline, verbose=False).fit(X)
        fits.append(g)
    g1, g0 = fits
    assert g1.best_restart_ == g0.best_restart_
    np.testing.assert_array_equal(g1.restart_lower_bounds_,
                                  g0.restart_lower_bounds_)
    _assert_trajectory_equal(g1, g0)


def test_pipeline_parity_fit_stream(mesh1):
    X = _blobs(n=1200)

    def blocks():
        for i in range(0, X.shape[0], 400):
            yield X[i:i + 400]

    fits = []
    for pipeline in (1, 0):
        g = GaussianMixture(n_components=3, max_iter=4, seed=0,
                            init_params="random", mesh=mesh1,
                            chunk_size=200, pipeline=pipeline,
                            verbose=False)
        g.fit_stream(blocks, d=X.shape[1], prefetch=0)
        fits.append(g)
    _assert_trajectory_equal(*fits)
    assert fits[0].estep_path_ == "pipelined"


def test_step_level_bit_parity(mesh1):
    """Scan-level: the two schedules' EStats are bit-identical per
    dispatch (not merely trajectory-close)."""
    rng = np.random.default_rng(1)
    n, d, k, chunk = 2048, 8, 4, 256
    x = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    w = jnp.asarray(rng.uniform(0, 2, size=(n,)), jnp.float32)
    shift = jnp.asarray(rng.normal(size=(d,)), jnp.float32)
    means = jnp.asarray(rng.normal(size=(k, d)), jnp.float32)
    inv_var = jnp.asarray(rng.uniform(0.5, 2, size=(k, d)), jnp.float32)
    log_det = -jnp.sum(jnp.log(inv_var), axis=1)
    log_w = jnp.full((k,), -np.log(k), jnp.float32)
    args = (x, w, shift, means, inv_var, log_det, log_w)
    s0 = gmm_step.make_gmm_step_fn(mesh1, chunk_size=chunk,
                                   pipeline=0)(*args)
    s1 = gmm_step.make_gmm_step_fn(mesh1, chunk_size=chunk,
                                   pipeline=1)(*args)
    for name in s0._fields:
        np.testing.assert_array_equal(np.asarray(getattr(s0, name)),
                                      np.asarray(getattr(s1, name)),
                                      err_msg=name)


def test_single_chunk_pipeline(mesh1):
    """One chunk = prologue + empty scan + epilogue; must equal serial."""
    g1, g0 = _fit_pair("diag", mesh1, X=_blobs(n=512), chunk=512,
                      max_iter=4)
    _assert_trajectory_equal(g1, g0)


def test_exp_dtype_rung_runs_and_stays_off_by_default(mesh1):
    """The bf16 responsibility-exp rung is buildable and close to the
    f32 softmax (the 25-sigma decision probe lives in
    experiments/exp_gmm_exp_precision.py); the DEFAULT step builder
    keeps f32 exp (bit-equal to an explicit exp_dtype=None build)."""
    rng = np.random.default_rng(2)
    n, d, k, chunk = 1024, 8, 4, 256
    x = jnp.asarray(rng.normal(size=(n, d)), jnp.float32)
    w = jnp.ones((n,), jnp.float32)
    shift = jnp.zeros((d,), jnp.float32)
    means = jnp.asarray(rng.normal(size=(k, d)), jnp.float32)
    inv_var = jnp.ones((k, d), jnp.float32)
    log_det = jnp.zeros((k,), jnp.float32)
    log_w = jnp.full((k,), -np.log(k), jnp.float32)
    args = (x, w, shift, means, inv_var, log_det, log_w)
    default = gmm_step.make_gmm_step_fn(mesh1, chunk_size=chunk)(*args)
    explicit = gmm_step.make_gmm_step_fn(mesh1, chunk_size=chunk,
                                         exp_dtype=None)(*args)
    np.testing.assert_array_equal(np.asarray(default.resp_sum),
                                  np.asarray(explicit.resp_sum))
    bf16 = gmm_step.make_gmm_step_fn(mesh1, chunk_size=chunk,
                                     exp_dtype=jnp.bfloat16)(*args)
    np.testing.assert_allclose(np.asarray(bf16.resp_sum),
                               np.asarray(default.resp_sum), rtol=2e-2)
    assert np.isfinite(float(bf16.loglik))


def test_pipeline_knob_validation_and_params():
    with pytest.raises(ValueError, match="pipeline"):
        GaussianMixture(n_components=2, pipeline=2)
    with pytest.raises(ValueError, match="pipeline"):
        GaussianMixture(n_components=2, pipeline="yes")
    g = GaussianMixture(n_components=2)
    assert g.pipeline == "auto"
    assert g.get_params()["pipeline"] == "auto"
    g.set_params(pipeline=0)
    assert g.pipeline == 0
    # 'auto' resolves by platform: serial on CPU (the measured 0.80x
    # regression), pipelined on accelerators.
    g.set_params(pipeline="auto")
    expected = 0 if jax.default_backend() == "cpu" else 1
    assert g._resolve_pipeline() == expected


def test_estep_path_attr(mesh1):
    X = _blobs(n=512)
    g = GaussianMixture(n_components=2, max_iter=2, seed=0,
                        init_params="random", mesh=mesh1, chunk_size=256,
                        pipeline=1, verbose=False).fit(X)
    assert g.estep_path_ == "pipelined"
    g = GaussianMixture(n_components=2, max_iter=2, seed=0,
                        init_params="random", mesh=mesh1, chunk_size=256,
                        pipeline=0, verbose=False).fit(X)
    assert g.estep_path_ == "serial"


def test_pipeline_save_load_roundtrip(tmp_path, mesh1):
    X = _blobs(n=512)
    g = GaussianMixture(n_components=2, max_iter=3, seed=0,
                        init_params="random", mesh=mesh1, chunk_size=256,
                        pipeline=0, verbose=False).fit(X)
    p = tmp_path / "gmm.npz"
    g.save(p)
    loaded = GaussianMixture.load(p)
    assert loaded.pipeline == 0
    np.testing.assert_array_equal(loaded.means_, g.means_)
    g_auto = GaussianMixture(n_components=2, max_iter=1, seed=0,
                             init_params="random", mesh=mesh1,
                             chunk_size=256, verbose=False).fit(X)
    g_auto.save(p)
    assert GaussianMixture.load(p).pipeline == "auto"


# ------------------------------------------------ phase-decomposition hooks

def test_measure_phase_ladder_math():
    """The ladder attributes per-rep differences, medians them, and
    clamps noise-negative phases at zero."""
    from kmeans_tpu.utils.profiling import measure_phase_ladder
    feed = {"a": iter([1.0, 1.2, 1.1]), "b": iter([3.0, 3.2, 3.1]),
            "c": iter([3.0, 3.1, 3.0])}      # c-b is negative -> clamp
    rungs = [(name, lambda name=name: next(feed[name]))
             for name in ("a", "b", "c")]
    out = measure_phase_ladder(rungs, reps=3)
    assert [r["phase"] for r in out] == ["a", "b", "c"]
    assert out[0]["seconds"] == pytest.approx(1.1)
    assert out[1]["seconds"] == pytest.approx(2.0)
    assert out[2]["seconds"] == 0.0          # clamped
    assert out[1]["cumulative"] == pytest.approx(3.1)


def test_estep_phase_fn_ladder(mesh4x2):
    """The phase-prefix programs compile and run on a (data, model)
    mesh and return finite scalars for every rung (timing itself is a
    hardware question — experiments/exp_headline_decomposition.py)."""
    from kmeans_tpu.parallel import distributed as dist
    from kmeans_tpu.parallel.sharding import shard_points
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1024, 8)).astype(np.float32)
    pts, w = shard_points(X, mesh4x2, 128)
    cents = jax.device_put(
        dist.pad_centroids(X[:6].copy(), 2),
        dist.centroid_sharding(mesh4x2))
    for phase in dist.ESTEP_PHASES:
        fn = dist.make_estep_phase_fn(mesh4x2, chunk_size=128, n_iters=3,
                                      phase=phase)
        assert np.isfinite(float(fn(pts, w, cents))), phase
    with pytest.raises(ValueError, match="phase"):
        dist.make_estep_phase_fn(mesh4x2, chunk_size=128, n_iters=1,
                                 phase="softmax")
    with pytest.raises(ValueError, match="Pallas"):
        dist.make_estep_phase_fn(mesh4x2, chunk_size=128, n_iters=1,
                                 phase="distance", mode="pallas")


def test_gmm_flops_and_mfu_helpers():
    from kmeans_tpu.benchmarks import gmm_flops_per_iter, step_mfu
    assert gmm_flops_per_iter(1000, 8, 4, "diag") == 8.0 * 1000 * 8 * 4
    full = gmm_flops_per_iter(1000, 8, 4, "full")
    assert full == 4.0 * 1000 * 4 * 64 + 4.0 * 1000 * 8 * 4
    with pytest.raises(ValueError):
        gmm_flops_per_iter(10, 2, 2, "banana")
    # No pinned peak for the CPU backend -> None (flops still derivable).
    if jax.default_backend() == "cpu":
        assert step_mfu(1e9, 1e-3) is None
