"""Process-local (multi-host) data loading.

True multi-process runs need a coordinator; here we test the single-process
equivalence contract, the padded layout math, and the multi-host error
guidance paths.
"""

import numpy as np
import pytest

from kmeans_tpu import KMeans, make_mesh
from conftest import jaxlib_cpu_multiprocess_skip

from kmeans_tpu.parallel.multihost import initialize, is_primary
from kmeans_tpu.parallel.sharding import (from_process_local,
                                          process_local_layout)


def test_layout_math():
    # 3 processes with uneven rows, 2 local shards, chunk 8:
    # max=21 -> ceil(21/2)=11 -> chunk-rounded 16 -> 32 rows/process.
    rows_per_shard, rows_per_proc = process_local_layout([21, 5, 13], 2, 8)
    assert rows_per_shard == 16 and rows_per_proc == 32
    # Degenerate: empty process still gets one chunk per shard.
    assert process_local_layout([0], 4, 8) == (8, 32)


def test_single_process_equivalence(mesh8):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1000, 8)).astype(np.float32)
    ds = from_process_local(X, mesh8, k_hint=5)
    assert ds.n == 1000 and ds.host is not None    # to_device passthrough
    km = KMeans(k=5, seed=0, verbose=False, mesh=mesh8).fit(ds)
    km_ref = KMeans(k=5, seed=0, verbose=False, mesh=mesh8).fit(X)
    np.testing.assert_allclose(km.centroids, km_ref.centroids, atol=1e-5)


def test_requires_mesh():
    with pytest.raises(ValueError, match="requires a mesh"):
        from_process_local(np.zeros((10, 2), np.float32), None)


def test_initialize_noop_single_process():
    initialize()                 # must not raise without a coordinator
    assert is_primary()


class _FakeNonAddressable:
    """Minimal stand-in for a multi-host global array."""

    def __init__(self, real):
        self._real = real
        self.is_fully_addressable = False

    def __getattr__(self, name):
        return getattr(self._real, name)


def _make_nonaddressable_ds(mesh):
    from kmeans_tpu.parallel.sharding import to_device
    rng = np.random.default_rng(1)
    X = rng.normal(size=(256, 4)).astype(np.float32)
    ds = to_device(X, mesh, 32, np.float32)
    ds._host = None
    ds._host_weights = None
    ds.points = _FakeNonAddressable(ds.points)
    ds.local_rows = None        # hand-built global array: layout unknown
    return ds, X


def test_nonaddressable_guards(mesh8):
    ds, X = _make_nonaddressable_ds(mesh8)
    with pytest.raises(ValueError, match="row gather"):
        ds.take([0, 1])
    with pytest.raises(ValueError, match="with_weights"):
        ds.with_weights(np.ones(ds.n, np.float32))
    with pytest.raises(ValueError, match="reshard"):
        ds.reshard(mesh8)
    km = KMeans(k=2, seed=0, verbose=False, mesh=mesh8)
    km.centroids = np.zeros((2, 4), np.float32)
    with pytest.raises(ValueError, match="local rows"):
        km.predict(ds)


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(nproc: int, tmp_path, timeout: int = 420) -> None:
    """Spawn ``nproc`` jax.distributed worker processes (Gloo collectives
    over 2 virtual CPU devices each) and wait for all to exit cleanly."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).parent.parent
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = ":".join(
        p for p in [str(repo), env.get("PYTHONPATH")] if p)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [subprocess.Popen(
        [sys.executable, str(repo / "tests" / "mh_worker.py"),
         str(i), str(nproc), str(port), str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in range(nproc)]
    outs = [p.communicate(timeout=timeout)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]


def _global_blob_data():
    """The deterministic global dataset every worker regenerates."""
    rng = np.random.default_rng(0)
    centers = np.array([[0, 0, 0, 0], [10, 10, 0, 0],
                        [-10, 0, 10, 0], [0, -10, 0, 10]], np.float32)
    X = (centers[rng.integers(0, 4, 3000)]
         + rng.normal(size=(3000, 4)).astype(np.float32))
    init = X[rng.choice(3000, size=4, replace=False)]
    return X, init


@jaxlib_cpu_multiprocess_skip
def test_two_process_fit_matches_single_process(tmp_path):
    """REAL multi-process run: 2 jax.distributed processes (Gloo collectives
    over CPU devices), uneven per-process rows, from_process_local +
    explicit init.  Both processes must agree exactly with each other and
    match a single-process fit of the same data within fp tolerance."""
    _run_workers(2, tmp_path)

    c0 = np.load(tmp_path / "centroids_0.npy")
    c1 = np.load(tmp_path / "centroids_1.npy")
    np.testing.assert_array_equal(c0, c1)     # replicated stats -> identical

    # Single-process reference on the concatenated data, same init.
    X, init = _global_blob_data()
    km = KMeans(k=4, seed=0, init=init, empty_cluster="keep",
                compute_sse=True, verbose=False).fit(X)
    np.testing.assert_allclose(c0, km.centroids, atol=1e-3)
    sse0 = np.load(tmp_path / "sse_0.npy")
    np.testing.assert_allclose(sse0, np.asarray(km.sse_history), rtol=1e-5)

    # Process-local labels (r3 VERDICT #4): each worker's labels_ covers
    # its OWN rows; the process-order concatenation equals the
    # single-process labels_ of the concatenated data.
    lab = np.concatenate([np.load(tmp_path / "labels_0.npy"),
                          np.load(tmp_path / "labels_1.npy")])
    np.testing.assert_array_equal(lab, km.labels_)

    # TP (model=2, model axis spanning the two processes) must agree too.
    tp0 = np.load(tmp_path / "centroids_tp_0.npy")
    tp1 = np.load(tmp_path / "centroids_tp_1.npy")
    np.testing.assert_array_equal(tp0, tp1)
    np.testing.assert_allclose(tp0, km.centroids, atol=1e-3)
    np.testing.assert_allclose(np.load(tmp_path / "sse_tp_0.npy"),
                               np.asarray(km.sse_history), rtol=1e-5)

    # save() was called by BOTH processes; the gating means exactly one
    # writer — the checkpoint must exist and load cleanly.
    loaded = KMeans.load(tmp_path / "mh_ckpt")
    np.testing.assert_allclose(loaded.centroids, c0)

    # 'resample' with forced empties on the process-local dataset: the
    # on-device draw is replicated, so both processes agree exactly.
    rs0 = np.load(tmp_path / "centroids_rs_0.npy")
    rs1 = np.load(tmp_path / "centroids_rs_1.npy")
    np.testing.assert_array_equal(rs0, rs1)
    assert np.all(np.isfinite(rs0))

    # GMM EM across the process boundary (r3): replicated results agree
    # bit-for-bit between processes and match a single-process fit.
    g0 = np.load(tmp_path / "gmm_means_0.npy")
    g1 = np.load(tmp_path / "gmm_means_1.npy")
    np.testing.assert_array_equal(g0, g1)
    from kmeans_tpu import GaussianMixture
    gm_ref = GaussianMixture(n_components=4,
                             means_init=init.astype(np.float64),
                             max_iter=5, tol=0.0, seed=0).fit(X)
    np.testing.assert_allclose(g0, gm_ref.means_, atol=1e-3)
    np.testing.assert_allclose(
        float(np.load(tmp_path / "gmm_ll_0.npy")[0]),
        gm_ref.lower_bound_, rtol=1e-4)

    _assert_r5_matrix(tmp_path, 2, X, init)
    _assert_fleet_obs(tmp_path, 2)


def _assert_fleet_obs(tmp_path, nproc: int) -> None:
    """ISSUE 13 coverage shared by the 2- and 4-process runs: the
    workers' per-process telemetry merges into one barrier-aligned
    timeline with every host present and the measured skew bound under
    the committed threshold; the healthy SPMD fleet's heartbeats stay
    straggler-silent; the injected-slow-host independent fleet flags
    exactly process 1.  (The workers already asserted obs=0 parity
    bit-exact and per-process sink paths internally.)"""
    from kmeans_tpu.obs import fleet

    traces = sorted(tmp_path.glob("fleet_trace.p*.jsonl"))
    assert len(traces) == nproc, traces
    merged = fleet.merge_traces(traces)
    assert [h["process_index"] for h in merged["hosts"]] \
        == list(range(nproc))
    assert merged["align"] == "barrier"
    assert merged["barriers"] == 2          # two instrumented fits
    assert merged["skew_bound_s"] is not None
    assert merged["skew_bound_s"] <= fleet.FLEET_SKEW_BOUND_S, merged
    # Every host's spans landed on the merged timeline.
    present = {r["process_index"] for r in merged["records"]}
    assert present == set(range(nproc)), present

    hb = fleet.merge_heartbeats(sorted(tmp_path.glob(
        "fleet_hb.p*.jsonl")))
    healthy = fleet.straggler_report(hb)
    assert healthy["healthy"], healthy

    slow = fleet.straggler_report(fleet.merge_heartbeats(sorted(
        tmp_path.glob("straggler_hb.p*.jsonl"))))
    assert 1 in slow["flagged"], slow
    assert 0 not in slow["flagged"], slow


def _assert_r5_matrix(tmp_path, nproc: int, X, init) -> None:
    """r4 VERDICT #7 coverage shared by the 2- and 4-process runs:
    fit_stream, MiniBatch device sampling, and full-covariance GMM must
    agree EXACTLY across processes and match single-process references."""
    from kmeans_tpu import GaussianMixture

    # fit_stream: bit-identical across processes; fp-close to a
    # single-process streamed fit of the same weighted blocks.
    st = [np.load(tmp_path / f"centroids_stream_{i}.npy")
          for i in range(nproc)]
    for c in st[1:]:
        np.testing.assert_array_equal(st[0], c)
    wts = (1.0 + (np.arange(3000) % 3)).astype(np.float32)

    def blocks():
        for i in range(0, 3000, 1000):
            yield X[i:i + 1000], wts[i:i + 1000]

    km_st = KMeans(k=4, seed=0, init=init, empty_cluster="keep",
                   compute_sse=True, max_iter=8, verbose=False)
    km_st.fit_stream(blocks)
    np.testing.assert_allclose(st[0], km_st.centroids, atol=1e-3)
    sse0 = np.load(tmp_path / "sse_stream_0.npy")
    np.testing.assert_allclose(sse0, np.asarray(km_st.sse_history),
                               rtol=1e-4)

    # MiniBatch (device sampling): replicated seeded draws -> exact
    # cross-process agreement.
    mb = [np.load(tmp_path / f"centroids_mb_{i}.npy")
          for i in range(nproc)]
    for c in mb[1:]:
        np.testing.assert_array_equal(mb[0], c)
    assert np.all(np.isfinite(mb[0]))

    # Full-covariance GMM: exact cross-process agreement; fp-close to a
    # single-process fit.
    means = [np.load(tmp_path / f"gmm_full_means_{i}.npy")
             for i in range(nproc)]
    covs = [np.load(tmp_path / f"gmm_full_covs_{i}.npy")
            for i in range(nproc)]
    for m, c in zip(means[1:], covs[1:]):
        np.testing.assert_array_equal(means[0], m)
        np.testing.assert_array_equal(covs[0], c)
    gm_ref = GaussianMixture(n_components=4, covariance_type="full",
                             means_init=init.astype(np.float64),
                             max_iter=5, tol=0.0, seed=0).fit(X)
    np.testing.assert_allclose(means[0], gm_ref.means_, atol=1e-3)
    np.testing.assert_allclose(covs[0], gm_ref.covariances_, atol=1e-3)


@jaxlib_cpu_multiprocess_skip
def test_four_process_fit_matches_single_process(tmp_path):
    """4 jax.distributed processes (8 virtual CPU devices total), uneven
    splits: the whole r5 matrix — flat fit, fit_stream, MiniBatch device
    sampling, full-covariance GMM, checkpoint — agrees exactly across all
    four processes (r4 VERDICT #7 asked the matrix to grow beyond 2)."""
    _run_workers(4, tmp_path, timeout=600)

    X, init = _global_blob_data()
    cents = [np.load(tmp_path / f"centroids_{i}.npy") for i in range(4)]
    for c in cents[1:]:
        np.testing.assert_array_equal(cents[0], c)
    km = KMeans(k=4, seed=0, init=init, empty_cluster="keep",
                compute_sse=True, verbose=False).fit(X)
    np.testing.assert_allclose(cents[0], km.centroids, atol=1e-3)

    lab = np.concatenate([np.load(tmp_path / f"labels_{i}.npy")
                          for i in range(4)])
    np.testing.assert_array_equal(lab, km.labels_)

    loaded = KMeans.load(tmp_path / "mh_ckpt")
    np.testing.assert_allclose(loaded.centroids, cents[0])

    _assert_r5_matrix(tmp_path, 4, X, init)
    _assert_fleet_obs(tmp_path, 4)


# (r1's up-front 'resample' rejection for process-local datasets is gone:
# the on-device Gumbel sampler serves it now.  Real coverage lives in the
# 2-process worker above — centroids_rs_*.npy — and in
# test_empty_clusters.py's host-less dataset tests; the _FakeNonAddressable
# mock cannot survive an actual dispatch.)


def test_positive_rows_guard_on_nonaddressable(mesh8):
    """ADVICE r1: positive_rows() must enforce addressability itself —
    global arange(n) indices don't map onto the interleaved process-local
    padded layout."""
    ds, _ = _make_nonaddressable_ds(mesh8)
    with pytest.raises(ValueError, match="positive_rows"):
        ds.positive_rows()


def test_initialize_reraises_valueerror_in_cluster_env(monkeypatch):
    """ADVICE r1: auto-detection failure (ValueError) inside a cluster job
    must raise, not silently downgrade every host to single-process."""
    import jax

    def boom(coordinator_address=None, num_processes=None, process_id=None):
        raise ValueError("could not auto-detect coordinator")

    # raising=False: pre-0.6 JAX has no is_initialized to replace — the
    # shim in multihost.initialize picks up the injected one either way.
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: False,
                        raising=False)
    monkeypatch.setattr(jax.distributed, "initialize", boom)
    monkeypatch.setenv("SLURM_JOB_ID", "12345")
    with pytest.raises(ValueError, match="auto-detect"):
        initialize()
    monkeypatch.delenv("SLURM_JOB_ID")
    for v in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
              "MEGASCALE_COORDINATOR_ADDRESS", "OMPI_COMM_WORLD_SIZE",
              "CLOUD_TPU_TASK_ID", "TPU_WORKER_ID"):
        monkeypatch.delenv(v, raising=False)
    initialize()                 # plain single-process: swallowed
