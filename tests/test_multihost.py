"""Process-local (multi-host) data loading.

True multi-process runs need a coordinator; here we test the single-process
equivalence contract, the padded layout math, and the multi-host error
guidance paths.
"""

import numpy as np
import pytest

from kmeans_tpu import KMeans, make_mesh
from kmeans_tpu.parallel.multihost import initialize, is_primary
from kmeans_tpu.parallel.sharding import (from_process_local,
                                          process_local_layout)


def test_layout_math():
    # 3 processes with uneven rows, 2 local shards, chunk 8:
    # max=21 -> ceil(21/2)=11 -> chunk-rounded 16 -> 32 rows/process.
    rows_per_shard, rows_per_proc = process_local_layout([21, 5, 13], 2, 8)
    assert rows_per_shard == 16 and rows_per_proc == 32
    # Degenerate: empty process still gets one chunk per shard.
    assert process_local_layout([0], 4, 8) == (8, 32)


def test_single_process_equivalence(mesh8):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1000, 8)).astype(np.float32)
    ds = from_process_local(X, mesh8, k_hint=5)
    assert ds.n == 1000 and ds.host is not None    # to_device passthrough
    km = KMeans(k=5, seed=0, verbose=False, mesh=mesh8).fit(ds)
    km_ref = KMeans(k=5, seed=0, verbose=False, mesh=mesh8).fit(X)
    np.testing.assert_allclose(km.centroids, km_ref.centroids, atol=1e-5)


def test_requires_mesh():
    with pytest.raises(ValueError, match="requires a mesh"):
        from_process_local(np.zeros((10, 2), np.float32), None)


def test_initialize_noop_single_process():
    initialize()                 # must not raise without a coordinator
    assert is_primary()


class _FakeNonAddressable:
    """Minimal stand-in for a multi-host global array."""

    def __init__(self, real):
        self._real = real
        self.is_fully_addressable = False

    def __getattr__(self, name):
        return getattr(self._real, name)


def _make_nonaddressable_ds(mesh):
    from kmeans_tpu.parallel.sharding import to_device
    rng = np.random.default_rng(1)
    X = rng.normal(size=(256, 4)).astype(np.float32)
    ds = to_device(X, mesh, 32, np.float32)
    ds._host = None
    ds._host_weights = None
    ds.points = _FakeNonAddressable(ds.points)
    return ds, X


def test_nonaddressable_guards(mesh8):
    ds, X = _make_nonaddressable_ds(mesh8)
    with pytest.raises(ValueError, match="row gather"):
        ds.take([0, 1])
    with pytest.raises(ValueError, match="with_weights"):
        ds.with_weights(np.ones(ds.n, np.float32))
    with pytest.raises(ValueError, match="reshard"):
        ds.reshard(mesh8)
    km = KMeans(k=2, seed=0, verbose=False, mesh=mesh8)
    km.centroids = np.zeros((2, 4), np.float32)
    with pytest.raises(ValueError, match="local rows"):
        km.predict(ds)
