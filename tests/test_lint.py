"""ISSUE 10: the invariant linter and the recompilation sentinel.

Three tiers of coverage:

1. **Fixture pairs per rule** — a minimal snippet every rule fires on
   and a corrected twin it stays silent on, so each rule is proven by
   construction (the issue contract: "every rule proven by a firing
   fixture test").
2. **Package-wide self-test** — the linter runs over the real shipped
   tree and must be clean (exit-0 contract of
   ``python -m kmeans_tpu lint kmeans_tpu/``), with every suppression
   explicit (reason-bearing) and counted.
3. **Recompilation sentinel** — the runtime twin: unit semantics
   (growth raises, naming the cache and key) plus the tier-1 guard
   that repeat same-shape predict/serve calls across the five model
   families add ZERO compile-cache entries (the r11 pinned property,
   generalized into a reusable context manager).
"""

import json
from pathlib import Path

import numpy as np
import pytest

import kmeans_tpu
from kmeans_tpu.analysis import RULES, lint_paths
from kmeans_tpu.analysis.cli import main as lint_main

PKG_DIR = Path(kmeans_tpu.__file__).parent


def run_on(tmp_path, source, subdir="parallel", name="mod.py",
           rules=None):
    """Lint one snippet placed under ``tmp_path/<subdir>/`` (the
    path-scoped rules key on ``parallel``/``ops``/``serving`` path
    segments) and return the findings list."""
    d = tmp_path / subdir
    d.mkdir(parents=True, exist_ok=True)
    f = d / name
    f.write_text(source)
    return lint_paths([f], rules=rules).findings


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# Rule registry surface
# ---------------------------------------------------------------------------

def test_registry_has_the_required_rules():
    """The six incident-class rules (plus the suppression-format
    meta-rule) are registered — the >= 6 acceptance bar."""
    assert {"trace-hazard", "cache-key", "dispatch", "thread",
            "counter-reset", "dead-private", "cache-name",
            "aot-key", "large-k", "fleet-record",
            "ingest-span", "fault-path", "atomic-swap"} <= set(RULES)
    assert len(RULES) >= 6
    for rule in RULES.values():
        assert rule.id and rule.incident, rule


# ---------------------------------------------------------------------------
# trace-hazard
# ---------------------------------------------------------------------------

_TRACE_BAD = """
from jax import lax


def make_step():
    def body(carry, chunk):
        v = float(carry)              # host cast of a tracer
        if chunk > 0:                 # Python branch on traced arg
            v = v + 1
        import numpy as np
        a = np.asarray(carry)         # host materialization
        b = carry.item()              # host sync
        while v > 0:                  # Python loop in traced body
            v -= 1
        return carry, v
    return lax.scan(body, 0.0, None)
"""

_TRACE_OK = """
import jax.numpy as jnp
from jax import lax


def make_step(mode):
    def body(carry, chunk):
        if mode == "fast":            # branch on a STATIC closure knob
            step = carry + 2.0
        else:
            step = carry + 1.0
        n = int(chunk.shape[0])       # shape cast: static at trace time
        return step, jnp.where(chunk > 0, step, carry)
    return lax.scan(body, 0.0, None)
"""


def test_trace_hazard_fires(tmp_path):
    findings = [f for f in run_on(tmp_path, _TRACE_BAD)
                if f.rule == "trace-hazard"]
    messages = " | ".join(f.message for f in findings)
    assert "float()" in messages
    assert "branch on traced parameter" in messages
    assert "np.asarray" in messages
    assert ".item()" in messages
    assert "while-loop" in messages
    assert len(findings) == 5


def test_trace_hazard_silent_on_static_branches_and_shape_casts(tmp_path):
    findings = run_on(tmp_path, _TRACE_OK)
    assert [f for f in findings if f.rule == "trace-hazard"] == []


def test_trace_hazard_scoped_to_compiled_layers(tmp_path):
    """The same hazard OUTSIDE parallel//ops/ is not this rule's
    business (models' host loops legitimately cast device scalars)."""
    findings = run_on(tmp_path, _TRACE_BAD, subdir="models")
    assert [f for f in findings if f.rule == "trace-hazard"] == []


def test_trace_hazard_sibling_scope_does_not_leak_params(tmp_path):
    """A nested def's params are traced only for ITS OWN subtree — a
    later branch on a same-named STATIC closure variable at the outer
    level must stay silent (review finding on this PR)."""
    src = """
from jax import lax


def make(c):                          # static builder knob named 'c'
    def body(carry, chunk):
        def inner(c, x):              # nested traced fn, param 'c'
            return c + x
        out = inner(carry, chunk)
        if c == "fast":               # outer 'c' is the STATIC knob
            out = out * 2
        return out, out
    return lax.scan(body, 0.0, None)
"""
    findings = run_on(tmp_path, src)
    assert [f for f in findings if f.rule == "trace-hazard"] == []


def test_trace_hazard_while_loop_body_and_lambda(tmp_path):
    src = """
from jax import lax


def run(x0):
    def cond(state):
        return bool(state[0])         # host cast in while cond

    def body(state):
        return (state[0] - 1, state[1])

    return lax.while_loop(cond, body, x0)


def run2(x0):
    return lax.fori_loop(0, 3, lambda i, c: c + float(c), x0)
"""
    findings = [f for f in run_on(tmp_path, src)
                if f.rule == "trace-hazard"]
    assert len(findings) == 2         # bool() in cond, float() in lambda


# ---------------------------------------------------------------------------
# cache-key
# ---------------------------------------------------------------------------

_CACHEKEY_BAD = """
from kmeans_tpu.utils.cache import LRUCache

_STEP_CACHE = LRUCache(8)


def get_fn(mesh, chunk, mode, build):
    return _STEP_CACHE.get_or_create(
        (mesh, chunk),
        lambda: build(mesh, chunk_size=chunk, mode=mode))
"""

_CACHEKEY_OK = """
from kmeans_tpu.utils.cache import LRUCache

_STEP_CACHE = LRUCache(8)


def get_fn(mesh, chunk, mode, build):
    return _STEP_CACHE.get_or_create(
        (mesh, chunk, mode, build, "salt"),
        lambda: build(mesh, chunk_size=chunk, mode=mode))
"""


def test_cache_key_fires_on_missing_knob(tmp_path):
    findings = [f for f in run_on(tmp_path, _CACHEKEY_BAD,
                                  subdir="models")
                if f.rule == "cache-key"]
    assert len(findings) == 1
    assert "mode" in findings[0].message
    assert "build" in findings[0].message


def test_cache_key_silent_when_key_spans_knobs(tmp_path):
    findings = run_on(tmp_path, _CACHEKEY_OK, subdir="models")
    assert [f for f in findings if f.rule == "cache-key"] == []


def test_cache_key_resolves_key_variable_and_attr_prefix(tmp_path):
    """A ``key = (...)`` variable is chased to its tuple; keying on
    ``self.mesh`` covers deeper reads like ``self.mesh.devices``."""
    src = """
from kmeans_tpu.utils.cache import LRUCache

_C_CACHE = LRUCache(8)


class M:
    def fn(self, chunk, build):
        key = (self.mesh, chunk, build, "predict")
        return _C_CACHE.get_or_create(
            key, lambda: build(self.mesh.devices, chunk))
"""
    findings = run_on(tmp_path, src, subdir="models")
    assert [f for f in findings if f.rule == "cache-key"] == []


def test_cache_key_flags_unresolvable_key(tmp_path):
    src = """
from kmeans_tpu.utils.cache import LRUCache

_C_CACHE = LRUCache(8)


def fn(key, build):
    return _C_CACHE.get_or_create(key, lambda: build())
"""
    findings = [f for f in run_on(tmp_path, src, subdir="models")
                if f.rule == "cache-key"]
    assert len(findings) == 1
    assert "not a tuple literal" in findings[0].message


def test_cache_key_ignores_function_local_imports(tmp_path):
    """An ``import ... as dist`` inside the function is a static module
    reference, never a knob (the minibatch.py false-positive class)."""
    src = """
from kmeans_tpu.utils.cache import LRUCache

_C_CACHE = LRUCache(8)


def fn(mesh, chunk):
    from kmeans_tpu.parallel import distributed as dist
    return _C_CACHE.get_or_create(
        (mesh, chunk), lambda: dist.make_step_fn(mesh, chunk_size=chunk))
"""
    findings = run_on(tmp_path, src, subdir="models")
    assert [f for f in findings if f.rule == "cache-key"] == []


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

_DISPATCH_BAD = """
from kmeans_tpu.utils.cache import LRUCache

_STEP_CACHE = LRUCache(8)


def serve(pts, mesh, chunk, build):
    fn = _STEP_CACHE.get_or_create((mesh, chunk), lambda: build(mesh))
    return fn(pts)
"""

_DISPATCH_OK = """
from kmeans_tpu.utils.cache import LRUCache
from kmeans_tpu.utils.profiling import note_dispatch

_STEP_CACHE = LRUCache(8)


def serve(pts, mesh, chunk, build):
    fn = _STEP_CACHE.get_or_create((mesh, chunk), lambda: build(mesh))
    note_dispatch("serve/predict")
    return fn(pts)
"""


def test_dispatch_fires_on_untagged_compiled_call(tmp_path):
    findings = [f for f in run_on(tmp_path, _DISPATCH_BAD,
                                  subdir="serving")
                if f.rule == "dispatch"]
    assert len(findings) == 1
    assert "serve()" in findings[0].message


def test_dispatch_silent_when_tagged(tmp_path):
    findings = run_on(tmp_path, _DISPATCH_OK, subdir="serving")
    assert [f for f in findings if f.rule == "dispatch"] == []


def test_dispatch_builders_that_only_return_are_exempt(tmp_path):
    """A function that builds-and-returns the compiled fn (no invoke)
    is accounted at its call sites, not at the build site."""
    src = """
from kmeans_tpu.utils.cache import LRUCache

_STEP_CACHE = LRUCache(8)


def get_fn(mesh, chunk, build):
    return _STEP_CACHE.get_or_create((mesh, chunk), lambda: build(mesh))
"""
    findings = run_on(tmp_path, src, subdir="serving")
    assert [f for f in findings if f.rule == "dispatch"] == []


# ---------------------------------------------------------------------------
# obs-span (ISSUE 11)
# ---------------------------------------------------------------------------

_OBS_SPAN_BAD = """
from kmeans_tpu.utils.cache import LRUCache
from kmeans_tpu.utils.profiling import note_dispatch

_STEP_CACHE = LRUCache(8)


def serve(pts, mesh, chunk, build):
    fn = _STEP_CACHE.get_or_create((mesh, chunk), lambda: build(mesh))
    note_dispatch("serve/predict")
    return fn(pts)
"""

_OBS_SPAN_OK = """
from kmeans_tpu.obs import trace as obs_trace
from kmeans_tpu.utils.cache import LRUCache
from kmeans_tpu.utils.profiling import note_dispatch

_STEP_CACHE = LRUCache(8)


def serve(pts, mesh, chunk, build):
    fn = _STEP_CACHE.get_or_create((mesh, chunk), lambda: build(mesh))
    note_dispatch("serve/predict")
    with obs_trace.span("serve.request"):
        return fn(pts)
"""


def test_obs_span_fires_on_unspanned_compiled_call(tmp_path):
    """Dispatch-tagged but span-less: the dispatch rule passes, the
    obs-span twin fires — the two rules close different halves of the
    same invisibility class."""
    findings = run_on(tmp_path, _OBS_SPAN_BAD, subdir="serving")
    assert [f.rule for f in findings
            if f.rule in ("dispatch", "obs-span")] == ["obs-span"]
    fire = [f for f in findings if f.rule == "obs-span"][0]
    assert "serve()" in fire.message and "span" in fire.message


def test_obs_span_silent_with_enclosing_span(tmp_path):
    findings = run_on(tmp_path, _OBS_SPAN_OK, subdir="serving")
    assert [f for f in findings if f.rule == "obs-span"] == []


def test_obs_span_builders_that_only_return_are_exempt(tmp_path):
    src = """
from kmeans_tpu.utils.cache import LRUCache

_STEP_CACHE = LRUCache(8)


def get_fn(mesh, chunk, build):
    return _STEP_CACHE.get_or_create((mesh, chunk), lambda: build(mesh))
"""
    findings = run_on(tmp_path, src, subdir="serving")
    assert [f for f in findings if f.rule == "obs-span"] == []


def test_obs_span_scoped_to_serving_and_parallel(tmp_path):
    """A models/-layer compiled call is out of the rule's scope (model
    dispatch sites are spanned at their engine choke points, not
    per-site)."""
    findings = run_on(tmp_path, _OBS_SPAN_BAD, subdir="models")
    assert [f for f in findings if f.rule == "obs-span"] == []


def test_obs_span_nested_closure_covered_by_driver_span(tmp_path):
    """A nested helper's compiled call counts against the DRIVER
    function, whose span covers the whole subtree (the
    verify_quantized/_distances shape)."""
    src = """
from kmeans_tpu.obs import trace as obs_trace
from kmeans_tpu.utils.cache import LRUCache
from kmeans_tpu.utils.profiling import note_dispatch

_STEP_CACHE = LRUCache(8)


def verify(pts, mesh, chunk, build):
    def _inner(m):
        fn = _STEP_CACHE.get_or_create((mesh, chunk, m),
                                       lambda: build(mesh, m))
        note_dispatch("verify/probe")
        return fn(pts)
    with obs_trace.span("dispatch", tag="verify"):
        return _inner("a") - _inner("b")
"""
    findings = run_on(tmp_path, src, subdir="serving")
    assert [f for f in findings if f.rule == "obs-span"] == []


def test_obs_span_suppression_honored(tmp_path):
    src = _OBS_SPAN_BAD.replace(
        "    return fn(pts)",
        "    # lint: ok(obs-span) — probe path, timeline coverage "
        "at the caller\n    return fn(pts)")
    findings = run_on(tmp_path, src, subdir="serving")
    assert [f for f in findings if f.rule == "obs-span"] == []


# ---------------------------------------------------------------------------
# ingest-span (ISSUE 18)
# ---------------------------------------------------------------------------

_INGEST_BAD = """
import jax
import numpy as np


def place_shards(x, sharding, n_pad, d):
    parts = [jax.device_put(x[lo:hi], dev)
             for lo, hi, dev in sharding]
    return jax.make_array_from_single_device_arrays(
        (n_pad, d), sharding, parts)
"""

_INGEST_OK = """
import jax
import numpy as np
from kmeans_tpu.obs import trace as obs_trace


def place_shards(x, sharding, n_pad, d):
    with obs_trace.span("stage", rows=int(n_pad),
                        bytes=int(x.nbytes)):
        parts = [jax.device_put(x[lo:hi], dev)
                 for lo, hi, dev in sharding]
        return jax.make_array_from_single_device_arrays(
            (n_pad, d), sharding, parts)
"""


def test_ingest_span_fires_on_unspanned_placement(tmp_path):
    findings = run_on(tmp_path, _INGEST_BAD, subdir="data")
    fire = [f for f in findings if f.rule == "ingest-span"]
    assert len(fire) == 1
    assert "place_shards()" in fire[0].message
    assert "stage" in fire[0].message


def test_ingest_span_silent_under_stage_span(tmp_path):
    findings = run_on(tmp_path, _INGEST_OK, subdir="data")
    assert [f for f in findings if f.rule == "ingest-span"] == []


def test_ingest_span_scoped_to_data_and_sharding(tmp_path):
    """A placement in models/ is out of scope (model-layer uploads run
    through to_device, which is already spanned at the choke point) —
    but the same snippet under parallel/sharding.py is in scope."""
    findings = run_on(tmp_path, _INGEST_BAD, subdir="models")
    assert [f for f in findings if f.rule == "ingest-span"] == []
    findings = run_on(tmp_path, _INGEST_BAD, subdir="parallel",
                      name="sharding.py")
    assert [f.rule for f in findings
            if f.rule == "ingest-span"] == ["ingest-span"]


def test_ingest_span_nested_producer_covered_by_driver(tmp_path):
    """A streamed producer closure's device_put counts against the
    enclosing driver, whose stage span covers the subtree (the
    _streamed_place shape)."""
    src = """
import jax
from kmeans_tpu.obs import trace as obs_trace


def stream_place(read_rows, plan, sharding):
    def producer(slab):
        return [jax.device_put(read_rows(lo, hi), dev)
                for lo, hi, dev in slab]
    with obs_trace.span("stage", rows=plan["n"], bytes=plan["bytes"]):
        return [producer(s) for s in plan["slabs"]]
"""
    findings = run_on(tmp_path, src, subdir="data")
    assert [f for f in findings if f.rule == "ingest-span"] == []


def test_ingest_span_suppression_honored(tmp_path):
    src = _INGEST_BAD.replace(
        "    return jax.make_array_from_single_device_arrays(",
        "    # lint: ok(ingest-span) — fixture path, spanned at the "
        "caller\n    return jax.make_array_from_single_device_arrays(")
    findings = run_on(tmp_path, src, subdir="data")
    assert [f for f in findings if f.rule == "ingest-span"] == []


# ---------------------------------------------------------------------------
# collective-span (ISSUE 13)
# ---------------------------------------------------------------------------

_COLLECTIVE_BAD = """
import numpy as np
from jax.experimental import multihost_utils


def gather_counts(n_local):
    return multihost_utils.process_allgather(
        np.asarray([n_local], dtype=np.int64))
"""


def test_collective_span_fires_on_uncovered_allgather(tmp_path):
    findings = run_on(tmp_path, _COLLECTIVE_BAD, subdir="parallel")
    fires = [f for f in findings if f.rule == "collective-span"]
    assert len(fires) == 1
    assert "gather_counts()" in fires[0].message
    assert "merged timeline" in fires[0].message


def test_collective_span_silent_with_span_or_tag(tmp_path):
    spanned = """
import numpy as np
from jax.experimental import multihost_utils

from kmeans_tpu.obs import trace as obs_trace


def gather_counts(n_local):
    with obs_trace.span("collective", op="process_allgather"):
        return multihost_utils.process_allgather(
            np.asarray([n_local], dtype=np.int64))
"""
    findings = run_on(tmp_path, spanned, subdir="parallel")
    assert [f for f in findings if f.rule == "collective-span"] == []
    tagged = """
import numpy as np
from jax.experimental import multihost_utils

from kmeans_tpu.utils.profiling import note_dispatch


def sync(tag):
    note_dispatch("fleet/barrier")
    multihost_utils.sync_global_devices(tag)
"""
    findings = run_on(tmp_path, tagged, subdir="parallel")
    assert [f for f in findings if f.rule == "collective-span"] == []


def test_collective_span_scoped_to_parallel(tmp_path):
    """The same uncovered collective outside parallel/ (e.g. the
    checkpoint barrier in utils/) is out of this rule's scope."""
    findings = run_on(tmp_path, _COLLECTIVE_BAD, subdir="utils")
    assert [f for f in findings if f.rule == "collective-span"] == []


def test_collective_span_suppression_honored(tmp_path):
    src = _COLLECTIVE_BAD.replace(
        "    return multihost_utils.process_allgather(",
        "    # lint: ok(collective-span) — covered by the caller's "
        "span\n    return multihost_utils.process_allgather(")
    findings = run_on(tmp_path, src, subdir="parallel")
    assert [f for f in findings if f.rule == "collective-span"] == []


# ---------------------------------------------------------------------------
# quality-counter (ISSUE 14)
# ---------------------------------------------------------------------------

_QUALITY_BAD = """
class Engine:
    def _record(self, rm, bucket, m):
        self.dispatches += 1

    def _dispatch(self, rm, rows):
        out = rows
        self._record(rm, 8, len(rows))
        return out
"""

_QUALITY_OK = """
class Engine:
    def _record(self, rm, bucket, m):
        self.dispatches += 1

    def _dispatch(self, rm, rows):
        out = rows
        self._record(rm, 8, len(rows))
        self._observe_quality(rm, 8, None, rows=len(rows), labels=out)
        return out
"""


def test_quality_counter_fires_on_unfed_record_path(tmp_path):
    findings = run_on(tmp_path, _QUALITY_BAD, subdir="serving")
    fires = [f for f in findings if f.rule == "quality-counter"]
    assert len(fires) == 1
    assert "_dispatch()" in fires[0].message
    assert "quality monitor" in fires[0].message


def test_quality_counter_silent_when_monitor_fed(tmp_path):
    findings = run_on(tmp_path, _QUALITY_OK, subdir="serving")
    assert [f for f in findings if f.rule == "quality-counter"] == []


def test_quality_counter_fires_on_packed_counter_increment(tmp_path):
    src = """
class Engine:
    def _dispatch_packed(self, items):
        self.packed_dispatches += 1
        return items
"""
    findings = run_on(tmp_path, src, subdir="serving")
    assert [f.rule for f in findings
            if f.rule == "quality-counter"] == ["quality-counter"]
    # The = 0 declarations in __init__ are setup, not traffic.
    init_only = """
class Engine:
    def __init__(self):
        self.packed_dispatches = 0
"""
    findings = run_on(tmp_path, init_only, subdir="serving")
    assert [f for f in findings if f.rule == "quality-counter"] == []


def test_quality_counter_scoped_to_serving(tmp_path):
    findings = run_on(tmp_path, _QUALITY_BAD, subdir="parallel")
    assert [f for f in findings if f.rule == "quality-counter"] == []


def test_quality_counter_suppression_honored(tmp_path):
    src = _QUALITY_BAD.replace(
        "        self._record(rm, 8, len(rows))",
        "        # lint: ok(quality-counter) — probe path, monitor fed "
        "by the caller\n        self._record(rm, 8, len(rows))")
    findings = run_on(tmp_path, src, subdir="serving")
    assert [f for f in findings if f.rule == "quality-counter"] == []


# ---------------------------------------------------------------------------
# fleet-record (ISSUE 17)
# ---------------------------------------------------------------------------

_FLEET_BAD = """
class Fleet:
    def forward(self, rep, model_id, rows):
        return rep.engine.call(model_id, rows)

    def admit(self, model_id):
        raise FleetOverloadError(model_id)
"""

_FLEET_OK = """
class Fleet:
    def forward(self, rep, model_id, rows):
        self._record_route(rep.name, model_id)
        return rep.engine.call(model_id, rows)

    def admit(self, model_id):
        self._record_shed(model_id)
        raise FleetOverloadError(model_id)
"""


def test_fleet_record_fires_on_unrecorded_forward_and_shed(tmp_path):
    findings = run_on(tmp_path, _FLEET_BAD, subdir="serving")
    fires = [f for f in findings if f.rule == "fleet-record"]
    assert len(fires) == 2
    assert "forward()" in fires[0].message
    assert "admit()" in fires[1].message
    assert "fleet.route/fleet.shed" in fires[0].message


def test_fleet_record_silent_when_recorded(tmp_path):
    findings = run_on(tmp_path, _FLEET_OK, subdir="serving")
    assert [f for f in findings if f.rule == "fleet-record"] == []


def test_fleet_record_ignores_non_dispatch_engine_calls(tmp_path):
    # Engine lifecycle/bookkeeping calls are not traffic: only the
    # dispatch surface (call/submit/score/predict/predict_multi)
    # through an `engine` attribute counts as a forward.
    src = """
class Fleet:
    def grow(self, rep, mid, model):
        rep.engine.add_model(mid, model)
        rep.engine.warmup()
        return rep.engine.stats()

    def helper(self, rows):
        return self.call("m", rows)
"""
    findings = run_on(tmp_path, src, subdir="serving")
    assert [f for f in findings if f.rule == "fleet-record"] == []


def test_fleet_record_scoped_to_serving(tmp_path):
    findings = run_on(tmp_path, _FLEET_BAD, subdir="parallel")
    assert [f for f in findings if f.rule == "fleet-record"] == []


def test_fleet_record_suppression_honored(tmp_path):
    src = _FLEET_BAD.replace(
        "        return rep.engine.call(model_id, rows)",
        "        # lint: ok(fleet-record) — warm probe, excluded from "
        "the SLO signal by design\n"
        "        return rep.engine.call(model_id, rows)").replace(
        "        raise FleetOverloadError(model_id)",
        "        # lint: ok(fleet-record) — test-only admission stub\n"
        "        raise FleetOverloadError(model_id)")
    findings = run_on(tmp_path, src, subdir="serving")
    assert [f for f in findings if f.rule == "fleet-record"] == []


# ---------------------------------------------------------------------------
# atomic-swap (ISSUE 20)
# ---------------------------------------------------------------------------

_SWAP_BAD = """
class Updater:
    def apply(self, model, new_cents):
        model.centroids = new_cents

    def invalidate(self, model):
        model._cents_cache = None
"""

_SWAP_OK = """
import numpy as np


def publish_tables(model, mesh, shards, *, centroids_f64, seen):
    model._centroids_f64 = np.asarray(centroids_f64)
    model._seen = np.array(seen, copy=True)
    new_cents = model._centroids_f64.astype(model.dtype)
    dev = model._put_centroids(new_cents, mesh, shards)
    model._cents_cache = (new_cents, mesh, dev)
    model.centroids = new_cents


class Updater:
    def apply(self, model, mesh, shards, cents, seen):
        publish_tables(model, mesh, shards,
                       centroids_f64=cents, seen=seen)
"""


def test_atomic_swap_fires_on_inline_table_rebind(tmp_path):
    findings = run_on(tmp_path, _SWAP_BAD, subdir="serving")
    fires = [f for f in findings if f.rule == "atomic-swap"]
    assert len(fires) == 2
    assert ".centroids" in fires[0].message
    assert "publish_tables" in fires[0].message
    assert "._cents_cache" in fires[1].message


def test_atomic_swap_silent_inside_the_helper(tmp_path):
    findings = run_on(tmp_path, _SWAP_OK, subdir="serving")
    assert [f for f in findings if f.rule == "atomic-swap"] == []


def test_atomic_swap_covers_gmm_tables_and_del(tmp_path):
    # The GMM family's tables and a `del`-style cache invalidation are
    # the same incident class: the _params_dev identity cache must not
    # be torn out from under a concurrent reader either.
    src = """
class Updater:
    def apply(self, model, means):
        model.means_ = means

    def drop(self, model):
        del model._params_cache
"""
    findings = run_on(tmp_path, src, subdir="serving")
    fires = [f for f in findings if f.rule == "atomic-swap"]
    assert len(fires) == 2


def test_atomic_swap_scoped_to_serving(tmp_path):
    # models/ code (fit loops, partial_fit, _learn_clone) legitimately
    # writes its own tables — only serving/ publication is in scope.
    findings = run_on(tmp_path, _SWAP_BAD, subdir="models")
    assert [f for f in findings if f.rule == "atomic-swap"] == []


def test_atomic_swap_suppression_honored(tmp_path):
    src = _SWAP_BAD.replace(
        "        model.centroids = new_cents",
        "        # lint: ok(atomic-swap) — add-time init, model not "
        "yet resident\n"
        "        model.centroids = new_cents").replace(
        "        model._cents_cache = None",
        "        # lint: ok(atomic-swap) — teardown after remove()\n"
        "        model._cents_cache = None")
    findings = run_on(tmp_path, src, subdir="serving")
    assert [f for f in findings if f.rule == "atomic-swap"] == []


def test_atomic_swap_shipped_serving_tree_clean():
    # The real serving/ package routes every table publication through
    # serving.learn.publish_tables — the satellite's shipped-tree bar.
    findings = lint_paths(
        sorted((PKG_DIR / "serving").glob("*.py"))).findings
    assert [f for f in findings if f.rule == "atomic-swap"] == []


# ---------------------------------------------------------------------------
# cache-name (ISSUE 12)
# ---------------------------------------------------------------------------

_CACHE_NAME_BAD = """
from kmeans_tpu.utils.cache import LRUCache

_STEP_CACHE = LRUCache(64)
"""

_CACHE_NAME_OK = """
from kmeans_tpu.utils.cache import LRUCache

_STEP_CACHE = LRUCache(64, name="mod._STEP_CACHE")
"""


def test_cache_name_fires_on_unnamed_module_cache(tmp_path):
    findings = run_on(tmp_path, _CACHE_NAME_BAD, subdir="models")
    fired = [f for f in findings if f.rule == "cache-name"]
    assert len(fired) == 1
    assert "name=" in fired[0].message
    assert "cost capture" in fired[0].message


def test_cache_name_silent_when_named(tmp_path):
    findings = run_on(tmp_path, _CACHE_NAME_OK, subdir="models")
    assert [f for f in findings if f.rule == "cache-name"] == []


def test_cache_name_exempts_function_local_caches(tmp_path):
    src = """
from kmeans_tpu.utils.cache import LRUCache


def make_scratch():
    local = LRUCache(4)          # test-fixture/ad-hoc scope: exempt
    return local
"""
    findings = run_on(tmp_path, src, subdir="models")
    assert [f for f in findings if f.rule == "cache-name"] == []


def test_cache_name_fires_anywhere_in_package(tmp_path):
    """Unlike the serving/parallel-scoped rules, an unnamed cache is a
    finding in ANY module — every module-level cache is a compile-span
    and cost-capture surface."""
    findings = run_on(tmp_path, _CACHE_NAME_BAD, subdir="utils")
    assert [f.rule for f in findings if f.rule == "cache-name"] \
        == ["cache-name"]


def test_cache_name_suppression_honored(tmp_path):
    src = _CACHE_NAME_BAD.replace(
        "_STEP_CACHE = LRUCache(64)",
        "_STEP_CACHE = LRUCache(64)  # lint: ok(cache-name) — "
        "measurement cache, opted out of telemetry")
    findings = run_on(tmp_path, src, subdir="models")
    assert [f for f in findings if f.rule == "cache-name"] == []


# ---------------------------------------------------------------------------
# aot-key (ISSUE 15: the cache-key rule family, across processes)
# ---------------------------------------------------------------------------

_AOT_KEY_BAD = """
def persist(store, compiled, mesh, chunk):
    fields = {"mesh": repr(mesh), "chunk": chunk}   # hand-rolled key
    store.put(fields, compiled)
"""

_AOT_KEY_OK = """
from kmeans_tpu.utils.aot import artifact_key

def persist(store, compiled, cache_name, key, sig):
    store.put(artifact_key(cache_name, key, sig), compiled)
"""

_AOT_KEY_OK_CHASED = """
from kmeans_tpu.utils.aot import artifact_key

def persist(store, compiled, cache_name, key, sig):
    fields = artifact_key(cache_name, key, sig)
    store.put(fields, compiled)
"""


def test_aot_key_fires_on_hand_rolled_key(tmp_path):
    findings = [f for f in run_on(tmp_path, _AOT_KEY_BAD,
                                  subdir="utils")
                if f.rule == "aot-key"]
    assert len(findings) == 1
    assert "artifact_key" in findings[0].message


def test_aot_key_silent_on_blessed_constructor(tmp_path):
    for src in (_AOT_KEY_OK, _AOT_KEY_OK_CHASED):
        findings = run_on(tmp_path, src, subdir="utils")
        assert [f for f in findings if f.rule == "aot-key"] == []


def test_aot_key_ignores_non_store_puts(tmp_path):
    src = """
import queue

def enqueue(q: queue.Queue, item):
    q.put({"raw": item})
"""
    findings = run_on(tmp_path, src, subdir="utils")
    assert [f for f in findings if f.rule == "aot-key"] == []


def test_aot_key_suppression_honored(tmp_path):
    src = _AOT_KEY_BAD.replace(
        "store.put(fields, compiled)",
        "store.put(fields, compiled)  # lint: ok(aot-key) — test "
        "fixture exercising the corrupt-artifact path")
    findings = run_on(tmp_path, src, subdir="utils")
    assert [f for f in findings if f.rule == "aot-key"] == []


# ---------------------------------------------------------------------------
# large-k
# ---------------------------------------------------------------------------

_LARGE_K_BAD = """
from kmeans_tpu.parallel import distributed as dist


class Estimator:
    def fit(self, pts, mesh, chunk):
        step = dist.make_step_fn(mesh, chunk_size=chunk, mode="matmul")
        return step(pts)
"""

_LARGE_K_OK_PLAN = """
from kmeans_tpu.obs.memory import plan_fit
from kmeans_tpu.parallel import distributed as dist


class Estimator:
    def fit(self, pts, mesh, chunk):
        self.plan_ = plan_fit("kmeans", 10, 4, 8, chunk=chunk)
        step = dist.make_step_fn(mesh, chunk_size=chunk, mode="matmul")
        return step(pts)
"""

_LARGE_K_OK_DISPATCH = """
from kmeans_tpu.parallel import distributed as dist


class Server:
    def predict(self, rm, pts, mesh, chunk):
        if rm.spec.get("assign") == "two_level":
            return self._route(rm, pts)
        fn = dist.make_predict_fn(mesh, chunk_size=chunk)
        return fn(pts)
"""

_LARGE_K_MODULE_LEVEL = """
from kmeans_tpu.parallel import distributed as dist


def bench_fit(mesh, chunk):
    return dist.make_fit_fn(mesh, chunk_size=chunk, mode="matmul")
"""


def test_large_k_fires_on_unguarded_class(tmp_path):
    findings = [f for f in run_on(tmp_path, _LARGE_K_BAD,
                                  subdir="models")
                if f.rule == "large-k"]
    assert len(findings) == 1
    assert "plan_fit" in findings[0].message
    assert "Estimator" in findings[0].message


def test_large_k_silent_on_planner_or_dispatch_guard(tmp_path):
    for src in (_LARGE_K_OK_PLAN, _LARGE_K_OK_DISPATCH):
        findings = run_on(tmp_path, src, subdir="models")
        assert [f for f in findings if f.rule == "large-k"] == []


def test_large_k_exempts_module_level_builders(tmp_path):
    """Class granularity: module-level builder calls (benchmarks, the
    builder layer) size their shapes deliberately."""
    findings = run_on(tmp_path, _LARGE_K_MODULE_LEVEL, subdir="models")
    assert [f for f in findings if f.rule == "large-k"] == []


def test_large_k_suppression_honored(tmp_path):
    src = _LARGE_K_BAD.replace(
        "step = dist.make_step_fn(mesh, chunk_size=chunk, "
        "mode=\"matmul\")",
        "step = dist.make_step_fn(mesh, chunk_size=chunk, "
        "mode=\"matmul\")  # lint: ok(large-k) — test fixture")
    findings = run_on(tmp_path, src, subdir="models")
    assert [f for f in findings if f.rule == "large-k"] == []


# ---------------------------------------------------------------------------
# thread
# ---------------------------------------------------------------------------

_THREAD_BAD = """
import threading


class Prefetcher:
    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def close(self):
        pass                              # never joins
"""

_THREAD_OK = """
import threading


class Prefetcher:
    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def close(self):
        self._stop = True
        self._thread.join()
"""


def test_thread_fires_without_owner_join(tmp_path):
    findings = [f for f in run_on(tmp_path, _THREAD_BAD, subdir="data")
                if f.rule == "thread"]
    assert len(findings) == 1
    assert "self._thread" in findings[0].message


def test_thread_silent_with_close_join(tmp_path):
    findings = run_on(tmp_path, _THREAD_OK, subdir="data")
    assert [f for f in findings if f.rule == "thread"] == []


def test_thread_local_variant(tmp_path):
    bad = """
import threading


def run():
    t = threading.Thread(target=print)
    t.start()
"""
    ok = bad + "    t.join()\n"
    assert [f.rule for f in run_on(tmp_path, bad, subdir="data")
            if f.rule == "thread"] == ["thread"]
    assert [f for f in run_on(tmp_path, ok, subdir="data",
                              name="ok.py")
            if f.rule == "thread"] == []


# ---------------------------------------------------------------------------
# counter-reset
# ---------------------------------------------------------------------------

_RESET_BAD = """
class Model:
    def __init__(self, k):
        self.k = k

    def fit(self, X):
        self.segments_ = 3               # never declared at init
        return self
"""

_RESET_OK = """
class Model:
    def __init__(self, k):
        self.k = k
        self.segments_ = None

    def fit(self, X):
        self.segments_ = 3
        return self
"""


def test_counter_reset_fires_on_undeclared_audit_attr(tmp_path):
    findings = [f for f in run_on(tmp_path, _RESET_BAD, subdir="models")
                if f.rule == "counter-reset"]
    assert len(findings) == 1
    assert "segments_" in findings[0].message


def test_counter_reset_silent_when_declared(tmp_path):
    findings = run_on(tmp_path, _RESET_OK, subdir="models")
    assert [f for f in findings if f.rule == "counter-reset"] == []


def test_counter_reset_checks_every_same_named_class(tmp_path):
    """Two classes sharing a name in different modules are BOTH checked
    — a name collision must never open a coverage hole in the gate
    (review finding on this PR)."""
    clean = """
class Engine:
    def __init__(self):
        self.runs_ = 0

    def fit(self, X):
        self.runs_ = 1
        return self
"""
    dirty = """
class Engine:
    def fit(self, X):
        self.runs_ = 1               # undeclared in THIS Engine
        return self
"""
    d = tmp_path / "models"
    d.mkdir()
    (d / "a.py").write_text(clean)
    (d / "b.py").write_text(dirty)
    findings = [f for f in lint_paths([d]).findings
                if f.rule == "counter-reset"]
    assert len(findings) == 1
    assert findings[0].path.endswith("b.py")


def test_counter_reset_accepts_ancestor_and_reset_method(tmp_path):
    """Declaration may live in an in-package base class __init__ or in
    a *reset* method — the mixin/AutoCheckpoint layout."""
    src = """
class Base:
    def __init__(self):
        self.retries_ = 0


class Model(Base):
    def _reset_fit_state(self):
        self.chunks_ = None

    def fit(self, X):
        self.retries_ = 1
        self.chunks_ = 2
        return self
"""
    findings = run_on(tmp_path, src, subdir="models")
    assert [f for f in findings if f.rule == "counter-reset"] == []


# ---------------------------------------------------------------------------
# dead-private
# ---------------------------------------------------------------------------

_DEAD_BAD = """
def _orphan(x):
    return x + 1


def used(x):
    return x
"""

_DEAD_OK = """
def _helper(x):
    return x + 1


def used(x):
    return _helper(x)
"""


def test_dead_private_fires_on_orphan(tmp_path):
    findings = [f for f in run_on(tmp_path, _DEAD_BAD, subdir="models")
                if f.rule == "dead-private"]
    assert len(findings) == 1
    assert "_orphan" in findings[0].message


def test_dead_private_silent_when_referenced(tmp_path):
    findings = run_on(tmp_path, _DEAD_OK, subdir="models")
    assert [f for f in findings if f.rule == "dead-private"] == []


def test_dead_private_docstring_mention_is_not_a_reference(tmp_path):
    src = '''
def _orphan(x):
    return x


def used(x):
    """Calls nothing; merely mentions _orphan in prose."""
    return x
'''
    findings = [f for f in run_on(tmp_path, src, subdir="models")
                if f.rule == "dead-private"]
    assert len(findings) == 1


def test_dead_private_string_call_arg_is_a_reference(tmp_path):
    src = """
def _hook(x):
    return x


def used(obj):
    return getattr(obj, "_hook")(1)
"""
    findings = run_on(tmp_path, src, subdir="models")
    assert [f for f in findings if f.rule == "dead-private"] == []


# ---------------------------------------------------------------------------
# fault-path (ISSUE 19)
# ---------------------------------------------------------------------------

_FAULT_BAD = """
from kmeans_tpu.utils.faults import SimulatedPreemption


def supervise(worker):
    try:
        worker.step()
    except SimulatedPreemption:
        pass                       # swallowed fault: never routed
"""

_FAULT_OK_RAISE = """
from kmeans_tpu.utils.faults import SimulatedPreemption


class HostPreempted(RuntimeError):
    pass


def supervise(worker):
    try:
        worker.step()
    except SimulatedPreemption as e:
        raise HostPreempted(str(e)) from e
"""

_FAULT_OK_ROUTED = """
def supervise(worker, policy):
    try:
        worker.step()
    except OSError as e:
        policy.record_retry(e)     # routed into the committed policy
"""

_FAULT_OK_TYPED_EXIT = """
from kmeans_tpu.orchestrator import policy


def worker_main(km, data):
    try:
        km.fit(data)
    except TimeoutError:
        return policy.EXIT_PREEMPTED
    return policy.EXIT_DONE
"""


def test_fault_path_fires_on_swallowed_fault(tmp_path):
    findings = [f for f in run_on(tmp_path, _FAULT_BAD,
                                  subdir="orchestrator")
                if f.rule == "fault-path"]
    assert len(findings) == 1
    assert "SimulatedPreemption" in findings[0].message


def test_fault_path_fires_on_tuple_catch_in_parallel(tmp_path):
    src = _FAULT_BAD.replace("except SimulatedPreemption:",
                             "except (ValueError, OSError):")
    findings = [f for f in run_on(tmp_path, src, subdir="parallel")
                if f.rule == "fault-path"]
    assert len(findings) == 1
    assert "OSError" in findings[0].message


def test_fault_path_silent_on_reraise(tmp_path):
    findings = run_on(tmp_path, _FAULT_OK_RAISE, subdir="orchestrator")
    assert [f for f in findings if f.rule == "fault-path"] == []


def test_fault_path_silent_when_routed_to_policy(tmp_path):
    findings = run_on(tmp_path, _FAULT_OK_ROUTED, subdir="orchestrator")
    assert [f for f in findings if f.rule == "fault-path"] == []


def test_fault_path_silent_on_typed_exit_return(tmp_path):
    findings = run_on(tmp_path, _FAULT_OK_TYPED_EXIT,
                      subdir="orchestrator")
    assert [f for f in findings if f.rule == "fault-path"] == []


def test_fault_path_ignores_non_fault_types(tmp_path):
    src = _FAULT_BAD.replace("except SimulatedPreemption:",
                             "except KeyError:")
    findings = run_on(tmp_path, src, subdir="orchestrator")
    assert [f for f in findings if f.rule == "fault-path"] == []


def test_fault_path_scoped_to_supervised_tree(tmp_path):
    findings = run_on(tmp_path, _FAULT_BAD, subdir="serving")
    assert [f for f in findings if f.rule == "fault-path"] == []
    findings = run_on(tmp_path, _FAULT_BAD, subdir="models")
    assert [f for f in findings if f.rule == "fault-path"] == []


def test_fault_path_suppression_honored(tmp_path):
    src = _FAULT_BAD.replace(
        "    except SimulatedPreemption:",
        "    # lint: ok(fault-path) — fixture proves suppression\n"
        "    except SimulatedPreemption:")
    findings = run_on(tmp_path, src, subdir="orchestrator")
    assert [f for f in findings if f.rule == "fault-path"] == []


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def test_suppression_absorbs_and_is_counted(tmp_path):
    src = _DEAD_BAD.replace(
        "def _orphan(x):",
        "def _orphan(x):  # lint: ok(dead-private) — kept as a fixture")
    d = tmp_path / "models"
    d.mkdir()
    (d / "m.py").write_text(src)
    report = lint_paths([d])
    assert [f for f in report.findings if f.rule == "dead-private"] == []
    assert report.suppressed == 1
    sup = [s for s in report.suppressions if s.used]
    assert len(sup) == 1 and sup[0].reason == "kept as a fixture"


def test_suppression_on_preceding_comment_line(tmp_path):
    src = ("# lint: ok(dead-private) — fixture helper\n"
           + _DEAD_BAD.lstrip("\n"))
    report = lint_paths([run_dir(tmp_path, src)])
    assert [f for f in report.findings
            if f.rule == "dead-private"] == []
    assert report.suppressed == 1


def run_dir(tmp_path, src, name="m.py"):
    d = tmp_path / "models"
    d.mkdir(exist_ok=True)
    (d / name).write_text(src)
    return d


def test_malformed_suppression_is_a_finding(tmp_path):
    src = "X = 1  # lint: ok(dead-private)\nY = 2  # lint: ok() — why\n"
    findings = [f for f in lint_paths([run_dir(tmp_path, src)]).findings
                if f.rule == "suppression"]
    assert len(findings) == 2


def test_unknown_rule_suppression_is_a_finding(tmp_path):
    src = "X = 1  # lint: ok(no-such-rule) — because\n"
    findings = [f for f in lint_paths([run_dir(tmp_path, src)]).findings
                if f.rule == "suppression"]
    assert len(findings) == 1
    assert "unknown rule id" in findings[0].message


# ---------------------------------------------------------------------------
# package-wide self-test (the tier-1 gate)
# ---------------------------------------------------------------------------

def test_package_lints_clean():
    """The shipped tree is clean: ``python -m kmeans_tpu lint`` exits 0.
    Any new violation (or any suppression without a reason) fails
    tier-1 — the linter IS a test."""
    report = lint_paths([PKG_DIR])
    assert report.files > 40
    assert report.findings == [], "\n".join(
        f.format() for f in report.findings)
    for sup in report.suppressions:
        assert sup.reason, f"reason-less suppression at " \
                           f"{sup.path}:{sup.line}"


def test_package_suppression_inventory_is_small_and_used():
    """Suppressions are counted; an UNUSED one is stale and must be
    removed (it would silently mask a future violation)."""
    report = lint_paths([PKG_DIR])
    assert len(report.suppressions) <= 3
    for sup in report.suppressions:
        assert sup.used > 0, f"stale suppression at " \
                             f"{sup.path}:{sup.line} absorbs nothing"


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_clean_run_exits_zero(tmp_path, capsys):
    d = run_dir(tmp_path, "def used(x):\n    return x\n")
    assert lint_main([str(d)]) == 0
    assert "0 findings" in capsys.readouterr().out


def test_cli_violation_exits_two_with_location(tmp_path, capsys):
    d = run_dir(tmp_path, _DEAD_BAD)
    assert lint_main([str(d)]) == 2
    out = capsys.readouterr().out
    assert "[dead-private]" in out and "m.py:2" in out


def test_cli_json_report(tmp_path, capsys):
    src = _DEAD_BAD + "\nZ = 1  # lint: ok(thread) — inert example\n"
    d = run_dir(tmp_path, src)
    assert lint_main(["--json", str(d)]) == 2
    payload = json.loads(capsys.readouterr().out)
    assert payload["counts"].get("dead-private") == 1
    assert payload["findings"][0]["incident"]
    # The suppression inventory rides in the JSON (reviewable in CI).
    assert len(payload["suppressions"]) == 1
    assert payload["suppressions"][0]["reason"] == "inert example"


def test_cli_malformed_path_exits_two(capsys):
    assert lint_main(["/no/such/lint/target"]) == 2
    assert "error:" in capsys.readouterr().err


def test_cli_non_python_file_exits_two(tmp_path, capsys):
    f = tmp_path / "notes.txt"
    f.write_text("hi")
    assert lint_main([str(f)]) == 2
    assert "error:" in capsys.readouterr().err


def test_cli_unknown_rule_filter_exits_two(capsys):
    assert lint_main(["--rule", "no-such-rule", str(PKG_DIR)]) == 2
    assert "unknown rule" in capsys.readouterr().err


def test_cli_rule_filter_runs_single_rule(tmp_path, capsys):
    d = run_dir(tmp_path, _DEAD_BAD)
    assert lint_main(["--rule", "thread", str(d)]) == 0
    assert lint_main(["--rule", "dead-private", str(d)]) == 2
    capsys.readouterr()


def test_cli_syntax_error_exits_two(tmp_path, capsys):
    d = run_dir(tmp_path, "def broken(:\n")
    assert lint_main([str(d)]) == 2
    assert "cannot parse" in capsys.readouterr().err


def test_main_module_routes_lint(monkeypatch, tmp_path, capsys):
    """``python -m kmeans_tpu lint`` reaches the analysis CLI."""
    import kmeans_tpu.__main__ as entry
    d = run_dir(tmp_path, "def used(x):\n    return x\n")
    monkeypatch.setattr("sys.argv", ["kmeans_tpu", "lint", str(d)])
    assert entry.main() == 0
    capsys.readouterr()


# ---------------------------------------------------------------------------
# recompilation sentinel
# ---------------------------------------------------------------------------

from kmeans_tpu.utils.profiling import (RecompilationError,  # noqa: E402
                                        compile_caches,
                                        recompilation_sentinel)


def test_compile_caches_discovers_package_caches():
    caches = compile_caches()
    names = set(caches)
    assert "kmeans_tpu.models.kmeans._STEP_CACHE" in names
    assert "kmeans_tpu.models.gmm._STEP_CACHE" in names
    assert "kmeans_tpu.models.init._PIPE_CACHE" in names


def test_sentinel_raises_on_growth_naming_cache_and_key():
    from kmeans_tpu.models import kmeans as km
    probe = ("recompilation-sentinel-probe",)
    try:
        with pytest.raises(RecompilationError) as ei:
            with recompilation_sentinel():
                km._STEP_CACHE[probe] = object()
        msg = str(ei.value)
        assert "kmeans_tpu.models.kmeans._STEP_CACHE" in msg
        assert "recompilation-sentinel-probe" in msg
    finally:
        km._STEP_CACHE._d.pop(probe, None)


def test_sentinel_allowed_new_budget():
    from kmeans_tpu.models import kmeans as km
    probe = ("recompilation-sentinel-probe-2",)
    try:
        with recompilation_sentinel(allowed_new=1) as rec:
            km._STEP_CACHE[probe] = object()
        assert rec["new"] == {
            "kmeans_tpu.models.kmeans._STEP_CACHE": [probe]}
    finally:
        km._STEP_CACHE._d.pop(probe, None)


def test_sentinel_clean_scope_records_empty():
    with recompilation_sentinel() as rec:
        pass
    assert rec["new"] == {}
    assert "kmeans_tpu.models.kmeans._STEP_CACHE" in rec["caches"]


# --------------------------------------------- tier-1 five-family guard

@pytest.fixture(scope="module")
def blob_data():
    rng = np.random.RandomState(7)
    centers = rng.randn(4, 6) * 6.0
    X = np.concatenate([c + rng.randn(50, 6) for c in centers])
    return X.astype(np.float32)


def _families():
    from kmeans_tpu import (BisectingKMeans, GaussianMixture, KMeans,
                            MiniBatchKMeans, SphericalKMeans)
    return {
        "kmeans": KMeans(k=3, max_iter=5, seed=0, verbose=False),
        "minibatch": MiniBatchKMeans(k=3, max_iter=6, batch_size=64,
                                     seed=0, verbose=False),
        "bisecting": BisectingKMeans(k=3, max_iter=5, seed=0,
                                     verbose=False),
        "spherical": SphericalKMeans(k=3, max_iter=5, seed=0,
                                     verbose=False),
        "gmm": GaussianMixture(n_components=3, max_iter=5, seed=0),
    }


@pytest.mark.parametrize("family", sorted(_families().keys()))
def test_repeat_predict_adds_zero_cache_entries(family, blob_data):
    """The r11 zero-new-entries property as a standing guard: after one
    warm call, repeat same-shape predict dispatches must reuse every
    compiled entry across ALL package caches."""
    model = _families()[family]
    model.fit(blob_data)
    warm = model.predict(blob_data)           # compile + place
    with recompilation_sentinel() as rec:
        for _ in range(3):
            got = model.predict(blob_data)
    np.testing.assert_array_equal(got, warm)
    assert rec["new"] == {}


def test_repeat_serving_calls_add_zero_cache_entries(blob_data):
    """Same guard through the serving engine: repeat same-bucket
    requests (predict + score_rows ops) reuse the warm kernels."""
    from kmeans_tpu import KMeans
    from kmeans_tpu.serving import ServingEngine
    model = KMeans(k=3, max_iter=5, seed=0, verbose=False)
    model.fit(blob_data)
    model.mesh = None
    with ServingEngine(max_wait_ms=1.0) as eng:
        eng.add_model("m", model)
        probe = blob_data[:17]
        warm = eng.predict("m", probe)        # compile the bucket
        eng.call("m", probe, op="score_rows")
        with recompilation_sentinel() as rec:
            for _ in range(3):
                got = eng.predict("m", probe)
                eng.call("m", probe, op="score_rows")
        np.testing.assert_array_equal(got, warm)
        assert rec["new"] == {}
