"""distance_mode='auto' resolution (r2): the fused Pallas kernel where it
measures faster on TPU, the XLA matmul path everywhere else.

These tests run on the CPU mesh, where auto must ALWAYS resolve to
'matmul' (the kernel's interpret mode is for correctness CI, not speed);
the shape rule itself is tested directly against the measured win/loss
configs from BASELINE.md.
"""

import numpy as np
import pytest

from kmeans_tpu import KMeans
from kmeans_tpu.data.synthetic import make_blobs


def test_auto_is_the_default():
    assert KMeans().distance_mode == "auto"


def test_auto_resolves_to_matmul_off_tpu():
    km = KMeans(k=3)
    assert km._mode(10_000, 16) == "matmul"


def test_explicit_mode_passes_through():
    km = KMeans(k=3, distance_mode="direct")
    assert km._mode(10_000, 16) == "direct"


def test_shape_rule_matches_measured_win_loss_regions(monkeypatch):
    """Pin the rule to the BASELINE.md measurements by faking a TPU
    backend (the rule is pure shape logic past the backend gate)."""
    import jax

    from kmeans_tpu.ops import pallas_kernels as pk

    # jax.enable_x64 is experimental-only before 0.6.
    enable_x64 = getattr(jax, "enable_x64", None)
    if enable_x64 is None:
        from jax.experimental import enable_x64

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    with enable_x64(False):
        # Measured wins (BASELINE.md): headline and GloVe-shaped configs.
        assert pk.pallas_preferred(2_000_000, 128, 1024)
        assert pk.pallas_preferred(400_000, 100, 3000)
        # Measured losses: lane-padding waste (blobs1m, mnist) and small k.
        assert not pk.pallas_preferred(1_000_000, 16, 64)      # 11x slower
        assert not pk.pallas_preferred(60_000, 784, 10)        # k pad 12.8x
        assert not pk.pallas_preferred(10_000, 2, 5)
        # k just under the gate.
        assert not pk.pallas_preferred(1_000_000, 128, 511)
        # Oversized centroid block falls back instead of raising.
        assert not pk.pallas_preferred(1_000_000, 512, 200_000)
    # x64 always falls back in AUTO mode — a precision contract (the
    # fused kernel is an f32 engine; explicit 'pallas' still works).
    with enable_x64(True):
        assert not pk.pallas_preferred(2_000_000, 128, 1024)


def test_auto_fit_matches_matmul_fit_on_cpu():
    X, _ = make_blobs(2_000, 3, 8, random_state=0, dtype=np.float32)
    a = KMeans(k=3, seed=1, verbose=False).fit(X)
    b = KMeans(k=3, seed=1, verbose=False, distance_mode="matmul").fit(X)
    np.testing.assert_array_equal(a.centroids, b.centroids)
