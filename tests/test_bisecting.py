"""BisectingKMeans: divisive hierarchical clustering (beyond-reference model
family; the reference implements flat K-Means only, kmeans_spark.py:19-352).

Quality oracle is sklearn's BisectingKMeans — trajectories are not comparable
(different inner seeding), so assertions are on clustering QUALITY (SSE
within a small factor of sklearn's) and structural invariants, not on
centroid parity.
"""

import numpy as np
import pytest
from sklearn.cluster import BisectingKMeans as SkBisecting
from sklearn.datasets import make_blobs

from kmeans_tpu import BisectingKMeans, KMeans


@pytest.fixture()
def blobs6():
    X, y = make_blobs(n_samples=1200, centers=6, n_features=4,
                      cluster_std=0.7, random_state=7)
    return np.asarray(X, dtype=np.float64), y


def _sse(X, centroids, labels):
    return float(np.sum((X - centroids[labels]) ** 2))


def test_finds_k_clusters_and_invariants(blobs6, mesh8):
    X, _ = blobs6
    model = BisectingKMeans(k=6, max_iter=50, compute_sse=True, seed=3,
                            mesh=mesh8, verbose=False)
    model.fit(X)
    assert model.centroids.shape == (6, 4)
    assert model.labels_.shape == (X.shape[0],)
    assert set(np.unique(model.labels_)) == set(range(6))
    assert model.iterations_run == 5            # k-1 splits
    # Weighted sizes sum to n and match the hierarchical label histogram.
    assert np.isclose(model.cluster_sizes_.sum(), X.shape[0])
    hist = np.bincount(model.labels_, minlength=6)
    np.testing.assert_allclose(model.cluster_sizes_, hist)
    # Per-leaf SSE is consistent with the hierarchical labels/centroids.
    total = _sse(X, model.centroids.astype(np.float64), model.labels_)
    assert np.isclose(model.cluster_sse_.sum(), total, rtol=1e-5)


def test_quality_vs_sklearn(blobs6, mesh8):
    X, _ = blobs6
    ours = BisectingKMeans(k=6, max_iter=50, seed=0, mesh=mesh8,
                           verbose=False).fit(X)
    sk = SkBisecting(n_clusters=6, random_state=0, n_init=1).fit(X)
    ours_sse = _sse(X, ours.centroids.astype(np.float64),
                    ours.predict(X))
    assert ours_sse <= 1.1 * sk.inertia_ + 1e-9


def test_sse_history_decreases_per_split(blobs6, mesh8):
    X, _ = blobs6
    model = BisectingKMeans(k=5, compute_sse=True, seed=1, mesh=mesh8,
                            verbose=False).fit(X)
    assert len(model.sse_history) == 4
    # Each split can only reduce the total SSE (children fit their members
    # at least as well as the parent centroid did).
    diffs = np.diff(model.sse_history)
    assert np.all(diffs <= 1e-6)


def test_largest_cluster_strategy(blobs6, mesh8):
    X, _ = blobs6
    model = BisectingKMeans(k=4, bisecting_strategy="largest_cluster",
                            seed=2, mesh=mesh8, verbose=False).fit(X)
    assert model.centroids.shape == (4, 4)
    assert set(np.unique(model.labels_)) == set(range(4))


def test_sample_weight_masks_points(mesh8):
    rng = np.random.default_rng(0)
    X = np.concatenate([rng.normal(0, 0.1, (100, 2)),
                        rng.normal(5, 0.1, (100, 2)),
                        rng.normal((0, 9), 0.1, (50, 2))])
    w = np.ones(250)
    w[200:] = 0.0            # third blob carries no weight
    model = BisectingKMeans(k=2, seed=0, mesh=mesh8, verbose=False,
                            dtype=np.float64)
    model.fit(X, sample_weight=w)
    cents = model.centroids[np.argsort(model.centroids[:, 0])]
    np.testing.assert_allclose(cents[0], [0, 0], atol=0.1)
    np.testing.assert_allclose(cents[1], [5, 5], atol=0.1)


def test_k1_is_weighted_mean(mesh8):
    rng = np.random.default_rng(1)
    X = rng.normal(size=(97, 3))
    model = BisectingKMeans(k=1, compute_sse=True, mesh=mesh8,
                            verbose=False, dtype=np.float64).fit(X)
    np.testing.assert_allclose(model.centroids[0], X.mean(axis=0),
                               atol=1e-8)
    expect = float(np.sum((X - X.mean(axis=0)) ** 2))
    assert np.isclose(model.sse_history[-1], expect, rtol=1e-6)


def test_k1_sse_no_cancellation_far_from_origin(mesh8):
    # Regression: SSE for the k=1 leaf must be computed against the mean
    # directly, not via the variance identity sum(w|x|^2) - |s|^2/W, which
    # cancels catastrophically in float32 for offset data.
    rng = np.random.default_rng(4)
    X = rng.normal(loc=5000.0, size=(2048, 8)).astype(np.float32)
    model = BisectingKMeans(k=1, compute_sse=True, mesh=mesh8,
                            verbose=False).fit(X)
    mu = X.astype(np.float64).mean(axis=0)
    expect = float(np.sum((X.astype(np.float64) - mu) ** 2))
    assert model.cluster_sse_[0] >= 0
    assert np.isclose(model.sse_history[-1], expect, rtol=1e-3)


def test_device_loop_inner_fits_match_host(blobs6, mesh8):
    """r3: host_loop=False runs each inner 2-means as ONE device
    dispatch (the tunneled-platform fix: per-iteration host RTT made a
    k=32 bisecting fit take ~13 minutes).  The split tree must come out
    identical to the host-loop fit; the shared make_fit_fn program is
    reused across splits because the draw seeds are a traced argument."""
    X, _ = blobs6
    kw = dict(k=6, seed=3, dtype=np.float64, mesh=mesh8, verbose=False)
    host = BisectingKMeans(host_loop=True, **kw).fit(X)
    dev = BisectingKMeans(host_loop=False, **kw).fit(X)
    np.testing.assert_allclose(dev.centroids, host.centroids, atol=1e-9)
    np.testing.assert_array_equal(dev.labels_, host.labels_)
    np.testing.assert_allclose(dev.cluster_sse_, host.cluster_sse_,
                               rtol=1e-9)


def test_empty_cluster_forwarded_to_inner_fits(blobs6, mesh8):
    X, _ = blobs6
    model = BisectingKMeans(k=4, empty_cluster="farthest", seed=0,
                            mesh=mesh8, verbose=False).fit(X)
    assert model.centroids.shape == (4, 4)
    assert np.all(np.isfinite(model.centroids))


def test_unsplittable_raises(mesh8):
    X = np.zeros((8, 2))      # eight identical points: one distinct location
    with pytest.raises(RuntimeError, match="Cannot bisect"):
        BisectingKMeans(k=3, mesh=mesh8, verbose=False).fit(X)


def test_resume_unsupported(blobs6, mesh8):
    X, _ = blobs6
    model = BisectingKMeans(k=3, mesh=mesh8, verbose=False).fit(X)
    with pytest.raises(ValueError, match="resume"):
        model.fit(X, resume=True)


def test_checkpoint_roundtrip(tmp_path, blobs6, mesh8):
    X, _ = blobs6
    model = BisectingKMeans(k=4, seed=5, mesh=mesh8, verbose=False,
                            bisecting_strategy="largest_cluster").fit(X)
    path = tmp_path / "bisect.npz"
    model.save(path)
    loaded = BisectingKMeans.load(path)
    assert isinstance(loaded, BisectingKMeans)
    assert loaded.bisecting_strategy == "largest_cluster"
    np.testing.assert_allclose(loaded.centroids, model.centroids)
    labels = loaded.predict(X[:50])
    np.testing.assert_array_equal(labels, model.predict(X[:50]))


def test_per_cluster_sse_matches_oracle(mesh8):
    """StepStats.sse_per_cluster (the fused field the split criterion uses)
    against a NumPy oracle."""
    from kmeans_tpu.ops.assign import assign_reduce

    rng = np.random.default_rng(3)
    X = rng.normal(size=(256, 5))
    C = rng.normal(size=(7, 5))
    d2 = ((X[:, None, :] - C[None, :, :]) ** 2).sum(-1)
    lab = d2.argmin(1)
    oracle = np.array([d2[lab == j, j].sum() for j in range(7)])

    import jax.numpy as jnp
    stats = assign_reduce(jnp.asarray(X), jnp.ones(256), jnp.asarray(C),
                          chunk_size=64)
    np.testing.assert_allclose(np.asarray(stats.sse_per_cluster), oracle,
                               rtol=1e-6)
    assert np.isclose(np.asarray(stats.sse_per_cluster).sum(),
                      float(stats.sse), rtol=1e-6)
