"""Preemption-safe fits (ISSUE 4): every recovery claim is PROVED under
deterministic fault injection (``utils.faults``), never mocked.

* Segmented auto-checkpointing (``checkpoint_every=N``) is pinned
  BIT-IDENTICAL to the ``checkpoint_every=0`` single-dispatch oracle at
  N in {1, 3, max_iter} — the r6 ``prefetch=0`` / r8 ``pipeline=0``
  parity-oracle discipline.
* Kill-at-iteration-j (``faults.inject_kill_after_iteration`` at the
  checkpoint boundary) followed by ``fit(resume=<path>)`` reproduces the
  uninterrupted trajectory bit-exactly for ALL FIVE model families, on
  host AND device loops, across 1/2/4/8-way data meshes and TP centroid
  sharding.
* Transient-IO retry (deterministic exponential backoff, epoch replay),
  the non-finite block quarantine, and the corrupt-checkpoint ``.prev``
  fallback are each exercised through the real streamed-fit code path.
"""

import os

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans
from kmeans_tpu.data import io as data_io
from kmeans_tpu.models import (BisectingKMeans, GaussianMixture,
                               MiniBatchKMeans, SphericalKMeans)
from kmeans_tpu.parallel.mesh import make_mesh
from kmeans_tpu.utils import checkpoint as ckpt
from kmeans_tpu.utils import faults


def _blobs(n=2000, d=3, centers=4, rs=9):
    # n=2000/rs=9 runs ~17 Lloyd iterations at tolerance=1e-12 — long
    # enough that every kill boundary below lands MID-fit (a fit that
    # converges before the armed boundary would never fire the kill).
    X, _ = make_blobs(n_samples=n, centers=centers, n_features=d,
                      random_state=rs)
    return X.astype(np.float32)


def _blocks_of(X, rows=256):
    def make_blocks():
        def gen():
            for i in range(0, X.shape[0], rows):
                yield X[i: i + rows]
        return gen()
    return make_blocks


def _fit_killed(model, j, fit_call):
    """Run ``fit_call(model)`` with a kill armed at checkpoint boundary
    ``j``; assert the preemption actually fired."""
    with faults.inject_kill_after_iteration(j) as rec:
        with pytest.raises(faults.SimulatedPreemption):
            fit_call(model)
    assert rec["fired_at"] is not None and rec["fired_at"] >= j
    return rec["fired_at"]


def _assert_same_kmeans(a, b):
    assert a.iterations_run == b.iterations_run
    np.testing.assert_array_equal(a.centroids, b.centroids)
    np.testing.assert_array_equal(np.asarray(a.sse_history),
                                  np.asarray(b.sse_history))


# ------------------------------------------------- segmented == oracle

@pytest.mark.parametrize("every", [1, 3, 30])
@pytest.mark.parametrize("host_loop", [True, False])
def test_segmented_matches_single_dispatch(tmp_path, mesh8, every,
                                           host_loop):
    """ceil(max_iter/N) dispatches with rotating checkpoints between
    them == the one-dispatch (checkpoint_every=0) oracle, bitwise."""
    X = _blobs()
    kw = dict(k=4, max_iter=30, tolerance=1e-12, seed=1, compute_sse=True,
              mesh=mesh8, host_loop=host_loop, verbose=False)
    oracle = KMeans(**kw).fit(X)
    seg = KMeans(**kw).fit(X, checkpoint_every=every,
                           checkpoint_path=tmp_path / "seg.npz")
    _assert_same_kmeans(seg, oracle)
    assert seg.checkpoint_segments_ >= 1
    if not host_loop:
        # Device loop: segment count is the dispatch count.
        assert seg.checkpoint_segments_ == -(-seg.iterations_run // every)


@pytest.mark.parametrize("every", [1, 3, 16])
def test_gmm_segmented_matches_single_dispatch(tmp_path, mesh8, every):
    X = _blobs()
    kw = dict(n_components=4, tol=1e-7, max_iter=16, init_params="random",
              seed=0, mesh=mesh8, host_loop=False, verbose=False)
    oracle = GaussianMixture(**kw).fit(X)
    seg = GaussianMixture(**kw).fit(
        X, checkpoint_every=every, checkpoint_path=tmp_path / "g.npz")
    assert seg.n_iter_ == oracle.n_iter_
    assert seg.converged_ == oracle.converged_
    assert seg.lower_bound_ == oracle.lower_bound_
    np.testing.assert_array_equal(seg.means_, oracle.means_)
    np.testing.assert_array_equal(seg.covariances_, oracle.covariances_)


# ------------------------------------------- kill -> resume, bit-exact

@pytest.mark.parametrize("data_shards", [1, 2, 4, 8])
@pytest.mark.parametrize("host_loop", [True, False])
def test_kmeans_kill_resume_across_meshes(tmp_path, data_shards,
                                          host_loop):
    """Injected kill at a checkpoint boundary, then resume-from-path:
    trajectory bit-identical to the uninterrupted fit on every mesh
    width."""
    import jax
    if len(jax.devices()) < data_shards:
        pytest.skip("needs %d devices" % data_shards)
    mesh = make_mesh(data=data_shards, model=1,
                     devices=jax.devices()[:data_shards])
    X = _blobs()
    kw = dict(k=4, max_iter=25, tolerance=1e-12, seed=1, compute_sse=True,
              mesh=mesh, host_loop=host_loop, verbose=False)
    full = KMeans(**kw).fit(X)
    p = tmp_path / "ck.npz"
    _fit_killed(KMeans(**kw), 4,
                lambda m: m.fit(X, checkpoint_every=2, checkpoint_path=p))
    resumed = KMeans(**kw)
    resumed.fit(X, resume=p)
    _assert_same_kmeans(resumed, full)


@pytest.mark.parametrize("host_loop", [True, False])
def test_kmeans_kill_resume_tp_sharding(tmp_path, mesh4x2, host_loop):
    """Same pin under 2-way TP centroid sharding (the multihost
    primary-write path's sharded-table case on one process)."""
    X = _blobs()
    kw = dict(k=6, max_iter=25, tolerance=1e-12, seed=1, compute_sse=True,
              mesh=mesh4x2, model_shards=2, empty_cluster="keep",
              host_loop=host_loop, verbose=False)
    full = KMeans(**kw).fit(X)
    p = tmp_path / "tp.npz"
    _fit_killed(KMeans(**kw), 4,
                lambda m: m.fit(X, checkpoint_every=2, checkpoint_path=p))
    resumed = KMeans(**kw)
    resumed.fit(X, resume=p)
    _assert_same_kmeans(resumed, full)


@pytest.mark.parametrize("engine",
                         ["device-loop", "device-step", "host-sampling"])
def test_minibatch_kill_resume(tmp_path, mesh8, engine):
    X = _blobs(n=2000)
    kw = dict(k=4, max_iter=24, tolerance=1e-12, seed=3, batch_size=256,
              compute_sse=True, mesh=mesh8, verbose=False,
              host_loop=(engine != "device-loop"),
              sampling=("host" if engine == "host-sampling" else "device"))
    full = MiniBatchKMeans(**kw).fit(X)
    p = tmp_path / "mb.npz"
    _fit_killed(MiniBatchKMeans(**kw), 10,
                lambda m: m.fit(X, checkpoint_every=5, checkpoint_path=p))
    resumed = MiniBatchKMeans(**kw)
    resumed.fit(X, resume=p)
    assert resumed.iterations_run == full.iterations_run
    np.testing.assert_array_equal(resumed.centroids, full.centroids)
    np.testing.assert_array_equal(resumed._seen, full._seen)


@pytest.mark.parametrize("cov_type", ["diag", "full"])
@pytest.mark.parametrize("host_loop", [True, False])
def test_gmm_kill_resume(tmp_path, mesh8, cov_type, host_loop):
    """EM kill/resume, diag + full, host loop (float64 attrs are the
    exact carry) AND device loop (raw centered-frame tables + traced
    prev0 baseline are the exact carry)."""
    X = _blobs(n=1500)
    kw = dict(n_components=4, covariance_type=cov_type, tol=1e-7,
              max_iter=40, init_params="random", seed=0, mesh=mesh8,
              host_loop=host_loop, verbose=False)
    full = GaussianMixture(**kw).fit(X)
    assert full.n_iter_ > 6      # the kill must land mid-fit
    p = tmp_path / "g.npz"
    _fit_killed(GaussianMixture(**kw), 6,
                lambda m: m.fit(X, checkpoint_every=3, checkpoint_path=p))
    resumed = GaussianMixture(**kw)
    resumed.fit(X, resume=p)
    assert resumed.n_iter_ == full.n_iter_
    assert resumed.converged_ == full.converged_
    assert resumed.lower_bound_ == full.lower_bound_
    np.testing.assert_array_equal(resumed.means_, full.means_)
    np.testing.assert_array_equal(resumed.covariances_, full.covariances_)
    np.testing.assert_array_equal(resumed.weights_, full.weights_)


def test_gmm_kill_resume_tp_sharding(tmp_path, mesh4x2):
    X = _blobs(n=1500)
    kw = dict(n_components=4, tol=1e-7, max_iter=40, init_params="random",
              seed=0, mesh=mesh4x2, model_shards=2, host_loop=False,
              verbose=False)
    full = GaussianMixture(**kw).fit(X)
    p = tmp_path / "gtp.npz"
    _fit_killed(GaussianMixture(**kw), 4,
                lambda m: m.fit(X, checkpoint_every=2, checkpoint_path=p))
    resumed = GaussianMixture(**kw)
    resumed.fit(X, resume=p)
    assert resumed.n_iter_ == full.n_iter_
    np.testing.assert_array_equal(resumed.means_, full.means_)


@pytest.mark.parametrize("host_loop", [True, False])
def test_spherical_kill_resume(tmp_path, mesh8, host_loop):
    X = _blobs(d=4)
    kw = dict(k=4, max_iter=25, tolerance=1e-12, seed=3, compute_sse=True,
              mesh=mesh8, empty_cluster="keep", host_loop=host_loop,
              verbose=False)
    full = SphericalKMeans(**kw).fit(X)
    p = tmp_path / "sp.npz"
    _fit_killed(SphericalKMeans(**kw), 4,
                lambda m: m.fit(X, checkpoint_every=2, checkpoint_path=p))
    resumed = SphericalKMeans(**kw)
    resumed.fit(X, resume=p)
    _assert_same_kmeans(resumed, full)
    assert np.allclose(np.linalg.norm(resumed.centroids, axis=1), 1.0,
                       atol=1e-5)


@pytest.mark.parametrize("host_loop", [True, False])
def test_bisecting_kill_resume(tmp_path, mesh8, host_loop):
    """Split-boundary checkpointing: kill after split j, resume rebuilds
    the tree and continues — final centroids, hierarchical labels, and
    per-leaf SSE all bit-identical."""
    X = _blobs(n=1500, d=4, centers=6, rs=2)
    kw = dict(k=6, max_iter=20, tolerance=1e-10, seed=7, compute_sse=True,
              mesh=mesh8, host_loop=host_loop, verbose=False)
    full = BisectingKMeans(**kw).fit(X)
    p = tmp_path / "bk.npz"
    _fit_killed(BisectingKMeans(**kw), 3,
                lambda m: m.fit(X, checkpoint_every=1, checkpoint_path=p))
    resumed = BisectingKMeans(**kw)
    resumed.fit(X, resume=p)
    assert resumed.iterations_run == full.iterations_run
    np.testing.assert_array_equal(resumed.centroids, full.centroids)
    np.testing.assert_array_equal(resumed.labels_, full.labels_)
    np.testing.assert_array_equal(resumed.cluster_sse_, full.cluster_sse_)


def test_bisecting_resume_without_tree_checkpoint_errors(mesh8):
    X = _blobs(centers=3)
    m = BisectingKMeans(k=3, mesh=mesh8, verbose=False).fit(X)
    with pytest.raises(ValueError, match="split-boundary checkpoint"):
        m.fit(X, resume=True)


def test_fit_stream_kill_resume(tmp_path, mesh8):
    X = _blobs()
    make_blocks = _blocks_of(X)
    kw = dict(k=4, max_iter=20, tolerance=1e-12, seed=1, compute_sse=True,
              mesh=mesh8, verbose=False)
    full = KMeans(**kw)
    full.fit_stream(make_blocks, prefetch=0)
    p = tmp_path / "s.npz"
    _fit_killed(KMeans(**kw), 3,
                lambda m: m.fit_stream(make_blocks, prefetch=0,
                                       checkpoint_every=3,
                                       checkpoint_path=p))
    resumed = KMeans(**kw)
    resumed.fit_stream(make_blocks, prefetch=0, resume=p)
    _assert_same_kmeans(resumed, full)


def test_gmm_fit_stream_kill_resume(tmp_path, mesh8):
    X = _blobs(n=1200, centers=3, rs=5)
    make_blocks = _blocks_of(X, rows=300)
    kw = dict(n_components=3, tol=1e-9, max_iter=30, init_params="random",
              seed=0, mesh=mesh8, verbose=False)
    full = GaussianMixture(**kw)
    full.fit_stream(make_blocks, prefetch=0)
    assert full.n_iter_ > 2
    p = tmp_path / "gs.npz"
    _fit_killed(GaussianMixture(**kw), 2,
                lambda m: m.fit_stream(make_blocks, prefetch=0,
                                       checkpoint_every=2,
                                       checkpoint_path=p))
    resumed = GaussianMixture(**kw)
    resumed.fit_stream(make_blocks, prefetch=0, resume=p)
    assert resumed.n_iter_ == full.n_iter_
    assert resumed.lower_bound_ == full.lower_bound_
    np.testing.assert_array_equal(resumed.means_, full.means_)


def test_kill_leaves_valid_checkpoint(tmp_path, mesh8):
    """The injection hook fires only AFTER the write is durable: the
    checkpoint on disk at kill time loads and reflects the boundary."""
    X = _blobs()
    p = tmp_path / "k.npz"
    kw = dict(k=4, max_iter=25, tolerance=1e-12, seed=1, mesh=mesh8,
              host_loop=False, verbose=False)
    fired = _fit_killed(
        KMeans(**kw), 6,
        lambda m: m.fit(X, checkpoint_every=3, checkpoint_path=p))
    state = ckpt.load_state(p)
    assert int(state["iterations_run"]) == fired
    assert state["centroids"].shape == (4, 3)


# -------------------------------------------- retry / backoff / skips

def test_stream_retry_recovers_bit_exact(tmp_path, mesh8):
    """A block read failing 3 times mid-epoch, with io_retries >= 3,
    recovers by deterministic epoch replay — trajectory bit-identical
    to the clean stream, retries counted."""
    X = _blobs()
    clean = _blocks_of(X)
    kw = dict(k=4, max_iter=15, tolerance=1e-12, seed=1, compute_sse=True,
              mesh=mesh8, verbose=False)
    ref = KMeans(**kw)
    ref.fit_stream(clean, prefetch=0)
    flaky = faults.flaky_blocks(clean, fail_block=2, fail_times=3)
    m = KMeans(**kw)
    m.fit_stream(flaky, prefetch=2, io_retries=5, io_backoff=0.0)
    _assert_same_kmeans(m, ref)
    assert m.io_retries_used_ == 3
    assert flaky.state["failures"] == 3


def test_stream_retry_budget_exhausted_raises(mesh8):
    X = _blobs()
    flaky = faults.flaky_blocks(_blocks_of(X), fail_block=1,
                                fail_times=5)
    m = KMeans(k=4, max_iter=5, seed=1, mesh=mesh8, verbose=False)
    with pytest.raises(faults.TransientIOError):
        m.fit_stream(flaky, prefetch=0, io_retries=2, io_backoff=0.0)


def test_nonfinite_block_error_names_position(mesh8):
    X = _blobs()
    poisoned = faults.poison_blocks(_blocks_of(X), block=1)
    m = KMeans(k=4, max_iter=5, seed=1, mesh=mesh8, verbose=False)
    with pytest.raises(ValueError, match="block 1"):
        m.fit_stream(poisoned, prefetch=0)


def test_nonfinite_skip_quarantines_block(mesh8):
    """on_nonfinite='skip': the poisoned block is dropped from EVERY
    pass — the fit equals a fit of the stream without that block, and
    the skip counter records it."""
    X = _blobs()
    rows = 256
    keep = np.concatenate([X[:rows], X[2 * rows:]])   # block 1 removed
    kw = dict(k=4, max_iter=15, tolerance=1e-12, seed=1, compute_sse=True,
              mesh=mesh8, verbose=False)
    ref = KMeans(**kw)
    ref.fit_stream(_blocks_of(keep, rows), prefetch=0)
    poisoned = faults.poison_blocks(_blocks_of(X, rows), block=1)
    m = KMeans(**kw)
    m.fit_stream(poisoned, prefetch=0, on_nonfinite="skip")
    _assert_same_kmeans(m, ref)
    assert m.blocks_skipped_ == 1


def test_gmm_stream_retry_and_skip(mesh8):
    X = _blobs(n=1200, centers=3, rs=5)
    clean = _blocks_of(X, rows=300)
    kw = dict(n_components=3, tol=1e-7, max_iter=10, init_params="random",
              seed=0, mesh=mesh8, verbose=False)
    ref = GaussianMixture(**kw)
    ref.fit_stream(clean, prefetch=0)
    flaky = faults.flaky_blocks(clean, fail_block=1, fail_times=2)
    m = GaussianMixture(**kw)
    m.fit_stream(flaky, prefetch=0, io_retries=3, io_backoff=0.0)
    np.testing.assert_array_equal(m.means_, ref.means_)
    assert m.io_retries_used_ == 2
    poisoned = faults.poison_blocks(clean, block=2)
    m2 = GaussianMixture(**kw)
    m2.fit_stream(poisoned, prefetch=0, on_nonfinite="skip")
    assert m2.blocks_skipped_ == 1
    assert np.isfinite(m2.lower_bound_)


def test_fail_first_attempts_retry_call():
    """The fail-first-K-dispatch-attempts injection point against the
    bounded deterministic retry primitive itself."""
    stats = data_io.IOStats()
    flaky = faults.fail_first_attempts(lambda: 42, 2)
    assert data_io.retry_call(flaky, retries=3, backoff=0.0,
                              stats=stats) == 42
    assert stats.retries_used == 2
    assert flaky.state == {"calls": 3, "failures": 2}
    flaky2 = faults.fail_first_attempts(lambda: 42, 3)
    with pytest.raises(faults.TransientIOError):
        data_io.retry_call(flaky2, retries=2, backoff=0.0)


def test_from_npy_io_retries_knob(tmp_path, mesh8):
    """The shard-read retry knob on the out-of-core loader: clean load
    works with retries armed and exposes the counter surface."""
    X = _blobs()
    path = tmp_path / "x.npy"
    np.save(path, X)
    ds = data_io.from_npy(path, mesh8, k_hint=4, io_retries=2,
                          io_backoff=0.0)
    assert ds.io_stats.retries_used == 0
    m = KMeans(k=4, max_iter=5, seed=1, verbose=False).fit(ds)
    assert m.io_retries_used_ == 0
    np.testing.assert_allclose(np.asarray(ds.points)[: ds.n], X,
                               rtol=1e-6)


def test_iter_npy_blocks_retry(tmp_path):
    X = _blobs()
    path = tmp_path / "x.npy"
    np.save(path, X)
    mk = data_io.iter_npy_blocks(path, 256, io_retries=2, io_backoff=0.0)
    out = np.concatenate(list(mk()))
    np.testing.assert_array_equal(out, X)
    assert mk.io_stats.retries_used == 0


# ------------------------------------------------ knob validation etc.

def test_checkpoint_knob_validation(mesh8):
    X = _blobs()
    m = KMeans(k=4, mesh=mesh8, verbose=False)
    with pytest.raises(ValueError, match="requires\\s+checkpoint_path"):
        m.fit(X, checkpoint_every=2)
    with pytest.raises(ValueError, match="checkpoint_every >= 1"):
        m.fit(X, checkpoint_path="x.npz")
    with pytest.raises(ValueError, match="int >= 0"):
        m.fit(X, checkpoint_every=-1, checkpoint_path="x.npz")
    multi = KMeans(k=4, n_init=3, mesh=mesh8, verbose=False)
    with pytest.raises(ValueError, match="n_init == 1"):
        multi.fit(X, checkpoint_every=2, checkpoint_path="x.npz")


def test_resume_rejects_mismatched_model(tmp_path, mesh8):
    X = _blobs()
    p = tmp_path / "m.npz"
    KMeans(k=4, max_iter=3, mesh=mesh8, verbose=False).fit(
        X, checkpoint_every=1, checkpoint_path=p)
    with pytest.raises(ValueError, match="k=4"):
        KMeans(k=5, mesh=mesh8, verbose=False).fit(X, resume=p)
    with pytest.raises(ValueError, match="KMeans"):
        MiniBatchKMeans(k=4, mesh=mesh8, verbose=False).fit(X, resume=p)


def test_resume_falls_back_to_prev_after_torn_file(tmp_path, mesh8):
    """Satellite: write a torn checkpoint over the newest rotation and
    resume anyway — the `.prev` last-good state (one boundary older, on
    the same trajectory) finishes bit-identically."""
    X = _blobs()
    kw = dict(k=4, max_iter=25, tolerance=1e-12, seed=1, compute_sse=True,
              mesh=mesh8, host_loop=False, verbose=False)
    full = KMeans(**kw).fit(X)
    p = tmp_path / "r.npz"
    _fit_killed(KMeans(**kw), 6,
                lambda m: m.fit(X, checkpoint_every=3, checkpoint_path=p))
    p.write_bytes(b"torn mid-write")      # newest checkpoint corrupted
    resumed = KMeans(**kw)
    with pytest.warns(UserWarning, match="last-good rotation"):
        resumed.fit(X, resume=p)
    _assert_same_kmeans(resumed, full)


def test_gmm_restart_sweep_raw_tables_match_winner(mesh8):
    """Review r9 regression: the sequential restart sweep must carry the
    WINNER's raw device tables — it used to leave the LAST restart's, so
    a later save()+fit(resume=path) silently continued a losing
    trajectory while the fitted attrs described the winner."""
    X = _blobs(n=1500)
    gm = GaussianMixture(n_components=4, covariance_type="tied", n_init=3,
                         tol=1e-7, max_iter=15, init_params="random",
                         seed=0, mesh=mesh8, host_loop=False,
                         verbose=False)
    gm.fit(X)
    assert gm._dev_tables is not None
    # _ingest_device_tables defines means_ = f64(means_c) + shift; the
    # carried raw tables must reproduce the published winner exactly.
    recon = np.asarray(gm._dev_tables["means_c"], np.float64)[:4] \
        + gm._shift()
    np.testing.assert_array_equal(recon, gm.means_)


def test_checkpoint_segments_resets_between_fits(tmp_path, mesh8):
    """Review r9: a non-checkpointed fit after a checkpointed one must
    read None, not the previous fit's stale segment count."""
    X = _blobs()
    for host_loop in (True, False):
        m = KMeans(k=4, max_iter=6, seed=1, mesh=mesh8,
                   host_loop=host_loop, verbose=False)
        m.fit(X, checkpoint_every=2, checkpoint_path=tmp_path / "c.npz")
        assert m.checkpoint_segments_ >= 1
        m.fit(X)
        assert m.checkpoint_segments_ is None


def test_checkpoint_oracle_default_untouched(tmp_path, mesh8):
    """checkpoint_every=0 (the default) writes nothing and reports no
    segments — the oracle path is byte-for-byte today's behavior."""
    X = _blobs()
    m = KMeans(k=4, max_iter=5, seed=1, mesh=mesh8, host_loop=False,
               verbose=False).fit(X)
    assert m.checkpoint_segments_ is None
    assert list(tmp_path.iterdir()) == []
