"""Checkpoint / resume (beyond-reference capability, SURVEY.md §5:
the reference has no model serialization at all) — plus the ISSUE 4
hardening: torn-file detection, last-good ``.prev`` rotation, and the
format-version gate in both directions."""

import json

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans
from kmeans_tpu.utils import checkpoint as ckpt


def _data():
    X, _ = make_blobs(n_samples=2000, centers=4, n_features=3,
                      random_state=9)
    return X.astype(np.float64)


def test_save_load_roundtrip(tmp_path, mesh8):
    X = _data()
    km = KMeans(k=4, seed=1, compute_sse=True, mesh=mesh8,
                dtype=np.float64, verbose=False).fit(X)
    p = tmp_path / "model.npz"
    km.save(p)
    back = KMeans.load(p)
    np.testing.assert_array_equal(back.centroids, km.centroids)
    assert back.sse_history == km.sse_history
    assert back.iterations_run == km.iterations_run
    # A loaded model predicts without refitting.
    np.testing.assert_array_equal(back.predict(X[:50]), km.predict(X[:50]))


def test_suffixless_path_roundtrips(tmp_path, mesh8):
    X = _data()
    km = KMeans(k=4, seed=1, mesh=mesh8, dtype=np.float64,
                verbose=False).fit(X)
    km.save(tmp_path / "ckpt")            # no .npz suffix — np.savez adds it
    back = KMeans.load(tmp_path / "ckpt")
    np.testing.assert_array_equal(back.centroids, km.centroids)


def test_load_preserves_extended_hyperparams(tmp_path, mesh8):
    X = _data()
    km = KMeans(k=4, seed=1, empty_cluster="farthest",
                distance_mode="direct", chunk_size=64, verbose=False,
                mesh=mesh8, dtype=np.float64).fit(X)
    km.save(tmp_path / "m.npz")
    back = KMeans.load(tmp_path / "m.npz")
    assert back.empty_cluster == "farthest"
    assert back.distance_mode == "direct"
    assert back.chunk_size == 64
    assert back.verbose is False


def test_minibatch_resume_matches_uninterrupted(tmp_path, mesh8):
    from kmeans_tpu.models import MiniBatchKMeans
    X = _data()
    kw = dict(k=4, tolerance=1e-12, seed=3, batch_size=256, mesh=mesh8,
              dtype=np.float64, verbose=False)
    full = MiniBatchKMeans(max_iter=20, **kw).fit(X)
    part = MiniBatchKMeans(max_iter=8, **kw).fit(X)
    part.save(tmp_path / "mb.npz")
    resumed = MiniBatchKMeans.load(tmp_path / "mb.npz")
    resumed.max_iter = 20
    resumed.mesh = mesh8
    resumed.fit(X, resume=True)
    np.testing.assert_allclose(resumed.centroids, full.centroids, atol=1e-12)


def test_resume_matches_uninterrupted(tmp_path, mesh8):
    X = _data()
    # Uninterrupted 30-iteration run.
    full = KMeans(k=4, max_iter=30, tolerance=1e-12, seed=1, mesh=mesh8,
                  compute_sse=True, dtype=np.float64, verbose=False).fit(X)
    # 10 iterations, checkpoint, load, resume to 30.
    part = KMeans(k=4, max_iter=10, tolerance=1e-12, seed=1, mesh=mesh8,
                  compute_sse=True, dtype=np.float64, verbose=False).fit(X)
    p = tmp_path / "ckpt.npz"
    part.save(p)
    resumed = KMeans.load(p)
    resumed.max_iter = 30
    resumed.mesh = mesh8
    resumed.verbose = False
    resumed.fit(X, resume=True)
    np.testing.assert_allclose(resumed.centroids, full.centroids, atol=1e-12)
    assert resumed.iterations_run == full.iterations_run
    np.testing.assert_allclose(resumed.sse_history, full.sse_history,
                               rtol=1e-12)


# ------------------------------------------- ISSUE 4 file-level hardening

def test_load_state_corrupt_names_file(tmp_path):
    p = tmp_path / "c.npz"
    p.write_bytes(b"definitely not an npz")
    with pytest.raises(ckpt.CheckpointCorruptError, match="c.npz"):
        ckpt.load_state(p)


def test_load_state_truncated_npz(tmp_path):
    p = tmp_path / "t.npz"
    ckpt.save_state(p, {"a": np.arange(1000.0), "x": 1})
    data = p.read_bytes()
    p.write_bytes(data[: len(data) // 2])         # torn mid-write copy
    with pytest.raises(ckpt.CheckpointCorruptError, match="t.npz"):
        ckpt.load_state(p)


def test_rotation_keeps_last_good(tmp_path):
    p = tmp_path / "r.npz"
    ckpt.save_state_rotating(p, {"x": 1})
    assert not ckpt.prev_path(p).exists()         # nothing to rotate yet
    ckpt.save_state_rotating(p, {"x": 2})
    state, used_prev = ckpt.load_state_with_fallback(p)
    assert state["x"] == 2 and not used_prev
    p.write_bytes(b"torn")
    state, used_prev = ckpt.load_state_with_fallback(p)
    assert state["x"] == 1 and used_prev


def test_fallback_both_unreadable_raises(tmp_path):
    p = tmp_path / "b.npz"
    ckpt.save_state_rotating(p, {"x": 1})
    ckpt.save_state_rotating(p, {"x": 2})
    p.write_bytes(b"torn")
    ckpt.prev_path(p).write_bytes(b"also torn")
    with pytest.raises(ckpt.CheckpointCorruptError,
                       match="also unreadable"):
        ckpt.load_state_with_fallback(p)


def _rewrite_version(src, dst, version):
    with np.load(src) as z:
        meta = json.loads(str(z["__meta__"]))
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    meta["__format_version__"] = version
    np.savez(dst, __meta__=json.dumps(meta), **arrays)


def test_format_version_newer_rejected_actionably(tmp_path):
    p = tmp_path / "v.npz"
    ckpt.save_state(p, {"x": 1})
    newer = tmp_path / "newer.npz"
    _rewrite_version(p, newer, ckpt.FORMAT_VERSION + 1)
    with pytest.raises(ValueError, match="NEWER kmeans_tpu"):
        ckpt.load_state(newer)


def test_format_version_older_rejected(tmp_path):
    p = tmp_path / "v.npz"
    ckpt.save_state(p, {"x": 1})
    older = tmp_path / "older.npz"
    _rewrite_version(p, older, ckpt.FORMAT_VERSION - 1)
    with pytest.raises(ValueError, match="obsolete format version"):
        ckpt.load_state(older)
    # Version mismatches are NOT corruption: they must never silently
    # fall back to a .prev written by the same (mismatched) build.
    with pytest.raises(ValueError) as ei:
        ckpt.load_state(older)
    assert not isinstance(ei.value, ckpt.CheckpointCorruptError)
