"""Sharding correctness: mesh layouts must not change the math.

The reference never verifies that different partition counts give the same
answer (SURVEY.md §4); here it's a hard invariant: 1-device, 8-way DP, and
4x2 DP x TP (centroid-sharded) runs must agree, including the padded-k path
when k doesn't divide the model axis.
"""

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans
from kmeans_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def data():
    X, _ = make_blobs(n_samples=3000, centers=5, n_features=8,
                      random_state=11)
    return X


def _fit(mesh, data, **kw):
    km = KMeans(k=5, max_iter=25, seed=42, compute_sse=True, mesh=mesh,
                dtype=np.float64, verbose=False, **kw)
    return km.fit(data)


def test_dp_matches_single_device(data, mesh1, mesh8):
    a = _fit(mesh1, data)
    b = _fit(mesh8, data)
    np.testing.assert_allclose(a.centroids, b.centroids, atol=1e-9)
    np.testing.assert_allclose(a.sse_history, b.sse_history, rtol=1e-12)
    assert a.iterations_run == b.iterations_run


def test_tp_matches_dp(data, mesh8, mesh4x2):
    a = _fit(mesh8, data)
    b = _fit(mesh4x2, data)     # k=5 doesn't divide model=2 -> padded table
    np.testing.assert_allclose(a.centroids, b.centroids, atol=1e-9)
    np.testing.assert_allclose(a.sse_history, b.sse_history, rtol=1e-12)


def test_tp_predict_matches(data, mesh8, mesh4x2):
    a = _fit(mesh8, data)
    b = _fit(mesh4x2, data)
    np.testing.assert_array_equal(a.predict(data), b.predict(data))


def test_uneven_shard_padding(mesh8):
    # N deliberately prime: shards can't be even -> exercises pad path.
    rng = np.random.default_rng(5)
    X = rng.normal(size=(1009, 3))
    km = KMeans(k=4, mesh=mesh8, dtype=np.float64, verbose=False).fit(X)
    assert int(km.cluster_sizes_.sum()) == 1009   # padding rows inert


def test_various_mesh_shapes(data):
    import jax
    for shape in [(1, 1), (2, 1), (2, 2), (1, 8), (8, 1)]:
        if shape[0] * shape[1] > len(jax.devices()):
            continue                     # single-chip hardware mode
        mesh = make_mesh(data=shape[0], model=shape[1],
                         devices=jax.devices()[: shape[0] * shape[1]])
        km = _fit(mesh, data)
        assert np.all(np.isfinite(km.centroids))


def test_mesh_validation():
    import jax
    if len(jax.devices()) >= 8:
        with pytest.raises(ValueError, match="divisible"):
            make_mesh(model=3, devices=jax.devices()[:8])
    with pytest.raises(ValueError, match="positive"):
        make_mesh(model=0)
    with pytest.raises(ValueError, match="needs"):
        make_mesh(data=16, model=1)
