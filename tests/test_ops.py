"""Unit tests for the fused assign+reduce kernels against a NumPy oracle.

The reference has no kernel-level tests (its closures are only exercised
end-to-end, SURVEY.md §4); these cover the gap: distances, argmin
tie-breaking (NumPy lowest-index rule, kmeans_spark.py:156), one-hot
reduction, padded-row inertness, SSE fusion, and farthest-point fusion
(the reference's dead policy, kmeans_spark.py:103-119).
"""

import numpy as np
import pytest

from kmeans_tpu.ops.assign import (assign_chunk, assign_labels,
                                   assign_reduce, pairwise_sq_dists)


def _numpy_oracle(X, C):
    """Per-point loop, exactly the reference's semantics
    (kmeans_spark.py:147-159, :169-188, :224-235, :103-119)."""
    k, d = C.shape
    sums = np.zeros((k, d))
    counts = np.zeros(k)
    sse = 0.0
    far_d, far_p = -1.0, None
    labels = []
    for p in X:
        dist = np.linalg.norm(C - p, axis=1)
        i = int(np.argmin(dist))
        labels.append(i)
        sums[i] += p
        counts[i] += 1
        sse += float(np.min(dist)) ** 2
        if np.min(dist) ** 2 > far_d:
            far_d, far_p = float(np.min(dist)) ** 2, p
    return np.array(labels), sums, counts, sse, far_d, far_p


@pytest.fixture()
def xc():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(257, 5))
    C = rng.normal(size=(7, 5))
    return X, C


@pytest.mark.parametrize("mode", ["matmul", "direct"])
def test_pairwise_sq_dists(xc, mode):
    X, C = xc
    expected = ((X[:, None, :] - C[None, :, :]) ** 2).sum(-1)
    got = np.asarray(pairwise_sq_dists(X, C, mode=mode))
    np.testing.assert_allclose(got, expected, rtol=1e-10, atol=1e-10)


def test_assign_chunk_matches_oracle(xc):
    X, C = xc
    labels, *_ = _numpy_oracle(X, C)
    best, mind2 = assign_chunk(X, C)
    np.testing.assert_array_equal(np.asarray(best), labels)


def test_argmin_tie_breaks_to_lowest_index():
    # Two identical centroids: NumPy's argmin (and the reference,
    # kmeans_spark.py:156) picks index 0.
    X = np.array([[1.0, 1.0], [2.0, 0.0]])
    C = np.array([[1.0, 1.0], [1.0, 1.0], [5.0, 5.0]])
    best, _ = assign_chunk(X, C)
    np.testing.assert_array_equal(np.asarray(best), [0, 0])


@pytest.mark.parametrize("mode", ["matmul", "direct"])
def test_assign_reduce_matches_oracle(xc, mode):
    X, C = xc
    _, sums, counts, sse, far_d, far_p = _numpy_oracle(X, C)
    # Pad to a chunk multiple with zero-weight rows.
    chunk = 64
    pad = (-len(X)) % chunk
    Xp = np.concatenate([X, np.zeros((pad, X.shape[1]))])
    w = np.concatenate([np.ones(len(X)), np.zeros(pad)])
    stats = assign_reduce(Xp, w, C, chunk_size=chunk, mode=mode)
    np.testing.assert_allclose(np.asarray(stats.sums), sums, atol=1e-8)
    np.testing.assert_allclose(np.asarray(stats.counts), counts)
    np.testing.assert_allclose(float(stats.sse), sse, rtol=1e-10)
    np.testing.assert_allclose(float(stats.farthest_dist), far_d, rtol=1e-9)
    np.testing.assert_allclose(np.asarray(stats.farthest_point), far_p,
                               atol=1e-12)


def test_padding_rows_are_inert(xc):
    X, C = xc
    chunk = 128
    pad = (-len(X)) % chunk
    Xp = np.concatenate([X, 1e6 * np.ones((pad, X.shape[1]))])  # poison rows
    w = np.concatenate([np.ones(len(X)), np.zeros(pad)])
    stats = assign_reduce(Xp, w, C, chunk_size=chunk)
    assert float(stats.counts.sum()) == len(X)
    assert float(stats.farthest_dist) < 1e6  # poison never wins farthest


def test_assign_labels_handles_any_length(xc):
    X, C = xc
    labels, *_ = _numpy_oracle(X, C)
    got = assign_labels(X, C, chunk_size=100)
    assert got.shape == (len(X),)
    np.testing.assert_array_equal(np.asarray(got), labels)


def test_chunk_size_must_divide():
    X = np.zeros((10, 2))
    with pytest.raises(ValueError, match="multiple of chunk_size"):
        assign_reduce(X, np.ones(10), np.zeros((2, 2)), chunk_size=64)


def test_sse_accumulation_accuracy_at_scale():
    """SURVEY.md §7 hard part (a): fp32 SSE accumulation order could lose
    the ±1e-4 (relative) parity budget at large N.  XLA's tree reductions
    keep the fused f32 SSE within the ±1e-4 relative parity budget
    (measured 3.3e-6 at 2M x 128 on TPU v5e; typical error at this CI
    shape is 5e-6..6e-5 across seeds, so the budget is asserted, not the
    lucky seed)."""
    import jax.numpy as jnp

    n, d, k, chunk = 200_000, 32, 64, 20_000
    rng = np.random.default_rng(0)
    X = rng.uniform(-1, 1, size=(n, d)).astype(np.float32)
    C = X[:k].copy()
    stats = assign_reduce(jnp.asarray(X), jnp.ones((n,), jnp.float32),
                          jnp.asarray(C), chunk_size=chunk)
    from conftest import sq_dists_f64
    sse64 = sq_dists_f64(X, C).min(1).sum()
    assert abs(float(stats.sse) - sse64) / sse64 < 1e-4
