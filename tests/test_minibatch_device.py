"""On-device mini-batch sampling engine (r1 VERDICT #4): resident dataset,
fused Gumbel-top-k sampling + batch statistics in one dispatch."""

import numpy as np
import pytest

from kmeans_tpu import KMeans
from kmeans_tpu.models import MiniBatchKMeans
from kmeans_tpu.data.synthetic import make_blobs


@pytest.fixture()
def data():
    X, _ = make_blobs(4000, centers=5, n_features=8, random_state=2,
                      dtype=np.float32)
    return X


def test_device_sampling_deterministic(data, mesh8):
    kw = dict(k=5, seed=3, batch_size=256, max_iter=8, verbose=False,
              mesh=mesh8, compute_sse=True)
    a = MiniBatchKMeans(**kw).fit(data)
    b = MiniBatchKMeans(**kw).fit(data)
    np.testing.assert_array_equal(a.centroids, b.centroids)
    np.testing.assert_array_equal(a.sse_history, b.sse_history)


def test_device_sampling_converges_near_fullbatch(data, mesh8):
    mb = MiniBatchKMeans(k=5, seed=0, batch_size=512, max_iter=60,
                         verbose=False, mesh=mesh8).fit(data)
    full = KMeans(k=5, seed=0, verbose=False, mesh=mesh8).fit(data)
    # Same data, same k: the mini-batch solution's inertia should be close.
    assert -mb.score(data) < -full.score(data) * 1.25


def test_hostless_sharded_dataset_accepted(data, mesh8):
    """The device engine must not require a host copy (the r1 host path
    refused ShardedDatasets without one)."""
    km = MiniBatchKMeans(k=5, seed=1, batch_size=256, max_iter=10,
                         init="k-means++", verbose=False, mesh=mesh8)
    ds = km.cache(data)
    ds._host = None                    # simulate a device-only dataset
    ds._host_weights = None
    km.fit(ds)
    assert np.all(np.isfinite(km.centroids))
    assert km.labels_.shape == (len(data),)   # lazy labels via predict(ds)


def test_host_engine_still_requires_host(data, mesh8):
    km = MiniBatchKMeans(k=5, sampling="host", verbose=False, mesh=mesh8)
    ds = km.cache(data)
    ds._host = None
    ds._host_weights = None
    with pytest.raises(ValueError, match="sampling='device'"):
        km.fit(ds)


def test_invalid_sampling_raises():
    with pytest.raises(ValueError, match="sampling"):
        MiniBatchKMeans(sampling="banana")


def test_device_sampling_under_tp(data, mesh4x2):
    """Mini-batch under centroid (model-axis) sharding: model replicas must
    draw IDENTICAL batches (key folds in the data index only)."""
    mb = MiniBatchKMeans(k=5, seed=4, batch_size=256, max_iter=8,
                         verbose=False, mesh=mesh4x2, compute_sse=True)
    mb.fit(data)
    assert np.all(np.isfinite(mb.centroids))
    # Same seed on a DP-only mesh with the same data-axis size -> the
    # sampled batches (and hence the whole trajectory) are identical.
    import jax
    from kmeans_tpu.parallel.mesh import make_mesh
    if len(jax.devices()) >= 8:
        mesh4 = make_mesh(data=4, model=1, devices=jax.devices()[:4])
        ref = MiniBatchKMeans(k=5, seed=4, batch_size=256, max_iter=8,
                              verbose=False, mesh=mesh4, compute_sse=True)
        ref.fit(data)
        np.testing.assert_allclose(mb.centroids, ref.centroids,
                                   rtol=0, atol=1e-5)


def test_device_resume_matches_uninterrupted(data, tmp_path, mesh8):
    kw = dict(k=4, tolerance=1e-12, seed=3, batch_size=256, mesh=mesh8,
              dtype=np.float64, verbose=False)
    full = MiniBatchKMeans(max_iter=16, **kw).fit(data)
    part = MiniBatchKMeans(max_iter=6, **kw).fit(data)
    part.save(tmp_path / "mb.npz")
    resumed = MiniBatchKMeans.load(tmp_path / "mb.npz")
    assert resumed.sampling == "device"
    resumed.max_iter = 16
    resumed.mesh = mesh8
    resumed.fit(data, resume=True)
    np.testing.assert_allclose(resumed.centroids, full.centroids,
                               atol=1e-12)


def test_sampling_roundtrips_via_checkpoint(data, tmp_path):
    mb = MiniBatchKMeans(k=3, sampling="host", max_iter=3,
                         verbose=False).fit(data)
    mb.save(tmp_path / "h.npz")
    assert MiniBatchKMeans.load(tmp_path / "h.npz").sampling == "host"


def test_device_loop_matches_per_iteration_path(data, mesh8):
    """host_loop=False (one dispatch) must follow the same batch sequence
    and trajectory as the per-iteration path (float64 makes the on-device
    Sculley interpolation bit-comparable to the host's)."""
    kw = dict(k=5, seed=7, batch_size=256, max_iter=10, tolerance=1e-12,
              verbose=False, mesh=mesh8, dtype=np.float64, compute_sse=True)
    a = MiniBatchKMeans(host_loop=True, **kw).fit(data)
    b = MiniBatchKMeans(host_loop=False, **kw).fit(data)
    np.testing.assert_allclose(b.centroids, a.centroids, atol=1e-10)
    np.testing.assert_allclose(b.sse_history, a.sse_history, rtol=1e-9)
    np.testing.assert_allclose(b._seen, a._seen)
    assert b.iterations_run == a.iterations_run


def test_device_loop_resume_continuity(data, tmp_path, mesh8):
    """A fit interrupted and resumed through the device loop draws the same
    batch stream (absolute-iteration keys) as an uninterrupted run."""
    kw = dict(k=4, tolerance=1e-12, seed=3, batch_size=256, mesh=mesh8,
              dtype=np.float64, verbose=False, host_loop=False)
    full = MiniBatchKMeans(max_iter=14, **kw).fit(data)
    part = MiniBatchKMeans(max_iter=5, **kw).fit(data)
    part.save(tmp_path / "mb.npz")
    resumed = MiniBatchKMeans.load(tmp_path / "mb.npz")
    resumed.max_iter = 14
    resumed.mesh = mesh8
    resumed.fit(data, resume=True)
    np.testing.assert_allclose(resumed.centroids, full.centroids,
                               atol=1e-10)
