"""Serving-fleet acceptance (ISSUE 17): fleet labels BIT-EQUAL to a
single engine's on every dispatch path (direct, queued, packed,
bf16-guarded); deterministic admission control at the committed bound
(explicit, counted — never a silent drop); the kill-a-replica chaos
pin (zero failed requests, survivors absorb the re-dispatches);
pack-group-aware placement under partial replication; no traffic
before warmup; and the serve CLI / status-CLI fleet surfaces."""

import io
import json

import numpy as np
import pytest
from sklearn.datasets import make_blobs

from kmeans_tpu import KMeans
from kmeans_tpu.obs import metrics_registry as obs_metrics
from kmeans_tpu.parallel.mesh import make_mesh
from kmeans_tpu.serving import (FleetOverloadError, ReplicaDeadError,
                                ServingEngine, ServingFleet)
from kmeans_tpu.serving.batching import bucket_for
from kmeans_tpu.serving.fleet import MIN_ROUTE_SAMPLES
from kmeans_tpu.utils.faults import inject_replica_kill


@pytest.fixture(autouse=True)
def _fresh_metrics():
    """Histograms/counters are PROCESS-GLOBAL and replica names repeat
    (r0, r1, ...) across fleets, so a stale registry would pre-warm a
    new fleet's router with a dead fleet's latency estimates."""
    obs_metrics.REGISTRY.reset()
    yield
    obs_metrics.REGISTRY.reset()


@pytest.fixture(scope="module")
def data():
    X, _ = make_blobs(n_samples=3000, centers=6, n_features=8,
                      random_state=3)
    return X.astype(np.float32)


@pytest.fixture(scope="module")
def km(data):
    model = KMeans(k=5, seed=0, verbose=False, max_iter=25).fit(data)
    model.mesh = None                   # engine re-points to its mesh
    return model


@pytest.fixture(scope="module")
def km2(data):
    model = KMeans(k=5, seed=11, verbose=False, max_iter=25).fit(data)
    model.mesh = None
    return model


def _fleet(n=2, **kw):
    kw.setdefault("mesh", make_mesh())
    kw.setdefault("max_wait_ms", 1.0)
    kw.setdefault("quality", False)
    return ServingFleet(n, **kw)


# ----------------------------------------------------------- parity


def test_fleet_labels_bitequal_every_path(data, km, km2):
    """Direct, queued, and packed fleet dispatches all return labels
    bit-equal to the model's own predict (and hence to a single
    engine's — ISSUE 6 parity composed through the router)."""
    with _fleet(3) as fleet:
        assert sorted(fleet.add_model("a", km)) == ["r0", "r1", "r2"]
        fleet.add_model("b", km2)
        fleet.warmup()
        for m_rows in (1, 7, 64, 300):      # several buckets + padding
            probe = data[:m_rows]
            want = km.predict(probe)
            np.testing.assert_array_equal(fleet.call("a", probe), want)
            np.testing.assert_array_equal(
                fleet.submit("a", probe).result(timeout=30.0), want)
        outs = fleet.predict_multi([("a", data[:50]),
                                    ("b", data[50:90])])
        np.testing.assert_array_equal(outs[0], km.predict(data[:50]))
        np.testing.assert_array_equal(outs[1], km2.predict(data[50:90]))
        # Same-(k, D, dtype) models co-reside, so the mixed batch rode
        # ONE packed dispatch on one replica (r11 stays alive).
        assert sum(r.engine.packed_dispatches
                   for r in fleet._replicas) == 1
        st = fleet.stats()
        assert st["routes"] >= 8 + 2 and st["sheds"] == 0
        assert st["models"]["a"]["requests"] >= 8
        assert obs_metrics.REGISTRY.counter("fleet.route").value \
            == st["routes"]


def test_fleet_bf16_guarded_path_matches_engine(data, km):
    """The quantized assignment path (near-tie guard included) routes
    through the fleet unchanged: labels bit-equal to a single bf16
    engine's AND to exact predict (the guard's contract)."""
    mesh = make_mesh()
    probe = data[:200]
    with ServingEngine(mesh=mesh, max_wait_ms=1.0, quality=False) as eng:
        eng.add_model("m", km, quantize="bf16")
        want = eng.predict("m", probe)
    with _fleet(2, mesh=mesh) as fleet:
        fleet.add_model("m", km, quantize="bf16")
        fleet.warmup()
        np.testing.assert_array_equal(fleet.call("m", probe), want)
        np.testing.assert_array_equal(want, km.predict(probe))


def test_score_routes_and_matches(data, km):
    """Fleet score == a single engine's score BIT-EXACT (both run the
    same padded-bucket program; the model's own unpadded score may
    differ in f32 accumulation order)."""
    mesh = make_mesh()
    with ServingEngine(mesh=mesh, max_wait_ms=1.0, quality=False) as eng:
        eng.add_model("m", km)
        want = eng.score("m", data[:100])
    with _fleet(2, mesh=mesh) as fleet:
        fleet.add_model("m", km)
        fleet.warmup()
        assert fleet.score("m", data[:100]) == want
        assert fleet.stats()["routes"] == 1


# -------------------------------------------- admission & shedding


def test_max_inflight_burst_sheds_deterministically(data, km):
    """A burst beyond fleet capacity sheds EXACTLY offered - capacity
    requests: in-flight slots release only at result() collection, so
    with the queue timer never firing (start=False) the shed count is
    a pure function of the burst size.  Sheds are explicit
    (FleetOverloadError) and counted — zero silent drops."""
    offered, per_rep = 9, 2
    with _fleet(2, start=False, max_inflight=per_rep) as fleet:
        fleet.add_model("m", km)
        fleet.warmup(prewarm=False)
        futs, shed = [], 0
        for i in range(offered):
            try:
                futs.append(fleet.submit("m", data[i:i + 1]))
            except FleetOverloadError:
                shed += 1
        assert len(futs) == 2 * per_rep     # capacity: 2 replicas x 2
        assert shed == offered - 2 * per_rep
        assert len(futs) + shed == offered  # nothing vanished
        st = fleet.stats()
        assert st["sheds"] == shed
        assert obs_metrics.REGISTRY.counter("fleet.shed").value == shed
        assert obs_metrics.REGISTRY.counter("fleet.shed.m").value == shed
        # Drain: close() flushes the workerless queues; every ADMITTED
        # request still completes bit-exact.
        fleet.close()
        for i, f in enumerate(futs):
            np.testing.assert_array_equal(f.result(timeout=30.0),
                                          km.predict(data[i:i + 1]))


def test_slo_bound_sheds_when_every_replica_breaches(data, km):
    """Committed-p99 admission: cold candidates admit (shedding needs
    evidence); once every candidate's histogram is warm and expected
    completion breaches the bound, the request sheds explicitly."""
    with _fleet(2, slo_p99_ms=1.0) as fleet:
        fleet.add_model("m", km)
        fleet.warmup()
        probe = data[:1]
        # Cold histograms: admitted despite the tight bound.
        np.testing.assert_array_equal(fleet.call("m", probe),
                                      km.predict(probe))
        b = bucket_for(1, fleet.buckets)
        for rep in fleet._replicas:
            h = fleet._hist(rep, "m", b)
            for _ in range(MIN_ROUTE_SAMPLES):
                h.observe(50.0)             # p99 = 50 ms >> 1 ms bound
        with pytest.raises(FleetOverloadError, match="p99 bound"):
            fleet.call("m", probe)
        assert fleet.stats()["sheds"] == 1
        assert obs_metrics.REGISTRY.counter("fleet.shed").value == 1


# ------------------------------------------------- chaos / lifecycle


def test_kill_a_replica_zero_failed_requests(data, km):
    """The ISSUE 17 chaos pin: kill a replica with queued work in
    flight — every request still completes bit-exact (failed == 0),
    the dead replica's members re-dispatch on the survivor, and
    routing never touches the corpse again."""
    with _fleet(2) as fleet:
        fleet.add_model("m", km)
        fleet.warmup()
        with inject_replica_kill(fleet, after_dispatches=0) as rec:
            futs = [fleet.submit("m", data[i:i + 1]) for i in range(24)]
            outs = [f.result(timeout=30.0) for f in futs]
        assert rec["killed"] and rec["replica"] in ("r0", "r1")
        for i, out in enumerate(outs):      # zero failed, all exact
            np.testing.assert_array_equal(out,
                                          km.predict(data[i:i + 1]))
        st = fleet.stats()
        assert st["n_serving"] == 1
        assert st["replicas"][rec["replica"]]["state"] == "dead"
        assert st["redispatches"] >= 1
        assert obs_metrics.REGISTRY.counter("fleet.redispatch").value \
            == st["redispatches"]
        # Direct calls keep working on the survivor.
        np.testing.assert_array_equal(fleet.call("m", data[:3]),
                                      km.predict(data[:3]))


def test_all_replicas_dead_is_loud(data, km):
    with _fleet(1) as fleet:
        fleet.add_model("m", km)
        fleet.warmup()
        fleet.kill_replica("r0")
        with pytest.raises(ReplicaDeadError, match="no serving replica"):
            fleet.call("m", data[:2])


def test_no_traffic_before_warmup(data, km):
    """A replica takes traffic only in state 'serving' — calls before
    warmup() fail loudly, naming the fix."""
    with _fleet(2) as fleet:
        fleet.add_model("m", km)
        with pytest.raises(ReplicaDeadError, match="warmup"):
            fleet.call("m", data[:2])
        fleet.warmup()
        np.testing.assert_array_equal(fleet.call("m", data[:2]),
                                      km.predict(data[:2]))


def test_add_replica_prewarms_before_serving(data, km):
    with _fleet(1) as fleet:
        fleet.add_model("m", km)
        fleet.warmup()
        name = fleet.add_replica()
        st = fleet.stats()
        assert st["replicas"][name]["state"] == "serving"
        assert st["replicas"][name]["prewarm_s"] is not None
        assert st["placement"]["m"] == ["r0", name]
        np.testing.assert_array_equal(fleet.call("m", data[:5]),
                                      km.predict(data[:5]))


def test_reap_stalled_replica_with_inflight_work(data, km):
    """Heartbeat-driven death: in-flight work + no completed dispatch
    past the stall window -> dead; an IDLE replica never reaps (no
    outstanding work is no evidence of death)."""
    with _fleet(2, heartbeat_interval_s=0.1) as fleet:
        fleet.add_model("m", km)
        fleet.warmup()
        rep = fleet._replicas[0]
        assert fleet.reap(now=fleet._clock() + 1e4) == []  # idle: never
        rep.inflight = 1
        rep.last_beat = fleet._clock()
        assert fleet.reap(now=rep.last_beat + 0.5) == []   # in window
        assert fleet.reap(now=rep.last_beat + 1e4) == ["r0"]
        assert rep.state == "dead"
        assert fleet.stats()["n_serving"] == 1


# -------------------------------------------------------- placement


def test_pack_group_coresidency_under_partial_replication(data, km,
                                                          km2):
    """replication=1 on a 3-replica fleet: same-(k, D, dtype) models
    co-reside with their pack group (predict_multi stays ONE packed
    dispatch), while an unrelated model lands on the least-loaded
    replica."""
    with _fleet(3, replication=1) as fleet:
        fleet.add_model("a", km)
        fleet.add_model("b", km2)           # same (k, D, dtype) as "a"
        other = KMeans(k=3, seed=2, verbose=False, max_iter=5).fit(
            data[:500])
        other.mesh = None
        fleet.add_model("c", other)         # different k: new home
        st = fleet.stats()
        assert st["placement"]["a"] == st["placement"]["b"]
        assert len(st["placement"]["a"]) == 1
        assert st["placement"]["c"] != st["placement"]["a"]
        assert sorted(st["pack_groups"].get("5/8/<f4", [])) \
            == ["a", "b"]
        fleet.warmup()
        outs = fleet.predict_multi([("a", data[:40]),
                                    ("b", data[40:70])])
        np.testing.assert_array_equal(outs[0], km.predict(data[:40]))
        np.testing.assert_array_equal(outs[1], km2.predict(data[40:70]))
        assert sum(r.engine.packed_dispatches
                   for r in fleet._replicas) == 1


def test_predict_multi_falls_back_when_no_coresident_replica(data, km):
    """Models sharing no replica still answer (per-request routed
    calls — correct, unpacked)."""
    with _fleet(2, replication=1) as fleet:
        fleet.add_model("a", km)
        other = KMeans(k=3, seed=2, verbose=False, max_iter=5).fit(
            data[:500])
        other.mesh = None
        fleet.add_model("c", other)
        st = fleet.stats()
        assert st["placement"]["a"] != st["placement"]["c"]
        fleet.warmup()
        outs = fleet.predict_multi([("a", data[:30]),
                                    ("c", data[30:60])])
        np.testing.assert_array_equal(outs[0], km.predict(data[:30]))
        np.testing.assert_array_equal(outs[1],
                                      other.predict(data[30:60]))
        assert sum(r.engine.packed_dispatches
                   for r in fleet._replicas) == 0


# ------------------------------------------------------ CLI surface


def test_serve_cli_fleet_mode(tmp_path, data, km, monkeypatch, capsys):
    """serve --replicas N: requests route through the fleet (results
    unchanged), {"fleet_stats": true} answers the fleet snapshot, and
    the final summary names the replica count."""
    from kmeans_tpu.cli import serve_main
    km.save(tmp_path / "km.npz")
    want = km.predict(data[:3]).tolist()
    lines = [
        json.dumps({"x": data[:3].tolist(), "id": "r1"}),
        json.dumps({"fleet_stats": True}),
    ]
    monkeypatch.setattr("sys.stdin", io.StringIO("\n".join(lines) + "\n"))
    rc = serve_main(["--model", str(tmp_path / "km.npz"), "--json",
                     "--no-warmup", "--no-quality", "--replicas", "2",
                     "--max-wait-ms", "1.0"])
    assert rc == 0
    out = [json.loads(ln) for ln in
           capsys.readouterr().out.strip().splitlines()]
    assert out[0]["result"] == want and out[0]["id"] == "r1"
    fs = out[1]
    assert fs["n_replicas"] == 2 and fs["n_serving"] == 2
    assert fs["routes"] >= 1 and fs["sheds"] == 0
    assert set(fs["replicas"]) == {"r0", "r1"}
    final = out[-1]
    assert final["n_replicas"] == 2
    assert final["models"]["km"]["replicas"] == ["r0", "r1"]


def test_serve_cli_fleet_stats_needs_fleet_mode(tmp_path, data, km,
                                                monkeypatch, capsys):
    from kmeans_tpu.cli import serve_main
    km.save(tmp_path / "km.npz")
    monkeypatch.setattr(
        "sys.stdin",
        io.StringIO(json.dumps({"fleet_stats": True}) + "\n"))
    rc = serve_main(["--model", str(tmp_path / "km.npz"),
                     "--no-warmup", "--no-quality"])
    assert rc == 0                          # per-request error, loop on
    out = [json.loads(ln) for ln in
           capsys.readouterr().out.strip().splitlines()]
    assert "error" in out[0] and "--replicas" in out[0]["error"]


def test_serve_cli_rejects_bad_replicas(tmp_path, km, capsys):
    from kmeans_tpu.cli import serve_main
    km.save(tmp_path / "km.npz")
    assert serve_main(["--model", str(tmp_path / "km.npz"),
                       "--replicas", "0"]) == 2
    assert "--replicas" in capsys.readouterr().err


def test_status_clis_read_fleet_dir(tmp_path, data, km, capsys):
    """One fleet_dir feeds BOTH status CLIs: serve-status merges the
    per-replica quality sinks per model, fleet-status renders the
    per-replica heartbeats — unchanged exit codes."""
    from kmeans_tpu.cli import fleet_status_main, serve_status_main
    fdir = tmp_path / "fleet"
    with _fleet(2, quality=True, fleet_dir=str(fdir)) as fleet:
        fleet.add_model("m", km)
        fleet.warmup()
        fleet.call("m", data[:64])
    names = sorted(p.name for p in fdir.iterdir())
    assert "hb.r0.jsonl" in names and "hb.r1.jsonl" in names
    assert any(n.startswith("quality.m.r") for n in names)
    assert serve_status_main([str(fdir)]) == 0
    assert serve_status_main([str(fdir), "--json"]) == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "m" in report["models"] and report["healthy"]
    assert len([f for f in report["files"]
                if "quality.m.r" in f]) == 2
    assert fleet_status_main([str(fdir)]) == 0
    assert fleet_status_main([str(fdir), "--json"]) == 0
    fs = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert {h["host"] for h in fs["hosts"]} == {"r0", "r1"}


def test_fleet_ctor_validation():
    with pytest.raises(ValueError, match="n_replicas"):
        ServingFleet(0)
    with pytest.raises(ValueError, match="replication"):
        ServingFleet(2, replication=0)
