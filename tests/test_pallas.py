"""Pallas fused kernel vs the XLA-path oracle (interpret mode on CPU;
the same kernel compiles to Mosaic on real TPU)."""

import numpy as np
import pytest

from conftest import pallas_x64_skip

pytestmark = pallas_x64_skip

from kmeans_tpu.ops.assign import assign_reduce
from kmeans_tpu.ops.pallas_kernels import fused_assign_reduce


def _case(n, d, k, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    C = rng.normal(size=(k, d)).astype(np.float32)
    w = np.ones(n, np.float32)
    return X, w, C


@pytest.mark.parametrize("n,d,k", [(257, 5, 7), (512, 128, 96),
                                   (1000, 17, 300)])
def test_fused_kernel_matches_xla_path(n, d, k):
    X, w, C = _case(n, d, k)
    labels, mind2, sums, counts = fused_assign_reduce(
        X, w, C, tile_n=128, tile_k=128, interpret=True)
    # Oracle: the jit/XLA path.
    pad = (-n) % 64
    Xp = np.concatenate([X, np.zeros((pad, d), np.float32)])
    wp = np.concatenate([w, np.zeros(pad, np.float32)])
    stats = assign_reduce(Xp, wp, C, chunk_size=64)
    ref_labels = np.array([np.argmin(((C - p) ** 2).sum(1)) for p in X])
    np.testing.assert_array_equal(np.asarray(labels), ref_labels)
    np.testing.assert_allclose(np.asarray(sums), np.asarray(stats.sums),
                               rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(np.asarray(counts), np.asarray(stats.counts))
    np.testing.assert_allclose(float((mind2 * w).sum()), float(stats.sse),
                               rtol=1e-5)


def test_fused_kernel_padding_inert():
    X, w, C = _case(300, 9, 11)
    w[250:] = 0.0                       # zero-weight rows must not count
    _, _, sums, counts = fused_assign_reduce(X, w, C, tile_n=128,
                                             tile_k=128, interpret=True)
    assert float(np.asarray(counts).sum()) == 250


def test_fused_kernel_tie_break_lowest_index():
    X = np.array([[1.0, 1.0], [2.0, 0.0]], np.float32)
    C = np.array([[1.0, 1.0], [1.0, 1.0], [5.0, 5.0]], np.float32)
    labels, *_ = fused_assign_reduce(X, np.ones(2, np.float32), C,
                                     tile_n=8, tile_k=128, interpret=True)
    np.testing.assert_array_equal(np.asarray(labels), [0, 0])


def test_fori_fallback_for_many_k_tiles():
    """k_tiles > _UNROLL_K_TILES exercises the fori_loop path (trace cost
    stays O(1) in k); interpret mode + x64 also covers its int32-carry
    handling."""
    X, w, C = _case(512, 6, 1200)
    labels, mind2, sums, counts = fused_assign_reduce(
        X, w, C, tile_k=128, interpret=True)       # k_tiles = 10
    ref = assign_reduce(X, w, C, chunk_size=512)
    np.testing.assert_array_equal(np.asarray(labels),
                                  np.asarray(ref_labels := np.argmin(
                                      ((X[:, None] - C[None]) ** 2).sum(2),
                                      axis=1)))
    np.testing.assert_array_equal(np.asarray(counts), np.asarray(ref.counts))
    np.testing.assert_allclose(np.asarray(sums), np.asarray(ref.sums),
                               rtol=1e-4, atol=1e-4)


def test_nonfinite_rows_get_in_range_labels():
    """NaN/Inf coordinates must never leak the manual argmin's index
    sentinel: the cross-tile merge guard (NaN < running-min is False)
    keeps such rows at label 0.  fit() rejects non-finite data up front;
    this pins the kernel's own behavior for raw callers."""
    X = np.random.default_rng(0).normal(size=(64, 8)).astype(np.float32)
    X[3, 2] = np.nan
    X[17, :] = np.inf
    C = np.random.default_rng(1).normal(size=(300, 8)).astype(np.float32)
    w = np.ones((64,), np.float32)
    labels, *_ = fused_assign_reduce(X, w, C, tile_n=32, tile_k=128,
                                     interpret=True)
    assert 0 <= int(np.min(labels)) and int(np.max(labels)) < 300
    assert int(labels[3]) == 0 and int(labels[17]) == 0


@pytest.mark.parametrize("n,d,k", [
    (513, 100, 9),     # fold path (d < 128), odd row count
    (300, 128, 7),     # no-fold path (d == d_pad)
    (257, 130, 5),     # d just past a lane boundary (d_pad = 256)
    (1000, 40, 600),   # wide single-tile fold path (tile_k = k_pad)
    (900, 40, 1100),   # TRUE multi-k-tile path (k_pad 1152 -> 2 tiles)
])
def test_fused_kernel_weighted_property_sweep(n, d, k):
    """Weighted stats across fold/no-fold and single/multi k-tile paths
    must match a NumPy oracle exactly on labels/counts and closely on
    sums/mind2 (interpret mode computes true f32)."""
    rng = np.random.default_rng(n + d + k)
    X = rng.normal(size=(n, d)).astype(np.float32) * 3
    C = rng.normal(size=(k, d)).astype(np.float32) * 3
    w = rng.uniform(0.0, 2.0, size=n).astype(np.float32)
    w[rng.choice(n, n // 5, replace=False)] = 0.0
    labels, mind2, sums, counts = fused_assign_reduce(X, w, C,
                                                      interpret=True)
    d2 = ((X[:, None, :].astype(np.float64)
           - C[None, :, :].astype(np.float64)) ** 2).sum(-1)
    ref_labels = d2.argmin(1)
    np.testing.assert_array_equal(np.asarray(labels), ref_labels)
    np.testing.assert_allclose(np.asarray(mind2), d2.min(1), rtol=1e-4,
                               atol=1e-4)
    oh = np.zeros((n, k)); oh[np.arange(n), ref_labels] = w
    np.testing.assert_allclose(np.asarray(sums), oh.T @ X, rtol=1e-4,
                               atol=1e-3)
    np.testing.assert_allclose(np.asarray(counts), oh.sum(0), rtol=1e-6,
                               atol=1e-5)


def test_prepped_inputs_match_raw_inputs():
    """prep_points + kernel == raw inputs + kernel (the prep is pure
    layout: row padding with zero weights, lane padding, fold column)."""
    from kmeans_tpu.ops.pallas_kernels import prep_points

    rng = np.random.default_rng(9)
    X = rng.normal(size=(700, 60)).astype(np.float32)
    C = rng.normal(size=(20, 60)).astype(np.float32)
    w = rng.uniform(0.5, 1.5, size=700).astype(np.float32)
    raw = fused_assign_reduce(X, w, C, interpret=True)
    import jax.numpy as jnp
    px, pw, pwc = prep_points(jnp.asarray(X), jnp.asarray(w))
    prep = fused_assign_reduce(px, pwc, C, interpret=True)
    np.testing.assert_array_equal(np.asarray(raw[0]),
                                  np.asarray(prep[0])[:700])
    # f32 accumulation order differs with the padded row tiling.
    np.testing.assert_allclose(np.asarray(raw[2]), np.asarray(prep[2]),
                               rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(np.asarray(raw[3]), np.asarray(prep[3]),
                               rtol=1e-5)
