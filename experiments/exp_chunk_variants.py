"""Microbenchmark of assignment-pass chunk-body variants on real TPU.

Isolates which formulation of the (chunk, k) tile work is fastest:
argmin input form (full d2 vs h - xc), one-hot build (convert*mul vs
single where-select), counts (VPU column sum vs ones-column in the
scatter matmul).  Marginal method: per-pass cost is the time difference
between chained fori_loop(2) and fori_loop(2+T) runs, where each pass
feeds the next through a real centroid update (prevents XLA hoisting).

Usage: python experiments/exp_chunk_variants.py [N] [D] [K] [T]
"""
from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
D = int(sys.argv[2]) if len(sys.argv) > 2 else 128
K = int(sys.argv[3]) if len(sys.argv) > 3 else 1024
T = int(sys.argv[4]) if len(sys.argv) > 4 else 20


def body_old(xc, wc, c, k):
    """Round-1 body: full d2, astype*mul one-hot, VPU counts."""
    x2 = jnp.sum(xc * xc, axis=-1, keepdims=True)
    c2 = jnp.sum(c * c, axis=-1)[None, :]
    xcp = lax.dot_general(xc, c, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    d2 = jnp.maximum(x2 + c2 - 2.0 * xcp, 0.0)
    best = jnp.argmin(d2, axis=1).astype(jnp.int32)
    onehot = (best[:, None] == jnp.arange(k, dtype=jnp.int32)[None, :])
    onehot = onehot.astype(jnp.float32) * wc[:, None]
    sums = lax.dot_general(onehot, xc, (((0,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)
    counts = jnp.sum(onehot, axis=0)
    return sums, counts


def body_d2h(xc, wc, c, k):
    """h - xc argmin, astype*mul one-hot."""
    h = 0.5 * jnp.sum(c * c, axis=-1)[None, :]
    xcp = lax.dot_general(xc, c, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    best = jnp.argmin(h - xcp, axis=1).astype(jnp.int32)
    onehot = (best[:, None] == jnp.arange(k, dtype=jnp.int32)[None, :])
    onehot = onehot.astype(jnp.float32) * wc[:, None]
    sums = lax.dot_general(onehot, xc, (((0,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)
    counts = jnp.sum(onehot, axis=0)
    return sums, counts


def body_where(xc, wc, c, k):
    """Full d2 argmin, single where-select one-hot."""
    x2 = jnp.sum(xc * xc, axis=-1, keepdims=True)
    c2 = jnp.sum(c * c, axis=-1)[None, :]
    xcp = lax.dot_general(xc, c, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    d2 = jnp.maximum(x2 + c2 - 2.0 * xcp, 0.0)
    best = jnp.argmin(d2, axis=1).astype(jnp.int32)
    onehot = jnp.where(
        best[:, None] == jnp.arange(k, dtype=jnp.int32)[None, :],
        wc[:, None], jnp.zeros((), jnp.float32))
    sums = lax.dot_general(onehot, xc, (((0,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)
    counts = jnp.sum(onehot, axis=0)
    return sums, counts


def body_both(xc, wc, c, k):
    """h - xc argmin + where-select one-hot (the regressed combo)."""
    h = 0.5 * jnp.sum(c * c, axis=-1)[None, :]
    xcp = lax.dot_general(xc, c, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    best = jnp.argmin(h - xcp, axis=1).astype(jnp.int32)
    onehot = jnp.where(
        best[:, None] == jnp.arange(k, dtype=jnp.int32)[None, :],
        wc[:, None], jnp.zeros((), jnp.float32))
    sums = lax.dot_general(onehot, xc, (((0,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)
    counts = jnp.sum(onehot, axis=0)
    return sums, counts


def make_fit(body, chunk, n_iter):
    @jax.jit
    def fit(points, weights, cents0):
        xs = (points.reshape(-1, chunk, D), weights.reshape(-1, chunk))

        def one_pass(cents):
            def scan_body(carry, chk):
                s, cnt = carry
                xc, wc = chk
                ds, dc = body(xc, wc, cents, K)
                return (s + ds, cnt + dc), None
            (s, cnt), _ = lax.scan(
                scan_body, (jnp.zeros((K, D), jnp.float32),
                            jnp.zeros((K,), jnp.float32)), xs)
            return s / jnp.maximum(cnt, 1.0)[:, None]

        return lax.fori_loop(0, n_iter, lambda i, c: one_pass(c), cents0)
    return fit


def measure(name, body, points, weights, cents, chunk):
    f2 = make_fit(body, chunk, 2)
    fb = make_fit(body, chunk, 2 + T)
    # warm both
    float(f2(points, weights, cents)[0, 0])
    float(fb(points, weights, cents)[0, 0])
    margins = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(f2(points, weights, cents)[0, 0])
        t_small = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(fb(points, weights, cents)[0, 0])
        t_big = time.perf_counter() - t0
        margins.append((t_big - t_small) / T)
    med = float(np.median(margins)) * 1e3
    print(f"{name:24s} {med:8.3f} ms/iter  (reps "
          f"{[f'{m*1e3:.2f}' for m in margins]})", flush=True)
    return med


def _scalar_body(scalar_of):
    """Diagnostic body: keeps only part of the pass live via a scalar
    data dependence (sums = eps*scalar so the next iteration's centroids
    depend on this pass without the one-hot/scatter work)."""
    def body(xc, wc, c, k):
        s = scalar_of(xc, wc, c, k).astype(jnp.float32)
        return (jnp.full((k, D), 1e-30, jnp.float32) * s,
                jnp.ones((k,), jnp.float32))
    return body


def _d2(xc, c):
    x2 = jnp.sum(xc * xc, axis=-1, keepdims=True)
    c2 = jnp.sum(c * c, axis=-1)[None, :]
    xcp = lax.dot_general(xc, c, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    return jnp.maximum(x2 + c2 - 2.0 * xcp, 0.0)


diag_mm = _scalar_body(lambda xc, wc, c, k: jnp.sum(
    lax.dot_general(xc, c, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)[:, :8]))
diag_argmin = _scalar_body(lambda xc, wc, c, k: jnp.sum(
    jnp.argmin(_d2(xc, c), axis=1)))
diag_min = _scalar_body(lambda xc, wc, c, k: jnp.sum(
    jnp.min(_d2(xc, c), axis=1)))


def diag_onehot(xc, wc, c, k):
    """Full old body minus the counts column-sum."""
    best = jnp.argmin(_d2(xc, c), axis=1).astype(jnp.int32)
    onehot = (best[:, None] == jnp.arange(k, dtype=jnp.int32)[None, :])
    onehot = onehot.astype(jnp.float32) * wc[:, None]
    sums = lax.dot_general(onehot, xc, (((0,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)
    return sums, jnp.ones((k,), jnp.float32)


BODIES = {"old": body_old, "d2h": body_d2h, "where": body_where,
          "both": body_both, "diag_mm": diag_mm,
          "diag_argmin": diag_argmin, "diag_min": diag_min,
          "diag_onehot": diag_onehot}


def main():
    rng = np.random.default_rng(0)
    which = (sys.argv[5].split(",") if len(sys.argv) > 5
             else list(BODIES))
    chunks = ([int(c) for c in sys.argv[6].split(",")]
              if len(sys.argv) > 6 else [32768])
    max_chunk = max(chunks)
    n_pad = -(-N // max_chunk) * max_chunk
    X = rng.uniform(-1, 1, size=(n_pad, D)).astype(np.float32)
    c0 = X[rng.choice(N, K, replace=False)].copy()
    w = np.zeros((n_pad,), np.float32)
    w[:N] = 1.0
    X[N:] = 0.0
    points = jax.device_put(jnp.asarray(X))
    weights = jax.device_put(jnp.asarray(w))
    cents = jax.device_put(jnp.asarray(c0))
    print(f"N={N} (pad {n_pad}) D={D} K={K} T={T} "
          f"backend={jax.default_backend()}", flush=True)
    for chunk in chunks:
        if n_pad % chunk:
            continue
        for name in which:
            measure(f"{name}@{chunk}", BODIES[name], points, weights,
                    cents, chunk)


if __name__ == "__main__":
    main()
