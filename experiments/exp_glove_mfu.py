"""GloVe-shape (400k x 100, k=3000) Pallas MFU investigation (r3 VERDICT
#7): the kernel-level analysis stopped at "tile choice" — this sweep
measures tile-balance and pipelining variants and tests the hypothesis
that the 55%-vs-70% MFU gap is EXACTLY the 128-lane padding waste
(D=100 -> 128 is 1.28x MXU work the real-FLOPs MFU definition gives no
credit for; k=3000 -> 3072 another 1.024x; 70% / 1.31 = 53.4%).

Run on TPU hardware:  python experiments/exp_glove_mfu.py
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kmeans_tpu.ops.pallas_kernels import (fused_assign_reduce,
                                           prep_points, choose_tiles,
                                           _round_up)

N, D, K = 400_000, 100, 3000
PEAK_TFLOPS = 197.0          # v5e bf16 peak (the rate f32 dots run at)
REAL_TFLOP_PER_PASS = 4.0 * N * D * K / 1e12   # distance + scatter matmuls


def bench(tile_n, tile_k, iters=60, gap=40):
    """Marginal ms/pass via the iteration-gap method, whole loop in one
    dispatch (the tunneled chip's dispatch latency would otherwise swamp
    a ~4 ms kernel; a scalar transfer is the only reliable barrier)."""
    key = jax.random.PRNGKey(0)
    x_raw = jax.random.normal(key, (N, D), jnp.float32)
    w_raw = jnp.ones((N,), jnp.float32)
    c0 = x_raw[:K] * 1.0
    x, w, w_col = prep_points(x_raw, w_raw)

    def many(n_it):
        @jax.jit
        def run(x, w_col, c):
            def body(i, c):
                _, _, sums, counts = fused_assign_reduce(
                    x, w_col, c, tile_n=tile_n, tile_k=tile_k,
                    with_mind2=False)
                # Data dependency so no pass is DCE'd; *0 keeps c fixed.
                return c + 0.0 * sums
            return jnp.sum(lax.fori_loop(0, n_it, body, c))

        float(run(x, w_col, c0))                 # compile + warm
        reps = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(run(x, w_col, c0))             # scalar sync
            reps.append(time.perf_counter() - t0)
        return float(np.median(reps))

    t_small = many(2)
    t_big = many(2 + gap)
    ms = (t_big - t_small) / gap * 1e3
    return ms


def main():
    assert jax.default_backend() == "tpu", "run on TPU hardware"
    d_pad = _round_up(D, 128)
    k_pad = _round_up(K, 128)
    auto = choose_tiles(N, d_pad, k_pad, fold=D < d_pad)
    print(f"auto tiles: {auto}; real TFLOP/pass {REAL_TFLOP_PER_PASS:.3f}; "
          f"pad waste {d_pad / D * _round_up(k_pad, auto[1]) / K:.3f}x",
          flush=True)
    results = {}
    for tile_n, tile_k in [(1024, 3072), (512, 3072), (2048, 3072),
                           (1024, 1536), (512, 1536), (2048, 1536),
                           (1024, 1024), (1024, 768)]:
        try:
            ms = bench(tile_n, tile_k)
        except Exception as e:                   # VMEM guard etc.
            print(f"tile_n={tile_n:5d} tile_k={tile_k:5d}: "
                  f"SKIP ({type(e).__name__})", flush=True)
            continue
        mfu = REAL_TFLOP_PER_PASS / (ms / 1e3) / PEAK_TFLOPS
        # Padded-FLOPs utilization: how hard the MXU actually runs.
        kp = _round_up(k_pad, tile_k)
        hw = mfu * (d_pad / D) * (kp / K)
        results[(tile_n, tile_k)] = ms
        print(f"tile_n={tile_n:5d} tile_k={tile_k:5d}: {ms:7.3f} ms/pass  "
              f"MFU(real) {mfu * 100:5.1f}%  MXU-util(padded) "
              f"{hw * 100:5.1f}%", flush=True)
    best = min(results, key=results.get)
    print(f"best: {best} at {results[best]:.3f} ms "
          f"(auto {auto}: {results.get(auto, float('nan')):.3f} ms)")


if __name__ == "__main__":
    main()
