"""Pipelined GMM E-step: the ISSUE 3 tentpole's decision experiment.

The diag EM loop runs at ~33% MFU (8.37 ms/iter at 2M x 128 k=256,
docs/PERFORMANCE.md "The mixture family") because the serial chunk body
strictly orders [logp matmuls (MXU)] -> [softmax (VPU, 5e8
transcendentals/iter)] -> [moment matmuls (MXU)], so the MXU idles
through the exp stage.  ``parallel/gmm_step.py`` now ships a
software-pipelined schedule (``pipeline=1``, the default): each scan
step computes chunk i's log-density matmuls while consuming chunk i-1's
carried logp tile (softmax + moments) — no data dependency between the
two stages inside a step, so XLA can overlap the VPU exp with the next
chunk's MXU work.  ``pipeline=0`` is the bit-exact serial oracle
(pinned, tests/test_gmm_pipeline.py).

DECISION RULES — committed before the hardware measurement, per repo
discipline (the r3/r5 Pallas rejections set the precedent that a
measured rejection with numbers is an acceptable outcome; an unmeasured
claim is not):

1. **Primary (the pinned BASELINE.json ``gmm-estep-pipeline`` row).**
   On TPU hardware at 2M x 128 k=256 diag, the pipelined one-dispatch
   EM loop (this script / ``BENCH_GMM=1 python bench.py``) must measure
   **> 40% MFU** (< ~6.9 ms/iter) with the serial oracle re-measured
   interleaved in the same process.  >= 1.10x interleaved-ratio speedup
   with the MFU target met -> the ``pipeline='auto'`` -> 1 default is
   CONFIRMED.  Speedup in (0.98x, 1.10x) or MFU target missed -> the
   default stays pipelined only if the speedup is >= 1.0x, and the row
   records the shortfall (a real but sub-target overlap).  Speedup
   < 0.98x -> the pipelined default is REJECTED: flip
   ``GaussianMixture._resolve_pipeline``'s 'auto' to 0, keep the knob,
   and record the rejection with these numbers.
2. **Chunk plateau re-sweep.**  The 32768-row ``EM_MAX_CHUNK`` plateau
   was priced for the serial fusion boundary; the pipelined carry adds
   one in-flight (chunk, k) logp tile + a centered chunk copy.  Sweep
   chunk in {8192, 16384, 32768, 65536} under BOTH schedules; if a
   different chunk beats 32768 by > 10% under pipeline=1, move
   ``EM_MAX_CHUNK`` (and re-run rule 1 at the new plateau), else the
   cap stands.
3. **Covariance-family spot checks.**  One pipelined-vs-serial
   interleaved ratio each for full (1M x 64 k=32, the r5 ladder shape)
   and tied at the same shape: > 1.05x -> note the win; < 0.98x ->
   pin ``pipeline=0`` inside that family's scan only (the knob is
   per-builder), never by extrapolation from diag.

CPU smoke (2026-08-03, 2-core shared container, no TPU reachable): the
schedules are bit-identical in results; this script's raw-scan
micro-timings are NOISE-DOMINATED here (per-chunk "speedups" scattered
0.62x-1.77x with no consistent direction across shapes — shared-host
drift at 50-100 ms/pass scales).  The publishable CPU-proxy number is
the estimator-level interleaved measurement (``BENCH_GMM=1 python
bench.py``): pipelined 0.80x/0.86x — consistently SLOWER on CPU, every
rep, which is why ``pipeline='auto'`` resolves serial on CPU
(BASELINE.md r8 section).  Every rule above is a HARDWARE decision.

Run on TPU hardware:  python experiments/exp_gmm_pipelined_estep.py
"""

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kmeans_tpu.benchmarks import gmm_flops_per_iter, step_mfu
from kmeans_tpu.parallel.gmm_step import (_scan_estats, _scan_estats_full,
                                          _scan_estats_tied)

N, D, K = 2_097_152, 128, 256


def bench_epass(x, w, params, *, chunk, pipeline, gap=80, reps=3,
                cov="diag"):
    """Marginal ms per E pass, whole chain in one dispatch (the
    exp_gmm_estep_retry method: a fori_loop chain whose carry consumes
    EVERY accumulator so nothing is DCE'd)."""
    shift = jnp.zeros((x.shape[1],), x.dtype)
    scan = {"diag": _scan_estats, "full": _scan_estats_full,
            "tied": _scan_estats_tied}[cov]

    def many(n_it):
        @jax.jit
        def run(x, w, p0):
            def body(i, p0):
                st = scan(x, w, p0, *params[1:], shift,
                          chunk_size=chunk, model_shards=1,
                          pipeline=pipeline)
                dep = st.loglik + jnp.sum(st.xsum) + jnp.sum(st.resp_sum)
                if hasattr(st, "x2sum"):
                    dep = dep + jnp.sum(st.x2sum)
                else:
                    dep = dep + jnp.sum(st.scatter)
                return p0 + 0.0 * dep
            return jnp.sum(lax.fori_loop(0, n_it, body, p0))

        float(run(x, w, params[0]))
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(run(x, w, params[0]))
            times.append(time.perf_counter() - t0)
        return float(np.median(times))

    return (many(2 + gap) - many(2)) / gap * 1e3


def diag_params(key, k, d):
    rng = np.random.default_rng(1)
    means = jnp.asarray(rng.normal(size=(k, d)), jnp.float32)
    return (means, jnp.ones((k, d), jnp.float32),
            jnp.zeros((k,), jnp.float32),
            jnp.full((k,), -np.log(k), jnp.float32))


def main():
    on_tpu = jax.default_backend() == "tpu"
    n = N if on_tpu else 131_072
    d = D if on_tpu else 32
    k = K if on_tpu else 32
    if not on_tpu:
        print("CPU smoke run — every decision rule above is a HARDWARE "
              "decision; this run only exercises the harness.",
              flush=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (n, d), jnp.float32)
    w = jnp.ones((n,), jnp.float32)
    params = diag_params(0, k, d)
    flops = gmm_flops_per_iter(n, d, k, "diag")

    # Rule 2: chunk sweep under both schedules (interleaved per chunk).
    results = {}
    for chunk in (8_192, 16_384, 32_768, 65_536):
        if n % chunk:
            continue
        row = {}
        for pipeline in (0, 1):
            ms = bench_epass(x, w, params, chunk=chunk, pipeline=pipeline,
                             gap=80 if on_tpu else 12)
            mfu = step_mfu(flops, ms / 1e3)
            row["pipe1" if pipeline else "pipe0"] = ms
            print(f"  diag chunk={chunk:<6} pipeline={pipeline} "
                  f"{ms:8.2f} ms/pass"
                  + (f"  {mfu:5.1%} MFU" if mfu is not None else ""),
                  flush=True)
        row["speedup"] = row["pipe0"] / row["pipe1"]
        results[chunk] = row
        print(f"  diag chunk={chunk:<6} overlap speedup "
              f"{row['speedup']:.3f}x", flush=True)

    # Rule 3: full/tied spot checks at the r5 ladder shape.
    if on_tpu:
        n2, d2, k2 = 1_048_576, 64, 32
    else:
        n2, d2, k2 = 65_536, 16, 8
    x2 = jax.random.normal(jax.random.PRNGKey(2), (n2, d2), jnp.float32)
    w2 = jnp.ones((n2,), jnp.float32)
    rng = np.random.default_rng(3)
    means2 = jnp.asarray(rng.normal(size=(k2, d2)), jnp.float32)
    lw2 = jnp.full((k2,), -np.log(k2), jnp.float32)
    pc = jnp.broadcast_to(jnp.eye(d2, dtype=jnp.float32), (k2, d2, d2))
    full_params = (means2, pc, jnp.zeros((k2,), jnp.float32), lw2)
    tied_params = (means2, jnp.eye(d2, dtype=jnp.float32),
                   jnp.zeros((), jnp.float32), lw2)
    for cov, p in (("full", full_params), ("tied", tied_params)):
        ms0 = bench_epass(x2, w2, p, chunk=8_192, pipeline=0, cov=cov,
                          gap=40 if on_tpu else 8)
        ms1 = bench_epass(x2, w2, p, chunk=8_192, pipeline=1, cov=cov,
                          gap=40 if on_tpu else 8)
        print(f"  {cov:<5} {n2}x{d2} k={k2}: serial {ms0:.2f} vs "
              f"pipelined {ms1:.2f} ms/pass ({ms0 / ms1:.3f}x)",
              flush=True)
        results[cov] = {"pipe0": ms0, "pipe1": ms1,
                        "speedup": ms0 / ms1}

    print(json.dumps({str(key): val for key, val in results.items()},
                     default=float))
    if on_tpu and 32_768 in results:
        mfu = step_mfu(flops, results[32_768]["pipe1"] / 1e3)
        print(f"RULE 1 VERDICT INPUT: pipelined MFU at chunk 32768 = "
              f"{mfu:.1%} (target > 40%); speedup "
              f"{results[32_768]['speedup']:.3f}x", flush=True)


if __name__ == "__main__":
    main()
