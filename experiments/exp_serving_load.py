"""Serving load-generator harness (ISSUE 6 closed loop + ISSUE 7 open
loop): load against the resident warm-kernel engine — the end-to-end
QPS / latency artifact behind the BASELINE.md r11 serving rows.

CLOSED LOOP (default): one K-Means model resident in a
``ServingEngine``; C client THREADS each submit single-row ``predict``
requests back-to-back through the micro-batch queue (closed loop — a
client's next request leaves when its previous one completes), for a
fixed per-client request budget.  Concurrency sweeps 1/8/64/512
clients; per level the harness reports:

* p50/p99 request latency (submit -> result; the ``max_wait_ms``
  batching timer is PART of the number — a lone request waits up to
  the timer for co-batchable traffic, concurrent ones flush earlier on
  fill, so p50 DROPS as concurrency rises until dispatch cost
  dominates),
* aggregate QPS (total completed requests / wall),
* mean rows per dispatch (how well the queue coalesced — the
  batch-fill evidence),
* the sequential-dispatch baseline QPS at the same request count (one
  ``engine.predict`` per request, no queue) and the resulting speedup.

OPEN LOOP (``SERVE_MODE=open``, the r11 REMAINING item, landed with
ISSUE 7 so sweep-selected models can be load-tested at fixed QPS): a
dispatcher submits single-row requests at a FIXED offered arrival rate
— arrivals do not wait for completions, so the measurement is free of
coordinated omission (a closed loop silently slows its own arrivals
when the server stalls; an open loop charges the stall to every
request scheduled behind it).  Per offered rate the harness reports
p50/p99 latency measured from each request's SCHEDULED arrival time
(send lag — the dispatcher falling behind, e.g. on an inline
flush-on-full dispatch — is part of the number, by design), achieved
QPS, rows per dispatch, and max send lag.  Rates default to
{25,50,75,90}% of a closed-loop calibration run's peak QPS at 64
clients.  ``SERVE_SWEEP=1`` selects the model's k with
``KMeans.sweep`` (ISSUE 7) instead of taking SERVE_K as given — the
sweep-selected-then-load-tested workflow end to end.

DECISION RULES (committed now, measured per platform):

* closed loop — micro-batching earns its complexity where concurrent
  traffic exists: the acceptance bar is batched QPS >= 2x the
  sequential baseline at >= 8 concurrent clients.  On the CPU
  container the bar is already cleared (~4x at 8, published r11); the
  HARDWARE run (tunneled chip, ~70-100 ms dispatch RTT —
  docs/PERFORMANCE.md) is where the amortization is existential:
  sequential per-request QPS is bounded by ~1/RTT (~10-14 QPS) and
  the batched path should clear 100x at 512 clients.  If hardware
  ever measures batched < sequential at >= 8 clients, the queue
  defaults (max_wait_ms, buckets) are wrong for that platform and the
  row must be published as a rejection with the engine defaulting to
  direct dispatch.
* open loop — the engine must SUSTAIN half its closed-loop peak: at
  offered load = 0.5x the calibration QPS, p99 (from scheduled
  arrival) <= max_wait_ms + 10x the direct single-dispatch latency,
  AND the end-of-run drain (wall past the last scheduled arrival
  until the final completion — the backlog the offered window left
  behind; it grows linearly with run length iff the rate exceeds
  capacity) <= the same bound.  A naive achieved/offered >= 95% rule
  is NOT used: for a finite run the final drain is charged to the
  wall either way, biasing the ratio low at exactly the rates a long
  run would sustain.  The first swept rate violating either bound is
  the knee; the largest sustained rate publishes as
  ``max_sustained_qps``.  A violation AT the 0.5x point is a
  rejection: the queue cannot absorb its own calibration traffic and
  its defaults must be re-tuned for that platform.

FLEET (``SERVE_MODE=fleet``, ISSUE 17): the open-loop harness pointed
at a :class:`ServingFleet` — N replica engines behind the SLO-aware
router — producing the published 1->N replica QPS/p99 scaling curve.
Per replica count R in ``SERVE_REPLICAS`` a FRESH fleet (same fitted
model object, shared mesh) runs the same coordinated-omission-free
open-loop level at a committed offered rate (0.5x a single-engine
closed-loop calibration at 64 clients), and the verdict applies the
PRE-COMMITTED rule: every R must sustain the committed rate (failed ==
0, p99 from scheduled arrival <= the r11 bound, drain <= bound), and
QPS(R) >= 0.8 x QPS(1) — replication through the router must not cost
more than 20% of single-replica throughput.  On this CPU container the
in-process replicas share one backend so the curve is FLAT by
construction (the property measured is "replication adds no loss");
near-linear QPS(R) needs one device set per replica — hardware row
pinned (docs/PERFORMANCE.md).  ``SERVE_CHAOS=1`` appends the
kill-a-replica run: an R=2 fleet serving the committed rate has one
replica killed mid-run (``utils.faults.inject_replica_kill`` — the
dispatch guard refuses the in-flight queued batch, the queue's
per-member isolation fails each member, the router re-dispatches on
the survivor), asserting ZERO failed requests and a bounded p99
excursion (chaos p99 <= 5x the no-chaos p99 at the same rate and R).

Run:  python experiments/exp_serving_load.py
Env:  SERVE_N / SERVE_D / SERVE_K (model shape), SERVE_CLIENTS
      (comma list, default 1,8,64,512), SERVE_REQS (per client,
      default 64), SERVE_WAIT_MS (default 2.0),
      SERVE_MODE (closed|open|fleet, default closed), SERVE_RATES
      (comma list of offered QPS; default auto-calibrated),
      SERVE_OPEN_REQS (requests per rate, default 512), SERVE_SWEEP
      (1 = pick k via KMeans.sweep over SERVE_SWEEP_KRANGE, default
      '4:65:4'), SERVE_REPLICAS (comma list, default 1,2),
      SERVE_CHAOS (1 = append the kill-a-replica run).
"""

import json
import os
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import numpy as np

from kmeans_tpu.models.kmeans import KMeans
from kmeans_tpu.serving import ServingEngine


def run_level(engine, pool, clients: int, reqs: int):
    """One closed-loop concurrency level; returns the metrics row."""
    lats = []
    lock = threading.Lock()
    start_gate = threading.Event()

    def client(cid: int):
        rng = np.random.default_rng(cid)
        mine = []
        start_gate.wait()
        for _ in range(reqs):
            row = pool[rng.integers(0, pool.shape[0])][None, :]
            t0 = time.perf_counter()
            engine.submit("serve", row).result(timeout=120.0)
            mine.append(time.perf_counter() - t0)
        with lock:
            lats.extend(mine)

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(clients)]
    for t in threads:
        t.start()
    d0 = engine.stats()["dispatches"]
    t0 = time.perf_counter()
    start_gate.set()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    d1 = engine.stats()["dispatches"]
    total = clients * reqs
    lats = np.sort(np.asarray(lats))

    # Sequential-dispatch baseline: same request count, one direct
    # dispatch each (no queue, no timer) from one thread.
    n_seq = min(total, 256)                 # bounded; per-request cost
    t0 = time.perf_counter()
    for i in range(n_seq):
        engine.predict("serve", pool[i % pool.shape[0]][None, :])
    seq_wall = time.perf_counter() - t0
    seq_qps = n_seq / seq_wall

    return {
        "clients": clients,
        "requests": total,
        "p50_ms": round(float(np.percentile(lats, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(lats, 99)) * 1e3, 3),
        "qps": round(total / wall, 1),
        "rows_per_dispatch": round(total / max(d1 - d0, 1), 2),
        "sequential_qps": round(seq_qps, 1),
        "speedup_vs_sequential": round((total / wall) / seq_qps, 2),
    }


def run_open_loop(engine, pool, rate_qps: float, n_reqs: int):
    """One open-loop offered-rate level; returns the metrics row.

    A dispatcher thread submits at scheduled instants t0 + i/rate
    without waiting for completions; latency is completion minus the
    SCHEDULED arrival (so a stalled server — or the dispatcher itself
    falling behind on an inline flush-on-full dispatch — is charged to
    every request queued behind the stall; no coordinated omission).
    Completion times come from a small FIFO waiter pool: the queue
    dispatches FIFO per model so completions land near submission
    order, and 8 concurrent waiters absorb the residual reordering
    (batch-boundary granularity, well under the ms-scale latencies
    being measured).
    """
    import queue as queue_mod
    done_q = queue_mod.Queue()
    lats = []
    failures = [0]
    lock = threading.Lock()

    def waiter():
        # A failed/timed-out request must not kill the waiter thread —
        # that silently drops every sample routed to it and skews the
        # published percentiles.  Count it and keep draining; the row
        # publishes ``failed`` and the judge treats any failure as
        # not-sustained (an overloaded level is exactly where timeouts
        # appear, and it is the answer, not noise).
        while True:
            item = done_q.get()
            if item is None:
                return
            sched, fut = item
            try:
                fut.result(timeout=120.0)
            except Exception:
                with lock:
                    failures[0] += 1
                continue
            t = time.perf_counter()
            with lock:
                lats.append(t - sched)

    waiters = [threading.Thread(target=waiter) for _ in range(8)]
    for w in waiters:
        w.start()

    rng = np.random.default_rng(1234)
    idx = rng.integers(0, pool.shape[0], size=n_reqs)
    interval = 1.0 / rate_qps
    max_send_lag = 0.0
    d0 = engine.stats()["dispatches"]
    t0 = time.perf_counter()
    for i in range(n_reqs):
        sched = t0 + i * interval
        now = time.perf_counter()
        if sched > now:
            time.sleep(sched - now)
        fut = engine.submit("serve", pool[idx[i]][None, :])
        max_send_lag = max(max_send_lag, time.perf_counter() - sched)
        done_q.put((sched, fut))
    for _ in waiters:
        done_q.put(None)
    for w in waiters:
        w.join()
    wall = time.perf_counter() - t0
    d1 = engine.stats()["dispatches"]
    lats = np.sort(np.asarray(lats))
    sched_duration = (n_reqs - 1) * interval
    # Percentiles cover COMPLETED requests only; ``failed`` > 0 marks
    # the row biased (and all-failed publishes null percentiles rather
    # than crashing on an empty array).
    return {
        "mode": "open",
        "offered_qps": round(rate_qps, 1),
        "achieved_qps": round((n_reqs - failures[0]) / wall, 1),
        "requests": n_reqs,
        "failed": failures[0],
        "p50_ms": (round(float(np.percentile(lats, 50)) * 1e3, 3)
                   if lats.size else None),
        "p99_ms": (round(float(np.percentile(lats, 99)) * 1e3, 3)
                   if lats.size else None),
        "drain_ms": round(max(wall - sched_duration, 0.0) * 1e3, 3),
        "max_send_lag_ms": round(max_send_lag * 1e3, 3),
        "rows_per_dispatch": round(n_reqs / max(d1 - d0, 1), 2),
    }


def open_loop_sweep(engine, pool, wait_ms: float):
    """The tail-latency-vs-offered-load curve + the committed decision
    (module docstring): calibrate peak QPS closed-loop at 64 clients,
    sweep SERVE_RATES (default {25,50,75,90}% of peak), and judge the
    0.5x-peak point against p99 AND end-of-run drain <= max_wait_ms +
    10x the direct single-dispatch latency (docstring rationale)."""
    n_open = int(os.environ.get("SERVE_OPEN_REQS", 512))

    # Direct single-dispatch latency (no queue, no timer): the p99
    # bound's scale term.
    for _ in range(8):                       # warm
        engine.predict("serve", pool[:1])
    t0 = time.perf_counter()
    n_direct = 64
    for i in range(n_direct):
        engine.predict("serve", pool[i % pool.shape[0]][None, :])
    direct_s = (time.perf_counter() - t0) / n_direct

    rates_env = os.environ.get("SERVE_RATES", "")
    cal_qps = None
    if rates_env:
        rates = [float(r) for r in rates_env.split(",")]
    else:
        cal = run_level(engine, pool, clients=64,
                        reqs=int(os.environ.get("SERVE_REQS", 64)))
        cal_qps = cal["qps"]
        print(json.dumps({"mode": "open-calibration", **cal}),
              flush=True)
        rates = [round(cal_qps * f, 1) for f in (0.25, 0.5, 0.75, 0.9)]
    p99_bound_ms = wait_ms + 10 * direct_s * 1e3

    # Discarded warm-up level: the first open-loop burst after the
    # closed-loop calibration consistently eats a scheduler cold-start
    # spike (waiter threads + queue worker warming up) that is not a
    # property of any offered rate.  Capped at ~2 s of paced traffic —
    # its only job is waking the threads, and an uncapped 128-request
    # warm-up at a low pinned SERVE_RATES would stall the run for
    # 128/rate seconds before the first measured level.
    n_warm = min(128, n_open, max(8, int(2.0 * rates[0])))
    run_open_loop(engine, pool, rates[0], n_warm)

    rows = []
    for rate in rates:
        row = run_open_loop(engine, pool, rate, n_open)
        row["sustained"] = bool(row["failed"] == 0
                                and row["p99_ms"] is not None
                                and row["p99_ms"] <= p99_bound_ms
                                and row["drain_ms"] <= p99_bound_ms)
        print(json.dumps(row), flush=True)
        rows.append(row)

    sustained = [r["offered_qps"] for r in rows if r["sustained"]]
    verdict = {
        "mode": "open",
        "direct_dispatch_ms": round(direct_s * 1e3, 3),
        "p99_bound_ms": round(p99_bound_ms, 3),
        "calibration_qps": cal_qps,
        "max_sustained_qps": max(sustained) if sustained else 0.0,
    }
    if cal_qps is not None:
        half = min(rows, key=lambda r: abs(r["offered_qps"]
                                           - 0.5 * cal_qps))
        verdict["passed"] = bool(half["sustained"])
        verdict["decision"] = (
            "engine sustains 0.5x its closed-loop peak open-loop"
            if half["sustained"] else
            "REJECTION: queue cannot absorb 0.5x its own calibration "
            "traffic — re-tune max_wait_ms/buckets for this platform")
    print(json.dumps(verdict), flush=True)
    return rows


def fleet_scaling(model, pool, wait_ms: float, replicas_list, *,
                  chaos: bool):
    """The 1->N replica scaling curve + the pre-committed verdict, and
    optionally the kill-a-replica chaos run (module docstring)."""
    from kmeans_tpu.obs import metrics_registry as obs_metrics
    from kmeans_tpu.parallel.mesh import make_mesh
    from kmeans_tpu.serving import ServingFleet
    from kmeans_tpu.utils.faults import inject_replica_kill

    n_open = int(os.environ.get("SERVE_OPEN_REQS", 512))
    reqs = int(os.environ.get("SERVE_REQS", 64))
    mesh = make_mesh()

    # Committed offered rate: 0.5x a single-ENGINE closed-loop
    # calibration at 64 clients (the r12 rule's operating point), so
    # every fleet size is judged against the same absolute traffic.
    cal_engine = ServingEngine(mesh=mesh, max_wait_ms=wait_ms,
                               quality=False)
    cal_engine.add_model("serve", model)
    cal_engine.warmup()
    for _ in range(8):
        cal_engine.predict("serve", pool[:1])
    t0 = time.perf_counter()
    n_direct = 64
    for i in range(n_direct):
        cal_engine.predict("serve", pool[i % pool.shape[0]][None, :])
    direct_s = (time.perf_counter() - t0) / n_direct
    p99_bound_ms = wait_ms + 10 * direct_s * 1e3
    cal = run_level(cal_engine, pool, clients=64, reqs=reqs)
    cal_engine.close()
    rate = round(0.5 * cal["qps"], 1)
    print(json.dumps({"mode": "fleet-calibration", "rate_qps": rate,
                      "p99_bound_ms": round(p99_bound_ms, 3), **cal}),
          flush=True)

    rows = []
    for R in replicas_list:
        # Fresh routing state per level: the fleet's latency
        # histograms live in the process-wide registry under
        # replica-name keys, so a previous level's estimates would
        # otherwise pre-warm this one's router.
        obs_metrics.REGISTRY.reset()
        fleet = ServingFleet(R, mesh=mesh, max_wait_ms=wait_ms,
                             quality=False)
        fleet.add_model("serve", model)
        fleet.warmup()
        n_warm = min(128, n_open, max(8, int(2.0 * rate)))
        run_open_loop(fleet, pool, rate, n_warm)   # thread warm-up
        row = run_open_loop(fleet, pool, rate, n_open)
        st = fleet.stats()
        row.update({
            "mode": "fleet", "replicas": R,
            "routes": st["routes"], "sheds": st["sheds"],
            "redispatches": st["redispatches"],
            "sustained": bool(row["failed"] == 0
                              and row["p99_ms"] is not None
                              and row["p99_ms"] <= p99_bound_ms
                              and row["drain_ms"] <= p99_bound_ms),
        })
        print(json.dumps(row), flush=True)
        rows.append(row)
        fleet.close()

    base = rows[0]
    scaling_ok = all(r["achieved_qps"] >= 0.8 * base["achieved_qps"]
                     for r in rows)
    all_sustained = all(r["sustained"] for r in rows)
    verdict = {
        "mode": "fleet", "rate_qps": rate,
        "p99_bound_ms": round(p99_bound_ms, 3),
        "replicas": list(replicas_list),
        "qps_curve": [r["achieved_qps"] for r in rows],
        "p99_curve": [r["p99_ms"] for r in rows],
        "passed": bool(all_sustained and scaling_ok),
        "decision": (
            "fleet sustains the committed rate at every replica count "
            "and replication costs < 20% throughput"
            if all_sustained and scaling_ok else
            "REJECTION: " +
            ("a replica count failed to sustain the committed rate"
             if not all_sustained else
             "replication through the router costs >= 20% throughput")),
        "note": "in-process replicas share one backend on CPU — flat "
                "QPS(R) is the expected curve here; near-linear "
                "scaling needs one device set per replica (hardware "
                "row pinned)",
    }
    print(json.dumps(verdict), flush=True)

    if not chaos:
        return rows

    # Kill-a-replica chaos run (the ISSUE 17 acceptance pin): R=2 at
    # the committed rate, one replica killed after a quarter of the
    # traffic has dispatched; the router must finish the level with
    # ZERO failed requests and a bounded p99 excursion.
    no_chaos_p99 = rows[-1]["p99_ms"] if rows else None
    obs_metrics.REGISTRY.reset()
    fleet = ServingFleet(2, mesh=mesh, max_wait_ms=wait_ms,
                         quality=False)
    fleet.add_model("serve", model)
    fleet.warmup()
    # Threshold in engine-dispatch (coalesced batch) units, fleet-wide.
    # At the committed rate the queue coalesces deeply (measured 12-55
    # rows/dispatch here), so a whole level is only ~n/12 dispatches;
    # arm after 4 so the kill always lands with queued work in flight.
    with inject_replica_kill(fleet, after_dispatches=4) as rec:
        row = run_open_loop(fleet, pool, rate, n_open)
    st = fleet.stats()
    excursion_ok = (no_chaos_p99 is None or row["p99_ms"] is None
                    or row["p99_ms"] <= 5 * no_chaos_p99)
    chaos_row = {
        "mode": "fleet-chaos", "replicas": 2,
        "killed_replica": rec["replica"], "kill_fired": rec["killed"],
        "failed": row["failed"], "p99_ms": row["p99_ms"],
        "no_chaos_p99_ms": no_chaos_p99,
        "redispatches": st["redispatches"],
        "n_serving_after": st["n_serving"],
        "zero_failed": bool(row["failed"] == 0),
        "p99_excursion_bounded": bool(excursion_ok),
        "passed": bool(row["failed"] == 0 and rec["killed"]
                       and excursion_ok),
    }
    print(json.dumps(chaos_row), flush=True)
    fleet.close()
    assert chaos_row["passed"], \
        f"chaos run failed the committed rule: {chaos_row}"
    return rows


def main():
    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    n = int(os.environ.get("SERVE_N",
                           2_000_000 if on_accel else 200_000))
    d = int(os.environ.get("SERVE_D", 128 if on_accel else 32))
    k = int(os.environ.get("SERVE_K", 1024 if on_accel else 64))
    clients = [int(c) for c in os.environ.get(
        "SERVE_CLIENTS", "1,8,64,512").split(",")]
    reqs = int(os.environ.get("SERVE_REQS", 64))
    wait_ms = float(os.environ.get("SERVE_WAIT_MS", 2.0))

    mode = os.environ.get("SERVE_MODE", "closed")

    rng = np.random.default_rng(42)
    X = rng.uniform(-1.0, 1.0, size=(n, d)).astype(np.float32)
    if os.environ.get("SERVE_SWEEP", "") == "1":
        # ISSUE 7 workflow end to end: pick k by a batched multi-k
        # sweep, then load-test the selected model.
        ks = os.environ.get("SERVE_SWEEP_KRANGE", "4:65:4")
        sweep_res = KMeans(k=2, max_iter=5, seed=0,
                           empty_cluster="keep",
                           verbose=False).sweep(X, k_range=ks,
                                                criterion="inertia")
        model, k = sweep_res.best_model, sweep_res.selected_k
        print(json.dumps({"sweep_selected_k": k,
                          "sweep_dispatches": sweep_res.n_dispatches,
                          "k_range": ks}), flush=True)
    else:
        init = X[np.sort(rng.choice(n, size=k, replace=False))].copy()
        model = KMeans(k=k, max_iter=5, seed=0, init=init,
                       empty_cluster="keep", verbose=False).fit(X)
    pool = rng.uniform(-1.0, 1.0, size=(4096, d)).astype(np.float32)

    print(f"serving load: backend={backend} devices="
          f"{len(jax.devices())} model k={k} d={d} (fit on {n:,} rows), "
          f"{reqs} reqs/client, max_wait_ms={wait_ms}, mode={mode}",
          file=sys.stderr)

    if mode == "fleet":
        replicas_list = [int(r) for r in os.environ.get(
            "SERVE_REPLICAS", "1,2").split(",")]
        fleet_scaling(model, pool, wait_ms, replicas_list,
                      chaos=os.environ.get("SERVE_CHAOS", "") == "1")
        return

    engine = ServingEngine(max_wait_ms=wait_ms)
    engine.add_model("serve", model)
    engine.warmup()

    if mode == "open":
        open_loop_sweep(engine, pool, wait_ms)
        st = engine.stats()
        print(f"serving load: batch_fill={st['batch_fill']}",
              file=sys.stderr)
        engine.close()
        return

    rows = []
    for c in clients:
        row = run_level(engine, pool, c, reqs)
        row.update({"platform": backend,
                    "n_devices": len(jax.devices()),
                    "max_wait_ms": wait_ms, "k": k, "d": d})
        print(json.dumps(row), flush=True)
        rows.append(row)

    st = engine.stats()
    print(f"serving load: batch_fill={st['batch_fill']}",
          file=sys.stderr)
    engine.close()

    bar = [r for r in rows if r["clients"] >= 8]
    if bar:
        ok = all(r["speedup_vs_sequential"] >= 2.0 for r in bar)
        print(json.dumps({
            "decision": "micro-batching clears the 2x bar at >= 8 "
                        "concurrent clients" if ok else
                        "REJECTION: batched under 2x sequential — "
                        "re-tune max_wait_ms/buckets for this platform",
            "passed": ok,
            "platform": backend,
        }), flush=True)


if __name__ == "__main__":
    main()
