"""Serving load-generator harness (ISSUE 6): closed-loop concurrent
clients against the resident warm-kernel engine — the end-to-end QPS /
latency artifact behind the BASELINE.md r11 serving rows.

Method: one K-Means model resident in a ``ServingEngine``; C client
THREADS each submit single-row ``predict`` requests back-to-back
through the micro-batch queue (closed loop — a client's next request
leaves when its previous one completes, the standard way to measure a
latency/throughput curve without an open-loop arrival model), for a
fixed per-client request budget.  Concurrency sweeps 1/8/64/512
clients; per level the harness reports:

* p50/p99 request latency (submit -> result; the ``max_wait_ms``
  batching timer is PART of the number — a lone request waits up to
  the timer for co-batchable traffic, concurrent ones flush earlier on
  fill, so p50 DROPS as concurrency rises until dispatch cost
  dominates),
* aggregate QPS (total completed requests / wall),
* mean rows per dispatch (how well the queue coalesced — the
  batch-fill evidence),
* the sequential-dispatch baseline QPS at the same request count (one
  ``engine.predict`` per request, no queue) and the resulting speedup.

DECISION RULE (committed now, measured per platform): micro-batching
earns its complexity where concurrent traffic exists — the acceptance
bar is batched QPS >= 2x the sequential baseline at >= 8 concurrent
clients.  On the CPU container the bar is already cleared (~4x at 8,
published r11); the HARDWARE run (tunneled chip, ~70-100 ms dispatch
RTT — docs/PERFORMANCE.md) is where the amortization is existential:
sequential per-request QPS is bounded by ~1/RTT (~10-14 QPS) and the
batched path should clear 100x at 512 clients.  If hardware ever
measures batched < sequential at >= 8 clients, the queue defaults
(max_wait_ms, buckets) are wrong for that platform and the row must be
published as a rejection with the engine defaulting to direct
dispatch.

Run:  python experiments/exp_serving_load.py
Env:  SERVE_N / SERVE_D / SERVE_K (model shape), SERVE_CLIENTS
      (comma list, default 1,8,64,512), SERVE_REQS (per client,
      default 64), SERVE_WAIT_MS (default 2.0).
"""

import json
import os
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import numpy as np

from kmeans_tpu.models.kmeans import KMeans
from kmeans_tpu.serving import ServingEngine


def run_level(engine, pool, clients: int, reqs: int):
    """One closed-loop concurrency level; returns the metrics row."""
    lats = []
    lock = threading.Lock()
    start_gate = threading.Event()

    def client(cid: int):
        rng = np.random.default_rng(cid)
        mine = []
        start_gate.wait()
        for _ in range(reqs):
            row = pool[rng.integers(0, pool.shape[0])][None, :]
            t0 = time.perf_counter()
            engine.submit("serve", row).result(timeout=120.0)
            mine.append(time.perf_counter() - t0)
        with lock:
            lats.extend(mine)

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(clients)]
    for t in threads:
        t.start()
    d0 = engine.stats()["dispatches"]
    t0 = time.perf_counter()
    start_gate.set()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    d1 = engine.stats()["dispatches"]
    total = clients * reqs
    lats = np.sort(np.asarray(lats))

    # Sequential-dispatch baseline: same request count, one direct
    # dispatch each (no queue, no timer) from one thread.
    n_seq = min(total, 256)                 # bounded; per-request cost
    t0 = time.perf_counter()
    for i in range(n_seq):
        engine.predict("serve", pool[i % pool.shape[0]][None, :])
    seq_wall = time.perf_counter() - t0
    seq_qps = n_seq / seq_wall

    return {
        "clients": clients,
        "requests": total,
        "p50_ms": round(float(np.percentile(lats, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(lats, 99)) * 1e3, 3),
        "qps": round(total / wall, 1),
        "rows_per_dispatch": round(total / max(d1 - d0, 1), 2),
        "sequential_qps": round(seq_qps, 1),
        "speedup_vs_sequential": round((total / wall) / seq_qps, 2),
    }


def main():
    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    n = int(os.environ.get("SERVE_N",
                           2_000_000 if on_accel else 200_000))
    d = int(os.environ.get("SERVE_D", 128 if on_accel else 32))
    k = int(os.environ.get("SERVE_K", 1024 if on_accel else 64))
    clients = [int(c) for c in os.environ.get(
        "SERVE_CLIENTS", "1,8,64,512").split(",")]
    reqs = int(os.environ.get("SERVE_REQS", 64))
    wait_ms = float(os.environ.get("SERVE_WAIT_MS", 2.0))

    rng = np.random.default_rng(42)
    X = rng.uniform(-1.0, 1.0, size=(n, d)).astype(np.float32)
    init = X[np.sort(rng.choice(n, size=k, replace=False))].copy()
    model = KMeans(k=k, max_iter=5, seed=0, init=init,
                   empty_cluster="keep", verbose=False).fit(X)
    pool = rng.uniform(-1.0, 1.0, size=(4096, d)).astype(np.float32)

    print(f"serving load: backend={backend} devices="
          f"{len(jax.devices())} model k={k} d={d} (fit on {n:,} rows), "
          f"{reqs} reqs/client, max_wait_ms={wait_ms}", file=sys.stderr)
    engine = ServingEngine(max_wait_ms=wait_ms)
    engine.add_model("serve", model)
    engine.warmup()

    rows = []
    for c in clients:
        row = run_level(engine, pool, c, reqs)
        row.update({"platform": backend,
                    "n_devices": len(jax.devices()),
                    "max_wait_ms": wait_ms, "k": k, "d": d})
        print(json.dumps(row), flush=True)
        rows.append(row)

    st = engine.stats()
    print(f"serving load: batch_fill={st['batch_fill']}",
          file=sys.stderr)
    engine.close()

    bar = [r for r in rows if r["clients"] >= 8]
    if bar:
        ok = all(r["speedup_vs_sequential"] >= 2.0 for r in bar)
        print(json.dumps({
            "decision": "micro-batching clears the 2x bar at >= 8 "
                        "concurrent clients" if ok else
                        "REJECTION: batched under 2x sequential — "
                        "re-tune max_wait_ms/buckets for this platform",
            "passed": ok,
            "platform": backend,
        }), flush=True)


if __name__ == "__main__":
    main()
