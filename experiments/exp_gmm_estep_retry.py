"""GMM E-step re-probe under the r4 fold-aware tile rules (r4 VERDICT
#4): the r3 fused-Pallas rejection (exp_gmm_estep_pallas.py — 3.35 s vs
3.5 ms, a ~1000x scheduling gap) predates ``choose_tiles(fold=...)``;
and the XLA EM step's 14.2 ms/iter ~19% MFU accounting says the real
cost driver is the two moment matmuls pinned at ``Precision.HIGHEST``
(the 6-pass bf16_6x split, the price of variances that survive
``S2/R - mu^2`` cancellation — parallel/gmm_step.py:105-116).

Three measured questions, each with a decision rule:

1. **Moment-precision ladder** (XLA path): HIGHEST vs HIGH vs DEFAULT
   for the two moment matmuls, timing AND the r3 hardware failure probe
   (a cluster offset ~25 sigma from the centering shift; its fitted
   variance must stay within 5% of truth, not collapse toward
   reg_covar).  If a cheaper precision keeps the bound on REAL v5e
   matmuls, wire it into ``_estep_tile`` and take the speedup;
   if not, the HIGHEST pin stays with fresh numbers on record.

2. **Chunk budget sweep** around the r3 2^23-element rule at each
   precision (the de-fuse boundary may sit elsewhere once the moment
   matmuls change cost).

3. **The r3 Pallas kernel with r4 tile_n** (1024 instead of the r3
   VMEM-target rule): a cheap re-run that either shows the scheduling
   gap closing (then the full pipelining port is worth scoping) or
   refreshes the rejection under the current toolchain.

Shape: N=2M x D=128, k=256 diag (the published 14.2 ms/iter config,
docs/PERFORMANCE.md "The mixture family").

Run on TPU hardware:  python experiments/exp_gmm_estep_retry.py

MEASURED (TPU v5e via tunnel, 2026-07-31):

  precision ladder (full E-pass, marginal, chunk sweep at each):
                 16384     32768     65536     131072   var_err(25sig)
    HIGHEST     14.34     13.79     20.06     28.56     3.024e-2
    HIGH         9.69      9.01     14.78     27.23     3.024e-2
    DEFAULT      8.15      7.29     13.11     27.27     4.126e-2

  1. HIGH is INDISTINGUISHABLE from HIGHEST on the r3 failure probe
     (3.024e-2 vs 3.024e-2 max relative variance error — the probe's
     own sampling noise at n=262144) and 1.53x faster -> WIRED into
     _estep_tile (gmm_step.py).  DEFAULT degrades the probe (4.1e-2,
     still under the 5% bar but a real ~2.8e-2 marginal error) for
     only 1.24x more -> stays rejected.  At the time of this ladder,
     full/tied scatter moments kept HIGHEST (this ladder only probed
     the diag moment structure); the dedicated full-covariance ladder
     (exp_gmm_full_precision.py, same round) subsequently relaxed FULL
     to HIGH on its own 25-sigma survival probe — only TIED keeps
     HIGHEST (its cancellation runs through the loop-invariant total
     scatter no ladder has probed).
     Shipped-loop effect: 14.2 -> 8.37 ms/iter (~33% MFU) measured on
     the full device EM fit at this shape.
  2. Chunk 32768 stays optimal at EVERY precision (16384 within 8%,
     65536+ collapses) — the r3 2^23-element budget rule is refreshed,
     no change.
  3. The r3 Pallas kernel under the r4 tile_n=1024: 3350 -> 4.16 ms
     per 524288-row E-pass (chained marginal) — the r3 rejection was a
     TILE-RULE artifact, not kernel structure.  Still 1.2x behind the
     HIGHEST XLA pass and ~1.8x behind the newly-wired HIGH pass at
     the same size (the kernel serializes softmax against the moment
     matmuls that XLA overlaps) -> rejection REFRESHED with the gap
     explained; the r3 tile rule (13.66 ms) is retired either way.
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

N, D, K = 2_097_152, 128, 256
PEAK_TFLOPS = 197.0
REAL_TFLOP_PER_ITER = 8.0 * N * D * K / 1e12     # 2 logp + 2 moment mm


def estep_variant(x, w, means, inv_var, log_det, log_w, *, chunk,
                  precision):
    """Chunked diag E pass with a configurable moment-matmul precision
    (everything else identical to parallel.gmm_step._estep_tile)."""
    from kmeans_tpu.parallel.gmm_step import _log_prob_chunk

    k, d = means.shape                 # NOT the module globals: the
    n_chunks = x.shape[0] // chunk     # variance probe passes k=8
    xs = (x.reshape(n_chunks, chunk, d), w.reshape(n_chunks, chunk))

    def body(carry, ch):
        xc, wc = ch
        logp = _log_prob_chunk(xc, means, inv_var, log_det, log_w)
        m = jnp.max(logp, axis=1, keepdims=True)
        p = jnp.exp(logp - m)
        denom = jnp.sum(p, axis=1, keepdims=True)
        resp = p * (wc / denom[:, 0])[:, None]
        r, s1, s2, ll = carry
        return (r + jnp.sum(resp, axis=0),
                s1 + lax.dot_general(resp, xc, (((0,), (0,)), ((), ())),
                                     preferred_element_type=xc.dtype,
                                     precision=precision),
                s2 + lax.dot_general(resp, xc * xc,
                                     (((0,), (0,)), ((), ())),
                                     preferred_element_type=xc.dtype,
                                     precision=precision),
                ll + jnp.sum(jnp.where(wc > 0,
                                       (m[:, 0] + jnp.log(denom[:, 0]))
                                       * wc, 0.0))), None

    init = (jnp.zeros((k,), x.dtype), jnp.zeros((k, d), x.dtype),
            jnp.zeros((k, d), x.dtype), jnp.zeros((), x.dtype))
    out, _ = lax.scan(body, init, xs)
    return out


def bench_estep(x, w, params, *, chunk, precision, gap=80):
    """Marginal ms/E-pass, whole chain in one dispatch."""
    means, inv_var, log_det, log_w = params

    def many(n_it):
        @jax.jit
        def run(x, w, means):
            def body(i, means):
                r, s1, s2, ll = estep_variant(
                    x, w, means, inv_var, log_det, log_w,
                    chunk=chunk, precision=precision)
                # EVERY accumulator feeds the carry (an s1-only
                # dependency lets XLA dead-code-eliminate the second
                # HIGHEST moment matmul and the logsumexp — review r5:
                # the ladder would time half the work it claims).
                return means + 0.0 * ((s1 + s2) / jnp.maximum(
                    r, 1.0)[:, None] + ll)
            return jnp.sum(lax.fori_loop(0, n_it, body, means))

        float(run(x, w, means))
        reps = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(run(x, w, means))
            reps.append(time.perf_counter() - t0)
        return float(np.median(reps))

    t_small = many(2)
    t_big = many(2 + gap)
    return (t_big - t_small) / gap * 1e3


def variance_probe(precision):
    """The r3 hardware failure shape: one cluster offset ~25 sigma from
    the centering shift.  Returns max relative variance error."""
    rng = np.random.default_rng(0)
    n_small, k_small = 262_144, 8
    true_var = 4.0
    offsets = np.linspace(0, 50, k_small)          # sigmas from shift
    comp = rng.integers(0, k_small, n_small)
    x_np = (offsets[comp][:, None] * np.sqrt(true_var)
            + rng.normal(size=(n_small, D)) * np.sqrt(true_var))
    x = jnp.asarray(x_np, jnp.float32)
    w = jnp.ones((n_small,), jnp.float32)
    shift = jnp.mean(x, axis=0)
    means0 = jnp.asarray(
        offsets[:, None] * np.sqrt(true_var) * np.ones((k_small, D)),
        jnp.float32)
    params = (means0 - shift[None, :], jnp.full((k_small, D), 1 / true_var,
                                                jnp.float32),
              jnp.full((k_small,), D * np.log(true_var), jnp.float32),
              jnp.full((k_small,), -np.log(k_small), jnp.float32))

    @jax.jit
    def one_pass(xc, wc):
        return estep_variant(xc - shift[None, :], wc, *params,
                             chunk=32_768, precision=precision)

    r, s1, s2, _ = one_pass(x, w)
    mu = s1 / r[:, None]
    var = np.asarray(s2 / r[:, None] - mu * mu)
    return float(np.max(np.abs(var - true_var) / true_var))


def main():
    assert jax.default_backend() == "tpu", "run on TPU hardware"
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (N, D), jnp.float32)
    w = jnp.ones((N,), jnp.float32)
    rng = np.random.default_rng(1)
    means = jnp.asarray(rng.normal(size=(K, D)), jnp.float32)
    inv_var = jnp.ones((K, D), jnp.float32)
    log_det = jnp.zeros((K,), jnp.float32)
    log_w = jnp.full((K,), -np.log(K), jnp.float32)
    params = (means, inv_var, log_det, log_w)

    results = {}
    for prec_name, prec in [("HIGHEST", lax.Precision.HIGHEST),
                            ("HIGH", lax.Precision.HIGH),
                            ("DEFAULT", lax.Precision.DEFAULT)]:
        err = variance_probe(prec)
        # The accuracy answer stands alone: a reduced-N smoke run may
        # skip every timing chunk below, and question 1's decision
        # metric must never be computed-then-discarded (review r5).
        print(f"  {prec_name:<8} variance probe (25+ sigma offsets): "
              f"var_err={err:.2e}", flush=True)
        results[(prec_name, "var_err")] = err
        for chunk in (16_384, 32_768, 65_536, 131_072):
            if chunk > x.shape[0] or x.shape[0] % chunk:
                continue                  # reduced-N smoke runs
            ms = bench_estep(x, w, params, chunk=chunk, precision=prec)
            mfu = REAL_TFLOP_PER_ITER / (ms / 1e3) / PEAK_TFLOPS
            results[(prec_name, chunk)] = (ms, mfu, err)
            print(f"  {prec_name:<8} chunk={chunk:<7} {ms:7.2f} ms/pass "
                  f"{mfu:5.1%} MFU  var_err={err:.2e}", flush=True)

    # 3. The r3 Pallas kernel with the r4 row-tile (1024) instead of the
    # r3 VMEM-target rule: the r3 gap was ~1000x, so two synced single
    # dispatches rank it — no marginal needed unless it lands within 2x
    # of the XLA pass.
    try:
        import experiments.exp_gmm_estep_pallas as p3
        for tile_rule, label in [(p3._tile_n_for, "r3 tile rule"),
                                 (lambda d, k: 1024, "r4 tile_n=1024")]:
            p3._tile_n_for = tile_rule
            # _tile_n_for is read at trace time; same-shape re-calls
            # would hit the jit cache and silently reuse the old tile.
            p3.pallas_estep.clear_cache()
            n_small = 524_288                      # the r3 probe size
            xs, ws = x[:n_small], w[:n_small]
            shift = jnp.zeros((D,), jnp.float32)

            def one_sync():
                out = p3.pallas_estep(xs, ws, shift, means, inv_var,
                                      log_det, log_w)
                jax.tree_util.tree_map(lambda a: np.asarray(a), out)

            one_sync()                             # compile + warm
            t0 = time.perf_counter()
            one_sync()
            ms = (time.perf_counter() - t0) * 1e3
            if ms < 500.0:
                # Out of the r3 1000x regime: a single dispatch now
                # mostly measures the ~70-100 ms tunnel RTT, which
                # would mask a fixed kernel (review r5) — switch to the
                # chained marginal before applying any decision rule.
                def chain(n_it):
                    @jax.jit
                    def run(xs, ws, m):
                        def body(i, m):
                            r_, s1, s2, ll = p3.pallas_estep(
                                xs, ws, shift, m, inv_var, log_det,
                                log_w)
                            return m + 0.0 * (
                                (s1 + s2) / jnp.maximum(
                                    r_, 1.0)[:, None] + ll)
                        return jnp.sum(lax.fori_loop(0, n_it, body, m))
                    float(run(xs, ws, means))
                    t0 = time.perf_counter()
                    float(run(xs, ws, means))
                    return time.perf_counter() - t0
                gap = max(int(1.5 / max(ms / 1e3, 1e-4)), 4)
                ms = (chain(2 + gap) - chain(2)) / gap * 1e3
            print(f"  pallas [{label}] {ms:9.2f} ms per "
                  f"{n_small}x{D} k={K} E-pass (r3 recorded 3350 ms; "
                  f"XLA ~3.5 ms at this size)", flush=True)
    except Exception as e:
        print(f"  pallas re-run unavailable: {type(e).__name__}: {e}",
              flush=True)
    print(results)


if __name__ == "__main__":
    main()
