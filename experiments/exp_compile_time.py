"""Where does bench.py's "compile+warmup" wall time go? (r2 VERDICT #6)

BENCH_r01 reported 47.6 s compile+warmup at the 2M benchmark shape;
BENCH_r02 164.1 s; the r3 10M run 323.7 s.  This experiment decomposes
the time into its actual phases — host data generation, host->device
transfer of the points array, jit trace+lowering, backend (Mosaic+XLA)
compilation of BOTH while_loop programs, and first execution — and
measures the persistent-compilation-cache mitigation.

Run (on the TPU):   python experiments/exp_compile_time.py [N] [mode]
Second run reuses the cache dir and shows the compile-phase savings.
Env: EXP_CACHE_DIR (default /tmp/jax_cache_exp; delete it for a cold
measurement), EXP_NO_CACHE=1 disables the cache entirely.

Findings (v5e, 2026-07-30, N=2M D=128 k=1024, mode=pallas — recorded in
docs/PERFORMANCE.md "Time to first iteration"): see the doc table.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    mode_arg = sys.argv[2] if len(sys.argv) > 2 else "auto"
    cache_dir = os.environ.get("EXP_CACHE_DIR", "/tmp/jax_cache_exp")

    import jax
    if not os.environ.get("EXP_NO_CACHE"):
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        print(f"compilation cache: {cache_dir} "
              f"({'present' if os.path.isdir(cache_dir) else 'cold'})")
    else:
        print("compilation cache: DISABLED")

    from kmeans_tpu.ops.pallas_kernels import resolve_auto
    from kmeans_tpu.parallel import distributed as dist
    from kmeans_tpu.parallel.mesh import make_mesh, mesh_shape
    from kmeans_tpu.parallel.sharding import choose_chunk_size, shard_points

    d, k, iters = 128, 1024, 20
    mode = resolve_auto(n, d, k) if mode_arg == "auto" else mode_arg
    print(f"N={n} D={d} k={k} mode={mode} "
          f"backend={jax.default_backend()}")

    def t(label, fn):
        start = time.perf_counter()
        out = fn()
        dt = time.perf_counter() - start
        print(f"  {label:<42s} {dt:8.2f} s", flush=True)
        return out, dt

    total0 = time.perf_counter()
    rng = np.random.default_rng(42)
    X, _ = t("host data gen (rng.uniform)",
             lambda: rng.uniform(-1, 1, size=(n, d)).astype(np.float32))
    init = X[rng.choice(n, size=k, replace=False)].copy()

    mesh = make_mesh()
    data_shards, model_shards = mesh_shape(mesh)
    chunk = choose_chunk_size(-(-n // data_shards), k, d)

    (points, weights), _ = t("device_put (async dispatch)",
                             lambda: shard_points(X, mesh, chunk))
    # Force the actual HBM transfer before anything else is timed: a
    # scalar reduction must read every element.
    _, t_xfer = t("host->device transfer (forced by sum)",
                  lambda: float(jax.jit(lambda p: p.sum())(points)))
    cents = jax.device_put(dist.pad_centroids(init, model_shards),
                           dist.centroid_sharding(mesh))

    def build(max_iter):
        return dist.make_fit_fn(mesh, chunk_size=chunk, mode=mode,
                                k_real=k, max_iter=max_iter,
                                tolerance=1e-30, empty_policy="keep",
                                history_sse=False)

    fit_small, fit_big = build(2), build(2 + iters)
    # Pre-placed ('keep': unused) so first-exec timings see no transfer.
    seeds_s = jax.device_put(np.zeros((2,), np.uint32))
    seeds_b = jax.device_put(np.zeros((2 + iters,), np.uint32))

    lowered_small, _ = t("trace+lower fit(2)",
                         lambda: fit_small.lower(points, weights, cents,
                                                 seeds_s))
    _, t_c_small = t("backend compile fit(2)  [Mosaic+XLA]",
                     lowered_small.compile)
    lowered_big, _ = t(f"trace+lower fit({2 + iters})",
                       lambda: fit_big.lower(points, weights, cents,
                                             seeds_b))
    _, t_c_big = t(f"backend compile fit({2 + iters})",
                   lowered_big.compile)

    def run(fn, seeds):
        out = fn(points, weights, cents, seeds)
        return int(out[1])
    _, _ = t("first exec fit(2)", lambda: run(fit_small, seeds_s))
    _, _ = t(f"first exec fit({2 + iters})", lambda: run(fit_big, seeds_b))
    print(f"  {'TOTAL':<42s} {time.perf_counter() - total0:8.2f} s")
    print(f"\ncompile phases alone: {t_c_small + t_c_big:.1f} s; "
          f"transfer: {t_xfer:.1f} s")


if __name__ == "__main__":
    main()
