"""TIED-covariance moment-precision ladder (ISSUE 2 satellite; closes the
loop the r5 full-covariance ladder deliberately left open: "tied stays
HIGHEST everywhere: its cancellation runs through a loop-invariant total
scatter this ladder did not probe").

The tied M-step derives the shared covariance as

    Sigma = (T - sum_k R_k mu_k mu_k^T) / W,      mu_k = xsum_k / R_k

with ``T`` the loop-INVARIANT total scatter (one pass per fit, pinned at
HIGHEST regardless — no per-iteration speedup exists there) and ``xsum``
the per-iteration E-pass moment currently accumulated at HIGHEST
(parallel/gmm_step._scan_estats_tied).  The cancellation here is HARSHER
than the diag family's: at cluster offsets of ~50 sigma the between-
cluster part of T/W is ~600x the within-cluster variance being recovered,
and an xsum product-rounding error of relative 2^-8 becomes an absolute
covariance error of ~2|mu|^2*2^-8 — far above the truth.  Whether the
3-pass HIGH split (bf16_3x) is already exact ENOUGH is precisely what the
ladder must measure on hardware.

Two measured questions, decision rules committed BEFORE measuring (the
repo's ladder methodology, exp_gmm_estep_retry.py / exp_gmm_full_precision.py):

1. **Covariance-survival probe** per precision rung: the r3 failure
   shape (clusters offset up to ~50 sigma, true covariance 4*I), one
   tied E-pass with perfectly-specified parameters, T computed at
   HIGHEST, then ``Sigma = (T - sum_k R_k mu_k mu_k^T)/W``.  PASS =
   every diagonal within 5% of truth AND max |off-diagonal| within 5%
   of the true variance.  If HIGH passes at HIGHEST-equivalent error,
   wire HIGH into ``_scan_estats_tied``'s xsum (and the device tied
   loop's copy); if it degrades, pin the rejection with these numbers
   in docs/PERFORMANCE.md.

2. **Timing ladder**: marginal ms per tied E-pass at N=1M x D=64,
   k=32, whole chain in one dispatch, gap ramped to a ~1.5 s big chain
   (the r5 harness rule).  The xsum matmul is 6 effective bf16 passes
   at HIGHEST vs 3 at HIGH, so the available win is bounded by xsum's
   share of the pass (~1.3-1.6x expected at this shape).

Run on TPU hardware:  python experiments/exp_gmm_tied_precision.py
CPU mechanics smoke (rungs are identical by construction there — XLA CPU
executes exact f32 dots at every precision):
GMM_TIED_ALLOW_CPU=1 python experiments/exp_gmm_tied_precision.py

STATUS (2026-08-03, ISSUE 2 round): no TPU was reachable from this
container (CPU-only).  CPU smoke run below confirms the harness and the
by-construction CPU result (all rungs identical error, timing flat);
the hardware ladder is PINNED for the next hardware session — decision
rules above are committed, docs/PERFORMANCE.md carries the pin.

CPU smoke (2-core container, N scaled to 262144, probe shape unchanged;
measured 2026-08-03):
  HIGHEST  probe: diag_err=6.36e-03 offdiag_err=7.70e-03 (probe noise)
  HIGH     probe: diag_err=6.36e-03 offdiag_err=7.70e-03 (identical —
           exact f32 dots on CPU at every rung, by construction)
  DEFAULT  probe: diag_err=6.36e-03 offdiag_err=7.70e-03 (identical)
  timing: 538/464/351 ms/pass at 36-76% spread — shared-host noise, not
  a precision effect (CPU ignores the enum); no decision can be made
  off-hardware, which is exactly why the pin exists.
"""

import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

N, D, K = 1_048_576, 64, 32
PEAK_TFLOPS = 197.0
# xt transform 2*N*D^2 + cross 2*N*D*K + xsum 2*N*D*K real FLOPs/E-pass.
REAL_TFLOP_PER_PASS = (2.0 * N * D * D + 4.0 * N * D * K) / 1e12


def estep_tied_variant(x, w, means_t, prec_chol, log_det_half, log_w, *,
                       chunk, precision):
    """Chunked TIED E pass with configurable xsum moment precision
    (everything else identical to _scan_estats_tied)."""
    from kmeans_tpu.parallel.gmm_step import (_log_prob_tied_chunk,
                                              _softmax_resp)

    k, d = means_t.shape
    n_chunks = x.shape[0] // chunk
    xs = (x.reshape(n_chunks, chunk, d), w.reshape(n_chunks, chunk))

    def body(carry, ch):
        xc, wc = ch
        logp = _log_prob_tied_chunk(xc, means_t, prec_chol, log_det_half,
                                    log_w)
        resp, lse = _softmax_resp(logp, wc, 1)
        r, s1, ll = carry
        return (r + jnp.sum(resp, axis=0),
                s1 + lax.dot_general(resp, xc, (((0,), (0,)), ((), ())),
                                     preferred_element_type=xc.dtype,
                                     precision=precision),
                ll + jnp.sum(jnp.where(wc > 0, lse * wc, 0.0))), None

    init = (jnp.zeros((k,), x.dtype), jnp.zeros((k, d), x.dtype),
            jnp.zeros((), x.dtype))
    out, _ = lax.scan(body, init, xs)
    return out


def bench_pass(x, w, params, *, chunk, precision):
    """Marginal ms/E-pass, one dispatch, r5 gap-ramp rule."""
    from kmeans_tpu.benchmarks import measure_marginal

    means_t, prec_chol, log_det_half, log_w = params

    @jax.jit
    def run(x, w, means_t, n_it):
        def body(i, m):
            r, s1, ll = estep_tied_variant(
                x, w, m, prec_chol, log_det_half, log_w,
                chunk=chunk, precision=precision)
            # Accumulators feed the carry so nothing is DCE'd.
            return m + 0.0 * (s1 / jnp.maximum(r, 1.0)[:, None] + ll)
        return jnp.sum(lax.fori_loop(0, n_it, body, means_t))

    def timed(n_it):
        t0 = time.perf_counter()
        float(run(x, w, means_t, n_it))
        return time.perf_counter() - t0

    timed(2)
    t_small = timed(2)
    gap, TARGET, CAP = 16, 1.5, 100_000
    while True:
        t_big = timed(2 + gap)
        if t_big >= TARGET or gap >= CAP:
            break
        per_iter = max((t_big - t_small) / gap, 1e-9)
        gap = int(min(CAP, min(gap * 25, max(TARGET / per_iter, gap * 5))))
    margin, spread, _ = measure_marginal(
        lambda: timed(2), lambda: timed(2 + gap), reps=5)
    return margin / gap * 1e3, gap, spread


def survival_probe(precision, n_small=262_144):
    """r3 failure shape, tied edition: one E-pass with perfect
    parameters; T at HIGHEST (the shipped once-per-fit rule); returns
    (max diag rel err, max |offdiag|/var) of (T - sum R mu mu^T)/W."""
    rng = np.random.default_rng(0)
    k_small = 8
    true_var = 4.0
    offsets = np.linspace(0, 50, k_small)
    comp = rng.integers(0, k_small, n_small)
    x_np = (offsets[comp][:, None] * np.sqrt(true_var)
            + rng.normal(size=(n_small, D)) * np.sqrt(true_var))
    x = jnp.asarray(x_np, jnp.float32)
    w = jnp.ones((n_small,), jnp.float32)
    shift = jnp.mean(x, axis=0)
    xc_frame = x - shift[None, :]
    prec_chol = jnp.asarray(np.eye(D, dtype=np.float32)
                            / np.sqrt(true_var))
    means_c = (jnp.asarray(offsets[:, None] * np.sqrt(true_var)
                           * np.ones((k_small, D)), jnp.float32)
               - shift[None, :])
    means_t = means_c @ prec_chol
    log_det_half = jnp.asarray(-0.5 * D * np.log(true_var), jnp.float32)
    log_w = jnp.full((k_small,), -np.log(k_small), jnp.float32)

    @jax.jit
    def one_pass(xc, wc):
        r, s1, _ = estep_tied_variant(
            xc, wc, means_t, prec_chol, log_det_half, log_w,
            chunk=32_768, precision=precision)
        # Loop-invariant total scatter: HIGHEST always (once per fit).
        t = lax.dot_general(xc * wc[:, None], xc, (((0,), (0,)), ((), ())),
                            preferred_element_type=xc.dtype,
                            precision=lax.Precision.HIGHEST)
        return r, s1, t

    r, s1, t = one_pass(xc_frame, w)
    r64 = np.asarray(r, np.float64)
    mu = np.asarray(s1, np.float64) / r64[:, None]
    W = r64.sum()
    C = (np.asarray(t, np.float64)
         - (r64[:, None, None] * mu[:, :, None] * mu[:, None, :]).sum(0)) / W
    diag = np.diagonal(C)
    diag_err = float(np.max(np.abs(diag - true_var) / true_var))
    off = C - np.diag(np.diagonal(C))
    offdiag_err = float(np.max(np.abs(off)) / true_var)
    return diag_err, offdiag_err


def main():
    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu and not os.environ.get("GMM_TIED_ALLOW_CPU"):
        raise SystemExit(
            "run on TPU hardware (the rungs only differ there); "
            "GMM_TIED_ALLOW_CPU=1 runs the CPU mechanics smoke")
    n = N if on_tpu else min(N, 262_144)
    from kmeans_tpu.models.gmm import EM_CHUNK_BUDGET
    chunk = max(128, EM_CHUNK_BUDGET // max(K, D) // 8 * 8)
    chunk = min(chunk, n)

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (n, D), jnp.float32)
    w = jnp.ones((n,), jnp.float32)
    rng = np.random.default_rng(1)
    prec_chol = jnp.asarray(np.eye(D, dtype=np.float32))
    means_t = jnp.asarray(rng.normal(size=(K, D)), jnp.float32)
    log_det_half = jnp.zeros((), jnp.float32)
    log_w = jnp.full((K,), -np.log(K), jnp.float32)
    params = (means_t, prec_chol, log_det_half, log_w)

    print(f"shape: N={n} D={D} k={K} tied, chunk={chunk}, "
          f"backend={jax.default_backend()}", flush=True)
    for prec_name, prec in [("HIGHEST", lax.Precision.HIGHEST),
                            ("HIGH", lax.Precision.HIGH),
                            ("DEFAULT", lax.Precision.DEFAULT)]:
        diag_err, off_err = survival_probe(prec)
        print(f"  {prec_name:<8} probe: diag_err={diag_err:.2e} "
              f"offdiag_err={off_err:.2e}", flush=True)
        ms, gap, spread = bench_pass(x, w, params, chunk=chunk,
                                     precision=prec)
        mfu = REAL_TFLOP_PER_PASS * (n / N) / (ms / 1e3) / PEAK_TFLOPS
        print(f"  {prec_name:<8} {ms:7.2f} ms/pass {mfu:5.1%} MFU "
              f"(gap {gap}, spread {spread:.1%})", flush=True)


if __name__ == "__main__":
    main()
