"""Headline KMeans MFU decomposition (ISSUE 3 satellite / VERDICT weak
#8): the ~70% MFU headline has been flat since r2 and the remaining
~30% has only ever been ASSERTED ("argmin + scatter + DMA") — this
harness decomposes it by measurement.

Method: a fused device step cannot be timed phase-by-phase from the
host, so ``parallel.distributed.make_estep_phase_fn`` builds a ladder
of cumulative-prefix programs over the XLA matmul path —

  distance  the (chunk, k) distance matmul + one cheap tile reduction
  assign    + argmin / min over the tile
  reduce    + the one-hot scatter-sum matmul, counts, and the (k, D)
            cross-shard psum (= the full per-iteration stats pass)

— each measured as a marginal between a 2- and a (2+T)-iteration chain
(one dispatch each; the repo's standard dispatch-latency cancellation),
with reps interleaved ACROSS rungs and per-rep differences taken before
the median (``utils.profiling.measure_phase_ladder``).  Alongside the
ladder the fused Pallas kernel's full step (the shipped headline mode,
whose phases cannot be prefix-laddered) is measured with the same
marginal so the XLA ladder can be scaled onto it.

Caveats printed with the numbers: the 'assign'-'distance' difference is
argmin-minus-sum (a slight undercount of the argmin reduction); the
per-iteration psum/DMA lands in 'reduce'; and the residual between the
'reduce' rung and the published full-fit ms/iter is M-step + while_loop
overhead.

DECISION RULE (committed now, measured on hardware): decompose the
headline shape (10M x 128, k=1024).  If one phase owns >= 15% of the
step (>= half the idle 30%), that phase is the next schedule target and
an ISSUE should be cut for it (the r8 GMM pipelining is the template);
if no phase owns >= 15%, the ~70% ceiling is PINNED as measured —
docs/PERFORMANCE.md "The remaining 30%" records whichever lands.

Run on TPU hardware:  python experiments/exp_headline_decomposition.py
(CPU smoke runs a scaled-down shape to exercise the harness; a 2-core
container's numbers decompose XLA:CPU scheduling, not the chip.)
"""

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import numpy as np

from kmeans_tpu.benchmarks import (PEAK_TFLOPS, kmeans_flops_per_iter,
                                   step_mfu)
from kmeans_tpu.parallel import distributed as dist
from kmeans_tpu.parallel.mesh import make_mesh, mesh_shape
from kmeans_tpu.parallel.sharding import choose_chunk_size, shard_points
from kmeans_tpu.utils.profiling import (measure_phase_ladder,
                                        phase_ceiling_table,
                                        sanitize_json)


def main():
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        n, d, k, gap = 10_000_000, 128, 1024, 80
    else:
        n, d, k, gap = 200_000, 32, 64, 12
        print("CPU smoke run — harness exercise only; the decision rule "
              "is a hardware measurement.", flush=True)

    mesh = make_mesh()
    data_shards, model_shards = mesh_shape(mesh)
    chunk = choose_chunk_size(-(-n // data_shards), k, d)
    rng = np.random.default_rng(42)
    X = rng.uniform(-1, 1, size=(n, d)).astype(np.float32)
    pts, w = shard_points(X, mesh, chunk)
    cents = jax.device_put(
        dist.pad_centroids(X[:k].copy(), model_shards),
        dist.centroid_sharding(mesh))

    fns = {}
    for ph in dist.ESTEP_PHASES:
        fns[ph] = {m: dist.make_estep_phase_fn(
            mesh, chunk_size=chunk, n_iters=m, phase=ph)
            for m in (2, 2 + gap)}
        for m in (2, 2 + gap):
            float(fns[ph][m](pts, w, cents))         # compile + warm

    def marginal(ph):
        def measure():
            t0 = time.perf_counter()
            float(fns[ph][2](pts, w, cents))
            t_small = time.perf_counter() - t0
            t0 = time.perf_counter()
            float(fns[ph][2 + gap](pts, w, cents))
            return max(time.perf_counter() - t0 - t_small, 1e-9) / gap
        return measure

    ladder = measure_phase_ladder(
        [(ph, marginal(ph)) for ph in dist.ESTEP_PHASES], reps=5)
    full = ladder[-1]["cumulative"]
    flops = kmeans_flops_per_iter(n, d, k)   # distance + scatter matmuls
    # The publishable MEASURED-CEILING table (ISSUE 8c): per-phase ms,
    # share, and the implied whole-pass ceiling if that phase were
    # perfectly hidden — the honest upper bound of any schedule attack
    # on it — with the committed >= 15% actionability rule applied.
    table = phase_ceiling_table(
        ladder, flops_per_iter=flops,
        peak_tflops=PEAK_TFLOPS.get(jax.default_backend()))
    for row in table:
        mfu_txt = ("" if row["implied_ceiling_mfu"] is None
                   else f"; MFU ceiling {row['implied_ceiling_mfu']:.1%}")
        print(f"  {row['phase']:9s} {row['ms']:8.3f} ms/iter "
              f"({row['share']:5.1%}; if free "
              f"{row['implied_ceiling_speedup']:.3f}x{mfu_txt}; "
              f"{'ACTIONABLE' if row['actionable'] else 'pinned'}; "
              f"spread {row['spread']:.0%})", flush=True)
    mfu = step_mfu(flops, full)
    if on_tpu and mfu is not None:
        print(f"  XLA stats pass: {full * 1e3:.2f} ms/iter = {mfu:.1%} "
              f"MFU; DECISION RULE: a phase owning >= 15% of the step "
              f"is the next schedule target (the ISSUE 8 pipelined "
              f"Lloyd schedule + guarded bf16 rung are the committed "
              f"attacks — adopt at >= 5% measured, "
              f"BENCH_LLOYD=1/BENCH_GUARD=1), else the ceiling is "
              f"pinned as measured", flush=True)

    # The shipped headline mode for scale: the fused Pallas kernel's
    # full step, same marginal method (phases not separable).
    try:
        from kmeans_tpu.ops.pallas_kernels import resolve_auto
        mode = resolve_auto(n, d, k)
        if mode in dist.PALLAS_MODES:
            fit_s = dist.make_fit_fn(mesh, chunk_size=chunk, mode=mode,
                                     k_real=k, max_iter=2,
                                     tolerance=1e-30, empty_policy="keep",
                                     history_sse=False)
            fit_b = dist.make_fit_fn(mesh, chunk_size=chunk, mode=mode,
                                     k_real=k, max_iter=2 + gap,
                                     tolerance=1e-30, empty_policy="keep",
                                     history_sse=False)
            seeds_s = jax.device_put(np.zeros((2,), np.uint32))
            seeds_b = jax.device_put(np.zeros((2 + gap,), np.uint32))

            def timed(fn, seeds):
                t0 = time.perf_counter()
                out = fn(pts, w, cents, seeds)
                int(out[1])
                return time.perf_counter() - t0

            timed(fit_s, seeds_s), timed(fit_b, seeds_b)
            ms = []
            for _ in range(5):
                ms.append((timed(fit_b, seeds_b) - timed(fit_s, seeds_s))
                          / gap)
            pallas_iter = float(np.median(ms))
            print(f"  pallas full step ({mode}): "
                  f"{pallas_iter * 1e3:.2f} ms/iter "
                  f"(the shipped headline path — scale the XLA ladder "
                  f"shares onto this)", flush=True)
        else:
            print(f"  auto resolves to {mode!r} at this shape — the XLA "
                  f"ladder above IS the shipped path", flush=True)
    except Exception as e:                    # noqa: BLE001 — context only
        print(f"  pallas comparison skipped: {e}", flush=True)

    print(json.dumps(sanitize_json({
        "shape": [n, d, k], "chunk": chunk, "ladder": ladder,
        "ceiling_table": table,
        "decision_rules": {"phase_actionable_share": 0.15,
                           "pipelined_vs_serial_adopt": 1.05,
                           "bf16_guard_adopt": 1.05,
                           "chunk_resweep_adopt_shift": 0.03},
        "full_harness": "BENCH_PHASES=1 python bench.py (adds the "
                        "chunk-geometry re-sweep at this shape)",
    }), default=float))


if __name__ == "__main__":
    main()
