"""Responsibility-exp precision rung (ISSUE 3): bf16 exp after max
subtraction, decided by the 25-sigma survival probe — never by
extrapolation.

The E-step's VPU cost is one ``exp`` per (point, component) pair.
After max subtraction the argument is <= 0 and gmm_step's own analysis
says relative logp error ~2^-8 "barely moves a softmax" — but that is
an argument, not a measurement, and the r3 variance-collapse bug came
from exactly this kind of extrapolation.  ``_softmax_resp`` now takes
an ``exp_dtype`` rung (None = f32, the shipped default; bf16 the
candidate: round the subtracted argument to bf16, exp, widen back —
the normalizer sum/divide stay f32).

DECISION RULES — committed before measurement:

1. **Accuracy gate (runs on THIS container — bf16 rounding is real
   arithmetic on every backend, unlike the matmul precision flags CPU
   ignores).**  The r3 failure probe (clusters offset up to ~50 sigma
   from the centering shift; fitted variance must not collapse toward
   reg_covar): the bf16-exp rung's max relative variance error must
   stay (a) under the 5% bar and (b) within 1.5x the f32-exp baseline
   error on the same draw.  FAIL -> the rung is REJECTED outright and
   the knob documented as probe-rejected; the timing gate never runs.
2. **Timing gate (hardware only — the VPU transcendental rate is the
   quantity at stake and this container has no VPU).**  On TPU at
   2M x 128 k=256 diag, pipelined schedule: bf16 exp must beat f32 exp
   by >= 5% per E-pass (interleaved marginal ratio).  PASS both gates
   -> wire ``exp_dtype=bf16`` as the mixture default (one commit, both
   numbers in the message).  FAIL timing -> the rung stays available
   but default-OFF, rejection recorded with the measured ratio.

MEASURED — accuracy gate, this container (CPU, 2026-08-03; bf16
rounding is genuine arithmetic on every backend, so unlike the matmul
precision rungs this probe is decisive off-hardware):

  f32 exp   max relative variance error 3.024197e-02
  bf16 exp  max relative variance error 3.024197e-02  (ratio 1.000000)

(3.024e-2 is the probe's own sampling-noise floor — the same figure the
r5 HIGHEST/HIGH moment ladder bottomed out at on this draw shape.)  The
bf16 rounding of the POST-SUBTRACTION argument is invisible to six
digits of the probe statistic — the softmax is insensitive exactly as
the 2^-8 analysis predicted, but now it is a measurement.  ACCURACY
GATE: PASSED.  The rung therefore survives to the hardware timing
gate, which is pinned for the next hardware session; until it runs the
default stays ``exp_dtype=None`` (f32) — adopting on accuracy alone
would claim an unmeasured speedup.

Run:  python experiments/exp_gmm_exp_precision.py        (both gates on
TPU; accuracy gate only elsewhere)
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kmeans_tpu.parallel.gmm_step import _scan_estats

D = 128
ACCURACY_BAR = 0.05          # rule 1(a)
ACCURACY_RATIO_BAR = 1.5     # rule 1(b)
TIMING_BAR = 1.05            # rule 2


def survival_probe(exp_dtype):
    """The r3 hardware failure shape (exp_gmm_estep_retry.variance_probe
    lineage): clusters offset 0..50 sigma from the centering shift; one
    E pass through the REAL _scan_estats with the candidate exp rung;
    returns max relative variance error of the M-step variance."""
    rng = np.random.default_rng(0)
    n, k = 262_144, 8
    true_var = 4.0
    offsets = np.linspace(0, 50, k)
    comp = rng.integers(0, k, n)
    x_np = (offsets[comp][:, None] * np.sqrt(true_var)
            + rng.normal(size=(n, D)) * np.sqrt(true_var))
    x = jnp.asarray(x_np, jnp.float32)
    w = jnp.ones((n,), jnp.float32)
    shift = jnp.mean(x, axis=0)
    means_c = jnp.asarray(
        offsets[:, None] * np.sqrt(true_var) * np.ones((k, D)),
        jnp.float32) - shift[None, :]
    inv_var = jnp.full((k, D), 1 / true_var, jnp.float32)
    log_det = jnp.full((k,), D * np.log(true_var), jnp.float32)
    log_w = jnp.full((k,), -np.log(k), jnp.float32)

    @jax.jit
    def one_pass(x, w):
        return _scan_estats(x, w, means_c, inv_var, log_det, log_w,
                            shift, chunk_size=32_768, model_shards=1,
                            pipeline=1, exp_dtype=exp_dtype)

    st = one_pass(x, w)
    mu = st.xsum / st.resp_sum[:, None]
    var = np.asarray(st.x2sum / st.resp_sum[:, None] - mu * mu)
    return float(np.max(np.abs(var - true_var) / true_var))


def timing_gate():
    """Rule 2 (TPU only): interleaved marginal ratio of the pipelined
    E pass with f32 vs bf16 exp at 2M x 128 k=256."""
    n, k, chunk, gap = 2_097_152, 256, 32_768, 80
    x = jax.random.normal(jax.random.PRNGKey(0), (n, D), jnp.float32)
    w = jnp.ones((n,), jnp.float32)
    rng = np.random.default_rng(1)
    means = jnp.asarray(rng.normal(size=(k, D)), jnp.float32)
    inv_var = jnp.ones((k, D), jnp.float32)
    log_det = jnp.zeros((k,), jnp.float32)
    log_w = jnp.full((k,), -np.log(k), jnp.float32)
    shift = jnp.zeros((D,), jnp.float32)

    def build(n_it, exp_dtype):
        @jax.jit
        def run(x, w, m):
            def body(i, m):
                st = _scan_estats(x, w, m, inv_var, log_det, log_w,
                                  shift, chunk_size=chunk,
                                  model_shards=1, pipeline=1,
                                  exp_dtype=exp_dtype)
                return m + 0.0 * (st.loglik + jnp.sum(st.xsum)
                                  + jnp.sum(st.x2sum)
                                  + jnp.sum(st.resp_sum))
            return jnp.sum(lax.fori_loop(0, n_it, body, m))

        float(run(x, w, means))                  # compile + warm ONCE
        return run

    # Four programs, compiled once — re-jitting per rep would spend the
    # hardware session recompiling identical chains (review r8).
    progs = {(n_it, dt): build(n_it, dt)
             for n_it in (2, 2 + gap) for dt in (None, jnp.bfloat16)}

    def many(n_it, exp_dtype):
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(progs[(n_it, exp_dtype)](x, w, means))
            times.append(time.perf_counter() - t0)
        return float(np.median(times))

    ratios = []
    for _ in range(3):
        m32 = many(2 + gap, None) - many(2, None)
        mbf = many(2 + gap, jnp.bfloat16) - many(2, jnp.bfloat16)
        ratios.append(m32 / max(mbf, 1e-9))
    r = float(np.median(ratios))
    print(f"  timing gate: f32/bf16 E-pass ratio {r:.3f}x "
          f"(bar {TIMING_BAR:.2f}x) -> "
          f"{'ADOPT bf16 exp' if r >= TIMING_BAR else 'default stays f32'}",
          flush=True)


def main():
    err_f32 = survival_probe(None)
    err_bf16 = survival_probe(jnp.bfloat16)
    ratio = err_bf16 / max(err_f32, 1e-300)
    ok = err_bf16 <= ACCURACY_BAR and ratio <= ACCURACY_RATIO_BAR
    print(f"  f32  exp survival probe: var_err={err_f32:.3e}", flush=True)
    print(f"  bf16 exp survival probe: var_err={err_bf16:.3e} "
          f"(ratio {ratio:.3f}; bars: abs {ACCURACY_BAR}, ratio "
          f"{ACCURACY_RATIO_BAR})", flush=True)
    verdict = "PASSED" if ok else "FAILED — rung REJECTED"
    print(f"  ACCURACY GATE: {verdict}", flush=True)
    if ok and jax.default_backend() == "tpu":
        timing_gate()
    elif ok:
        print("  timing gate requires TPU hardware (VPU transcendental "
              "rate) — pinned for the next hardware session; default "
              "stays exp_dtype=None", flush=True)


if __name__ == "__main__":
    main()
