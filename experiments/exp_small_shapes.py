"""Small-D / small-k step variants (r4 VERDICT #3): the reference's own
bench shapes — T2 stress 100k x 10, k=5 (kmeans_spark.py:402-454) and
blobs1m 1M x 16, k=64 — sit in this repo's weakest MFU region (~3.6%
at blobs1m): at D=16 the distance matmul uses 16/128 of the MXU's
contraction depth and k=64 half its lanes, and the Pallas tier
correctly refuses (16x padding waste, ops/pallas_kernels.py:150).

This sweep measures, per shape, the fused one-pass step under:

  matmul        the shipped XLA path (baseline; chunked scan as shipped)
  direct        (n, k, D) differences on the VPU — no MXU at all; at
                tiny D the VPU's 8x-lower peak may still beat a mostly
                idle MXU
  matmul_bf16   bf16 cross-term (2x MXU rate on the same idle layout)
  packed        ROW-PACKING: fold P = 128//D_pad8 points into one
                128-lane register row and replace the two skinny
                matmuls with full-tile ones —
                  distances: (n/P, P*D) @ kron(I_P, C^T) -> (n/P, P*k),
                  scatter:   onehot_packed^T @ X_packed -> (P*k, P*D),
                             block-diagonal einsum 'akad->kd' extract.
                Same 8x FLOP overhead the idle MXU already paid, but in
                layouts XLA tiles at full rate; whether the conversion
                wins is exactly what this measures.
  chunk sweep   the shipped path at alternative scan chunk sizes (the
                default VMEM-budget chunk may leave scan overhead on
                the table at sub-ms steps)

Harness: every variant runs its whole iteration chain inside ONE
dispatch (lax.fori_loop with a data dependency through the centroid
update, exp_glove_mfu.py pattern — per-dispatch RTT through the tunnel
is ~70-100 ms vs sub-ms steps), scalar-transfer synced, median of 5,
iteration-gap marginal.

Decision rule (r4 VERDICT #3): a variant that beats the shipped path
>= 1.3x at a shape gets wired into ``resolve_auto``'s rule for that
region; target >= 2x at blobs1m.  Anything else: this file is the
measured rejection, results inline below.  ONLY EXACT variants are
wirable into ``auto`` (packed / chunk / direct): ``matmul_bf16``
changes boundary assignments (~2^-8 relative distance error) and the
library's default must stay exact — a bf16 win is reported as the
opt-in speedup it already is.

Run on TPU hardware:  python experiments/exp_small_shapes.py
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kmeans_tpu.ops.assign import assign_reduce

SHAPES = [
    ("blobs1m", 1_000_000, 16, 64),
    ("t2_stress", 100_000, 10, 5),
    ("mnist_shaped", 60_000, 784, 10),
]


def _round_up(v, m):
    return -(-v // m) * m


def packed_step(x, w, c, P):
    """Row-packed fused step: distances + argmin + one-hot stats with
    every matmul at full 128-lane width.  x:(n, D) with n % P == 0 and
    P*D giving full lanes; returns (sums, counts, sse)."""
    n, d = x.shape
    k = c.shape[0]
    acc = x.dtype
    xp = x.reshape(n // P, P * d)
    B = jnp.kron(jnp.eye(P, dtype=acc), c.T)            # (P*d, P*k)
    dots = (xp @ B).reshape(n // P, P, k)
    x2 = jnp.sum(x * x, axis=1).reshape(n // P, P, 1)
    c2 = jnp.sum(c * c, axis=1)
    d2 = x2 - 2.0 * dots + c2[None, None, :]
    labels = jnp.argmin(d2, axis=-1)                    # (n/P, P)
    mind2 = jnp.maximum(jnp.min(d2, axis=-1), 0.0)
    wp = w.reshape(n // P, P)
    oh = jax.nn.one_hot(labels, k, dtype=acc) * wp[..., None]
    ohp = oh.reshape(n // P, P * k)
    S = (ohp.T @ xp).reshape(P, k, P, d)                # full-tile scatter
    sums = jnp.einsum("akad->kd", S)                    # block-diag extract
    counts = jnp.sum(oh, axis=(0, 1))
    sse = jnp.sum(wp * mind2)
    return sums, counts, sse


def bench_variant(make_step, n, d, k, iters=None, gap=None):
    """Marginal ms/iteration of ``step(x, w, c) -> (sums, counts, sse)``
    chained through the Lloyd update inside one dispatch."""
    # Adaptive gap: aim the big chain at ~1.5 s wall (BASELINE.md rule).
    key = jax.random.PRNGKey(0)
    x = jax.random.uniform(key, (n, d), jnp.float32, -1.0, 1.0)
    w = jnp.ones((n,), jnp.float32)
    c0 = x[:k] * 1.0
    step = make_step

    def many(n_it):
        @jax.jit
        def run(x, w, c):
            def body(i, c):
                sums, counts, _ = step(x, w, c)
                return jnp.where(counts[:, None] > 0,
                                 sums / jnp.maximum(counts[:, None], 1.0),
                                 c).astype(c.dtype)
            return jnp.sum(lax.fori_loop(0, n_it, body, c))

        float(run(x, w, c0))                          # compile + warm
        reps = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(run(x, w, c0))
            reps.append(time.perf_counter() - t0)
        return float(np.median(reps))

    # Probe once to size the gap (~1.5 s big chain, capped for sanity).
    t1 = max(many(2) / 2, 1e-5)
    gap = gap or int(min(max(1.5 / t1, 8), 20_000))
    t_small = many(2)
    t_big = many(2 + gap)
    return (t_big - t_small) / gap * 1e3, gap


def main():
    assert jax.default_backend() == "tpu", "run on TPU hardware"
    results = {}
    for name, n, d, k in SHAPES:
        print(f"== {name}: N={n} D={d} k={k}", flush=True)
        from kmeans_tpu.parallel.sharding import choose_chunk_size
        auto_chunk = choose_chunk_size(n, k, d)

        def shipped(chunk, mode):
            n_pad = _round_up(n, chunk)

            def step(x, w, c):
                xr = jnp.pad(x, ((0, n_pad - n), (0, 0)))
                wr = jnp.pad(w, (0, n_pad - n))
                st = assign_reduce(xr, wr, c, chunk_size=chunk, mode=mode)
                return st.sums, st.counts, st.sse
            return step

        for mode in ("matmul", "direct", "matmul_bf16"):
            try:
                ms, gap = bench_variant(shipped(auto_chunk, mode), n, d, k)
                results[(name, mode)] = ms
                print(f"  {mode:<14} chunk={auto_chunk:<8} "
                      f"{ms:8.4f} ms/iter  (gap {gap})", flush=True)
            except Exception as e:
                print(f"  {mode:<14} FAILED: {type(e).__name__}: {e}",
                      flush=True)

        for chunk in (auto_chunk // 4, auto_chunk * 4):
            if chunk < 256 or chunk > n:   # chunk > n pads fake rows
                continue
            try:
                ms, gap = bench_variant(shipped(chunk, "matmul"), n, d, k)
                results[(name, f"matmul@{chunk}")] = ms
                print(f"  matmul         chunk={chunk:<8} "
                      f"{ms:8.4f} ms/iter  (gap {gap})", flush=True)
            except Exception as e:
                print(f"  matmul@{chunk} FAILED: {e}", flush=True)

        d_pad8 = _round_up(d, 8)
        P = max(128 // d_pad8, 1)
        if P > 1:
            n_packp = _round_up(n, P)

            def packed(x, w, c):
                xr = jnp.pad(x, ((0, n_packp - n), (0, d_pad8 - d)))
                wr = jnp.pad(w, (0, n_packp - n))
                cr = jnp.pad(c, ((0, 0), (0, d_pad8 - d)))
                sums, counts, sse = packed_step(xr, wr, cr, P)
                return sums[:, :d], counts, sse
            try:
                ms, gap = bench_variant(packed, n, d, k)
                results[(name, "packed")] = ms
                print(f"  packed(P={P:<3})  "
                      f"             {ms:8.4f} ms/iter  (gap {gap})",
                      flush=True)
            except Exception as e:
                print(f"  packed FAILED: {type(e).__name__}: {e}",
                      flush=True)

        base = results.get((name, "matmul"))
        if base:
            best = min((v, kk) for kk, v in results.items()
                       if kk[0] == name)
            print(f"  -> best {best[1][1]}: {base / best[0]:.2f}x vs "
                  f"shipped matmul", flush=True)
    print(results)


if __name__ == "__main__":
    main()
