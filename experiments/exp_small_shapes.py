"""Small-D / small-k step variants (r4 VERDICT #3): the reference's own
bench shapes — T2 stress 100k x 10, k=5 (kmeans_spark.py:402-454) and
blobs1m 1M x 16, k=64 — sit in this repo's weakest MFU region (~3.6%
at blobs1m): at D=16 the distance matmul uses 16/128 of the MXU's
contraction depth and k=64 half its lanes, and the Pallas tier
correctly refuses (16x padding waste, ops/pallas_kernels.py:150).

This sweep measures, per shape, the fused one-pass step under:

  matmul        the shipped XLA path (baseline; chunked scan as shipped)
  direct        (n, k, D) differences on the VPU — no MXU at all; at
                tiny D the VPU's 8x-lower peak may still beat a mostly
                idle MXU
  matmul_bf16   bf16 cross-term (2x MXU rate on the same idle layout)
  packed        ROW-PACKING: fold P = 128//D_pad8 points into one
                128-lane register row and replace the two skinny
                matmuls with full-tile ones —
                  distances: (n/P, P*D) @ kron(I_P, C^T) -> (n/P, P*k),
                  scatter:   onehot_packed^T @ X_packed -> (P*k, P*D),
                             block-diagonal einsum 'akad->kd' extract.
                Same 8x FLOP overhead the idle MXU already paid, but in
                layouts XLA tiles at full rate; whether the conversion
                wins is exactly what this measures.
  chunk sweep   the shipped path at alternative scan chunk sizes (the
                default VMEM-budget chunk may leave scan overhead on
                the table at sub-ms steps)

Harness: every variant runs its whole iteration chain inside ONE
dispatch (lax.fori_loop with a data dependency through the centroid
update, exp_glove_mfu.py pattern — per-dispatch RTT through the tunnel
is ~70-100 ms vs sub-ms steps), scalar-transfer synced, median of 5
interleaved pairs, iteration-gap marginal.  Two r5 fixes after the
first run produced garbage (negative marginals, a 5x disagreement with
the published blobs1m row): (1) the gap ramps like benchmarks.
bench_config — grow until the BIG chain's direct wall time reaches
~1.5 s — instead of sizing off an RTT-dominated 2-iteration probe
(gaps of 20-43 put sub-ms signals under the ±25 ms tunnel jitter);
(2) padding to the chunk multiple happens ONCE outside the chain, not
inside the loop body (an in-body jnp.pad re-copies the full dataset
every iteration — 64 MB/iter at blobs1m — which is not a cost the
shipped path pays: shard_points pads at placement time).

Decision rule (r4 VERDICT #3): a variant that beats the shipped path
>= 1.3x at a shape gets wired into ``resolve_auto``'s rule for that
region; target >= 2x at blobs1m.  Anything else: this file is the
measured rejection, results inline below.  ONLY EXACT variants are
wirable into ``auto`` (packed / chunk / direct): ``matmul_bf16``
changes boundary assignments (~2^-8 relative distance error) and the
library's default must stay exact — a bf16 win is reported as the
opt-in speedup it already is.

Run on TPU hardware:  python experiments/exp_small_shapes.py

MEASURED (TPU v5e via tunnel, 2026-07-31, fixed harness — gaps ramp to
a 1.5 s big chain; all spreads <= 1.8%):

  blobs1m (1M x 16, k=64), shipped auto chunk = 131072:
    matmul           0.5801 ms/iter   (matches the published 0.579 row)
    direct           1.8308           matmul_bf16  0.5811 (BW-bound: the
                                      MXU is not the limiter at D=16)
    packed(P=8)      1.9815           (the kron conversion costs more
                                      than the idle MXU it fills)
    chunk sweep      8192: 1.2625   16384: 1.2033   32768: 1.1726
                     65536: 0.5502  250000: 1.3360  524288: 0.5010
                     1000000 (SINGLE CHUNK, no scan): 0.3370  <- 1.72x
  t2_stress (100k x 10, k=5), shipped chunk = n = 100000 (single):
    matmul           0.0108 ms/iter   <- already the best variant
    direct 0.0603 · bf16 0.0108 · packed 0.0429 · all smaller chunks worse
  mnist_shaped (60k x 784, k=10), shipped chunk = n = 60000 (single):
    matmul           0.0643 ms/iter   <- best (published row: 0.0668)
    direct 0.6512 · bf16 0.0689 · chunks 3744/7496: ~1.0

CONCLUSIONS (wired r5):
  1. The ONLY variant clearing the 1.3x bar is "don't scan at all":
     single-chunk beats the 2^17-capped scan 1.72x at blobs1m, and the
     two shapes that already ran single-chunk (t2_stress, mnist) beat
     every chunked variant too.  The scan's value is bounding the
     (chunk, k) HBM temporaries — at n*k <= 2^26 elems (256 MB f32)
     that bound is unnecessary on a 16 GB chip.  choose_chunk_size now
     returns a single whole-dataset chunk in that region (the
     SINGLE_CHUNK_ELEMS budget); the scan rule is unchanged elsewhere
     (headline/glove shapes are far above the budget).
  2. Row-packing (the kron full-tile conversion) is a measured
     REJECTION: 3.4x slower than shipped at blobs1m, 4x at t2_stress —
     the pass is HBM-bandwidth-bound, so converting 8x FLOP overhead
     into full-rate tiles buys nothing the memory system can pay for.
  3. bf16 cross-terms: no effect at D<=16 (BW-bound), mild penalty at
     mnist (0.0689 vs 0.0643, extra convert pass on a compute-light
     shape) — stays opt-in, auto keeps f32.
  4. The non-monotonic chunk curve (65536 fast, 250000 slow, single
     fast) tracks XLA's fusion decisions, not a smooth overhead model —
     chunk-rule changes must be measured, not extrapolated.
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kmeans_tpu.ops.assign import assign_reduce

SHAPES = [
    ("blobs1m", 1_000_000, 16, 64),
    ("t2_stress", 100_000, 10, 5),
    ("mnist_shaped", 60_000, 784, 10),
]


def _round_up(v, m):
    return -(-v // m) * m


def packed_step(x, w, c, P):
    """Row-packed fused step: distances + argmin + one-hot stats with
    every matmul at full 128-lane width.  x:(n, D) with n % P == 0 and
    P*D giving full lanes; returns (sums, counts, sse)."""
    n, d = x.shape
    k = c.shape[0]
    acc = x.dtype
    xp = x.reshape(n // P, P * d)
    B = jnp.kron(jnp.eye(P, dtype=acc), c.T)            # (P*d, P*k)
    dots = (xp @ B).reshape(n // P, P, k)
    x2 = jnp.sum(x * x, axis=1).reshape(n // P, P, 1)
    c2 = jnp.sum(c * c, axis=1)
    d2 = x2 - 2.0 * dots + c2[None, None, :]
    labels = jnp.argmin(d2, axis=-1)                    # (n/P, P)
    mind2 = jnp.maximum(jnp.min(d2, axis=-1), 0.0)
    wp = w.reshape(n // P, P)
    oh = jax.nn.one_hot(labels, k, dtype=acc) * wp[..., None]
    ohp = oh.reshape(n // P, P * k)
    S = (ohp.T @ xp).reshape(P, k, P, d)                # full-tile scatter
    sums = jnp.einsum("akad->kd", S)                    # block-diag extract
    counts = jnp.sum(oh, axis=(0, 1))
    sse = jnp.sum(wp * mind2)
    return sums, counts, sse


def bench_variant(step, x, w, c0):
    """Marginal ms/iteration of ``step(x, w, c) -> (sums, counts, sse)``
    chained through the Lloyd update inside one dispatch.

    ``x``/``w`` are PRE-padded device arrays (padding belongs outside
    the timed chain).  The trip count is a traced scalar, so the whole
    gap ramp reuses ONE compiled while_loop program.  Gap rule =
    benchmarks.bench_config: grow (clamped 25x/step) until the big
    chain's direct wall time reaches ~1.5 s, then take the median of 5
    interleaved (small, big) marginals."""
    from kmeans_tpu.benchmarks import measure_marginal

    @jax.jit
    def run(x, w, c, n_it):
        def body(i, c):
            sums, counts, _ = step(x, w, c)
            return jnp.where(counts[:, None] > 0,
                             sums / jnp.maximum(counts[:, None], 1.0),
                             c).astype(c.dtype)
        return jnp.sum(lax.fori_loop(0, n_it, body, c))

    def timed(n_it):
        t0 = time.perf_counter()
        float(run(x, w, c0, n_it))
        return time.perf_counter() - t0

    timed(2)                                          # compile
    t_small = timed(2)                                # warm dispatch floor
    gap, TARGET, CAP = 64, 1.5, 2_000_000
    while True:
        t_big = timed(2 + gap)
        if t_big >= TARGET or gap >= CAP:
            break
        per_iter = max((t_big - t_small) / gap, 1e-9)
        gap = int(min(CAP, min(gap * 25, max(TARGET / per_iter, gap * 5))))
    margin, spread, _ = measure_marginal(
        lambda: timed(2), lambda: timed(2 + gap), reps=5)
    return margin / gap * 1e3, gap, spread


def _padded(x, w, n_pad, d_pad=None):
    n, d = x.shape
    d_pad = d_pad or d
    if n_pad == n and d_pad == d:
        return x, w
    xr = jnp.pad(x, ((0, n_pad - n), (0, d_pad - d)))
    wr = jnp.pad(w, (0, n_pad - n))
    return jax.device_put(xr), jax.device_put(wr)


def main():
    import os
    assert jax.default_backend() == "tpu", "run on TPU hardware"
    only = os.environ.get("SHAPES")          # e.g. SHAPES=blobs1m,t2_stress
    results = {}
    for name, n, d, k in SHAPES:
        if only and name not in only.split(","):
            continue
        print(f"== {name}: N={n} D={d} k={k}", flush=True)
        from kmeans_tpu.parallel.sharding import choose_chunk_size
        auto_chunk = choose_chunk_size(n, k, d)

        key = jax.random.PRNGKey(0)
        x = jax.random.uniform(key, (n, d), jnp.float32, -1.0, 1.0)
        w = jnp.ones((n,), jnp.float32)
        c0 = x[:k] * 1.0

        def shipped(chunk, mode):
            def step(x, w, c):
                st = assign_reduce(x, w, c, chunk_size=chunk, mode=mode)
                return st.sums, st.counts, st.sse
            return step

        def run_one(label, step, xr, wr):
            try:
                ms, gap, spread = bench_variant(step, xr, wr, c0)
                results[(name, label)] = ms
                print(f"  {label:<16} {ms:8.4f} ms/iter  "
                      f"(gap {gap}, spread {spread:.1%})", flush=True)
            except Exception as e:
                print(f"  {label:<16} FAILED: {type(e).__name__}: {e}",
                      flush=True)

        xr, wr = _padded(x, w, _round_up(n, auto_chunk))
        for mode in ("matmul", "direct", "matmul_bf16"):
            run_one(mode, shipped(auto_chunk, mode), xr, wr)

        for chunk in sorted({(c // 8) * 8 for c in
                             (auto_chunk // 16, auto_chunk // 8,
                              auto_chunk // 4, auto_chunk // 2,
                              auto_chunk * 4)}):
            if chunk < 2048 or chunk > n or chunk == auto_chunk:
                continue
            xr, wr = _padded(x, w, _round_up(n, chunk))
            run_one(f"matmul@{chunk}", shipped(chunk, "matmul"), xr, wr)

        d_pad8 = _round_up(d, 8)
        P = max(128 // d_pad8, 1)
        if P > 1:
            xr, wr = _padded(x, w, _round_up(n, P), d_pad8)
            cr = jnp.pad(c0, ((0, 0), (0, d_pad8 - d)))

            def packed(xp, wp, c):
                # c arrives (k, d_pad8) from the chain's own update of
                # the padded centroid table.
                sums, counts, sse = packed_step(xp, wp, c, P)
                return sums, counts, sse
            try:
                ms, gap, spread = bench_variant(packed, xr, wr, cr)
                results[(name, "packed")] = ms
                print(f"  packed(P={P:<3})    {ms:8.4f} ms/iter  "
                      f"(gap {gap}, spread {spread:.1%})", flush=True)
            except Exception as e:
                print(f"  packed FAILED: {type(e).__name__}: {e}",
                      flush=True)

        base = results.get((name, "matmul"))
        if base:
            best = min((v, kk) for kk, v in results.items()
                       if kk[0] == name)
            print(f"  -> best {best[1][1]}: {base / best[0]:.2f}x vs "
                  f"shipped matmul", flush=True)
    print(results)


if __name__ == "__main__":
    main()
