"""Time-to-solution at the headline config (r4 VERDICT #5): the
published story is steady-state ms/iter; this measures what a user
actually WAITS for — full ``fit()`` wall time including init, restarts,
and compile — and decomposes it.

Measured quantities (10M x 128, k=1024, data generated ON DEVICE,
device loop, tolerance tightened so every run does exactly
``max_iter`` iterations):

  init_forgy        resolve_init('forgy') alone (seeded k-row gather)
  init_kmeanspp     resolve_init('k-means||') alone — since ISSUE 2 the
                    ONE-DISPATCH device pipeline (plus _warm repeat)
  init_kmeanspp_legacy  the device=False per-round legacy engine (the
                    7.4 s-warm r5 number; plus _warm repeat)
  fit_cold          first fit() in the process with an EMPTY compilation
                    cache (compile + init + 20 iterations)
  fit_warm          same fit() again (program cached in-process)
  fit_warm_kmeanspp same but init='k-means||'
  fit_n_init4       n_init=4 BATCHED sweep (host_loop=False: one
                    dispatch, restart axis vmapped) — vs 4x a single fit
  persistent-cache  fit_cold in a SECOND process with the persistent
                    JAX compilation cache warm (the deployment story:
                    cold-process, warm-cache)

The reference's T5 times whole fits including startup
(kmeans_spark.py:575-579); BASELINE.md's "Time to solution" section
publishes these numbers so the headline claim rolls up to the same
end-to-end quantity.

Run on TPU hardware:  python experiments/exp_time_to_solution.py
   (optionally TTS_N / TTS_ITERS env overrides for smoke runs)
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

CACHE_DIR = "/tmp/kmeans_tpu_tts_cache"


def build_ds(n, d, k):
    """Headline dataset generated on device, sharded, zero upload
    (bench.py pattern)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kmeans_tpu.parallel.mesh import DATA_AXIS, make_mesh, mesh_shape
    from kmeans_tpu.parallel.sharding import (ShardedDataset,
                                              choose_chunk_size)

    mesh = make_mesh()
    data_shards, _ = mesh_shape(mesh)
    chunk = choose_chunk_size(-(-n // data_shards), k, d)
    n_pad = -(-n // (data_shards * chunk)) * (data_shards * chunk)
    gen = jax.jit(
        lambda key: (jax.random.uniform(key, (n_pad, d), jnp.float32,
                                        -1.0, 1.0),
                     (jnp.arange(n_pad) < n).astype(jnp.float32)),
        out_shardings=(NamedSharding(mesh, P(DATA_AXIS, None)),
                       NamedSharding(mesh, P(DATA_AXIS))))
    pts, w = gen(jax.random.PRNGKey(42))
    pts.block_until_ready()
    # Scalar-transfer sync (block_until_ready is unreliable on the
    # tunneled platform).
    float(w[0])
    return ShardedDataset(pts, w, n, chunk, mesh), mesh


def run_measurements():
    import jax
    import numpy as np

    from kmeans_tpu import KMeans
    from kmeans_tpu.models.init import resolve_init

    n = int(os.environ.get("TTS_N", 10_000_000))
    d, k = 128, 1024
    iters = int(os.environ.get("TTS_ITERS", 20))
    out = {"n": n, "d": d, "k": k, "iters": iters,
           "backend": jax.default_backend()}

    t0 = time.perf_counter()
    ds, mesh = build_ds(n, d, k)
    out["data_gen"] = time.perf_counter() - t0

    kw = dict(k=k, max_iter=iters, tolerance=1e-30, seed=42,
              empty_cluster="keep", verbose=False, host_loop=False,
              mesh=mesh, compute_sse=False)

    def timed(label, fn):
        t0 = time.perf_counter()
        r = fn()
        out[label] = time.perf_counter() - t0
        print(f"  {label:<22} {out[label]:8.2f} s", flush=True)
        return r

    # Init costs alone (seeded; sync via host materialization).  Since
    # ISSUE 2 'k-means||' resolves to the ONE-DISPATCH device pipeline;
    # the legacy per-round engine is timed alongside as the before/after
    # (its r5 warm in-process number was 7.4 s at this shape — the cost
    # the pipeline exists to remove).  Warm repeats (program already
    # compiled) are the deployment-relevant quantity for both.
    from kmeans_tpu.models.init import kmeans_parallel_init
    timed("init_forgy", lambda: np.asarray(
        resolve_init("forgy", ds, k, 42)))
    timed("init_kmeanspp", lambda: np.asarray(
        resolve_init("k-means||", ds, k, 42)))
    timed("init_kmeanspp_warm", lambda: np.asarray(
        resolve_init("k-means||", ds, k, 43)))
    timed("init_kmeanspp_legacy", lambda: np.asarray(
        kmeans_parallel_init(ds, k, 42, device=False)))
    timed("init_kmeanspp_legacy_warm", lambda: np.asarray(
        kmeans_parallel_init(ds, k, 43, device=False)))

    # Cold fit: this process has an empty compilation cache (main()
    # pointed JAX_COMPILATION_CACHE_DIR at a fresh dir).
    km = KMeans(init="forgy", **kw)
    timed("fit_cold", lambda: km.fit(ds))
    assert km.iterations_run == iters
    timed("fit_warm", lambda: KMeans(init="forgy", **kw).fit(ds))
    timed("fit_warm_kmeanspp",
          lambda: KMeans(init="k-means||", **kw).fit(ds))
    timed("fit_n_init4",
          lambda: KMeans(init="forgy", n_init=4, **kw).fit(ds))
    print(json.dumps(out), flush=True)
    return out


def main():
    if os.environ.get("TTS_CHILD"):
        run_measurements()
        return
    # Fresh persistent cache so fit_cold is a TRUE cold compile, then a
    # second child measures the cold-process/warm-cache deployment story.
    import shutil
    shutil.rmtree(CACHE_DIR, ignore_errors=True)
    env = dict(os.environ, TTS_CHILD="1",
               JAX_COMPILATION_CACHE_DIR=CACHE_DIR)
    for tag in ("cold-cache process", "warm-cache process"):
        print(f"== {tag}", flush=True)
        r = subprocess.run([sys.executable, __file__], env=env,
                           capture_output=True, text=True, timeout=3600)
        sys.stderr.write(r.stderr[-2000:])
        print(r.stdout, flush=True)
        if r.returncode != 0:
            raise SystemExit(f"{tag} failed rc={r.returncode}")


if __name__ == "__main__":
    main()
