"""FULL-covariance moment-precision ladder (r5 follow-up to
exp_gmm_estep_retry.py): the diag ladder measured HIGH (3-pass bf16_3x)
indistinguishable from HIGHEST (6-pass bf16_6x ~ f32) on the r3
variance-collapse shape and 1.53x faster, and it was wired into
_estep_tile.  The FULL-covariance scatter moment
(``einsum('ck,cd,ce->kde')``, parallel/gmm_step._scan_estats_full) kept
HIGHEST because its cancellation structure — the covariance is
``scatter/R - mu mu^T`` — was NOT probed.  This experiment probes it.

Two measured questions, same decision rules as the diag ladder:

1. **Covariance-survival probe** at each precision: the r3 failure
   shape (clusters offset up to ~50 sigma from the centering shift,
   true covariance 4*I), one E-pass with perfectly-specified
   parameters, then ``C_k = scatter_k/r_k - mu_k mu_k^T``.  PASS =
   every diagonal within 5% of truth AND max |off-diagonal| within 5%
   of the true variance (the full-covariance failure mode has an extra
   axis: off-diagonal garbage, not just diagonal collapse).  If HIGH
   passes at HIGHEST-equivalent error, wire HIGH into the scatter/xsum
   moments of ``_scan_estats_full`` (the tied path's per-fit total
   scatter is loop-INVARIANT — one pass per fit, no per-iteration
   speedup to claim — and stays HIGHEST either way).

2. **Timing ladder**: marginal ms per full E-pass at N=1M x D=64,
   k=32 full components (tile width k*D = 2048 -> EM-budget chunk
   4096), whole chain in one dispatch, gap ramped to a ~1.5 s big
   chain (the r5 harness rule).

Run on TPU hardware:  python experiments/exp_gmm_full_precision.py
(decision rules above were committed BEFORE measuring).

MEASURED (TPU v5e via tunnel, 2026-07-31, N=1M x D=64, k=32 full,
chunk=4096):

  precision   ms/E-pass   MFU    probe diag_err   probe offdiag_err
    HIGHEST     27.50    10.1%      2.07e-02          2.39e-02
    HIGH        17.99    15.5%      2.53e-02          2.27e-02
    DEFAULT     11.91    23.4%      2.04e-02          2.40e-02

  1. HIGH passes at HIGHEST-equivalent error (all probe stats ~2e-2 =
     the probe's own noise scale for a max over k*D^2 entries; the 5%
     bar is cleared 2x over) and is 1.53x faster -> WIRED into
     _scan_estats_full's moments (gmm_step.py).
  2. DEFAULT ALSO passes this probe (2.04e-2/2.40e-2) — unlike the
     diag ladder, where it showed real marginal degradation.  Kept
     rejected anyway: the full probe's max-statistic is visibly
     jumpier (HIGH's diag_err 2.53e-2 > HIGHEST's 2.07e-2 is already
     probe noise), a single passing run is not evidence DEFAULT's
     known 2^-8 product rounding is safe across shapes, and the diag
     family's measured degradation is the controlling precedent.
  3. Tied stays HIGHEST everywhere: its per-iteration xsum feeds the
     T - sum R_k mu mu^T cancellation through a DIFFERENT structure
     (loop-invariant total scatter) that this ladder did not probe,
     and its total-scatter term is once-per-fit (no per-iteration
     speedup to claim).
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

N, D, K = 1_048_576, 64, 32
PEAK_TFLOPS = 197.0
# logp transform einsum 2*N*k*D^2 + scatter einsum 2*N*k*D^2 (+ small
# xsum/quad terms, uncounted) per E-pass.
REAL_TFLOP_PER_PASS = 4.0 * N * K * D * D / 1e12


def estep_full_variant(x, w, means, prec_chol, log_det_half, log_w, *,
                      chunk, precision):
    """Chunked FULL-covariance E pass with configurable moment
    precision (everything else identical to _scan_estats_full)."""
    from kmeans_tpu.parallel.gmm_step import (_log_prob_full_chunk,
                                              _softmax_resp)

    k, d = means.shape
    n_chunks = x.shape[0] // chunk
    xs = (x.reshape(n_chunks, chunk, d), w.reshape(n_chunks, chunk))

    def body(carry, ch):
        xc, wc = ch
        logp = _log_prob_full_chunk(xc, means, prec_chol, log_det_half,
                                    log_w)
        resp, lse = _softmax_resp(logp, wc, 1)
        r, s1, sc, ll = carry
        return (r + jnp.sum(resp, axis=0),
                s1 + lax.dot_general(resp, xc, (((0,), (0,)), ((), ())),
                                     preferred_element_type=xc.dtype,
                                     precision=precision),
                sc + jnp.einsum("ck,cd,ce->kde", resp, xc, xc,
                                preferred_element_type=xc.dtype,
                                precision=precision),
                ll + jnp.sum(jnp.where(wc > 0, lse * wc, 0.0))), None

    init = (jnp.zeros((k,), x.dtype), jnp.zeros((k, d), x.dtype),
            jnp.zeros((k, d, d), x.dtype), jnp.zeros((), x.dtype))
    out, _ = lax.scan(body, init, xs)
    return out


def bench_pass(x, w, params, *, chunk, precision):
    """Marginal ms/E-pass, whole chain in one dispatch, gap ramped to a
    ~1.5 s big chain (the r5 harness rule)."""
    from kmeans_tpu.benchmarks import measure_marginal

    means, prec_chol, log_det_half, log_w = params

    @jax.jit
    def run(x, w, means, n_it):
        def body(i, means):
            r, s1, sc, ll = estep_full_variant(
                x, w, means, prec_chol, log_det_half, log_w,
                chunk=chunk, precision=precision)
            # Every accumulator feeds the carry so nothing is DCE'd.
            return means + 0.0 * (s1 / jnp.maximum(r, 1.0)[:, None]
                                  + jnp.einsum("kdd->kd", sc) + ll)
        return jnp.sum(lax.fori_loop(0, n_it, body, means))

    def timed(n_it):
        t0 = time.perf_counter()
        float(run(x, w, means, n_it))
        return time.perf_counter() - t0

    timed(2)
    t_small = timed(2)
    gap, TARGET, CAP = 16, 1.5, 100_000
    while True:
        t_big = timed(2 + gap)
        if t_big >= TARGET or gap >= CAP:
            break
        per_iter = max((t_big - t_small) / gap, 1e-9)
        gap = int(min(CAP, min(gap * 25, max(TARGET / per_iter, gap * 5))))
    margin, spread, _ = measure_marginal(
        lambda: timed(2), lambda: timed(2 + gap), reps=5)
    return margin / gap * 1e3, gap, spread


def survival_probe(precision):
    """r3 failure shape, full-covariance edition: one E-pass with
    perfect parameters; returns (max diag rel err, max |offdiag|/var)."""
    rng = np.random.default_rng(0)
    n_small, k_small = 262_144, 8
    true_var = 4.0
    offsets = np.linspace(0, 50, k_small)
    comp = rng.integers(0, k_small, n_small)
    x_np = (offsets[comp][:, None] * np.sqrt(true_var)
            + rng.normal(size=(n_small, D)) * np.sqrt(true_var))
    x = jnp.asarray(x_np, jnp.float32)
    w = jnp.ones((n_small,), jnp.float32)
    shift = jnp.mean(x, axis=0)
    means0 = np.asarray(offsets[:, None] * np.sqrt(true_var)
                        * np.ones((k_small, D)), np.float32)
    prec_chol = np.broadcast_to(
        np.eye(D, dtype=np.float32) / np.sqrt(true_var),
        (k_small, D, D)).copy()
    log_det_half = np.full((k_small,), -0.5 * D * np.log(true_var),
                           np.float32)
    log_w = np.full((k_small,), -np.log(k_small), np.float32)
    params = (jnp.asarray(means0) - shift[None, :], jnp.asarray(prec_chol),
              jnp.asarray(log_det_half), jnp.asarray(log_w))

    @jax.jit
    def one_pass(xc, wc):
        return estep_full_variant(xc - shift[None, :], wc, *params,
                                  chunk=32_768, precision=precision)

    r, s1, sc, _ = one_pass(x, w)
    mu = np.asarray(s1 / r[:, None], np.float64)
    C = np.asarray(sc / r[:, None, None], np.float64) \
        - mu[:, :, None] * mu[:, None, :]
    diag = np.diagonal(C, axis1=1, axis2=2)
    diag_err = float(np.max(np.abs(diag - true_var) / true_var))
    off = C.copy()
    off[:, np.arange(D), np.arange(D)] = 0.0
    offdiag_err = float(np.max(np.abs(off)) / true_var)
    return diag_err, offdiag_err


def main():
    assert jax.default_backend() == "tpu", "run on TPU hardware"
    from kmeans_tpu.models.gmm import EM_CHUNK_BUDGET
    chunk = max(128, EM_CHUNK_BUDGET // (K * D) // 8 * 8)

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (N, D), jnp.float32)
    w = jnp.ones((N,), jnp.float32)
    rng = np.random.default_rng(1)
    means = jnp.asarray(rng.normal(size=(K, D)), jnp.float32)
    prec_chol = jnp.asarray(np.broadcast_to(
        np.eye(D, dtype=np.float32), (K, D, D)).copy())
    log_det_half = jnp.zeros((K,), jnp.float32)
    log_w = jnp.full((K,), -np.log(K), jnp.float32)
    params = (means, prec_chol, log_det_half, log_w)

    print(f"shape: N={N} D={D} k={K} full, chunk={chunk}", flush=True)
    for prec_name, prec in [("HIGHEST", lax.Precision.HIGHEST),
                            ("HIGH", lax.Precision.HIGH),
                            ("DEFAULT", lax.Precision.DEFAULT)]:
        diag_err, off_err = survival_probe(prec)
        print(f"  {prec_name:<8} probe: diag_err={diag_err:.2e} "
              f"offdiag_err={off_err:.2e}", flush=True)
        ms, gap, spread = bench_pass(x, w, params, chunk=chunk,
                                     precision=prec)
        mfu = REAL_TFLOP_PER_PASS / (ms / 1e3) / PEAK_TFLOPS
        print(f"  {prec_name:<8} {ms:7.2f} ms/pass {mfu:5.1%} MFU "
              f"(gap {gap}, spread {spread:.1%})", flush=True)


if __name__ == "__main__":
    main()
