"""Pallas fused-kernel variant lab (real TPU).

Builds parameterized variants of the fused assign+reduce kernel and
times them with the marginal method (chained fori_loop(2) vs
fori_loop(2+T) with a real centroid update between passes).  Each
variant is correctness-checked against a NumPy oracle on a small slice
before timing.

Knobs per variant:
  tile_n, tile_k      - grid/block tiling
  pipe                - software-pipeline: accumulate tile i-1's one-hot
                        scatter while tile i's distance matmul runs
  man_argmin          - manual min + select-iota-min instead of lax.argmin
  ones_col            - counts via a constant-1 column in the scatter
                        matmul (needs d < d_pad) instead of a VPU sum
  bf16                - bf16 matmul inputs
  vmem_mb             - Mosaic scoped-VMEM limit

Usage: python experiments/exp_pallas_kernel.py N D K T spec1 spec2 ...
  spec: name=tile_n,tile_k,flags   flags subset of {p,m,o,b}
  e.g.  pipe1=512,3072,pmo
"""
from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BIG = 1e30          # added to h for padded centroid rows
IDX_BIG = 2 ** 30


def _round_up(a, b):
    return -(-a // b) * b


def build_kernel(*, tile_n, tile_k, pipe, man_argmin, ones_col, bf16,
                 fold_h, vmem_mb, n_pad, d, d_pad, k, k_pad):
    """Returns fn(x_pad (n_pad, d_pad), w (n_pad,), c_pad (k_pad, d_pad),
    h (1, k_pad)) -> (labels, mind2, sums (k_pad, d_pad), counts)."""
    k_tiles = k_pad // tile_k
    n_tiles = n_pad // tile_n
    mm = jnp.bfloat16 if bf16 else jnp.float32
    d_col = d  # column used for counts when ones_col

    def argmin_tiles(x, c_ref, h_ref):
        """(best, mind2h) over all k tiles; d2h = h - x @ c.T.

        With fold_h, x must carry 1.0 in column d and c_ref carries -h in
        column d, so the MXU emits x@c.T - h directly and the kernel
        argmaxes it (no (n, k) subtract)."""
        def one(off, carry):
            best, mind2h = carry
            c = c_ref[pl.ds(off, tile_k), :]
            xc = lax.dot_general(x.astype(mm), c.astype(mm),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
            if fold_h:
                d2h = xc                                    # actually -d2h
                ids = lax.broadcasted_iota(jnp.int32, (tile_n, tile_k), 1)
                lb = lax.argmax(d2h, 1, jnp.int32)
                m = -jnp.max(d2h, axis=1)
            else:
                h = h_ref[:, pl.ds(off, tile_k)]            # (1, tile_k)
                d2h = h - xc                                # (tile_n, tile_k)
                ids = lax.broadcasted_iota(jnp.int32, (tile_n, tile_k), 1)
                if man_argmin:
                    m = jnp.min(d2h, axis=1)
                    lb = jnp.min(jnp.where(d2h == m[:, None], ids,
                                           IDX_BIG), axis=1)
                else:
                    lb = lax.argmin(d2h, 1, jnp.int32)
                    m = jnp.min(d2h, axis=1)
            upd = m < mind2h
            best = jnp.where(upd, lb + off, best)
            return best, jnp.where(upd, m, mind2h)
        carry = (jnp.zeros((tile_n,), jnp.int32),
                 jnp.full((tile_n,), jnp.inf, jnp.float32))
        for kt in range(k_tiles):
            carry = one(kt * tile_k, carry)
        return carry

    def accum(best, x, w, sums_ref, counts_ref):
        """One-hot scatter of one tile into the accumulators."""
        if fold_h:
            x_aug = x                       # ones column already in x
        elif ones_col:
            lanes = lax.broadcasted_iota(jnp.int32, (tile_n, d_pad), 1)
            x_aug = jnp.where(lanes == d_col, 1.0, x)
        else:
            x_aug = x
        for kt in range(k_tiles):
            off = kt * tile_k
            ids = lax.broadcasted_iota(jnp.int32, (tile_n, tile_k), 1) + off
            ohw = jnp.where(best[:, None] == ids, w, 0.0)   # (tile_n, tile_k)
            sums_ref[pl.ds(off, tile_k), :] += lax.dot_general(
                ohw.astype(mm), x_aug.astype(mm), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            if not ones_col:
                counts_ref[:, pl.ds(off, tile_k)] += jnp.sum(
                    ohw, axis=0, keepdims=True)

    x2_corr = 1.0 if fold_h else 0.0   # ones column contributes 1 to x2

    def kernel_plain(x_ref, w_ref, c_ref, h_ref, labels_ref, mind2_ref,
                     sums_ref, counts_ref):
        i = pl.program_id(0)
        x = x_ref[:, :]
        w = w_ref[:, :]
        best, mind2h = argmin_tiles(x, c_ref, h_ref)
        x2 = jnp.sum(x * x, axis=1) - x2_corr
        labels_ref[:, :] = best[:, None]
        mind2_ref[:, :] = jnp.maximum(2.0 * mind2h + x2, 0.0)[:, None]

        @pl.when(i == 0)
        def _():
            sums_ref[:, :] = jnp.zeros_like(sums_ref)
            counts_ref[:, :] = jnp.zeros_like(counts_ref)

        accum(best, x, w, sums_ref, counts_ref)

    def kernel_pipe(x_ref, w_ref, c_ref, h_ref, labels_ref, mind2_ref,
                    sums_ref, counts_ref, xs, ws, bs):
        i = pl.program_id(0)
        slot = lax.rem(i, 2)
        prev = lax.rem(i + 1, 2)

        @pl.when(i == 0)
        def _():
            sums_ref[:, :] = jnp.zeros_like(sums_ref)
            counts_ref[:, :] = jnp.zeros_like(counts_ref)

        # Phase 2 first in program order: accumulate tile i-1 (independent
        # of this step's matmul -> Mosaic may overlap MXU/VPU chains).
        @pl.when(i > 0)
        def _():
            accum(bs[prev, :, 0], xs[prev], ws[prev, :, :],
                  sums_ref, counts_ref)

        @pl.when(i < n_tiles)
        def _():
            x = x_ref[:, :]
            w = w_ref[:, :]
            best, mind2h = argmin_tiles(x, c_ref, h_ref)
            x2 = jnp.sum(x * x, axis=1) - x2_corr
            labels_ref[:, :] = best[:, None]
            mind2_ref[:, :] = jnp.maximum(2.0 * mind2h + x2, 0.0)[:, None]
            xs[slot] = x
            ws[slot, :, :] = w
            bs[slot, :, 0] = best

    grid = (n_tiles + 1,) if pipe else (n_tiles,)
    nclamp = (lambda i: (min(i, n_tiles - 1) if isinstance(i, int)
                         else jnp.minimum(i, n_tiles - 1), 0))
    in_specs = [
        pl.BlockSpec((tile_n, d_pad), nclamp, memory_space=pltpu.VMEM),
        pl.BlockSpec((tile_n, 1), nclamp, memory_space=pltpu.VMEM),
        pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
    ]
    out_specs = [
        pl.BlockSpec((tile_n, 1), nclamp, memory_space=pltpu.VMEM),
        pl.BlockSpec((tile_n, 1), nclamp, memory_space=pltpu.VMEM),
        pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
        jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
    ]
    scratch = []
    if pipe:
        scratch = [pltpu.VMEM((2, tile_n, d_pad), jnp.float32),
                   pltpu.VMEM((2, tile_n, 1), jnp.float32),
                   pltpu.VMEM((2, tile_n, 1), jnp.int32)]

    fn = pl.pallas_call(
        kernel_pipe if pipe else kernel_plain,
        grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape, scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=vmem_mb * 1024 * 1024),
    )
    return fn


def make_variant(name, spec, n, d, k):
    tile_n, tile_k, flags = spec
    d_pad = _round_up(d, 128)
    fold_h = "f" in flags and d < d_pad
    ones_col = fold_h or ("o" in flags and d < d_pad)
    tile_k = min(tile_k, _round_up(k, 128))
    k_pad = _round_up(k, tile_k)
    n_pad = _round_up(n, tile_n)
    kern = build_kernel(
        tile_n=tile_n, tile_k=tile_k, pipe="p" in flags,
        man_argmin="m" in flags, ones_col=ones_col, bf16="b" in flags,
        fold_h=fold_h, vmem_mb=100, n_pad=n_pad, d=d, d_pad=d_pad, k=k,
        k_pad=k_pad)

    def run(x_pad, w_col, c, k_real):
        # c: (k, d) real centroids -> pad to (k_pad, d_pad) zeros
        c_p = jnp.zeros((k_pad, d_pad), jnp.float32)
        c_p = lax.dynamic_update_slice(c_p, c.astype(jnp.float32), (0, 0))
        h = 0.5 * jnp.sum(c_p * c_p, axis=1)
        h = h + jnp.where(jnp.arange(k_pad) >= k_real, BIG, 0.0)
        if fold_h:
            c_p = c_p.at[:, d].set(-h)      # MXU emits x@c.T - h directly
        labels, mind2, sums, counts = kern(x_pad, w_col, c_p, h[None, :])
        if ones_col:
            counts = sums[:, d]
        else:
            counts = counts[0]
        return labels[:, 0], mind2[:, 0], sums[:k, :d], counts[:k]

    return run, n_pad, fold_h


def oracle(X, w, c, fold=False):
    """bf16-aware oracle: one-pass bf16 dot (operands rounded, f32/f64
    accumulate) mirroring what Mosaic/excess-precision XLA do; argmin over
    h - xc like the kernel.  fold=True also rounds h to bf16 (the -h
    column rides through the MXU in the fold_h variants)."""
    import ml_dtypes
    bf = lambda a: a.astype(ml_dtypes.bfloat16).astype(np.float64)
    xc = bf(X) @ bf(c).T
    h = 0.5 * (c.astype(np.float64) ** 2).sum(-1)[None, :]
    if fold:
        h = bf(h)
    d2h = h - xc
    best = d2h.argmin(1)
    x2 = (X.astype(np.float64) ** 2).sum(-1)
    mind2 = np.maximum(2.0 * d2h.min(1) + x2, 0.0)
    k = c.shape[0]
    onehot = np.eye(k)[best] * w[:, None]
    return best, mind2, onehot.T @ bf(X), onehot.sum(0)


def main():
    N = int(sys.argv[1]); D = int(sys.argv[2]); K = int(sys.argv[3])
    T = int(sys.argv[4])
    specs = {}
    for s in sys.argv[5:]:
        name, rest = s.split("=")
        parts = rest.split(",")
        specs[name] = (int(parts[0]), int(parts[1]),
                       parts[2] if len(parts) > 2 else "")

    rng = np.random.default_rng(0)
    Xs = rng.standard_t(df=4, size=(4096, D)).astype(np.float32)
    cs = Xs[rng.choice(4096, min(K, 512), replace=False)].copy()
    ws = np.ones((4096,), np.float32)

    X = rng.standard_t(df=4, size=(N, D)).astype(np.float32)
    X /= np.sqrt((X * X).mean())
    c0 = X[rng.choice(N, K, replace=False)].copy()

    print(f"N={N} D={D} K={K} T={T}", flush=True)
    for name, spec in specs.items():
        # correctness on the small slice (against its own k for speed)
        try:
            run_s, n_pad_s, fold_s = make_variant(name, spec, 4096, D,
                                                  len(cs))
            x_pad = jnp.zeros((n_pad_s, _round_up(D, 128)), jnp.float32)
            x_pad = x_pad.at[:4096, :D].set(Xs)
            if fold_s:
                x_pad = x_pad.at[:, D].set(1.0)
            w_col = jnp.zeros((n_pad_s, 1), jnp.float32).at[:4096, 0].set(ws)
            lb, m2, sm, cn = jax.jit(functools.partial(
                run_s, k_real=len(cs)))(x_pad, w_col, jnp.asarray(cs))
            ob, om, os_, oc = oracle(Xs, ws, cs, fold=fold_s)
            # Labels must agree with the bf16-aware oracle except on
            # ULP-close pairs (accumulation tree differs); counts must be
            # EXACTLY self-consistent with the kernel's own labels, sums
            # approximately so.
            lb = np.asarray(lb)[:4096]
            m2 = np.asarray(m2)[:4096]
            sm, cn = np.asarray(sm), np.asarray(cn)
            mism = (lb != ob).mean()
            cn_self = np.bincount(lb, weights=ws, minlength=len(cs))
            oh_self = np.eye(len(cs))[lb] * ws[:, None]
            sm_self = oh_self.T @ Xs.astype(np.float64)
            ok = (mism <= 1e-3
                  and np.allclose(m2, om, rtol=1e-2, atol=1.0)
                  and np.array_equal(cn, cn_self)
                  and np.allclose(sm, sm_self, rtol=1e-2, atol=0.5))
            if not ok:
                print(f"{name:16s} mism={mism:.2e} "
                      f"m2max={np.abs(m2-om).max():.3g} "
                      f"cnok={np.array_equal(cn, cn_self)} "
                      f"smmax={np.abs(sm-sm_self).max():.3g}", flush=True)
        except Exception as e:
            print(f"{name:16s} BUILD/CHECK FAILED: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)
            continue
        if not ok:
            print(f"{name:16s} WRONG RESULT — skipping timing", flush=True)
            continue

        run, n_pad, fold_b = make_variant(name, spec, N, D, K)
        d_pad = _round_up(D, 128)
        x_pad = jnp.zeros((n_pad, d_pad), jnp.float32).at[:N, :D].set(X)
        if fold_b:
            x_pad = x_pad.at[:, D].set(1.0)
        w_col = jnp.zeros((n_pad, 1), jnp.float32).at[:N, 0].set(1.0)

        def fit(n_iter, x_pad, w_col, cents0):
            def body(i, cents):
                _, _, sums, counts = run(x_pad, w_col, cents, K)
                return sums / jnp.maximum(counts, 1.0)[:, None]
            return lax.fori_loop(0, n_iter, body, cents0)

        try:
            f2 = jax.jit(functools.partial(fit, 2))
            fb = jax.jit(functools.partial(fit, 2 + T))
            cents = jnp.asarray(c0)
            float(f2(x_pad, w_col, cents)[0, 0])
            float(fb(x_pad, w_col, cents)[0, 0])
        except Exception as e:
            print(f"{name:16s} COMPILE FAILED: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)
            continue
        margins = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(f2(x_pad, w_col, cents)[0, 0])
            ts = time.perf_counter() - t0
            t0 = time.perf_counter()
            float(fb(x_pad, w_col, cents)[0, 0])
            tb = time.perf_counter() - t0
            margins.append((tb - ts) / T)
        med = float(np.median(margins)) * 1e3
        print(f"{name:16s} {med:8.3f} ms/iter  (reps "
              f"{[f'{m*1e3:.2f}' for m in margins]})", flush=True)


if __name__ == "__main__":
    main()
