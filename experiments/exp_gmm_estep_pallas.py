"""Fused Pallas/Mosaic E-step kernel for the diagonal GMM — a REJECTED
r3 experiment, kept with its measurements (repo policy: rejected
alternatives stay on the record).

Verdict (v5e, 2026-07-30): numerically EXACT — matches the XLA oracle
in interpret mode and on hardware (incl. HIGHEST-precision moments,
which Mosaic honors: measured 2e-7 rel err vs 1.1e-3 at default
bf16-product rate) — but catastrophically slow as scheduled here:
**3.35 s warm for one 500k x 128, k=256 E-step vs ~3.5 ms for the XLA
scan path** (scalar-transfer-synced single-dispatch timing).  The
naive sequential grid with fixed-index (k_pad, d_pad) accumulator
blocks and 3-pass HIGHEST scatter matmuls serializes Mosaic's
pipeline; closing a ~1000x gap needs the same multi-round scheduling
investment the r2 K-Means kernel got (ping-pong scratch, software
pipelining, phase overlap) for a bounded prize — the XLA EM step is
already within ~2x of its matmul+exp floor after the r3 chunk-budget
fix (docs/PERFORMANCE.md "The mixture family").  Parked here.

Original design notes follow.

One kernel per data shard computes the ENTIRE E-step contribution —
log-density matmuls, max-subtracted softmax, and the three
responsibility-weighted accumulators — without ever materializing the
(n, k) log-density tile in HBM.  The XLA scan path round-trips that
tile between the matmul, softmax, and moment stages (the r3 chunk-size
finding, docs/PERFORMANCE.md: past ~2^23 tile elements the stages
de-fuse); here ``logp`` lives only in VMEM for the current row tile.

Formulation (see parallel.gmm_step): with a = 1/sigma^2, b = mu*a, and
the per-component constant

    c1_k = log pi_k - 0.5*(d*log 2pi + sum_d log sigma^2 + sum_d mu^2 a),

the weighted log joint is  logp = c1 + x@b.T - 0.5*(x*x)@a.T  — two
MXU matmuls per row tile.  Per tile: m = rowmax(logp),
p = exp(logp - m), r = p * w / rowsum(p), then

    rsum += colsum(r)          (1, k)
    s1   += r.T @ x            (k, d)   [Precision.HIGHEST]
    s2   += r.T @ (x*x)        (k, d)   [Precision.HIGHEST]
    ll   += sum(w * (m + log rowsum(p)))

accumulated across the sequential row grid in VMEM.  The two moment
matmuls run at HIGHEST precision — Mosaic honors it (measured 2e-7
rel err vs 1.1e-3 for the default bf16-rate products), which is what
keeps ``S2/R - mu^2`` from cancelling for clusters offset from the
centering shift (the r3 hardware finding, tests/test_gmm_tpu.py).

Centering: the kernel subtracts the caller's ``shift`` row in
registers, so the means/moments are in the centered frame exactly like
the XLA path.

Scope: single component block (no model-axis sharding — the softmax
normalizer would need a cross-shard psum mid-kernel); the whole
(k_pad, d_pad) parameter set plus one (tile_n, k_pad) logp tile must
fit the VMEM budget (``pallas_estep_supported``).  Padding components
carry ``c1 = -_PAD_BIG`` so they never receive responsibility; padding
rows carry ``w = 0`` so they contribute to nothing.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_PAD_BIG = 1e30
_VMEM_LIMIT = 100 * 1024 * 1024


def _round_up(a: int, b: int) -> int:
    return -(-a // b) * b


def _tile_n_for(d_pad: int, k_pad: int) -> int:
    """Row-tile height: target ~2^21 logp elements, power of two,
    128..2048 (the r3 chunk finding scaled to VMEM residency)."""
    t = max(128, min(2048, (1 << 21) // max(k_pad, d_pad)))
    return 1 << (t.bit_length() - 1)


def _vmem_estimate(tile_n: int, d_pad: int, k_pad: int) -> int:
    tiles = tile_n * (2 * d_pad + 2 * k_pad + 8) * 4   # x, x2, logp, p
    params = (3 * k_pad * d_pad + 2 * k_pad) * 4       # a, b, outs, c1
    outs = 2 * k_pad * d_pad * 4 + k_pad * 4
    return tiles + params + outs


def pallas_estep_supported(n: int, d: int, k: int) -> bool:
    """Can the fused kernel run this shape inside the VMEM budget?"""
    d_pad = _round_up(d, 128)
    k_pad = _round_up(k, 128)
    tile_n = _tile_n_for(d_pad, k_pad)
    return _vmem_estimate(tile_n, d_pad, k_pad) <= _VMEM_LIMIT


def _kernel(x_ref, w_ref, shift_ref, a_ref, b_ref, c1_ref,
            rsum_ref, s1_ref, s2_ref, ll_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        rsum_ref[:, :] = jnp.zeros_like(rsum_ref)
        s1_ref[:, :] = jnp.zeros_like(s1_ref)
        s2_ref[:, :] = jnp.zeros_like(s2_ref)
        ll_ref[:, :] = jnp.zeros_like(ll_ref)

    x = x_ref[:, :] - shift_ref[:, :]              # centered frame
    w = w_ref[:, :]
    x2 = x * x
    logp = (c1_ref[:, :]
            + lax.dot_general(x, b_ref[:, :], (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
            - 0.5 * lax.dot_general(x2, a_ref[:, :],
                                    (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32))
    m = jnp.max(logp, axis=1, keepdims=True)       # (tile_n, 1)
    p = jnp.exp(logp - m)
    denom = jnp.sum(p, axis=1, keepdims=True)      # (tile_n, 1)
    r = p * (w / denom)                            # weighted resp
    hi = lax.Precision.HIGHEST
    rsum_ref[:, :] += jnp.sum(r, axis=0, keepdims=True)
    s1_ref[:, :] += lax.dot_general(r, x, (((0,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32,
                                    precision=hi)
    s2_ref[:, :] += lax.dot_general(r, x2, (((0,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32,
                                    precision=hi)
    ll_ref[:, :] += jnp.sum(w * (m + jnp.log(denom)), keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_estep(points: jax.Array, weights: jax.Array, shift: jax.Array,
                 means_c: jax.Array, inv_var: jax.Array,
                 log_det: jax.Array, log_weights: jax.Array,
                 *, interpret: bool = False
                 ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """(resp_sum (k,), xsum (k, d), x2sum (k, d), loglik ()) for one
    shard — the fused equivalent of ``gmm_step._scan_estats`` at
    ``model_shards == 1``.  ``means_c`` must already be centered by
    ``shift``; padded rows must carry ``weights == 0``."""
    n, d = points.shape
    k = means_c.shape[0]
    f32 = jnp.float32
    d_pad = _round_up(d, 128)
    k_pad = _round_up(k, 128)
    tile_n = _tile_n_for(d_pad, k_pad)
    n_pad = _round_up(n, tile_n)

    x = points.astype(f32)
    w = weights.astype(f32)
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
        w = jnp.pad(w, (0, n_pad - n))
    if d_pad != d:
        x = jnp.pad(x, ((0, 0), (0, d_pad - d)))

    a = jnp.pad(inv_var.astype(f32), ((0, k_pad - k), (0, d_pad - d)))
    mu = means_c.astype(f32)
    b = jnp.pad(mu * inv_var.astype(f32),
                ((0, k_pad - k), (0, d_pad - d)))
    c1 = (log_weights.astype(f32)
          - 0.5 * (d * np.log(2.0 * np.pi) + log_det.astype(f32)
                   + jnp.sum(mu * mu * inv_var.astype(f32), axis=1)))
    c1 = jnp.pad(c1, (0, k_pad - k), constant_values=-_PAD_BIG)[None, :]
    shift_row = jnp.pad(shift.astype(f32), (0, d_pad - d))[None, :]

    n_tiles = n_pad // tile_n
    zero = np.int32(0)
    nmap = lambda i: (i, zero)
    fixed = lambda i: (zero, zero)
    rsum, s1, s2, ll = pl.pallas_call(
        _kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile_n, d_pad), nmap, memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, 1), nmap, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d_pad), fixed, memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d_pad), fixed, memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d_pad), fixed, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), fixed, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, k_pad), fixed, memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d_pad), fixed, memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d_pad), fixed, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), fixed, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, k_pad), f32),
            jax.ShapeDtypeStruct((k_pad, d_pad), f32),
            jax.ShapeDtypeStruct((k_pad, d_pad), f32),
            jax.ShapeDtypeStruct((1, 1), f32),
        ],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
    )(x, w[:, None], shift_row, a, b, c1)
    return (rsum[0, :k], s1[:k, :d], s2[:k, :d], ll[0, 0])
