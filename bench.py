"""Headline benchmark: K-Means iteration throughput on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "points*dims/sec/chip", "vs_baseline": N}

Measures the fused SPMD iteration (assign + reduce + SSE) on the headline
configuration family from BASELINE.json (uniform points, D=128, k=1024),
with compile/warmup excluded (the reference times cold, kmeans_spark.py:
575-579 — SURVEY.md §6 flags this).

``vs_baseline`` is measured against an on-host re-enactment of the
reference's per-point executor loop (``assign_partition``,
kmeans_spark.py:147-159: np.linalg.norm per point + argmin), scaled by
BASELINE.json's 8 Spark workers with PERFECT linear scaling assumed — a
deliberately generous baseline (real Spark adds shuffle/serialization
overhead on top, and its reduceByKey pass is not even counted here).

Env overrides: BENCH_N, BENCH_D, BENCH_K, BENCH_ITERS, BENCH_DTYPE.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def baseline_throughput(d: int, k: int, workers: int = 8,
                        sample: int = 512) -> float:
    """Reference-style per-point loop throughput, points*dims/sec for
    `workers` perfectly-scaled workers (kmeans_spark.py:147-159)."""
    rng = np.random.default_rng(0)
    pts = rng.uniform(-1, 1, size=(sample, d))
    centroids = rng.uniform(-1, 1, size=(k, d))
    # Warm the BLAS path once.
    _ = np.linalg.norm(centroids - pts[0], axis=1)
    start = time.perf_counter()
    for p in pts:
        dist = np.linalg.norm(centroids - p, axis=1)
        _ = int(np.argmin(dist))
    elapsed = time.perf_counter() - start
    per_point = elapsed / sample
    return workers * d / per_point


def main() -> None:
    import jax

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    n = int(os.environ.get("BENCH_N", 2_000_000 if on_accel else 100_000))
    d = int(os.environ.get("BENCH_D", 128))
    k = int(os.environ.get("BENCH_K", 1024))
    iters = int(os.environ.get("BENCH_ITERS", 5))
    dtype = np.dtype(os.environ.get("BENCH_DTYPE", "float32"))

    log(f"bench: backend={backend} devices={len(jax.devices())} "
        f"N={n} D={d} k={k} iters={iters} dtype={dtype}")

    from kmeans_tpu.models.kmeans import _get_step_fns
    from kmeans_tpu.parallel import distributed as dist
    from kmeans_tpu.parallel.mesh import make_mesh, mesh_shape
    from kmeans_tpu.parallel.sharding import choose_chunk_size, shard_points

    rng = np.random.default_rng(42)
    X = rng.uniform(-1, 1, size=(n, d)).astype(dtype)
    init = X[rng.choice(n, size=k, replace=False)]

    mesh = make_mesh()
    data_shards, model_shards = mesh_shape(mesh)
    chunk = choose_chunk_size(-(-n // data_shards), k, d)
    points, weights = shard_points(X, mesh, chunk)
    cents = jax.device_put(dist.pad_centroids(init, model_shards),
                           dist.centroid_sharding(mesh))
    step_fn, _ = _get_step_fns(mesh, chunk, "matmul")

    # Warmup: compile + one extra steady-state step.  Synchronization is via
    # a scalar transfer (float(sse)) — block_until_ready is not a reliable
    # barrier on tunneled/experimental PJRT platforms.
    t0 = time.perf_counter()
    float(step_fn(points, weights, cents).sse)
    log(f"bench: compile+first step {time.perf_counter() - t0:.1f}s")
    float(step_fn(points, weights, cents).sse)

    start = time.perf_counter()
    for _ in range(iters):
        stats = step_fn(points, weights, cents)
        float(stats.sse)
    per_iter = (time.perf_counter() - start) / iters
    log(f"bench: {per_iter*1e3:.1f} ms/iter, sse={float(stats.sse):.4e}")

    n_chips = max(1, len(jax.devices()))
    throughput = n * d / per_iter / n_chips

    base = baseline_throughput(d, k)
    log(f"bench: baseline (8 ideal Spark workers) {base:.3e} pts*dims/s")

    print(json.dumps({
        "metric": f"kmeans_iter_throughput_N{n}_D{d}_k{k}",
        "value": round(throughput, 1),
        "unit": "points*dims/sec/chip",
        "vs_baseline": round(throughput * n_chips / base, 2),
    }))


if __name__ == "__main__":
    main()
