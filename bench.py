"""Headline benchmark: K-Means iteration throughput on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "points*dims/sec/chip", "vs_baseline": N}

Measures the STEADY-STATE per-iteration cost of the fused SPMD training
step on the headline configuration family from BASELINE.json (uniform
points, D=128, k=1024).  Method: the whole training loop runs on device
(``lax.while_loop``, one dispatch — parallel.distributed.make_fit_fn), and
the per-iteration cost is the marginal time between a 2-iteration and a
(2+iters)-iteration fit, which cancels dispatch latency and host/transfer
overhead exactly.  Compile time is excluded (the reference times cold,
kmeans_spark.py:575-579 — SURVEY.md §6 flags this); synchronization is via
scalar transfer (block_until_ready is not a reliable barrier on tunneled
PJRT platforms).

``vs_baseline`` compares against the reference's per-point executor loop
(``assign_partition``, kmeans_spark.py:147-159: np.linalg.norm per point +
argmin), scaled by BASELINE.json's 8 Spark workers with PERFECT linear
scaling assumed — a deliberately generous baseline (real Spark adds
shuffle/serialization overhead on top, and its reduceByKey pass is not
even counted here).  At the headline shape the divisor is PINNED to the
median of the r1-r4 recorded probes (``BASELINE.json.published``) so the
multiplier stops drifting with host load; a live probe is still run and
logged as a drift check (other shapes use the live probe directly).

Env overrides: BENCH_N, BENCH_D, BENCH_K, BENCH_ITERS, BENCH_MODE.

BENCH_CKPT=1 switches to the SEGMENTED-CHECKPOINT benchmark (ISSUE 4):
checkpoint_every=N device-loop fit vs the single-dispatch oracle at the
same shape (extra dispatches + boundary round trips + rotating .npz
writes), interleaved per-rep ratios.  Env: BENCH_N/D/K/ITERS,
BENCH_CKPT_EVERY (default 8).

BENCH_INIT=1 switches to the SEEDING-COST benchmark (ISSUE 2): warm
k-means|| init at BENCH_N/D/K (accelerator default 2M x 128 k=1024 —
the shape whose legacy init measured 7.4 s warm vs a 0.77 s training
loop), one-dispatch device pipeline vs the legacy per-round engine,
one JSON line with the <= 2 s acceptance target recorded.

BENCH_STREAM=1 switches to the STREAMED-EPOCH benchmark instead
(``kmeans_tpu.benchmarks.bench_stream``): ``fit_stream`` epoch cost off
an on-disk ``.npy`` with the double-buffered input pipeline ON
(prefetch=2) vs OFF (0), interleaved marginal pairs, one JSON line.
Env: BENCH_STREAM_N / _D / _K / _BLOCK_ROWS / _EPOCHS / _PATH
(accelerator default = the declared bigger-than-HBM config, 40M x 128
k=1024 in 2M-row blocks; CPU default scales down to 1M x 32).

BENCH_SERVE=1 switches to the SERVING latency/QPS benchmark (ISSUE 6):
a resident warm K-Means model served through the micro-batching
engine — batched-vs-sequential-dispatch speedup (interleaved per-rep
ratios) plus p50/p99 request latency and QPS at 1/8/64/512-request
batches (``kmeans_tpu.benchmarks.bench_serving``).  Env: BENCH_N/D/K,
BENCH_SERVE_BATCHES, BENCH_SERVE_WAIT_MS.

BENCH_GMM=1 switches to the GMM E-STEP PIPELINE benchmark (ISSUE 3
tentpole): the one-dispatch diag EM loop with the software-pipelined
chunk schedule (pipeline=1) vs the serial oracle (pipeline=0),
per-rep interleaved marginal ratios + the step-MFU column
(``kmeans_tpu.benchmarks.bench_gmm_pipeline``).  Accelerator default is
the pinned decision shape 2M x 128 k=256 diag (target >40% MFU vs the
33% serial baseline, BASELINE.json ``gmm-estep-pipeline`` row); the CPU
default scales down to the published gmm family-row shape 200k x 32
k=32.  Env: BENCH_N / _D / _K / _ITERS, BENCH_GMM_COV.

BENCH_LLOYD=1 switches to the PIPELINED LLOYD E-STEP benchmark
(ISSUE 8 tentpole): the one-dispatch K-Means loop with the two-stage
chunk schedule (pipeline=1) vs the serial bit-exact oracle
(pipeline=0), interleaved per-rep marginal ratio pairs + step MFU
(``kmeans_tpu.benchmarks.bench_lloyd_pipeline``).  Accelerator default
is the 10M x 128 k=1024 headline shape (committed adopt rule: >= 5%);
the CPU default scales down to 200k x 32 k=64, where a measured
rejection is the expected publishable outcome (the r8 GMM precedent —
'auto' resolves serial on CPU).  Env: BENCH_N/_D/_K/_ITERS.

BENCH_GUARD=1 switches to the GUARDED-bf16 DISTANCE RUNG benchmark
(ISSUE 8 tentpole): distance_mode='matmul_bf16_guarded' vs the f32
'matmul' class on the one-dispatch loop — centroid BIT-parity asserted
every run, the corrected-rows audit published with the rate
(``kmeans_tpu.benchmarks.bench_bf16_guard``; committed adopt rule:
>= 5% at the headline shape).  Env: BENCH_N/_D/_K/_ITERS.

BENCH_LARGEK=1 switches to the MASSIVE-k SCALING CURVE (ISSUE 16
tentpole): ms/iter vs k at fixed N x D for the dense Lloyd oracle vs
the routed large-k tier — k_shard=model_shards (TP-sharded centroid
table, pair all-reduce assignment) on a model-sharded mesh,
assign='two_level' (coarse-cell candidate routing) on a data-parallel
one — interleaved per-rep marginal ratio pairs, the in-bench parity
oracle (k-shard: bit parity asserted; two-level: SSE gap published),
and the planner's predicted-vs-observed HBM bytes per row
(``kmeans_tpu.benchmarks.bench_large_k``).  Accelerator default is
2M x 128 over k in {1024, 4096, 16384, 65536}; the CPU proxy scales
to 50k x 32 over k in {256, 512, 1024, 2048}.  Env: BENCH_N/_D,
BENCH_LARGEK_KS (comma list), BENCH_ITERS, BENCH_MODEL_SHARDS
(builds a TP mesh and benches the k-sharded route instead).

BENCH_OBS=1 switches to the TELEMETRY-OVERHEAD benchmark (ISSUE 11):
obs-on (span tracing + heartbeat) vs obs-off fits, interleaved per-rep
ratios on BOTH the one-dispatch device loop and the telemetry-dense
per-iteration host loop, plus a cold-cache traced fit emitting the
span-derived time-to-first-iteration table (trace JSONL artifact at
BENCH_OBS_TRACE, default artifacts/trace_ttfi.jsonl).  Committed rule:
<= 1% median overhead on the 200k x 32 k=64 proxy or per-iteration
spans demote to segment-level.  Env: BENCH_N/_D/_K/_ITERS.

BENCH_TTFI=1 switches to the TIME-TO-FIRST-ITERATION attack rows
(ISSUE 15): cold / same-process-warm / AOT-warm(second process) /
compile-ingest-overlap TTFI tables measured across fresh subprocesses
sharing one AOT executable store, with the committed rules (AOT-warm
compile row <= 10% of cold; overlapped prelude window < serial
stage+compile sum).  Cold/AOT-warm traces land in artifacts/ for the
bench-diff TTFI guard.  Env: BENCH_N/_D/_K, BENCH_ITERS,
BENCH_AOT_DIR.

BENCH_INGEST=1 switches to the STAGED-INGEST decision rows (ISSUE 18):
interleaved mono/slab placement walls of a >= 1 GB proxy in a fresh
process (medians + the committed >= 1.2x adoption rule and the
bit-parity column), fresh-process serial-vs-overlapped TTFI pairs with
slabbed ingest (window < serial PASS row + re-measured place/stage
share), load-whole-file vs streamed from_npy host high-water children
(committed saved-copy rule: naive - stream maxrss >= 0.8x file bytes),
and the 1e9-row weak-scaling config declared through
plan_fit/plan_ingest.  Measured outcome (r22, BASELINE.md): the CPU
proxy REJECTS slab-for-'auto' (median mono/slab 1.04x on the
single-core box — nothing to overlap against) -> 'auto' = mono on
CPU, slab on accelerators; saved-copy and 1e9-plan rows PASS.
Env: BENCH_N/_D/_K, BENCH_ITERS, BENCH_REPS.

BENCH_QUALITY=1 switches to the SERVING-QUALITY MONITORING overhead
benchmark (ISSUE 14): monitoring-on vs monitoring-off serving
throughput against a resident warm K-Means model, interleaved per-rep
on/off ratio pairs with labels asserted bit-equal in-bench (the obs=0
parity contract applied to serving).  Committed rule: <= 1.01 median
overhead keeps ``quality='auto'`` resolving ON for the measured
platform; a breach resolves 'auto' to off there — published either
way (measured outcome: CPU breaches at ~1.1-1.2x against sub-ms local
dispatches -> 'auto' = off on CPU, on on accelerators; hardware row
pinned).  Env: BENCH_N/_D/_K, BENCH_QUALITY_BATCH (rows per dispatch,
default 512).

BENCH_FLEET=1 switches to the SERVING-FLEET rows (ISSUE 17): router
overhead at R=1 (committed <= 1.05 routed/direct rule), the open-loop
(coordinated-omission-free) 1->N replica QPS/p99 scaling curve at a
committed offered rate with failed==0 asserted every rep, shed rate
at the committed admission bound (served + shed == offered asserted —
zero silent drops), and add_replica prewarm cost vs the initial
warmup (``kmeans_tpu.benchmarks.bench_fleet``).  On this CPU
container in-process replicas share one backend so QPS(R) is flat by
construction — the published property is replication-adds-no-loss;
real scaling is a hardware row.  Env: BENCH_N/_D/_K,
BENCH_FLEET_REPLICAS (comma list, default "1,2").

BENCH_LEARN=1 switches to the SERVE-AND-LEARN p99 EXCURSION row
(ISSUE 20): per-request serving latency measured DURING an in-place
online update (snapshot -> clone partial_fit -> atomic swap on a
background thread) vs a quiet engine, interleaved per-rep p99 ratio
pairs with ZERO failed requests asserted in-bench (the chaos
contract).  Committed rule: <= 3x median excursion
(``serving.learn.LEARN_P99_EXCURSION_BOUND``) — the update runs off
the dispatch lock, so a breach means update work leaked into the
serve path.  Env: BENCH_N/_D/_K, BENCH_LEARN_BATCH (rows per
dispatch, default 512).

BENCH_COST=1 switches to the DEVICE-COST OBSERVABILITY rows (ISSUE 12):
analytic-vs-XLA-reported FLOPs and predicted-vs-observed peak-memory
comparisons for the kmeans and gmm-diag step programs, captured
through the real step-cache path (``kmeans_tpu.benchmarks.bench_cost``)
— one JSON line per family for BASELINE.md/json.  Committed rule:
analytic flops within +-10% of XLA at the 10M x 128 k=1024 headline
shape keeps the hand-formula MFU numerator; a breach publishes as a
finding and MFU switches to the XLA numerator.  Env: BENCH_N/_D/_K
(kmeans), BENCH_GMM_N/_D/_K (gmm-diag; defaults scale with platform).

BENCH_PHASES=1 switches to the MEASURED PER-PHASE CEILING TABLE
(ISSUE 8c): the r8 cumulative-prefix phase ladder (distance ->
+argmin -> +scatter/psum) with implied-ceiling-if-free columns and the
committed >= 15% actionability rule, plus a chunk-geometry re-sweep AT
the benched shape (the 32768-131072 plateau was derived at 2M; adopt
rule >= 3% shift) — ``kmeans_tpu.benchmarks.bench_phases``, one JSON
line with both tables.  Accelerator default 10M x 128 k=1024; CPU
smoke scales to 200k x 32 k=64 (harness exercise — the decision rules
are hardware measurements).  Env: BENCH_N/_D/_K/_ITERS,
BENCH_PHASES_CHUNKS (comma list), BENCH_PHASES_NO_SWEEP=1.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def baseline_throughput(d: int, k: int, workers: int = 8,
                        sample: int = 512) -> float:
    """Reference-style per-point loop throughput, points*dims/sec for
    `workers` perfectly-scaled workers (kmeans_spark.py:147-159)."""
    rng = np.random.default_rng(0)
    pts = rng.uniform(-1, 1, size=(sample, d))
    centroids = rng.uniform(-1, 1, size=(k, d))
    # Warm the BLAS path once.
    _ = np.linalg.norm(centroids - pts[0], axis=1)
    start = time.perf_counter()
    for p in pts:
        dist = np.linalg.norm(centroids - p, axis=1)
        _ = int(np.argmin(dist))
    elapsed = time.perf_counter() - start
    per_point = elapsed / sample
    return workers * d / per_point


def pinned_baseline(d: int, k: int):
    """Pinned Spark-loop baseline from ``BASELINE.json.published`` (r5).

    The live ``baseline_throughput`` probe drifts with host load (recorded
    r1-r4 probes span 2.76e6-4.05e6, a 1.5x swing that moved the published
    multiplier 8.2k<->12k between artifacts — r4 verdict #2), so the
    published multiplier is measured against the pinned median of those
    probes instead.  Only valid at the shape it was probed at; other
    shapes fall back to the live probe.  Returns ``(value, "ok")`` or
    ``(None, reason)`` — the reason string distinguishes a benign shape
    mismatch from a lost/corrupt pin file, which at the headline shape
    means the published multiplier silently reverts to drifting."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE.json")
    try:
        with open(path) as f:
            pub = json.load(f)["published"]["spark_baseline"]
        if (int(pub["probe_shape"]["d"]), int(pub["probe_shape"]["k"])) \
                != (d, k):
            return None, "shape_mismatch"
        value = float(pub["pts_dims_per_s"])
        if not value > 0:
            return None, f"load_error: non-positive pin {value!r}"
        return value, "ok"
    except (OSError, KeyError, TypeError, ValueError) as e:
        return None, f"load_error: {type(e).__name__}: {e}"


def timed_fit(fit_fn, points, weights, cents, seeds) -> float:
    """Wall seconds for one fit dispatch (scalar-transfer synchronized)."""
    start = time.perf_counter()
    out = fit_fn(points, weights, cents, seeds)
    int(out[1])                                    # n_iters -> sync barrier
    return time.perf_counter() - start


def main() -> None:
    import jax

    from kmeans_tpu.benchmarks import (enable_compilation_cache,
                                       measure_marginal)

    enable_compilation_cache()
    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)

    if os.environ.get("BENCH_INIT"):
        # Seeding-cost benchmark (ISSUE 2 acceptance): warm k-means||
        # init, device one-dispatch pipeline vs the legacy per-round
        # engine, at the shape where the legacy engine's ~5 RTTs + host
        # reduce measured 7.4 s warm (BASELINE.json.time_to_solution).
        # Data generated on device, sharded, zero upload.
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from kmeans_tpu.benchmarks import bench_init
        from kmeans_tpu.parallel.mesh import (DATA_AXIS, make_mesh,
                                              mesh_shape)
        from kmeans_tpu.parallel.sharding import (ShardedDataset,
                                                  choose_chunk_size)
        n = int(os.environ.get("BENCH_N",
                               2_000_000 if on_accel else 100_000))
        d = int(os.environ.get("BENCH_D", 128 if on_accel else 32))
        k = int(os.environ.get("BENCH_K", 1024 if on_accel else 64))
        mesh = make_mesh()
        data_shards, _ = mesh_shape(mesh)
        chunk = choose_chunk_size(-(-n // data_shards), k, d)
        n_pad = -(-n // (data_shards * chunk)) * (data_shards * chunk)
        gen = jax.jit(
            lambda key: (jax.random.uniform(key, (n_pad, d), jnp.float32,
                                            -1.0, 1.0),
                         (jnp.arange(n_pad) < n).astype(jnp.float32)),
            out_shardings=(NamedSharding(mesh, P(DATA_AXIS, None)),
                           NamedSharding(mesh, P(DATA_AXIS))))
        points, weights = gen(jax.random.PRNGKey(42))
        ds = ShardedDataset(points, weights, n, chunk, mesh)
        log(f"bench: INIT mode backend={backend} N={n} D={d} k={k}")
        dev_s, legacy_s = bench_init(ds, k)
        log(f"bench: warm k-means|| init device {dev_s:.3f}s vs legacy "
            f"{legacy_s:.3f}s ({legacy_s / max(dev_s, 1e-9):.2f}x)")
        print(json.dumps({
            "metric": f"kmeans_parallel_init_warm_N{n}_D{d}_k{k}",
            "value": round(dev_s, 3),
            "unit": "s (warm, one-dispatch device pipeline)",
            "legacy_s": round(legacy_s, 3),
            "speedup_vs_legacy": round(legacy_s / max(dev_s, 1e-9), 2),
            "target_s_at_2Mx128_k1024": 2.0,
            "platform": backend,
            "n_devices": len(jax.devices()),
        }))
        return

    if os.environ.get("BENCH_GMM"):
        # GMM E-step pipeline benchmark (ISSUE 3): pipelined vs serial
        # chunk schedule on the one-dispatch diag EM loop, interleaved
        # per-rep ratios, step MFU on platforms with a pinned peak.
        from kmeans_tpu.benchmarks import bench_gmm_pipeline
        gn = int(os.environ.get("BENCH_N",
                                2_097_152 if on_accel else 200_000))
        gd = int(os.environ.get("BENCH_D", 128 if on_accel else 32))
        gk = int(os.environ.get("BENCH_K", 256 if on_accel else 32))
        gi = int(os.environ.get("BENCH_ITERS", 20))
        gct = os.environ.get("BENCH_GMM_COV", "diag")
        log(f"bench: GMM-PIPELINE mode backend={backend} N={gn} D={gd} "
            f"k={gk} iters_gap={gi} cov={gct}")
        bench_gmm_pipeline(gn, gd, gk, gi, cov_type=gct)
        return

    if os.environ.get("BENCH_LLOYD") or os.environ.get("BENCH_GUARD"):
        # Pipelined-Lloyd / guarded-bf16 rung benchmarks (ISSUE 8):
        # interleaved per-rep marginal ratio pairs on the one-dispatch
        # loop; headline shape on accelerators, scaled CPU proxy
        # otherwise (a measured CPU rejection is a publishable result).
        from kmeans_tpu.benchmarks import (bench_bf16_guard,
                                           bench_lloyd_pipeline)
        ln = int(os.environ.get("BENCH_N",
                                10_000_000 if on_accel else 200_000))
        ld = int(os.environ.get("BENCH_D", 128 if on_accel else 32))
        lk = int(os.environ.get("BENCH_K", 1024 if on_accel else 64))
        li = int(os.environ.get("BENCH_ITERS", 20))
        if os.environ.get("BENCH_LLOYD"):
            log(f"bench: LLOYD-PIPELINE mode backend={backend} N={ln} "
                f"D={ld} k={lk} iters_gap={li}")
            bench_lloyd_pipeline(ln, ld, lk, li)
        if os.environ.get("BENCH_GUARD"):
            log(f"bench: BF16-GUARD mode backend={backend} N={ln} "
                f"D={ld} k={lk} iters_gap={li}")
            bench_bf16_guard(ln, ld, lk, li)
        return

    if os.environ.get("BENCH_LARGEK"):
        # Massive-k scaling curve (ISSUE 16): dense oracle vs the
        # routed large-k tier across a k sweep at fixed N x D,
        # interleaved per-rep ratios + parity oracles + planner
        # predicted-vs-observed HBM rows.
        from kmeans_tpu.benchmarks import bench_large_k
        xn = int(os.environ.get("BENCH_N",
                                2_000_000 if on_accel else 50_000))
        xd = int(os.environ.get("BENCH_D", 128 if on_accel else 32))
        xks = tuple(int(v) for v in os.environ.get(
            "BENCH_LARGEK_KS",
            "1024,4096,16384,65536" if on_accel
            else "256,512,1024,2048").split(","))
        xi = int(os.environ.get("BENCH_ITERS", 8))
        xm = int(os.environ.get("BENCH_MODEL_SHARDS", 0))
        log(f"bench: LARGE-K mode backend={backend} N={xn} D={xd} "
            f"ks={xks} iters_gap={xi}"
            + (f" model_shards={xm}" if xm else ""))
        bench_large_k(xn, xd, xks, iters=xi, model_shards=xm)
        return

    if os.environ.get("BENCH_INGEST"):
        # Staged-ingest decision rows (ISSUE 18): slab-vs-mono ratio on
        # the >= 1 GB proxy with the committed 1.2x adoption rule,
        # ingest/compile overlap PASS, streamed-vs-naive host
        # high-water, and the declared 1e9-row config.
        from kmeans_tpu.benchmarks import bench_ingest
        gn = int(os.environ.get("BENCH_N",
                                8_000_000 if on_accel else 4_200_000))
        gd = int(os.environ.get("BENCH_D", 64))
        gk = int(os.environ.get("BENCH_K", 64))
        gi = int(os.environ.get("BENCH_ITERS", 4))
        gr = int(os.environ.get("BENCH_REPS", 3))
        log(f"bench: INGEST mode backend={backend} N={gn} D={gd} "
            f"({gn * gd * 4 / 2**30:.2f} GiB proxy) reps={gr}")
        bench_ingest(gn, gd, k=gk, max_iter=gi, reps=gr)
        return

    if os.environ.get("BENCH_QUALITY"):
        # Serving-quality monitoring overhead (ISSUE 14): drift
        # monitor fed per dispatch vs the blind engine, interleaved
        # per-rep ratios, committed <=1.01 rule.
        from kmeans_tpu.benchmarks import bench_quality
        qn = int(os.environ.get("BENCH_N",
                                2_000_000 if on_accel else 200_000))
        qd = int(os.environ.get("BENCH_D", 128 if on_accel else 32))
        qk = int(os.environ.get("BENCH_K", 1024 if on_accel else 64))
        qb = int(os.environ.get("BENCH_QUALITY_BATCH", 512))
        log(f"bench: QUALITY mode backend={backend} N={qn} D={qd} "
            f"k={qk} batch={qb}")
        bench_quality(qn, qd, qk, batch=qb)
        return

    if os.environ.get("BENCH_FLEET"):
        # Serving-fleet rows (ISSUE 17): router overhead at R=1, the
        # open-loop 1->N replica QPS/p99 scaling curve, shed rate at
        # the committed admission bound, and replica prewarm cost.
        from kmeans_tpu.benchmarks import bench_fleet
        fn_ = int(os.environ.get("BENCH_N",
                                 2_000_000 if on_accel else 200_000))
        fd = int(os.environ.get("BENCH_D", 128 if on_accel else 32))
        fk = int(os.environ.get("BENCH_K", 1024 if on_accel else 64))
        fr = tuple(int(v) for v in os.environ.get(
            "BENCH_FLEET_REPLICAS", "1,2").split(","))
        log(f"bench: FLEET mode backend={backend} N={fn_} D={fd} "
            f"k={fk} replicas={fr}")
        bench_fleet(fn_, fd, fk, replicas=fr)
        return

    if os.environ.get("BENCH_LEARN"):
        # Serve-and-learn p99 excursion (ISSUE 20): serving latency
        # during an in-place update vs quiet, interleaved per-rep
        # ratios, committed <= 3x bound, zero failed requests asserted.
        from kmeans_tpu.benchmarks import bench_learn
        ln_ = int(os.environ.get("BENCH_N",
                                 2_000_000 if on_accel else 200_000))
        ld = int(os.environ.get("BENCH_D", 128 if on_accel else 32))
        lk = int(os.environ.get("BENCH_K", 1024 if on_accel else 64))
        lb = int(os.environ.get("BENCH_LEARN_BATCH", 512))
        log(f"bench: LEARN mode backend={backend} N={ln_} D={ld} "
            f"k={lk} batch={lb}")
        bench_learn(ln_, ld, lk, batch=lb)
        return

    if os.environ.get("BENCH_COST"):
        # Device-cost observability rows (ISSUE 12): analytic-vs-XLA
        # flops + predicted-vs-observed peak memory for the kmeans and
        # gmm-diag step programs, committed 10% agreement rule at the
        # hardware headline shape.
        from kmeans_tpu.benchmarks import bench_cost
        kn = int(os.environ.get("BENCH_N",
                                10_000_000 if on_accel else 200_000))
        kd = int(os.environ.get("BENCH_D", 128))
        kk = int(os.environ.get("BENCH_K", 1024 if on_accel else 64))
        gn = int(os.environ.get("BENCH_GMM_N",
                                2_097_152 if on_accel else 100_000))
        gd = int(os.environ.get("BENCH_GMM_D", 128 if on_accel else 64))
        gk = int(os.environ.get("BENCH_GMM_K", 256 if on_accel else 32))
        log(f"bench: COST mode backend={backend} kmeans {kn}x{kd} "
            f"k={kk}; gmm-diag {gn}x{gd} k={gk}")
        bench_cost(kn, kd, kk, gmm_n=gn, gmm_d=gd, gmm_k=gk)
        return

    if os.environ.get("BENCH_PHASES"):
        # Measured per-phase ceiling table + chunk re-sweep (ISSUE 8c).
        from kmeans_tpu.benchmarks import bench_phases
        pn = int(os.environ.get("BENCH_N",
                                10_000_000 if on_accel else 200_000))
        pd = int(os.environ.get("BENCH_D", 128 if on_accel else 32))
        pk = int(os.environ.get("BENCH_K", 1024 if on_accel else 64))
        pg = int(os.environ.get("BENCH_ITERS", 20))
        chunks = os.environ.get("BENCH_PHASES_CHUNKS")
        chunks = tuple(int(c) for c in chunks.split(",")) if chunks \
            else None
        log(f"bench: PHASES mode backend={backend} N={pn} D={pd} k={pk} "
            f"gap={pg}")
        bench_phases(pn, pd, pk, gap=pg, chunks=chunks,
                     skip_sweep=bool(os.environ.get(
                         "BENCH_PHASES_NO_SWEEP")))
        return

    if os.environ.get("BENCH_TTFI"):
        # Time-to-first-iteration attack rows (ISSUE 15): cold / warm /
        # AOT-warm / overlap TTFI, measured across fresh processes
        # against one shared AOT executable store, with the committed
        # rules (AOT-warm compile <= 10% of cold; overlapped prelude
        # window < serial stage+compile sum).  Env: BENCH_N/_D/_K,
        # BENCH_ITERS (device-loop iterations), BENCH_AOT_DIR.
        from kmeans_tpu.benchmarks import bench_ttfi
        tn = int(os.environ.get("BENCH_N",
                                2_000_000 if on_accel else 400_000))
        td = int(os.environ.get("BENCH_D", 128 if on_accel else 64))
        tk = int(os.environ.get("BENCH_K", 1024 if on_accel else 64))
        ti = int(os.environ.get("BENCH_ITERS", 4))
        log(f"bench: TTFI mode backend={backend} N={tn} D={td} k={tk} "
            f"iters={ti}")
        bench_ttfi(tn, td, tk, max_iter=ti,
                   aot_dir=os.environ.get("BENCH_AOT_DIR"))
        return

    if os.environ.get("BENCH_OBS"):
        # Telemetry-overhead benchmark (ISSUE 11): obs-on (tracing +
        # heartbeat) vs obs-off fits, interleaved per-rep ratios, on
        # both the one-dispatch device loop and the telemetry-dense
        # per-iteration host loop; plus the cold-cache traced fit whose
        # span-derived time-to-first-iteration table is the BASELINE
        # artifact.  Committed rule: <=1% median overhead on the
        # 200k x 32 k=64 proxy or per-iteration spans go coarse.
        from kmeans_tpu.benchmarks import bench_obs
        on_ = int(os.environ.get("BENCH_N",
                                 2_000_000 if on_accel else 200_000))
        od = int(os.environ.get("BENCH_D", 128 if on_accel else 32))
        ok = int(os.environ.get("BENCH_K", 1024 if on_accel else 64))
        oi = int(os.environ.get("BENCH_ITERS", 20))
        art = os.environ.get("BENCH_OBS_TRACE",
                             "artifacts/trace_ttfi.jsonl")
        os.makedirs(os.path.dirname(art) or ".", exist_ok=True)
        log(f"bench: OBS mode backend={backend} N={on_} D={od} k={ok} "
            f"iters={oi} trace={art}")
        bench_obs(on_, od, ok, iters=oi, artifact_path=art)
        return

    if os.environ.get("BENCH_CKPT"):
        # Segmented-dispatch cost (ISSUE 4): checkpoint_every=N device
        # loop vs the single-dispatch oracle, interleaved per-rep
        # ratios.  Default N matches the docs/PERFORMANCE.md pinned row.
        # Followed by the ELASTIC-RESUME row (ISSUE 5): save + canonical
        # gather + reshard-resume wall onto a half-width mesh.
        from kmeans_tpu.benchmarks import (bench_checkpoint_segments,
                                           bench_cross_mesh_resume)
        cn = int(os.environ.get("BENCH_N",
                                2_000_000 if on_accel else 200_000))
        cd = int(os.environ.get("BENCH_D", 128 if on_accel else 32))
        ck = int(os.environ.get("BENCH_K", 1024 if on_accel else 64))
        ci = int(os.environ.get("BENCH_ITERS", 32))
        ce = int(os.environ.get("BENCH_CKPT_EVERY", 8))
        log(f"bench: CKPT mode backend={backend} N={cn} D={cd} k={ck} "
            f"iters={ci} every={ce}")
        bench_checkpoint_segments(cn, cd, ck, ci, ce)
        bench_cross_mesh_resume(cn, cd, ck, ci, ce)
        return

    if os.environ.get("BENCH_SERVE"):
        # Serving latency/QPS benchmark (ISSUE 6): micro-batched
        # dispatch vs sequential per-request dispatch at 1/8/64/512-
        # request batches against a resident warm model, interleaved
        # per-rep speedup ratios + p50/p99 request latencies under the
        # batching timer.  Env: BENCH_N/D/K, BENCH_SERVE_BATCHES,
        # BENCH_SERVE_WAIT_MS.
        from kmeans_tpu.benchmarks import bench_serving
        vn = int(os.environ.get("BENCH_N",
                                2_000_000 if on_accel else 200_000))
        vd = int(os.environ.get("BENCH_D", 128 if on_accel else 32))
        vk = int(os.environ.get("BENCH_K", 1024 if on_accel else 64))
        vb = tuple(int(b) for b in os.environ.get(
            "BENCH_SERVE_BATCHES", "1,8,64,512").split(","))
        vw = float(os.environ.get("BENCH_SERVE_WAIT_MS", 2.0))
        log(f"bench: SERVE mode backend={backend} N={vn} D={vd} k={vk} "
            f"batches={vb} max_wait_ms={vw}")
        bench_serving(vn, vd, vk, batch_sizes=vb, max_wait_ms=vw)
        return

    if os.environ.get("BENCH_SWEEP"):
        # Multi-k sweep benchmark (ISSUE 7): the batched fit-many/
        # pick-best sweep (one vmapped dispatch for all (k, restart)
        # members) vs the sequential per-member oracle, interleaved
        # per-rep ratios + the wasted-FLOPs (padding economics) column.
        # CPU proxy default: 200k x 32, k 2..17, n_init=2 (the pinned
        # acceptance config, >= 2x); accelerator default: the 10M x 128
        # headline family with the >= 3x decision rule.
        from kmeans_tpu.benchmarks import bench_sweep
        from kmeans_tpu.sweep import parse_k_range
        wn = int(os.environ.get("BENCH_N",
                                10_000_000 if on_accel else 200_000))
        wd = int(os.environ.get("BENCH_D", 128 if on_accel else 32))
        # Same half-open 'lo:hi[:step]' / comma grammar as the CLI's
        # --k-range, so a bench config reproduces verbatim through the
        # sweep subcommand (default 2:18 = k in {2..17}).
        ks = parse_k_range(os.environ.get("BENCH_SWEEP_KRANGE", "2:18"))
        wi = int(os.environ.get("BENCH_ITERS", 10))
        wni = int(os.environ.get("BENCH_SWEEP_NINIT", 2))
        log(f"bench: SWEEP mode backend={backend} N={wn} D={wd} "
            f"k={ks[0]}..{ks[-1]} n_init={wni} max_iter={wi}")
        bench_sweep(wn, wd, ks, wni, wi)
        return

    if os.environ.get("BENCH_STREAM"):
        # Streamed-epoch benchmark (fit_stream, disk blocks through the
        # double-buffered pipeline): prefetch on vs off by the marginal
        # method, one JSON line.  The declared bigger-than-HBM shape on
        # a 16 GB chip is N=40M x D=128 (20 GB of f32 rows, block_rows
        # 2M — ~(prefetch+2) x 1 GB resident); the CPU default is
        # scaled down so the harness stays runnable anywhere.  Env:
        # BENCH_STREAM_N/D/K/BLOCK_ROWS/EPOCHS/PATH.
        from kmeans_tpu.benchmarks import bench_stream
        sn = int(os.environ.get("BENCH_STREAM_N",
                                40_000_000 if on_accel else 1_000_000))
        sd = int(os.environ.get("BENCH_STREAM_D",
                                128 if on_accel else 32))
        sk = int(os.environ.get("BENCH_STREAM_K",
                                1024 if on_accel else 64))
        sb = int(os.environ.get("BENCH_STREAM_BLOCK_ROWS",
                                2_000_000 if on_accel else 125_000))
        se = int(os.environ.get("BENCH_STREAM_EPOCHS", 4))
        log(f"bench: STREAM mode backend={backend} N={sn} D={sd} k={sk} "
            f"block_rows={sb} epochs_gap={se}")
        bench_stream(sn, sd, sk, sb, se,
                     path=os.environ.get("BENCH_STREAM_PATH"))
        return
    # Default = the BASELINE.json NORTH-STAR config (10M x 128, k=1024)
    # on accelerators.  Affordable as a default since r3 because the
    # dataset is generated ON DEVICE (below): the former 5 GB host
    # upload — ~10 MB/s through the tunneled PJRT transport, the
    # dominant share of r2's "compile+warmup" (docs/PERFORMANCE.md
    # "Time to first iteration") — no longer exists.
    n = int(os.environ.get("BENCH_N", 10_000_000 if on_accel else 100_000))
    d = int(os.environ.get("BENCH_D", 128))
    k = int(os.environ.get("BENCH_K", 1024))
    # 32 iters x ~38 ms/iter puts the marginal at ~1.2 s — large enough
    # that the tunneled platform's ±25 ms per-pair dispatch noise stays
    # under the ~5% publication bar (BASELINE.md method notes, r4).
    iters = int(os.environ.get("BENCH_ITERS", 32))
    mode = os.environ.get("BENCH_MODE", "auto")

    if mode == "auto":
        # The library's own resolution rule (KMeans distance_mode='auto').
        from kmeans_tpu.ops.pallas_kernels import resolve_auto
        mode = resolve_auto(n, d, k)
    log(f"bench: backend={backend} devices={len(jax.devices())} "
        f"N={n} D={d} k={k} iters={iters} mode={mode}")

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kmeans_tpu.parallel import distributed as dist
    from kmeans_tpu.parallel.mesh import DATA_AXIS, make_mesh, mesh_shape
    from kmeans_tpu.parallel.sharding import choose_chunk_size

    mesh = make_mesh()
    data_shards, model_shards = mesh_shape(mesh)
    chunk = choose_chunk_size(-(-n // data_shards), k, d)
    n_pad = -(-n // (data_shards * chunk)) * (data_shards * chunk)

    # Seeded uniform points generated ON DEVICE, already sharded (GSPMD
    # materializes each shard locally): zero host->device transfer.
    gen = jax.jit(
        lambda key: (jax.random.uniform(key, (n_pad, d), jnp.float32,
                                        -1.0, 1.0),
                     (jnp.arange(n_pad) < n).astype(jnp.float32)),
        out_shardings=(NamedSharding(mesh, P(DATA_AXIS, None)),
                       NamedSharding(mesh, P(DATA_AXIS))))
    points, weights = gen(jax.random.PRNGKey(42))
    # Forgy init from the generated rows (a tiny k-row device gather).
    rng = np.random.default_rng(42)
    init = np.asarray(points[np.sort(rng.choice(n, size=k,
                                                replace=False))])
    cents = jax.device_put(dist.pad_centroids(init, model_shards),
                           dist.centroid_sharding(mesh))

    def build(max_iter: int):
        # history_sse=False mirrors the reference's stress-bench semantics
        # (T2 runs compute_sse=False, kmeans_spark.py:424) — and the
        # baseline loop below doesn't compute SSE either.
        return dist.make_fit_fn(mesh, chunk_size=chunk, mode=mode, k_real=k,
                                max_iter=max_iter, tolerance=1e-30,
                                empty_policy="keep", history_sse=False)

    fit_small, fit_big = build(2), build(2 + iters)
    # Pre-placed ('keep': unused); transferring inside the timed window
    # would bias the big side of each marginal pair by O(iters) bytes.
    seeds_s = jax.device_put(np.zeros((2,), np.uint32))
    seeds_b = jax.device_put(np.zeros((2 + iters,), np.uint32))
    t0 = time.perf_counter()
    timed_fit(fit_small, points, weights, cents, seeds_s)
    timed_fit(fit_big, points, weights, cents, seeds_b)
    log(f"bench: compile+warmup {time.perf_counter() - t0:.1f}s")

    # The shared measurement protocol (kmeans_tpu.benchmarks.
    # measure_marginal): median of 5 interleaved marginals + relative
    # spread, so both harnesses measure under identical rules.
    margin, spread, margins = measure_marginal(
        lambda: timed_fit(fit_small, points, weights, cents, seeds_s),
        lambda: timed_fit(fit_big, points, weights, cents, seeds_b),
        reps=5)
    for rep, m in enumerate(margins):
        log(f"bench: rep {rep + 1}/{len(margins)}: marginal "
            f"{m*1e3:.0f} ms over {iters} iters -> "
            f"{m/iters*1e3:.2f} ms/iter")
    per_iter = margin / iters
    log(f"bench: median {per_iter*1e3:.2f} ms/iter, spread "
        f"{spread*100:.0f}% over {len(margins)} reps")
    if margin <= 0.05:
        log("bench: WARNING: marginal time is within dispatch-latency "
            "noise (~50 ms) — raise BENCH_N/BENCH_ITERS for a trustworthy "
            "number (python -m kmeans_tpu bench does this adaptively)")

    n_chips = max(1, len(jax.devices()))
    throughput = n * d / per_iter / n_chips

    base_live = baseline_throughput(d, k)
    base, pin_status = pinned_baseline(d, k)
    pinned = base is not None
    if pinned:
        drift = base_live / base - 1.0
        log(f"bench: baseline (8 ideal Spark workers) PINNED {base:.3e} "
            f"pts*dims/s (BASELINE.json.published; live probe "
            f"{base_live:.3e}, {drift:+.0%} vs pin)")
        if abs(drift) > 0.3:   # r4's incident measured +45%; fire below it
            log("bench: WARNING: live baseline probe drifts >30% from the "
                "pin — host-load artifact (the r4 8.2k<->12k failure mode) "
                "or a genuinely different host; the published multiplier "
                "stays pinned either way")
    else:
        base = base_live
        # A lost pin at the headline shape is the r4-verdict drift bug
        # reappearing — say WHY the pin was skipped, loudly.
        log(f"bench: baseline (8 ideal Spark workers) {base:.3e} "
            f"pts*dims/s (LIVE probe, un-pinned: {pin_status})")

    print(json.dumps({
        "metric": f"kmeans_iter_throughput_N{n}_D{d}_k{k}",
        "value": round(throughput, 1),
        "unit": "points*dims/sec/chip",
        "vs_baseline": round(throughput * n_chips / base, 2),
        "ms_per_iter": round(per_iter * 1e3, 3),
        "spread": round(spread, 3),
        "mode": mode,
        # Divisor provenance: without these, a pinned 11,937x and a
        # live-probe multiplier taken under host load are
        # indistinguishable in the one-line artifact (review r5).
        "baseline": round(base, 1),
        "baseline_pinned": pinned,
    }))


if __name__ == "__main__":
    main()
