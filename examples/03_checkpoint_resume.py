"""Checkpoint / resume (beyond-reference capability, SURVEY.md §5).

The reference keeps centroids only in memory (``kmeans_spark.py:44``).
Here a fit can be checkpointed and resumed exactly — including the
mini-batch sampler's RNG continuity — so long jobs survive preemption.

Run: ``python examples/03_checkpoint_resume.py``
"""

import tempfile
from pathlib import Path

import numpy as np

from kmeans_tpu import KMeans
from kmeans_tpu.data.synthetic import make_blobs

X, _ = make_blobs(100_000, centers=16, n_features=32, random_state=2,
                  dtype=np.float32)

ckpt = Path(tempfile.mkdtemp()) / "kmeans.ckpt"

# Phase 1: run a few iterations, then "get preempted".
km = KMeans(k=16, max_iter=3, seed=42, compute_sse=True, verbose=False)
km.fit(X)
km.save(ckpt)
print(f"saved after {km.iterations_run} iterations, "
      f"SSE={km.sse_history[-1]:.1f}")

# Phase 2: reload and continue to convergence from the saved state.
km2 = KMeans.load(ckpt)
km2.set_params(max_iter=100)
km2.fit(X, resume=True)
print(f"resumed -> converged after {km2.iterations_run} total iterations, "
      f"SSE={km2.sse_history[-1]:.1f}")
