"""The model family: KMeans, MiniBatchKMeans, BisectingKMeans,
SphericalKMeans, GaussianMixture — all sharing the same fused TPU step.

Run: ``python examples/04_model_zoo.py``
"""

import numpy as np

from kmeans_tpu import (BisectingKMeans, GaussianMixture, KMeans,
                        MiniBatchKMeans, SphericalKMeans)
from kmeans_tpu.data.synthetic import make_blobs
from kmeans_tpu.metrics import silhouette_score

X, _ = make_blobs(30_000, centers=6, n_features=24, random_state=3,
                  dtype=np.float32)

for cls, kwargs in [
    (KMeans, dict(n_init=4, init="kmeans++")),   # multi-restart + smart init
    (MiniBatchKMeans, dict(batch_size=2048)),    # sampled incremental updates
    (BisectingKMeans, {}),                       # divisive hierarchical
    (SphericalKMeans, {}),                       # cosine-similarity clustering
]:
    model = cls(k=6, seed=42, verbose=False, **kwargs).fit(X)
    sil = silhouette_score(X, model.predict(X), sample_size=5_000, seed=0)
    print(f"{cls.__name__:18s} iters={model.iterations_run:3d} "
          f"silhouette={sil:.3f}")

# Soft clustering: EM on the same SPMD machinery (covariance_type picks
# diag/spherical/tied/full) — here with every EM iteration in ONE device
# dispatch (host_loop=False) and 2 seeded restarts.
gm = GaussianMixture(n_components=6, seed=42, n_init=2,
                     host_loop=False).fit(X)
sil = silhouette_score(X, gm.predict(X), sample_size=5_000, seed=0)
print(f"{'GaussianMixture':18s} iters={gm.n_iter_:3d} "
      f"silhouette={sil:.3f} loglik={gm.lower_bound_:.3f}")
