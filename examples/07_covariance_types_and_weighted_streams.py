"""Round-4 capabilities tour: GMM covariance types, weighted streams,
and mini-batch dead-center recovery.

Run:  python examples/07_covariance_types_and_weighted_streams.py
"""

import numpy as np

from kmeans_tpu import GaussianMixture, KMeans
from kmeans_tpu.models import MiniBatchKMeans

rng = np.random.default_rng(0)

# Correlated blobs — the shape diagonal covariances cannot represent.
A = np.array([[1.0, 0.8], [0.0, 0.6]])
X = np.concatenate([
    rng.normal(size=(2000, 2)) @ A.T + [5, 5],
    rng.normal(size=(2000, 2)) * 0.7 + [-5, -3],
    rng.normal(size=(2000, 2)) * 0.9 + [5, -6],
]).astype(np.float32)
init = np.array([[5, 5], [-5, -3], [5, -6]], np.float64)

# 1. All four sklearn covariance types, each in its natural TPU form.
#    'full' wins on correlated clusters; host_loop=False runs every EM
#    iteration in ONE device dispatch for every type.
for ct in ("diag", "spherical", "tied", "full"):
    gm = GaussianMixture(n_components=3, covariance_type=ct,
                         means_init=init, max_iter=40, tol=1e-5,
                         seed=0, host_loop=False).fit(X)
    print(f"covariance_type={ct:9s} lower_bound={gm.lower_bound_:+.4f} "
          f"covariances_.shape={gm.covariances_.shape}")

# 2. Weighted streams: (block, weights) items fold into every statistic
#    exactly like fit(sample_weight=...) — here a 3x-weighted duplicate
#    region shifts the centroids the same way in both engines.
w = np.where(X[:, 0] > 0, 3.0, 1.0)
mem = KMeans(k=3, seed=0, init=init.astype(np.float32), verbose=False,
             empty_cluster="keep").fit(X, sample_weight=w)

def weighted_blocks():
    for i in range(0, len(X), 1500):
        yield X[i: i + 1500], w[i: i + 1500]

st = KMeans(k=3, seed=0, init=init.astype(np.float32), verbose=False,
            empty_cluster="keep")
st.fit_stream(weighted_blocks)
print("weighted stream == weighted fit:",
      np.allclose(st.centroids, mem.centroids, atol=1e-3))

# 3. Mini-batch dead-center recovery: a far-out init center would stay
#    frozen forever under the pure Sculley update; reassignment_ratio
#    (default 0.01, sklearn-style) re-seeds it from the current batch.
bad_init = np.concatenate([init[:2], [[1e3, 1e3]]]).astype(np.float32)
mb = MiniBatchKMeans(k=3, init=bad_init, batch_size=512, max_iter=100,
                     seed=0, verbose=False).fit(X)
print("dead center revived:",
      not np.allclose(mb.centroids[2], bad_init[2]),
      "| cluster sizes:", mb.cluster_sizes_.tolist())
