"""Quickstart: fit, predict, inspect — the reference's core workflow.

Mirrors the usage shown in the reference's README (``README.md:30-54``):
construct, fit, predict, read ``centroids`` / ``sse_history`` — except the
data is a plain NumPy array instead of an RDD and the execution is a fused
SPMD step on whatever devices are visible (TPU chips, or CPU).

Run: ``python examples/01_quickstart.py``
"""

import numpy as np

from kmeans_tpu import KMeans
from kmeans_tpu.data.synthetic import make_blobs

X, _ = make_blobs(50_000, centers=8, n_features=16, random_state=0,
                  dtype=np.float32)

km = KMeans(k=8, max_iter=100, tolerance=1e-4, seed=42, compute_sse=True)
km.fit(X)

print("\ncentroids:", km.centroids.shape)
print("iterations:", km.iterations_run)
print("final SSE:", km.sse_history[-1])
print("labels:", km.labels_[:10], "...")
print("score (negative SSE):", km.score(X))
