"""Distributed fit over an explicit device mesh (DP x TP).

The reference scales by Spark partition count (``repartition(4)``,
``kmeans_spark.py:418``); here the analogue is a ``jax.sharding.Mesh`` with
a ``data`` axis (points sharded over N) and an optional ``model`` axis (the
(k, D) centroid table row-sharded — useful when k*D is large).  The same
script runs unchanged on real TPU chips or on virtual CPU devices.

Run (8 virtual CPU devices):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/02_multichip_mesh.py
"""

import jax
import numpy as np

from kmeans_tpu import KMeans, make_mesh
from kmeans_tpu.data.synthetic import make_blobs

devs = jax.devices()
print(f"{len(devs)} devices: {devs[0].platform}")

# data x model mesh: DP over points, TP over the centroid table.
model = 2 if len(devs) % 2 == 0 and len(devs) > 1 else 1
mesh = make_mesh(data=len(devs) // model, model=model)
print("mesh:", dict(mesh.shape))

X, _ = make_blobs(200_000, centers=32, n_features=64, random_state=1,
                  dtype=np.float32)

km = KMeans(k=32, seed=42, compute_sse=True, mesh=mesh)
ds = km.cache(X)          # upload + shard once (the rdd.cache() analogue)
km.fit(ds)
print("iterations:", km.iterations_run, "SSE:", km.sse_history[-1])

labels = km.predict(ds)   # reuses the device-resident shards
print("cluster sizes:", np.bincount(labels, minlength=32))
