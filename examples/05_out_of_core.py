"""Out-of-core ingestion: fit from an on-disk .npy without loading it
whole on any single host (each shard mmap-reads only its own rows).

The reference reads everything through the Spark driver; here
``data.io.from_npy`` maps shard-local row ranges straight to devices.

Run: ``python examples/05_out_of_core.py``
"""

import tempfile
from pathlib import Path

import numpy as np

from kmeans_tpu import KMeans, make_mesh
from kmeans_tpu.data.io import from_npy
from kmeans_tpu.data.synthetic import make_blobs

path = Path(tempfile.mkdtemp()) / "points.npy"
X, _ = make_blobs(500_000, centers=12, n_features=32, random_state=4,
                  dtype=np.float32)
np.save(path, X)
print(f"wrote {path} ({path.stat().st_size / 1e6:.0f} MB)")

mesh = make_mesh()                     # data axis over all visible devices
ds = from_npy(path, mesh=mesh, k_hint=12)   # shard-local mmap reads
km = KMeans(k=12, seed=42, compute_sse=True, verbose=False, mesh=mesh)
km.fit(ds)
print("iterations:", km.iterations_run, "SSE:", km.sse_history[-1])
