"""Streaming EXACT Lloyd over a dataset that never fits in device memory.

Unlike MiniBatchKMeans (sampled approximation), ``fit_stream`` computes
true full-batch K-Means: each iteration streams disk blocks through the
fused SPMD step and sums the dense (k, D+1) statistics, so the result
matches an in-memory fit of the whole file. Only one block is ever
resident on device (or in host RAM, thanks to the mmap reader).

Run: ``python examples/06_streaming_bigger_than_memory.py``
"""

import tempfile
from pathlib import Path

import numpy as np

from kmeans_tpu import KMeans
from kmeans_tpu.data.io import iter_npy_blocks
from kmeans_tpu.data.synthetic import make_blobs

path = Path(tempfile.mkdtemp()) / "big.npy"
X, _ = make_blobs(300_000, centers=10, n_features=32, random_state=6,
                  dtype=np.float32)
np.save(path, X)
print(f"wrote {path} ({path.stat().st_size / 1e6:.0f} MB)")

# Shared explicit init so the streamed and in-memory fits follow the
# same trajectory.  (Named strategies also work: 'forgy' runs one
# reservoir pass over the FULL stream — the reference's takeSample
# capability — and 'k-means++'/'k-means||' run a streamed kmeans||.)
rng = np.random.RandomState(42)
init = X[rng.choice(len(X), 10, replace=False)].copy()

km = KMeans(k=10, seed=42, compute_sse=True, empty_cluster="keep",
            init=init, max_iter=30, verbose=False)
# Each epoch streams through the double-buffered pipeline: a background
# producer reads + uploads block i+1 while block i computes
# (prefetch=2 is the default; prefetch=0 restores the synchronous path
# — the trajectory is bit-identical either way).
km.fit_stream(iter_npy_blocks(path, block_rows=50_000))   # 6 blocks/epoch
print("streamed fit: iterations", km.iterations_run,
      "SSE", round(km.sse_history[-1], 1))

ref = KMeans(k=10, seed=42, compute_sse=True, empty_cluster="keep",
             init=init, max_iter=30, verbose=False).fit(X)
print("in-memory fit:", ref.iterations_run, "iterations,",
      "centroid max |diff| =",
      float(np.abs(km.centroids - ref.centroids).max()))
