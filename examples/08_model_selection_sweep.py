"""Model selection: batched multi-k sweeps — fit-many, pick-best, in
O(1) dispatches.

Real users don't know k.  The classic workflow — fit k_max models,
plot the elbow / silhouette / BIC curve, pick one — pays k_max full
fits.  ``sweep()`` collapses the whole grid into ONE vmapped device
dispatch: every (k, restart) member is padded to k_max with inert
components and rides the batched restart machinery, then the criterion
curve is scored in a constant number of further dispatches.

Run: ``python examples/08_model_selection_sweep.py``
"""

import numpy as np

from kmeans_tpu import GaussianMixture, KMeans
from kmeans_tpu.data.synthetic import make_blobs

# Ground truth: 5 well-separated blobs (so the curves have a clean
# answer to find).
X, _ = make_blobs(40_000, centers=5, n_features=16, random_state=7,
                  dtype=np.float32)

# --- Elbow sweep: k ∈ {2..9} × 2 restarts = 16 fits, ONE dispatch ----
km = KMeans(k=2, seed=0, n_init=2, max_iter=50, empty_cluster="keep",
            verbose=False)
res = km.sweep(X, k_range=range(2, 10), criterion="inertia")
print(f"elbow sweep: selected k={res.selected_k} in "
      f"{res.n_dispatches} device dispatch(es)")
for k, score in zip(res.k_range, res.scores):
    bar = "#" * max(1, int(40 * score / res.scores[0]))
    print(f"  k={k}: inertia {score:12.1f}  {bar}")

# The winner is a normally-fitted model: predict/score/save all work.
best = res.best_model
print(f"best model: k={best.k}, {best.iterations_run} iterations, "
      f"restart {res.selected_restart} won of {res.member_scores.shape[1]}")
labels = best.predict(X[:1000])
print(f"labels of 1000 rows -> {np.bincount(labels)}")

# --- Silhouette criterion: same batched fit, batched scoring ---------
res_sil = KMeans(k=2, seed=0, max_iter=50, empty_cluster="keep",
                 verbose=False).sweep(
    X[::5], k_range=range(2, 8), criterion="silhouette")
print(f"silhouette sweep: selected k={res_sil.selected_k} "
      f"({res_sil.n_dispatches} dispatches; scores "
      f"{np.round(res_sil.scores, 3).tolist()})")

# --- BIC sweep for mixtures: the principled k selector ---------------
gm = GaussianMixture(n_components=2, covariance_type="diag", seed=0,
                     max_iter=40, init_params="random", verbose=False)
res_bic = gm.sweep(X, k_range=range(2, 10), criterion="bic")
print(f"BIC sweep: selected k={res_bic.selected_k} in "
      f"{res_bic.n_dispatches} dispatch(es)")
for k, score in zip(res_bic.k_range, res_bic.scores):
    mark = " <-- min" if k == res_bic.selected_k else ""
    print(f"  k={k}: BIC {score:14.1f}{mark}")

# The sequential oracle (batched=0) is the parity/debug path: same
# members, one fit per member — what the batched sweep must match.
res_seq = KMeans(k=2, seed=0, n_init=2, max_iter=50,
                 empty_cluster="keep", verbose=False).sweep(
    X, k_range=range(2, 10), criterion="inertia", batched=0)
assert res_seq.selected_k == res.selected_k
print(f"sequential oracle agrees: k={res_seq.selected_k} "
      f"({res_seq.n_dispatches} dispatches vs {res.n_dispatches})")
